/* spillz.h — native spill-run compression: delta + fixed-width bitpack.
 *
 * The external sort's spill runs (mpitest_tpu/store/runs.py) are
 * SORTED key words — the best-case input for delta coding: consecutive
 * encoded keys differ by small non-negative amounts, so a block of
 * 64-bit "wide" key values (the msw/lsw uint32 word planes combined,
 * lexicographic order == numeric uint64 order) packs into
 * `width = bit_length(max delta)` bits per key instead of 32/64.  The
 * kernels here are the per-block codec: one pass computes the deltas,
 * the width and the integrity checksum; a second pass bit-packs the
 * deltas LSB-first (little-endian bit order — the exact layout of
 * numpy's packbits(bitorder="little"), so the pure-Python fallback in
 * mpitest_tpu/store/compress.py is bit-identical byte for byte).
 * Deltas wrap mod 2^64, so ANY input block round-trips exactly —
 * unsorted (corrupted-upstream) data costs width, never correctness.
 *
 * Exposed to Python via ctypes (mpitest_tpu/store/compress.py, knob
 * SORT_SPILL_COMPRESS={auto,on,off}); ctypes releases the GIL around
 * every call, so the read-ahead/write-behind threads of
 * mpitest_tpu/store/aio.py decode/encode in real parallelism.  Parity
 * contract: bit-identical packed bytes and checksums vs the fallback
 * on every input — fuzzed (with ASan/UBSan in `make sanitize-selftest`)
 * by native/spillz_fuzz.c, which also drives corrupt-block corpora
 * through the decoder (it must fail loudly, never read out of
 * bounds).  The symbol surface below is cross-checked against
 * spillz.c by tools/comm_parity.py, like encode.h's.
 */
#ifndef SPILLZ_H
#define SPILLZ_H

#include <stddef.h>
#include <stdint.h>

/* Status codes.  The ctypes shim maps each to the exception the
 * pure-Python fallback raises for the same input (parity by TYPE):
 * SPZ_EBOUNDS -> ValueError (length/capacity mismatch — a torn or
 * garbage block body), SPZ_EWIDTH -> ValueError (header width > 64). */
#define SPZ_OK       0
#define SPZ_EBOUNDS (-1)  /* in/out length disagrees with (n, width) */
#define SPZ_EWIDTH  (-2)  /* delta width outside 0..64 */

/* ABI version stamp — the ctypes shim refuses a stale .so loudly
 * instead of calling into a mismatched symbol surface. */
#define SPZ_ABI_VERSION 1
int spz_abi_version(void);

/* Pack one block of n wide (uint64) key values into out[0..cap).
 * Writes the block metadata the run framing stores in the block
 * header: *first = vals[0], *width = bit_length(max wrapping delta)
 * (0..64; 0 == constant block, zero packed bytes), *checksum = the
 * 32-bit fold of the values (murmur3-finalizer mix per value, then
 * XOR + wrapping sum, halves mixed — the pre-mix keeps high-bit
 * corruption visible) the decoder re-derives.  Returns the packed byte
 * count
 * ceil((n-1)*width/8), or SPZ_EBOUNDS when cap is too small.
 * n==0 is SPZ_EBOUNDS (the framing never writes empty blocks). */
long long spz_pack_block(const uint64_t *vals, size_t n,
                         unsigned char *out, size_t cap,
                         uint64_t *first, int *width,
                         uint32_t *checksum);

/* Unpack one block: reconstruct n wide values into vals_out from the
 * packed delta bytes in[0..in_len), given the block header's first
 * value and delta width, and fold *checksum_out from the
 * reconstructed values (the caller compares it against the stored
 * block checksum — a mismatch is disk corruption, typed Python-side).
 * Pre-checks in_len == ceil((n-1)*width/8) and bounds-guards every
 * read, so garbage (n, width, in_len) combinations fail with
 * SPZ_EBOUNDS/SPZ_EWIDTH instead of reading out of bounds.  Returns n
 * or a negative status. */
long long spz_unpack_block(const unsigned char *in, size_t in_len,
                           size_t n, uint64_t first,
                           int width, uint64_t *vals_out,
                           uint32_t *checksum_out);

#endif /* SPILLZ_H */
