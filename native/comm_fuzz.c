/* comm_fuzz — differential randomized tester for the comm.h surface.
 *
 * Executes a seeded random sequence of collectives (ragged counts,
 * zero-length segments, random roots, mixed reduction types) and folds
 * every byte each rank RECEIVES into a position-weighted checksum; the
 * combined checksum is printed by rank 0.  The op sequence and all
 * sizes derive from a PRNG stream shared by every rank (seed, iter), so
 * the run is deterministic given (seed, iters, P) — and therefore the
 * printed checksum must be IDENTICAL across comm backends (pthreads,
 * minimpi multi-process, real MPI).  tests/test_native.py runs the same
 * seeds on two backends and diffs the lines: a protocol bug that unit
 * tests miss (count plumbing on an unusual root, a zero-length segment
 * offset, an exscan edge) shows up as a checksum divergence.
 *
 * This extends the test strategy SURVEY.md §4 prescribes (the reference
 * has no tests at all) from per-primitive closed-form checks
 * (comm_selftest.c) to randomized cross-backend differential testing.
 *
 * Usage: comm_fuzz <seed> <iters>   (ranks from COMM_RANKS / MINIMPI_NP
 * / mpirun -np; per-op payloads bounded to a few KiB so hundreds of
 * iterations run in well under a second)
 */
#include "comm.h"

#include <inttypes.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define MAX_ELEMS 1024           /* per-segment u32 payload bound */

/* splitmix64 — tiny deterministic PRNG */
static uint64_t mix(uint64_t *s) {
    uint64_t z = (*s += 0x9E3779B97F4A7C15ull);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
}

typedef struct {
    uint64_t shared;   /* stream identical on every rank: op choices */
    uint64_t mine;     /* stream per (seed, rank): my payload bytes */
    uint64_t check;    /* running checksum of received bytes */
    size_t pos;        /* global fold position */
} fuzz_state;

static void fold(fuzz_state *f, const void *data, size_t bytes) {
    const unsigned char *p = (const unsigned char *)data;
    for (size_t i = 0; i < bytes; i++) {
        uint64_t x = ((uint64_t)p[i] + 1) * (uint64_t)(f->pos + 0x9E3779B9ull);
        f->check ^= x + (f->check << 6) + (f->check >> 2);
        f->pos++;
    }
}

static void fill(fuzz_state *f, uint32_t *buf, size_t elems) {
    for (size_t i = 0; i < elems; i++) buf[i] = (uint32_t)mix(&f->mine);
}

static void run(comm_ctx *c, void *arg) {
    uint64_t *args = (uint64_t *)arg;
    uint64_t seed = args[0];
    int iters = (int)args[1];
    const int rank = comm_rank(c), P = comm_size(c);

    fuzz_state f = {
        .shared = seed * 0x2545F4914F6CDD1Dull + 1,
        .mine = seed ^ ((uint64_t)0xA24BAED4963EE407ull * (uint64_t)(rank + 1)),
        .check = 0,
        .pos = 0,
    };

    uint32_t *a = (uint32_t *)malloc((size_t)P * MAX_ELEMS * sizeof(uint32_t));
    uint32_t *b = (uint32_t *)malloc((size_t)P * MAX_ELEMS * sizeof(uint32_t));
    size_t *cnt = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *dsp = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rcnt = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rdsp = (size_t *)malloc((size_t)P * sizeof(size_t));

    for (int it = 0; it < iters; it++) {
        int op = (int)(mix(&f.shared) % 10);
        int root = (int)(mix(&f.shared) % (uint64_t)P);
        size_t e = mix(&f.shared) % (MAX_ELEMS + 1); /* may be 0 */
        switch (op) {
        case 0: { /* bcast */
            fill(&f, a, e);
            comm_bcast(c, a, e * 4, root); /* non-roots overwritten */
            fold(&f, a, e * 4);
            break;
        }
        case 1: { /* scatter */
            fill(&f, a, (size_t)P * e);
            comm_scatter(c, a, b, e * 4, root);
            fold(&f, b, e * 4);
            break;
        }
        case 2: { /* gather */
            fill(&f, a, e);
            comm_gather(c, a, b, e * 4, root);
            if (rank == root) fold(&f, b, (size_t)P * e * 4);
            break;
        }
        case 3: { /* scatterv: ragged, zeros allowed */
            size_t tot = 0;
            for (int i = 0; i < P; i++) {
                cnt[i] = (mix(&f.shared) % (MAX_ELEMS + 1)) * 4;
                dsp[i] = tot;
                tot += cnt[i];
            }
            fill(&f, a, tot / 4);
            comm_scatterv(c, a, cnt, dsp, b, cnt[rank], root);
            fold(&f, b, cnt[rank]);
            break;
        }
        case 4: { /* gatherv: ragged, zeros allowed */
            size_t tot = 0;
            for (int i = 0; i < P; i++) {
                cnt[i] = (mix(&f.shared) % (MAX_ELEMS + 1)) * 4;
                dsp[i] = tot;
                tot += cnt[i];
            }
            fill(&f, a, cnt[rank] / 4);
            comm_gatherv(c, a, cnt[rank], b, cnt, dsp, root);
            if (rank == root) fold(&f, b, tot);
            break;
        }
        case 5: { /* allgather */
            fill(&f, a, e);
            comm_allgather(c, a, b, e * 4);
            fold(&f, b, (size_t)P * e * 4);
            break;
        }
        case 6: { /* allreduce, typed */
            comm_type t = (mix(&f.shared) & 1) ? COMM_T_U64 : COMM_T_U32;
            comm_op o = (comm_op)(mix(&f.shared) % 3);
            size_t cnt_e = e / (t == COMM_T_U64 ? 2 : 1);
            fill(&f, a, e);
            comm_allreduce(c, a, b, cnt_e, t, o);
            fold(&f, b, cnt_e * (t == COMM_T_U64 ? 8 : 4));
            break;
        }
        case 7: { /* exscan, typed (rank 0 = defined identity) */
            comm_type t = (mix(&f.shared) & 1) ? COMM_T_U64 : COMM_T_U32;
            comm_op o = (comm_op)(mix(&f.shared) % 3);
            size_t cnt_e = e / (t == COMM_T_U64 ? 2 : 1);
            fill(&f, a, e);
            comm_exscan(c, a, b, cnt_e, t, o);
            fold(&f, b, cnt_e * (t == COMM_T_U64 ? 8 : 4));
            break;
        }
        case 8: { /* alltoall */
            fill(&f, a, (size_t)P * e);
            comm_alltoall(c, a, b, e * 4);
            fold(&f, b, (size_t)P * e * 4);
            break;
        }
        default: { /* alltoallv: ragged matrix row per rank */
            /* every rank derives the FULL [P][P] count matrix from the
             * shared stream so recv counts/displs are locally known */
            size_t stot = 0, rtot = 0;
            for (int i = 0; i < P; i++) {
                for (int j = 0; j < P; j++) {
                    size_t bytes = (mix(&f.shared) % (MAX_ELEMS + 1)) * 4;
                    if (i == rank) { cnt[j] = bytes; }
                    if (j == rank) { rcnt[i] = bytes; }
                }
            }
            for (int j = 0; j < P; j++) { dsp[j] = stot; stot += cnt[j]; }
            for (int i = 0; i < P; i++) { rdsp[i] = rtot; rtot += rcnt[i]; }
            fill(&f, a, stot / 4);
            comm_alltoallv(c, a, cnt, dsp, b, rcnt, rdsp);
            fold(&f, b, rtot);
            break;
        }
        }
        if ((it & 31) == 31) comm_barrier(c);
    }

    /* combine: every rank's checksum must agree across backends */
    uint64_t mine2[2] = {f.check, (uint64_t)f.pos}, *all =
        (uint64_t *)malloc((size_t)P * 2 * sizeof(uint64_t));
    comm_allgather(c, mine2, all, sizeof mine2);
    uint64_t combined = 0x243F6A8885A308D3ull;
    for (int i = 0; i < 2 * P; i++)
        combined = (combined ^ all[i]) * 0x100000001B3ull;
    if (rank == 0)
        printf("comm_fuzz OK seed=%" PRIu64 " iters=%d ranks=%d "
               "checksum=%016" PRIx64 "\n", seed, iters, P, combined);
    free(a); free(b); free(cnt); free(dsp); free(rcnt); free(rdsp);
    free(all);
}

int main(int argc, char **argv) {
    if (argc != 3) {
        fprintf(stderr, "Usage: %s <seed> <iters>\n", argv[0]);
        return EXIT_FAILURE;
    }
    uint64_t args[2] = {strtoull(argv[1], NULL, 10),
                        strtoull(argv[2], NULL, 10)};
    return comm_launch(run, args);
}
