/* spillz_fuzz.c — seeded, bounded fuzz driver over the native spill
 * block codec (ISSUE 20 satellite).  Usage: spillz_fuzz <seed> <iters>.
 *
 * Three corpora per run, drawn from one splitmix64 stream so the SAME
 * seed replays the SAME blocks in every build:
 *
 *  - roundtrip: random value blocks (sorted ramps, plateaus, raw
 *    random — the wrapping-delta codec must be total) packed and
 *    unpacked; the reconstruction must be exact, the checksums must
 *    agree, and an independent naive scalar bit-gather re-decode of
 *    the packed bytes must match the kernel's output bit for bit
 *    (catches any bit-order/flush divergence);
 *  - corrupt: a valid packed block with header fields and/or body
 *    bytes scrambled; the decoder must either return a negative
 *    status or a checksum that differs from the original — and under
 *    ASan/UBSan it must never read out of bounds;
 *  - garbage: wholly random (in_len, n, width) headers over random
 *    bytes; any non-negative return must have consumed a
 *    self-consistent length.
 *
 * Everything folds into one checksum printed at exit:
 * `make sanitize-selftest` runs this under ASan+UBSan and as a plain
 * build and requires identical output (the cross-build differential).
 * Any internal inconsistency exits 1 immediately.
 */
#include <inttypes.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "spillz.h"

static uint64_t sm_state;

static uint64_t sm_next(void) {
    uint64_t z = (sm_state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

static uint64_t checksum;

static void fold_u64(uint64_t v) {
    checksum = (checksum ^ v) * 0x100000001B3ULL;  /* FNV-ish mix */
}

static void die(const char *what, uint64_t iter) {
    fprintf(stderr, "spillz_fuzz: INVARIANT VIOLATION: %s (iter %" PRIu64
            ")\n", what, iter);
    exit(1);
}

#define MAX_N 2048

/* independent naive re-decode: gather each delta bit by bit straight
 * from the packed bytes (LSB-first), no shared helpers with the kernel */
static void naive_unpack(const unsigned char *in, size_t n,
                         uint64_t first, int width, uint64_t *out) {
    uint64_t v = first;
    out[0] = v;
    for (size_t i = 1; i < n; i++) {
        uint64_t d = 0;
        for (int b = 0; b < width; b++) {
            size_t bit = (i - 1) * (size_t)width + (size_t)b;
            if ((in[bit / 8u] >> (bit % 8u)) & 1u)
                d |= 1ULL << b;
        }
        v += d;
        out[i] = v;
    }
}

static void gen_block(uint64_t *vals, size_t n) {
    uint64_t shape = sm_next() % 4u;
    if (shape == 0) {                 /* sorted ramp, narrow deltas */
        uint64_t v = sm_next();
        uint64_t step = sm_next() % 1024u;
        for (size_t i = 0; i < n; i++) {
            vals[i] = v;
            v += sm_next() % (step + 1u);
        }
    } else if (shape == 1) {          /* plateau: width-0 block */
        uint64_t v = sm_next();
        for (size_t i = 0; i < n; i++)
            vals[i] = v;
    } else if (shape == 2) {          /* sorted with rare wide jumps */
        uint64_t v = sm_next();
        for (size_t i = 0; i < n; i++) {
            vals[i] = v;
            v += (sm_next() % 64u == 0) ? sm_next() : sm_next() % 16u;
        }
    } else {                          /* raw random: wrapping totality */
        for (size_t i = 0; i < n; i++)
            vals[i] = sm_next();
    }
}

static void fuzz_roundtrip(uint64_t iter) {
    size_t n = (size_t)(sm_next() % MAX_N) + 1u;
    uint64_t *vals = (uint64_t *)malloc(n * 8u);
    uint64_t *back = (uint64_t *)malloc(n * 8u);
    uint64_t *naive = (uint64_t *)malloc(n * 8u);
    unsigned char *buf = (unsigned char *)malloc(n * 8u + 8u);
    if (!vals || !back || !naive || !buf) die("malloc", iter);
    gen_block(vals, n);
    uint64_t first = 0;
    int width = -1;
    uint32_t chk = 0;
    long long plen = spz_pack_block(vals, n, buf, n * 8u + 8u,
                                    &first, &width, &chk);
    if (plen < 0) die("pack rc", iter);
    if (width < 0 || width > 64) die("pack width", iter);
    if ((uint64_t)plen != ((n - 1) * (uint64_t)width + 7u) / 8u)
        die("pack length", iter);
    uint32_t chk2 = 0;
    long long rn = spz_unpack_block(buf, (size_t)plen, n, first, width,
                                    back, &chk2);
    if (rn != (long long)n) die("unpack rc", iter);
    if (chk2 != chk) die("checksum roundtrip", iter);
    if (memcmp(back, vals, n * 8u) != 0) die("values roundtrip", iter);
    naive_unpack(buf, n, first, width, naive);
    if (memcmp(naive, vals, n * 8u) != 0) die("naive re-decode", iter);
    /* short output capacity must be refused, never overrun */
    if (plen > 0 && spz_pack_block(vals, n, buf, (size_t)plen - 1u,
                                   &first, &width, &chk) != SPZ_EBOUNDS)
        die("pack cap", iter);
    fold_u64((uint64_t)plen ^ ((uint64_t)chk << 32) ^ (uint64_t)width);
    for (long long i = 0; i < plen; i += 31)
        fold_u64((uint64_t)buf[i]);
    free(vals); free(back); free(naive); free(buf);
}

static void fuzz_corrupt(uint64_t iter) {
    size_t n = (size_t)(sm_next() % 256u) + 2u;
    uint64_t *vals = (uint64_t *)malloc(n * 8u);
    uint64_t *back = (uint64_t *)malloc(n * 8u);
    unsigned char *buf = (unsigned char *)malloc(n * 8u + 8u);
    if (!vals || !back || !buf) die("malloc", iter);
    gen_block(vals, n);
    uint64_t first = 0;
    int width = 0;
    uint32_t chk = 0;
    long long plen = spz_pack_block(vals, n, buf, n * 8u + 8u,
                                    &first, &width, &chk);
    if (plen < 0) die("pack rc (corrupt leg)", iter);
    /* scramble: body byte flips, a lying first value, a lying width —
     * the decoder must fail the length pre-check, hit the bounds
     * guard, or surface a checksum that no longer matches */
    uint64_t bad_first = first;
    int bad_width = width;
    size_t bad_len = (size_t)plen;
    uint64_t nbits = (uint64_t)(n - 1) * (uint64_t)width;
    switch (sm_next() % 3u) {
    case 0:
        if (nbits > 0) {
            /* flip a MEANINGFUL packed bit (never the zero-padding
             * tail, which the decoder rightly ignores) */
            uint64_t bit = sm_next() % nbits;
            buf[bit / 8u] ^= (unsigned char)(1u << (bit % 8u));
        } else {
            bad_first ^= sm_next() | 1u;  /* width-0 block: lie about
                                           * the only stored value */
        }
        break;
    case 1:
        bad_first ^= sm_next() | 1u;
        break;
    default:
        bad_width = (int)(sm_next() % 80u);  /* may exceed 64 */
        if (bad_width == width)
            bad_width = width ? 0 : 65;
        break;
    }
    uint32_t chk2 = 0;
    long long rn = spz_unpack_block(buf, bad_len, n, bad_first,
                                    bad_width, back, &chk2);
    if (rn >= 0 && bad_width == width && bad_first == first &&
        chk2 == chk) {
        /* every corruption above changes bytes/fields the checksum or
         * the length pre-check covers; silent agreement is a miss */
        die("corruption went undetected", iter);
    }
    fold_u64((uint64_t)(rn < 0 ? -rn : rn) ^ ((uint64_t)chk2 << 16));
    free(vals); free(back); free(buf);
}

static void fuzz_garbage(uint64_t iter) {
    size_t blen = (size_t)(sm_next() % 512u);
    size_t n = (size_t)(sm_next() % 300u);
    int width = (int)(sm_next() % 80u);
    unsigned char *buf = (unsigned char *)malloc(blen ? blen : 1u);
    uint64_t *out = (uint64_t *)malloc((n ? n : 1u) * 8u);
    if (!buf || !out) die("malloc", iter);
    for (size_t i = 0; i < blen; i++)
        buf[i] = (unsigned char)sm_next();
    uint32_t chk = 0;
    long long rn = spz_unpack_block(buf, blen, n,
                                    sm_next(),
                                    width, out, &chk);
    if (rn >= 0) {
        if ((size_t)rn != n || n == 0) die("garbage rc shape", iter);
        if (blen != ((n - 1) * (uint64_t)width + 7u) / 8u)
            die("garbage accepted bad length", iter);
    }
    fold_u64((uint64_t)(rn < 0 ? -rn : rn) ^ (uint64_t)chk);
    free(buf); free(out);
}

int main(int argc, char **argv) {
    if (argc != 3) {
        fprintf(stderr, "Usage: %s <seed> <iters>\n", argv[0]);
        return 2;
    }
    uint64_t seed = (uint64_t)strtoull(argv[1], NULL, 10);
    uint64_t iters = (uint64_t)strtoull(argv[2], NULL, 10);
    sm_state = seed;
    checksum = 0xCBF29CE484222325ULL;
    if (spz_abi_version() != SPZ_ABI_VERSION) {
        fprintf(stderr, "spillz_fuzz: ABI mismatch\n");
        return 1;
    }
    for (uint64_t i = 0; i < iters; i++) {
        switch (sm_next() % 3u) {
        case 0: fuzz_roundtrip(i); break;
        case 1: fuzz_corrupt(i); break;
        default: fuzz_garbage(i); break;
        }
    }
    printf("spillz_fuzz seed=%" PRIu64 " iters=%" PRIu64
           " checksum=%016" PRIx64 "\n", seed, iters, checksum);
    return 0;
}
