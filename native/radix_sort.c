/* radix_sort — distributed LSD radix sort on the comm.h shim.
 *
 * Same capability as the reference program (mpi_radix_sort.c:60-205),
 * redesigned the way this repo's TPU engine does it
 * (mpitest_tpu/models/radix_sort.py — same algorithm over XLA
 * collectives):
 *
 *   - keys stay RESIDENT on their ranks across all passes; only
 *     256-entry histograms are exchanged.  The reference re-Scatters and
 *     re-Gathers the whole array through rank 0 every pass
 *     (mpi_radix_sort.c:139,192) — O(N) root traffic per digit;
 *   - destination = exact global stable position (histogram exscan), so
 *     every rank holds exactly its block size after every pass
 *     regardless of skew.  The reference routes digit d to rank d
 *     (radix coupled to P, :64), piling skewed data onto one rank;
 *   - digits are shift/mask of a bias-encoded key — integer math, 8-bit
 *     digits by default (RADIX_BITS env), not float pow() of |x|
 *     (:54-58), so negatives order correctly and precision cannot bite;
 *   - pass count = ceil(bits(global max XOR min)/digit_bits): digits
 *     above the highest globally-differing bit are skipped (the
 *     principled form of the number_digits pre-pass, :100).
 *
 * The pass loop itself lives in radix_core.h (shared with
 * sample_sort.c's skew fallback), including the reference's per-pass
 * debug contract: "[VERBOSE] Scatter OK LOOP" at debug>=1 and the
 * "DUMP: LOOP %u RADIX %u = %u" intermediate dumps at debug>2
 * (mpi_radix_sort.c:142,175-178).
 *
 * Output contract matches the reference byte-for-byte: "The n/2-th
 * sorted element: %d" (:201), stderr "Endtime()-Starttime() = %.5f sec"
 * (:203), full "%u|%u" dump at debug>2 (:199).
 */
#include "comm.h"
#include "radix_core.h"
#include "sort_common.h"

typedef struct {
    sort_args a;
} prog_state;

static void run(comm_ctx *c, void *vs) {
    prog_state *st = (prog_state *)vs;
    const int rank = comm_rank(c), P = comm_size(c);
    const int debug = st->a.debug;
    const unsigned bits = radix_bits_env(c);

    /* -- rank 0: read + encode -------------------------------------- */
    uint32_t *all = NULL;
    size_t n = 0;
    double start = 0;
    if (rank == 0) {
        size_t nn = 0;
        int32_t *raw = read_keys_file(st->a.path, &nn);
        if (!raw || nn == 0) {
            char msg[512];
            snprintf(msg, sizeof msg,
                     "sort(): '%s' is not a valid file for read.", st->a.path);
            comm_abort(c, 1, msg);
        }
        all = (uint32_t *)malloc(nn * sizeof(uint32_t));
        for (size_t i = 0; i < nn; i++) all[i] = key_encode(raw[i]);
        free(raw);
        n = nn;
        if (debug > 1) printf("[MASTER] Read file: %s (%zu keys)\n", st->a.path, n);
        start = comm_wtime();
    }
    uint64_t n64 = (uint64_t)n;
    comm_bcast(c, &n64, sizeof n64, 0);
    n = (size_t)n64;

    /* -- distribute ONCE; keys stay resident across passes ---------- */
    size_t m = block_count(n, P, rank);
    uint32_t *mine = (uint32_t *)malloc((m ? m : 1) * sizeof(uint32_t));
    size_t *counts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *displs = (size_t *)malloc((size_t)P * sizeof(size_t));
    for (int i = 0; i < P; i++) {
        counts[i] = block_count(n, P, i) * sizeof(uint32_t);
        displs[i] = block_start(n, P, i) * sizeof(uint32_t);
    }
    comm_scatterv(c, all, counts, displs, mine, m * sizeof(uint32_t), 0);

    radix_passes_resident(c, mine, m, n, bits, debug);

    /* -- gather to root (verification/output only) ------------------ */
    comm_gatherv(c, mine, m * sizeof(uint32_t), all, counts, displs, 0);

    if (rank == 0) {
        double end = comm_wtime();
        print_result(all, n, end - start, debug);
        free(all);
    }
    free(mine); free(counts); free(displs);
}

int main(int argc, char **argv) {
    prog_state st = {{NULL, 0}};
    if (parse_args(argc, argv, &st.a) != 0) return EXIT_FAILURE;
    return comm_launch(run, &st);
}
