/* radix_sort — distributed LSD radix sort on the comm.h shim.
 *
 * Same capability as the reference program (mpi_radix_sort.c:60-205),
 * redesigned the way this repo's TPU engine does it
 * (mpitest_tpu/models/radix_sort.py — same algorithm over XLA
 * collectives):
 *
 *   - keys stay RESIDENT on their ranks across all passes; only
 *     256-entry histograms are exchanged.  The reference re-Scatters and
 *     re-Gathers the whole array through rank 0 every pass
 *     (mpi_radix_sort.c:139,192) — O(N) root traffic per digit;
 *   - destination = exact global stable position (histogram exscan), so
 *     every rank holds exactly its block size after every pass
 *     regardless of skew.  The reference routes digit d to rank d
 *     (radix coupled to P, :64), piling skewed data onto one rank;
 *   - digits are shift/mask of a bias-encoded key — integer math, 8-bit
 *     digits by default (RADIX_BITS env), not float pow() of |x|
 *     (:54-58), so negatives order correctly and precision cannot bite;
 *   - pass count = ceil(bits(global max XOR min)/digit_bits): digits
 *     above the highest globally-differing bit are skipped (the
 *     principled form of the number_digits pre-pass, :100).
 *
 * Output contract matches the reference byte-for-byte: "The n/2-th
 * sorted element: %d" (:201), stderr "Endtime()-Starttime() = %.5f sec"
 * (:203), full "%u|%u" dump at debug>2 (:199).
 */
#include "comm.h"
#include "sort_common.h"

typedef struct {
    sort_args a;
} prog_state;

/* Stable counting sort of `m` keys by digit (shift/mask), also filling
 * hist[bins].  `tmp` is scratch of m elements; result ends in keys. */
static void counting_sort_digit(uint32_t *keys, uint32_t *tmp, size_t m,
                                unsigned shift, unsigned bins,
                                size_t *hist, size_t *offs) {
    const uint32_t mask = bins - 1;
    memset(hist, 0, bins * sizeof(size_t));
    for (size_t i = 0; i < m; i++) hist[(keys[i] >> shift) & mask]++;
    size_t acc = 0;
    for (unsigned b = 0; b < bins; b++) { offs[b] = acc; acc += hist[b]; }
    for (size_t i = 0; i < m; i++) tmp[offs[(keys[i] >> shift) & mask]++] = keys[i];
    memcpy(keys, tmp, m * sizeof(uint32_t));
}

static void run(comm_ctx *c, void *vs) {
    prog_state *st = (prog_state *)vs;
    const int rank = comm_rank(c), P = comm_size(c);
    const int debug = st->a.debug;
    const char *env_bits = getenv("RADIX_BITS");
    const unsigned bits = env_bits ? (unsigned)atoi(env_bits) : 8u;
    if (bits < 1 || bits > 16)
        comm_abort(c, 1, "radix_sort: RADIX_BITS must be in [1, 16]");
    const unsigned bins = 1u << bits;

    /* -- rank 0: read + encode -------------------------------------- */
    uint32_t *all = NULL;
    size_t n = 0;
    double start = 0;
    if (rank == 0) {
        size_t nn = 0;
        int32_t *raw = read_keys_file(st->a.path, &nn);
        if (!raw || nn == 0) {
            char msg[512];
            snprintf(msg, sizeof msg,
                     "sort(): '%s' is not a valid file for read.", st->a.path);
            comm_abort(c, 1, msg);
        }
        all = (uint32_t *)malloc(nn * sizeof(uint32_t));
        for (size_t i = 0; i < nn; i++) all[i] = key_encode(raw[i]);
        free(raw);
        n = nn;
        if (debug > 1) printf("[MASTER] Read file: %s (%zu keys)\n", st->a.path, n);
        start = comm_wtime();
    }
    uint64_t n64 = (uint64_t)n;
    comm_bcast(c, &n64, sizeof n64, 0);
    n = (size_t)n64;

    /* -- distribute ONCE; keys stay resident across passes ---------- */
    size_t m = block_count(n, P, rank);
    size_t cap = m + 1;
    uint32_t *mine = (uint32_t *)malloc(cap * sizeof(uint32_t));
    uint32_t *tmp = (uint32_t *)malloc(cap * sizeof(uint32_t));
    size_t *counts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *displs = (size_t *)malloc((size_t)P * sizeof(size_t));
    for (int i = 0; i < P; i++) {
        counts[i] = block_count(n, P, i) * sizeof(uint32_t);
        displs[i] = block_start(n, P, i) * sizeof(uint32_t);
    }
    comm_scatterv(c, all, counts, displs, mine, m * sizeof(uint32_t), 0);

    /* -- pass planning: bits above msb(global max^min) are constant -- */
    uint32_t lmin = 0xFFFFFFFFu, lmax = 0; /* identities for empty blocks */
    for (size_t i = 0; i < m; i++) {
        if (mine[i] < lmin) lmin = mine[i];
        if (mine[i] > lmax) lmax = mine[i];
    }
    uint32_t gmin, gmax;
    comm_allreduce(c, &lmin, &gmin, 1, COMM_T_U32, COMM_OP_MIN);
    comm_allreduce(c, &lmax, &gmax, 1, COMM_T_U32, COMM_OP_MAX);
    uint32_t diff = gmin ^ gmax;
    unsigned need_bits = 0; /* bound the shift: x>>32 is UB on uint32 */
    while (need_bits < 32 && (diff >> need_bits)) need_bits++;
    unsigned passes = (need_bits + bits - 1) / bits;
    if (debug && rank == 0)
        printf("[COMMON] 0: %u digit passes of %u bits\n", passes, bits);

    /* comm_exscan/allreduce traffic in uint64; size_t buffers are passed
     * through directly, which is only sound on LP64. */
    _Static_assert(sizeof(size_t) == sizeof(uint64_t),
                   "radix_sort assumes 64-bit size_t");
    size_t *hist = (size_t *)malloc(bins * sizeof(size_t));
    size_t *offs = (size_t *)malloc(bins * sizeof(size_t));
    size_t *before = (size_t *)malloc(bins * sizeof(size_t));
    size_t *tot = (size_t *)malloc(bins * sizeof(size_t));
    size_t *scounts = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t *sdispls = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t *rcounts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rdispls = (size_t *)malloc((size_t)P * sizeof(size_t));
    uint32_t *recvbuf = (uint32_t *)malloc(cap * sizeof(uint32_t));

    for (unsigned pass = 0; pass < passes; pass++) {
        const unsigned shift = pass * bits;

        /* local stable counting sort by this digit (+ histogram) */
        counting_sort_digit(mine, tmp, m, shift, bins, hist, offs);

        /* Global layout from two bins-wide reductions: before[d] =
         * Σ_{r<rank} hist_r[d] (the MPI_Exscan census row) and tot[d] =
         * Σ_r hist_r[d].  My element with digit d, occurrence o sits at
         * global position digit_base[d] + before[d] + o; walk digits in
         * order accumulating my segment boundaries to get send counts.
         * (The reference's MPI_Gather+prefix+Gatherv root dance,
         * :180-194, reduced to O(bins) replicated data per rank.) */
        comm_exscan(c, hist, before, bins, COMM_T_U64, COMM_OP_SUM);
        comm_allreduce(c, hist, tot, bins, COMM_T_U64, COMM_OP_SUM);
        memset(scounts, 0, (size_t)P * sizeof(size_t));
        size_t digit_base = 0;
        for (unsigned d = 0; d < bins; d++) {
            size_t pos = digit_base + before[d]; /* my run of hist[d] keys */
            for (size_t o = 0; o < hist[d];) {
                int owner = block_owner(n, P, pos + o);
                size_t owner_end = block_start(n, P, owner) + block_count(n, P, owner);
                size_t take = owner_end - (pos + o);
                if (take > hist[d] - o) take = hist[d] - o;
                scounts[owner] += take * sizeof(uint32_t);
                o += take;
            }
            digit_base += tot[d];
        }
        size_t acc = 0;
        for (int p = 0; p < P; p++) { sdispls[p] = acc; acc += scounts[p]; }

        /* counts as data, then the key exchange */
        comm_alltoall(c, scounts, rcounts, sizeof(size_t));
        size_t total = 0;
        for (int p = 0; p < P; p++) { rdispls[p] = total; total += rcounts[p]; }
        comm_alltoallv(c, mine, scounts, sdispls, recvbuf, rcounts, rdispls);

        /* receiver merge: concatenation is source-major; a stable
         * counting sort by the SAME digit restores (digit, source,
         * occurrence) = exact global order (the TPU receiver does this
         * with one lax.sort; the reference re-gathers to root instead). */
        memcpy(mine, recvbuf, m * sizeof(uint32_t));
        counting_sort_digit(mine, tmp, m, shift, bins, hist, offs);
    }

    /* -- gather to root (verification/output only) ------------------ */
    size_t my_bytes = m * sizeof(uint32_t);
    comm_gatherv(c, mine, my_bytes, all, counts, displs, 0);

    if (rank == 0) {
        double end = comm_wtime();
        print_result(all, n, end - start, debug);
        free(all);
    }
    free(mine); free(tmp); free(counts); free(displs);
    free(hist); free(offs); free(before); free(tot); free(scounts);
    free(sdispls); free(rcounts); free(rdispls); free(recvbuf);
}

int main(int argc, char **argv) {
    prog_state st = {{NULL, 0}};
    if (parse_args(argc, argv, &st.a) != 0) return EXIT_FAILURE;
    return comm_launch(run, &st);
}
