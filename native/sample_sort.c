/* sample_sort — distributed splitter-based sort on the comm.h shim.
 *
 * Same capability as the reference program (mpi_sample_sort.c:28-218):
 * block-distribute, local sort, sample, splitters, repartition by
 * splitter, exchange, local sort, gather to root — with the redesigned
 * internals this repo's TPU engine uses (mpitest_tpu/models/
 * sample_sort.py is the same algorithm over XLA collectives):
 *
 *   - splitters are computed REPLICATED from an allgather of samples —
 *     no root protocol, no per-sample Isend with index-as-tag
 *     (mpi_sample_sort.c:101-132);
 *   - bucket boundaries come from binary search over the locally sorted
 *     block (O(P log m)), not an O(P)-per-key linear scan (:148-155);
 *   - the exchange is a real alltoallv with explicit counts — no fixed
 *     1.5x bucket cap to overflow under skew (:140-144), no payload
 *     length smuggled in message tags (:161);
 *   - P ∤ N is correct (scatterv), negatives are correct (bias encode).
 *
 * Output contract is byte-compatible: "Each bucket will be put %u
 * items." (:74), "The n/2-th sorted element: %d" (:205), stderr
 * "Endtime()-Starttime() = %.5f sec" (:207).
 */
#include "comm.h"
#include "radix_core.h"
#include "sort_common.h"

enum { OVERSAMPLE_FACTOR = 2 }; /* samples/rank = 2P-1, like :89 */

typedef struct {
    sort_args a;
} prog_state;

static void run(comm_ctx *c, void *vs) {
    prog_state *st = (prog_state *)vs;
    const int rank = comm_rank(c), P = comm_size(c);
    const int debug = st->a.debug;

    /* -- rank 0: read + encode ------------------------------------- */
    uint32_t *all = NULL;
    size_t n = 0;
    double start = 0;
    if (rank == 0) {
        size_t nn = 0;
        int32_t *raw = read_keys_file(st->a.path, &nn);
        if (!raw || nn == 0) {
            char msg[512];
            snprintf(msg, sizeof msg,
                     "sort(): '%s' is not a valid file for read.", st->a.path);
            comm_abort(c, 1, msg);
        }
        all = (uint32_t *)malloc(nn * sizeof(uint32_t));
        for (size_t i = 0; i < nn; i++) all[i] = key_encode(raw[i]);
        free(raw);
        n = nn;
        if (debug > 1) printf("[MASTER] Read file: %s (%zu keys)\n", st->a.path, n);
        start = comm_wtime();
    }
    uint64_t n64 = (uint64_t)n;
    comm_bcast(c, &n64, sizeof n64, 0);
    n = (size_t)n64;
    if (rank == 0) printf("Each bucket will be put %zu items.\n", (n + (size_t)P - 1) / (size_t)P);

    /* -- block distribution (scatterv: correct for P ∤ N) ----------- */
    size_t m = block_count(n, P, rank);
    uint32_t *mine = (uint32_t *)malloc((m ? m : 1) * sizeof(uint32_t));
    size_t *counts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *displs = (size_t *)malloc((size_t)P * sizeof(size_t));
    for (int i = 0; i < P; i++) {
        counts[i] = block_count(n, P, i) * sizeof(uint32_t);
        displs[i] = block_start(n, P, i) * sizeof(uint32_t);
    }
    comm_scatterv(c, all, counts, displs, mine, m * sizeof(uint32_t), 0);

    /* -- local sort + evenly spaced samples ------------------------- */
    qsort(mine, m, sizeof(uint32_t), cmp_u32);
    if (debug) printf("[COMMON] %d: local sort of %zu keys OK\n", rank, m);

    const size_t S = (size_t)OVERSAMPLE_FACTOR * (size_t)P - 1; /* 2P-1, like :89 */
    uint32_t *samples = (uint32_t *)malloc(S * sizeof(uint32_t));
    for (size_t i = 0; i < S; i++) {
        /* spread over [0, m) inclusive of both ends; UINT32_MAX pads an
         * empty block (no "no enough sample" abort path, :96-99) */
        samples[i] = m ? mine[i * (m - 1) / (S > 1 ? S - 1 : 1)] : UINT32_MAX;
    }

    /* -- replicated splitters from an allgather --------------------- */
    uint32_t *all_samples = (uint32_t *)malloc((size_t)P * (size_t)S * sizeof(uint32_t));
    comm_allgather(c, samples, all_samples, (size_t)S * sizeof(uint32_t));
    qsort(all_samples, (size_t)P * (size_t)S, sizeof(uint32_t), cmp_u32);
    uint32_t *splitters = (uint32_t *)malloc((size_t)(P - 1) * sizeof(uint32_t));
    for (int i = 1; i < P; i++)
        splitters[i - 1] = all_samples[(size_t)i * (size_t)S];
    if (debug > 1 && rank == 0)
        for (int i = 0; i < P - 1; i++)
            printf("[MASTER] Splitter: %u.\n", splitters[i]);

    /* Skew sniff (the TPU path's _sample_skew_sniff contract,
     * mpitest_tpu/models/api.py): two equal adjacent splitters mean at
     * least 2/P of the sample mass sits on one key value — every copy
     * would route to a single rank and its bucket grows O(N).  The
     * splitters are replicated, so every rank reaches the same verdict
     * with zero extra communication; reroute to the radix core, whose
     * destination = exact global position is skew-immune. */
    int degenerate = 0;
    for (int i = 0; i + 1 < P - 1; i++)
        if (splitters[i] == splitters[i + 1]) { degenerate = 1; break; }

    /* -- bucket boundaries by binary search over the sorted block --- */
    size_t *scounts = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t *sdispls = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t prev = 0;
    for (int p = 0; p < P; p++) {
        size_t hi = m;
        if (p < P - 1) { /* upper_bound(splitters[p]): keys <= splitter go left, like :149 */
            size_t lo = prev;
            hi = m;
            while (lo < hi) {
                size_t mid = lo + (hi - lo) / 2;
                if (mine[mid] <= splitters[p]) lo = mid + 1; else hi = mid;
            }
            hi = lo;
        }
        sdispls[p] = prev * sizeof(uint32_t);
        scounts[p] = (hi - prev) * sizeof(uint32_t);
        prev = hi;
    }

    /* -- exchange: counts as data, then alltoallv ------------------- */
    size_t *rcounts = (size_t *)malloc((size_t)P * sizeof(size_t));
    comm_alltoall(c, scounts, rcounts, sizeof(size_t));
    size_t *rdispls = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t total = 0;
    for (int p = 0; p < P; p++) { rdispls[p] = total; total += rcounts[p]; }

    /* Skew bound (the TPU path's SAMPLE_CAP_LIMIT_FACTOR contract,
     * mpitest_tpu/models/api.py): degenerate splitters under heavy
     * duplication route every copy of a hot key to one rank, making its
     * bucket O(N) instead of O(n/P).  If any rank's incoming bucket
     * would exceed 8·ceil(n/P) keys, all ranks reroute to the radix
     * core, whose destination = exact global position is skew-immune —
     * recv memory stays O(n/P) per rank.  The counts are exact and
     * already exchanged, so detection costs one 8-byte allreduce and
     * happens BEFORE any key moves (the TPU path must run its padded
     * exchange to learn the true counts; here they are free). */
    size_t my_in = total / sizeof(uint32_t), max_in = 0;
    comm_allreduce(c, &my_in, &max_in, 1, COMM_T_U64, COMM_OP_MAX);
    size_t cap_keys = 8 * ((n + (size_t)P - 1) / (size_t)P);
    uint32_t *bucket;
    size_t bn;
    if (degenerate || max_in > cap_keys) {
        if (debug && rank == 0) {
            if (degenerate)
                printf("[COMMON] 0: degenerate splitters (heavy duplication); "
                       "falling back to radix\n");
            else
                printf("[COMMON] 0: exchange needs %zu > O(n) bound %zu keys; "
                       "falling back to radix\n", max_in, cap_keys);
        }
        radix_passes_resident(c, mine, m, n, radix_bits_env(c), debug);
        bn = m;
        bucket = (uint32_t *)malloc((m ? m : 1) * sizeof(uint32_t));
        memcpy(bucket, mine, m * sizeof(uint32_t));
    } else {
        bucket = (uint32_t *)malloc((total ? total : 1));
        comm_alltoallv(c, mine, scounts, sdispls, bucket, rcounts, rdispls);
        bn = my_in;
        if (debug) printf("[COMMON] %d: exchange OK, bucket=%zu keys\n", rank, bn);

        /* final local sort */
        qsort(bucket, bn, sizeof(uint32_t), cmp_u32);
    }

    /* Each rank's output offset is the exclusive prefix of bucket sizes —
     * comm_exscan (the :188-192 root-side displacement loop, computed
     * where the data lives); root collects counts+offsets for gatherv. */
    _Static_assert(sizeof(size_t) == sizeof(uint64_t),
                   "sample_sort assumes 64-bit size_t");
    size_t my_bytes = bn * sizeof(uint32_t), my_off = 0;
    comm_exscan(c, &my_bytes, &my_off, 1, COMM_T_U64, COMM_OP_SUM);
    size_t *gcounts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *gdispls = (size_t *)malloc((size_t)P * sizeof(size_t));
    comm_gather(c, &my_bytes, gcounts, sizeof(size_t), 0);
    comm_gather(c, &my_off, gdispls, sizeof(size_t), 0);
    comm_gatherv(c, bucket, my_bytes, all, gcounts, gdispls, 0);

    if (rank == 0) {
        double end = comm_wtime();
        print_result(all, n, end - start, debug);
        free(all);
    }
    free(mine); free(counts); free(displs); free(samples); free(all_samples);
    free(splitters); free(scounts); free(sdispls); free(rcounts);
    free(rdispls); free(bucket); free(gcounts); free(gdispls);
}

int main(int argc, char **argv) {
    prog_state st = {{NULL, 0}};
    if (parse_args(argc, argv, &st.a) != 0) return EXIT_FAILURE;
    return comm_launch(run, &st);
}
