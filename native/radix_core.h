/* radix_core.h — the rank-resident distributed radix pass loop, shared
 * by radix_sort.c (its whole algorithm) and sample_sort.c (its skew
 * fallback: when degenerate splitters would blow the O(n) exchange
 * bound, the sample program reroutes to this skew-immune core — the
 * same fallback the TPU path takes, mpitest_tpu/models/api.py
 * SAMPLE_CAP_LIMIT_FACTOR).
 *
 * Design (vs the reference's per-pass root round-trip,
 * mpi_radix_sort.c:133-195): keys stay RESIDENT on their ranks across
 * all passes; destination = exact global stable position from two
 * bins-wide reductions (exscan + allreduce of the digit histogram), so
 * every rank holds exactly its block size after every pass regardless
 * of skew.
 *
 * Debug contract (the reference's last observable behavior,
 * mpi_radix_sort.c:142,175-178):
 *   debug>=1: per pass, "[VERBOSE] %d: Scatter OK LOOP %u - %u" with
 *             the rank's first/last resident key (the reference prints
 *             its freshly scattered batch bounds; keys here are already
 *             resident, same information).
 *   debug>2:  per pass, "[COMMON] %d: Main Queue Completed, LEN=%zu"
 *             then one "DUMP: LOOP %u RADIX %d = %u" line per resident
 *             key — LOOP counts from 1, RADIX is the rank id, the value
 *             prints as %u of the raw int32 pattern, all exactly like
 *             the reference.
 */
#ifndef RADIX_CORE_H
#define RADIX_CORE_H

#include "comm.h"
#include "sort_common.h"

/* Stable counting sort of `m` keys by digit (shift/mask), also filling
 * hist[bins].  `tmp` is scratch of m elements; result ends in keys. */
static inline void counting_sort_digit(uint32_t *keys, uint32_t *tmp, size_t m,
                                       unsigned shift, unsigned bins,
                                       size_t *hist, size_t *offs) {
    const uint32_t mask = bins - 1;
    memset(hist, 0, bins * sizeof(size_t));
    for (size_t i = 0; i < m; i++) hist[(keys[i] >> shift) & mask]++;
    size_t acc = 0;
    for (unsigned b = 0; b < bins; b++) { offs[b] = acc; acc += hist[b]; }
    for (size_t i = 0; i < m; i++) tmp[offs[(keys[i] >> shift) & mask]++] = keys[i];
    memcpy(keys, tmp, m * sizeof(uint32_t));
}

/* Run all needed LSD digit passes over the rank-resident block `mine`
 * (m = block_count(n, P, rank) keys, bias-encoded).  On return, `mine`
 * holds block `rank` of the globally sorted array.  `bits` is the digit
 * width in [1, 16]. */
static inline void radix_passes_resident(comm_ctx *c, uint32_t *mine,
                                         size_t m, size_t n, unsigned bits,
                                         int debug) {
    const int rank = comm_rank(c), P = comm_size(c);
    const unsigned bins = 1u << bits;

    /* pass planning: bits above msb(global max^min) are constant */
    uint32_t lmin = 0xFFFFFFFFu, lmax = 0; /* identities for empty blocks */
    for (size_t i = 0; i < m; i++) {
        if (mine[i] < lmin) lmin = mine[i];
        if (mine[i] > lmax) lmax = mine[i];
    }
    uint32_t gmin, gmax;
    comm_allreduce(c, &lmin, &gmin, 1, COMM_T_U32, COMM_OP_MIN);
    comm_allreduce(c, &lmax, &gmax, 1, COMM_T_U32, COMM_OP_MAX);
    uint32_t diff = gmin ^ gmax;
    unsigned need_bits = 0; /* bound the shift: x>>32 is UB on uint32 */
    while (need_bits < 32 && (diff >> need_bits)) need_bits++;
    unsigned passes = (need_bits + bits - 1) / bits;
    if (debug && rank == 0)
        printf("[COMMON] 0: %u digit passes of %u bits\n", passes, bits);

    /* comm_exscan/allreduce traffic in uint64; size_t buffers are passed
     * through directly, which is only sound on LP64. */
    _Static_assert(sizeof(size_t) == sizeof(uint64_t),
                   "radix core assumes 64-bit size_t");
    size_t cap = m + 1;
    uint32_t *tmp = (uint32_t *)malloc(cap * sizeof(uint32_t));
    size_t *hist = (size_t *)malloc(bins * sizeof(size_t));
    size_t *offs = (size_t *)malloc(bins * sizeof(size_t));
    size_t *before = (size_t *)malloc(bins * sizeof(size_t));
    size_t *tot = (size_t *)malloc(bins * sizeof(size_t));
    size_t *scounts = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t *sdispls = (size_t *)calloc((size_t)P, sizeof(size_t));
    size_t *rcounts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rdispls = (size_t *)malloc((size_t)P * sizeof(size_t));
    uint32_t *recvbuf = (uint32_t *)malloc(cap * sizeof(uint32_t));

    for (unsigned pass = 0; pass < passes; pass++) {
        const unsigned shift = pass * bits;
        if (debug && m)
            printf("[VERBOSE] %d: Scatter OK LOOP %u - %u\n", rank,
                   (uint32_t)key_decode(mine[0]),
                   (uint32_t)key_decode(mine[m - 1]));

        /* local stable counting sort by this digit (+ histogram) */
        counting_sort_digit(mine, tmp, m, shift, bins, hist, offs);

        /* Global layout from two bins-wide reductions: before[d] =
         * Σ_{r<rank} hist_r[d] (the MPI_Exscan census row) and tot[d] =
         * Σ_r hist_r[d].  My element with digit d, occurrence o sits at
         * global position digit_base[d] + before[d] + o; walk digits in
         * order accumulating my segment boundaries to get send counts.
         * (The reference's MPI_Gather+prefix+Gatherv root dance,
         * :180-194, reduced to O(bins) replicated data per rank.) */
        comm_exscan(c, hist, before, bins, COMM_T_U64, COMM_OP_SUM);
        comm_allreduce(c, hist, tot, bins, COMM_T_U64, COMM_OP_SUM);
        memset(scounts, 0, (size_t)P * sizeof(size_t));
        size_t digit_base = 0;
        for (unsigned d = 0; d < bins; d++) {
            size_t pos = digit_base + before[d]; /* my run of hist[d] keys */
            for (size_t o = 0; o < hist[d];) {
                int owner = block_owner(n, P, pos + o);
                size_t owner_end = block_start(n, P, owner) + block_count(n, P, owner);
                size_t take = owner_end - (pos + o);
                if (take > hist[d] - o) take = hist[d] - o;
                scounts[owner] += take * sizeof(uint32_t);
                o += take;
            }
            digit_base += tot[d];
        }
        size_t acc = 0;
        for (int p = 0; p < P; p++) { sdispls[p] = acc; acc += scounts[p]; }

        /* counts as data, then the key exchange */
        comm_alltoall(c, scounts, rcounts, sizeof(size_t));
        size_t total = 0;
        for (int p = 0; p < P; p++) { rdispls[p] = total; total += rcounts[p]; }
        comm_alltoallv(c, mine, scounts, sdispls, recvbuf, rcounts, rdispls);

        /* receiver merge: concatenation is source-major; a stable
         * counting sort by the SAME digit restores (digit, source,
         * occurrence) = exact global order (the TPU receiver does this
         * with one lax.sort; the reference re-gathers to root instead). */
        memcpy(mine, recvbuf, m * sizeof(uint32_t));
        counting_sort_digit(mine, tmp, m, shift, bins, hist, offs);

        /* the reference's per-pass intermediate dump
         * (mpi_radix_sort.c:175-178) */
        if (debug > 2) {
            printf("[COMMON] %d: Main Queue Completed, LEN=%zu\n", rank, m);
            for (size_t i = 0; i < m; i++)
                printf("DUMP: LOOP %u RADIX %d = %u\n", pass + 1, rank,
                       (uint32_t)key_decode(mine[i]));
        }
    }

    free(tmp); free(hist); free(offs); free(before); free(tot);
    free(scounts); free(sdispls); free(rcounts); free(rdispls); free(recvbuf);
}

/* Digit width from the RADIX_BITS env knob (default 8); aborts on an
 * out-of-range value. */
static inline unsigned radix_bits_env(comm_ctx *c) {
    const char *env_bits = getenv("RADIX_BITS");
    unsigned bits = env_bits ? (unsigned)atoi(env_bits) : 8u;
    if (bits < 1 || bits > 16)
        comm_abort(c, 1, "radix_sort: RADIX_BITS must be in [1, 16]");
    return bits;
}

#endif /* RADIX_CORE_H */
