/* Supervision probe for the minimpi runtime (links mpi_stub/mpi.h
 * directly — this tests the runtime's job control, not the comm.h
 * surface): rank 1 exits with status 0 BEFORE MPI_Finalize, the
 * "clean" early return that used to strand every peer in the
 * process-shared barrier forever.  The supervisor must detect the
 * unfinalized exit and kill the whole job with a nonzero status
 * (ADVICE r3: zero-exit-before-finalize hang). */
#include <stdlib.h>

#include <mpi.h>

int main(int argc, char **argv) {
    MPI_Init(&argc, &argv);
    int rank;
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    if (rank == 1) exit(0); /* before Finalize: abnormal in all but status */
    MPI_Barrier(MPI_COMM_WORLD); /* peers would block here forever */
    MPI_Finalize();
    return 0;
}
