/* spillz.c — the native spill-run block codec behind
 * mpitest_tpu/store/compress.py.
 *
 * Pack is two tight passes: pass one streams the wrapping deltas to
 * find the pack width while folding the checksum through registers;
 * pass two bit-packs the deltas LSB-first.  The bit buffer never holds
 * more than 39 live bits (deltas enter in <=32-bit slices after a
 * byte-flush), so plain uint64 arithmetic suffices for width 64.
 * Unpack is the mirror image with every read bounds-guarded — the
 * decoder is the one kernel that eats raw disk bytes, so a torn or
 * rotted block must fail loudly (SPZ_EBOUNDS / checksum mismatch) and
 * never index past in[in_len).  Built as libspillz.so by bench/Makefile
 * (`make -C bench libspillz`); -Wconversion -Wshadow -Werror clean
 * (root cwarn-check), ASan/UBSan fuzzed via native/spillz_fuzz.c.
 */
#include "spillz.h"

int spz_abi_version(void) { return SPZ_ABI_VERSION; }

/* 32-bit fold of a uint64 value stream: each value is avalanche-mixed
 * (the murmur3 finalizer) BEFORE the XOR and wrapping-sum accumulate,
 * halves mixed down at the end.  The pre-mix matters: raw XOR+sum is
 * blind to a 2^63 shift applied to an even-length suffix (bit-63 adds
 * are carry-free, so the XOR flips cancel pairwise and the sum wraps
 * to zero) — exactly the shape a single high packed-bit flip produces.
 * Kept in a tiny struct so pack and unpack share the exact rule (and
 * the numpy fallback mirrors it elementwise: m = mix64(vals),
 * x = XOR-reduce(m), s = sum(m) mod 2^64,
 * chk = (x ^ x>>32 ^ s ^ s>>32) & 0xFFFFFFFF). */
typedef struct {
    uint64_t x;
    uint64_t s;
} spz_fold;

static uint64_t mix64(uint64_t z) {
    z ^= z >> 33;
    z *= 0xFF51AFD7ED558CCDULL;
    z ^= z >> 33;
    z *= 0xC4CEB9FE1A85EC53ULL;
    z ^= z >> 33;
    return z;
}

static void fold_step(spz_fold *f, uint64_t v) {
    uint64_t m = mix64(v);
    f->x ^= m;
    f->s += m;
}

static uint32_t fold_final(const spz_fold *f) {
    uint64_t m = f->x ^ (f->x >> 32) ^ f->s ^ (f->s >> 32);
    return (uint32_t)(m & 0xFFFFFFFFu);
}

static int delta_width(uint64_t maxd) {
    int w = 0;
    while (maxd) {
        w++;
        maxd >>= 1;
    }
    return w;
}

static size_t packed_bytes(size_t n, int width) {
    /* n >= 1: (n-1) deltas at width bits, zero-padded to whole bytes */
    return ((n - 1) * (size_t)width + 7u) / 8u;
}

long long spz_pack_block(const uint64_t *vals, size_t n,
                         unsigned char *out, size_t cap,
                         uint64_t *first, int *width,
                         uint32_t *checksum) {
    spz_fold fold = {0, 0};
    uint64_t maxd = 0;
    size_t i, need, pos = 0;
    uint64_t acc = 0;
    unsigned nbits = 0;
    int w;

    if (n == 0)
        return SPZ_EBOUNDS;
    fold_step(&fold, (uint64_t)vals[0]);
    for (i = 1; i < n; i++) {
        uint64_t d = (uint64_t)vals[i] - (uint64_t)vals[i - 1];
        if (d > maxd)
            maxd = d;
        fold_step(&fold, (uint64_t)vals[i]);
    }
    w = delta_width(maxd);
    need = packed_bytes(n, w);
    if (need > cap)
        return SPZ_EBOUNDS;
    for (i = 1; i < n; i++) {
        uint64_t d = (uint64_t)vals[i] - (uint64_t)vals[i - 1];
        unsigned rem = (unsigned)w;
        while (rem > 0) {
            /* flush first, then take <=32 bits: nbits <= 7 here, so
             * the buffer tops out at 39 live bits — no 128-bit math */
            unsigned take = rem > 32u ? 32u : rem;
            uint64_t mask = (take == 64u) ? ~0ULL
                                          : ((1ULL << take) - 1ULL);
            acc |= (d & mask) << nbits;
            nbits += take;
            d >>= take;
            rem -= take;
            while (nbits >= 8u) {
                out[pos++] = (unsigned char)(acc & 0xFFu);
                acc >>= 8;
                nbits -= 8u;
            }
        }
    }
    if (nbits > 0u)
        out[pos++] = (unsigned char)(acc & 0xFFu);  /* zero-padded tail */
    *first = (unsigned long long)vals[0];
    *width = w;
    *checksum = fold_final(&fold);
    return (long long)pos;
}

long long spz_unpack_block(const unsigned char *in, size_t in_len,
                           size_t n, uint64_t first,
                           int width, uint64_t *vals_out,
                           uint32_t *checksum_out) {
    spz_fold fold = {0, 0};
    uint64_t v = (uint64_t)first;
    uint64_t acc = 0;
    unsigned nbits = 0;
    size_t i, pos = 0;

    if (n == 0)
        return SPZ_EBOUNDS;
    if (width < 0 || width > 64)
        return SPZ_EWIDTH;
    if (in_len != packed_bytes(n, width))
        return SPZ_EBOUNDS;
    vals_out[0] = (unsigned long long)v;
    fold_step(&fold, v);
    for (i = 1; i < n; i++) {
        uint64_t d = 0;
        unsigned got = 0;
        while (got < (unsigned)width) {
            unsigned take;
            if (nbits == 0u) {
                if (pos >= in_len)
                    return SPZ_EBOUNDS;  /* belt-and-braces: torn body */
                acc = (uint64_t)in[pos++];
                nbits = 8u;
            }
            take = (unsigned)width - got;
            if (take > nbits)
                take = nbits;
            d |= (acc & ((1ULL << take) - 1ULL)) << got;
            acc >>= take;
            nbits -= take;
            got += take;
        }
        v += d;  /* wrapping: the pack side's deltas are mod 2^64 */
        vals_out[i] = (unsigned long long)v;
        fold_step(&fold, v);
    }
    *checksum_out = fold_final(&fold);
    return (long long)n;
}
