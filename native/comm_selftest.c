/* comm_selftest — correctness harness for the comm.h shim surface.
 *
 * Exercises every collective against closed-form expectations on
 * rank-dependent inputs, across whatever COMM_RANKS the runner sets.
 * This is the test the reference never had for its hand-rolled
 * collectives (SURVEY.md §4: the reference's only verification is a
 * human eyeballing the median line); here each primitive is checked in
 * isolation so a shim bug cannot hide behind an algorithm bug.
 *
 * Exit 0 on success; prints the failing check and exits nonzero via
 * comm_abort otherwise.
 */
#include "comm.h"

#include <inttypes.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define CHECK(c, cond, what)                                               \
    do {                                                                   \
        if (!(cond)) {                                                     \
            char _m[256];                                                  \
            snprintf(_m, sizeof _m, "comm_selftest FAILED: %s (rank %d)",  \
                     (what), comm_rank(c));                                \
            comm_abort((c), 1, _m);                                        \
        }                                                                  \
    } while (0)

static void run(comm_ctx *c, void *arg) {
    (void)arg;
    const int r = comm_rank(c), P = comm_size(c);

    /* bcast: root's payload reaches everyone */
    uint64_t v = (r == 0) ? 0xC0FFEEu : 0;
    comm_bcast(c, &v, sizeof v, 0);
    CHECK(c, v == 0xC0FFEEu, "bcast");

    /* scatter/gather round-trip: rank r gets block r, returns it */
    uint32_t *blocks = NULL, got = 0;
    if (r == 0) {
        blocks = (uint32_t *)malloc((size_t)P * sizeof(uint32_t));
        for (int i = 0; i < P; i++) blocks[i] = 100u + (uint32_t)i;
    }
    comm_scatter(c, blocks, &got, sizeof got, 0);
    CHECK(c, got == 100u + (uint32_t)r, "scatter");
    comm_gather(c, &got, blocks, sizeof got, 0);
    if (r == 0)
        for (int i = 0; i < P; i++)
            CHECK(c, blocks[i] == 100u + (uint32_t)i, "gather");

    /* scatterv/gatherv: ragged blocks of r+1 elements */
    size_t *cnt = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *dsp = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t tot = 0;
    for (int i = 0; i < P; i++) {
        cnt[i] = (size_t)(i + 1) * sizeof(uint32_t);
        dsp[i] = tot;
        tot += cnt[i];
    }
    uint32_t *ragged = NULL;
    if (r == 0) {
        ragged = (uint32_t *)malloc(tot);
        for (size_t i = 0; i < tot / sizeof(uint32_t); i++)
            ragged[i] = (uint32_t)i;
    }
    uint32_t mine[1024];
    comm_scatterv(c, ragged, cnt, dsp, mine, sizeof mine, 0);
    for (int i = 0; i <= r; i++)
        CHECK(c, mine[i] == (uint32_t)(dsp[r] / sizeof(uint32_t) + (size_t)i),
              "scatterv");
    for (int i = 0; i <= r; i++) mine[i] += 1000u;
    comm_gatherv(c, mine, cnt[r], ragged, cnt, dsp, 0);
    if (r == 0)
        for (size_t i = 0; i < tot / sizeof(uint32_t); i++)
            CHECK(c, ragged[i] == 1000u + (uint32_t)i, "gatherv");

    /* allgather */
    uint32_t *ag = (uint32_t *)malloc((size_t)P * sizeof(uint32_t));
    uint32_t me32 = 7u * (uint32_t)r + 3u;
    comm_allgather(c, &me32, ag, sizeof me32);
    for (int i = 0; i < P; i++)
        CHECK(c, ag[i] == 7u * (uint32_t)i + 3u, "allgather");

    /* allreduce: sum / min / max, u32 and u64, vector width 3 */
    uint32_t s32[3] = {(uint32_t)r, 1u, (uint32_t)(r * r)}, o32[3];
    comm_allreduce(c, s32, o32, 3, COMM_T_U32, COMM_OP_SUM);
    CHECK(c, o32[1] == (uint32_t)P, "allreduce sum u32");
    CHECK(c, o32[0] == (uint32_t)(P * (P - 1) / 2), "allreduce sum series");
    comm_allreduce(c, s32, o32, 3, COMM_T_U32, COMM_OP_MIN);
    CHECK(c, o32[0] == 0u, "allreduce min");
    comm_allreduce(c, s32, o32, 3, COMM_T_U32, COMM_OP_MAX);
    CHECK(c, o32[0] == (uint32_t)(P - 1), "allreduce max");
    uint64_t s64 = 1ull << (r % 48), o64 = 0;
    comm_allreduce(c, &s64, &o64, 1, COMM_T_U64, COMM_OP_MAX);
    CHECK(c, o64 == 1ull << (P - 1 < 48 ? P - 1 : 47), "allreduce max u64");

    /* exscan: rank 0 gets the defined identity, rank r the prefix */
    uint64_t inc = (uint64_t)r + 1, pre = 42;
    comm_exscan(c, &inc, &pre, 1, COMM_T_U64, COMM_OP_SUM);
    CHECK(c, pre == (uint64_t)r * (uint64_t)(r + 1) / 2, "exscan sum");
    uint32_t one = (uint32_t)r, lowest = 0;
    comm_exscan(c, &one, &lowest, 1, COMM_T_U32, COMM_OP_MIN);
    CHECK(c, lowest == (r == 0 ? 0xFFFFFFFFu : 0u), "exscan min identity");

    /* alltoall: block (i -> j) carries i*P+j */
    uint32_t *sa = (uint32_t *)malloc((size_t)P * sizeof(uint32_t));
    uint32_t *ra = (uint32_t *)malloc((size_t)P * sizeof(uint32_t));
    for (int j = 0; j < P; j++) sa[j] = (uint32_t)(r * P + j);
    comm_alltoall(c, sa, ra, sizeof(uint32_t));
    for (int i = 0; i < P; i++)
        CHECK(c, ra[i] == (uint32_t)(i * P + r), "alltoall");

    /* alltoallv: rank i sends j+1 elements to rank j, value i*1000+j */
    size_t *sc = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *sd = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rc = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *rd = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t off = 0;
    for (int j = 0; j < P; j++) {
        sc[j] = (size_t)(j + 1) * sizeof(uint32_t);
        sd[j] = off;
        off += sc[j];
    }
    uint32_t *sbuf = (uint32_t *)malloc(off);
    for (int j = 0; j < P; j++)
        for (int k = 0; k <= j; k++)
            sbuf[sd[j] / sizeof(uint32_t) + (size_t)k] = (uint32_t)(r * 1000 + j);
    off = 0;
    for (int i = 0; i < P; i++) {
        rc[i] = (size_t)(r + 1) * sizeof(uint32_t);
        rd[i] = off;
        off += rc[i];
    }
    uint32_t *rbuf = (uint32_t *)malloc(off);
    comm_alltoallv(c, sbuf, sc, sd, rbuf, rc, rd);
    for (int i = 0; i < P; i++)
        for (int k = 0; k <= r; k++)
            CHECK(c, rbuf[rd[i] / sizeof(uint32_t) + (size_t)k] ==
                         (uint32_t)(i * 1000 + r), "alltoallv");

    /* wtime monotonic; barrier completes */
    double t0 = comm_wtime();
    comm_barrier(c);
    CHECK(c, comm_wtime() >= t0, "wtime monotonic");

    if (r == 0) printf("comm_selftest OK (%d ranks)\n", P);
    free(blocks); free(cnt); free(dsp); free(ragged); free(ag);
    free(sa); free(ra); free(sc); free(sd); free(rc); free(rd);
    free(sbuf); free(rbuf);
}

int main(void) { return comm_launch(run, NULL); }
