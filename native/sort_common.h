/* sort_common.h — pieces shared by the two native sort drivers.
 *
 * Reproduces the reference's I/O and CLI behavior minus its bugs
 * (SURVEY.md §7.4): the reader counts exactly the integers present (no
 * feof overcount, mpi_sample_sort.c:50), grows geometrically instead of
 * one int per realloc (:53), and keys are bias-encoded to uint32 so
 * negative keys order correctly (the reference sorts by |x|,
 * mpi_radix_sort.c:50,56).
 */
#ifndef SORT_COMMON_H
#define SORT_COMMON_H

#include <inttypes.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "comm.h"

/* Order-preserving encode: int32 -> uint32 (flip sign bit). */
static inline uint32_t key_encode(int32_t v) {
    return (uint32_t)v ^ 0x80000000u;
}
static inline int32_t key_decode(uint32_t u) {
    return (int32_t)(u ^ 0x80000000u);
}

/* Binary input header: 8 bytes "SORTBIN1", 1 byte dtype kind
 * ('i'/'u'), 1 byte itemsize, 6 pad bytes, then raw little-endian
 * keys.  The dtype tag makes width/signedness mismatches a hard error.
 * The text contract stays the reference's; binary is the fast path for
 * 2^28+ benches where text parsing would dominate setup (the Python
 * side mirrors this in mpitest_tpu/utils/io.py). */
#define SORT_BIN_MAGIC "SORTBIN1"
#define SORT_BIN_HEADER_LEN 16

/* Read keys: binary if the file starts with SORT_BIN_MAGIC (int32 tag
 * required), else all whitespace-separated decimal int32s (exact
 * count, geometric growth — no feof overcount).  Returns NULL (with
 * *out_n untouched) on open failure or a dtype-tag mismatch. */
static inline int32_t *read_keys_file(const char *path, size_t *out_n) {
    FILE *f = fopen(path, "rb");
    if (!f) return NULL;
    unsigned char header[SORT_BIN_HEADER_LEN];
    size_t got = fread(header, 1, sizeof header, f);
    if (got == sizeof header && memcmp(header, SORT_BIN_MAGIC, 8) == 0) {
        if (header[8] != 'i' || header[9] != sizeof(int32_t)) {
            fprintf(stderr, "read_keys_file: '%s' holds %c%u keys, not int32\n",
                    path, header[8], 8u * header[9]);
            fclose(f);
            return NULL;
        }
        if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return NULL; }
        long end = ftell(f);
        if (end < SORT_BIN_HEADER_LEN) { fclose(f); return NULL; }
        size_t n = ((size_t)end - SORT_BIN_HEADER_LEN) / sizeof(int32_t);
        int32_t *buf = (int32_t *)malloc((n ? n : 1) * sizeof(int32_t));
        if (!buf) { fclose(f); return NULL; }
        if (fseek(f, SORT_BIN_HEADER_LEN, SEEK_SET) != 0 ||
            fread(buf, sizeof(int32_t), n, f) != n) {
            free(buf); fclose(f); return NULL;
        }
        fclose(f);
        *out_n = n;
        return buf;
    }
    rewind(f);
    size_t cap = 1024, n = 0;
    int32_t *buf = (int32_t *)malloc(cap * sizeof(int32_t));
    if (!buf) { fclose(f); return NULL; }
    long long v;
    while (fscanf(f, "%lld", &v) == 1) {
        if (n == cap) {
            cap *= 2;
            int32_t *nb = (int32_t *)realloc(buf, cap * sizeof(int32_t));
            if (!nb) { free(buf); fclose(f); return NULL; }
            buf = nb;
        }
        buf[n++] = (int32_t)v;
    }
    fclose(f);
    *out_n = n;
    return buf;
}

/* Block distribution: rank i owns n/P + (i < n%P) keys — every rank's
 * buffer matches what it receives (the reference ships ceil(N/P) to a
 * smaller last-rank buffer whenever P does not divide N,
 * mpi_sample_sort.c:80-82). */
static inline size_t block_count(size_t n, int nranks, int rank) {
    size_t q = n / (size_t)nranks, r = n % (size_t)nranks;
    return q + ((size_t)rank < r ? 1 : 0);
}
static inline size_t block_start(size_t n, int nranks, int rank) {
    size_t q = n / (size_t)nranks, r = n % (size_t)nranks;
    size_t rr = (size_t)rank < r ? (size_t)rank : r;
    return q * (size_t)rank + rr;
}
/* Owner of global position `pos` under the same distribution. */
static inline int block_owner(size_t n, int nranks, size_t pos) {
    size_t q = n / (size_t)nranks, r = n % (size_t)nranks;
    if (q == 0) return (int)pos; /* n < P: one key per low rank */
    if (pos < (q + 1) * r) return (int)(pos / (q + 1));
    return (int)(r + (pos - (q + 1) * r) / q);
}

static inline int cmp_u32(const void *a, const void *b) {
    uint32_t x = *(const uint32_t *)a, y = *(const uint32_t *)b;
    return x < y ? -1 : (x > y ? 1 : 0);
}

/* The reference's machine interface (SURVEY.md §5 metrics row):
 * stdout median probe + optional full dump, stderr elapsed seconds. */
static inline void print_result(const uint32_t *sorted, size_t n,
                                double elapsed, int debug) {
    if (debug > 2) {
        for (size_t i = 0; i < n; i++)
            printf("%zu|%u\n", i, (uint32_t)key_decode(sorted[i]));
    }
    size_t mid = n >= 2 ? n / 2 - 1 : 0;
    printf("The n/2-th sorted element: %d\n", key_decode(sorted[mid]));
    fprintf(stderr, "Endtime()-Starttime() = %.5f sec\n", elapsed);
}

/* argv contract shared by both drivers (mpi_sample_sort.c:230-237). */
typedef struct {
    const char *path;
    int debug;
} sort_args;

static inline int parse_args(int argc, char **argv, sort_args *out) {
    if (argc != 2 && argc != 3) {
        fprintf(stderr, "Usage: %s <file: Data file to read>\n", argv[0]);
        return -1;
    }
    out->path = argv[1];
    out->debug = argc == 3 ? atoi(argv[2]) : 0;
    return 0;
}

#endif /* SORT_COMMON_H */
