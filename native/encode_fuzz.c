/* encode_fuzz.c — seeded, bounded fuzz driver over the native encode
 * kernels (ISSUE 6 satellite).  Usage: encode_fuzz <seed> <iters>.
 *
 * Three corpora per run, drawn from one splitmix64 stream so the SAME
 * seed replays the SAME byte sequences in every build:
 *
 *  - binary: random bytes encoded as each supported dtype, fp on AND
 *    off; the fold returned by the kernel is re-derived from the words
 *    it wrote by an independent scalar loop and must match exactly
 *    (catches any vectorization/UB divergence between the two);
 *  - text: token streams mixing valid decimals (all widths, both
 *    signs, container-boundary values), oversized numbers and garbage
 *    bytes; enc_count_tokens must agree with the parse count on
 *    success, and error statuses/offsets fold into the checksum;
 *  - header: random and near-valid 16-byte SORTBIN1 headers.
 *
 * Everything folds into one checksum printed at exit:
 * `make sanitize-selftest` runs this under ASan+UBSan and as a plain
 * build and requires identical output (the cross-build differential),
 * with the shared suppressions file empty by policy.  Any internal
 * inconsistency exits 1 immediately.
 */
#include <inttypes.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "encode.h"

static uint64_t sm_state;

static uint64_t sm_next(void) {
    uint64_t z = (sm_state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

static uint64_t checksum;

static void fold_u64(uint64_t v) {
    checksum = (checksum ^ v) * 0x100000001B3ULL;  /* FNV-ish mix */
}

static void die(const char *what, uint64_t iter) {
    fprintf(stderr, "encode_fuzz: INVARIANT VIOLATION: %s (iter %" PRIu64
            ")\n", what, iter);
    exit(1);
}

/* independent scalar re-derivation of the fold from the written words */
static void check_fold_against_words(const uint32_t *w0, const uint32_t *w1,
                                     size_t n, int two, const enc_fold *f,
                                     int fp, uint64_t iter) {
    uint32_t mn0 = 0xFFFFFFFFu, mx0 = 0, xr0 = 0, sm0 = 0;
    uint32_t mn1 = 0xFFFFFFFFu, mx1 = 0, xr1 = 0, sm1 = 0;
    uint64_t lex = 0;
    for (size_t i = 0; i < n; i++) {
        uint32_t hi = w0[i], lo = two ? w1[i] : 0;
        if (hi < mn0) mn0 = hi;
        if (hi > mx0) mx0 = hi;
        xr0 ^= hi; sm0 += hi;
        if (two) {
            if (lo < mn1) mn1 = lo;
            if (lo > mx1) mx1 = lo;
            xr1 ^= lo; sm1 += lo;
            uint64_t u = ((uint64_t)hi << 32) | lo;
            if (u > lex) lex = u;
        }
    }
    if (f->count != (uint64_t)n) die("fold count", iter);
    if (n == 0) return;
    if (f->min0 != mn0 || f->max0 != mx0) die("word0 min/max", iter);
    if (fp && (f->xor0 != xr0 || f->sum0 != sm0)) die("word0 fp", iter);
    if (two) {
        if (f->min1 != mn1 || f->max1 != mx1) die("word1 min/max", iter);
        if (fp && (f->xor1 != xr1 || f->sum1 != sm1)) die("word1 fp", iter);
        if (f->lexmax0 != (uint32_t)(lex >> 32) ||
            f->lexmax1 != (uint32_t)(lex & 0xFFFFFFFFu))
            die("lexmax", iter);
    } else if (f->lexmax0 != mx0) {
        die("lexmax (1w)", iter);
    }
}

static const struct { char kind; int size; } DTYPES[] = {
    {'i', 1}, {'u', 1}, {'i', 2}, {'u', 2}, {'i', 4}, {'u', 4},
    {'i', 8}, {'u', 8}, {'f', 4}, {'f', 8},
};

#define MAX_N 4096

static void fuzz_binary(uint64_t iter) {
    size_t n = (size_t)(sm_next() % (MAX_N + 1));
    unsigned d = (unsigned)(sm_next() % 10u);
    char kind = DTYPES[d].kind;
    int isz = DTYPES[d].size;
    uint64_t *src = (uint64_t *)malloc((n ? n : 1) * 8u);
    uint32_t *w0 = (uint32_t *)malloc((n ? n : 1) * 4u);
    uint32_t *w1 = (uint32_t *)malloc((n ? n : 1) * 4u);
    if (!src || !w0 || !w1) die("malloc", iter);
    for (size_t i = 0; i < (n * (size_t)isz + 7) / 8; i++)
        src[i] = sm_next();
    int fp = (int)(sm_next() & 1u);
    enc_fold f;
    int rc = enc_encode_fold(src, n, kind, isz, w0, w1, fp, &f);
    if (rc != ENC_OK) die("encode rc", iter);
    int two = isz == 8;
    /* fp=0 still folds min/max/lexmax; re-derive with fp checking only
     * when the kernel was asked to fold it */
    check_fold_against_words(w0, w1, n, two, &f, fp, iter);
    fold_u64(f.count); fold_u64(((uint64_t)f.xor0 << 32) | f.sum0);
    fold_u64(((uint64_t)f.min0 << 32) | f.max0);
    fold_u64(((uint64_t)f.lexmax0 << 32) | f.lexmax1);
    for (size_t i = 0; i < n; i += 97)
        fold_u64(w0[i]);
    /* unsupported dtype probe must never write */
    if (enc_encode_fold(src, n, 'c', 8, w0, w1, 1, &f) != ENC_EDTYPE)
        die("EDTYPE", iter);
    free(src); free(w0); free(w1);
}

static void fuzz_text(uint64_t iter) {
    char buf[2048];
    size_t len = 0;
    unsigned n_toks = (unsigned)(sm_next() % 64u);
    for (unsigned t = 0; t < n_toks && len + 64 < sizeof buf; t++) {
        uint64_t r = sm_next() % 16u;
        if (r == 0) {                     /* mixed digit/letter garbage:
                                           * the mid-token ENC_EBADTOK
                                           * branch ("12a3") */
            unsigned gl = (unsigned)(sm_next() % 8u) + 1u;
            for (unsigned i = 0; i < gl; i++)
                buf[len++] = (sm_next() & 1u)
                    ? (char)('0' + (int)(sm_next() % 10u))
                    : (char)('a' + (int)(sm_next() % 26u));
        } else if (r == 1) {              /* bare sign token */
            buf[len++] = (sm_next() & 1u) ? '-' : '+';
        } else {                          /* decimal: maybe signed,
                                           * maybe oversized, maybe
                                           * underscore-grouped (legal
                                           * AND illegal placements) */
            if (sm_next() & 1u)
                buf[len++] = (sm_next() & 1u) ? '-' : '+';
            unsigned dl = (unsigned)(sm_next() % 24u) + 1u;
            for (unsigned i = 0; i < dl; i++) {
                buf[len++] = (char)('0' + (int)(sm_next() % 10u));
                if (sm_next() % 8u == 0)
                    buf[len++] = '_';    /* sometimes trailing = bad */
            }
        }
        buf[len++] = (sm_next() & 1u) ? ' ' : '\n';
    }
    long long cnt = enc_count_tokens(buf, len);
    if (cnt < 0 || (uint64_t)cnt > len) die("count_tokens", iter);
    size_t cap = (size_t)cnt;
    int64_t *oi = (int64_t *)malloc((cap ? cap : 1) * 8u);
    uint64_t *ou = (uint64_t *)malloc((cap ? cap : 1) * 8u);
    if (!oi || !ou) die("malloc", iter);
    size_t bad = 0;
    long long ri = enc_parse_i64(buf, len, oi, cap, &bad);
    if (ri >= 0) {
        if (ri != cnt) die("i64 count mismatch", iter);
        for (long long i = 0; i < ri; i++)
            fold_u64((uint64_t)oi[i]);
    } else {
        if (ri == ENC_ECAP || bad >= len) die("i64 error shape", iter);
        fold_u64((uint64_t)(-ri) ^ (uint64_t)bad);
    }
    long long ru = enc_parse_u64(buf, len, ou, cap, &bad);
    if (ru >= 0) {
        if (ru != cnt) die("u64 count mismatch", iter);
        for (long long i = 0; i < ru; i++)
            fold_u64(ou[i]);
    } else {
        if (ru == ENC_ECAP || bad >= len) die("u64 error shape", iter);
        fold_u64((uint64_t)(-ru) ^ (uint64_t)bad);
    }
    free(oi); free(ou);
}

static void fuzz_header(uint64_t iter) {
    unsigned char hdr[16];
    uint64_t r = sm_next();
    if (r & 1u)
        memcpy(hdr, "SORTBIN1", 8);
    else
        for (int i = 0; i < 8; i++)
            hdr[i] = (unsigned char)sm_next();
    hdr[8] = (unsigned char)"iufc"[sm_next() % 4u];
    hdr[9] = (unsigned char)(sm_next() % 12u);
    for (int i = 10; i < 16; i++)
        hdr[i] = (unsigned char)sm_next();
    char gk = 0;
    int gs = 0;
    unsigned d = (unsigned)(sm_next() % 10u);
    int rc = enc_check_header(hdr, sizeof hdr, DTYPES[d].kind,
                              DTYPES[d].size, &gk, &gs);
    if (rc != ENC_OK && rc != ENC_EMAGIC && rc != ENC_EHDR)
        die("header rc", iter);
    /* truncated header is never OK */
    if (enc_check_header(hdr, 8, DTYPES[d].kind, DTYPES[d].size,
                         &gk, &gs) == ENC_OK)
        die("short header accepted", iter);
    fold_u64((uint64_t)(uint32_t)(int32_t)rc ^ (r << 8));
}

int main(int argc, char **argv) {
    if (argc != 3) {
        fprintf(stderr, "Usage: %s <seed> <iters>\n", argv[0]);
        return 2;
    }
    uint64_t seed = (uint64_t)strtoull(argv[1], NULL, 10);
    uint64_t iters = (uint64_t)strtoull(argv[2], NULL, 10);
    sm_state = seed;
    checksum = 0xCBF29CE484222325ULL;
    if (enc_abi_version() != ENC_ABI_VERSION) {
        fprintf(stderr, "encode_fuzz: ABI mismatch\n");
        return 1;
    }
    for (uint64_t i = 0; i < iters; i++) {
        switch (sm_next() % 3u) {
        case 0: fuzz_binary(i); break;
        case 1: fuzz_text(i); break;
        default: fuzz_header(i); break;
        }
    }
    printf("encode_fuzz seed=%" PRIu64 " iters=%" PRIu64
           " checksum=%016" PRIx64 "\n", seed, iters, checksum);
    return 0;
}
