/* comm_bench — collective micro-benchmark on the comm.h shim.
 *
 * Measures achieved alltoallv bandwidth (the BASELINE.json secondary
 * metric: "Alltoallv vs lax.all_to_all GB/s" — the Python half lives in
 * bench/collective_bench.py).  Every rank sends `bytes_per_peer` to every
 * peer for `reps` rounds; reported bandwidth is aggregate moved bytes /
 * wall time on rank 0.
 *
 * Usage: comm_bench [bytes_per_peer] [reps]     (COMM_RANKS / mpirun -np)
 * Output (rank 0, stdout): one JSON line
 *   {"metric": "alltoallv_gb_per_s", "value": V, "unit": "GB/s",
 *    "ranks": P, "bytes_per_peer": B, "reps": R}
 */
#include "comm.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    size_t bytes_per_peer;
    int reps;
} bench_args;

static void run(comm_ctx *c, void *va) {
    const bench_args *a = (const bench_args *)va;
    const int rank = comm_rank(c), P = comm_size(c);
    const size_t B = a->bytes_per_peer;

    char *send = (char *)malloc((size_t)P * B);
    char *recv = (char *)malloc((size_t)P * B);
    size_t *counts = (size_t *)malloc((size_t)P * sizeof(size_t));
    size_t *displs = (size_t *)malloc((size_t)P * sizeof(size_t));
    if (!send || !recv || !counts || !displs)
        comm_abort(c, 1, "comm_bench: allocation failed");
    memset(send, (char)rank, (size_t)P * B);
    for (int p = 0; p < P; p++) {
        counts[p] = B;
        displs[p] = (size_t)p * B;
    }

    /* warmup round, then timed reps */
    comm_alltoallv(c, send, counts, displs, recv, counts, displs);
    comm_barrier(c);
    double t0 = comm_wtime();
    for (int r = 0; r < a->reps; r++)
        comm_alltoallv(c, send, counts, displs, recv, counts, displs);
    comm_barrier(c);
    double dt = comm_wtime() - t0;

    if (rank == 0) {
        /* bytes crossing between ranks per round: P ranks × (P-1) remote
         * peers × B (self-destined blocks are local memcpys, excluded) */
        double moved = (double)P * (double)(P > 1 ? P - 1 : 1) * (double)B
                       * (double)a->reps;
        printf("{\"metric\": \"alltoallv_gb_per_s\", \"value\": %.3f, "
               "\"unit\": \"GB/s\", \"ranks\": %d, \"bytes_per_peer\": %zu, "
               "\"reps\": %d}\n",
               moved / dt / 1e9, P, B, a->reps);
    }
    free(send); free(recv); free(counts); free(displs);
}

int main(int argc, char **argv) {
    bench_args a;
    a.bytes_per_peer = argc > 1 ? (size_t)atoll(argv[1]) : (size_t)1 << 22;
    a.reps = argc > 2 ? atoi(argv[2]) : 20;
    if (a.bytes_per_peer == 0 || a.reps <= 0) {
        fprintf(stderr, "Usage: %s [bytes_per_peer] [reps]\n", argv[0]);
        return EXIT_FAILURE;
    }
    return comm_launch(run, &a);
}
