/* encode.c — the native ingest engine behind mpitest_tpu/utils/native_encode.py.
 *
 * One pass per chunk: each key is read once, its order-preserving
 * uint32-word encoding (mpitest_tpu/ops/keys.py codec, msw first) is
 * written to the planar out arrays, and min/max/XOR/wrapping-sum/count
 * plus the lexicographic-maximum key fold through registers on the way.
 * Float encodes read the IEEE bit pattern straight off the buffer (the
 * totalOrder flip is pure bit arithmetic), so no FP instruction runs at
 * all.  Built as libencode.so by bench/Makefile (`make native-encode`);
 * -Wconversion -Wshadow -Werror clean (root cwarn-check), ASan/UBSan
 * fuzzed via native/encode_fuzz.c.
 */
#include "encode.h"

int enc_abi_version(void) { return ENC_ABI_VERSION; }

/* ------------------------------------------------------------- encode */

#define SIGN32 0x80000000u
#define SIGN64 0x8000000000000000ULL

static void fold_init(enc_fold *f) {
    f->count = 0;
    f->xor0 = f->xor1 = 0;
    f->sum0 = f->sum1 = 0;
    f->min0 = f->min1 = 0xFFFFFFFFu;
    f->max0 = f->max1 = 0;
    f->lexmax0 = f->lexmax1 = 0;
}

/* 1-word fold step, kept branch-light so gcc vectorizes the loops.
 * `fp` is a compile-time constant at every call site (the dispatcher
 * below passes literals), so the fingerprint branch folds away. */
#define FOLD1(e)                                                        \
    do {                                                                \
        uint32_t e_ = (e);                                              \
        w0[i] = e_;                                                     \
        if (e_ < mn0) mn0 = e_;                                         \
        if (e_ > mx0) mx0 = e_;                                         \
        if (fp) { xr0 ^= e_; sm0 += e_; }                               \
    } while (0)

#define FOLD2(u)                                                        \
    do {                                                                \
        uint64_t u_ = (u);                                              \
        uint32_t hi_ = (uint32_t)(u_ >> 32);                            \
        uint32_t lo_ = (uint32_t)(u_ & 0xFFFFFFFFu);                    \
        w0[i] = hi_;                                                    \
        w1[i] = lo_;                                                    \
        if (hi_ < mn0) mn0 = hi_;                                       \
        if (hi_ > mx0) mx0 = hi_;                                       \
        if (lo_ < mn1) mn1 = lo_;                                       \
        if (lo_ > mx1) mx1 = lo_;                                       \
        if (u_ > lex) lex = u_;                                         \
        if (fp) { xr0 ^= hi_; sm0 += hi_; xr1 ^= lo_; sm1 += lo_; }     \
    } while (0)

static int encode_fold_impl(const void *src, size_t n, char kind,
                            int itemsize, uint32_t *w0, uint32_t *w1,
                            const int fp, enc_fold *fold) {
    uint32_t mn0 = 0xFFFFFFFFu, mx0 = 0, xr0 = 0, sm0 = 0;
    uint32_t mn1 = 0xFFFFFFFFu, mx1 = 0, xr1 = 0, sm1 = 0;
    uint64_t lex = 0;
    int two_words = 0;

    if (kind == 'i' && itemsize == 1) {
        const int8_t *p = (const int8_t *)src;
        for (size_t i = 0; i < n; i++)
            FOLD1((uint32_t)(int32_t)p[i] ^ SIGN32);
    } else if (kind == 'i' && itemsize == 2) {
        const int16_t *p = (const int16_t *)src;
        for (size_t i = 0; i < n; i++)
            FOLD1((uint32_t)(int32_t)p[i] ^ SIGN32);
    } else if (kind == 'i' && itemsize == 4) {
        const uint32_t *p = (const uint32_t *)src;  /* int32 bits */
        for (size_t i = 0; i < n; i++)
            FOLD1(p[i] ^ SIGN32);
    } else if (kind == 'u' && itemsize == 1) {
        const uint8_t *p = (const uint8_t *)src;
        for (size_t i = 0; i < n; i++)
            FOLD1((uint32_t)p[i]);
    } else if (kind == 'u' && itemsize == 2) {
        const uint16_t *p = (const uint16_t *)src;
        for (size_t i = 0; i < n; i++)
            FOLD1((uint32_t)p[i]);
    } else if (kind == 'u' && itemsize == 4) {
        const uint32_t *p = (const uint32_t *)src;
        for (size_t i = 0; i < n; i++)
            FOLD1(p[i]);
    } else if (kind == 'f' && itemsize == 4) {
        const uint32_t *p = (const uint32_t *)src;  /* IEEE bits */
        for (size_t i = 0; i < n; i++) {
            uint32_t u = p[i];
            FOLD1((u & SIGN32) ? ~u : (u ^ SIGN32));
        }
    } else if (kind == 'i' && itemsize == 8) {
        const uint64_t *p = (const uint64_t *)src;  /* int64 bits */
        two_words = 1;
        for (size_t i = 0; i < n; i++)
            FOLD2(p[i] ^ SIGN64);
    } else if (kind == 'u' && itemsize == 8) {
        const uint64_t *p = (const uint64_t *)src;
        two_words = 1;
        for (size_t i = 0; i < n; i++)
            FOLD2(p[i]);
    } else if (kind == 'f' && itemsize == 8) {
        const uint64_t *p = (const uint64_t *)src;  /* IEEE bits */
        two_words = 1;
        for (size_t i = 0; i < n; i++) {
            uint64_t u = p[i];
            FOLD2((u & SIGN64) ? ~u : (u ^ SIGN64));
        }
    } else {
        return ENC_EDTYPE;
    }

    fold->count = (uint64_t)n;
    fold->xor0 = xr0; fold->xor1 = xr1;
    fold->sum0 = sm0; fold->sum1 = sm1;
    fold->min0 = mn0; fold->min1 = mn1;
    fold->max0 = mx0; fold->max1 = mx1;
    if (two_words) {
        fold->lexmax0 = (uint32_t)(lex >> 32);
        fold->lexmax1 = (uint32_t)(lex & 0xFFFFFFFFu);
    } else {
        fold->lexmax0 = mx0;
        fold->lexmax1 = 0;
    }
    return ENC_OK;
}

int enc_encode_fold(const void *src, size_t n, char kind, int itemsize,
                    uint32_t *w0, uint32_t *w1, int fold_fp,
                    enc_fold *fold) {
    fold_init(fold);
    if (n == 0) {
        /* neutral fold; still reject an unsupported dtype loudly */
        if (!((kind == 'i' || kind == 'u') &&
              (itemsize == 1 || itemsize == 2 || itemsize == 4 ||
               itemsize == 8)) &&
            !(kind == 'f' && (itemsize == 4 || itemsize == 8)))
            return ENC_EDTYPE;
        return ENC_OK;
    }
    /* constant-propagated specializations: the fingerprint branch is
     * dead code in the fp=0 instantiation (SORT_VERIFY=0 pays nothing) */
    return fold_fp
        ? encode_fold_impl(src, n, kind, itemsize, w0, w1, 1, fold)
        : encode_fold_impl(src, n, kind, itemsize, w0, w1, 0, fold);
}

/* -------------------------------------------------------------- parse */

/* ASCII whitespace, the Python bytes.split() set. */
static int is_ws(char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\v' ||
           c == '\f' || c == '\r';
}

long long enc_count_tokens(const char *buf, size_t len) {
    long long n = 0;
    size_t i = 0;
    while (i < len) {
        while (i < len && is_ws(buf[i])) i++;
        if (i >= len) break;
        n++;
        while (i < len && !is_ws(buf[i])) i++;
    }
    return n;
}

/* Shared token scanner: parses one [+-]?digits token at buf[i..), with
 * magnitude accumulated in uint64 (overflow-guarded).  Underscores are
 * accepted strictly BETWEEN digits (PEP 515), because the Python
 * engine's token cast routes through int() which accepts "1_000" — the
 * parity contract is int()'s grammar, not fscanf's.  Returns ENC_OK
 * and advances *ip past the token, or a negative status. */
static int parse_tok(const char *buf, size_t len, size_t *ip,
                     uint64_t *mag_out, int *neg_out) {
    size_t i = *ip;
    int neg = 0;
    if (buf[i] == '+' || buf[i] == '-') {
        neg = buf[i] == '-';
        i++;
    }
    if (i >= len || buf[i] < '0' || buf[i] > '9')
        return ENC_EBADTOK;  /* empty digits: bare sign, or non-digit */
    uint64_t mag = 0;
    int prev_digit = 0;
    int over = 0;  /* overflow reported only for a WELL-FORMED token:
                    * int() rejects "9...9x" as a bad literal before any
                    * range question arises, so garbage must win */
    while (i < len && !is_ws(buf[i])) {
        char c = buf[i];
        if (c == '_') {
            /* legal only between digits: previous char a digit AND the
             * next char a digit (int() rejects "1_", "1__2", "_1") */
            if (!prev_digit || i + 1 >= len ||
                buf[i + 1] < '0' || buf[i + 1] > '9')
                return ENC_EBADTOK;
            prev_digit = 0;
            i++;
            continue;
        }
        if (c < '0' || c > '9')
            return ENC_EBADTOK;
        uint64_t d = (uint64_t)(c - '0');
        if (over || mag > (0xFFFFFFFFFFFFFFFFULL - d) / 10u)
            over = 1;  /* keep scanning: a later bad char outranks this */
        else
            mag = mag * 10u + d;
        prev_digit = 1;
        i++;
    }
    if (over)
        return ENC_ERANGE;
    *ip = i;
    *mag_out = mag;
    *neg_out = neg;
    return ENC_OK;
}

long long enc_parse_i64(const char *buf, size_t len, int64_t *out,
                        size_t cap, size_t *bad_off) {
    size_t i = 0, n = 0;
    while (i < len) {
        while (i < len && is_ws(buf[i])) i++;
        if (i >= len) break;
        size_t tok_start = i;
        uint64_t mag;
        int neg;
        int rc = parse_tok(buf, len, &i, &mag, &neg);
        if (rc == ENC_OK) {
            uint64_t limit = neg ? SIGN64 : SIGN64 - 1u;
            if (mag > limit) rc = ENC_ERANGE;
        }
        if (rc != ENC_OK) {
            *bad_off = tok_start;
            return rc;
        }
        if (n >= cap) {
            *bad_off = tok_start;
            return ENC_ECAP;
        }
        out[n++] = neg ? (int64_t)(0u - mag) : (int64_t)mag;
    }
    return (long long)n;
}

long long enc_parse_u64(const char *buf, size_t len, uint64_t *out,
                        size_t cap, size_t *bad_off) {
    size_t i = 0, n = 0;
    while (i < len) {
        while (i < len && is_ws(buf[i])) i++;
        if (i >= len) break;
        size_t tok_start = i;
        uint64_t mag;
        int neg;
        int rc = parse_tok(buf, len, &i, &mag, &neg);
        if (rc == ENC_OK && neg && mag > 0)
            rc = ENC_ERANGE;  /* int(tok) < 0: out of uint64 bounds */
        if (rc != ENC_OK) {
            *bad_off = tok_start;
            return rc;
        }
        if (n >= cap) {
            *bad_off = tok_start;
            return ENC_ECAP;
        }
        out[n++] = mag;
    }
    return (long long)n;
}

/* ------------------------------------------------------------- header */

int enc_check_header(const unsigned char *hdr, size_t len, char kind,
                     int itemsize, char *got_kind, int *got_size) {
    static const unsigned char magic[8] = {'S', 'O', 'R', 'T',
                                           'B', 'I', 'N', '1'};
    if (len < 16)
        return ENC_EMAGIC;
    for (int i = 0; i < 8; i++)
        if (hdr[i] != magic[i])
            return ENC_EMAGIC;
    *got_kind = (char)hdr[8];
    *got_size = (int)hdr[9];
    if ((char)hdr[8] != kind || (int)hdr[9] != itemsize)
        return ENC_EHDR;
    return ENC_OK;
}
