/* encode.h — native ingest engine: one-pass parse/encode/reduce kernels.
 *
 * The Python ingest pipeline (mpitest_tpu/models/ingest.py) used to pay
 * four to five separate numpy passes per chunk — materialize the mmap
 * slice, codec-encode it into uint32 words, per-word min(), per-word
 * max(), then the XOR/sum fingerprint fold — which pinned text/SORTBIN1
 * ingest at ~1.2-1.4 GB/s while the device sort idled (ISSUE 6).  The
 * kernels here do the whole per-chunk job in ONE pass over the buffer:
 * read each key once, write its order-preserving uint32 word encoding
 * (the exact codec of mpitest_tpu/ops/keys.py, msw first), and fold
 * min/max/XOR/wrapping-sum/count and the lexicographic max key as the
 * values stream through registers.  gcc -O3 autovectorizes the 4-byte
 * paths; the loops carry no branches beyond the dtype dispatch.
 *
 * Exposed to Python via ctypes (mpitest_tpu/utils/native_encode.py,
 * knob SORT_NATIVE_ENCODE={auto,on,off}); ctypes releases the GIL
 * around every call, so the encode worker pool gets real parallelism.
 * Parity contract: bit-identical words/fold values and the SAME typed
 * errors as the pure-Python path on every input, malformed included —
 * enforced by tests/test_native_encode.py and fuzzed (with ASan/UBSan
 * in `make sanitize-selftest`) by native/encode_fuzz.c.  The symbol
 * surface below is cross-checked against encode.c by
 * tools/comm_parity.py, like comm.h's.
 */
#ifndef ENCODE_H
#define ENCODE_H

#include <stddef.h>
#include <stdint.h>

/* Status codes.  The ctypes shim maps each to the exception class the
 * pure-Python path raises for the same input (parity is by TYPE):
 * ENC_EBADTOK -> ValueError, ENC_ERANGE -> OverflowError,
 * ENC_EMAGIC / ENC_EHDR -> ValueError with io.py's exact messages. */
#define ENC_OK       0
#define ENC_EDTYPE  (-1)  /* unsupported (kind, itemsize) pair */
#define ENC_EBADTOK (-2)  /* malformed decimal token */
#define ENC_ERANGE  (-3)  /* token overflows the 64-bit container */
#define ENC_EMAGIC  (-4)  /* header does not start with SORTBIN1 */
#define ENC_EHDR    (-5)  /* header dtype tag mismatch */
#define ENC_ECAP    (-6)  /* out buffer too small (caller bug) */

/* One-pass reduction state over a chunk's encoded words.  Word 0 is the
 * most significant; 1-word dtypes leave the *1 slots at their neutral
 * values.  sum/xor are the multiset fingerprint of models/verify.py
 * (wrapping uint32); lexmax is the encoded form of the chunk's MAXIMUM
 * key under native order (== the pad value the ingest pipeline
 * replicates), which per-word max alone cannot provide for 2-word
 * dtypes. */
typedef struct {
    uint64_t count;
    uint32_t xor0, xor1;
    uint32_t sum0, sum1;
    uint32_t min0, min1;
    uint32_t max0, max1;
    uint32_t lexmax0, lexmax1;
} enc_fold;

/* ABI version stamp — the ctypes shim refuses a stale .so loudly
 * instead of calling into a mismatched struct layout. */
#define ENC_ABI_VERSION 1
int enc_abi_version(void);

/* Encode n keys of numpy dtype (kind in {'i','u','f'}, itemsize in
 * {1,2,4,8}) from src into planar uint32 word arrays w0 (msw) and w1
 * (lsw; ignored, may be NULL, for 1-word dtypes), folding the
 * reductions into *fold as the values stream through.  fold_fp=0 skips
 * the XOR/sum fingerprint components (SORT_VERIFY=0 must not pay for
 * them), min/max/lexmax always fold.  n==0 is ENC_OK with a neutral
 * fold.  Returns ENC_OK or ENC_EDTYPE. */
int enc_encode_fold(const void *src, size_t n, char kind, int itemsize,
                    uint32_t *w0, uint32_t *w1, int fold_fp,
                    enc_fold *fold);

/* Number of whitespace-separated tokens in buf[0..len) — the exact
 * allocation size for the parse calls below (ASCII whitespace set
 * matches Python bytes.split(): space \t \n \v \f \r). */
long long enc_count_tokens(const char *buf, size_t len);

/* Parse every whitespace-separated decimal token ([+-]?digits only,
 * fscanf/int() common subset) into out[0..cap).  Returns the token
 * count parsed, or a negative status; on error *bad_off is the byte
 * offset of the offending token (for the shim's error message).
 * enc_parse_i64 range-checks against int64 (narrower int dtypes
 * truncate Python-side, matching toks.astype(int64).astype(dt));
 * enc_parse_u64 is the uint64-exact path (rejects signs below zero and
 * values >= 2^64, like numpy's str->uint64). */
long long enc_parse_i64(const char *buf, size_t len, int64_t *out,
                        size_t cap, size_t *bad_off);
long long enc_parse_u64(const char *buf, size_t len, uint64_t *out,
                        size_t cap, size_t *bad_off);

/* Validate a SORTBIN1 header (16 bytes: magic, dtype kind, itemsize,
 * pad) against the expected key dtype.  Returns ENC_OK, ENC_EMAGIC,
 * or ENC_EHDR; on ENC_EHDR, *got_kind and *got_size carry the tag
 * so the shim can reproduce io.py's exact mismatch message. */
int enc_check_header(const unsigned char *hdr, size_t len, char kind,
                     int itemsize, char *got_kind, int *got_size);

#endif /* ENCODE_H */
