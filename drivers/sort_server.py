#!/usr/bin/env python3
"""Sort-as-a-service entry point: the persistent server (ISSUE 8).

Where ``sort_cli.py`` is the reference's one-shot batch contract, this
driver is the production shape the ROADMAP's north star actually needs:
a long-lived process that compiles once (AOT executor cache with
power-of-two shape bucketing), bounds its queue (typed backpressure),
packs concurrent small requests into one segmented device dispatch
(multi-tenant batching), and supervises every request so a poisoned
input yields a typed per-request error — never server death.

Usage::

    python drivers/sort_server.py            # knobs configure everything

Knobs (all validated fail-fast — garbage is one ``[ERROR]`` line and
exit 1, never a traceback): ``SORT_SERVE_PORT`` (0 = ephemeral; the
bound port is printed either way), ``SORT_SERVE_HOST``,
``SORT_SERVE_MAX_INFLIGHT`` / ``SORT_SERVE_MAX_BYTES`` (admission),
``SORT_SERVE_BATCH_WINDOW_MS`` / ``SORT_SERVE_BATCH_KEYS`` (batching),
``SORT_SERVE_SHAPE_BUCKETS`` / ``SORT_SERVE_PREWARM`` (executor cache),
``SORT_SERVE_ALLOW_FAULTS`` (test mode), plus every ordinary sort knob
(``SORT_ALGO``, ``SORT_DEVICES``, ``SORT_VERIFY``, ...).

Startup prints exactly one ``sort_server listening on HOST:PORT`` line
to stdout (flushed) once the socket accepts — load generators and the
selftest synchronize on it.  ``SIGTERM``/``SIGINT`` drain gracefully:
in-flight requests complete, new work gets a typed ``draining``
rejection, then the process exits 0.

Telemetry: ``SORT_TRACE=<path>`` streams every ``serve.request`` /
``serve.batch`` / ``serve.compile_cache`` span (plus all the ordinary
sort spans) as JSONL; ``python -m mpitest_tpu.report`` renders the
p50/p99 SLO table from exactly that stream.

Live telemetry (ISSUE 10): a second stdout line ``sort_server metrics
on HOST:PORT`` names the side port (``SORT_METRICS_PORT``; -1 disables)
serving ``/metrics`` (Prometheus text), ``/healthz``, ``/varz``,
``/flightrecorder`` (the in-memory span ring; ``?dump=1`` writes an
artifact) and ``/profile?n=K`` (jax.profiler capture of the next K
dispatches).  Every request carries a ``trace_id`` (client-minted or
server-minted, echoed in the response) stamped on every span it
touches; ``SIGQUIT`` dumps the flight recorder WITHOUT shutting down;
``SORT_TRACE_SAMPLE`` down-samples the full JSONL stream under load.
"""

from __future__ import annotations

import signal
import sys
import threading
from pathlib import Path

# Script-invocation bootstrap: the repo root (not drivers/) holds the
# package, and this image cannot `pip install -e .`.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv if argv is None else argv
    if len(argv) != 1:
        print(f"Usage: {argv[0]}  (configuration rides the SORT_SERVE_* "
              "environment knobs)", file=sys.stderr)
        return 1

    from mpitest_tpu.utils import knobs

    def err(msg: str) -> None:
        print(f"[ERROR] {msg}", file=sys.stderr)

    # Fail-fast knob validation — the CLI contract: a garbage knob is
    # one clean [ERROR] line naming the knob, before any JAX work.
    try:
        host = knobs.get("SORT_SERVE_HOST")
        port = knobs.get("SORT_SERVE_PORT")
        metrics_port = knobs.get("SORT_METRICS_PORT")
        knobs.validate(
            "SORT_SERVE_MAX_INFLIGHT", "SORT_SERVE_MAX_BYTES",
            "SORT_SERVE_BATCH_WINDOW_MS", "SORT_SERVE_BATCH_KEYS",
            "SORT_SERVE_SHAPE_BUCKETS", "SORT_SERVE_PREWARM",
            "SORT_SERVE_ALLOW_FAULTS",
            # the request-lifecycle robustness layer (ISSUE 11)
            "SORT_SERVE_IDLE_TIMEOUT_S", "SORT_SERVE_READ_TIMEOUT_S",
            "SORT_SERVE_DISPATCH_TIMEOUT_S",
            "SORT_SERVE_BREAKER_BACKOFF_S",
            "SORT_SERVE_COMPLETION_TIMEOUT_S", "SORT_FAULT_STALL_MS",
            # the live-telemetry layer (ISSUE 10)
            "SORT_TRACE_SAMPLE", "SORT_FLIGHT_RECORDER_SIZE",
            "SORT_FLIGHT_RECORDER_DIR", "SORT_PROFILE",
            "SORT_PROFILE_EVERY",
            # the sort knobs every dispatch consumes
            "SORT_ALGO", "SORT_DTYPE", "SORT_DEVICES", "SORT_RANKS",
            "SORT_VERIFY", "SORT_MAX_RETRIES", "SORT_RETRY_BACKOFF",
            "SORT_FALLBACK", "SORT_FAULTS", "SORT_FAULTS_SEED",
            "SORT_LOCAL_ENGINE", "SORT_EXCHANGE_ENGINE",
            "SORT_NEGOTIATE", "SORT_RESTAGE",
            "SORT_RESTAGE_RATIO", "SORT_NATIVE_ENCODE",
            # plan provenance (ISSUE 12): the decision record behind
            # the response header's plan digest and /varz snapshot
            "SORT_PLAN",
            # self-tuning planner (ISSUE 14): per-request policies +
            # the serve window/bucket tuner
            "SORT_PLANNER", "SORT_PLANNER_WINDOW",
            "SORT_PLANNER_HYSTERESIS",
            # out-of-core spill tier (ISSUE 15): over-budget requests
            # stream to disk and ride the external sort
            "SORT_SERVE_SPILL", "SORT_SPILL_DIR", "SORT_MEM_BUDGET",
            "SORT_MERGE_FANIN",
            # spill compression + simulated-disk throttle (ISSUE 20)
            "SORT_SPILL_COMPRESS", "SORT_SPILL_THROTTLE_MBPS",
            # crash-durable spill tier (ISSUE 18): journaled manifests,
            # kill-resume, the orphan GC sweep, the disk-fault drills
            "SORT_RESUME", "SORT_SPILL_GC_AGE_S", "SORT_FAULT_ENOSPC_AT",
            # streaming sentinel (ISSUE 16): live anomaly alerting in
            # the serve core — garbage thresholds die here, not on the
            # first span close
            "SORT_SENTINEL", "SORT_SENTINEL_WINDOW_S",
            "SORT_ALERT_BURN_RATE",
        )
        from mpitest_tpu.utils import native_encode

        native_encode.engine()  # =on with no usable lib dies HERE
    except (ValueError, RuntimeError) as e:
        err(str(e))
        return 1

    from mpitest_tpu.serve.server import ServerCore, SortServer

    def log(msg: str) -> None:
        print(f"sort_server: {msg}", file=sys.stderr, flush=True)

    core = ServerCore()
    core.prewarm(log)
    # Startup orphan GC (ISSUE 18): reclaim spill files no live
    # manifest references — age-gated (SORT_SPILL_GC_AGE_S) so a
    # concurrent sort's fresh files are never swept.  Journals that DO
    # replay are left alone: they are exactly the resume signal.
    if knobs.get("SORT_SERVE_SPILL") != "off":
        from mpitest_tpu.store import external as _external

        swept = _external.gc_spill_dir(tracer=core.tracer)
        if swept:
            log(f"spill GC: reclaimed {swept} orphaned file(s)")
    # dispatch watchdog (ISSUE 11): monitors the single dispatch
    # thread's heartbeat; a dispatch past SORT_SERVE_DISPATCH_TIMEOUT_S
    # trips the circuit breaker (healthz 503, fast typed rejections,
    # flight-recorder artifact) and half-opens with a probe after
    # backoff.  0 disables.
    core.start_watchdog()
    try:
        server = SortServer(core, host, port)
    except OSError as e:
        err(f"cannot bind {host}:{port}: {e}")
        return 1
    # Live-telemetry side port (ISSUE 10): /metrics, /healthz, /varz,
    # /flightrecorder, /profile.  -1 disables; 0 = ephemeral.
    telemetry = None
    if metrics_port >= 0:
        from mpitest_tpu.serve.telemetry import TelemetryServer

        try:
            telemetry = TelemetryServer(core, host, metrics_port)
            telemetry.start()
        except OSError as e:
            err(f"cannot bind metrics port {host}:{metrics_port}: {e}")
            server.server_close()
            return 1
    stop = threading.Event()

    def on_signal(signum: int, frame: object) -> None:
        log(f"signal {signum}: draining (in-flight requests complete; "
            "new work gets a typed 'draining' rejection)")
        core.start_drain()
        stop.set()

    def on_sigquit(signum: int, frame: object) -> None:
        # incident snapshot, NOT shutdown: dump the flight-recorder
        # ring and keep serving (the operator's kill -QUIT at 3am).
        from mpitest_tpu.utils import flight_recorder

        path = flight_recorder.get().dump("sigquit")
        log(f"SIGQUIT: flight recorder dumped to {path or '(nothing)'}")

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGQUIT, on_sigquit)

    serve_thread = threading.Thread(target=server.serve_forever,
                                    name="serve-accept", daemon=True)
    serve_thread.start()
    # The sync line load generators / the selftest wait for (stdout, one
    # line, flushed — parse the real bound port from it when PORT=0).
    # The metrics line follows SECOND so single-line readers keep
    # working; scrapers read both.
    print(f"sort_server listening on {host}:{server.bound_port}",
          flush=True)
    if telemetry is not None:
        print(f"sort_server metrics on {host}:{telemetry.bound_port}",
              flush=True)
    stop.wait()
    drained = core.drain_and_stop(timeout=60.0)
    server.shutdown()
    server.server_close()
    if telemetry is not None:
        telemetry.shutdown()
        telemetry.server_close()
    if not drained:
        # ISSUE 11 satellite: a drain timeout is an INCIDENT, not a
        # quiet log line — name the stuck requests, record the typed
        # drain_timeout evidence (span event -> live counter via the
        # bridge), dump the flight recorder, exit dirty.
        import time as _time

        from mpitest_tpu.utils import flight_recorder

        stuck = core.stuck_trace_ids()
        core.tracer.spans.record(
            "serve.watchdog", _time.perf_counter(), 0.0,
            event="drain_timeout", trace_ids=stuck)
        path = flight_recorder.get().dump("drain_timeout")
        log(f"drain TIMEOUT: {len(stuck)} request(s) still in flight "
            f"(trace_ids={stuck}); flight recorder dumped to "
            f"{path or '(nothing)'}")
        # ISSUE 18: a dirty exit may strand journaled external sorts —
        # name the datasets a restarted server can warm-resume (the
        # manifests stay on disk; only a clean finish deletes them).
        if knobs.get("SORT_SERVE_SPILL") != "off":
            from mpitest_tpu.store import external as _external
            from mpitest_tpu.store import manifest as _mfst

            live = _mfst.live_manifests(
                _external.resolve_spill_dir(None))
            if live:
                log("resumable spill datasets: "
                    + ", ".join(m.dataset for m in live)
                    + " (a restarted server re-enters them at the "
                    "merge phase)")
    log(f"drained={'clean' if drained else 'TIMEOUT'} "
        f"served_ok={core.requests_ok} errors={core.requests_err} "
        f"rejected={core.admission.rejected} "
        f"batches={core.batcher.batches} "
        f"watchdog_trips={core.breaker.trips} "
        f"cache_hits={core.cache.stats.hits} "
        f"cache_misses={core.cache.stats.misses}")
    return 0 if drained else 1


if __name__ == "__main__":
    sys.exit(main())
