#!/usr/bin/env python3
"""CLI driver — the reference's argv/stdout/stderr contract on the TPU backend.

Byte-compatible machine interface (SURVEY.md §5 metrics row):

* argv: ``sort_cli.py <datafile> [debug]`` — positional, like the
  reference ``main()`` (``mpi_sample_sort.c:220-241``); wrong argc prints
  ``Usage: %s <file: Data file to read>`` to stderr and exits non-zero
  (``:230-234``), unreadable file prints ``sort(): '<file>' is not a
  valid file for read.`` (``:46-48``).
* stdout: ``Each bucket will be put %u items.`` (sample algorithm,
  ``:74``), full ``%u|%u`` dump at debug>2 (``:203``), and the
  correctness probe ``The n/2-th sorted element: %d`` (``:205``).
* stderr: ``Endtime()-Starttime() = %.5f sec`` (``:207``), spanning
  after-file-read to result materialization, like the reference's
  ``MPI_Wtime`` pair (``:61,201``).

Knobs the reference put in ``mpirun -np``/source constants ride env vars
here: ``SORT_ALGO`` ∈ {sample, radix} (default sample — the reference
binary of the same name), ``SORT_RANKS`` (mesh size; default all
devices), ``SORT_DIGIT_BITS`` (radix digit width, default auto),
``SORT_DTYPE`` (default int32), ``SORT_CAP_FACTOR`` (exchange cap as a
multiple of the fair per-peer share, default 2.0 — the principled form
of the reference's fixed ``1.5*size_bucket`` bucket cap,
``mpi_sample_sort.c:140``), ``SORT_OVERSAMPLE`` (samples per shard for
splitter selection, default ``2P-1`` like the reference ``:90``).

Streaming ingest (ISSUE 2 — on by default for large inputs): the file
format is sniffed once (``read_keys_auto``) and SORTBIN1 inputs open as
an mmap (no upfront materialization); the sort's host path then runs the
chunked parse/encode/DMA pipeline (``mpitest_tpu/models/ingest.py``),
emitting ``ingest.*`` / ``egress.*`` spans into ``SORT_TRACE``.  Knobs:
``SORT_INGEST`` ∈ {auto, stream, mono} (auto streams above ~32 MiB),
``SORT_INGEST_CHUNK`` (keys per chunk, default 2^22),
``SORT_INGEST_THREADS`` (parse/encode workers, default 2) — all
validated fail-fast like every other knob.

Observability (SURVEY.md §5 metrics row — additions the reference
lacks, off by default so the byte-compatible contract is untouched):
``SORT_METRICS=<path>`` appends one JSON sidecar line per run (phase ms,
Mkeys/s, exchange bytes + achieved GB/s); ``SORT_TRACE=<path>`` streams
the structured span log (nested phases, jit compile-vs-execute split,
one span per radix pass / splitter round / collective with byte counts
— ``mpitest_tpu/utils/spans.py``) as JSONL, aggregated by ``python -m
mpitest_tpu.report`` alongside the native backends' ``COMM_STATS``
records; ``SORT_TRACE_CHROME=<path>`` writes the same run as Chrome
trace-event JSON (opens in Perfetto); ``SORT_PROFILE=<logdir>`` wraps
the sort in a ``jax.profiler`` trace for TensorBoard.

Robustness (ISSUE 3 — the supervised, self-verifying sort): every run
is verified (on-device sortedness + multiset fingerprint against the
input, ``SORT_VERIFY={1,0}``), dispatch retries transient failures
(``SORT_MAX_RETRIES``, ``SORT_RETRY_BACKOFF``) and degrades gracefully
(``SORT_FALLBACK={1,0}``: other algorithm, then host sort).  Fault
injection for drills: ``SORT_FAULTS=<spec>`` / ``SORT_FAULTS_SEED``
(``mpitest_tpu/faults.py``).  Terminal failures map to DISTINCT exit
codes so wrappers can tell data corruption from infrastructure death:

* exit :data:`EXIT_INTEGRITY` (3) — ``SortIntegrityError``: no path
  produced a result that passes verification;
* exit :data:`EXIT_RETRIES` (4) — ``SortRetryExhausted``: dispatch kept
  failing past the retry budget (and fallback was off or failed too).

Both print one ``[ERROR]`` line to stderr — never a traceback — the
same fail-fast contract as the env-knob validation.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

import numpy as np

# Script-invocation bootstrap: the repo root (not drivers/) holds the
# package, and this image cannot `pip install -e .` (see verify skill).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: Distinct terminal exit codes (see module docstring).  1 stays the
#: usage/knob/file-error code, matching the reference binaries.
EXIT_INTEGRITY = 3
EXIT_RETRIES = 4


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv if argv is None else argv
    # --explain (ISSUE 12): print the run's decision tree (plan
    # provenance) after the sort.  A flag, not a positional — the
    # byte-compatible reference argv contract stays untouched without it.
    explain = "--explain" in argv
    if explain:
        argv = [a for a in argv if a != "--explain"]
    if len(argv) not in (2, 3):
        print(f"Usage: {argv[0]} <file: Data file to read>", file=sys.stderr)
        return 1
    path = argv[1]
    # atoi() semantics, like the reference (mpi_sample_sort.c:237):
    # non-numeric debug arg parses as 0, never crashes.
    debug = 0
    if len(argv) == 3:
        import re

        m = re.match(r"\s*[+-]?\d+", argv[2])
        debug = int(m.group()) if m else 0

    from mpitest_tpu.models.api import sort
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.utils import io as kio
    from mpitest_tpu.utils import knobs
    from mpitest_tpu.utils.trace import Tracer, jax_profile

    # Env-knob validation: any garbage value is one clean `[ERROR]` line
    # to stderr + nonzero exit — the reference's fail-fast contract
    # (mpi_sample_sort.c:46-48,230-234 prints and aborts; it never dumps
    # a stack trace), VERDICT r4 weak #5.  Every knob reads through the
    # central registry (utils/knobs.py), which owns the typed parsing
    # and the knob-naming error messages; one validate() sweep covers
    # the knobs the sort consumes later (ingest, robustness, faults) so
    # a garbage fault spec dies here, not mid-sort.
    def knob_error(msg: str) -> None:
        print(f"[ERROR] {msg}", file=sys.stderr)

    tracer = Tracer(level=debug)
    try:
        algo = knobs.get("SORT_ALGO")
        dtype = knobs.get("SORT_DTYPE")
        digit_bits = knobs.get("SORT_DIGIT_BITS")
        ranks = knobs.get("SORT_RANKS")
        cap_factor = knobs.get("SORT_CAP_FACTOR")
        oversample = knobs.get("SORT_OVERSAMPLE")
        knobs.validate(
            "SORT_INGEST", "SORT_INGEST_CHUNK", "SORT_INGEST_THREADS",
            "SORT_DONATE", "SORT_NATIVE_ENCODE", "SORT_VERIFY",
            "SORT_MAX_RETRIES", "SORT_RETRY_BACKOFF", "SORT_FALLBACK",
            "SORT_FAULTS", "SORT_FAULTS_SEED", "SORT_LOCAL_ENGINE",
            "SORT_EXCHANGE_ENGINE",
            "SORT_DEVICES", "SORT_NEGOTIATE", "SORT_RESTAGE",
            "SORT_RESTAGE_RATIO",
            # live-telemetry knobs (ISSUE 10): the span sampler runs in
            # every SpanLog and the flight recorder dumps on typed
            # errors, so garbage dies here, not mid-sort
            "SORT_TRACE_SAMPLE", "SORT_FLIGHT_RECORDER_SIZE",
            "SORT_FLIGHT_RECORDER_DIR",
            # plan provenance (ISSUE 12): minted on every run by default
            "SORT_PLAN",
            # self-tuning planner (ISSUE 14): the policy layer rides
            # every sort when enabled, so garbage dies here
            "SORT_PLANNER", "SORT_PLANNER_WINDOW",
            "SORT_PLANNER_HYSTERESIS",
            # out-of-core external sort (ISSUE 15): inputs above the
            # byte budget spill to runs and k-way merge back
            "SORT_MEM_BUDGET", "SORT_SPILL_DIR", "SORT_MERGE_FANIN",
            # spill compression + simulated-disk throttle (ISSUE 20):
            # both are read in the spill hot path, so garbage dies here
            "SORT_SPILL_COMPRESS", "SORT_SPILL_THROTTLE_MBPS",
            # streaming sentinel (ISSUE 16): the knobs are serve-side
            # but shared tooling (report --doctor thresholds) reads
            # them, so garbage dies here too
            "SORT_SENTINEL", "SORT_SENTINEL_WINDOW_S",
            "SORT_ALERT_BURN_RATE",
        )
        # resolve the encode engine NOW: SORT_NATIVE_ENCODE=on with no
        # usable libencode.so is one clean [ERROR] line here, never a
        # RuntimeError traceback out of the first streamed chunk
        from mpitest_tpu.utils import native_encode

        native_encode.engine()
    except (ValueError, RuntimeError) as e:
        knob_error(str(e))
        return 1
    # Out-of-core routing (ISSUE 15): with a byte budget set and a file
    # larger than it, the sort runs externally — partition chunks spill
    # to sorted runs (text inputs parse chunk-by-chunk straight into
    # runs, so even THEY never materialize: the PR 2 documented
    # full-file text peak is gone on this path) and a streamed k-way
    # merge probes the median without holding the result.  Debug runs
    # (dump lines, per-rank logs) keep the materializing path —
    # observability over memory, by choice.
    mem_budget = knobs.get("SORT_MEM_BUDGET")
    try:
        file_bytes = Path(path).stat().st_size
    except OSError:
        print(f"sort(): '{path}' is not a valid file for read.",
              file=sys.stderr)
        return 1
    if mem_budget and file_bytes > mem_budget and debug <= 0:
        return _external_main(path, dtype, algo, mem_budget, ranks,
                              tracer)

    try:
        # One magic sniff; SORTBIN1 opens as an mmap so the streaming
        # ingest pages keys in chunk-by-chunk instead of materializing
        # the file up front (text parses through the threaded chunk
        # reader).
        keys = kio.read_keys_auto(path, dtype=dtype, mmap=True)
    except (OSError, ValueError, OverflowError):
        # OverflowError: an out-of-range decimal token (both engines
        # raise it — numpy's int cast and the native parser's ERANGE)
        print(f"sort(): '{path}' is not a valid file for read.", file=sys.stderr)
        return 1
    n = keys.size
    if n == 0:
        print(f"sort(): '{path}' is not a valid file for read.", file=sys.stderr)
        return 1

    mesh = make_mesh(ranks)
    n_ranks = int(mesh.devices.size)
    # Per-rank protocol lines, debug>=2 — the reference's shapes
    # (mpi_sample_sort.c:30 "[COMMON] Working %u/%u", :68 "[SLAVE] %u
    # Recv(size_input): %u").  One host drives all mesh ranks, so the
    # lines are emitted in rank order instead of interleaving.
    for r in range(n_ranks):
        tracer.common(f"Working {r}/{n_ranks}", min_level=2)
    tracer.master(f"Read file: {path}")
    tracer.master(f"File read OK, {n} numbers {keys[0]}-{keys[-1]}.")
    for r in range(1, n_ranks):
        tracer.slave(f"{r} Recv(size_input): {n}")

    if algo == "sample":
        # ceil(N/P): the reference's size_bucket line (mpi_sample_sort.c:74).
        print(f"Each bucket will be put {-(-n // n_ranks)} items.")

    from mpitest_tpu.models.supervisor import (SortIntegrityError,
                                               SortRetryExhausted)

    start = time.perf_counter()  # after file read, like MPI_Wtime at :61
    try:
        with jax_profile(knobs.get("SORT_PROFILE")):
            res = sort(
                keys, algorithm=algo, mesh=mesh, digit_bits=digit_bits,
                cap_factor=cap_factor, oversample=oversample,
                tracer=tracer, return_result=True,
            )
            # materialize = the reference's final Gatherv (streamed egress
            # above the auto threshold: decode overlaps the shard fetches)
            out = res.to_numpy(tracer=tracer)
    except SortIntegrityError as e:
        # Data-integrity terminal: the result could not be verified and
        # every recovery rung failed — distinct exit code so callers can
        # quarantine the input/run, never trust partial output.
        knob_error(f"sort integrity failure: {e}")
        return EXIT_INTEGRITY
    except SortRetryExhausted as e:
        # Infrastructure terminal: dispatch kept dying past the retry
        # budget — distinct code so schedulers can retry elsewhere.
        knob_error(f"sort failed after retries: {e}")
        return EXIT_RETRIES
    end = time.perf_counter()

    chrome_path = knobs.get("SORT_TRACE_CHROME")
    if chrome_path:
        # Perfetto / chrome://tracing export of the same span log the
        # SORT_TRACE JSONL streams (utils/spans.py).
        import json

        with open(chrome_path, "w") as f:
            json.dump(tracer.spans.to_chrome_trace(), f)

    metrics_path = knobs.get("SORT_METRICS")
    if metrics_path:
        from mpitest_tpu.utils.metrics import Metrics

        m = Metrics(config={"algo": algo, "n": n, "dtype": dtype.name,
                            "ranks": n_ranks, "digit_bits": digit_bits})
        m.record("wall_time_s", round(end - start, 6), "s")
        m.throughput("sort_mkeys_per_s", n, end - start)
        m.record_tracer(tracer)
        m.dump(metrics_path)

    if debug > 2:
        mask = (1 << (8 * dtype.itemsize)) - 1
        if algo == "radix" and dtype.kind in "iu":
            # Per-pass intermediate dumps — the reference's debug>2 loop
            # contract (mpi_radix_sort.c:175-178), same line format as the
            # native core (native/radix_core.h).  Emitted outside the
            # timed span (observability must not bend the benchmark).
            from mpitest_tpu.models.api import radix_pass_states

            for k, _shard, full in radix_pass_states(
                keys, mesh=mesh, digit_bits=digit_bits, cap_factor=cap_factor
            ):
                # Pads are copies of the max real key and, by stability,
                # the LAST occurrences of that value in every pass state:
                # drop exactly those to recover the real-key global order.
                pc = full.size - n
                if pc:
                    keep = np.ones(full.size, bool)
                    keep[np.flatnonzero(full == full.max())[-pc:]] = False
                    real = full[keep]
                else:
                    real = full
                # RADIX labels follow the reference/native block contract
                # (rank r owns n//P + (r < n%P) keys — sort_common.h
                # block_count), not the padded uniform device shards, so
                # the dump is line-for-line comparable for ANY N.
                q, rem = divmod(n, n_ranks)
                off = 0
                for r in range(n_ranks):
                    cnt = q + (1 if r < rem else 0)
                    print(f"[COMMON] {r}: Main Queue Completed, LEN={cnt}")
                    for v in real[off:off + cnt]:
                        print(f"DUMP: LOOP {k} RADIX {r} = {int(v) & mask}")
                    off += cnt
        for i, v in enumerate(out):
            # Floats dump as shortest-unique decimals (round-trippable
            # bits); the reference's %u masking is an int-key contract.
            print(f"{i}|{v}" if dtype.kind == "f" else f"{i}|{int(v) & mask}")
    # The reference indexes size_input/2 - 1 (UB for n == 1; we clamp).
    med = out[max(n // 2 - 1, 0)]
    if dtype.kind == "f":
        # Bit-exact float probe: numpy's shortest-unique decimal str
        # round-trips to the same bits.  int truncation would collide
        # distinct float medians — the pitfall bench.py's encoded_median
        # fixes (VERDICT r3 weak #3).
        print(f"The n/2-th sorted element: {med}")
    else:
        print(f"The n/2-th sorted element: {int(med)}")
    print(f"Endtime()-Starttime() = {end - start:.5f} sec", file=sys.stderr)
    if explain:
        # the same renderer report.py --explain uses, fed from this
        # run's in-process span log — no trace file required
        from mpitest_tpu.report import explain_view

        rows = [dict(s.to_dict(), kind="span")
                for s in tracer.spans.spans]
        view = explain_view(rows)
        print(view if view is not None
              else "(no plan recorded — SORT_PLAN=off)")
    return 0


def _external_main(path: str, dtype, algo: str, mem_budget: int,
                   ranks, tracer) -> int:
    """The out-of-core CLI leg (ISSUE 15): streamed external sort of
    ``path`` under ``SORT_MEM_BUDGET`` — chunks spill to sorted runs,
    the k-way merge streams past a running median probe, and the full
    result is never materialized.  Same stdout/stderr/exit contract as
    the in-memory path (the timer starts before the read because the
    read IS interleaved with the sort here)."""
    import time as _time

    from mpitest_tpu.models.supervisor import (SortIntegrityError,
                                               SortRetryExhausted)
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.store import external
    from mpitest_tpu.utils import knobs
    from mpitest_tpu.utils.io import sniff_format

    def knob_error(msg: str) -> None:
        print(f"[ERROR] {msg}", file=sys.stderr)

    try:
        sniff_format(path)
    except OSError:
        print(f"sort(): '{path}' is not a valid file for read.",
              file=sys.stderr)
        return 1
    mesh = make_mesh(ranks)
    n_ranks = int(mesh.devices.size)
    probe = {"off": 0, "med": None, "n": 0, "announced": False}

    def sink_factory(n: int):
        # invoked once per MERGE ATTEMPT (an integrity recovery re-runs
        # the merge): reset the running probe so a recovered attempt
        # can never report a median captured from the aborted stream
        probe["off"], probe["med"], probe["n"] = 0, None, n
        if algo == "sample" and not probe["announced"]:
            # the reference's size_bucket line (mpi_sample_sort.c:74) —
            # printable only once the partition pass measured n
            print(f"Each bucket will be put {-(-n // n_ranks)} items.")
            probe["announced"] = True
        med_idx = max(n // 2 - 1, 0)

        def sink(k, _p) -> None:
            off = probe["off"]
            if off <= med_idx < off + int(k.size):
                probe["med"] = k[med_idx - off]
            probe["off"] = off + int(k.size)

        return sink

    start = _time.perf_counter()
    try:
        external.external_sort_file(
            path, dtype=dtype, algorithm=algo, mesh=mesh, tracer=tracer,
            budget=mem_budget, sink="array", sink_factory=sink_factory)
    except SortIntegrityError as e:
        knob_error(f"sort integrity failure: {e}")
        return EXIT_INTEGRITY
    except SortRetryExhausted as e:
        knob_error(f"sort failed after retries: {e}")
        return EXIT_RETRIES
    except (OSError, ValueError, OverflowError):
        print(f"sort(): '{path}' is not a valid file for read.",
              file=sys.stderr)
        return 1
    end = _time.perf_counter()
    if probe["n"] == 0:
        print(f"sort(): '{path}' is not a valid file for read.",
              file=sys.stderr)
        return 1

    metrics_path = knobs.get("SORT_METRICS")
    if metrics_path:
        from mpitest_tpu.utils.metrics import Metrics

        m = Metrics(config={"algo": algo, "n": probe["n"],
                            "dtype": dtype.name, "ranks": n_ranks,
                            "external": True})
        m.record("wall_time_s", round(end - start, 6), "s")
        m.throughput("sort_mkeys_per_s", probe["n"], end - start)
        m.record_tracer(tracer)
        m.dump(metrics_path)

    med = probe["med"]
    if dtype.kind == "f":
        print(f"The n/2-th sorted element: {med}")
    else:
        print(f"The n/2-th sorted element: {int(med)}")
    print(f"Endtime()-Starttime() = {end - start:.5f} sec",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
