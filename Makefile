# Top-level entry points (SURVEY.md §7.2 step 6: one-command test/CI).
#
#   make test    — full verification: Python suite (virtual 8-device CPU
#                  mesh via tests/conftest.py) + native builds + shim
#                  selftest + MPI-backend typecheck
#   make native  — build both sort binaries (local backend) + bench tools
#   make chip-test — ON-CHIP regression gate (needs a real TPU): real-
#                  Mosaic bitonic vs lax.sort numerics + timing at 2^26,
#                  segment_pack, the 5-pattern adversarial battery; one
#                  JSONL row appended to bench/BASELINE_RESULTS.jsonl.
#                  Finishes in minutes — run it in every chip session.
#   make clean   — remove all build artifacts

PYTHON ?= python3

.PHONY: test native chip-test clean

chip-test:
	$(PYTHON) -u bench/chip_regression.py

test: native
	$(PYTHON) -m pytest tests/ -q

native:
	$(MAKE) -C mpi_sample_sort BACKEND=local
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench BACKEND=local
	$(MAKE) -C bench mpi-syntax-check

clean:
	$(MAKE) -C mpi_sample_sort clean
	$(MAKE) -C mpi_radix_sort clean
	$(MAKE) -C bench clean
