# Top-level entry points (SURVEY.md §7.2 step 6: one-command test/CI).
#
#   make test    — full verification: Python suite (virtual 8-device CPU
#                  mesh via tests/conftest.py) + native builds + shim
#                  selftest + MPI-backend typecheck
#   make native  — build both sort binaries (local backend) + bench tools
#   make chip-test — ON-CHIP regression gate (needs a real TPU): real-
#                  Mosaic bitonic vs lax.sort numerics + timing at 2^26,
#                  segment_pack, the 5-pattern adversarial battery; one
#                  JSONL row appended to bench/BASELINE_RESULTS.jsonl.
#                  Finishes in minutes — run it in every chip session.
#   make telemetry-selftest — end-to-end check of the unified telemetry
#                  layer: a tiny TPU-path sort with SORT_TRACE (span
#                  JSONL) + a native run with COMM_STATS, both validated
#                  by `python -m mpitest_tpu.report --check`
#   make fault-selftest — chaos-test matrix (ISSUE 3): the full
#                  SORT_FAULTS grid (8 fault sites x {sample, radix}),
#                  persistent-fault ladder cells, the CLI's typed exit
#                  codes, and the native COMM_FAULTS kill/stall drills.
#                  Every cell must recover with a fingerprint-verified
#                  result or fail loudly with a nonzero exit — zero
#                  silent-wrong-answer cells; warm verifier overhead is
#                  asserted < 5% of sort wall.
#   make ingest-selftest — end-to-end check of the streaming ingest
#                  pipeline: a SORTBIN1 sort forced through the chunked
#                  pipeline under SORT_TRACE; `report.py --check
#                  --require-ingest-overlap` then asserts the emitted
#                  ingest.* spans show parse/encode genuinely
#                  overlapping the host→device transfers
#   make clean   — remove all build artifacts

PYTHON ?= python3

.PHONY: test native chip-test telemetry-selftest ingest-selftest \
    fault-selftest clean

chip-test:
	$(PYTHON) -u bench/chip_regression.py

test: native
	$(PYTHON) -m pytest tests/ -q

native:
	$(MAKE) -C mpi_sample_sort BACKEND=local
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench BACKEND=local
	$(MAKE) -C bench mpi-syntax-check

# One-command proof that both telemetry producers emit what the report
# CLI can validate: TPU span stream (SORT_TRACE) on a virtual CPU mesh
# + native COMM_STATS from a pthreads sort, same tiny input.
TELEMETRY_TMP := /tmp/mpitest_telemetry_selftest
telemetry-selftest:
	$(MAKE) -C mpi_radix_sort BACKEND=local
	rm -rf $(TELEMETRY_TMP) && mkdir -p $(TELEMETRY_TMP)
	$(PYTHON) -c "import numpy as np; np.savetxt('$(TELEMETRY_TMP)/keys.txt', \
	    np.random.default_rng(0).integers(-2**31, 2**31-1, size=4096, \
	    dtype=np.int32), fmt='%d')"
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
	    SORT_ALGO=radix SORT_RANKS=4 \
	    SORT_TRACE=$(TELEMETRY_TMP)/trace.jsonl \
	    $(PYTHON) drivers/sort_cli.py $(TELEMETRY_TMP)/keys.txt
	COMM_RANKS=4 COMM_STATS=$(TELEMETRY_TMP)/comm_stats.jsonl \
	    mpi_radix_sort/radix_sort $(TELEMETRY_TMP)/keys.txt
	$(PYTHON) -m mpitest_tpu.report --check \
	    $(TELEMETRY_TMP)/trace.jsonl $(TELEMETRY_TMP)/comm_stats.jsonl
	$(PYTHON) -m mpitest_tpu.report \
	    $(TELEMETRY_TMP)/trace.jsonl $(TELEMETRY_TMP)/comm_stats.jsonl

# The chaos matrix (ISSUE 3 acceptance gate) — see bench/fault_selftest.py.
# Builds the native binaries the COMM_FAULTS drills target first.
fault-selftest:
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench radix_sort_minimpi
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    $(PYTHON) -u bench/fault_selftest.py

# Proof the streamed ingest pipeline is live and actually overlapping:
# a 2^22-key SORTBIN1 file (mmap-sliced into 16 chunks) sorted on a
# virtual CPU mesh with the pipeline forced on; the span stream must
# pass the schema check AND show nonzero parse/encode ∩ transfer
# overlap — a serialized pipeline fails the gate.
INGEST_TMP := /tmp/mpitest_ingest_selftest
ingest-selftest:
	rm -rf $(INGEST_TMP) && mkdir -p $(INGEST_TMP)
	$(PYTHON) -c "import numpy as np; \
	    from mpitest_tpu.utils.io import write_keys_binary; \
	    write_keys_binary('$(INGEST_TMP)/keys.bin', \
	    np.random.default_rng(0).integers(-2**31, 2**31-1, size=1<<22, \
	    dtype=np.int32))"
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
	    SORT_ALGO=radix SORT_RANKS=4 \
	    SORT_INGEST=stream SORT_INGEST_CHUNK=262144 SORT_INGEST_THREADS=2 \
	    SORT_TRACE=$(INGEST_TMP)/trace.jsonl \
	    $(PYTHON) drivers/sort_cli.py $(INGEST_TMP)/keys.bin > /dev/null
	$(PYTHON) -m mpitest_tpu.report --check --require-ingest-overlap \
	    $(INGEST_TMP)/trace.jsonl

clean:
	$(MAKE) -C mpi_sample_sort clean
	$(MAKE) -C mpi_radix_sort clean
	$(MAKE) -C bench clean
