# Top-level entry points (SURVEY.md §7.2 step 6: one-command test/CI).
#
#   make test    — full verification: Python suite (virtual 8-device CPU
#                  mesh via tests/conftest.py) + native builds + shim
#                  selftest + MPI-backend typecheck
#   make native  — build both sort binaries (local backend) + bench tools
#   make clean   — remove all build artifacts

PYTHON ?= python3

.PHONY: test native clean

test: native
	$(PYTHON) -m pytest tests/ -q

native:
	$(MAKE) -C mpi_sample_sort BACKEND=local
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench BACKEND=local
	$(MAKE) -C bench mpi-syntax-check

clean:
	$(MAKE) -C mpi_sample_sort clean
	$(MAKE) -C mpi_radix_sort clean
	$(MAKE) -C bench clean
