# Top-level entry points (SURVEY.md §7.2 step 6: one-command test/CI).
#
#   make test    — full verification: Python suite (virtual 8-device CPU
#                  mesh via tests/conftest.py) + native builds + shim
#                  selftest + MPI-backend typecheck
#   make native  — build both sort binaries (local backend) + bench tools
#   make chip-test — ON-CHIP regression gate (needs a real TPU): real-
#                  Mosaic bitonic vs lax.sort numerics + timing at 2^26,
#                  segment_pack, the 5-pattern adversarial battery; one
#                  JSONL row appended to bench/BASELINE_RESULTS.jsonl.
#                  Finishes in minutes — run it in every chip session.
#   make telemetry-selftest — end-to-end check of the unified telemetry
#                  layer: a tiny TPU-path sort with SORT_TRACE (span
#                  JSONL) + a native run with COMM_STATS, both validated
#                  by `python -m mpitest_tpu.report --check`
#   make fault-selftest — chaos-test matrix (ISSUE 3): the full
#                  SORT_FAULTS grid (9 fault sites x {sample, radix}),
#                  persistent-fault ladder cells, the CLI's typed exit
#                  codes, and the native COMM_FAULTS kill/stall drills.
#                  Every cell must recover with a fingerprint-verified
#                  result or fail loudly with a nonzero exit — zero
#                  silent-wrong-answer cells; warm verifier overhead is
#                  asserted < 5% of sort wall.
#   make ingest-selftest — end-to-end check of the streaming ingest
#                  pipeline WITH the native encode engine forced on
#                  (ISSUE 6): a SORTBIN1 sort forced through the chunked
#                  pipeline under SORT_TRACE; `report.py --check
#                  --require-ingest-overlap` then asserts the emitted
#                  ingest.* spans show parse/encode genuinely
#                  overlapping the host→device transfers AND the
#                  recorded ingest_ratio meets the 0.5x end-to-end
#                  gate; bench/ingest_selftest.py additionally asserts
#                  native encode >= 2x the Python engine on this host
#   make native-encode — build native/libencode.so (the C ingest
#                  engine behind SORT_NATIVE_ENCODE, ISSUE 6)
#   make multichip-selftest — the scale-out gate (ISSUE 7), on a
#                  virtual 8-device CPU mesh so it runs on any image:
#                  8-device output bit-identical to 1-device for both
#                  algorithms (uniform / N<P / non-divisible / skewed),
#                  per-rank exchange-byte imbalance under the gate, and
#                  negotiated capacity strictly below the worst-case
#                  cap with zero overflow retries on skewed inputs
#   make serve-selftest — the sort-as-a-service gate (ISSUE 8): spins
#                  drivers/sort_server.py in subprocesses and drives
#                  bench/serve_load.py's closed-loop small-request mix
#                  against them.  Asserts: warm-cache requests record
#                  ZERO compile spans (the AOT executor cache), batched
#                  multi-tenant dispatch is bit-identical to
#                  per-request sorts AND >= 2x their dispatch
#                  throughput, backpressure rejections and injected
#                  per-request faults come back as TYPED errors while
#                  the server keeps serving, and SIGTERM drains
#                  gracefully.  The server span stream then passes
#                  `report.py --check --require-registered-spans` and
#                  renders the p50/p99 SLO table.
#   make chaos-serve-selftest — the wire-chaos gate (ISSUE 11): a real
#                  sort_server behind the chaos TCP proxy
#                  (bench/wire_chaos.py).  Every wire-fault cell (torn
#                  header, stalled/slow-dripped payload, raw-RST kill
#                  mid-payload, mid-response disconnect, connect-then-
#                  silence) must end with the server alive, in-flight
#                  admission bytes back to 0 (scraped from /metrics),
#                  zero leaked handler threads, and a clean follow-up
#                  request served bit-exact; a wedged dispatch must
#                  trip the watchdog (healthz 503, typed fast
#                  rejections, flight-recorder artifact that passes
#                  report.py --check) and recover via the breaker's
#                  half-open probe; and hedging must cut the
#                  injected-tail p99 strictly below the unhedged run.
#   make external-selftest — the out-of-core gate (ISSUE 15): a
#                  dataset 4x a forced SORT_MEM_BUDGET spills to
#                  SORTBIN1-framed sorted runs and k-way merges back
#                  bit-identical to the in-memory sort; key+payload
#                  record parity vs the numpy stable argsort-gather
#                  oracle across all dtypes; spill_corrupt/merge_drop
#                  fault cells recover verified or fail typed; a
#                  spawned server serves a payload_bytes request and
#                  an over-admission request (via the spill tier) each
#                  bit-identical to the solo in-memory oracle.
#   make spillperf-selftest — the disk-speed gate (ISSUE 20): on a
#                  simulated slow disk (SORT_SPILL_THROTTLE_MBPS token
#                  bucket) an external sort over compressed SORTRUN2
#                  runs must run >= 1.5x the raw-run baseline (both
#                  legs bit-identical to np.sort AND the in-memory
#                  sort), and the final merge's measured read-ahead/
#                  write-behind disk/compute overlap must be >= 0.5.
#   make durability-selftest — the crash-durability gate (ISSUE 18):
#                  a real spawned server is SIGKILLed mid-external-sort
#                  (merge wedged by an armed stall, every spill run
#                  already committed to the dataset's journaled .mfst
#                  manifest); a restarted server retrying the same
#                  dataset_id must resume at the merge phase — reply
#                  bit-identical, plan digest resumed:true, ZERO
#                  external.run spans in the restart's trace, and the
#                  manifest retired afterwards.
#   make localsort-selftest — the fused local-engine gate (ISSUE 17):
#                  interpret-mode bit-identity vs the lax engine across
#                  every codec dtype x input class (kernel + api level,
#                  ladder pinned off), one pallas_call per planned
#                  radix pass, narrow key-width plans shorter than
#                  full width, external-sort merge device-vs-host
#                  bit-identical, and the radix_compact policy's pass
#                  prediction honest (lying profiles stamp regret).
#   make lint    — static analysis (ISSUE 4): sortlint (the project's
#                  custom AST rules — env-knob registry, span schema,
#                  SPMD safety, fault coverage, typed core), threadlint
#                  (ISSUE 19: interprocedural concurrency analysis over
#                  the registered thread roots and lock ranks), the
#                  cross-backend comm parity checker, a
#                  -Wconversion/-Wshadow -Werror pass over every C
#                  source, and mypy strict on the typed core / a
#                  clang-tidy pass where those tools are installed
#                  (CI's lint job installs mypy; this image ships
#                  neither).  No JAX device needed.
#   make sanitize-selftest — the native sanitizer matrix: TSan on the
#                  pthreads backend (comm_selftest + seeded comm_fuzz —
#                  a real race detector over the barrier/alltoallv
#                  paths), ASan+UBSan on BOTH backends (pthreads and
#                  the fork-based minimpi runtime), with a
#                  cross-sanitizer checksum differential and an
#                  empty-by-policy suppressions file
#                  (tools/sanitize.supp).
#   make knob-docs — regenerate README's env-knob reference table from
#                  the central registry (mpitest_tpu/utils/knobs.py)
#   make clean   — remove all build artifacts

PYTHON ?= python3

.PHONY: test native native-encode chip-test telemetry-selftest \
    ingest-selftest fault-selftest multichip-selftest serve-selftest \
    chaos-serve-selftest planner-selftest external-selftest \
    spillperf-selftest durability-selftest doctor-selftest \
    localsort-selftest lint \
    threadlint-fixtures cwarn-check typecheck tidy-check knob-docs \
    sanitize-selftest bench-history clean

chip-test:
	$(PYTHON) -u bench/chip_regression.py

# The CI test job (ISSUE 12 satellite): the pytest suite PLUS the
# bench-trajectory gate — a perf regression in a recorded BENCH_rNN row
# fails the build instead of only rendering under `make bench-history`.
# Threshold 0.8 sits just below the known r05 ingest-ratio wobble
# (0.85x of the r03 best), so the pre-existing trajectory stays green
# and only NEW regressions fail.
test: native
	$(PYTHON) -m pytest tests/ -q
	$(PYTHON) tools/bench_history.py --strict --threshold 0.8

native:
	$(MAKE) -C mpi_sample_sort BACKEND=local
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench BACKEND=local
	$(MAKE) -C bench mpi-syntax-check

# The native ingest engine alone (ISSUE 6): native/libencode.so for the
# ctypes shim (utils/native_encode.py; SORT_NATIVE_ENCODE selects it).
native-encode:
	$(MAKE) -C bench libencode

# One-command proof that both telemetry producers emit what the report
# CLI can validate: TPU span stream (SORT_TRACE) on a virtual CPU mesh
# + native COMM_STATS from a pthreads sort, same tiny input.  The LIVE
# leg (ISSUE 10) then spins a real sort_server and proves the
# operational layer: client trace ids echoed and reconstructable via
# `report.py --trace-id` (queue wait, batch membership, dispatch,
# reply), /metrics exposition valid with every exported name registered
# and request counts reconciling exactly with the client, /healthz +
# /varz + /flightrecorder + /profile live, a fault-injected typed error
# leaving a flight-recorder artifact that `report.py --check` accepts,
# and a SORT_TRACE_SAMPLE-downsampled stream still schema-valid.
TELEMETRY_TMP := /tmp/mpitest_telemetry_selftest
telemetry-selftest:
	$(MAKE) -C mpi_radix_sort BACKEND=local
	rm -rf $(TELEMETRY_TMP) && mkdir -p $(TELEMETRY_TMP)
	$(PYTHON) -c "import numpy as np; np.savetxt('$(TELEMETRY_TMP)/keys.txt', \
	    np.random.default_rng(0).integers(-2**31, 2**31-1, size=4096, \
	    dtype=np.int32), fmt='%d')"
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
	    SORT_ALGO=radix SORT_RANKS=4 \
	    SORT_TRACE=$(TELEMETRY_TMP)/trace.jsonl \
	    $(PYTHON) drivers/sort_cli.py $(TELEMETRY_TMP)/keys.txt
	COMM_RANKS=4 COMM_STATS=$(TELEMETRY_TMP)/comm_stats.jsonl \
	    mpi_radix_sort/radix_sort $(TELEMETRY_TMP)/keys.txt
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(TELEMETRY_TMP)/trace.jsonl $(TELEMETRY_TMP)/comm_stats.jsonl
	$(PYTHON) -m mpitest_tpu.report \
	    $(TELEMETRY_TMP)/trace.jsonl $(TELEMETRY_TMP)/comm_stats.jsonl
	# explain leg (ISSUE 12): the CLI run's decision record renders as
	# an EXPLAIN tree from the same stream; the live selftest then
	# asserts the serve-side half (plan spans registered, regret
	# metrics scraped, negotiate-off > negotiated cap regret)
	$(PYTHON) -m mpitest_tpu.report --explain $(TELEMETRY_TMP)/trace.jsonl
	# doctor leg (ISSUE 16): the same CLI trace renders through the
	# pathology diagnoser; diagnosis is a report, not a gate, so a
	# healthy run exits 0 with zero findings
	$(PYTHON) -m mpitest_tpu.report --doctor $(TELEMETRY_TMP)/trace.jsonl
	JAX_PLATFORMS=cpu \
	    $(PYTHON) -u bench/telemetry_live_selftest.py \
	    --out $(TELEMETRY_TMP)/live
	$(PYTHON) -m mpitest_tpu.report --prom $(TELEMETRY_TMP)/live/scrape.prom

# The BENCH_r01..rNN trajectory (throughput / ingest ratio / cap saving
# / serve SLO) as one markdown table with per-metric regression flags —
# the pinned snapshots nothing read across runs before ISSUE 10.
bench-history:
	$(PYTHON) tools/bench_history.py

# The chaos matrix (ISSUE 3 acceptance gate) — see bench/fault_selftest.py.
# Builds the native binaries the COMM_FAULTS drills target first.
fault-selftest:
	$(MAKE) -C mpi_radix_sort BACKEND=local
	$(MAKE) -C bench radix_sort_minimpi
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    $(PYTHON) -u bench/fault_selftest.py

# The scale-out gate (ISSUE 7) — see bench/multichip_selftest.py.
# Virtual 8-device CPU mesh: runs on any image; identical shard_map
# code drives real chips.
multichip-selftest:
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    $(PYTHON) -u bench/multichip_selftest.py

# The sort-as-a-service gate (ISSUE 8) — see bench/serve_load.py.
# Servers are spawned as subprocesses on a plain 1-device CPU backend
# (the fault leg forces its own 2-device virtual mesh); the final
# report passes validate the server's span stream against the
# registered schema and render the p50/p99 SLO table from it.
SERVE_TMP := /tmp/mpitest_serve_selftest
serve-selftest:
	rm -rf $(SERVE_TMP) && mkdir -p $(SERVE_TMP)
	JAX_PLATFORMS=cpu \
	    SORT_METRICS=$(SERVE_TMP)/metrics.jsonl \
	    $(PYTHON) -u bench/serve_load.py --selftest --out $(SERVE_TMP)
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(SERVE_TMP)/server_trace_batched.jsonl
	$(PYTHON) -m mpitest_tpu.report \
	    $(SERVE_TMP)/server_trace_batched.jsonl $(SERVE_TMP)/metrics.jsonl

# The self-tuning planner gate (ISSUE 14) — see
# bench/planner_selftest.py.  The adversarial mix (sorted/near-sorted/
# dup/skew/uniform, cpu:8 virtual mesh) planner-off vs planner-on:
# throughput >= 1.3x, aggregate plan_regret strictly lower, planner-off
# AND shadow byte-identical; plus the serve window-auto A/B against a
# mis-set fixed window.  The final report pass renders the explain
# trees (planner policy census included) from the recorded metrics.
PLANNER_TMP := /tmp/mpitest_planner_selftest
planner-selftest:
	rm -rf $(PLANNER_TMP) && mkdir -p $(PLANNER_TMP)
	JAX_PLATFORMS=cpu \
	    SORT_METRICS=$(PLANNER_TMP)/metrics.jsonl \
	    SORT_TRACE=$(PLANNER_TMP)/trace.jsonl \
	    $(PYTHON) -u bench/planner_selftest.py --out $(PLANNER_TMP)
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(PLANNER_TMP)/trace.jsonl
	$(PYTHON) -m mpitest_tpu.report --explain $(PLANNER_TMP)/trace.jsonl

# The out-of-core gate (ISSUE 15) — see bench/external_selftest.py.
# A dataset 4x the forced SORT_MEM_BUDGET spills to sorted runs and
# k-way merges back bit-identical to the in-memory sort; record
# (key+payload) parity vs the numpy stable argsort-gather oracle across
# all dtypes; spill_corrupt/merge_drop fault cells recover verified or
# fail typed; and a spawned server proves payload_bytes requests plus
# the over-admission spill tier end to end.  The final report pass
# validates the emitted external.* spans against the registered schema.
EXTERNAL_TMP := /tmp/mpitest_external_selftest
external-selftest:
	rm -rf $(EXTERNAL_TMP) && mkdir -p $(EXTERNAL_TMP)
	JAX_PLATFORMS=cpu \
	    SORT_TRACE=$(EXTERNAL_TMP)/trace.jsonl \
	    $(PYTHON) -u bench/external_selftest.py
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(EXTERNAL_TMP)/trace.jsonl

# The disk-speed gate (ISSUE 20) — see bench/spillperf_selftest.py.
# On a simulated slow disk (the SORT_SPILL_THROTTLE_MBPS token bucket),
# external sort over compressed SORTRUN2 runs vs the raw baseline:
# both legs bit-identical to np.sort AND the in-memory sort, the
# compressed leg >= 1.5x faster at the disk-bound budget, and the
# final merge's measured read-ahead/write-behind disk/compute overlap
# >= 0.5.  Builds the native codec first (the gate measures it; the
# pure-Python fallback is covered by the unit tests instead).
SPILLPERF_TMP := /tmp/mpitest_spillperf_selftest
spillperf-selftest:
	$(MAKE) -C bench libspillz
	rm -rf $(SPILLPERF_TMP) && mkdir -p $(SPILLPERF_TMP)
	JAX_PLATFORMS=cpu \
	    SORT_TRACE=$(SPILLPERF_TMP)/trace.jsonl \
	    $(PYTHON) -u bench/spillperf_selftest.py
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(SPILLPERF_TMP)/trace.jsonl

# The crash-durability gate (ISSUE 18) — see bench/durability_selftest.py.
# SIGKILL a real server mid-external-sort, restart, retry the same
# dataset_id: the journaled manifest must turn the crash into a
# checkpoint (resume at the merge phase, bit-identical reply, zero
# external.run spans on the restart, manifest retired).
DURABILITY_TMP := /tmp/mpitest_durability_selftest
durability-selftest:
	rm -rf $(DURABILITY_TMP) && mkdir -p $(DURABILITY_TMP)
	JAX_PLATFORMS=cpu \
	    $(PYTHON) -u bench/durability_selftest.py --out $(DURABILITY_TMP)

# The fused local-sort gate (ISSUE 17) — see bench/localsort_selftest.py.
# The third local engine (fused per-pass radix kernel + device-side
# merge-order kernel + planner key-width compaction) proven TPU-free:
# interpret-mode bit-identity vs lax across every codec dtype x input
# class (kernel AND api level, SORT_FALLBACK=0 so no silent degrade),
# one pallas_call per planned pass, narrow plans shorter than full
# width, the external-sort merge device-vs-host bit-identical, and the
# radix_compact policy's pass prediction honest (lying profiles stamp
# regret).  The final report pass schema-checks the emitted spans.
LOCALSORT_TMP := /tmp/mpitest_localsort_selftest
localsort-selftest:
	rm -rf $(LOCALSORT_TMP) && mkdir -p $(LOCALSORT_TMP)
	JAX_PLATFORMS=cpu \
	    SORT_TRACE=$(LOCALSORT_TMP)/trace.jsonl \
	    $(PYTHON) -u bench/localsort_selftest.py
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(LOCALSORT_TMP)/trace.jsonl

# The sort-doctor gate (ISSUE 16) — see bench/doctor_selftest.py.
# Every DOCTOR_RULES pathology is planted deterministically and must be
# diagnosed EXACTLY (right rule, evidence cited, knob suggested); a
# real clean run must produce zero findings; the in-process sentinel
# cells prove the full alert loop (serve.alert span -> bridged
# sort_alerts_total -> flight-recorder dump that itself passes the
# schema check).  The final report passes re-validate a planted trace
# and render its diagnosis through the public --doctor CLI.
DOCTOR_TMP := /tmp/mpitest_doctor_selftest
doctor-selftest:
	rm -rf $(DOCTOR_TMP) && mkdir -p $(DOCTOR_TMP)
	JAX_PLATFORMS=cpu \
	    $(PYTHON) -u bench/doctor_selftest.py --out $(DOCTOR_TMP)
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(DOCTOR_TMP)/skew_imbalance.jsonl \
	    $(DOCTOR_TMP)/deadline_burn.jsonl
	$(PYTHON) -m mpitest_tpu.report --doctor $(DOCTOR_TMP)/skew_imbalance.jsonl

# The wire-chaos gate (ISSUE 11) — see bench/chaos_serve_selftest.py.
# Real servers behind the chaos TCP proxy on a plain 1-device CPU
# backend: the faults live on the wire and in the dispatch thread, not
# in the device math.
CHAOS_TMP := /tmp/mpitest_chaos_selftest
chaos-serve-selftest:
	rm -rf $(CHAOS_TMP) && mkdir -p $(CHAOS_TMP)
	JAX_PLATFORMS=cpu \
	    $(PYTHON) -u bench/chaos_serve_selftest.py --out $(CHAOS_TMP)
	$(PYTHON) -m mpitest_tpu.report --check --require-registered-spans \
	    $(CHAOS_TMP)/server_trace_chaos.jsonl \
	    $(CHAOS_TMP)/server_trace_watchdog.jsonl
	$(PYTHON) -m mpitest_tpu.report \
	    $(CHAOS_TMP)/server_trace_watchdog.jsonl

# Proof the streamed ingest pipeline is live, overlapping, and fast
# (ISSUE 6): the NATIVE encode engine is built and FORCED ON for every
# leg.  Leg 1: a 2^22-key SORTBIN1 file (mmap-sliced into 16 chunks)
# sorted through the CLI on a virtual CPU mesh; the span stream must
# pass the schema check AND show nonzero parse/encode ∩ transfer
# overlap — a serialized pipeline fails the gate.  Leg 2:
# bench/ingest_selftest.py asserts the perf contract — native encode
# throughput >= 2x the Python engine's on this host, and
# sort_incl_ingest_mkeys_per_s >= 0.5 x sort_mkeys_per_s — and records
# both in a metrics sidecar; the final report pass re-checks the ratio
# gate from that sidecar (--require-ingest-overlap reads ingest_ratio).
INGEST_TMP := /tmp/mpitest_ingest_selftest
ingest-selftest: native-encode
	rm -rf $(INGEST_TMP) && mkdir -p $(INGEST_TMP)
	$(PYTHON) -c "import numpy as np; \
	    from mpitest_tpu.utils.io import write_keys_binary; \
	    write_keys_binary('$(INGEST_TMP)/keys.bin', \
	    np.random.default_rng(0).integers(-2**31, 2**31-1, size=1<<22, \
	    dtype=np.int32))"
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
	    SORT_ALGO=radix SORT_RANKS=4 SORT_NATIVE_ENCODE=on \
	    SORT_INGEST=stream SORT_INGEST_CHUNK=262144 SORT_INGEST_THREADS=2 \
	    SORT_TRACE=$(INGEST_TMP)/trace.jsonl \
	    $(PYTHON) drivers/sort_cli.py $(INGEST_TMP)/keys.bin > /dev/null
	JAX_PLATFORMS=cpu \
	    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
	    SORT_NATIVE_ENCODE=on \
	    SORT_METRICS=$(INGEST_TMP)/metrics.jsonl \
	    $(PYTHON) -u bench/ingest_selftest.py $(INGEST_TMP)/keys.bin
	$(PYTHON) -m mpitest_tpu.report --check --require-ingest-overlap \
	    $(INGEST_TMP)/trace.jsonl $(INGEST_TMP)/metrics.jsonl

# ---------------------------------------------------------------- lint
# The static-analysis gate (ISSUE 4).  Always-on legs: sortlint,
# threadlint (ISSUE 19: interprocedural concurrency analysis — JAX
# fence, lock order, blocking-under-lock, shared-write locksets, GIL
# wedge), the comm parity checker, and the C warning gate (gcc is in
# every image).  mypy / clang-tidy legs run when installed and report
# a loud SKIP otherwise — never a silent pass of a gate that did not
# run.
lint: cwarn-check
	$(PYTHON) -m tools.sortlint
	$(PYTHON) -m tools.threadlint
	$(PYTHON) tools/comm_parity.py
	$(MAKE) typecheck tidy-check

#: Fixture drift gate: every threadlint rule must still FIRE on its
#: planted bad fixture — a silently-dead rule is worse than no rule.
threadlint-fixtures:
	$(PYTHON) -m tools.threadlint --selftest

#: Every C source must compile warning-free under the strict set.  The
#: two MPI-linked files typecheck against the vendored stub header.
CWARN := -O2 -std=c11 -Wall -Wextra -Wconversion -Wshadow -Werror \
    -fsyntax-only
cwarn-check:
	$(CC) $(CWARN) -Icomm comm/comm_local.c
	$(CC) $(CWARN) -Icomm -Icomm/mpi_stub comm/comm_mpi.c
	$(CC) $(CWARN) -Icomm -Icomm/mpi_stub comm/mpi_stub/mpi_mock.c
	$(CC) $(CWARN) -Icomm -Icomm/mpi_stub comm/mpi_stub/minimpi.c
	$(CC) $(CWARN) -Icomm -Inative native/sample_sort.c
	$(CC) $(CWARN) -Icomm -Inative native/radix_sort.c
	$(CC) $(CWARN) -Icomm native/comm_selftest.c
	$(CC) $(CWARN) -Icomm native/comm_bench.c
	$(CC) $(CWARN) -Icomm native/comm_fuzz.c
	$(CC) $(CWARN) -Icomm/mpi_stub native/minimpi_earlyexit.c
	$(CC) $(CWARN) -Inative native/encode.c
	$(CC) $(CWARN) -Inative native/encode_fuzz.c
	$(CC) $(CWARN) -Inative native/spillz.c
	$(CC) $(CWARN) -Inative native/spillz_fuzz.c
	@echo "cwarn-check OK (-Wconversion -Wshadow -Werror clean)"

typecheck:
	@if $(PYTHON) -c "import mypy" 2>/dev/null; then \
	    $(PYTHON) -m mypy --config-file pyproject.toml && \
	    echo "mypy strict OK (typed core)"; \
	else \
	    echo "SKIP: mypy not installed (CI lint job runs it;" \
	         "sortlint SL040 enforces annotation completeness here)"; \
	fi

tidy-check:
	@if command -v clang-tidy >/dev/null 2>&1; then \
	    clang-tidy --quiet comm/comm_local.c native/sample_sort.c \
	        native/radix_sort.c -- -Icomm -Inative -std=c11 && \
	    echo "clang-tidy OK"; \
	else \
	    echo "SKIP: clang-tidy not installed (cwarn-check is the" \
	         "always-on C gate)"; \
	fi

knob-docs:
	$(PYTHON) tools/gen_knob_docs.py

# ---------------------------------------------------- sanitize-selftest
# The runtime half of the gate: build + RUN the comm selftest and a
# seeded, bounded fuzz run under each sanitizer.  Same seed must fold to
# the same checksum across sanitizer builds and backends (any divergence
# means a sanitizer-visible bug altered behavior).  bench/Makefile's
# build stamp rebuilds on SANITIZE changes for the BACKEND targets; the
# minimpi binaries are removed explicitly (they carry no stamp).
SAN_SEEDS := 1 42 1234
SAN_SUPP  := $(CURDIR)/tools/sanitize.supp
# checkout-scoped staging for the differential (NOT a shared /tmp path:
# a concurrent run in another checkout must not interleave with ours)
SAN_OUT   := $(CURDIR)/bench/.san-out
sanitize-selftest:
	@echo "== TSan: pthreads backend (race detector) =="
	mkdir -p $(SAN_OUT)
	$(MAKE) -C bench SANITIZE=thread BACKEND=local comm_selftest comm_fuzz
	TSAN_OPTIONS="suppressions=$(SAN_SUPP)" COMM_RANKS=4 ./bench/comm_selftest
	TSAN_OPTIONS="suppressions=$(SAN_SUPP)" COMM_RANKS=8 ./bench/comm_selftest
	# NOTE: fuzz output goes to a file, never through a pipe — `| tee`
	# would take tee's exit status and mask a sanitizer's nonzero exit,
	# which is the one signal this gate exists to propagate.
	for s in $(SAN_SEEDS); do \
	    TSAN_OPTIONS="suppressions=$(SAN_SUPP)" COMM_RANKS=5 \
	        ./bench/comm_fuzz $$s 200 > $(SAN_OUT)/tsan_$$s || exit 1; \
	    cat $(SAN_OUT)/tsan_$$s; \
	done
	@echo "== ASan+UBSan: pthreads backend =="
	$(MAKE) -C bench SANITIZE=address,undefined BACKEND=local \
	    comm_selftest comm_fuzz
	ASAN_OPTIONS="suppressions=$(SAN_SUPP)" COMM_RANKS=4 ./bench/comm_selftest
	for s in $(SAN_SEEDS); do \
	    ASAN_OPTIONS="suppressions=$(SAN_SUPP)" COMM_RANKS=5 \
	        ./bench/comm_fuzz $$s 200 > $(SAN_OUT)/asan_$$s || exit 1; \
	    cat $(SAN_OUT)/asan_$$s; \
	done
	@echo "== ASan+UBSan: native encode kernel fuzz (ISSUE 6) =="
	rm -f bench/encode_fuzz
	$(MAKE) -C bench SANITIZE=address,undefined encode_fuzz
	for s in $(SAN_SEEDS); do \
	    ASAN_OPTIONS="suppressions=$(SAN_SUPP)" \
	        ./bench/encode_fuzz $$s 300 > $(SAN_OUT)/encasan_$$s || exit 1; \
	    cat $(SAN_OUT)/encasan_$$s; \
	done
	rm -f bench/encode_fuzz
	$(MAKE) -C bench encode_fuzz
	# sanitized-vs-plain differential: same seed must fold to the same
	# checksum (UB the sanitizers altered would diverge here)
	for s in $(SAN_SEEDS); do \
	    ./bench/encode_fuzz $$s 300 > $(SAN_OUT)/encplain_$$s || exit 1; \
	    cmp $(SAN_OUT)/encasan_$$s $(SAN_OUT)/encplain_$$s || exit 1; \
	done
	@echo "== ASan+UBSan: spill block-codec fuzz, corrupt corpora (ISSUE 20) =="
	rm -f bench/spillz_fuzz
	$(MAKE) -C bench SANITIZE=address,undefined spillz_fuzz
	for s in $(SAN_SEEDS); do \
	    ASAN_OPTIONS="suppressions=$(SAN_SUPP)" \
	        ./bench/spillz_fuzz $$s 1500 > $(SAN_OUT)/spzasan_$$s || exit 1; \
	    cat $(SAN_OUT)/spzasan_$$s; \
	done
	rm -f bench/spillz_fuzz
	$(MAKE) -C bench spillz_fuzz
	for s in $(SAN_SEEDS); do \
	    ./bench/spillz_fuzz $$s 1500 > $(SAN_OUT)/spzplain_$$s || exit 1; \
	    cmp $(SAN_OUT)/spzasan_$$s $(SAN_OUT)/spzplain_$$s || exit 1; \
	done
	@echo "== ASan+UBSan: MPI backend over the fork-based minimpi runtime =="
	rm -f bench/comm_selftest_minimpi bench/comm_fuzz_minimpi
	$(MAKE) -C bench SANITIZE=address,undefined \
	    comm_selftest_minimpi comm_fuzz_minimpi
	ASAN_OPTIONS="suppressions=$(SAN_SUPP)" MINIMPI_NP=4 \
	    ./bench/comm_selftest_minimpi
	for s in $(SAN_SEEDS); do \
	    ASAN_OPTIONS="suppressions=$(SAN_SUPP)" MINIMPI_NP=5 \
	        ./bench/comm_fuzz_minimpi $$s 200 \
	        > $(SAN_OUT)/minimpi_$$s || exit 1; \
	    cat $(SAN_OUT)/minimpi_$$s; \
	done
	@echo "== cross-sanitizer / cross-backend checksum differential =="
	for s in $(SAN_SEEDS); do \
	    cmp $(SAN_OUT)/tsan_$$s $(SAN_OUT)/asan_$$s || exit 1; \
	    a=$$(grep -o 'checksum=.*' $(SAN_OUT)/asan_$$s); \
	    b=$$(grep -o 'checksum=.*' $(SAN_OUT)/minimpi_$$s); \
	    test "$$a" = "$$b" || { echo "checksum mismatch seed $$s"; exit 1; }; \
	done
	rm -f bench/comm_selftest_minimpi bench/comm_fuzz_minimpi
	$(MAKE) -C bench BACKEND=local  # restore unsanitized default builds
	@echo "sanitize-selftest OK (TSan + ASan/UBSan x both backends," \
	    "suppressions file empty)"

clean:
	$(MAKE) -C mpi_sample_sort clean
	$(MAKE) -C mpi_radix_sort clean
	$(MAKE) -C bench clean
	rm -rf $(SAN_OUT) $(CURDIR)/bench/.spill-out
