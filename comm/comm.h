/* comm.h — the communication-backend shim (the BASELINE.json north star).
 *
 * Every communication step both sort programs need, factored behind one
 * tiny API so the algorithms are backend-agnostic.  This is the surface
 * SURVEY.md §2.3 censuses from the reference's raw MPI calls
 * (mpi_sample_sort.c / mpi_radix_sort.c), redesigned:
 *
 *   - no hand-rolled collectives from Isend/Recv, no payload-length-in-
 *     message-tag tricks (mpi_sample_sort.c:159-171), no unwaited
 *     requests (mpi_sample_sort.c:37) — counts travel as data and every
 *     transfer completes before the call returns;
 *   - variable-size distribution is first-class (scatterv/gatherv/
 *     alltoallv with explicit counts), fixing the reference's
 *     equal-chunk Scatter overflow when P does not divide N
 *     (mpi_sample_sort.c:72-82);
 *   - SPMD entry is comm_launch(), so one binary runs identically over
 *     OS processes (MPI backend, via mpirun) or shared-memory threads
 *     (local backend, COMM_RANKS env — how this repo's CI runs without
 *     an MPI installation).
 *
 * Backends: comm_local.c (pthreads + shared memory), comm_mpi.c (thin
 * passthrough to an MPI library).  The TPU backend is the Python/JAX
 * package (mpitest_tpu.parallel.collectives) — same logical surface over
 * XLA collectives on an ICI mesh; drivers/sort_cli.py is its driver.
 */
#ifndef COMM_H
#define COMM_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct comm_ctx comm_ctx; /* opaque per-rank handle */

/* SPMD entry: run fn(ctx, arg) on every rank.  Rank count comes from the
 * backend (COMM_RANKS env for local; mpirun -np for MPI).  Returns 0 on
 * normal completion, nonzero on launch failure; comm_abort never returns
 * here — it terminates the whole job with its code directly. */
int comm_launch(void (*fn)(comm_ctx *, void *), void *arg);

int comm_rank(const comm_ctx *c);
int comm_size(const comm_ctx *c);

/* Monotonic wall clock in seconds (MPI_Wtime contract). */
double comm_wtime(void);

/* Print message to stderr and terminate ALL ranks with `code`
 * (MPI_Abort contract — fail-fast, §5 failure-detection row). */
void comm_abort(comm_ctx *c, int code, const char *msg);

void comm_barrier(comm_ctx *c);

/* Rooted collectives.  `bytes` are per-element payload sizes × counts,
 * i.e. plain byte counts; element typing is the caller's business. */
void comm_bcast(comm_ctx *c, void *buf, size_t bytes, int root);

/* Equal-chunk scatter/gather: `bytes` per rank. */
void comm_scatter(comm_ctx *c, const void *send, void *recv, size_t bytes,
                  int root);
void comm_gather(comm_ctx *c, const void *send, void *recv, size_t bytes,
                 int root);

/* Variable-size: counts/displs are per-rank BYTE counts/offsets, valid on
 * the root (scatterv: send side; gatherv: recv side). */
void comm_scatterv(comm_ctx *c, const void *send, const size_t *counts,
                   const size_t *displs, void *recv, size_t recv_bytes,
                   int root);
void comm_gatherv(comm_ctx *c, const void *send, size_t send_bytes,
                  void *recv, const size_t *counts, const size_t *displs,
                  int root);

/* Every rank gets every rank's `bytes`-sized block, rank-major. */
void comm_allgather(comm_ctx *c, const void *send, void *recv, size_t bytes);

/* Typed elementwise reductions (MPI_Allreduce / MPI_Exscan).  These are
 * the two census rows (SURVEY.md §2.3/§5) the byte-oriented collectives
 * cannot express: a reduction needs element type + operator. */
typedef enum { COMM_OP_SUM, COMM_OP_MIN, COMM_OP_MAX } comm_op;
typedef enum { COMM_T_U32, COMM_T_U64 } comm_type;

/* recv[i] = op over all ranks of their send[i]; every rank gets the
 * result (MPI_Allreduce semantics — strictly more than a rooted Reduce,
 * matching how the TPU twin's psum/pmax replicate for free). */
void comm_allreduce(comm_ctx *c, const void *send, void *recv, size_t count,
                    comm_type t, comm_op op);

/* recv[i] = op over ranks r < my rank of their send[i] — the exclusive
 * prefix (MPI_Exscan), except rank 0's result is DEFINED here as the
 * operator identity (0 for SUM/MAX on unsigned, type-max for MIN); MPI
 * leaves it undefined and every caller then special-cases it. */
void comm_exscan(comm_ctx *c, const void *send, void *recv, size_t count,
                 comm_type t, comm_op op);

/* Fixed-size all-to-all: block i of `send` goes to rank i; block s of
 * `recv` came from rank s.  `bytes` per block. */
void comm_alltoall(comm_ctx *c, const void *send, void *recv, size_t bytes);

/* Variable all-to-all with EXPLICIT counts (the reference smuggled
 * lengths through message tags; here they are arguments).  All arrays
 * are per-peer byte counts/offsets into send/recv. */
void comm_alltoallv(comm_ctx *c, const void *send, const size_t *scounts,
                    const size_t *sdispls, void *recv, const size_t *rcounts,
                    const size_t *rdispls);

#ifdef __cplusplus
}
#endif

#endif /* COMM_H */
