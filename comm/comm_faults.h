/* comm_faults.h — deterministic fault injection for the native comm
 * backends: the C mirror of the Python SORT_FAULTS registry
 * (mpitest_tpu/faults.py), aimed at the failure class the reference
 * made catastrophic — a rank that stalls or dies mid-protocol strands
 * every peer in a collective forever (native/minimpi_earlyexit.c,
 * SURVEY §7.4).
 *
 * COMM_FAULTS=<spec>, a comma list of:
 *
 *   kill:<rank>@<nth>         rank <rank> dies (exit COMM_FAULT_EXIT)
 *                             entering its <nth> collective call
 *   stall:<rank>@<nth>:<ms>   rank <rank> sleeps <ms> milliseconds
 *                             entering its <nth> collective call
 *
 * Counting is per rank and 1-based over that rank's own collective
 * entries (barrier included), so a spec is deterministic for a given
 * program + input — same property as the Python registry's seeded
 * counts.
 *
 * What the spec must PROVE per backend:
 *   - comm_local (pthreads): ranks share one process — a "killed" rank
 *     takes the process down loudly ([FAULT] line + nonzero exit), the
 *     only honest semantic for shared memory (a silently-exited thread
 *     would strand its peers in pthread_barrier_wait forever, which is
 *     exactly the reference's hang reborn).
 *   - comm_mpi over minimpi: the killed rank is a real process; the
 *     minimpi supervisor must reap it and bring the whole job down
 *     with the fault code instead of hanging — the mpirun contract the
 *     early-exit fix established, now exercised mid-collective.
 *   - stall on either backend: peers WAIT (barriers are blocking, not
 *     timing out) and the run completes with byte-identical output —
 *     slowness is not data loss.
 *
 * Header-only, zero overhead when COMM_FAULTS is unset (one getenv at
 * launch, one n==0 branch per collective).
 */
#ifndef COMM_FAULTS_H
#define COMM_FAULTS_H

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

/* Distinct from the sort programs' 1 (usage/file) and the sanitizers'
 * codes, so tests can assert the death was the injected fault. */
#define COMM_FAULT_EXIT 43

enum { COMM_FAULT_NONE = 0, COMM_FAULT_KILL = 1, COMM_FAULT_STALL = 2 };

typedef struct {
    int kind;      /* COMM_FAULT_KILL | COMM_FAULT_STALL */
    int rank;      /* which rank the fault targets */
    long nth;      /* 1-based collective-entry count on that rank */
    long ms;       /* stall duration (STALL only) */
} comm_fault_spec_t;

#define COMM_FAULTS_MAX 8

typedef struct {
    int n;                                   /* 0 = injection off */
    comm_fault_spec_t f[COMM_FAULTS_MAX];
} comm_faults_t;

/* Parse the COMM_FAULTS env (NULL/"" = off).  Returns 0 on success,
 * -1 on a malformed spec (callers must fail the launch loudly — a
 * typo'd drill that silently runs clean would report false health). */
static inline int comm_faults_parse(const char *env, comm_faults_t *out) {
    memset(out, 0, sizeof *out);
    if (!env || !*env)
        return 0;
    char buf[256];
    snprintf(buf, sizeof buf, "%s", env);
    char *save = NULL;
    for (char *tok = strtok_r(buf, ",", &save); tok;
         tok = strtok_r(NULL, ",", &save)) {
        if (out->n >= COMM_FAULTS_MAX) {
            fprintf(stderr, "COMM_FAULTS: more than %d entries\n",
                    COMM_FAULTS_MAX);
            return -1;
        }
        comm_fault_spec_t *f = &out->f[out->n];
        int rank;
        long nth, ms;
        /* %n + full-token check: bare sscanf ignores trailing junk, so
         * "kill:1@3:50" (a mistyped stall) would silently run a KILL —
         * a typo'd drill executing the wrong fault is exactly the
         * false-health outcome the -1 path exists to prevent. */
        int used = -1;
        if (sscanf(tok, "kill:%d@%ld%n", &rank, &nth, &used) == 2 &&
            used >= 0 && tok[used] == '\0') {
            f->kind = COMM_FAULT_KILL;
            f->rank = rank;
            f->nth = nth;
        } else if ((used = -1,
                    sscanf(tok, "stall:%d@%ld:%ld%n", &rank, &nth, &ms,
                           &used) == 3) &&
                   used >= 0 && tok[used] == '\0') {
            f->kind = COMM_FAULT_STALL;
            f->rank = rank;
            f->nth = nth;
            f->ms = ms;
        } else {
            fprintf(stderr, "COMM_FAULTS: bad entry '%s' (use "
                            "kill:<rank>@<nth> or stall:<rank>@<nth>:<ms>)\n",
                    tok);
            return -1;
        }
        if (f->rank < 0 || f->nth < 1 ||
            (f->kind == COMM_FAULT_STALL && f->ms < 0)) {
            fprintf(stderr, "COMM_FAULTS: out-of-range values in '%s'\n", tok);
            return -1;
        }
        out->n++;
    }
    return 0;
}

/* Collective-entry hook: bump this rank's counter and apply any
 * matching fault.  KILL never returns. */
static inline void comm_faults_enter(const comm_faults_t *cf, int rank,
                                     unsigned long long *counter) {
    if (cf->n == 0)
        return;
    unsigned long long call = ++*counter;
    for (int i = 0; i < cf->n; i++) {
        const comm_fault_spec_t *f = &cf->f[i];
        if (f->rank != rank || (unsigned long long)f->nth != call)
            continue;
        if (f->kind == COMM_FAULT_KILL) {
            fprintf(stderr, "[FAULT] rank %d killed entering collective "
                            "#%llu (COMM_FAULTS)\n", rank, call);
            fflush(NULL);
            _exit(COMM_FAULT_EXIT);
        }
        fprintf(stderr, "[FAULT] rank %d stalling %ld ms at collective "
                        "#%llu (COMM_FAULTS)\n", rank, f->ms, call);
        struct timespec ts = {f->ms / 1000, (f->ms % 1000) * 1000000L};
        nanosleep(&ts, NULL);
    }
}

#endif /* COMM_FAULTS_H */
