/* comm_local.c — shared-memory backend: P ranks as pthreads, collectives
 * by direct memcpy through published pointers.
 *
 * This is the moral equivalent of MPI's shared-memory (vader/sm)
 * transport, which is what the reference actually exercised when run as
 * `mpirun -np P` on one host — minus the MPI installation requirement.
 * Rank count comes from the COMM_RANKS env var (default 4).
 *
 * Synchronization model: every collective is two barrier epochs —
 * (1) publish: each participating rank stores its buffer/metadata
 * pointers into per-rank slots, barrier; (2) copy: readers memcpy what
 * they need from peers' published buffers, barrier.  The second barrier
 * keeps publishers' buffers alive until all readers finish.  No
 * reordering hazards: pthread_barrier_wait is a full memory fence.
 * Race-free by construction — the reference's unwaited-Isend /
 * ANY_SOURCE hazards (SURVEY.md §5 race-detection row) cannot be
 * expressed in this API.
 */
#define _GNU_SOURCE
#include "comm.h"
#include "comm_faults.h"
#include "comm_stats.h"

#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

typedef struct {
    const void *ptr;          /* published data */
    const size_t *counts;     /* published byte counts (v-collectives) */
    const size_t *displs;     /* published byte offsets (v-collectives) */
} slot_t;

typedef struct world {
    int nranks;
    pthread_barrier_t bar;
    slot_t *slots;            /* [nranks] */
    /* COMM_STATS telemetry (comm_stats.h): one table per rank, written
     * lock-free by its owner thread, folded + dumped by the launcher.
     * NULL when COMM_STATS is unset — collectives then pay one branch. */
    comm_stat_t (*stats)[COMM_ST_N];
    /* COMM_FAULTS injection (comm_faults.h): parsed spec + one
     * collective-entry counter per rank (owner-thread only). */
    comm_faults_t faults;
    unsigned long long *fault_calls;         /* [nranks] */
} world_t;

struct comm_ctx {
    world_t *w;
    int rank;
};

typedef struct {
    world_t *w;
    int rank;
    void (*fn)(comm_ctx *, void *);
    void *arg;
} thread_arg_t;

int comm_rank(const comm_ctx *c) { return c->rank; }
int comm_size(const comm_ctx *c) { return c->w->nranks; }

double comm_wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

void comm_abort(comm_ctx *c, int code, const char *msg) {
    if (msg) fprintf(stderr, "%s\n", msg);
    (void)c;
    exit(code ? code : 1); /* whole process: all ranks die (MPI_Abort) */
}

/* Internal barrier (the two-epoch publish/copy fences): NOT counted in
 * COMM_STATS — the enclosing collective's timer already covers it, and
 * counting it would bill every collective as two extra barriers. */
static void bar(comm_ctx *c) { pthread_barrier_wait(&c->w->bar); }

/* Telemetry shims: t0 sentinel < 0 means stats off (no clock calls).
 * Every collective enters through here, so this is also the ONE
 * COMM_FAULTS injection point (kill/stall at the rank's nth collective
 * — comm_faults.h; a no-op branch when the env is unset). */
static double st_begin(const comm_ctx *c) {
    comm_faults_enter(&c->w->faults, c->rank, &c->w->fault_calls[c->rank]);
    return c->w->stats ? comm_stats_now() : -1.0;
}

static void st_end(comm_ctx *c, int which, size_t bytes, double t0) {
    if (t0 >= 0.0)
        comm_stats_add(c->w->stats[c->rank], which, bytes,
                       comm_stats_now() - t0);
}

void comm_barrier(comm_ctx *c) {
    double t0 = st_begin(c);
    bar(c);
    st_end(c, COMM_ST_BARRIER, 0, t0);
}

static slot_t *my_slot(comm_ctx *c) { return &c->w->slots[c->rank]; }

void comm_bcast(comm_ctx *c, void *buf, size_t bytes, int root) {
    double t0 = st_begin(c);
    if (c->rank == root) my_slot(c)->ptr = buf;
    bar(c);
    if (c->rank != root) memcpy(buf, c->w->slots[root].ptr, bytes);
    bar(c);
    st_end(c, COMM_ST_BCAST, bytes, t0);
}

void comm_scatter(comm_ctx *c, const void *send, void *recv, size_t bytes,
                  int root) {
    double t0 = st_begin(c);
    if (c->rank == root) my_slot(c)->ptr = send;
    bar(c);
    const char *base = (const char *)c->w->slots[root].ptr;
    memcpy(recv, base + (size_t)c->rank * bytes, bytes);
    bar(c);
    st_end(c, COMM_ST_SCATTER, bytes, t0);
}

void comm_scatterv(comm_ctx *c, const void *send, const size_t *counts,
                   const size_t *displs, void *recv, size_t recv_bytes,
                   int root) {
    double t0 = st_begin(c);
    if (c->rank == root) {
        my_slot(c)->ptr = send;
        my_slot(c)->counts = counts;
        my_slot(c)->displs = displs;
    }
    bar(c);
    const slot_t *rs = &c->w->slots[root];
    size_t n = rs->counts[c->rank];
    if (n > recv_bytes)
        comm_abort(c, 1, "comm_scatterv: recv buffer smaller than root's "
                         "published count (truncation would corrupt data)");
    memcpy(recv, (const char *)rs->ptr + rs->displs[c->rank], n);
    bar(c);
    st_end(c, COMM_ST_SCATTERV, n, t0);
}

void comm_gather(comm_ctx *c, const void *send, void *recv, size_t bytes,
                 int root) {
    double t0 = st_begin(c);
    my_slot(c)->ptr = send;
    bar(c);
    if (c->rank == root) {
        for (int s = 0; s < c->w->nranks; s++)
            memcpy((char *)recv + (size_t)s * bytes, c->w->slots[s].ptr, bytes);
    }
    bar(c);
    st_end(c, COMM_ST_GATHER, bytes, t0);
}

void comm_gatherv(comm_ctx *c, const void *send, size_t send_bytes,
                  void *recv, const size_t *counts, const size_t *displs,
                  int root) {
    double t0 = st_begin(c);
    my_slot(c)->ptr = send;
    bar(c);
    if (c->rank == root) {
        for (int s = 0; s < c->w->nranks; s++)
            memcpy((char *)recv + displs[s], c->w->slots[s].ptr, counts[s]);
    }
    bar(c);
    st_end(c, COMM_ST_GATHERV, send_bytes, t0);
}

void comm_allgather(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    double t0 = st_begin(c);
    my_slot(c)->ptr = send;
    bar(c);
    for (int s = 0; s < c->w->nranks; s++)
        memcpy((char *)recv + (size_t)s * bytes, c->w->slots[s].ptr, bytes);
    bar(c);
    st_end(c, COMM_ST_ALLGATHER, bytes * (size_t)c->w->nranks, t0);
}

/* -- typed reductions ------------------------------------------------ */

static void reduce_identity(void *acc, size_t count, comm_type t, comm_op op) {
    size_t esz = (t == COMM_T_U32) ? 4 : 8;
    if (op == COMM_OP_MIN)
        memset(acc, 0xFF, count * esz);       /* type-max for unsigned */
    else
        memset(acc, 0, count * esz);          /* 0: identity of SUM and
                                               * of MAX on unsigned */
}

static void reduce_fold(void *acc, const void *in, size_t count, comm_type t,
                        comm_op op) {
    if (t == COMM_T_U32) {
        uint32_t *a = (uint32_t *)acc;
        const uint32_t *b = (const uint32_t *)in;
        for (size_t i = 0; i < count; i++) {
            if (op == COMM_OP_SUM) a[i] += b[i];
            else if (op == COMM_OP_MIN) { if (b[i] < a[i]) a[i] = b[i]; }
            else { if (b[i] > a[i]) a[i] = b[i]; }
        }
    } else {
        uint64_t *a = (uint64_t *)acc;
        const uint64_t *b = (const uint64_t *)in;
        for (size_t i = 0; i < count; i++) {
            if (op == COMM_OP_SUM) a[i] += b[i];
            else if (op == COMM_OP_MIN) { if (b[i] < a[i]) a[i] = b[i]; }
            else { if (b[i] > a[i]) a[i] = b[i]; }
        }
    }
}

/* Shared core: fold ranks [0, limit) into recv.  Deterministic rank
 * order, so float-free integer ops aside, results are identical on every
 * rank and every run. */
static void reduce_ranks(comm_ctx *c, const void *send, void *recv,
                         size_t count, comm_type t, comm_op op, int limit) {
    my_slot(c)->ptr = send;
    bar(c);
    reduce_identity(recv, count, t, op);
    for (int s = 0; s < limit; s++)
        reduce_fold(recv, c->w->slots[s].ptr, count, t, op);
    bar(c);
}

void comm_allreduce(comm_ctx *c, const void *send, void *recv, size_t count,
                    comm_type t, comm_op op) {
    double t0 = st_begin(c);
    reduce_ranks(c, send, recv, count, t, op, c->w->nranks);
    st_end(c, COMM_ST_ALLREDUCE, count * ((t == COMM_T_U32) ? 4 : 8), t0);
}

void comm_exscan(comm_ctx *c, const void *send, void *recv, size_t count,
                 comm_type t, comm_op op) {
    double t0 = st_begin(c);
    reduce_ranks(c, send, recv, count, t, op, c->rank);
    st_end(c, COMM_ST_EXSCAN, count * ((t == COMM_T_U32) ? 4 : 8), t0);
}

void comm_alltoall(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    double t0 = st_begin(c);
    my_slot(c)->ptr = send;
    bar(c);
    for (int s = 0; s < c->w->nranks; s++)
        memcpy((char *)recv + (size_t)s * bytes,
               (const char *)c->w->slots[s].ptr + (size_t)c->rank * bytes,
               bytes);
    bar(c);
    st_end(c, COMM_ST_ALLTOALL, bytes * (size_t)c->w->nranks, t0);
}

void comm_alltoallv(comm_ctx *c, const void *send, const size_t *scounts,
                    const size_t *sdispls, void *recv, const size_t *rcounts,
                    const size_t *rdispls) {
    double t0 = st_begin(c);
    size_t sent = 0;
    if (t0 >= 0.0)  /* O(P) byte sum only when telemetry is on */
        for (int p = 0; p < c->w->nranks; p++) sent += scounts[p];
    slot_t *s = my_slot(c);
    s->ptr = send;
    s->counts = scounts;
    s->displs = sdispls;
    bar(c);
    for (int p = 0; p < c->w->nranks; p++) {
        const slot_t *ps = &c->w->slots[p];
        size_t n = ps->counts[c->rank];
        if (n > rcounts[p])
            comm_abort(c, 1, "comm_alltoallv: posted recv count smaller than "
                             "sender's published count (MPI truncation error)");
        memcpy((char *)recv + rdispls[p],
               (const char *)ps->ptr + ps->displs[c->rank], n);
    }
    bar(c);
    st_end(c, COMM_ST_ALLTOALLV, sent, t0);
}

static void *thread_main(void *va) {
    thread_arg_t *ta = (thread_arg_t *)va;
    comm_ctx ctx = {ta->w, ta->rank};
    ta->fn(&ctx, ta->arg);
    return NULL;
}

int comm_launch(void (*fn)(comm_ctx *, void *), void *arg) {
    const char *env = getenv("COMM_RANKS");
    int nranks = env ? atoi(env) : 4;
    if (nranks < 1 || nranks > 1024) {
        fprintf(stderr, "comm_local: bad COMM_RANKS=%s\n", env ? env : "");
        return 1;
    }
    world_t w;
    w.nranks = nranks;
    w.slots = (slot_t *)calloc((size_t)nranks, sizeof(slot_t));
    const char *stats_path = comm_stats_path();
    w.stats = stats_path
        ? (comm_stat_t (*)[COMM_ST_N])calloc((size_t)nranks,
                                             sizeof(*w.stats))
        : NULL;
    /* COMM_FAULTS: a malformed drill spec fails the launch loudly — a
     * typo that silently ran clean would report false health. */
    if (comm_faults_parse(getenv("COMM_FAULTS"), &w.faults) != 0)
        return 1;
    w.fault_calls = (unsigned long long *)calloc((size_t)nranks,
                                                 sizeof(unsigned long long));
    if (!w.slots || !w.fault_calls || (stats_path && !w.stats)
        || pthread_barrier_init(&w.bar, NULL, (unsigned)nranks)) {
        fprintf(stderr, "comm_local: init failed\n");
        return 1;
    }
    pthread_t *tids = (pthread_t *)calloc((size_t)nranks, sizeof(pthread_t));
    thread_arg_t *tas = (thread_arg_t *)calloc((size_t)nranks, sizeof(thread_arg_t));
    for (int r = 0; r < nranks; r++) {
        tas[r] = (thread_arg_t){&w, r, fn, arg};
        if (pthread_create(&tids[r], NULL, thread_main, &tas[r])) {
            fprintf(stderr, "comm_local: pthread_create failed\n");
            exit(1);
        }
    }
    for (int r = 0; r < nranks; r++) pthread_join(tids[r], NULL);
    if (w.stats) {
        /* Fold per-rank tables (sum calls/bytes, max seconds — see
         * comm_stats.h) and append the one-line JSON record. */
        comm_stat_t totals[COMM_ST_N] = {{0, 0, 0.0}};
        for (int r = 0; r < nranks; r++)
            comm_stats_fold(totals, w.stats[r]);
        comm_stats_dump(stats_path, "local", nranks, totals);
        free(w.stats);
    }
    pthread_barrier_destroy(&w.bar);
    free(tids);
    free(tas);
    free(w.slots);
    free(w.fault_calls);
    return 0;
}
