/* mpi_stub/mpi.h — prototypes-only stub of the MPI-3.1 subset that
 * comm_mpi.c uses, vendored so images WITHOUT an MPI installation can
 * still typecheck the MPI backend (`cc -fsyntax-only -I comm/mpi_stub`).
 *
 * Two uses: a signature-rot guard (`cc -fsyntax-only`), and — linked
 * with the sibling mpi_mock.c — a functional SINGLE-RANK runtime that
 * executes comm_mpi.c end-to-end (`make -C bench mpi-mock`).  Real
 * multi-rank builds use the system <mpi.h> via mpicc
 * (`make BACKEND=mpi`), which shadows this header entirely.  Signatures
 * follow MPI 3.1 §5-6 (const-correct send buffers, int
 * counts/displacements).
 */
#ifndef COMM_MPI_STUB_H
#define COMM_MPI_STUB_H

#ifdef __cplusplus
extern "C" {
#endif

typedef struct mpi_stub_comm *MPI_Comm;
typedef struct mpi_stub_datatype *MPI_Datatype;
typedef struct mpi_stub_op *MPI_Op;

extern MPI_Comm MPI_COMM_WORLD;
extern MPI_Datatype MPI_BYTE, MPI_UINT32_T, MPI_UINT64_T;
extern MPI_Op MPI_SUM, MPI_MIN, MPI_MAX;

int MPI_Init(int *argc, char ***argv);
int MPI_Finalize(void);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Abort(MPI_Comm comm, int errorcode);
double MPI_Wtime(void);
int MPI_Barrier(MPI_Comm comm);

int MPI_Bcast(void *buffer, int count, MPI_Datatype datatype, int root,
              MPI_Comm comm);
int MPI_Scatter(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, int recvcount, MPI_Datatype recvtype, int root,
                MPI_Comm comm);
int MPI_Scatterv(const void *sendbuf, const int *sendcounts,
                 const int *displs, MPI_Datatype sendtype, void *recvbuf,
                 int recvcount, MPI_Datatype recvtype, int root,
                 MPI_Comm comm);
int MPI_Gather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
               void *recvbuf, int recvcount, MPI_Datatype recvtype, int root,
               MPI_Comm comm);
int MPI_Gatherv(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, const int *recvcounts, const int *displs,
                MPI_Datatype recvtype, int root, MPI_Comm comm);
int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm);
int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm);
int MPI_Alltoallv(const void *sendbuf, const int *sendcounts,
                  const int *sdispls, MPI_Datatype sendtype, void *recvbuf,
                  const int *recvcounts, const int *rdispls,
                  MPI_Datatype recvtype, MPI_Comm comm);
int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype datatype, MPI_Op op, MPI_Comm comm);
int MPI_Exscan(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype datatype, MPI_Op op, MPI_Comm comm);

#ifdef __cplusplus
}
#endif

#endif /* COMM_MPI_STUB_H */
