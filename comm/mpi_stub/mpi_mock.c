/* mpi_mock.c — a functional SINGLE-RANK implementation of the mpi_stub
 * surface, so the comm.h MPI backend (comm_mpi.c) can be EXECUTED — not
 * just typechecked — on images without an MPI installation.
 *
 * Rationale: every round-1 artifact could only prove comm_mpi.c's
 * signatures compile (`cc -fsyntax-only`); its call paths had never run
 * anywhere.  At P=1 the MPI collectives have exact, trivial semantics
 * (self-communication: memcpy by counts/displacements; reductions of a
 * single contribution are the contribution), so linking this file gives
 * a real end-to-end run of the full driver -> sort -> comm_mpi.c stack
 * with byte-identical output to the pthreads backend.  This validates
 * the passthrough's argument plumbing (counts, displacements, datatype
 * sizes, buffer roles) — exactly what signature checks cannot.
 *
 * Semantics notes:
 *  - MPI_Exscan on rank 0 leaves recvbuf undefined per MPI 3.1 §5.11.2;
 *    this mock zero-fills it, matching the defined behavior of
 *    comm_local.c that callers actually rely on.
 *  - MPI_IN_PLACE is not modeled (comm_mpi.c never uses it).
 *  - Never link this into a real `make BACKEND=mpi` build: the system
 *    <mpi.h>/libmpi own those; this file pairs only with mpi_stub/mpi.h.
 */
#define _POSIX_C_SOURCE 199309L  /* CLOCK_MONOTONIC under -std=c11 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include "mpi.h"

struct mpi_stub_datatype { int size; };
struct mpi_stub_op { int which; };
struct mpi_stub_comm { int unused; };

static struct mpi_stub_datatype dt_byte = {1};
static struct mpi_stub_datatype dt_u32 = {4};
static struct mpi_stub_datatype dt_u64 = {8};
static struct mpi_stub_op op_sum = {0}, op_min = {1}, op_max = {2};
static struct mpi_stub_comm world;

MPI_Comm MPI_COMM_WORLD = &world;
MPI_Datatype MPI_BYTE = &dt_byte;
MPI_Datatype MPI_UINT32_T = &dt_u32;
MPI_Datatype MPI_UINT64_T = &dt_u64;
MPI_Op MPI_SUM = &op_sum, MPI_MIN = &op_min, MPI_MAX = &op_max;

int MPI_Init(int *argc, char ***argv) { (void)argc; (void)argv; return 0; }
int MPI_Finalize(void) { return 0; }
int MPI_Comm_rank(MPI_Comm comm, int *rank) { (void)comm; *rank = 0; return 0; }
int MPI_Comm_size(MPI_Comm comm, int *size) { (void)comm; *size = 1; return 0; }

int MPI_Abort(MPI_Comm comm, int errorcode) {
    (void)comm;
    fprintf(stderr, "MPI_Abort(mock, %d)\n", errorcode);
    exit(errorcode ? errorcode : 1);
}

double MPI_Wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

int MPI_Barrier(MPI_Comm comm) { (void)comm; return 0; }

int MPI_Bcast(void *buffer, int count, MPI_Datatype datatype, int root,
              MPI_Comm comm) {
    (void)buffer; (void)count; (void)datatype; (void)root; (void)comm;
    return 0; /* root's data is already in root's buffer */
}

static void copy(const void *src, void *dst, int count, MPI_Datatype dt) {
    if (src != dst && count > 0)
        memcpy(dst, src, (size_t)count * (size_t)dt->size);
}

int MPI_Scatter(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, int recvcount, MPI_Datatype recvtype, int root,
                MPI_Comm comm) {
    (void)recvcount; (void)recvtype; (void)root; (void)comm;
    copy(sendbuf, recvbuf, sendcount, sendtype);
    return 0;
}

int MPI_Scatterv(const void *sendbuf, const int *sendcounts,
                 const int *displs, MPI_Datatype sendtype, void *recvbuf,
                 int recvcount, MPI_Datatype recvtype, int root,
                 MPI_Comm comm) {
    (void)recvcount; (void)recvtype; (void)root; (void)comm;
    copy((const char *)sendbuf + (size_t)displs[0] * (size_t)sendtype->size,
         recvbuf, sendcounts[0], sendtype);
    return 0;
}

int MPI_Gather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
               void *recvbuf, int recvcount, MPI_Datatype recvtype, int root,
               MPI_Comm comm) {
    (void)recvcount; (void)recvtype; (void)root; (void)comm;
    copy(sendbuf, recvbuf, sendcount, sendtype);
    return 0;
}

int MPI_Gatherv(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                void *recvbuf, const int *recvcounts, const int *displs,
                MPI_Datatype recvtype, int root, MPI_Comm comm) {
    (void)recvcounts; (void)root; (void)comm;
    copy(sendbuf,
         (char *)recvbuf + (size_t)displs[0] * (size_t)recvtype->size,
         sendcount, sendtype);
    return 0;
}

int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm) {
    (void)recvcount; (void)recvtype; (void)comm;
    copy(sendbuf, recvbuf, sendcount, sendtype);
    return 0;
}

int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                 void *recvbuf, int recvcount, MPI_Datatype recvtype,
                 MPI_Comm comm) {
    (void)recvcount; (void)recvtype; (void)comm;
    copy(sendbuf, recvbuf, sendcount, sendtype);
    return 0;
}

int MPI_Alltoallv(const void *sendbuf, const int *sendcounts,
                  const int *sdispls, MPI_Datatype sendtype, void *recvbuf,
                  const int *recvcounts, const int *rdispls,
                  MPI_Datatype recvtype, MPI_Comm comm) {
    (void)recvcounts; (void)comm;
    copy((const char *)sendbuf + (size_t)sdispls[0] * (size_t)sendtype->size,
         (char *)recvbuf + (size_t)rdispls[0] * (size_t)recvtype->size,
         sendcounts[0], sendtype);
    return 0;
}

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
    (void)op; (void)comm; /* reduction over one contribution = identity */
    copy(sendbuf, recvbuf, count, datatype);
    return 0;
}

int MPI_Exscan(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype datatype, MPI_Op op, MPI_Comm comm) {
    (void)sendbuf; (void)op; (void)comm;
    if (count > 0)
        memset(recvbuf, 0, (size_t)count * (size_t)datatype->size);
    return 0;
}
