/* minimpi.c — a REAL multi-process implementation of the mpi_stub
 * surface, so the comm.h MPI backend (comm_mpi.c) executes at P > 1 on
 * images WITHOUT an MPI installation.
 *
 * Rationale: the single-rank mock (mpi_mock.c) proves comm_mpi.c's
 * argument plumbing, but P=1 collectives are degenerate — truncation
 * paths, Exscan-on-rank-0, per-peer count plumbing and displacement
 * arithmetic only bite with real concurrent ranks.  This file is a
 * from-scratch MPI subset with genuine multi-process semantics:
 *
 *   - launch: fork-based.  `MINIMPI_NP=P ./prog args` — rank 0's
 *     MPI_Init maps an anonymous MAP_SHARED region, initializes a
 *     process-shared pthread barrier, and forks P-1 children which
 *     resume from inside MPI_Init with their own rank.  (This is
 *     possible because fork without exec inherits the mapping; an
 *     mpirun-style exec launcher would need a named shm rendezvous for
 *     zero extra capability here.)
 *   - data plane: a shared staging area + a published count matrix.
 *     Every collective is write-phase / barrier / read-phase / barrier;
 *     the trailing barrier keeps a fast rank from clobbering staging
 *     for a peer still reading.  The comm.h surface is purely
 *     collective (no point-to-point), so this bulletin-board design is
 *     complete and deadlock-free by construction.
 *   - supervision: the parent reaps children from a SIGCHLD handler; an
 *     abnormal child exit (nonzero, signal) kills the job, matching
 *     mpirun.  MPI_Abort records its code in the shared header, signals
 *     the parent, and the whole job dies with that code.  Children set
 *     PR_SET_PDEATHSIG so a killed parent can never leave orphans
 *     spinning in a barrier.
 *
 * Semantics notes (MPI 3.1):
 *   - Gatherv/Scatterv/Alltoallv counts and displacements are honored
 *     on the ranks MPI defines them on (root resp. all); displacements
 *     are in elements of the declared datatype.
 *   - Exscan leaves rank 0's recvbuf untouched (§5.11.2 "undefined");
 *     comm_mpi.c overwrites it with the comm.h identity, and this
 *     runtime is exactly the multi-rank regime that verifies it does.
 *   - Reductions support MPI_UINT32_T/MPI_UINT64_T (all comm.h needs)
 *     in deterministic rank order.
 *   - Every collective chunks through staging automatically.  The
 *     equal-size ones publish the deciding rank's byte count first and
 *     abort on a mismatch (MPI 3.1 makes some count arguments
 *     significant only at the root — deriving the chunk-loop trip count
 *     from a non-significant argument would desynchronize the barrier
 *     phases and hang).  The ragged ones (scatterv/gatherv/alltoallv)
 *     stream their concatenated segment layout through staging in
 *     windows, so exchanges larger than MINIMPI_SHM_BYTES (default
 *     256 MiB, lazily committed pages) work at any size.
 *
 * This file pairs ONLY with mpi_stub/mpi.h — never mix it with the
 * system <mpi.h>/libmpi (mismatched ABIs).  `make BACKEND=mpi` links
 * it automatically as the fallback when mpicc is absent (the binary
 * then launches via MINIMPI_NP=P / bench/minirun, not mpirun);
 * REQUIRE_MPICC=1 forbids the fallback where the real thing is
 * mandatory (CI's real-MPI job).
 */
#define _GNU_SOURCE /* prctl, MAP_ANONYMOUS */

#include <errno.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include "mpi.h"

struct mpi_stub_datatype { int size; };
struct mpi_stub_op { int which; }; /* 0=sum 1=min 2=max */
struct mpi_stub_comm { int unused; };

static struct mpi_stub_datatype dt_byte = {1};
static struct mpi_stub_datatype dt_u32 = {4};
static struct mpi_stub_datatype dt_u64 = {8};
static struct mpi_stub_op op_sum = {0}, op_min = {1}, op_max = {2};
static struct mpi_stub_comm world;

MPI_Comm MPI_COMM_WORLD = &world;
MPI_Datatype MPI_BYTE = &dt_byte;
MPI_Datatype MPI_UINT32_T = &dt_u32;
MPI_Datatype MPI_UINT64_T = &dt_u64;
MPI_Op MPI_SUM = &op_sum, MPI_MIN = &op_min, MPI_MAX = &op_max;

#define MINIMPI_MAX_RANKS 256

struct shm_hdr {
    pthread_barrier_t barrier;
    int np;
    volatile sig_atomic_t abort_code;
    size_t staging_cap;
    /* set by each rank in MPI_Finalize: a child that exits with status 0
     * BEFORE finalizing (early clean return) would otherwise leave its
     * peers blocked in the process-shared barrier forever — the
     * supervisor treats that as abnormal and kills the job. */
    volatile sig_atomic_t finalized[MINIMPI_MAX_RANKS];
    size_t counts[]; /* np*np published byte counts, then staging */
};

static struct shm_hdr *H;    /* shared header */
static unsigned char *STG;   /* shared staging area */
static int RANK = 0, NP = 1;
static pid_t PARENT_PID;

/* parent-only supervision state (updated from the SIGCHLD handler) */
static pid_t child_pid[MINIMPI_MAX_RANKS];
static volatile sig_atomic_t n_children = 0, n_reaped = 0, worst_status = 0;

static void kill_children(void) {
    for (int i = 0; i < n_children; i++)
        if (child_pid[i] > 0) kill(child_pid[i], SIGKILL);
}

static void on_sigchld(int sig) {
    (void)sig;
    int st, saved = errno;
    pid_t p;
    while ((p = waitpid(-1, &st, WNOHANG)) > 0) {
        int code = 0;
        if (WIFEXITED(st)) code = WEXITSTATUS(st);
        else if (WIFSIGNALED(st)) code = 128 + WTERMSIG(st);
        int rank = -1; /* which rank was this pid? */
        for (int i = 0; i < n_children; i++)
            if (child_pid[i] == p) { rank = i + 1; break; }
        if (code == 0 && rank > 0 && H && !H->finalized[rank]) {
            /* exit(0) before MPI_Finalize: a "clean" early return that
             * nevertheless strands every peer in the next barrier.
             * Abnormal in all but status — kill the job (mpirun does
             * the same for a rank that vanishes mid-run). */
            static const char msg[] =
                "minimpi: a rank exited before MPI_Finalize; killing job\n";
            write(2, msg, sizeof msg - 1);
            code = 1;
        }
        n_reaped++;
        if (code != 0) {
            /* a rank died abnormally: the job cannot complete (peers
             * would block in the next barrier forever) — kill it all,
             * like mpirun. */
            worst_status = code;
            kill_children();
            _exit(code);
        }
    }
    errno = saved;
}

static void on_sigterm(int sig) {
    (void)sig; /* abort notification from a child */
    signal(SIGCHLD, SIG_IGN); /* the SIGKILLed children are expected —
                               * don't let the SIGCHLD handler rewrite
                               * the abort code with 128+SIGKILL */
    kill_children();
    _exit(H && H->abort_code ? H->abort_code : 1);
}

static void die(const char *msg) {
    fprintf(stderr, "minimpi: %s\n", msg);
    exit(1);
}

int MPI_Init(int *argc, char ***argv) {
    (void)argc; (void)argv;
    const char *np_env = getenv("MINIMPI_NP");
    NP = np_env ? atoi(np_env) : 1;
    if (NP < 1 || NP > MINIMPI_MAX_RANKS) die("MINIMPI_NP out of range");
    /* Ranks share stdout.  A pipe-backed stdout is block-buffered and a
     * 4096-byte flush can tear a line mid-write, interleaving with a
     * peer's output; line buffering makes each line one write(), which
     * is atomic on pipes up to PIPE_BUF. */
    setvbuf(stdout, NULL, _IOLBF, 0);

    const char *cap_env = getenv("MINIMPI_SHM_BYTES");
    size_t cap = cap_env ? (size_t)strtoull(cap_env, NULL, 10)
                         : ((size_t)256 << 20);
    if (cap == 0) die("MINIMPI_SHM_BYTES must be > 0"); /* 0 would make the
        chunked collectives silently transfer nothing */
    size_t hdr = (sizeof(struct shm_hdr) +
                  (size_t)NP * (size_t)NP * sizeof(size_t) + 63) & ~(size_t)63;
    void *m = mmap(NULL, hdr + cap, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_ANONYMOUS, -1, 0);
    if (m == MAP_FAILED) die("mmap failed (lower MINIMPI_SHM_BYTES?)");
    H = (struct shm_hdr *)m;
    STG = (unsigned char *)m + hdr;
    H->np = NP;
    H->staging_cap = cap;
    H->abort_code = 0;

    pthread_barrierattr_t ba;
    pthread_barrierattr_init(&ba);
    pthread_barrierattr_setpshared(&ba, PTHREAD_PROCESS_SHARED);
    if (pthread_barrier_init(&H->barrier, &ba, (unsigned)NP) != 0)
        die("barrier init failed");
    pthread_barrierattr_destroy(&ba);

    PARENT_PID = getpid();
    if (NP == 1) return 0;

    struct sigaction sa = {0};
    sa.sa_handler = on_sigchld;
    sa.sa_flags = SA_RESTART | SA_NOCLDSTOP;
    sigaction(SIGCHLD, &sa, NULL);
    sa.sa_handler = on_sigterm;
    sigaction(SIGTERM, &sa, NULL);

    fflush(stdout);
    fflush(stderr);
    /* Hold SIGCHLD until every child's pid is recorded: a child that
     * exits instantly would otherwise fire the handler before its pid
     * is in child_pid[], and the pid→rank lookup (which decides whether
     * a status-0 exit was finalized or a job-stranding early return)
     * would miss it. */
    sigset_t blk, old;
    sigemptyset(&blk);
    sigaddset(&blk, SIGCHLD);
    sigprocmask(SIG_BLOCK, &blk, &old);
    for (int r = 1; r < NP; r++) {
        pid_t pid = fork();
        if (pid < 0) {
            kill_children();
            die("fork failed");
        }
        if (pid == 0) { /* child = rank r; resume into the program */
            RANK = r;
            n_children = 0;
            signal(SIGCHLD, SIG_DFL);
            signal(SIGTERM, SIG_DFL);
            sigprocmask(SIG_SETMASK, &old, NULL); /* undo the parent block */
            prctl(PR_SET_PDEATHSIG, SIGKILL); /* no orphans in barriers */
            if (getppid() != PARENT_PID) _exit(1); /* parent already gone */
            return 0;
        }
        child_pid[r - 1] = pid;
        n_children = r;
    }
    RANK = 0;
    sigprocmask(SIG_SETMASK, &old, NULL); /* deliver any held SIGCHLD now */
    return 0;
}

int MPI_Finalize(void) {
    if (H) H->finalized[RANK] = 1; /* legitimizes this rank's exit(0) */
    if (NP > 1 && RANK == 0) {
        /* mpirun contract: the launcher (here: rank 0's process, which
         * the shell waits on) outlives every rank and fails if any rank
         * failed.  Children exit shortly after their own Finalize; the
         * SIGCHLD handler reaps them. */
        while (n_reaped < NP - 1) {
            struct timespec ts = {0, 2 * 1000 * 1000};
            nanosleep(&ts, NULL);
        }
        if (worst_status != 0) _exit(worst_status);
    }
    return 0;
}

int MPI_Comm_rank(MPI_Comm comm, int *rank) { (void)comm; *rank = RANK; return 0; }
int MPI_Comm_size(MPI_Comm comm, int *size) { (void)comm; *size = NP; return 0; }

int MPI_Abort(MPI_Comm comm, int errorcode) {
    (void)comm;
    int code = errorcode ? errorcode : 1;
    if (H) H->abort_code = code;
    fflush(stdout);
    fflush(stderr);
    if (NP > 1) {
        if (RANK == 0) {
            signal(SIGCHLD, SIG_IGN); /* see on_sigterm */
            kill_children();
        } else {
            kill(PARENT_PID, SIGTERM);
        }
    }
    _exit(code);
}

double MPI_Wtime(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static void bar(void) { pthread_barrier_wait(&H->barrier); }

int MPI_Barrier(MPI_Comm comm) { (void)comm; bar(); return 0; }

static void need(size_t bytes, const char *who) {
    if (bytes > H->staging_cap) {
        char m[160];
        snprintf(m, sizeof m,
                 "%s needs %zu staging bytes, have %zu "
                 "(raise MINIMPI_SHM_BYTES)", who, bytes, H->staging_cap);
        fprintf(stderr, "minimpi: %s\n", m);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
}

/* ---- equal-size collectives: chunk automatically through staging ---- */

/* The chunk-loop trip count must be identical on every rank or the
 * barrier phases desynchronize and the job hangs.  MPI 3.1 makes some
 * count arguments significant only at the root (Scatter's sendcount,
 * Gather's recvcount) — so the deciding rank publishes its byte count
 * through the shared header first, every rank chunks by the published
 * value, and a rank whose own significant count disagrees aborts with a
 * diagnosis instead of deadlocking (ADVICE r3). */
static size_t published_bytes(int owner, size_t mine, const char *who) {
    if (RANK == owner) H->counts[0] = mine;
    bar();
    size_t b = H->counts[0];
    if (mine != b) {
        fprintf(stderr,
                "minimpi: %s count mismatch: rank %d has %zu bytes, rank %d "
                "published %zu\n", who, RANK, mine, owner, b);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    bar(); /* counts[0] stays stable until every rank has read it */
    return b;
}

int MPI_Bcast(void *buffer, int count, MPI_Datatype dt, int root,
              MPI_Comm comm) {
    (void)comm;
    size_t bytes = published_bytes(
        root, (size_t)count * (size_t)dt->size, "MPI_Bcast");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < H->staging_cap ? bytes - off : H->staging_cap;
        if (RANK == root && c) memcpy(STG, (char *)buffer + off, c);
        bar();
        if (RANK != root && c) memcpy((char *)buffer + off, STG, c);
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

/* per-rank slice chunk size for rooted equal-size collectives */
static size_t slice_chunk(size_t bytes) {
    size_t per = H->staging_cap / (size_t)NP;
    return bytes < per ? bytes : per;
}

int MPI_Scatter(const void *sendbuf, int sendcount, MPI_Datatype st,
                void *recvbuf, int recvcount, MPI_Datatype rt, int root,
                MPI_Comm comm) {
    (void)comm;
    /* sendcount is significant only at the root; non-roots contribute
     * their (significant) recv-side byte count to the mismatch check. */
    size_t bytes = published_bytes(
        root,
        RANK == root ? (size_t)sendcount * (size_t)st->size
                     : (size_t)recvcount * (size_t)rt->size,
        "MPI_Scatter");
    size_t step = slice_chunk(bytes);
    if (bytes && !step) need(bytes * (size_t)NP, "MPI_Scatter");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (RANK == root && c)
            for (int i = 0; i < NP; i++)
                memcpy(STG + (size_t)i * c,
                       (const char *)sendbuf + (size_t)i * bytes + off, c);
        bar();
        if (c) memcpy((char *)recvbuf + off, STG + (size_t)RANK * c, c);
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

int MPI_Gather(const void *sendbuf, int sendcount, MPI_Datatype st,
               void *recvbuf, int recvcount, MPI_Datatype rt, int root,
               MPI_Comm comm) {
    (void)comm;
    /* recvcount is significant only at the root; the root's per-rank
     * recv slice is the published size every sendcount must match. */
    size_t bytes = published_bytes(
        root,
        RANK == root ? (size_t)recvcount * (size_t)rt->size
                     : (size_t)sendcount * (size_t)st->size,
        "MPI_Gather");
    if (RANK == root && (size_t)sendcount * (size_t)st->size != bytes) {
        fprintf(stderr, "minimpi: MPI_Gather root send/recv count mismatch\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    size_t step = slice_chunk(bytes);
    if (bytes && !step) need(bytes * (size_t)NP, "MPI_Gather");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (c) memcpy(STG + (size_t)RANK * c, (const char *)sendbuf + off, c);
        bar();
        if (RANK == root && c)
            for (int i = 0; i < NP; i++)
                memcpy((char *)recvbuf + (size_t)i * bytes + off,
                       STG + (size_t)i * c, c);
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype st,
                  void *recvbuf, int recvcount, MPI_Datatype rt,
                  MPI_Comm comm) {
    (void)recvcount; (void)rt; (void)comm;
    /* rootless: every rank's sendcount is significant and must agree;
     * rank 0 publishes, everyone cross-checks. */
    size_t bytes = published_bytes(
        0, (size_t)sendcount * (size_t)st->size, "MPI_Allgather");
    size_t step = slice_chunk(bytes);
    if (bytes && !step) need(bytes * (size_t)NP, "MPI_Allgather");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (c) memcpy(STG + (size_t)RANK * c, (const char *)sendbuf + off, c);
        bar();
        if (c)
            for (int i = 0; i < NP; i++)
                memcpy((char *)recvbuf + (size_t)i * bytes + off,
                       STG + (size_t)i * c, c);
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

int MPI_Alltoall(const void *sendbuf, int sendcount, MPI_Datatype st,
                 void *recvbuf, int recvcount, MPI_Datatype rt,
                 MPI_Comm comm) {
    (void)recvcount; (void)rt; (void)comm;
    size_t bytes = published_bytes(
        0, (size_t)sendcount * (size_t)st->size, "MPI_Alltoall");
    size_t per = H->staging_cap / ((size_t)NP * (size_t)NP);
    size_t step = bytes < per ? bytes : per;
    if (bytes && !step) need(bytes * (size_t)NP * (size_t)NP, "MPI_Alltoall");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (c)
            for (int j = 0; j < NP; j++)
                memcpy(STG + ((size_t)RANK * (size_t)NP + (size_t)j) * c,
                       (const char *)sendbuf + (size_t)j * bytes + off, c);
        bar();
        if (c)
            for (int i = 0; i < NP; i++)
                memcpy((char *)recvbuf + (size_t)i * bytes + off,
                       STG + ((size_t)i * (size_t)NP + (size_t)RANK) * c, c);
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

/* ---- ragged collectives: publish counts, then stream the concatenated
 * segment layout through staging in windows of staging_cap bytes, so a
 * single exchange can exceed the staging area by any factor (VERDICT r3
 * #5 — BACKEND=mpi now runs the 2^28-scale benches the pthreads backend
 * can).  Writers copy in the part of each of their segments overlapping
 * the current window; after a barrier, readers copy their parts out.
 * The published count matrix makes the window count identical on every
 * rank, so the barrier phases stay aligned by construction. ---- */

/* Copy the overlap of virtual-layout segment [off, off+len) with the
 * staging window [w, w+wlen): into staging on write, out on read. */
static void seg_window(void *bufseg, size_t off, size_t len,
                       size_t w, size_t wlen, int write) {
    size_t lo = off > w ? off : w;
    size_t end = off + len, wend = w + wlen;
    size_t hi = end < wend ? end : wend;
    if (lo >= hi) return;
    if (write)
        memcpy(STG + (lo - w), (char *)bufseg + (lo - off), hi - lo);
    else
        memcpy((char *)bufseg + (lo - off), STG + (lo - w), hi - lo);
}

int MPI_Scatterv(const void *sendbuf, const int *sendcounts,
                 const int *displs, MPI_Datatype st, void *recvbuf,
                 int recvcount, MPI_Datatype rt, int root, MPI_Comm comm) {
    (void)recvcount; (void)rt; (void)comm;
    if (RANK == root)
        for (int i = 0; i < NP; i++)
            H->counts[i] = (size_t)sendcounts[i] * (size_t)st->size;
    bar();
    size_t tot = 0, mine_off = 0;
    for (int i = 0; i < NP; i++) {
        if (i == RANK) mine_off = tot;
        tot += H->counts[i];
    }
    size_t mine = H->counts[RANK], cap = H->staging_cap;
    for (size_t w = 0; w < tot || w == 0; w += cap) {
        size_t wlen = tot - w < cap ? tot - w : cap;
        if (RANK == root) {
            size_t off = 0;
            for (int i = 0; i < NP; i++) {
                seg_window((char *)sendbuf + (size_t)displs[i] * (size_t)st->size,
                           off, H->counts[i], w, wlen, 1);
                off += H->counts[i];
            }
        }
        bar();
        seg_window(recvbuf, mine_off, mine, w, wlen, 0);
        bar();
        if (tot == 0) break;
    }
    return 0;
}

int MPI_Gatherv(const void *sendbuf, int sendcount, MPI_Datatype st,
                void *recvbuf, const int *recvcounts, const int *displs,
                MPI_Datatype rt, int root, MPI_Comm comm) {
    (void)recvcounts; (void)comm;
    H->counts[RANK] = (size_t)sendcount * (size_t)st->size;
    bar();
    size_t tot = 0, mine_off = 0;
    for (int i = 0; i < NP; i++) {
        if (i == RANK) mine_off = tot;
        tot += H->counts[i];
    }
    size_t mine = H->counts[RANK], cap = H->staging_cap;
    for (size_t w = 0; w < tot || w == 0; w += cap) {
        size_t wlen = tot - w < cap ? tot - w : cap;
        seg_window((void *)sendbuf, mine_off, mine, w, wlen, 1);
        bar();
        if (RANK == root) {
            size_t off = 0;
            for (int i = 0; i < NP; i++) {
                seg_window((char *)recvbuf + (size_t)displs[i] * (size_t)rt->size,
                           off, H->counts[i], w, wlen, 0);
                off += H->counts[i];
            }
        }
        bar();
        if (tot == 0) break;
    }
    return 0;
}

int MPI_Alltoallv(const void *sendbuf, const int *sendcounts,
                  const int *sdispls, MPI_Datatype st, void *recvbuf,
                  const int *recvcounts, const int *rdispls,
                  MPI_Datatype rt, MPI_Comm comm) {
    (void)recvcounts; (void)comm;
    for (int j = 0; j < NP; j++)
        H->counts[(size_t)RANK * (size_t)NP + (size_t)j] =
            (size_t)sendcounts[j] * (size_t)st->size;
    bar();
    /* row-major exclusive prefix over the published [NP,NP] count matrix
     * gives every (src,dst) segment a unique layout offset */
    size_t tot = 0;
    for (int i = 0; i < NP * NP; i++) tot += H->counts[i];
    size_t cap = H->staging_cap;
    for (size_t w = 0; w < tot || w == 0; w += cap) {
        size_t wlen = tot - w < cap ? tot - w : cap;
        size_t off = 0;
        for (int i = 0; i < NP; i++)
            for (int j = 0; j < NP; j++) {
                size_t c = H->counts[(size_t)i * (size_t)NP + (size_t)j];
                if (i == RANK)
                    seg_window((char *)sendbuf + (size_t)sdispls[j] * (size_t)st->size,
                               off, c, w, wlen, 1);
                off += c;
            }
        bar();
        off = 0;
        for (int i = 0; i < NP; i++)
            for (int j = 0; j < NP; j++) {
                size_t c = H->counts[(size_t)i * (size_t)NP + (size_t)j];
                if (j == RANK)
                    seg_window((char *)recvbuf + (size_t)rdispls[i] * (size_t)rt->size,
                               off, c, w, wlen, 0);
                off += c;
            }
        bar();
        if (tot == 0) break;
    }
    return 0;
}

/* ---- typed reductions, deterministic rank order ---- */

#define REDUCE_LOOP(T)                                                      \
    do {                                                                    \
        const T *src = (const T *)STG;                                      \
        T *dst = (T *)((char *)recvbuf + off);                              \
        size_t n = c / sizeof(T);                                           \
        for (size_t e = 0; e < n; e++) {                                    \
            T acc = src[e]; /* rank 0's contribution */                     \
            for (int i = 1; i < NP; i++) {                                  \
                T v = src[(size_t)i * n + e];                               \
                acc = op->which == 0 ? (T)(acc + v)                         \
                    : op->which == 1 ? (acc < v ? acc : v)                  \
                                     : (acc > v ? acc : v);                 \
            }                                                               \
            dst[e] = acc;                                                   \
        }                                                                   \
    } while (0)

int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    (void)comm;
    if (dt->size != 4 && dt->size != 8) {
        fprintf(stderr, "minimpi: unsupported reduction datatype\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    size_t bytes = published_bytes(
        0, (size_t)count * (size_t)dt->size, "MPI_Allreduce");
    size_t step = slice_chunk(bytes);
    step -= step % (size_t)dt->size; /* keep rank slices element-aligned */
    if (bytes && !step) need(bytes * (size_t)NP, "MPI_Allreduce");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (c) memcpy(STG + (size_t)RANK * c, (const char *)sendbuf + off, c);
        bar();
        if (c) {
            if (dt->size == 4) REDUCE_LOOP(uint32_t);
            else REDUCE_LOOP(uint64_t);
        }
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}

#define EXSCAN_LOOP(T)                                                      \
    do {                                                                    \
        const T *src = (const T *)STG;                                      \
        T *dst = (T *)((char *)recvbuf + off);                              \
        size_t n = c / sizeof(T);                                           \
        for (size_t e = 0; e < n; e++) {                                    \
            T acc = src[e]; /* rank 0's contribution */                     \
            for (int i = 1; i < RANK; i++) {                                \
                T v = src[(size_t)i * n + e];                               \
                acc = op->which == 0 ? (T)(acc + v)                         \
                    : op->which == 1 ? (acc < v ? acc : v)                  \
                                     : (acc > v ? acc : v);                 \
            }                                                               \
            dst[e] = acc;                                                   \
        }                                                                   \
    } while (0)

int MPI_Exscan(const void *sendbuf, void *recvbuf, int count,
               MPI_Datatype dt, MPI_Op op, MPI_Comm comm) {
    (void)comm;
    if (dt->size != 4 && dt->size != 8) {
        fprintf(stderr, "minimpi: unsupported reduction datatype\n");
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    size_t bytes = published_bytes(
        0, (size_t)count * (size_t)dt->size, "MPI_Exscan");
    size_t step = slice_chunk(bytes);
    step -= step % (size_t)dt->size; /* keep rank slices element-aligned */
    if (bytes && !step) need(bytes * (size_t)NP, "MPI_Exscan");
    for (size_t off = 0; off < bytes || off == 0; ) {
        size_t c = bytes - off < step ? bytes - off : step;
        if (c) memcpy(STG + (size_t)RANK * c, (const char *)sendbuf + off, c);
        bar();
        /* rank 0's result is undefined per MPI 3.1 §5.11.2 — left
         * untouched so callers (comm_mpi.c) must supply the identity,
         * which is exactly the behavior this runtime exists to test. */
        if (c && RANK > 0) {
            if (dt->size == 4) EXSCAN_LOOP(uint32_t);
            else EXSCAN_LOOP(uint64_t);
        }
        bar();
        off += c;
        if (c == 0) break;
    }
    return 0;
}
