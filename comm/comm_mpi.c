/* comm_mpi.c — thin MPI passthrough backend for comm.h.
 *
 * Every call maps to the matching real MPI collective — none of the
 * reference's hand-rolled emulations survive (SURVEY.md §2.3): its
 * Isend-per-peer Bcast (mpi_sample_sort.c:63-69), tag-as-length
 * Alltoallv (:159-171) and ANY_SOURCE collection (:167) become plain
 * MPI_Bcast / MPI_Alltoallv with explicit counts, so there are no
 * unwaited requests and no nondeterministic arrival orders.
 *
 * Build with `make BACKEND=mpi` (requires an MPI toolchain; the CI image
 * for this repo has none, so the local backend is the default there).
 *
 * Counts/displacements: comm.h traffics in size_t bytes; MPI wants int
 * element counts.  We transfer MPI_BYTE and range-check the casts.
 */
#include "comm_stats.h"   /* first: defines the POSIX feature macro */
#include "comm.h"
#include "comm_faults.h"

#include <mpi.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

struct comm_ctx {
    int rank, size;
};

static int chk_int(size_t v) {
    if (v > (size_t)INT_MAX) {
        fprintf(stderr, "comm_mpi: byte count %zu exceeds INT_MAX\n", v);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    return (int)v;
}

int comm_rank(const comm_ctx *c) { return c->rank; }
int comm_size(const comm_ctx *c) { return c->size; }
double comm_wtime(void) { return MPI_Wtime(); }

void comm_abort(comm_ctx *c, int code, const char *msg) {
    if (msg) fprintf(stderr, "%s\n", msg);
    (void)c;
    MPI_Abort(MPI_COMM_WORLD, code ? code : 1);
}

/* COMM_STATS telemetry (comm_stats.h): one rank per process, so the
 * table is file-static; comm_launch reduces across ranks and rank 0
 * appends the JSON line before MPI_Finalize. */
static comm_stat_t g_stats[COMM_ST_N];
static int g_stats_on;

/* COMM_FAULTS injection (comm_faults.h): one rank per process, so the
 * spec + per-rank collective counter are file-static; comm_launch
 * parses after MPI_Init (the rank is needed).  Over the minimpi
 * runtime a killed rank is a real child process — the supervisor must
 * bring the job down, mpirun-style, instead of hanging. */
static comm_faults_t g_faults;
static unsigned long long g_fault_calls;
static int g_fault_rank;

/* Every collective enters through st_begin — the one injection point,
 * mirroring comm_local.c. */
static double st_begin(void) {
    comm_faults_enter(&g_faults, g_fault_rank, &g_fault_calls);
    return g_stats_on ? MPI_Wtime() : -1.0;
}

static void st_end(int which, size_t bytes, double t0) {
    if (t0 >= 0.0)
        comm_stats_add(g_stats, which, bytes, MPI_Wtime() - t0);
}

void comm_barrier(comm_ctx *c) {
    (void)c;
    double t0 = st_begin();
    MPI_Barrier(MPI_COMM_WORLD);
    st_end(COMM_ST_BARRIER, 0, t0);
}

void comm_bcast(comm_ctx *c, void *buf, size_t bytes, int root) {
    (void)c;
    double t0 = st_begin();
    MPI_Bcast(buf, chk_int(bytes), MPI_BYTE, root, MPI_COMM_WORLD);
    st_end(COMM_ST_BCAST, bytes, t0);
}

void comm_scatter(comm_ctx *c, const void *send, void *recv, size_t bytes,
                  int root) {
    (void)c;
    double t0 = st_begin();
    MPI_Scatter((void *)send, chk_int(bytes), MPI_BYTE, recv, chk_int(bytes),
                MPI_BYTE, root, MPI_COMM_WORLD);
    st_end(COMM_ST_SCATTER, bytes, t0);
}

static int *to_int_array(const size_t *v, int n) {
    int *out = (int *)malloc((size_t)n * sizeof(int));
    for (int i = 0; i < n; i++) out[i] = chk_int(v[i]);
    return out;
}

void comm_scatterv(comm_ctx *c, const void *send, const size_t *counts,
                   const size_t *displs, void *recv, size_t recv_bytes,
                   int root) {
    int *ic = NULL, *id = NULL;
    double t0 = st_begin();
    size_t payload = 0;
    if (c->rank == root) {
        ic = to_int_array(counts, c->size);
        id = to_int_array(displs, c->size);
        /* total payload on the root; other ranks record the call only,
         * so the cross-rank SUM matches the local backend's accounting */
        if (g_stats_on)
            for (int i = 0; i < c->size; i++) payload += counts[i];
    }
    MPI_Scatterv((void *)send, ic, id, MPI_BYTE, recv, chk_int(recv_bytes),
                 MPI_BYTE, root, MPI_COMM_WORLD);
    st_end(COMM_ST_SCATTERV, payload, t0);
    free(ic);
    free(id);
}

void comm_gather(comm_ctx *c, const void *send, void *recv, size_t bytes,
                 int root) {
    (void)c;
    double t0 = st_begin();
    MPI_Gather((void *)send, chk_int(bytes), MPI_BYTE, recv, chk_int(bytes),
               MPI_BYTE, root, MPI_COMM_WORLD);
    st_end(COMM_ST_GATHER, bytes, t0);
}

void comm_gatherv(comm_ctx *c, const void *send, size_t send_bytes,
                  void *recv, const size_t *counts, const size_t *displs,
                  int root) {
    int *ic = NULL, *id = NULL;
    double t0 = st_begin();
    if (c->rank == root) {
        ic = to_int_array(counts, c->size);
        id = to_int_array(displs, c->size);
    }
    MPI_Gatherv((void *)send, chk_int(send_bytes), MPI_BYTE, recv, ic, id,
                MPI_BYTE, root, MPI_COMM_WORLD);
    st_end(COMM_ST_GATHERV, send_bytes, t0);
    free(ic);
    free(id);
}

void comm_allgather(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    double t0 = st_begin();
    MPI_Allgather((void *)send, chk_int(bytes), MPI_BYTE, recv,
                  chk_int(bytes), MPI_BYTE, MPI_COMM_WORLD);
    st_end(COMM_ST_ALLGATHER, bytes * (size_t)c->size, t0);
}

static MPI_Datatype mpi_type(comm_type t) {
    return t == COMM_T_U32 ? MPI_UINT32_T : MPI_UINT64_T;
}

static MPI_Op mpi_op(comm_op op) {
    return op == COMM_OP_SUM ? MPI_SUM : (op == COMM_OP_MIN ? MPI_MIN : MPI_MAX);
}

void comm_allreduce(comm_ctx *c, const void *send, void *recv, size_t count,
                    comm_type t, comm_op op) {
    (void)c;
    double t0 = st_begin();
    MPI_Allreduce((void *)send, recv, chk_int(count), mpi_type(t), mpi_op(op),
                  MPI_COMM_WORLD);
    st_end(COMM_ST_ALLREDUCE, count * ((t == COMM_T_U32) ? 4 : 8), t0);
}

void comm_exscan(comm_ctx *c, const void *send, void *recv, size_t count,
                 comm_type t, comm_op op) {
    double t0 = st_begin();
    MPI_Exscan((void *)send, recv, chk_int(count), mpi_type(t), mpi_op(op),
               MPI_COMM_WORLD);
    st_end(COMM_ST_EXSCAN, count * ((t == COMM_T_U32) ? 4 : 8), t0);
    if (c->rank == 0) {
        /* MPI leaves rank 0's Exscan result undefined; comm.h defines it
         * as the operator identity. */
        size_t esz = (t == COMM_T_U32) ? 4 : 8;
        memset(recv, op == COMM_OP_MIN ? 0xFF : 0, count * esz);
    }
}

void comm_alltoall(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    double t0 = st_begin();
    MPI_Alltoall((void *)send, chk_int(bytes), MPI_BYTE, recv,
                 chk_int(bytes), MPI_BYTE, MPI_COMM_WORLD);
    st_end(COMM_ST_ALLTOALL, bytes * (size_t)c->size, t0);
}

void comm_alltoallv(comm_ctx *c, const void *send, const size_t *scounts,
                    const size_t *sdispls, void *recv, const size_t *rcounts,
                    const size_t *rdispls) {
    int n = c->size;
    double t0 = st_begin();
    size_t sent = 0;
    if (t0 >= 0.0)  /* O(P) byte sum only when telemetry is on */
        for (int i = 0; i < n; i++) sent += scounts[i];
    int *isc = to_int_array(scounts, n), *isd = to_int_array(sdispls, n);
    int *irc = to_int_array(rcounts, n), *ird = to_int_array(rdispls, n);
    MPI_Alltoallv((void *)send, isc, isd, MPI_BYTE, recv, irc, ird, MPI_BYTE,
                  MPI_COMM_WORLD);
    st_end(COMM_ST_ALLTOALLV, sent, t0);
    free(isc);
    free(isd);
    free(irc);
    free(ird);
}

int comm_launch(void (*fn)(comm_ctx *, void *), void *arg) {
    MPI_Init(NULL, NULL);
    comm_ctx ctx;
    MPI_Comm_rank(MPI_COMM_WORLD, &ctx.rank);
    MPI_Comm_size(MPI_COMM_WORLD, &ctx.size);
    g_fault_rank = ctx.rank;
    if (comm_faults_parse(getenv("COMM_FAULTS"), &g_faults) != 0)
        MPI_Abort(MPI_COMM_WORLD, 1); /* bad drill spec: fail loudly */
    const char *stats_path = comm_stats_path();
    g_stats_on = stats_path != NULL;
    fn(&ctx, arg);
    if (g_stats_on) {
        /* Reduce the per-rank tables to the comm_stats.h totals
         * semantics — SUM calls/bytes, MAX seconds (as integer ns: the
         * comm.h type census has no float reduction) — then rank 0
         * appends the JSON line.  Raw MPI calls so the reduction never
         * bills itself into the counters it is reducing. */
        uint64_t cb[2 * COMM_ST_N], cb_tot[2 * COMM_ST_N];
        uint64_t ns[COMM_ST_N], ns_max[COMM_ST_N];
        for (int i = 0; i < COMM_ST_N; i++) {
            cb[2 * i] = g_stats[i].calls;
            cb[2 * i + 1] = g_stats[i].bytes;
            ns[i] = (uint64_t)(g_stats[i].seconds * 1e9);
        }
        MPI_Allreduce(cb, cb_tot, 2 * COMM_ST_N, MPI_UINT64_T, MPI_SUM,
                      MPI_COMM_WORLD);
        MPI_Allreduce(ns, ns_max, COMM_ST_N, MPI_UINT64_T, MPI_MAX,
                      MPI_COMM_WORLD);
        if (ctx.rank == 0) {
            comm_stat_t totals[COMM_ST_N];
            for (int i = 0; i < COMM_ST_N; i++) {
                totals[i].calls = cb_tot[2 * i];
                totals[i].bytes = cb_tot[2 * i + 1];
                totals[i].seconds = (double)ns_max[i] * 1e-9;
            }
            comm_stats_dump(stats_path, "mpi", ctx.size, totals);
        }
    }
    MPI_Finalize();
    return 0;
}
