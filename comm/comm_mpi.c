/* comm_mpi.c — thin MPI passthrough backend for comm.h.
 *
 * Every call maps to the matching real MPI collective — none of the
 * reference's hand-rolled emulations survive (SURVEY.md §2.3): its
 * Isend-per-peer Bcast (mpi_sample_sort.c:63-69), tag-as-length
 * Alltoallv (:159-171) and ANY_SOURCE collection (:167) become plain
 * MPI_Bcast / MPI_Alltoallv with explicit counts, so there are no
 * unwaited requests and no nondeterministic arrival orders.
 *
 * Build with `make BACKEND=mpi` (requires an MPI toolchain; the CI image
 * for this repo has none, so the local backend is the default there).
 *
 * Counts/displacements: comm.h traffics in size_t bytes; MPI wants int
 * element counts.  We transfer MPI_BYTE and range-check the casts.
 */
#include "comm.h"

#include <mpi.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

struct comm_ctx {
    int rank, size;
};

static int chk_int(size_t v) {
    if (v > (size_t)INT_MAX) {
        fprintf(stderr, "comm_mpi: byte count %zu exceeds INT_MAX\n", v);
        MPI_Abort(MPI_COMM_WORLD, 1);
    }
    return (int)v;
}

int comm_rank(const comm_ctx *c) { return c->rank; }
int comm_size(const comm_ctx *c) { return c->size; }
double comm_wtime(void) { return MPI_Wtime(); }

void comm_abort(comm_ctx *c, int code, const char *msg) {
    if (msg) fprintf(stderr, "%s\n", msg);
    (void)c;
    MPI_Abort(MPI_COMM_WORLD, code ? code : 1);
}

void comm_barrier(comm_ctx *c) { (void)c; MPI_Barrier(MPI_COMM_WORLD); }

void comm_bcast(comm_ctx *c, void *buf, size_t bytes, int root) {
    (void)c;
    MPI_Bcast(buf, chk_int(bytes), MPI_BYTE, root, MPI_COMM_WORLD);
}

void comm_scatter(comm_ctx *c, const void *send, void *recv, size_t bytes,
                  int root) {
    (void)c;
    MPI_Scatter((void *)send, chk_int(bytes), MPI_BYTE, recv, chk_int(bytes),
                MPI_BYTE, root, MPI_COMM_WORLD);
}

static int *to_int_array(const size_t *v, int n) {
    int *out = (int *)malloc((size_t)n * sizeof(int));
    for (int i = 0; i < n; i++) out[i] = chk_int(v[i]);
    return out;
}

void comm_scatterv(comm_ctx *c, const void *send, const size_t *counts,
                   const size_t *displs, void *recv, size_t recv_bytes,
                   int root) {
    int *ic = NULL, *id = NULL;
    if (c->rank == root) {
        ic = to_int_array(counts, c->size);
        id = to_int_array(displs, c->size);
    }
    MPI_Scatterv((void *)send, ic, id, MPI_BYTE, recv, chk_int(recv_bytes),
                 MPI_BYTE, root, MPI_COMM_WORLD);
    free(ic);
    free(id);
}

void comm_gather(comm_ctx *c, const void *send, void *recv, size_t bytes,
                 int root) {
    (void)c;
    MPI_Gather((void *)send, chk_int(bytes), MPI_BYTE, recv, chk_int(bytes),
               MPI_BYTE, root, MPI_COMM_WORLD);
}

void comm_gatherv(comm_ctx *c, const void *send, size_t send_bytes,
                  void *recv, const size_t *counts, const size_t *displs,
                  int root) {
    int *ic = NULL, *id = NULL;
    if (c->rank == root) {
        ic = to_int_array(counts, c->size);
        id = to_int_array(displs, c->size);
    }
    MPI_Gatherv((void *)send, chk_int(send_bytes), MPI_BYTE, recv, ic, id,
                MPI_BYTE, root, MPI_COMM_WORLD);
    free(ic);
    free(id);
}

void comm_allgather(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    (void)c;
    MPI_Allgather((void *)send, chk_int(bytes), MPI_BYTE, recv,
                  chk_int(bytes), MPI_BYTE, MPI_COMM_WORLD);
}

static MPI_Datatype mpi_type(comm_type t) {
    return t == COMM_T_U32 ? MPI_UINT32_T : MPI_UINT64_T;
}

static MPI_Op mpi_op(comm_op op) {
    return op == COMM_OP_SUM ? MPI_SUM : (op == COMM_OP_MIN ? MPI_MIN : MPI_MAX);
}

void comm_allreduce(comm_ctx *c, const void *send, void *recv, size_t count,
                    comm_type t, comm_op op) {
    (void)c;
    MPI_Allreduce((void *)send, recv, chk_int(count), mpi_type(t), mpi_op(op),
                  MPI_COMM_WORLD);
}

void comm_exscan(comm_ctx *c, const void *send, void *recv, size_t count,
                 comm_type t, comm_op op) {
    MPI_Exscan((void *)send, recv, chk_int(count), mpi_type(t), mpi_op(op),
               MPI_COMM_WORLD);
    if (c->rank == 0) {
        /* MPI leaves rank 0's Exscan result undefined; comm.h defines it
         * as the operator identity. */
        size_t esz = (t == COMM_T_U32) ? 4 : 8;
        memset(recv, op == COMM_OP_MIN ? 0xFF : 0, count * esz);
    }
}

void comm_alltoall(comm_ctx *c, const void *send, void *recv, size_t bytes) {
    (void)c;
    MPI_Alltoall((void *)send, chk_int(bytes), MPI_BYTE, recv,
                 chk_int(bytes), MPI_BYTE, MPI_COMM_WORLD);
}

void comm_alltoallv(comm_ctx *c, const void *send, const size_t *scounts,
                    const size_t *sdispls, void *recv, const size_t *rcounts,
                    const size_t *rdispls) {
    int n = c->size;
    int *isc = to_int_array(scounts, n), *isd = to_int_array(sdispls, n);
    int *irc = to_int_array(rcounts, n), *ird = to_int_array(rdispls, n);
    MPI_Alltoallv((void *)send, isc, isd, MPI_BYTE, recv, irc, ird, MPI_BYTE,
                  MPI_COMM_WORLD);
    free(isc);
    free(isd);
    free(irc);
    free(ird);
}

int comm_launch(void (*fn)(comm_ctx *, void *), void *arg) {
    MPI_Init(NULL, NULL);
    comm_ctx ctx;
    MPI_Comm_rank(MPI_COMM_WORLD, &ctx.rank);
    MPI_Comm_size(MPI_COMM_WORLD, &ctx.size);
    fn(&ctx, arg);
    MPI_Finalize();
    return 0;
}
