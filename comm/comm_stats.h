/* comm_stats.h — optional per-collective telemetry for the comm.h shim.
 *
 * The native twin of the TPU span layer (mpitest_tpu/utils/spans.py):
 * when COMM_STATS=<path> is set, each backend counts every collective's
 * calls / payload bytes / wall seconds per rank and appends ONE JSON
 * line at the end of comm_launch (the shim's finalize point), so native
 * and TPU runs feed `python -m mpitest_tpu.report` with the same
 * per-collective schema:
 *
 *   {"v": "comm_stats.v1", "backend": "local"|"mpi", "ranks": P,
 *    "collectives": {"alltoallv": {"calls": C, "bytes": B,
 *                                  "seconds": S}, ...}}
 *
 * Aggregation semantics (documented in README/PARITY): calls and bytes
 * are SUMS over ranks of each rank's per-call payload bytes (the buffer
 * byte counts the caller passed — the same quantity the TPU spans
 * record per collective); seconds is the MAX over ranks of that rank's
 * accumulated wall time in the collective — critical-path time, so a
 * P-rank barrier-bound run does not report P-fold inflated seconds.
 *
 * Header-only (static functions): both backends include it and stay
 * single-translation-unit, so no Makefile in the tree needs a new
 * object file.  Overhead when COMM_STATS is unset: one getenv at
 * launch, one branch per collective.
 */
#ifndef COMM_STATS_H
#define COMM_STATS_H

/* clock_gettime under -std=c11 needs a POSIX feature macro; it only
 * takes effect if no system header ran first, so backends include this
 * header BEFORE comm.h (comm_local.c's _GNU_SOURCE subsumes it). */
#if !defined(_GNU_SOURCE) && !defined(_POSIX_C_SOURCE)
#define _POSIX_C_SOURCE 199309L
#endif

#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

enum {
    COMM_ST_BARRIER,
    COMM_ST_BCAST,
    COMM_ST_SCATTER,
    COMM_ST_SCATTERV,
    COMM_ST_GATHER,
    COMM_ST_GATHERV,
    COMM_ST_ALLGATHER,
    COMM_ST_ALLREDUCE,
    COMM_ST_EXSCAN,
    COMM_ST_ALLTOALL,
    COMM_ST_ALLTOALLV,
    COMM_ST_N
};

typedef struct {
    unsigned long long calls;
    unsigned long long bytes;
    double seconds;
} comm_stat_t;

static const char *const comm_stat_names[COMM_ST_N] = {
    "barrier",   "bcast",  "scatter",   "scatterv", "gather", "gatherv",
    "allgather", "allreduce", "exscan", "alltoall", "alltoallv",
};

/* getenv once at launch; NULL means telemetry off (zero timer calls). */
static inline const char *comm_stats_path(void) { return getenv("COMM_STATS"); }

static inline double comm_stats_now(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

static inline void comm_stats_add(comm_stat_t *table, int which, size_t bytes,
                           double seconds) {
    table[which].calls += 1;
    table[which].bytes += (unsigned long long)bytes;
    table[which].seconds += seconds;
}

/* Fold rank tables into totals: sum calls/bytes, max seconds (see the
 * aggregation semantics above). */
static inline void comm_stats_fold(comm_stat_t *tot, const comm_stat_t *rank_tab) {
    for (int i = 0; i < COMM_ST_N; i++) {
        tot[i].calls += rank_tab[i].calls;
        tot[i].bytes += rank_tab[i].bytes;
        if (rank_tab[i].seconds > tot[i].seconds)
            tot[i].seconds = rank_tab[i].seconds;
    }
}

/* Append the one-line JSON record.  Returns 0 on success; on failure
 * prints to stderr and returns nonzero — telemetry must never abort a
 * completed sort. */
static inline int comm_stats_dump(const char *path, const char *backend, int nranks,
                           const comm_stat_t *totals) {
    FILE *f = fopen(path, "a");
    if (!f) {
        fprintf(stderr, "comm_stats: cannot open %s for append\n", path);
        return 1;
    }
    fprintf(f, "{\"v\": \"comm_stats.v1\", \"backend\": \"%s\", "
               "\"ranks\": %d, \"collectives\": {", backend, nranks);
    int first = 1;
    for (int i = 0; i < COMM_ST_N; i++) {
        if (!totals[i].calls)
            continue;
        fprintf(f, "%s\"%s\": {\"calls\": %llu, \"bytes\": %llu, "
                   "\"seconds\": %.9f}",
                first ? "" : ", ", comm_stat_names[i], totals[i].calls,
                totals[i].bytes, totals[i].seconds);
        first = 0;
    }
    fprintf(f, "}}\n");
    fclose(f);
    return 0;
}

#endif /* COMM_STATS_H */
