#!/usr/bin/env python3
"""Bench-trajectory report: the BENCH_r01→rNN history as one table.

The repo pins one ``BENCH_rNN.json`` snapshot per bench round (the
driver envelope: ``{"n", "cmd", "rc", "tail", "parsed"}`` where
``tail`` carries the run's stdout — metrics-sidecar and bench-row JSON
lines included), but nothing read them ACROSS rounds: the performance
story lived in prose.  This tool parses every snapshot, extracts the
trajectory columns — raw sort throughput, end-to-end (ingest-included)
throughput and its ratio, the scale-out row's cap saving, the serve
row's SLO numbers — and renders a markdown table with one row per
round plus per-metric regression flags: a value below ``threshold``
(default 0.9) of the best earlier round is marked ``⚠ (0.83x)``.

Usage::

    python tools/bench_history.py [--dir .] [--threshold 0.9]
    make bench-history

Exit code 0 always — the trajectory is a report, not a gate (the
per-PR gates live in report.py ``--baseline`` and the selftests);
``--strict`` exits 2 when any flag fires, for CI jobs that want one.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

#: (column key, pretty header, unit, higher-is-better) — the trajectory
#: columns.  ``None`` cells render as ``-`` (a round predating the
#: metric is not a regression).
COLUMNS: tuple[tuple[str, str, str, bool], ...] = (
    ("sort_mkeys_per_s", "sort", "Mkeys/s", True),
    ("sort_incl_ingest_mkeys_per_s", "incl-ingest", "Mkeys/s", True),
    ("ingest_ratio", "ingest ratio", "x", True),
    ("encode_gb_per_s", "encode", "GB/s", True),
    ("cap_saving_pct", "cap saving", "%", True),
    ("serve_mkeys_per_s", "serve", "Mkeys/s", True),
    ("serve_p99_ms", "serve p99", "ms", False),
    # plan provenance (ISSUE 12): rows record the run's decision regret
    # beside its throughput, so the trajectory captures DECISIONS —
    # rising regret is a planner/negotiation regression even when the
    # throughput column still looks fine
    ("plan_regret", "plan regret", "x", False),
    # out-of-core external sort (ISSUE 15): spill+merge throughput
    # under a forced memory budget; pre-r06 rounds render "-"
    ("external_mkeys_per_s", "external", "Mkeys/s", True),
)

#: String-valued trajectory columns (ISSUE 13): rendered verbatim, no
#: regression math — the engine column exists so `exchange_engine=
#: {lax,pallas}` rows land comparable from r06 onward (a throughput
#: jump that coincides with an engine flip is attribution, not noise).
LABEL_COLUMNS: tuple[tuple[str, str], ...] = (
    ("exchange_engine", "engine"),
    # ISSUE 17: the local-sort engine the row measured under (lax /
    # bitonic family / radix_pallas family) — pinned on measured rows
    # via setdefault; pre-r06 rounds render "-".
    ("local_engine", "local"),
    # ISSUE 14: the planner mode the row measured under — pinned "off"
    # on measured rows via setdefault; pre-r06 rounds render "-".
    ("planner", "planner"),
    # ISSUE 16: the timeline fold's per-pass straggler factor
    # (max/median rank bytes, the 8dev row's value when present) —
    # rendered as a ratio string, no regression math, pre-r06 "-".
    ("straggler", "straggler"),
    # ISSUE 20: the external row's spill compression ratio (logical /
    # spilled bytes) and measured final-merge disk/compute overlap —
    # rendered verbatim (pre-r06 rounds, and rounds predating the
    # fields, render "-"; no regression math).
    ("spill_ratio", "spill ratio"),
    ("disk_overlap", "disk ov"),
)

_RUN_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: Absolute floor for LOWER-is-better columns when comparing against
#: the best earlier round: a best of 0 (common for plan_regret — every
#: prediction matched) would otherwise make ANY later nonzero value an
#: infinite-ratio regression, failing the strict CI gate on meaningless
#: near-zero jitter.  Values must exceed best-or-floor / threshold to
#: flag.
LOWER_BEST_FLOOR = 0.25


def _json_lines(text: str) -> list[dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            out.append(obj)
    return out


def load_run(path: Path) -> dict[str, object]:
    """Extract the trajectory metrics from one BENCH_rNN.json envelope.
    Both record shapes in the tail are folded: metrics sidecars
    (``{"config", "metrics": {name: {"value": ...}}}``) and bench rows
    (``{"metric", "value", ...}`` — including the ``_8dev`` scale-out
    and serve rows with their extra fields).  Numeric trajectory values
    keyed by metric name, plus a ``"_labels"`` dict of string columns
    (the ISSUE 13 engine column)."""
    env = json.loads(path.read_text())
    vals: dict[str, float] = {}
    labels: dict[str, str] = {}

    def put(name: str, v: object) -> None:
        try:
            vals[name] = float(v)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            pass

    for obj in _json_lines(str(env.get("tail", ""))):
        if "metrics" in obj and "config" in obj:
            for mname, m in obj["metrics"].items():
                if isinstance(m, dict) and "value" in m:
                    put(mname, m["value"])
        elif "metric" in obj and "value" in obj:
            name = str(obj["metric"])
            if name.startswith("serve_"):
                put("serve_mkeys_per_s", obj["value"])
                put("serve_p99_ms", obj.get("p99_ms"))
            elif name.endswith("_8dev"):
                put("cap_saving_pct", obj.get("cap_saving_pct"))
                put("plan_regret", obj.get("plan_regret"))
                # ISSUE 16: the scale-out row is the one with a real
                # exchange, so its straggler wins over the primary's
                sf = obj.get("straggler_factor")
                if isinstance(sf, (int, float)):
                    labels["straggler"] = f"{sf:g}x"
            elif name.startswith("external_sort_"):
                # ISSUE 15: the out-of-core row — never folded into
                # the in-memory sort column
                put("external_mkeys_per_s", obj["value"])
                # ISSUE 20: compression + IO-overlap labels (rows
                # predating the fields render "-")
                sr = obj.get("spill_ratio")
                if isinstance(sr, (int, float)):
                    labels["spill_ratio"] = f"{sr:g}x"
                do = obj.get("disk_overlap")
                if isinstance(do, (int, float)):
                    labels["disk_overlap"] = f"{100 * do:.0f}%"
            else:
                put("sort_row_mkeys_per_s", obj["value"])
                if "plan_regret" not in vals:
                    put("plan_regret", obj.get("plan_regret"))
                # ISSUE 13: the primary row's exchange engine (pre-r06
                # rounds predate the field and render "-")
                if isinstance(obj.get("exchange_engine"), str):
                    labels["exchange_engine"] = obj["exchange_engine"]
                # ISSUE 14: ditto the planner column
                if isinstance(obj.get("planner"), str):
                    labels["planner"] = obj["planner"]
                # ISSUE 17: ditto the local-sort engine column
                if isinstance(obj.get("local_engine"), str):
                    labels["local_engine"] = obj["local_engine"]
                # ISSUE 16: primary-row straggler only when no 8dev
                # row carried one (single-device runs usually don't)
                sf = obj.get("straggler_factor")
                if isinstance(sf, (int, float)):
                    labels.setdefault("straggler", f"{sf:g}x")
    vals["_labels"] = labels  # type: ignore[assignment]
    # derived: end-to-end ratio when a round recorded both throughputs
    # but not the ratio itself (pre-ISSUE-6 rounds)
    if "ingest_ratio" not in vals and \
            vals.get("sort_mkeys_per_s") and \
            vals.get("sort_incl_ingest_mkeys_per_s"):
        vals["ingest_ratio"] = round(
            vals["sort_incl_ingest_mkeys_per_s"] / vals["sort_mkeys_per_s"],
            3)
    # the sidecar's sort_mkeys_per_s and the bench row agree by
    # construction; fall back to the row when only it parsed
    if "sort_mkeys_per_s" not in vals and "sort_row_mkeys_per_s" in vals:
        vals["sort_mkeys_per_s"] = vals["sort_row_mkeys_per_s"]
    return vals


def find_runs(directory: Path) -> list[tuple[int, Path]]:
    runs = []
    for p in sorted(directory.glob("BENCH_r*.json")):
        m = _RUN_RE.search(p.name)
        if m:
            runs.append((int(m.group(1)), p))
    return sorted(runs)


def build_table(runs: list[tuple[int, Path]],
                threshold: float = 0.9) -> tuple[str, list[str]]:
    """(markdown table, regression flag descriptions).  A cell is
    flagged when it is worse than ``threshold`` x the best earlier
    round (direction per column); earlier-missing metrics never flag."""
    rows = [(rid, load_run(p)) for rid, p in runs]
    flags: list[str] = []
    header = "| run | " + " | ".join(
        f"{title} ({unit})" for _k, title, unit, _hib in COLUMNS)
    header += " | " + " | ".join(t for _k, t in LABEL_COLUMNS) + " |"
    sep = "|---" * (len(COLUMNS) + len(LABEL_COLUMNS) + 1) + "|"
    lines = [header, sep]
    best: dict[str, float] = {}
    for rid, vals in rows:
        labels = vals.get("_labels") or {}
        cells = [f"r{rid:02d}"]
        for key, title, _unit, hib in COLUMNS:
            v = vals.get(key)
            if not isinstance(v, (int, float)):
                cells.append("-")
                continue
            cell = f"{v:g}"
            prev = best.get(key)
            if prev is not None:
                floor = max(prev, LOWER_BEST_FLOOR)
                regressed = (v < threshold * prev) if hib else \
                    (v > floor / threshold)
                if regressed:
                    ratio = (v / prev) if hib else (floor / v)
                    cell += f" ⚠ ({ratio:.2f}x)"
                    flags.append(
                        f"r{rid:02d} {title}: {v:g} vs best {prev:g} "
                        f"({ratio:.2f}x, threshold {threshold:g})")
            best[key] = max(prev, v) if (prev is not None and hib) else \
                min(prev, v) if prev is not None else v
            cells.append(cell)
        for key, _title in LABEL_COLUMNS:
            lv = labels.get(key) if isinstance(labels, dict) else None
            cells.append(lv if isinstance(lv, str) else "-")
        lines.append("| " + " | ".join(cells) + " |")
    return "\n".join(lines), flags


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_rNN.json (default .)")
    ap.add_argument("--threshold", type=float, default=0.9,
                    help="flag when worse than THRESHOLD x the best "
                         "earlier round (default 0.9)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 2 when any regression flag fires")
    args = ap.parse_args(argv)
    runs = find_runs(Path(args.dir))
    if not runs:
        print(f"[ERROR] no BENCH_rNN.json under {args.dir!r}",
              file=sys.stderr)
        return 1
    table, flags = build_table(runs, args.threshold)
    print(f"bench trajectory ({len(runs)} round(s), regression "
          f"threshold {args.threshold:g}):\n")
    print(table)
    if flags:
        print("\nregression flags:")
        for f in flags:
            print(f"  ⚠ {f}")
    else:
        print("\nno regression flags")
    return 2 if (flags and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
