"""sortlint — the project's custom AST linter (stdlib ``ast``, zero deps).

Generic linters check style; this one checks the **project invariants**
that PRs 1-3 accumulated and that nothing enforced mechanically until
now.  Each rule encodes one hard-won lesson:

========  =================================================================
SL001     env knobs are read ONLY through ``mpitest_tpu/utils/knobs.py``
          (typed, validated, self-documenting); scattered ``os.environ``
          reads are where unvalidated garbage enters.  Writes stay legal.
SL002     spans are opened only as context managers (``with ...span(...)``)
          — an un-entered span silently records nothing.
SL003     literal span/phase names must come from the registered schema
          (``utils/span_schema.py``) that report.py aggregates by — a
          renamed span must fail the lint, not silently vanish from the
          telemetry tables.
SL004     literal live-metric names (``<metrics>.counter/gauge/
          histogram("...")``) must come from the registered vocabulary
          in ``utils/metrics_live.py`` — same contract as SL003 for the
          /metrics exposition surface (ISSUE 10).
SL005     literal plan decision names (``<plan>.decide/actual/
          bump("...")``) must come from ``models/plan.py``
          PLAN_DECISIONS (ISSUE 12 provenance vocabulary).
SL006     literal planner policy names must come from
          ``models/planner.py`` PLANNER_POLICIES — at lookups and at
          recorded planner verdicts (ISSUE 14).
SL007     literal pathology rule names (doctor ``run_rule``, sentinel
          ``.alert/._alert``, ``serve.alert`` emissions' ``rule=``)
          must come from ``mpitest_tpu/doctor.py`` DOCTOR_RULES
          (ISSUE 16 diagnosis vocabulary).
SL010     no ``lax.reduce`` — custom reduction computations are
          UNIMPLEMENTED under the SPMD partitioner (CHANGES.md, PR 3);
          use halving folds / jnp reductions.
SL011     no bare ``jax.device_put`` — ``checked_device_put`` exists
          because a silent dtype downcast produced a wrong sort once
          (bench.py:171, PR 2); the guard is mandatory.
SL012     no host syncs (``np.asarray`` / ``np.array`` /
          ``jax.device_get`` / ``.block_until_ready`` / ``.item``)
          inside functions that are jitted or shard_map'ed — they poison
          the trace or force mid-program round-trips.
SL020     fault-registry completeness: every ``faults.SITES`` entry is
          exercised by ``bench/fault_selftest.py``; every COMM_FAULTS
          kind in ``comm/comm_faults.h`` is hooked in BOTH C backends
          and drilled by the selftest.
SL030     every registered knob carries a nonempty one-line doc.
SL031     every registered knob appears in README's reference table.
SL040     the typed core (``models/``, ``parallel/``, ``utils/spans.py``,
          ``faults.py``) carries full signature annotations — the
          in-container proxy for the mypy strict gate (mypy itself runs
          in CI's lint job and wherever installed).
========  =================================================================

Suppressions are explicit and must carry a reason::

    something_flagged()  # sortlint: disable=SL003 -- why this is safe

A directive without a reason is itself a finding (SL000).  The linter
imports nothing from the package under lint (pure ``ast`` + text), so
the CI lint job needs no jax/numpy stack.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable

#: Bumped when rules change meaningfully; recorded in bench run metadata
#: so BENCH rows are attributable to a tooling state.
LINT_VERSION = "sortlint.v1"

#: Default lint targets relative to the repo root.  tests/ is excluded
#: on purpose: fixture snippets there exist to VIOLATE the rules.
DEFAULT_TARGETS = ("mpitest_tpu", "drivers", "tools", "bench.py", "bench")

_SUPPRESS_RE = re.compile(
    r"#\s*sortlint:\s*disable=(?P<ids>[A-Z0-9, ]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    msg: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    doc: str
    #: check(path, src, tree) -> findings; ``tree`` is None for repo
    #: rules (scope == "repo"), which run once with path = repo root.
    check: Callable[[str, str, ast.AST | None], list[Finding]]
    scope: str = "file"  # "file" | "repo"


def _suppressions(src: str) -> dict[int, tuple[set[str], str | None]]:
    """line -> (rule ids, reason) for every suppression directive."""
    out: dict[int, tuple[set[str], str | None]] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            ids = {t.strip() for t in m.group("ids").split(",") if t.strip()}
            out[i] = (ids, m.group("reason"))
    return out


def apply_suppressions(src: str, findings: list[Finding],
                       path: str) -> list[Finding]:
    """Drop findings suppressed on their own line (or the line above);
    emit SL000 for directives missing a reason — a suppression is an
    inline design note, not a mute button."""
    sup = _suppressions(src)
    out = []
    for i, (ids, reason) in sup.items():
        if reason is None:
            out.append(Finding(
                "SL000", path, i,
                f"suppression of {','.join(sorted(ids))} has no reason; "
                "write `# sortlint: disable=<ID> -- <why>`"))
    for f in findings:
        killed = False
        for ln in (f.line, f.line - 1):
            entry = sup.get(ln)
            if entry and f.rule in entry[0] and entry[1]:
                killed = True
                break
        if not killed:
            out.append(f)
    return out


# Rule registration happens in tools/sortlint/rules.py (imported at the
# bottom of this module to avoid a cycle: rules need Finding).
RULES: list[Rule] = []


def register(rule: Rule) -> None:
    RULES.append(rule)


def lint_source(src: str, path: str = "<snippet>",
                rules: Iterable[str] | None = None) -> list[Finding]:
    """Lint one source string (the test harness entry point)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("SL999", path, e.lineno or 0, f"syntax error: {e.msg}")]
    findings: list[Finding] = []
    for rule in RULES:
        if rule.scope != "file":
            continue
        if rules is not None and rule.id not in rules:
            continue
        findings.extend(rule.check(path, src, tree))
    return apply_suppressions(src, findings, path)


def iter_target_files(root: Path, targets: Iterable[str]) -> list[Path]:
    files: list[Path] = []
    for t in targets:
        p = root / t
        if p.is_file() and p.suffix == ".py":
            files.append(p)
        elif p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
    return files


def lint_repo(root: str | Path = ".",
              targets: Iterable[str] = DEFAULT_TARGETS) -> list[Finding]:
    """Lint the repo: file rules over ``targets`` + repo rules once."""
    root = Path(root)
    findings: list[Finding] = []
    for f in iter_target_files(root, targets):
        rel = str(f.relative_to(root))
        findings.extend(lint_source(f.read_text(), rel))
    for rule in RULES:
        if rule.scope == "repo":
            findings.extend(rule.check(str(root), "", None))
    return findings


from tools.sortlint import rules as _rules  # noqa: E402,F401  (registers RULES)
