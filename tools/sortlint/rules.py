"""The sortlint rules (see package docstring for the one-line census).

Everything here is pure ``ast`` + text: the linter never imports the
package under lint, so it runs on a bare Python with no jax/numpy —
the CI lint job's whole point.  The span schema is loaded from
``mpitest_tpu/utils/span_schema.py`` by file path (that module is
stdlib-only by design) so SL003 checks against the real registry, not
a copy.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

from tools.registry_load import load_registry_module
from tools.sortlint import Finding, Rule, register

REPO_ROOT = Path(__file__).resolve().parents[2]

_SCHEMA = load_registry_module(
    "_sortlint_span_schema",
    REPO_ROOT / "mpitest_tpu" / "utils" / "span_schema.py")


def _ends(path: str, *suffixes: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in suffixes)


def _walk(node: ast.AST,
          stack: tuple[str, ...] = ()) -> Iterator[tuple[ast.AST,
                                                         tuple[str, ...]]]:
    """ast.walk with the enclosing-function-name stack attached."""
    yield node, stack
    child_stack = stack
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        child_stack = stack + (node.name,)
    for child in ast.iter_child_nodes(node):
        yield from _walk(child, child_stack)


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain ('' when not a chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------- SL001

def _check_env_read(path: str, src: str, tree: ast.AST) -> list[Finding]:
    if _ends(path, "mpitest_tpu/utils/knobs.py"):
        return []
    out = []
    for node, _ in _walk(tree):
        chain = ""
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("os.environ.get", "os.getenv"):
                out.append(Finding(
                    "SL001", path, node.lineno,
                    f"env read via {chain}; read knobs through "
                    "mpitest_tpu.utils.knobs (get/get_raw) so the value "
                    "is typed, validated and documented"))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                _attr_chain(node.value) == "os.environ":
            out.append(Finding(
                "SL001", path, node.lineno,
                "env read via os.environ[...]; use mpitest_tpu.utils."
                "knobs instead (writes are fine, reads are not)"))
        elif isinstance(node, ast.Compare) and \
                any(isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops) and \
                any(_attr_chain(c) == "os.environ"
                    for c in node.comparators):
            out.append(Finding(
                "SL001", path, node.lineno,
                "membership test on os.environ; knobs.get_raw() is None "
                "when unset"))
    return out


register(Rule(
    "SL001", "env-knob-read",
    "os.environ/os.getenv reads outside utils/knobs.py (writes allowed)",
    _check_env_read))


# --------------------------------------------------------- SL002 / SL003

#: Modules that ARE the span mechanism — the rules police its users.
_SPAN_EXEMPT = ("mpitest_tpu/utils/spans.py", "mpitest_tpu/utils/trace.py")


def _span_call_kind(call: ast.Call) -> str | None:
    """'span' for span-opening calls, 'point' for event/record/emit,
    'phase' for Tracer.phase — None for anything else.

    Matching is attribute-shaped on purpose: bare names like ``emit``
    collide with unrelated local helpers, so only the idioms the repo
    actually uses match — ``<x>.span`` / ``<x>.maybe_span`` (any base),
    ``<x>.phase`` (Tracer), and ``event``/``record``/``emit`` when the
    base is a span log (``spans`` / ``log`` / ``slog`` / ``span_log``).
    """
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr in ("span", "maybe_span"):
        return "span"
    if f.attr == "phase":
        return "phase"
    if f.attr in ("event", "record", "emit"):
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else ""
        if base_name in ("spans", "log", "slog", "span_log"):
            return "point"
    return None


def _check_span_ctx(path: str, src: str, tree: ast.AST) -> list[Finding]:
    if _ends(path, *_SPAN_EXEMPT):
        return []
    allowed: set[int] = set()
    for node, _ in _walk(tree):
        if isinstance(node, ast.With):
            for item in node.items:
                allowed.add(id(item.context_expr))
        elif isinstance(node, ast.Return) and node.value is not None:
            # wrapper idiom: `return spans.maybe_span(...)` — the caller
            # enters it; the definition modules are exempt anyway
            allowed.add(id(node.value))
    out = []
    for node, _ in _walk(tree):
        if isinstance(node, ast.Call) \
                and _span_call_kind(node) in ("span", "phase") \
                and id(node) not in allowed:
            out.append(Finding(
                "SL002", path, node.lineno,
                "span/phase opened outside a `with` statement (or "
                "returned as one) — an un-entered span records nothing; "
                "use `with ...span(...):` / `with ...phase(...):`"))
    return out


register(Rule(
    "SL002", "span-context-manager",
    "spans may only be opened as context managers",
    _check_span_ctx))


def _check_span_name(path: str, src: str, tree: ast.AST) -> list[Finding]:
    if _ends(path, *_SPAN_EXEMPT):
        return []
    out = []
    for node, _ in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _span_call_kind(node)
        if kind is None or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            name = first.value
            ok = (name in _SCHEMA.PHASE_NAMES if kind == "phase"
                  else _SCHEMA.is_registered(name))
            if not ok:
                where = ("utils/span_schema.py PHASE_NAMES"
                         if kind == "phase" else "utils/span_schema.py")
                out.append(Finding(
                    "SL003", path, node.lineno,
                    f"span name {name!r} is not registered in {where}; "
                    "register it there (report.py aggregates by these "
                    "names — unregistered spans vanish from the tables)"))
        else:
            out.append(Finding(
                "SL003", path, node.lineno,
                "non-literal span name — the registered-schema check "
                "cannot see it; use a literal, or suppress with the "
                "reason the name is provably schema-bound"))
    return out


register(Rule(
    "SL003", "span-name-schema",
    "literal span/phase names must come from utils/span_schema.py",
    _check_span_name))


# ---------------------------------------------------------------- SL004

#: utils/metrics_live.py by file path (stdlib-only by design, like
#: span_schema) — SL004 checks against the real METRICS dict.
_METRICS_MOD = load_registry_module(
    "_sortlint_metrics_live",
    REPO_ROOT / "mpitest_tpu" / "utils" / "metrics_live.py")

#: The module that IS the metric registry — the rule polices its users.
_METRICS_EXEMPT = ("mpitest_tpu/utils/metrics_live.py",)

#: Receiver names that denote a live-metrics registry.  Attribute-shaped
#: matching like SL003: `<metrics-ish>.counter/gauge/histogram("name")`
#: — unrelated bases (e.g. ``kernels.histogram``) never match.
_METRIC_BASES = ("metrics", "live_metrics", "mlive", "registry")


def _check_metric_name(path: str, src: str, tree: ast.AST) -> list[Finding]:
    if _ends(path, *_METRICS_EXEMPT):
        return []
    out = []
    for node, _ in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) or \
                f.attr not in ("counter", "gauge", "histogram"):
            continue
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else ""
        if base_name not in _METRIC_BASES or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            name = first.value
            if name not in _METRICS_MOD.METRICS:
                out.append(Finding(
                    "SL004", path, node.lineno,
                    f"metric name {name!r} is not registered in "
                    "utils/metrics_live.py METRICS; register it there "
                    "(the /metrics exposition check and report.py key "
                    "on these names — unregistered metrics fail the "
                    "telemetry selftest)"))
            else:
                kind = _METRICS_MOD.METRICS[name][0]
                if kind != f.attr:
                    out.append(Finding(
                        "SL004", path, node.lineno,
                        f"metric {name!r} is registered as a {kind} but "
                        f"used via .{f.attr}()"))
        else:
            out.append(Finding(
                "SL004", path, node.lineno,
                "non-literal metric name — the registered-name check "
                "cannot see it; use a literal, or suppress with a "
                "reason"))
    return out


register(Rule(
    "SL004", "metric-name-registry",
    "literal metric names must come from utils/metrics_live.py METRICS",
    _check_metric_name))


# ---------------------------------------------------------------- SL005

#: models/plan.py by file path (stdlib-only at import by design, like
#: span_schema) — SL005 checks against the real PLAN_DECISIONS.
#: plan.py declares dataclasses -> register=True (span_schema/metrics
#: carry none, so their loads skip it).
_PLAN_MOD = load_registry_module(
    "_sortlint_plan",
    REPO_ROOT / "mpitest_tpu" / "models" / "plan.py", register=True)

#: The module that IS the decision registry — the rule polices users.
_PLAN_EXEMPT = ("mpitest_tpu/models/plan.py",)

#: Receiver names that denote a SortPlan.  Attribute-shaped matching
#: like SL003/SL004: ``<plan-ish>.decide/actual/bump("name", ...)`` —
#: unrelated bases never match.
_PLAN_BASES = ("plan", "sort_plan", "bplan", "splan")


def _check_plan_decision(path: str, src: str,
                         tree: ast.AST) -> list[Finding]:
    if _ends(path, *_PLAN_EXEMPT):
        return []
    out = []
    for node, _ in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) or \
                f.attr not in ("decide", "actual", "bump"):
            continue
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else ""
        if base_name not in _PLAN_BASES or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            name = first.value
            if name not in _PLAN_MOD.PLAN_DECISIONS:
                out.append(Finding(
                    "SL005", path, node.lineno,
                    f"plan decision {name!r} is not registered in "
                    "models/plan.py PLAN_DECISIONS; register it there "
                    "(report.py --explain and the /varz decision "
                    "snapshot key on these names — unregistered "
                    "decisions vanish from the provenance surfaces)"))
        else:
            out.append(Finding(
                "SL005", path, node.lineno,
                "non-literal plan decision name — the registered-"
                "vocabulary check cannot see it; use a literal, or "
                "suppress with a reason"))
    return out


register(Rule(
    "SL005", "plan-decision-registry",
    "literal plan decision names must come from models/plan.py "
    "PLAN_DECISIONS",
    _check_plan_decision))


# ---------------------------------------------------------------- SL006

#: models/planner.py by file path (stdlib-only at import by design,
#: like plan.py, dataclasses included) — SL006 checks against the real
#: PLANNER_POLICIES.
_PLANNER_MOD = load_registry_module(
    "_sortlint_planner",
    REPO_ROOT / "mpitest_tpu" / "models" / "planner.py", register=True)

#: The module that IS the policy registry — the rule polices users.
_PLANNER_EXEMPT = ("mpitest_tpu/models/planner.py",)

#: Receiver names that denote the planner module / a tuner object.
_PLANNER_BASES = ("planner", "planner_mod", "sort_planner", "tuner")


def _check_planner_policy(path: str, src: str,
                          tree: ast.AST) -> list[Finding]:
    """SL006: literal planner policy names must come from the
    registered ``PLANNER_POLICIES`` vocabulary (models/planner.py) —
    both at the lookup (``planner.policy("x")``) and where a plan
    records the planner verdict (``plan.decide("planner",
    chosen="x")``).  An unregistered policy would vanish from the
    explain census, the /metrics decision labels and the selftest's
    policy accounting."""
    if _ends(path, *_PLANNER_EXEMPT):
        return []
    out = []
    for node, _ in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else ""
        if f.attr == "policy" and base_name in _PLANNER_BASES \
                and node.args:
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                if first.value not in _PLANNER_MOD.PLANNER_POLICIES:
                    out.append(Finding(
                        "SL006", path, node.lineno,
                        f"planner policy {first.value!r} is not "
                        "registered in models/planner.py "
                        "PLANNER_POLICIES; register it there (the "
                        "explain census, /metrics labels and the "
                        "planner selftest key on these names)"))
            # non-literal names are fine HERE: planner.policy() raises
            # KeyError on unregistered names at runtime — the dynamic
            # call IS the registry check this rule enforces statically
            continue
        # plan.decide("planner", chosen="<policy>"): the recorded
        # verdict must use a registered policy name too
        if f.attr == "decide" and base_name in _PLAN_BASES and node.args:
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and first.value == "planner"):
                continue
            for kw in node.keywords:
                if kw.arg == "chosen" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str) \
                        and kw.value.value not in \
                        _PLANNER_MOD.PLANNER_POLICIES:
                    out.append(Finding(
                        "SL006", path, node.lineno,
                        f"planner decision records unregistered policy "
                        f"{kw.value.value!r}; register it in "
                        "models/planner.py PLANNER_POLICIES"))
    return out


register(Rule(
    "SL006", "planner-policy-registry",
    "literal planner policy names must come from models/planner.py "
    "PLANNER_POLICIES",
    _check_planner_policy))


# ---------------------------------------------------------------- SL007

#: mpitest_tpu/doctor.py by file path (stdlib-only at import by
#: design, like plan.py, dataclasses included) — SL007 checks against
#: the real DOCTOR_RULES.
_DOCTOR_MOD = load_registry_module(
    "_sortlint_doctor", REPO_ROOT / "mpitest_tpu" / "doctor.py",
    register=True)

#: The module that IS the rule registry — SL007 polices users.
_DOCTOR_EXEMPT = ("mpitest_tpu/doctor.py",)

#: Receiver names that denote the doctor module.
_DOCTOR_BASES = ("doctor", "doctor_mod", "sort_doctor")


def _check_doctor_rule(path: str, src: str,
                       tree: ast.AST) -> list[Finding]:
    """SL007: literal pathology rule names must come from the
    registered ``DOCTOR_RULES`` vocabulary (mpitest_tpu/doctor.py) —
    at doctor lookups (``doctor.run_rule("x", ...)``), at sentinel
    alert raises (``<any>.alert("x", ...)`` / ``._alert``), and on the
    ``rule=`` kwarg of a literal ``"serve.alert"`` span emission.  An
    unregistered rule name would vanish from the /alerts surfaces, the
    ``sort_alerts_total{rule}`` labels and the doctor-selftest's
    pathology accounting."""
    if _ends(path, *_DOCTOR_EXEMPT):
        return []
    out = []

    def vet(node: ast.Call, name: str, what: str) -> None:
        if name not in _DOCTOR_MOD.DOCTOR_RULES:
            out.append(Finding(
                "SL007", path, node.lineno,
                f"{what} {name!r} is not registered in "
                "mpitest_tpu/doctor.py DOCTOR_RULES; register it there "
                "(/alerts, the sort_alerts_total rule labels and the "
                "doctor selftest key on these names)"))

    for node, _ in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        base = f.value
        base_name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else ""
        first = node.args[0] if node.args else None
        literal = (first.value if isinstance(first, ast.Constant)
                   and isinstance(first.value, str) else None)
        if f.attr == "run_rule" and base_name in _DOCTOR_BASES \
                and literal is not None:
            # non-literal names are fine HERE: run_rule raises KeyError
            # on unregistered names at runtime (the SL006 pattern)
            vet(node, literal, "doctor rule")
        elif f.attr in ("alert", "_alert") and literal is not None:
            # attribute-shaped like SL003's .span: any receiver — the
            # sentinel is the producer today, but a rule name baked
            # into ANY alert raise must be registered
            vet(node, literal, "alert rule")
        elif f.attr in ("record", "event", "emit") \
                and literal == "serve.alert":
            # the span-emission chokepoint: a literal rule= kwarg on a
            # serve.alert emission is a rule name too (non-literal
            # kwargs route through SortSentinel._alert, which vets at
            # runtime)
            for kw in node.keywords:
                if kw.arg == "rule" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    vet(node, kw.value.value, "serve.alert rule")
    return out


register(Rule(
    "SL007", "doctor-rule-registry",
    "literal pathology rule names must come from mpitest_tpu/doctor.py "
    "DOCTOR_RULES",
    _check_doctor_rule))


# ------------------------------------------------------- SL010 / SL011 / SL012

def _check_lax_reduce(path: str, src: str, tree: ast.AST) -> list[Finding]:
    out = []
    for node, _ in _walk(tree):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.endswith("lax.reduce") or chain == "lax.reduce":
                out.append(Finding(
                    "SL010", path, node.lineno,
                    "custom lax.reduce is UNIMPLEMENTED under the SPMD "
                    "partitioner (PR 3 lesson); use a halving fold or a "
                    "jnp reduction"))
    return out


register(Rule(
    "SL010", "spmd-lax-reduce",
    "lax.reduce is banned (SPMD partitioner cannot lower it)",
    _check_lax_reduce))


def _check_device_put(path: str, src: str, tree: ast.AST) -> list[Finding]:
    out = []
    for node, stack in _walk(tree):
        if isinstance(node, ast.Call) and \
                _attr_chain(node.func) == "jax.device_put" and \
                "checked_device_put" not in stack:
            out.append(Finding(
                "SL011", path, node.lineno,
                "bare jax.device_put silently downcasts when x64 is off "
                "(PR 2 regression); use models.ingest.checked_device_put"))
    return out


register(Rule(
    "SL011", "bare-device-put",
    "jax.device_put only inside checked_device_put",
    _check_device_put))

_HOST_SYNC_CALLS = {
    "np.asarray": "materializes the traced value on host",
    "np.array": "materializes the traced value on host",
    "numpy.asarray": "materializes the traced value on host",
    "jax.device_get": "forces a device->host round-trip",
    "jax.device_put": "host placement inside a traced region",
}


def _traced_function_names(tree: ast.AST) -> set[str]:
    """Names of functions passed to jit()/shard_map() or decorated so."""
    traced: set[str] = set()
    for node, _ in _walk(tree):
        if isinstance(node, ast.Call):
            callee = _attr_chain(node.func)
            if callee.split(".")[-1] in ("jit", "shard_map"):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        traced.add(arg.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _attr_chain(target).split(".")[-1] in ("jit",
                                                          "shard_map"):
                    traced.add(node.name)
    return traced


def _check_host_sync(path: str, src: str, tree: ast.AST) -> list[Finding]:
    traced = _traced_function_names(tree)
    if not traced:
        return []
    out = []
    for node, stack in _walk(tree):
        if not stack or not any(s in traced for s in stack):
            continue
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain in _HOST_SYNC_CALLS:
            out.append(Finding(
                "SL012", path, node.lineno,
                f"{chain} inside traced function "
                f"{[s for s in stack if s in traced][-1]!r}: "
                f"{_HOST_SYNC_CALLS[chain]}"))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("block_until_ready", "item"):
            out.append(Finding(
                "SL012", path, node.lineno,
                f".{node.func.attr}() inside a traced function forces a "
                "host sync / fails at trace time"))
    return out


register(Rule(
    "SL012", "host-sync-in-traced",
    "no host syncs inside jitted/shard_map'ed functions",
    _check_host_sync))


# ---------------------------------------------------------------- SL013

#: The kernel home: the ONE directory Pallas lowering may live in.
#: Everything else composes kernels through these entry points, so the
#: interpret-mode parity gates (bitonic suite, exchange engine axis)
#: cover every kernel the production paths can reach.
_PALLAS_HOME = "mpitest_tpu/ops/"


def _fn_params(node: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    a = node.args
    return {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs
                            + ([a.vararg] if a.vararg else [])
                            + ([a.kwarg] if a.kwarg else []))}


def _check_pallas_home(path: str, src: str, tree: ast.AST) -> list[Finding]:
    p = path.replace("\\", "/")
    in_home = ("/" + _PALLAS_HOME in p) or p.startswith(_PALLAS_HOME)
    out = []

    def visit(node: ast.AST,
              fn_stack: tuple[ast.FunctionDef | ast.AsyncFunctionDef, ...],
              ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_stack = fn_stack + (node,)
        if isinstance(node, ast.Call) and \
                _attr_chain(node.func).split(".")[-1] == "pallas_call":
            if not in_home:
                out.append(Finding(
                    "SL013", path, node.lineno,
                    "pl.pallas_call outside mpitest_tpu/ops/ — kernels "
                    "live in ops/ behind interpret-capable entry points "
                    "so the CPU parity gates can exercise them; compose "
                    "the existing ops/ entry points instead"))
            elif not any("interpret" in _fn_params(f) for f in fn_stack):
                out.append(Finding(
                    "SL013", path, node.lineno,
                    "pallas_call inside an entry point with no "
                    "`interpret=` parameter — every kernel entry point "
                    "must be drivable by the interpret-mode parity "
                    "gates (tests/bitonic suite, exchange engine axis)"))
        for child in ast.iter_child_nodes(node):
            visit(child, fn_stack)

    visit(tree, ())
    return out


register(Rule(
    "SL013", "pallas-kernel-home",
    "pl.pallas_call only inside mpitest_tpu/ops/, behind interpret= "
    "entry points",
    _check_pallas_home))


# ---------------------------------------------------------------- SL014

#: The ONE module allowed to open spill/run files (ISSUE 15): the
#: SORTBIN1-framed run format, its payload section and its fingerprint
#: sidecar are a contract — ad-hoc reads/writes elsewhere would bypass
#: the framing checks and the sidecar fold that make a bad run file
#: loud instead of silently wrong.
_RUN_FILE_HOME = "mpitest_tpu/store/runs.py"

#: The ONE module allowed to open spill-manifest journals (ISSUE 18):
#: the journal's commit protocol (atomic begin, fsync'd appends,
#: torn-tail replay) lives in store/manifest.py — ad-hoc ``.mfst``
#: writes elsewhere would break the crash-resume guarantee silently.
_MANIFEST_HOME = "mpitest_tpu/store/manifest.py"

#: File-name suffixes that identify a spill artifact (the run format's
#: whole on-disk surface: keys, payload, sidecar, wire staging, and
#: the ISSUE 18 manifest journal).
_RUN_SUFFIXES = (".run", ".runz", ".pay", ".fpr.json", ".spill",
                 ".mfst")

#: RunInfo path accessors — passing one to open()/np.memmap is the
#: other ad-hoc bypass shape.
_RUN_PATH_ATTRS = ("pay_path", "sidecar_path")

_OPENERS = ("open", "memmap")


def _spill_suffix(node: ast.AST) -> str | None:
    """The run-suffix an argument expression names, or None: a string
    constant (or f-string tail) ending in a run suffix, or a RunInfo
    path accessor (reported as ``.run``-family)."""
    text = None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, str):
            text = last.value
    if text is not None:
        for suf in _RUN_SUFFIXES:
            if text.endswith(suf):
                return suf
    if isinstance(node, ast.Attribute) and node.attr in _RUN_PATH_ATTRS:
        return ".run"
    return None


def _spill_literalish(node: ast.AST) -> bool:
    """True when an argument expression names a spill artifact."""
    return _spill_suffix(node) is not None


def _check_run_file_fence(path: str, src: str,
                          tree: ast.AST) -> list[Finding]:
    p = path.replace("\\", "/")
    in_runs_home = p.endswith(_RUN_FILE_HOME)
    in_manifest_home = p.endswith(_MANIFEST_HOME)
    out = []
    for node, _stk in _walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        leaf = chain.split(".")[-1]
        # os.rename of a spill artifact is a finding ANYWHERE (both
        # homes included): a non-atomic publish loses the all-or-
        # nothing crash guarantee — spill artifacts commit via
        # os.replace + fsync(dir) (ISSUE 18 durable-commit protocol)
        if chain in ("os.rename", "rename") and leaf == "rename":
            if any(_spill_literalish(a) for a in node.args):
                out.append(Finding(
                    "SL014", path, node.lineno,
                    "os.rename of a spill artifact — spill files "
                    "commit via os.replace (+ fsync of the directory) "
                    "so a crash leaves them fully present or absent, "
                    "never half-published"))
            continue
        if leaf not in _OPENERS:
            continue
        for a in node.args:
            suf = _spill_suffix(a)
            if suf is None:
                continue
            if suf == ".mfst":
                if in_manifest_home:
                    continue
                out.append(Finding(
                    "SL014", path, node.lineno,
                    "ad-hoc open of a spill-manifest journal (.mfst) "
                    "outside store/manifest.py — the journal's commit "
                    "protocol (atomic begin, fsync'd appends, "
                    "torn-tail replay) lives there; go through "
                    "store.manifest (load/live_manifests/"
                    "ManifestWriter) so crash resume stays sound"))
            else:
                if in_runs_home:
                    continue
                out.append(Finding(
                    "SL014", path, node.lineno,
                    "ad-hoc open()/memmap of a spill-run artifact "
                    "(.run/.pay/.fpr.json/.spill) outside "
                    "store/runs.py — run files carry SORTBIN1 framing "
                    "+ a fingerprint sidecar; go through store.runs "
                    "(write_run/open_run/read_run_chunks/"
                    "run_body_views) so a bad file stays a typed, "
                    "loud error"))
            break
    return out


register(Rule(
    "SL014", "spill-file-fence",
    "spill-run files only via store/runs.py, manifest journals only "
    "via store/manifest.py, publishes via os.replace (never os.rename)",
    _check_run_file_fence))


# ---------------------------------------------------------------- SL020

def _parse_sites(faults_path: Path) -> list[str]:
    tree = ast.parse(faults_path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "SITES" and \
                        isinstance(node.value, ast.Tuple):
                    return [e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)]
    return []


def _check_fault_coverage(root: str, _src: str,
                          _tree: ast.AST | None) -> list[Finding]:
    rootp = Path(root)
    out = []
    faults_py = rootp / "mpitest_tpu" / "faults.py"
    selftest = rootp / "bench" / "fault_selftest.py"
    if faults_py.exists() and selftest.exists():
        sites = _parse_sites(faults_py)
        if not sites:
            out.append(Finding("SL020", "mpitest_tpu/faults.py", 1,
                               "could not parse the SITES tuple"))
        body = selftest.read_text()
        # The grid enumerates the registry itself (`for site in
        # faults.SITES`) — that IS full coverage, and it stays complete
        # when a new site is added.  Without that idiom, every site must
        # appear literally.
        if "faults.SITES" not in body:
            for site in sites:
                if site not in body:
                    out.append(Finding(
                        "SL020", "bench/fault_selftest.py", 1,
                        f"fault site {site!r} (mpitest_tpu/faults.py "
                        "SITES) is never exercised by the chaos grid"))
    faults_h = rootp / "comm" / "comm_faults.h"
    if faults_h.exists():
        kinds = [m.group(1).lower() for m in
                 re.finditer(r"COMM_FAULT_([A-Z]+)\s*=\s*\d",
                             faults_h.read_text())
                 if m.group(1) != "NONE"]
        for backend in ("comm_local.c", "comm_mpi.c"):
            src_c = (rootp / "comm" / backend).read_text()
            if "comm_faults_enter" not in src_c:
                out.append(Finding(
                    "SL020", f"comm/{backend}", 1,
                    "backend never calls comm_faults_enter — COMM_FAULTS "
                    "drills are dead on this backend"))
        if selftest.exists():
            body = selftest.read_text()
            for kind in kinds:
                if f"{kind}:" not in body:
                    out.append(Finding(
                        "SL020", "bench/fault_selftest.py", 1,
                        f"COMM_FAULTS kind {kind!r} (comm/comm_faults.h) "
                        "is never drilled by the selftest"))
    return out


register(Rule(
    "SL020", "fault-registry-coverage",
    "every declared fault site is exercised; both C backends hook faults",
    _check_fault_coverage, scope="repo"))


# ------------------------------------------------------- SL030 / SL031

def _registered_knobs(root: Path) -> list[tuple[str, int, str | None]]:
    """(name, lineno, doc literal or None) per register() call."""
    knobs_py = root / "mpitest_tpu" / "utils" / "knobs.py"
    tree = ast.parse(knobs_py.read_text())
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "register"):
            continue
        name = (node.args[0].value
                if node.args and isinstance(node.args[0], ast.Constant)
                else None)
        doc = None
        if len(node.args) >= 5 and isinstance(node.args[4], ast.Constant):
            doc = node.args[4].value
        for kw in node.keywords:
            if kw.arg == "doc" and isinstance(kw.value, ast.Constant):
                doc = kw.value.value
        out.append((name, node.lineno, doc))
    return out


def _check_knob_docs(root: str, _src: str,
                     _tree: ast.AST | None) -> list[Finding]:
    out = []
    for name, lineno, doc in _registered_knobs(Path(root)):
        where = "mpitest_tpu/utils/knobs.py"
        if name is None:
            out.append(Finding("SL030", where, lineno,
                               "register() with a non-literal knob name — "
                               "the registry must be statically auditable"))
        elif not doc:
            out.append(Finding("SL030", where, lineno,
                               f"knob {name} registered without a literal "
                               "nonempty doc"))
    return out


register(Rule(
    "SL030", "knob-doc",
    "every registered knob carries a literal nonempty doc",
    _check_knob_docs, scope="repo"))


def _check_knob_readme(root: str, _src: str,
                       _tree: ast.AST | None) -> list[Finding]:
    rootp = Path(root)
    readme = rootp / "README.md"
    if not readme.exists():
        return [Finding("SL031", "README.md", 1, "README.md missing")]
    body = readme.read_text()
    out = []
    for name, lineno, _doc in _registered_knobs(rootp):
        if name and f"`{name}`" not in body:
            out.append(Finding(
                "SL031", "README.md", 1,
                f"registered knob {name} is not documented in README "
                "(run `make knob-docs` to regenerate the embedded table)"))
    return out


register(Rule(
    "SL031", "knob-readme",
    "every registered knob appears in README's reference table",
    _check_knob_readme, scope="repo"))


# ---------------------------------------------------------------- SL040

#: The typed core: modules where every function signature must be fully
#: annotated (the in-container proxy for the mypy strict gate).
TYPED_MODULES = (
    "mpitest_tpu/models/", "mpitest_tpu/parallel/",
    "mpitest_tpu/utils/spans.py", "mpitest_tpu/utils/span_schema.py",
    "mpitest_tpu/utils/knobs.py", "mpitest_tpu/faults.py",
)


def _in_typed_core(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(("/" + t in p or p.startswith(t)) if t.endswith(".py")
               else ("/" + t in p or p.startswith(t)) for t in TYPED_MODULES)


def _check_typed_core(path: str, src: str, tree: ast.AST) -> list[Finding]:
    if not _in_typed_core(path):
        return []
    out = []

    def visit_scope(body: list[ast.stmt], in_class: bool) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit_scope(node.body, in_class=True)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                args = (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else []))
                skip_self = in_class and args and \
                    args[0].arg in ("self", "cls")
                missing = [arg.arg for arg in args[1 if skip_self else 0:]
                           if arg.annotation is None]
                if missing:
                    out.append(Finding(
                        "SL040", path, node.lineno,
                        f"typed-core function {node.name!r} has "
                        f"unannotated parameter(s): {', '.join(missing)}"))
                if node.returns is None:
                    out.append(Finding(
                        "SL040", path, node.lineno,
                        f"typed-core function {node.name!r} has no return "
                        "annotation"))
                # nested defs (jit bodies etc.) are exempt by design

    if isinstance(tree, ast.Module):
        visit_scope(tree.body, in_class=False)
    return out


register(Rule(
    "SL040", "typed-core",
    "full signature annotations in the typed core modules",
    _check_typed_core))
