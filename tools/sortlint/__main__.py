"""CLI: ``python -m tools.sortlint [--root DIR] [targets...]``.

Exit 0 on a clean run, 1 on findings — the `make lint` contract.
``--list-rules`` prints the rule census (the count is also recorded in
bench run metadata so BENCH rows are attributable to a tooling state).
"""

from __future__ import annotations

import argparse
import sys

from tools.sortlint import DEFAULT_TARGETS, LINT_VERSION, RULES, lint_repo


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.sortlint")
    ap.add_argument("targets", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS})")
    ap.add_argument("--root", default=".", help="repo root")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(f"{LINT_VERSION}: {len(RULES)} rules")
        for r in RULES:
            print(f"  {r.id} [{r.scope}] {r.name}: {r.doc}")
        return 0

    findings = lint_repo(args.root, args.targets or DEFAULT_TARGETS)
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        print(f.render())
    n = len(findings)
    print(f"sortlint: {n} finding(s), {len(RULES)} rules ({LINT_VERSION})",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
