"""Shared file-path registry loader for the lint tools.

Both linters (sortlint, threadlint) check source against the repo's
REAL registries — span schema, metric vocabulary, plan decisions,
planner policies, doctor rules, thread roots/locks — without ever
importing the ``mpitest_tpu`` package: the registry modules are
stdlib-only by design, so loading them by file path keeps the CI lint
job free of jax/numpy.  This helper is that loader, factored out of the
five near-identical ``_load_*`` functions sortlint's SL003/SL004/SL005/
SL006/SL007 grew one PR at a time.

``register=True`` inserts the module into ``sys.modules`` BEFORE exec:
registries that declare dataclasses need it (dataclass processing looks
the defining module up by name), while pure-dict registries don't.  The
alias deliberately carries a private per-tool prefix (``_sortlint_*``,
``_threadlint_*``) so a file-path load can never shadow a real package
import in the same process (the test suite imports both).
"""

from __future__ import annotations

import importlib.util
import sys
from pathlib import Path
from typing import Any


def load_registry_module(alias: str, path: Path, *,
                         register: bool = False) -> Any:
    """Exec ``path`` as a standalone module named ``alias`` and return
    it.  Raises ``FileNotFoundError`` for a missing file and whatever
    the module itself raises on exec — a registry that fails to load is
    a lint-tool configuration bug, never silently skipped."""
    if not path.is_file():
        raise FileNotFoundError(f"registry module not found: {path}")
    spec = importlib.util.spec_from_file_location(alias, path)
    assert spec is not None and spec.loader is not None
    mod = importlib.util.module_from_spec(spec)
    if register:
        # dataclass-bearing registries: processing looks the module up
        # in sys.modules during exec, so insert first
        sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod
