#!/usr/bin/env python3
"""Cross-backend comm-layer parity checker (the static half of `make lint`).

The native comm surface exists in FIVE places that must agree and that
nothing cross-checked until now:

* ``comm/comm.h`` — the declared ``comm_*`` API;
* ``comm/comm_local.c`` and ``comm/comm_mpi.c`` — the two backends, each
  of which must define every declared symbol (a missing definition only
  surfaces when some program first links it — possibly in the one CI job
  with a real MPI install);
* ``comm/mpi_stub/mpi.h`` + ``mpi_mock.c`` + ``minimpi.c`` — every
  ``MPI_*`` function the MPI backend calls must be declared in the
  vendored stub and implemented by BOTH mock runtimes, or the
  MPI-without-MPI builds rot silently.

It also extracts the collective call-sequence from each native sorter
and flags the classic static deadlock smell: a collective call inside a
rank-conditional branch (``if (rank == ...) comm_barrier(...)`` hangs
every other rank forever — the reference's stranded-peer failure shape,
SURVEY §7.4).  Genuinely-safe cases carry an inline
``/* parity: ok -- <reason> */`` on the same line.

Pure text/regex over the C sources — no compiler needed; runs in the CI
lint job.  Exit 0 clean / 1 on mismatches (printed one per line).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

#: comm.h symbols every backend must define.
_DECL_RE = re.compile(r"^\s*(?:int|void|double)\s+(comm_\w+)\s*\(",
                      re.MULTILINE)

#: native/encode.h symbols (the ingest-engine surface, ISSUE 6): every
#: declared enc_* function must be defined in encode.c, or the ctypes
#: shim's _bind() dies at runtime in whichever job first loads the .so.
_ENC_DECL_RE = re.compile(
    r"^\s*(?:int|void|long long|size_t)\s+(enc_\w+)\s*\(", re.MULTILINE)

#: native/spillz.h symbols (the spill-compression surface, ISSUE 20):
#: same contract as encode.h — every declared spz_* function must be
#: defined in spillz.c or store/compress.py's _bind() dies at load.
_SPZ_DECL_RE = re.compile(
    r"^\s*(?:int|void|long long|size_t)\s+(spz_\w+)\s*\(", re.MULTILINE)


#: A function DEFINITION: return type + name + ( ... with no trailing ';'
#: on the prototype line run (brace may sit on a later line).
def _defined_symbols(src: str,
                     pattern: str = r"comm_\w+|MPI_\w+") -> set[str]:
    out = set()
    for m in re.finditer(
            r"^[A-Za-z_][\w\s\*]*?\b(" + pattern + r")\s*\(", src,
            re.MULTILINE):
        # walk to the matching ')' then check for '{' (definition) vs ';'
        i = m.end() - 1
        depth = 0
        while i < len(src):
            if src[i] == "(":
                depth += 1
            elif src[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        rest = src[i + 1:i + 80].lstrip()
        if rest.startswith("{"):
            out.add(m.group(1))
    return out


#: Collectives (entered by every rank together); rooted or not, ALL of
#: them block in both backends, so a rank-conditional call is a hang.
_COLLECTIVES = ("comm_barrier", "comm_bcast", "comm_scatter",
                "comm_scatterv", "comm_gather", "comm_gatherv",
                "comm_allgather", "comm_allreduce", "comm_exscan",
                "comm_alltoall", "comm_alltoallv")

_RANK_COND_RE = re.compile(
    r"if\s*\([^)]*\b(rank|RANK|me|myid)\b[^)]*\)")

_OK_RE = re.compile(r"/\*\s*parity:\s*ok\s*--\s*\S[^*]*\*/")


def _strip_comments(src: str) -> str:
    # newline-preserving blanking, so line numbers survive the strip
    blank = lambda m: re.sub(r"[^\n]", " ", m.group())  # noqa: E731
    src = re.sub(r"/\*.*?\*/", blank, src, flags=re.S)
    return re.sub(r"//[^\n]*", blank, src)


def _brace_depth_prefix(src: str) -> list[int]:
    depth, out = 0, []
    for ch in src:
        out.append(depth)
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
    return out


def check_rank_conditional_collectives(path: Path) -> list[str]:
    """Flag collective calls lexically inside a rank-conditional block.

    Heuristic: a collective call whose enclosing brace depth is deeper
    than the nearest preceding rank-test ``if`` at a shallower depth,
    within the same function.  Conservative (single-statement ifs
    without braces are caught by same-line/next-line adjacency)."""
    raw = path.read_text()
    src = _strip_comments(raw)
    lines = raw.splitlines()
    findings = []
    for m in re.finditer(r"\b(" + "|".join(_COLLECTIVES) + r")\s*\(", src):
        lineno = src.count("\n", 0, m.start()) + 1
        # window: the preceding ~6 lines; a rank-conditional guard there
        # with an unclosed brace (or same/previous line, unbraced) is
        # the smell.  Suppression comment on the call line passes it.
        if _OK_RE.search(lines[lineno - 1]):
            continue
        window_start = max(0, lineno - 7)
        window = "\n".join(lines[window_start:lineno])
        for g in _RANK_COND_RE.finditer(window):
            tail = window[g.end():]
            # guard still open if no '}' closed it before the call
            if tail.count("}") < tail.count("{") or \
                    ("{" not in tail and "}" not in tail
                     and tail.strip().count(";") == 0):
                findings.append(
                    f"{path.name}:{lineno}: {m.group(1)} under a "
                    "rank-conditional branch — static deadlock smell "
                    "(peers block in a collective this rank may skip); "
                    "annotate `/* parity: ok -- <reason> */` if every "
                    "rank provably takes the same branch")
                break
    return findings


#: Blocking C calls that must never run while a pthread mutex is held
#: (threadlint TL003's C-side twin): every comm_* collective, the raw
#: MPI blocking surface, and pthread barriers.  A rank stalled inside
#: one of these while holding the stats mutex blocks every other thread
#: touching the stats for as long as the slowest PEER takes to arrive.
_C_BLOCKING = _COLLECTIVES + (
    "MPI_Barrier", "MPI_Bcast", "MPI_Scatter", "MPI_Scatterv",
    "MPI_Gather", "MPI_Gatherv", "MPI_Allgather", "MPI_Allgatherv",
    "MPI_Allreduce", "MPI_Reduce", "MPI_Alltoall", "MPI_Alltoallv",
    "MPI_Exscan", "MPI_Scan", "MPI_Send", "MPI_Recv", "MPI_Sendrecv",
    "MPI_Wait", "MPI_Waitall", "pthread_barrier_wait",
)

_MUTEX_LOCK_RE = re.compile(r"\bpthread_mutex_lock\s*\(\s*&?\s*([\w.\->\[\]]+)")
_MUTEX_UNLOCK_RE = re.compile(
    r"\bpthread_mutex_unlock\s*\(\s*&?\s*([\w.\->\[\]]+)")


def check_mutex_blocking_collectives(src: str,
                                     name: str) -> list[str]:
    """threadlint TL003's C-side twin (regex-level): flag any blocking
    collective called while a ``pthread_mutex_lock`` region is open.

    Linear scan tracking the set of currently-locked mutex names
    (``pthread_mutex_lock(&m)`` opens, ``pthread_mutex_unlock(&m)``
    closes); a :data:`_C_BLOCKING` call on a line with a nonempty set
    is a finding.  ``/* parity: ok -- <reason> */`` on the call line or
    the line above passes it."""
    stripped = _strip_comments(src)
    raw_lines = src.splitlines()
    held: set[str] = set()
    findings: list[str] = []
    blocking_re = re.compile(r"\b(" + "|".join(_C_BLOCKING) + r")\s*\(")
    for i, line in enumerate(stripped.splitlines(), 1):
        events = [(m.start(), "lock", m.group(1))
                  for m in _MUTEX_LOCK_RE.finditer(line)]
        events += [(m.start(), "unlock", m.group(1))
                   for m in _MUTEX_UNLOCK_RE.finditer(line)]
        events += [(m.start(), "block", m.group(1))
                   for m in blocking_re.finditer(line)]
        for _pos, kind, what in sorted(events):
            if kind == "lock":
                held.add(what)
            elif kind == "unlock":
                held.discard(what)
            elif held:
                window = raw_lines[max(0, i - 2):i]
                if any(_OK_RE.search(w) for w in window):
                    continue
                findings.append(
                    f"{name}:{i}: {what} while holding mutex(es) "
                    f"{', '.join(sorted(held))} — a peer-paced "
                    "blocking call under a lock stalls every thread "
                    "contending on it; annotate `/* parity: ok -- "
                    "<reason> */` if the hold is provably bounded")
    return findings


def collective_sequence(path: Path) -> list[str]:
    src = _strip_comments(path.read_text())
    return [m.group(1) for m in
            re.finditer(r"\b(" + "|".join(_COLLECTIVES) + r")\s*\(", src)]


def main() -> int:
    errors: list[str] = []

    comm_h = (REPO / "comm" / "comm.h").read_text()
    declared = sorted(set(_DECL_RE.findall(comm_h)))
    if not declared:
        errors.append("comm/comm.h: no comm_* declarations parsed")

    backends = {
        "comm/comm_local.c": _defined_symbols(
            (REPO / "comm" / "comm_local.c").read_text()),
        "comm/comm_mpi.c": _defined_symbols(
            (REPO / "comm" / "comm_mpi.c").read_text()),
    }
    for backend, defined in backends.items():
        for sym in declared:
            if sym not in defined:
                errors.append(f"{backend}: declared symbol {sym} has no "
                              "definition in this backend")

    # MPI surface: calls made by comm_mpi.c must exist in the stub header
    # and in both mock runtimes.
    mpi_src = _strip_comments((REPO / "comm" / "comm_mpi.c").read_text())
    called = sorted({m.group(1) for m in
                     re.finditer(r"\b(MPI_[A-Z]\w+)\s*\(", mpi_src)})
    stub_h = (REPO / "comm" / "mpi_stub" / "mpi.h").read_text()
    mock = _defined_symbols((REPO / "comm" / "mpi_stub" / "mpi_mock.c")
                            .read_text())
    mini = _defined_symbols((REPO / "comm" / "mpi_stub" / "minimpi.c")
                            .read_text())
    for fn in called:
        if not re.search(r"\b" + fn + r"\s*\(", stub_h):
            errors.append(f"comm/mpi_stub/mpi.h: {fn} (called by "
                          "comm_mpi.c) is not declared in the stub")
        for name, impl in (("mpi_mock.c", mock), ("minimpi.c", mini)):
            if fn not in impl:
                errors.append(f"comm/mpi_stub/{name}: {fn} (called by "
                              "comm_mpi.c) is not implemented")

    # Ingest-engine surface (ISSUE 6): encode.h declarations must all be
    # defined in encode.c (the ctypes shim binds every one at load), and
    # encode.c must not define enc_* API surface the header hides.
    enc_h = (REPO / "native" / "encode.h").read_text()
    enc_declared = sorted(set(_ENC_DECL_RE.findall(enc_h)))
    if not enc_declared:
        errors.append("native/encode.h: no enc_* declarations parsed")
    enc_defined = _defined_symbols(
        _strip_comments((REPO / "native" / "encode.c").read_text()),
        pattern=r"enc_\w+")
    for sym in enc_declared:
        if sym not in enc_defined:
            errors.append(f"native/encode.c: declared symbol {sym} has "
                          "no definition")
    for sym in sorted(enc_defined - set(enc_declared)):
        errors.append(f"native/encode.c: defines {sym} which encode.h "
                      "does not declare (shim-invisible API surface)")

    # Spill-compression surface (ISSUE 20): spillz.h vs spillz.c, same
    # both-directions check as the encode unit.
    spz_h = (REPO / "native" / "spillz.h").read_text()
    spz_declared = sorted(set(_SPZ_DECL_RE.findall(spz_h)))
    if not spz_declared:
        errors.append("native/spillz.h: no spz_* declarations parsed")
    spz_defined = _defined_symbols(
        _strip_comments((REPO / "native" / "spillz.c").read_text()),
        pattern=r"spz_\w+")
    for sym in spz_declared:
        if sym not in spz_defined:
            errors.append(f"native/spillz.c: declared symbol {sym} has "
                          "no definition")
    for sym in sorted(spz_defined - set(spz_declared)):
        errors.append(f"native/spillz.c: defines {sym} which spillz.h "
                      "does not declare (shim-invisible API surface)")

    # Blocking-under-mutex (threadlint TL003's C-side twin) over both
    # backends — the stats mutex must never pend on a peer.
    for backend in ("comm/comm_local.c", "comm/comm_mpi.c"):
        errors.extend(check_mutex_blocking_collectives(
            (REPO / backend).read_text(), backend))

    # Sorter call-sequences + the deadlock smell.
    for sorter in ("native/sample_sort.c", "native/radix_sort.c"):
        p = REPO / sorter
        seq = collective_sequence(p)
        # every comm_* symbol a sorter calls must exist in the declared
        # API — a private backend helper leaking into a sorter would
        # link against one backend and not the other
        calls = {m.group(1) for m in re.finditer(
            r"\b(comm_\w+)\s*\(", _strip_comments(p.read_text()))}
        undeclared = sorted(calls - set(declared))
        if undeclared:
            errors.append(f"{sorter}: calls comm_* symbols not declared "
                          f"in comm/comm.h: {undeclared}")
        errors.extend(check_rank_conditional_collectives(p))
        print(f"{sorter}: {len(seq)} collective calls "
              f"({' -> '.join(dict.fromkeys(seq))})")

    for e in errors:
        print(f"[PARITY] {e}", file=sys.stderr)
    print(f"comm parity: {len(errors)} mismatch(es); "
          f"{len(declared)} comm.h symbols x {len(backends)} backends, "
          f"{len(called)} MPI calls x 2 runtimes, "
          f"{len(enc_declared)} encode.h + {len(spz_declared)} "
          "spillz.h symbols checked")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
