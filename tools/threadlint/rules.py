"""threadlint's rules: reachability from registered roots + vocabulary.

The traversal is a worklist over ``(function, frozenset(held locks))``
states seeded at every registered thread root.  Held sets propagate
through call sites (a callee inherits the caller's held locks plus any
``with`` scope the call sits inside), so "blocking under lock" and
"lock nested inside lock" are judged on the EFFECTIVE held set, not the
lexical one.  Lock-order edges and local blocking checks additionally
run over every function regardless of reachability — a bad nesting in
main-thread-only code still poisons the global order for everyone else.
"""

from __future__ import annotations

from tools.threadlint import Finding, Registry
from tools.threadlint.engine import Program


def run_rules(program: Program, registry: Registry, check_vocab: bool,
              suppressions: dict | None = None) -> list:
    findings: list[Finding] = []
    findings += _vocab_rules(program, registry, check_vocab)
    edges, jax_hits, blocking_hits, writes = _traverse(
        program, registry, suppressions or {})
    findings += _tl001(jax_hits)
    findings += _tl002(edges, registry)
    findings += _tl003(blocking_hits, registry)
    findings += _tl004(writes, registry)
    findings += _tl005(program, registry)
    return findings


# ------------------------------------------------------------ traversal

def _traverse(program: Program, registry: Registry,
              suppressions: dict):
    edges: dict[tuple, tuple] = {}          # (held, acquired) -> site
    jax_hits: dict[tuple, set] = {}         # (path,line,label) -> roots
    blocking_hits: dict[tuple, set] = {}    # (path,line,label) -> locks
    writes: dict[str, dict] = {}            # attr -> root -> [(p,l,held)]

    def severed(path: str, line: int) -> bool:
        """A reasoned `# threadlint: disable=TL003` at a call site is a
        reviewed blocking-under-these-locks decision, so it also stops
        held-set propagation THROUGH that call — otherwise the same
        reviewed hazard re-fires at every interior blocking touch."""
        sup = suppressions.get(path, {})
        for ln in (line, line - 1):
            entry = sup.get(ln)
            if entry and "TL003" in entry[0] and entry[1]:
                return True
        return False

    # local legs (reachability-independent)
    for fi in program.functions.values():
        for t in fi.blocking:
            if t.held:
                blocking_hits.setdefault(
                    (fi.path, t.line, t.label), set()).update(t.held)
        for a in fi.acquires:
            for l1 in a.held:
                edges.setdefault((l1, a.site), (fi.path, a.line))

    # interprocedural legs
    for root in registry.roots.values():
        if root.entry not in program.functions:
            continue
        seen: set = set()
        stack: list = [(root.entry, frozenset())]
        while stack:
            qual, held = stack.pop()
            if (qual, held) in seen:
                continue
            seen.add((qual, held))
            fi = program.functions.get(qual)
            if fi is None:
                continue
            if not root.jax_ok:
                for t in fi.jax:
                    jax_hits.setdefault(
                        (fi.path, t.line, t.label), set()).add(root.name)
            for t in fi.blocking:
                eff = held | t.held
                if eff:
                    blocking_hits.setdefault(
                        (fi.path, t.line, t.label), set()).update(eff)
            for a in fi.acquires:
                for l1 in held | a.held:
                    edges.setdefault((l1, a.site), (fi.path, a.line))
            if not fi.is_init:
                for w in fi.writes:
                    writes.setdefault(w.site, {}).setdefault(
                        root.name, []).append(
                            (fi.path, w.line, held | w.held))
            for c in fi.calls:
                eff = frozenset() if severed(fi.path, c.line) \
                    else held | c.held
                for tgt in c.targets:
                    stack.append((tgt, eff))
            for tgt in registry.extra_edges.get(qual, ()):
                stack.append((tgt, held))
    return edges, jax_hits, blocking_hits, writes


# ---------------------------------------------------------------- rules

def _tl001(jax_hits: dict) -> list:
    out = []
    for (path, line, label), roots in sorted(jax_hits.items()):
        out.append(Finding(
            "TL001", path, line,
            f"JAX surface `{label}` reachable from thread root(s) "
            f"{', '.join(sorted(roots))} not marked jax_ok"))
    return out


def _lock_name(site: str, registry: Registry) -> str:
    lock = registry.locks.get(site)
    return lock.name if lock else site


def _tl002(edges: dict, registry: Registry) -> list:
    out = []
    graph: dict[str, set] = {}
    for (l1, l2), (path, line) in sorted(edges.items()):
        n1, n2 = _lock_name(l1, registry), _lock_name(l2, registry)
        if l1 == l2:
            lock = registry.locks.get(l1)
            if lock is None or not lock.reentrant:
                out.append(Finding(
                    "TL002", path, line,
                    f"lock `{n1}` re-acquired while already held "
                    "(not registered reentrant)"))
            continue
        graph.setdefault(l1, set()).add(l2)
        r1 = registry.locks.get(l1)
        r2 = registry.locks.get(l2)
        if r1 and r2 and r2.rank <= r1.rank:
            out.append(Finding(
                "TL002", path, line,
                f"lock rank inversion: `{n2}` (rank {r2.rank}) acquired "
                f"while holding `{n1}` (rank {r1.rank}); ranks must "
                "strictly increase"))
    # cycle detection (DFS, report each back edge once)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(graph) | {m for vs in graph.values() for m in vs}}
    cycles: list[tuple] = []

    def visit(node, trail):
        color[node] = GRAY
        for nxt in sorted(graph.get(node, ())):
            if color[nxt] == GRAY:
                i = trail.index(nxt)
                cycles.append(tuple(trail[i:]) + (nxt,))
            elif color[nxt] == WHITE:
                visit(nxt, trail + [nxt])
        color[node] = BLACK

    for n in sorted(color):
        if color[n] == WHITE:
            visit(n, [n])
    for cyc in cycles:
        first = (cyc[0], cyc[1])
        path, line = edges[first]
        names = " -> ".join(_lock_name(s, registry) for s in cyc)
        out.append(Finding(
            "TL002", path, line, f"lock-order cycle: {names}"))
    return out


def _tl003(blocking_hits: dict, registry: Registry) -> list:
    out = []
    for (path, line, label), locks in sorted(blocking_hits.items()):
        names = ", ".join(sorted(
            _lock_name(s, registry) for s in locks))
        out.append(Finding(
            "TL003", path, line,
            f"blocking call ({label}) while holding lock(s) {names}"))
    return out


def _tl004(writes: dict, registry: Registry) -> list:
    out = []
    for site, per_root in sorted(writes.items()):
        if site in registry.atomic_ok or len(per_root) < 2:
            continue
        all_writes = [w for lst in per_root.values() for w in lst]
        common = set(all_writes[0][2])
        for _, _, held in all_writes[1:]:
            common &= held
        if common:
            continue
        path, line, _ = min(all_writes,
                            key=lambda w: (len(w[2]), w[0], w[1]))
        out.append(Finding(
            "TL004", path, line,
            f"attribute `{site}` written from thread roots "
            f"{', '.join(sorted(per_root))} with no common lock on "
            "every write path"))
    return out


def _tl005(program: Program, registry: Registry) -> list:
    out = []
    for fi in program.functions.values():
        if fi.path in registry.gil_wedge_home:
            continue
        for t in fi.wedge:
            out.append(Finding(
                "TL005", fi.path, t.line,
                f"GIL-wedge call `{t.label}` outside the bounded-"
                "subprocess probe (can block forever holding the GIL; "
                "route through topology_probe)"))
    return sorted(out, key=lambda f: (f.path, f.line))


# ----------------------------------------------------------- vocabulary

def _vocab_rules(program: Program, registry: Registry,
                 check_vocab: bool) -> list:
    out = []
    # TL011: every lock creation site is registered (through aliases)
    for lc in program.lock_creations:
        site = program.canon_lock(lc.site) if lc.site else None
        if site is None or site not in registry.lock_sites:
            out.append(Finding(
                "TL011", lc.path, lc.line,
                f"unregistered {lc.kind} creation"
                + (f" at site `{lc.site}`" if lc.site else "")
                + "; add a LockDecl (name + rank) to thread_registry"))
    # TL010: thread/pool/submit/signal/handler vocabulary
    entries = set(registry.roots)
    for ts in program.thread_sites:
        if ts.entry is None:
            out.append(Finding(
                "TL010", ts.path, ts.line,
                f"cannot resolve Thread target `{ts.desc}`; threadlint "
                "needs a resolvable registered root"))
        elif ts.entry not in entries:
            out.append(Finding(
                "TL010", ts.path, ts.line,
                f"Thread target `{ts.entry}` is not a registered "
                "thread root"))
    for ps in program.pool_sites:
        if ps.prefix is None:
            out.append(Finding(
                "TL010", ps.path, ps.line,
                "ThreadPoolExecutor without thread_name_prefix= "
                "(pool threads must be attributable in stacks)"))
    for ss in program.submit_sites:
        if ss.entry is None:
            out.append(Finding(
                "TL010", ss.path, ss.line,
                f"cannot resolve pool submit target `{ss.desc}`"))
        elif ss.entry not in entries:
            out.append(Finding(
                "TL010", ss.path, ss.line,
                f"pool submit target `{ss.entry}` is not a registered "
                "thread root"))
    for sg in program.signal_sites:
        if sg.entry is None or sg.entry not in entries:
            out.append(Finding(
                "TL010", sg.path, sg.line,
                f"signal handler `{sg.entry or sg.desc}` is not a "
                "registered thread root"))
    for he in program.handler_entries:
        if he.entry not in entries:
            out.append(Finding(
                "TL010", he.path, he.line,
                f"handler entry `{he.entry}` is not a registered "
                "thread root"))
    # vocabulary drift (full-repo runs only): registered things that no
    # longer exist in the program
    if check_vocab:
        used_entries = set(program.functions)
        used_entries.update(t.entry for t in program.thread_sites
                            if t.entry)
        used_entries.update(s.entry for s in program.submit_sites
                            if s.entry)
        used_entries.update(s.entry for s in program.signal_sites
                            if s.entry)
        used_entries.update(h.entry for h in program.handler_entries)
        for entry, root in sorted(registry.roots.items()):
            if entry not in used_entries:
                out.append(Finding(
                    "TL010", "<thread_registry>", 0,
                    f"registered root `{root.name}` entry `{entry}` "
                    "not found in the program (stale registration?)"))
        created = {program.canon_lock(lc.site)
                   for lc in program.lock_creations if lc.site}
        for site, lock in sorted(registry.locks.items()):
            if site not in created:
                out.append(Finding(
                    "TL011", "<thread_registry>", 0,
                    f"registered lock `{lock.name}` site `{site}` has "
                    "no creation site in the program (stale "
                    "registration?)"))
    return out
