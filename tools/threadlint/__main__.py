"""CLI: ``python -m tools.threadlint [targets...]``.

Exit 0 when clean, 1 when findings survive suppression.  ``--selftest``
runs every rule against its planted bad fixture (``make
threadlint-fixtures``): a rule that stops firing is a broken rule, and
the cheapest place to learn that is the lint job itself.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.threadlint import (LINT_VERSION, RULES, Registry, lint_files,
                              lint_repo)

# --------------------------------------------------------- selftest

#: One deliberately-bad fixture per rule; the selftest asserts the rule
#: FIRES (fixture drift fails fast).  Each fixture is a tiny standalone
#: module linted against a matching synthetic registry.
_BAD_FIXTURES: dict[str, str] = {
    "TL000": (
        "import threading\n"
        "L = threading.Lock()  # threadlint: disable=TL011\n"
    ),
    "TL001": (
        "import threading\n"
        "import jax\n"
        "def work():\n"
        "    jax.device_put([1, 2])\n"
        "def start():\n"
        "    threading.Thread(target=work).start()\n"
    ),
    "TL002": (
        "import threading\n"
        "A = threading.Lock()\n"
        "B = threading.Lock()\n"
        "def one():\n"
        "    with A:\n"
        "        with B:\n"
        "            pass\n"
        "def other():\n"
        "    with B:\n"
        "        with A:\n"
        "            pass\n"
    ),
    "TL003": (
        "import threading\n"
        "import os\n"
        "L = threading.Lock()\n"
        "def flush(fd):\n"
        "    with L:\n"
        "        os.fsync(fd)\n"
    ),
    "TL004": (
        "import threading\n"
        "class Cell:\n"
        "    def writer_a(self):\n"
        "        self.value = 1\n"
        "    def writer_b(self):\n"
        "        self.value = 2\n"
        "def start(c):\n"
        "    threading.Thread(target=c.writer_a).start()\n"
        "    threading.Thread(target=c.writer_b).start()\n"
    ),
    "TL005": (
        "def peek(client):\n"
        "    return client.get_topology_desc()\n"
    ),
    "TL010": (
        "import threading\n"
        "def anon():\n"
        "    pass\n"
        "def start():\n"
        "    threading.Thread(target=anon).start()\n"
    ),
    "TL011": (
        "import threading\n"
        "STRAY = threading.Lock()\n"
    ),
}


def _fixture_registry(rule: str) -> Registry:
    """The minimal vocabulary each bad fixture lints against."""
    from tools.threadlint import Lock, Root
    mod = "fixture_" + rule.lower()
    if rule == "TL001":
        return Registry(roots=[Root("bad-root", "thread",
                                    f"{mod}.work", False)])
    if rule in ("TL002", "TL003"):
        locks = [Lock("a", 10, f"{mod}.A"), Lock("b", 20, f"{mod}.B"),
                 Lock("l", 10, f"{mod}.L")]
        roots = [Root("r-one", "thread", f"{mod}.one", False),
                 Root("r-other", "thread", f"{mod}.other", False),
                 Root("r-flush", "thread", f"{mod}.flush", False)]
        return Registry(roots=roots, locks=locks,
                        blocking_calls={"os.fsync": "fsync"})
    if rule == "TL004":
        return Registry(roots=[
            Root("wa", "thread", f"{mod}.Cell.writer_a", False),
            Root("wb", "thread", f"{mod}.Cell.writer_b", False)])
    if rule == "TL005":
        return Registry(gil_wedge_calls=("get_topology_desc",))
    if rule == "TL000":
        return Registry(locks=[Lock("l", 10, f"{mod}.L")])
    return Registry()   # TL010 / TL011: empty vocabulary


def selftest() -> int:
    failed = []
    for rule, src in sorted(_BAD_FIXTURES.items()):
        path = f"fixture_{rule.lower()}.py"
        findings = lint_files({path: src}, _fixture_registry(rule))
        fired = sorted({f.rule for f in findings})
        if rule not in fired:
            failed.append((rule, findings))
        print(f"threadlint selftest {rule}: "
              f"{'fires' if rule in fired else 'SILENT'} "
              f"({len(findings)} finding(s): {', '.join(fired) or '-'})")
    if failed:
        for rule, findings in failed:
            print(f"FAIL: {rule} did not fire on its bad fixture",
                  file=sys.stderr)
            for f in findings:
                print("  " + f.render(), file=sys.stderr)
        return 1
    print(f"threadlint selftest: all {len(_BAD_FIXTURES)} rules fire "
          f"({LINT_VERSION})")
    return 0


# -------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.threadlint",
        description="interprocedural concurrency lint (stdlib-only)")
    ap.add_argument("targets", nargs="*", default=None,
                    help="files/dirs relative to --root "
                         "(default: the registered lint targets)")
    ap.add_argument("--root", default=".", help="repo root")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--selftest", action="store_true",
                    help="assert every rule fires on its bad fixture")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in sorted(RULES.items()):
            print(f"{rid}  {doc}")
        return 0
    if args.selftest:
        return selftest()

    root = Path(args.root)
    if args.targets:
        findings = lint_repo(root, targets=args.targets)
    else:
        findings = lint_repo(root)
    for f in findings:
        print(f.render())
    print(f"threadlint: {len(findings)} finding(s), "
          f"{len(RULES)} rules ({LINT_VERSION})", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
