"""threadlint — interprocedural concurrency lint for the serving stack.

sortlint checks per-file invariants; threadlint checks the ones that
live BETWEEN files: which thread runs what.  It builds a call graph of
``mpitest_tpu/``, ``drivers/`` and ``bench/`` (pure ``ast``, zero deps,
never imports the package under lint), walks it from every thread root
registered in ``mpitest_tpu/utils/thread_registry.py``, and enforces:

========  ===============================================================
TL001     **JAX fence** — the JAX/XLA surface (``jax.*``/``jnp.*``,
          ``device_put``/``checked_device_put``, ``block_until_ready``,
          executor-cache ``get_packed``, ``compile_packed_sort``) is
          reachable only from roots registered ``jax_ok`` (the dispatch
          thread, the tuner prewarm, the ingest transfer/egress fetch
          stages, process main).
TL002     **lock order** — ``with <lock>`` nesting across the call
          graph must follow the registry's global rank order (strictly
          increasing); any cycle, rank inversion, or non-reentrant
          re-acquisition is a finding.
TL003     **blocking under lock** — fsync / socket send-recv /
          subprocess / sleep / XLA compile reachable while a registered
          lock is held.  The PR 15 ``_build_detached``
          compile-outside-the-lock fix is a checked invariant.
TL004     **unfenced shared write** — an attribute written from >= 2
          thread roots with no common lock on every write path
          (classic Eraser lockset discipline).
TL005     **GIL wedge** — registered can-block-forever-holding-the-GIL
          calls (``get_topology_desc``) are legal only inside the
          bounded-subprocess probe module.
TL010     unregistered thread root: every ``threading.Thread``, pool
          submit target, socketserver/http handler entry and signal
          handler must name a root in the registry; pools must carry
          ``thread_name_prefix``.
TL011     unregistered lock: every Lock/RLock/Condition creation site
          must carry a registered :class:`LockDecl` (name + rank).
========  ===============================================================

Suppressions mirror sortlint's reasoned grammar::

    risky()  # threadlint: disable=TL003 -- compile dogpile tradeoff

A directive without a reason is itself a finding (TL000) and does not
suppress.  ``make lint`` runs threadlint beside sortlint in CI.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Optional

from tools.registry_load import load_registry_module
from tools.sortlint import iter_target_files

LINT_VERSION = "threadlint.v1"

#: Default lint targets relative to the repo root.  tests/ is excluded
#: (fixtures there violate the rules on purpose); tools/ is excluded
#: because the analyzer does not lint itself.
DEFAULT_TARGETS = ("mpitest_tpu", "drivers", "bench.py", "bench")

#: Static rule table (--list-rules, README).
RULES: dict[str, str] = {
    "TL000": "suppression directive without a reason (and not honored)",
    "TL001": "JAX surface reached from a thread root not marked jax_ok",
    "TL002": "lock-order violation: cycle, rank inversion, or "
             "non-reentrant re-acquisition",
    "TL003": "blocking call (fsync/socket/subprocess/sleep/XLA compile) "
             "reachable while a registered lock is held",
    "TL004": "attribute written from >=2 thread roots with no common "
             "lock on every write path",
    "TL005": "GIL-wedge call outside the bounded-subprocess probe",
    "TL010": "unregistered thread root (Thread/pool submit/handler/"
             "signal) or pool without thread_name_prefix",
    "TL011": "unregistered lock creation site",
    "TL999": "target file failed to parse",
}

_SUPPRESS_RE = re.compile(
    r"#\s*threadlint:\s*disable=(?P<ids>[A-Z0-9, ]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    msg: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.msg}"


# ------------------------------------------------------------ registry

@dataclass(frozen=True)
class Root:
    name: str
    kind: str
    entry: str
    jax_ok: bool


@dataclass(frozen=True)
class Lock:
    name: str
    rank: int
    site: str
    reentrant: bool = False


class Registry:
    """Normalized vocabulary the engine and rules consume — built from
    the real ``thread_registry`` module or synthesized by tests."""

    def __init__(self, *, roots: Iterable = (), locks: Iterable = (),
                 lock_aliases: Optional[dict] = None,
                 receiver_types: Optional[dict] = None,
                 attr_calls: Optional[dict] = None,
                 return_types: Optional[dict] = None,
                 extra_edges: Optional[dict] = None,
                 jax_surface_heads: Iterable[str] = ("jax", "jnp"),
                 jax_surface_calls: Iterable[str] = (),
                 blocking_calls: Optional[dict] = None,
                 compile_funcs: Iterable[str] = (),
                 gil_wedge_calls: Iterable[str] = (),
                 gil_wedge_home: Iterable[str] = (),
                 atomic_ok: Iterable[str] = ()) -> None:
        self.roots: dict[str, Root] = {}
        for r in roots:
            root = r if isinstance(r, Root) else Root(
                r.name, r.kind, r.entry, r.jax_ok)
            if root.entry in self.roots:
                raise ValueError(f"duplicate root entry {root.entry}")
            self.roots[root.entry] = root
        self.locks: dict[str, Lock] = {}
        for l in locks:
            lock = l if isinstance(l, Lock) else Lock(
                l.name, l.rank, l.site, getattr(l, "reentrant", False))
            if lock.site in self.locks:
                raise ValueError(f"duplicate lock site {lock.site}")
            self.locks[lock.site] = lock
        self.lock_sites = set(self.locks)
        self.lock_aliases = dict(lock_aliases or {})
        self.receiver_types = dict(receiver_types or {})
        self.attr_calls = dict(attr_calls or {})
        self.return_types = dict(return_types or {})
        self.extra_edges = dict(extra_edges or {})
        self.jax_surface_heads = tuple(jax_surface_heads)
        self.jax_surface_calls = tuple(jax_surface_calls)
        self.blocking_calls = dict(blocking_calls or {})
        self.compile_funcs = tuple(compile_funcs)
        self.gil_wedge_calls = tuple(gil_wedge_calls)
        self.gil_wedge_home = tuple(gil_wedge_home)
        self.atomic_ok = tuple(atomic_ok)

    @classmethod
    def from_module(cls, mod) -> "Registry":
        return cls(
            roots=mod.THREAD_ROOTS, locks=mod.LOCKS,
            lock_aliases=mod.LOCK_ALIASES,
            receiver_types=mod.RECEIVER_TYPES,
            attr_calls=mod.ATTR_CALLS, return_types=mod.RETURN_TYPES,
            extra_edges=mod.EXTRA_EDGES,
            jax_surface_heads=mod.JAX_SURFACE_HEADS,
            jax_surface_calls=mod.JAX_SURFACE_CALLS,
            blocking_calls=mod.BLOCKING_CALLS,
            compile_funcs=mod.COMPILE_FUNCS,
            gil_wedge_calls=mod.GIL_WEDGE_CALLS,
            gil_wedge_home=mod.GIL_WEDGE_HOME,
            atomic_ok=mod.ATOMIC_OK)


def load_default_registry(root: str | Path = ".") -> Registry:
    mod = load_registry_module(
        "_threadlint_thread_registry",
        Path(root) / "mpitest_tpu" / "utils" / "thread_registry.py",
        register=True)
    return Registry.from_module(mod)


# -------------------------------------------------------- suppressions

def _suppressions(src: str) -> dict[int, tuple[set, Optional[str]]]:
    out: dict[int, tuple[set, Optional[str]]] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            ids = {t.strip() for t in m.group("ids").split(",")
                   if t.strip()}
            out[i] = (ids, m.group("reason"))
    return out


def apply_suppressions(src: str, findings: list, path: str) -> list:
    """Drop findings suppressed on their own line (or the line above);
    a directive without a reason becomes TL000 and mutes nothing."""
    sup = _suppressions(src)
    out = []
    for i, (ids, reason) in sup.items():
        if reason is None:
            out.append(Finding(
                "TL000", path, i,
                f"suppression of {','.join(sorted(ids))} has no reason; "
                "write `# threadlint: disable=<ID> -- <why>`"))
    for f in findings:
        killed = False
        for ln in (f.line, f.line - 1):
            entry = sup.get(ln)
            if entry and f.rule in entry[0] and entry[1]:
                killed = True
                break
        if not killed:
            out.append(f)
    return out


# ------------------------------------------------------- entry points

def lint_files(files: dict[str, str], registry: Registry,
               check_vocab: bool = False) -> list:
    """Analyze a {relative path: source} mapping against a registry.
    ``check_vocab=True`` additionally pins the registry against the
    program (roots/locks that no longer exist are findings) — on for
    full-repo runs, off for partial fixture runs."""
    from tools.threadlint.engine import Program
    from tools.threadlint.rules import run_rules

    program = Program(registry)
    findings: list[Finding] = []
    for path in sorted(files):
        try:
            program.add_module(path, files[path])
        except SyntaxError as e:
            findings.append(Finding(
                "TL999", path, e.lineno or 0, f"syntax error: {e.msg}"))
    program.analyze()
    sup = {path: _suppressions(src) for path, src in files.items()}
    findings.extend(run_rules(program, registry, check_vocab,
                              suppressions=sup))
    by_path: dict[str, list] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: list[Finding] = []
    for path, fs in sorted(by_path.items()):
        src = files.get(path)
        out.extend(apply_suppressions(src, fs, path) if src is not None
                   else fs)
    # suppression directives in clean files still need the TL000 scan
    for path in sorted(set(files) - set(by_path)):
        out.extend(apply_suppressions(files[path], [], path))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_source(src: str, path: str, registry: Registry) -> list:
    """Single-snippet convenience for the test harness."""
    return lint_files({path: src}, registry)


def lint_repo(root: str | Path = ".",
              targets: Iterable[str] = DEFAULT_TARGETS) -> list:
    root = Path(root)
    registry = load_default_registry(root)
    files = {str(f.relative_to(root)): f.read_text()
             for f in iter_target_files(root, targets)}
    return lint_files(files, registry, check_vocab=True)
