"""threadlint's program model: qualnames, call resolution, lock scopes.

Pure ``ast`` over the lint targets — the analyzer never imports the
package under lint (sortlint's contract).  Two passes:

* **collect** — every module yields its import map, function defs
  (module-qualified, nested defs joined with dots:
  ``mpitest_tpu.models.ingest.stream_to_mesh.parse_chunks``), class
  method tables, lock creation sites and handler classes;
* **analyze** — every function body is walked once, outer functions
  before their nested defs (closures consult enclosing local scopes),
  tracking the ``with``-lock stack per statement and recording calls,
  lock acquisitions, attribute writes and JAX/blocking/GIL-wedge
  surface touches, each stamped with the locks held at that point.

Method calls resolve by receiver type: ``self`` binds to the enclosing
class, local variables type from ``x = ClassName(...)`` / registered
factory returns, and object attributes type from same-class
``self.a = ClassName(...)`` assignments plus the registry's explicit
``RECEIVER_TYPES`` alias table.  Constructor-injected callbacks ride
``ATTR_CALLS``; dynamic observer fan-out rides ``EXTRA_EDGES``.
Anything unresolvable stays unresolved — the analysis is conservative
by construction, and the vocabulary rules (TL010/TL011) keep the parts
that matter explicit.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

#: Tails of base-class names that make a ClassDef a request handler —
#: its ``handle``/``do_*`` methods run on server-spawned threads.
HANDLER_BASE_TAILS = (
    "BaseRequestHandler", "StreamRequestHandler",
    "DatagramRequestHandler", "BaseHTTPRequestHandler",
    "SimpleHTTPRequestHandler",
)

#: Pseudo-type assigned to ThreadPoolExecutor instances so ``.submit``
#: sites are recognizable.
POOL_TYPE = "@pool"


@dataclass
class CallSite:
    targets: tuple          # resolved callee qualnames (possibly empty)
    chain: str              # syntactic dotted chain ("" when exotic)
    tail: str               # last segment of the callee expression
    line: int
    held: frozenset         # lock sites held locally at the call


@dataclass
class LockUse:
    site: str               # canonical lock site
    line: int
    held: frozenset         # locks already held (outer withs) locally


@dataclass
class AttrWrite:
    site: str               # "module.Class.attr" or "module.NAME"
    line: int
    held: frozenset


@dataclass
class Touch:
    label: str
    line: int
    held: frozenset


@dataclass
class ThreadSite:
    entry: Optional[str]    # resolved target qualname (None: opaque)
    line: int
    path: str
    desc: str               # human description of the target expr


@dataclass
class PoolSite:
    line: int
    path: str
    prefix: Optional[str]   # thread_name_prefix literal (None: absent)


@dataclass
class SubmitSite:
    entry: Optional[str]
    line: int
    path: str
    desc: str


@dataclass
class SignalSite:
    entry: Optional[str]
    line: int
    path: str
    desc: str


@dataclass
class HandlerEntry:
    entry: str              # qualname of the handle/do_* method
    line: int
    path: str


@dataclass
class LockCreation:
    site: Optional[str]     # None when the lock has no nameable site
    line: int
    path: str
    kind: str               # Lock | RLock | Condition


@dataclass
class FunctionInfo:
    qual: str
    path: str
    line: int
    cls: Optional[str]          # enclosing class qualname
    parent: Optional[str]       # enclosing function qualname
    is_init: bool
    node: ast.AST = field(repr=False, default=None)
    calls: list = field(default_factory=list)
    acquires: list = field(default_factory=list)
    writes: list = field(default_factory=list)
    jax: list = field(default_factory=list)
    blocking: list = field(default_factory=list)
    wedge: list = field(default_factory=list)
    # local name environments, consulted by nested defs
    var_types: dict = field(default_factory=dict)
    var_locks: dict = field(default_factory=dict)
    # locals bound to a constructor call IN THIS function: attribute
    # writes through them hit a fresh, thread-confined object (Eraser
    # first-thread discipline), so TL004 skips them
    fresh_locals: set = field(default_factory=set)


class Program:
    """The whole-target model the rules run over."""

    def __init__(self, registry) -> None:
        self.registry = registry
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, dict] = {}   # qual -> {method -> fnqual}
        self.class_attr_types: dict[str, str] = {}   # "M.C.attr" -> cls
        self.class_attr_locks: dict[str, str] = {}   # "M.C.attr" -> site
        self.module_locks: dict[str, str] = {}       # "M.NAME" -> site
        self.lock_aliases: dict[str, str] = dict(registry.lock_aliases)
        self.lock_creations: list[LockCreation] = []
        self.thread_sites: list[ThreadSite] = []
        self.pool_sites: list[PoolSite] = []
        self.submit_sites: list[SubmitSite] = []
        self.signal_sites: list[SignalSite] = []
        self.handler_entries: list[HandlerEntry] = []
        self.imports: dict[str, dict[str, str]] = {}  # module -> name map
        self._order: list[str] = []                   # analysis order

    # -- construction -------------------------------------------------
    def add_module(self, path: str, src: str) -> None:
        module = _module_name(path)
        tree = ast.parse(src, filename=path)
        self.imports.setdefault(module, {})
        _Collector(self, path, module).visit(tree)

    def analyze(self) -> None:
        for qual in self._order:
            _analyze_function(self, self.functions[qual])

    # -- lock canonicalization ---------------------------------------
    def canon_lock(self, site: str) -> str:
        seen = set()
        while site in self.lock_aliases and site not in seen:
            seen.add(site)
            site = self.lock_aliases[site]
        return site


def _module_name(path: str) -> str:
    p = path.replace("\\", "/")
    if p.endswith("/__init__.py"):
        p = p[: -len("/__init__.py")]
    elif p.endswith(".py"):
        p = p[:-3]
    return p.replace("/", ".")


def _attr_chain(node: ast.AST) -> str:
    """Dotted chain for Name/Attribute trees; "" when any link is
    exotic (a call, a subscript...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _tail(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


# ---------------------------------------------------------------- pass A

class _Collector(ast.NodeVisitor):
    """Collects defs/classes/imports/module-level lock sites and
    queues every function for the analysis pass."""

    def __init__(self, program: Program, path: str, module: str) -> None:
        self.p = program
        self.path = path
        self.module = module
        self.scope: list[tuple[str, str]] = []  # (kind, qual)

    # scope helpers
    def _qual(self, name: str) -> str:
        return (self.scope[-1][1] + "." + name) if self.scope \
            else (self.module + "." + name)

    def _enclosing_class(self) -> Optional[str]:
        for kind, qual in reversed(self.scope):
            if kind == "class":
                return qual
        return None

    def _enclosing_func(self) -> Optional[str]:
        for kind, qual in reversed(self.scope):
            if kind == "func":
                return qual
        return None

    # imports (collected module-wide wherever they appear)
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.p.imports[self.module][a.asname or
                                        a.name.split(".")[0]] = a.name
    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and node.level == 0:
            for a in node.names:
                self.p.imports[self.module][a.asname or a.name] = \
                    node.module + "." + a.name

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        qual = self._qual(node.name)
        self.p.classes.setdefault(qual, {})
        # handler classes: every handle/do_* method is a thread entry
        is_handler = any(_tail(b) in HANDLER_BASE_TAILS
                         for b in node.bases)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.p.classes[qual][stmt.name] = qual + "." + stmt.name
                if is_handler and (stmt.name == "handle"
                                   or stmt.name.startswith("do_")):
                    self.p.handler_entries.append(HandlerEntry(
                        qual + "." + stmt.name, stmt.lineno, self.path))
            elif isinstance(stmt, ast.Assign):
                # class-body lock: `_flush_lock = threading.Lock()`
                kind = _lock_kind(stmt.value, self.p.imports[self.module])
                if kind and len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name):
                    site = qual + "." + stmt.targets[0].id
                    self.p.class_attr_locks[site] = site
                    self.p.lock_creations.append(LockCreation(
                        site, stmt.lineno, self.path, kind))
        self.scope.append(("class", qual))
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._def(node)
    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._def(node)

    def _def(self, node) -> None:
        qual = self._qual(node.name)
        fi = FunctionInfo(
            qual=qual, path=self.path, line=node.lineno,
            cls=self._enclosing_class(), parent=self._enclosing_func(),
            is_init=node.name in ("__init__", "__post_init__"),
            node=node)
        self.p.functions[qual] = fi
        self.p._order.append(qual)   # outer before nested (visit order)
        self.scope.append(("func", qual))
        self.generic_visit(node)
        self.scope.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        # module-level lock: `_LOAD_LOCK = threading.Lock()`
        if not self.scope:
            kind = _lock_kind(node.value, self.p.imports[self.module])
            if kind and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                site = self.module + "." + node.targets[0].id
                self.p.module_locks[site] = site
                self.p.lock_creations.append(LockCreation(
                    site, node.lineno, self.path, kind))
        self.generic_visit(node)


def _lock_kind(value: ast.AST,
               imports: dict[str, str]) -> Optional[str]:
    """"Lock"/"RLock"/"Condition" when ``value`` creates one."""
    if not isinstance(value, ast.Call):
        return None
    chain = _attr_chain(value.func)
    for kind in ("Lock", "RLock", "Condition"):
        if chain == "threading." + kind:
            return kind
        if chain == kind and imports.get(kind) == "threading." + kind:
            return kind
    return None


# ---------------------------------------------------------------- pass B

class _FnCtx:
    """Resolution context for one function: scope chain + envs."""

    def __init__(self, p: Program, fi: FunctionInfo) -> None:
        self.p = p
        self.fi = fi
        self.module = fi.qual.rsplit(".", 1)[0]
        # the module is the qual prefix up to the first def/class name;
        # recover it by stripping known function/class suffixes
        q = fi.qual
        while True:
            head = q.rsplit(".", 1)[0]
            if head in p.functions or head in p.classes:
                q = head
                continue
            break
        self.module = q.rsplit(".", 1)[0]
        self.imports = p.imports.get(self.module, {})
        self.globals_decl: set[str] = set()

    # -- scope-chained lookups ---------------------------------------
    def _chain(self):
        fi = self.fi
        while fi is not None:
            yield fi
            fi = self.p.functions.get(fi.parent) if fi.parent else None

    def local_type(self, name: str) -> Optional[str]:
        for fi in self._chain():
            if name in fi.var_types:
                return fi.var_types[name]
        return None

    def local_lock(self, name: str) -> Optional[str]:
        for fi in self._chain():
            if name in fi.var_locks:
                return fi.var_locks[name]
        return None

    def resolve_name(self, name: str) -> Optional[str]:
        """A bare name used as a callable/target: nested defs of any
        enclosing function, then module defs/classes, then imports."""
        for fi in self._chain():
            cand = fi.qual + "." + name
            if cand in self.p.functions:
                return cand
        for cand in (self.module + "." + name,):
            if cand in self.p.functions or cand in self.p.classes:
                return cand
        imp = self.imports.get(name)
        if imp:
            return imp
        return None

    # -- typing -------------------------------------------------------
    def type_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id == "self" and self.fi.cls:
                return self.fi.cls
            t = self.local_type(node.id)
            if t:
                return t
            imp = self.imports.get(node.id)
            if imp and imp in self.p.classes:
                return imp
            return None
        if isinstance(node, ast.Attribute):
            base_t = self.type_of(node.value)
            if base_t:
                return self.attr_type(base_t, node.attr)
            return None
        if isinstance(node, ast.Call):
            # class construction types directly, with or without an
            # explicit __init__ (stdlib subclasses often inherit it)
            f = node.func
            if isinstance(f, ast.Name):
                t = self.resolve_name(f.id)
                if t in self.p.classes:
                    return t
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name):
                imp = self.imports.get(f.value.id)
                if imp and imp + "." + f.attr in self.p.classes:
                    return imp + "." + f.attr
            for t in self.resolve_call_targets(f):
                rt = self.p.registry.return_types.get(t)
                if rt:
                    return rt
            if _tail(f) == "ThreadPoolExecutor":
                return POOL_TYPE
            return None
        return None

    def attr_type(self, cls: str, attr: str) -> Optional[str]:
        key = cls + "." + attr
        return self.p.registry.receiver_types.get(key) or \
            self.p.class_attr_types.get(key)

    def is_constructor_call(self, node: ast.AST) -> bool:
        """True when ``node`` constructs a program class directly (NOT
        a factory return — factories may hand out shared singletons)."""
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Name):
            return self.resolve_name(f.id) in self.p.classes
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            imp = self.imports.get(f.value.id)
            return bool(imp) and imp + "." + f.attr in self.p.classes
        return False

    # -- lock resolution ----------------------------------------------
    def lock_of(self, node: ast.AST) -> Optional[str]:
        site = None
        if isinstance(node, ast.Name):
            site = self.local_lock(node.id) or \
                self.p.module_locks.get(self.module + "." + node.id)
        elif isinstance(node, ast.Attribute):
            base_t = self.type_of(node.value)
            if base_t:
                key = base_t + "." + node.attr
                if key in self.p.class_attr_locks or \
                        key in self.p.lock_aliases or \
                        key in self.p.registry.lock_sites:
                    site = key
        return self.p.canon_lock(site) if site else None

    # -- call resolution ----------------------------------------------
    def resolve_call_targets(self, func: ast.AST) -> tuple:
        """Resolved qualnames a call on ``func`` may run."""
        if isinstance(func, ast.Name):
            t = self.resolve_name(func.id)
            if t is None:
                return ()
            if t in self.p.classes:
                init = self.p.classes[t].get("__init__")
                return (init,) if init else ()
            return (t,) if t in self.p.functions else ()
        if isinstance(func, ast.Attribute):
            # module-qualified: `flight_recorder.get(...)`
            if isinstance(func.value, ast.Name):
                imp = self.imports.get(func.value.id)
                if imp:
                    cand = imp + "." + func.attr
                    if cand in self.p.functions:
                        return (cand,)
                    if cand in self.p.classes:
                        init = self.p.classes[cand].get("__init__")
                        return (init,) if init else ()
            base_t = self.type_of(func.value)
            if base_t:
                key = base_t + "." + func.attr
                if key in self.p.functions:
                    return (key,)
                cb = self.p.registry.attr_calls.get(key)
                if cb:
                    return tuple(cb)
        return ()

    def resolve_target_ref(self, node: ast.AST) -> tuple:
        """Resolve a function REFERENCE (thread target, submit arg,
        signal handler) to (qualname-or-None, description).  Unlike a
        call, an unresolved method reference on a typed receiver still
        yields the syntactic ``Class.attr`` name (stdlib entries like
        ``serve_forever`` register that way)."""
        desc = _attr_chain(node) or ast.dump(node)[:40]
        if isinstance(node, ast.Name):
            return self.resolve_name(node.id), desc
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name):
                imp = self.imports.get(node.value.id)
                if imp:
                    return imp + "." + node.attr, desc
            base_t = self.type_of(node.value)
            if base_t:
                return base_t + "." + node.attr, desc
        return None, desc


def _analyze_function(p: Program, fi: FunctionInfo) -> None:
    ctx = _FnCtx(p, fi)
    node = fi.node
    # phase 0: parameter defaults carry types/locks into the local env
    # (the closure-capture idiom `def _prewarm(cache=self.cache):`)
    a = node.args
    pos = list(getattr(a, "posonlyargs", [])) + list(a.args)
    for arg, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        _bind_default(ctx, fi, arg.arg, d)
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            _bind_default(ctx, fi, arg.arg, d)
    # phase 1: local env (assignments + global decls), no nested defs
    for stmt in _iter_stmts(node.body):
        if isinstance(stmt, ast.Global):
            ctx.globals_decl.update(stmt.names)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            kind = _lock_kind(stmt.value, ctx.imports)
            if isinstance(tgt, ast.Name):
                if kind:
                    site = fi.qual + "." + tgt.id
                    fi.var_locks[tgt.id] = site
                    p.lock_creations.append(LockCreation(
                        site, stmt.lineno, fi.path, kind))
                    if kind == "Condition" and \
                            isinstance(stmt.value, ast.Call) and \
                            stmt.value.args:
                        inner = ctx.lock_of(stmt.value.args[0])
                        if inner:
                            p.lock_aliases[site] = inner
                else:
                    t = ctx.type_of(stmt.value)
                    if t:
                        fi.var_types[tgt.id] = t
                        if ctx.is_constructor_call(stmt.value):
                            fi.fresh_locals.add(tgt.id)
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self" and fi.cls:
                site = fi.cls + "." + tgt.attr
                if kind:
                    p.class_attr_locks[site] = site
                    p.lock_creations.append(LockCreation(
                        site, stmt.lineno, fi.path, kind))
                    if kind == "Condition" and \
                            isinstance(stmt.value, ast.Call) and \
                            stmt.value.args:
                        inner = ctx.lock_of(stmt.value.args[0])
                        if inner:
                            p.lock_aliases[site] = inner
                else:
                    t = ctx.type_of(stmt.value)
                    if t and site not in p.class_attr_types:
                        p.class_attr_types[site] = t
        # `with ThreadPoolExecutor(...) as ex:` pool typing
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                if isinstance(item.optional_vars, ast.Name):
                    t = ctx.type_of(item.context_expr)
                    if t:
                        fi.var_types[item.optional_vars.id] = t
    # phase 2: the lock-scoped walk
    _walk_block(p, ctx, fi, node.body, frozenset())


def _bind_default(ctx: _FnCtx, fi: FunctionInfo, name: str,
                  default: ast.AST) -> None:
    t = ctx.type_of(default)
    if t:
        fi.var_types[name] = t
        return
    lk = ctx.lock_of(default)
    if lk:
        fi.var_locks[name] = lk


def _iter_stmts(body):
    """Every statement in a block, recursively, EXCLUDING nested
    def/class bodies (they are separate FunctionInfos)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if isinstance(sub, list):
                yield from _iter_stmts(sub)
        for h in getattr(stmt, "handlers", []) or []:
            yield from _iter_stmts(h.body)


def _walk_block(p: Program, ctx: _FnCtx, fi: FunctionInfo,
                body, held: frozenset) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.With):
            inner = set(held)
            for item in stmt.items:
                _scan_exprs(p, ctx, fi, item.context_expr,
                            frozenset(inner))
                lock = ctx.lock_of(item.context_expr)
                if lock:
                    fi.acquires.append(LockUse(
                        lock, stmt.lineno, frozenset(inner)))
                    inner.add(lock)
            _walk_block(p, ctx, fi, stmt.body, frozenset(inner))
            continue
        # expressions owned by this statement line
        for expr in _stmt_exprs(stmt):
            _scan_exprs(p, ctx, fi, expr, held)
        _record_writes(p, ctx, fi, stmt, held)
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if isinstance(sub, list):
                _walk_block(p, ctx, fi, sub, held)
        for h in getattr(stmt, "handlers", []) or []:
            _walk_block(p, ctx, fi, h.body, held)


def _stmt_exprs(stmt):
    """The expression trees evaluated AT this statement (child block
    statements are walked separately)."""
    for f in ast.iter_fields(stmt):
        name, value = f
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v


def _record_writes(p: Program, ctx: _FnCtx, fi: FunctionInfo,
                   stmt, held: frozenset) -> None:
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for tgt in targets:
        for node in ast.walk(tgt) if isinstance(tgt, ast.Tuple) \
                else [tgt]:
            if isinstance(node, ast.Attribute):
                # writes through a same-function constructor-fresh
                # local hit a thread-confined object: not shared state
                if isinstance(node.value, ast.Name) and \
                        node.value.id in fi.fresh_locals:
                    continue
                base_t = ctx.type_of(node.value)
                if base_t and base_t != POOL_TYPE:
                    fi.writes.append(AttrWrite(
                        base_t + "." + node.attr, stmt.lineno, held))
            elif isinstance(node, ast.Name) and \
                    node.id in ctx.globals_decl:
                fi.writes.append(AttrWrite(
                    ctx.module + "." + node.id, stmt.lineno, held))


def _scan_exprs(p: Program, ctx: _FnCtx, fi: FunctionInfo,
                expr: ast.AST, held: frozenset) -> None:
    """Record calls/surface touches in one expression tree (lambdas
    inline: a deferred body is attributed to the defining function)."""
    reg = p.registry
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        tail = _tail(node.func)
        targets = ctx.resolve_call_targets(node.func)
        fi.calls.append(CallSite(targets, chain, tail,
                                 node.lineno, held))
        head = chain.split(".", 1)[0] if chain else ""
        # JAX surface (TL001)
        if head in reg.jax_surface_heads or \
                tail in reg.jax_surface_calls or \
                any(t in reg.compile_funcs for t in targets):
            fi.jax.append(Touch(chain or tail, node.lineno, held))
        # blocking surface (TL003)
        label = reg.blocking_calls.get(chain) or \
            (reg.blocking_calls.get("." + tail)
             if isinstance(node.func, ast.Attribute) else None)
        if label is None and any(t in reg.compile_funcs
                                 for t in targets):
            label = "XLA compile"
        if label is None and chain == "jax.jit":
            label = "XLA compile"
        if label is not None:
            fi.blocking.append(Touch(label, node.lineno, held))
        # GIL-wedge surface (TL005)
        if tail in reg.gil_wedge_calls:
            fi.wedge.append(Touch(chain or tail, node.lineno, held))
        # thread/pool/signal vocabulary sites (TL010)
        _record_vocab_sites(p, ctx, fi, node, chain, tail)


def _record_vocab_sites(p: Program, ctx: _FnCtx, fi: FunctionInfo,
                        node: ast.Call, chain: str, tail: str) -> None:
    imports = ctx.imports
    if tail == "Thread" and (chain in ("threading.Thread", "Thread")):
        if chain == "Thread" and \
                imports.get("Thread") != "threading.Thread":
            return
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        if target is None:
            p.thread_sites.append(ThreadSite(
                None, node.lineno, fi.path, "Thread without target="))
            return
        entry, desc = ctx.resolve_target_ref(target)
        p.thread_sites.append(ThreadSite(
            entry, node.lineno, fi.path, desc))
    elif tail == "ThreadPoolExecutor":
        if chain not in ("ThreadPoolExecutor",
                         "concurrent.futures.ThreadPoolExecutor",
                         "futures.ThreadPoolExecutor"):
            return
        prefix = next(
            (kw.value.value for kw in node.keywords
             if kw.arg == "thread_name_prefix"
             and isinstance(kw.value, ast.Constant)), None)
        p.pool_sites.append(PoolSite(node.lineno, fi.path, prefix))
    elif tail == "submit" and isinstance(node.func, ast.Attribute):
        if ctx.type_of(node.func.value) == POOL_TYPE and node.args:
            entry, desc = ctx.resolve_target_ref(node.args[0])
            p.submit_sites.append(SubmitSite(
                entry, node.lineno, fi.path, desc))
    elif chain == "signal.signal" and len(node.args) == 2:
        handler = node.args[1]
        # SIG_IGN / SIG_DFL / literals are not code entries
        if _tail(handler) in ("SIG_IGN", "SIG_DFL"):
            return
        entry, desc = ctx.resolve_target_ref(handler)
        p.signal_sites.append(SignalSite(
            entry, node.lineno, fi.path, desc))
