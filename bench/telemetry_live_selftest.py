#!/usr/bin/env python3
"""Live-telemetry selftest (ISSUE 10) — the `make telemetry-selftest`
extension that proves the OPERATIONAL layer end to end against a real
spawned server:

1. **trace reconstruction** — a batched request's client-minted
   ``trace_id`` is echoed on the wire AND fully reconstructable from
   telemetry alone: ``report.py --trace-id`` shows its queue wait,
   batch membership, dispatch and reply spans (the acceptance demo).
6. **explain leg (ISSUE 12)** — a traced request's decision record
   renders as a ``report.py --explain`` tree; ``sort.plan`` spans pass
   ``--require-registered-spans``; the plan-regret metrics appear in
   the ``/metrics`` scrape; and the acceptance comparison: the same
   skewed 2-device input with ``SORT_NEGOTIATE=off`` exports strictly
   MORE cap regret than the negotiated run (and the negotiated run's
   explain tree shows the restage decision with predicted peer-need vs
   measured recv bytes and a finite regret).
2. **/metrics** — scrapeable while serving; exposition format valid;
   every exported name registered in ``utils/metrics_live.py``;
   request counters reconcile EXACTLY with the client's own accounting.
3. **/healthz, /varz, /flightrecorder, /profile, /alerts** — live and
   sane; the sentinel's alert total reconciles with
   ``sort_alerts_total`` and stays zero on this healthy run.
4. **flight recorder** — a fault-injected typed error leaves a dump
   artifact that ``report.py --check`` accepts; the ``/flightrecorder``
   snapshot parses as span JSONL.
5. **sampling** — a ``SORT_TRACE_SAMPLE``-downsampled stream still
   passes the schema check (root-coherent sampling keeps parent links).

Run directly (``--out DIR``) or through ``make telemetry-selftest``.
"""

from __future__ import annotations

import argparse
import glob
import json
import shutil
import sys
import threading
import urllib.request
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

from serve_load import HOST, Server, log          # noqa: E402

from mpitest_tpu import report                    # noqa: E402
from mpitest_tpu.serve.client import ServeClient  # noqa: E402
from mpitest_tpu.utils import metrics_live        # noqa: E402


def http_get(port: int, path: str) -> bytes:
    with urllib.request.urlopen(f"http://{HOST}:{port}{path}",
                                timeout=30) as r:
        return r.read()


def explain_leg(streamed: list, tids: list, fams: dict,
                trace_path: Path) -> list:
    """The ISSUE 12 acceptance checks (see module docstring item 6).
    ``streamed``: the server's (sampled) span stream as dicts;
    ``tids``: surviving live-req trace ids; ``fams``: the parsed
    /metrics scrape; ``trace_path``: the stream on disk (driven
    through the real ``--explain --trace-id`` CLI)."""
    import io
    from contextlib import redirect_stdout

    import numpy as np

    from mpitest_tpu import report as report_mod
    from mpitest_tpu.utils import knobs

    fails: list[str] = []
    # 1. plan spans reached the wire stream and render as a tree;
    #    a batched request's tree is reachable via `report.py --explain
    #    --trace-id` (the sampler drops whole roots, so ANY surviving
    #    id suffices)
    rows = [dict(s, kind="span") for s in streamed]
    agg_view = report_mod.explain_view(rows)
    if agg_view is None or "plan algo=" not in agg_view:
        fails.append("no sort.plan span in the server stream (explain "
                     "view empty)")
    traced_ok = False
    for t in tids:
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = report_mod.main(["--explain", "--trace-id", t,
                                  str(trace_path)])
        if rc == 0 and "plan algo=" in buf.getvalue():
            traced_ok = True
            print(buf.getvalue())
            break
    if tids and not traced_ok:
        fails.append("no live-req trace id resolves to a plan via "
                     "--explain --trace-id (batch_id linkage broken?)")
    # 2. regret metrics appear in the /metrics scrape (the span-close
    #    bridge maps sort.plan onto the registered families)
    for name in ("sort_plans_total", "sort_plan_regret"):
        fam = fams.get(name)
        if not fam or not fam["samples"]:
            fails.append(f"/metrics: expected {name} after served "
                         "requests (plan bridge dead?)")
    # 3. the acceptance comparison, in-process on a skewed 2-device
    #    mesh: SORT_NEGOTIATE=off must export strictly MORE cap regret
    #    than the negotiated run, whose explain tree shows the restage
    #    decision with predicted peer-need vs measured recv bytes
    from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices

    ensure_virtual_cpu_devices(2)
    from mpitest_tpu.models.api import sort
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.utils.metrics_live import (LiveMetrics,
                                                SpanMetricsBridge)
    from mpitest_tpu.utils.trace import Tracer

    mesh = make_mesh(2)
    x = np.arange(1 << 15, dtype=np.int32)   # arrangement-skewed

    def one(**env):
        m = LiveMetrics()
        tr = Tracer()
        tr.spans.observers.append(SpanMetricsBridge(m))
        with knobs.scoped_env(SORT_RESTAGE_RATIO="1.5",
                              SORT_TRACE_SAMPLE=None, **env):
            sort(x, algorithm="sample", mesh=mesh, tracer=tr)
        return m, tr

    m_on, tr_on = one()
    m_off, _tr_off = one(SORT_NEGOTIATE="off")
    on_regret = m_on.gauge("sort_plan_cap_regret").get()
    off_regret = m_off.gauge("sort_plan_cap_regret").get()
    if not off_regret > on_regret:
        fails.append(f"SORT_NEGOTIATE=off cap regret {off_regret} not "
                     f"above negotiated {on_regret}")
    else:
        log(f"cap regret: negotiated {on_regret} < off {off_regret} "
            "(negotiation visibly earns its keep)")
    view = report_mod.explain_view(
        [dict(s.to_dict(), kind="span") for s in tr_on.spans.spans])
    for needle in ("restage", "chosen=True", "peer_recv_bytes",
                   "need="):
        if view is None or needle not in view:
            fails.append(f"negotiated explain tree missing {needle!r}")
    if view is not None:
        print(view)
    return fails


def run(out: Path) -> int:
    fails: list[str] = []
    fr_dir = out / "flight"
    srv = Server(out, "live", {
        "SORT_SERVE_BATCH_WINDOW_MS": "30",
        "SORT_SERVE_SHAPE_BUCKETS": "10,11,12",
        "SORT_SERVE_ALLOW_FAULTS": "1",
        "SORT_FALLBACK": "0",
        "SORT_MAX_RETRIES": "0",
        "SORT_FLIGHT_RECORDER_DIR": str(fr_dir),
        # the result-corruption fault sites live on the distributed
        # path (same arrangement as the serve selftest's limits leg)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        # prove sampling keeps the streamed JSONL schema-valid: every
        # 2nd root span (and its whole subtree) is dropped
        "SORT_TRACE_SAMPLE": "0.5",
    })
    assert srv.metrics_port is not None
    rng = np.random.default_rng(7)
    statuses: dict[str, int] = {}
    lock = threading.Lock()

    def count(st: str) -> None:
        with lock:
            statuses[st] = statuses.get(st, 0) + 1

    try:
        # -- concurrent small requests with KNOWN trace ids (batching
        #    engages inside the 30 ms window).  Per-worker rng: a
        #    Generator is not thread-safe to share.
        def worker(i: int) -> None:
            wrng = np.random.default_rng(700 + i)
            x = wrng.integers(-2**31, 2**31 - 1, size=300, dtype=np.int32)
            with ServeClient(HOST, srv.port) as c:
                r = c.sort(x, trace_id=f"live-req-{i}")
                count("ok" if r.ok else (r.error or "?"))
                if r.ok and not np.array_equal(r.arr, np.sort(x)):
                    fails.append(f"req {i}: reply not bit-identical")
                if r.trace_id != f"live-req-{i}":
                    fails.append(f"req {i}: trace_id not echoed "
                                 f"(got {r.trace_id!r})")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # -- one poisoned request -> typed error + flight-dump artifact
        x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            r = c.sort(x, faults="result_swap:inf", trace_id="live-bad")
            count("ok" if r.ok else (r.error or "?"))
            if r.ok or r.error != "integrity":
                fails.append(f"poisoned request: expected typed "
                             f"'integrity', got {r.header}")
            r2 = c.sort(x, trace_id="live-after")
            count("ok" if r2.ok else (r2.error or "?"))
            if not r2.ok:
                fails.append("server did not keep serving after the "
                             "poisoned request")
        dumps = sorted(glob.glob(str(fr_dir / "flight-*.jsonl")))
        if not dumps:
            fails.append("typed error left NO flight-recorder artifact")
        else:
            log(f"flight artifact: {dumps[0]}")
            if report.main(["--check", dumps[0]]) != 0:
                fails.append(f"report.py --check rejected the flight "
                             f"dump {dumps[0]}")

        # -- /metrics: exposition + registry + exact reconciliation
        prom = http_get(srv.metrics_port, "/metrics").decode()
        (out / "scrape.prom").write_text(prom)
        for e in metrics_live.check_exposition(prom):
            fails.append(f"/metrics: {e}")
        fams = metrics_live.parse_prom_text(prom)
        reqs = fams.get("sort_serve_requests_total")
        server_total = int(sum(v for _n, _l, v in reqs["samples"])) \
            if reqs else 0
        client_total = sum(statuses.values())
        if server_total != client_total:
            fails.append(f"count reconciliation: server {server_total} "
                         f"!= client {client_total} ({statuses})")
        else:
            log(f"reconciled: {server_total} requests on both sides "
                f"({statuses})")
        for name in ("sort_serve_request_latency_seconds",
                     "sort_serve_queue_wait_seconds",
                     "sort_serve_batches_total",
                     "sort_serve_cache_hits_total",
                     "sort_verify_runs_total"):
            fam = fams.get(name)
            if not fam or not sum(v for _n, _l, v in fam["samples"]):
                fails.append(f"/metrics: expected nonzero {name}")

        # -- /healthz, /varz, /flightrecorder, /profile
        hz = json.loads(http_get(srv.metrics_port, "/healthz"))
        if not hz.get("ok") or hz.get("requests_ok", 0) < 1:
            fails.append(f"/healthz not healthy: {hz}")
        vz = json.loads(http_get(srv.metrics_port, "/varz"))
        if vz.get("cache", {}).get("prewarmed", 0) < 1 \
                or "knobs_set" not in vz:
            fails.append(f"/varz incomplete: {sorted(vz)}")
        ring = http_get(srv.metrics_port, "/flightrecorder").decode()
        ring_rows = [json.loads(ln) for ln in ring.splitlines() if ln]
        if not any(r.get("name") == "serve.request" for r in ring_rows):
            fails.append("/flightrecorder snapshot carries no "
                         "serve.request span")
        pf = json.loads(http_get(srv.metrics_port, "/profile?n=1"))
        if pf.get("armed", 0) < 1:
            fails.append(f"/profile did not arm: {pf}")
        # -- /alerts (ISSUE 16): the sentinel is on by default, so the
        # endpoint must report enabled with the rolling series visible,
        # every raised rule must come from the registered vocabulary,
        # and the alert total must reconcile EXACTLY with a fresh
        # sort_alerts_total scrape.  (This run is NOT clean by design —
        # the fault leg injects a typed error the sentinel may burn on;
        # the zero-false-alert guarantee is doctor_selftest's clean
        # cell.)
        az = json.loads(http_get(srv.metrics_port, "/alerts"))
        if not az.get("enabled") or "series" not in az:
            fails.append(f"/alerts incomplete: {sorted(az)}")
        from mpitest_tpu.doctor import DOCTOR_RULES
        bad_rules = [a["rule"] for a in az.get("alerts", [])
                     if a.get("rule") not in DOCTOR_RULES]
        if bad_rules:
            fails.append(f"/alerts carries unregistered rules: "
                         f"{bad_rules}")
        fams_now = metrics_live.parse_prom_text(
            http_get(srv.metrics_port, "/metrics").decode())
        alerts_fam = fams_now.get("sort_alerts_total")
        prom_alerts = sum(v for _n, _l, v in alerts_fam["samples"]) \
            if alerts_fam else 0
        if az.get("alerts_total", -1) != prom_alerts:
            fails.append(f"/alerts total {az.get('alerts_total')} != "
                         f"sort_alerts_total {prom_alerts}")
        with ServeClient(HOST, srv.port) as c:
            r3 = c.sort(rng.integers(-100, 100, size=256, dtype=np.int32))
            count("ok" if r3.ok else (r3.error or "?"))
        prom2 = http_get(srv.metrics_port, "/metrics").decode()
        fams2 = metrics_live.parse_prom_text(prom2)
        cap = fams2.get("sort_profile_captures_total")
        if not cap or not sum(v for _n, _l, v in cap["samples"]):
            fails.append("armed /profile capture never fired")
        else:
            log("profile capture fired (sort_profile_captures_total > 0)")
    finally:
        rc = srv.stop()
    if rc != 0:
        fails.append(f"server exited rc={rc} on SIGTERM")

    # -- the sampled span stream still passes the schema check --------
    if report.main(["--check", "--require-registered-spans",
                    str(srv.trace)]) != 0:
        fails.append("sampled SORT_TRACE stream failed the schema check "
                     "(root-coherent sampling broke parent links?)")

    # -- acceptance demo: reconstruct one batched request end to end.
    # The 0.5 sampler drops every 2nd root span from the stream, so
    # pick a request whose serve.request SURVIVED sampling (the point
    # of root-coherent sampling is that survivors stay complete).
    import io
    from contextlib import redirect_stdout

    streamed = [json.loads(ln) for ln in
                srv.trace.read_text().splitlines() if ln.strip()]
    tids = [s["attrs"]["trace_id"] for s in streamed
            if s.get("name") == "serve.request"
            and str(s.get("attrs", {}).get("trace_id", "")
                    ).startswith("live-req-")]
    if not tids:
        fails.append("no live-req-* serve.request span survived "
                     "sampling (8 requests at rate 0.5)")
    else:
        buf = io.StringIO()
        with redirect_stdout(buf):
            view_rc = report.main(["--trace-id", tids[0], str(srv.trace)])
        view = buf.getvalue()
        if view_rc != 0:
            fails.append(f"report.py --trace-id found no spans for "
                         f"{tids[0]} (trace propagation broken)")
        else:
            for needle in ("serve.request", "queue_wait"):
                if needle not in view:
                    fails.append(f"--trace-id view missing {needle!r}")
            print(view)
    # the sampled stream may have dropped this request's batch subtree;
    # batch membership is asserted from the (unsampled) ring snapshot
    if not any(r.get("name") == "serve.batch"
               and "trace_ids" in r.get("attrs", {})
               for r in ring_rows):
        fails.append("no serve.batch span with trace_ids in the flight "
                     "ring (batch membership not reconstructable)")

    # -- explain leg (ISSUE 12) ---------------------------------------
    fails.extend(explain_leg(streamed, tids, fams, srv.trace))

    if fails:
        for f in fails:
            log(f"[FAIL] {f}")
        return 1
    log("telemetry live selftest OK (trace ids, /metrics reconciled, "
        "health/varz/flightrecorder/profile/alerts endpoints, flight "
        "dump passes report --check, sampled stream schema-valid)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/mpitest_telemetry_live",
                    help="artifact dir (cleared first: the flight-dump "
                         "and trace assertions must see THIS run only)")
    args = ap.parse_args()
    out = Path(args.out)
    shutil.rmtree(out, ignore_errors=True)
    out.mkdir(parents=True, exist_ok=True)
    return run(out)


if __name__ == "__main__":
    sys.exit(main())
