#!/usr/bin/env python3
"""Run the BASELINE.md measurement plan end-to-end and record results.

Executes every config the driver metadata defines (scaled to the hardware
this image actually has — one TPU chip and one CPU core), appending one
JSON line per measurement to ``bench/BASELINE_RESULTS.jsonl``:

  1. native sample_sort wall-time, 2^20 uniform int32, 4 local ranks
  2. native radix_sort  wall-time, 2^20 uniform int32, 4 local ranks
  3. TPU sample_sort Mkeys/s        (BENCH_LOG2N, default 2^26)
  4. TPU radix_sort  Mkeys/s        (BENCH_LOG2N, default 2^26)
  5. Zipf(1.1) int64 skew stress    (TPU path via host codec)
  6. native alltoallv GB/s + lax.all_to_all GB/s (BASELINE row 7)

Usage: python bench/run_baselines.py [--log2n-native 20] [--log2n-tpu 26]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "bench" / "BASELINE_RESULTS.jsonl"


def emit(obj: dict) -> None:
    obj = {"ts": time.time(), **obj}
    with open(OUT, "a") as f:
        f.write(json.dumps(obj) + "\n")
    print(json.dumps(obj))


def run_native(tag: str, binary: Path, path: str, ranks: int) -> None:
    from mpitest_tpu.utils.nativebench import run_native_sort

    secs, err = run_native_sort(binary, path, ranks, timeout=600)
    if err:
        emit({"config": tag, "error": err[:200]})
        return
    emit({"config": tag, "metric": "wall_time_s", "value": secs,
          "ranks": ranks})


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2n-native", type=int, default=20)
    ap.add_argument("--log2n-tpu", type=int, default=26)
    args = ap.parse_args()

    sys.path.insert(0, str(REPO))
    import numpy as np

    from mpitest_tpu.utils import io

    # build native binaries + micro-bench
    for d in ("mpi_sample_sort", "mpi_radix_sort", "bench"):
        subprocess.run(["make", "-C", str(REPO / d)], check=True,
                       capture_output=True)

    # configs 1-2: native CPU reference numbers, reference timer contract
    n_native = 1 << args.log2n_native
    keys = io.generate_uniform(n_native, seed=0)
    datafile = "/tmp/baseline_keys.txt"
    io.write_keys_text(datafile, keys)
    run_native("native_sample_2e%d_4ranks" % args.log2n_native,
               REPO / "mpi_sample_sort" / "sample_sort", datafile, 4)
    run_native("native_radix_2e%d_4ranks" % args.log2n_native,
               REPO / "mpi_radix_sort" / "radix_sort", datafile, 4)

    # configs 3-4: TPU Mkeys/s via bench.py (one JSON line on stdout)
    for algo in ("sample", "radix"):
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py")], capture_output=True,
            text=True, timeout=1200,
            env=dict(os.environ, BENCH_ALGO=algo,
                     BENCH_LOG2N=str(args.log2n_tpu)),
        )
        if r.returncode == 0 and r.stdout.strip():
            emit({"config": f"tpu_{algo}_2e{args.log2n_tpu}",
                  **json.loads(r.stdout.strip().splitlines()[-1])})
        else:
            emit({"config": f"tpu_{algo}", "error": r.stderr.strip()[-200:]})

    # config 5: Zipf(1.1) int64 skew stress (host codec path, real TPU)
    from mpitest_tpu.models.api import sort
    from mpitest_tpu.parallel.mesh import make_mesh

    from mpitest_tpu.utils.trace import Tracer

    n_zipf = 1 << max(args.log2n_tpu - 4, 16)
    z = io.generate_zipf(n_zipf, dtype=np.int64, seed=1)
    mesh = make_mesh()
    sort(z, algorithm="sample", mesh=mesh)  # warm/compile + settle caps
    tr = Tracer()
    t0 = time.perf_counter()
    out = sort(z, algorithm="sample", mesh=mesh, tracer=tr)
    dt = time.perf_counter() - t0
    ok = bool(np.array_equal(out, np.sort(z)))
    # NOTE: unlike the device-resident headline metric, this row times
    # the full HOST round-trip — encode, device_put and result decode
    # ride this image's ~0.1-1 GB/s tunnel, which dominates dt here
    # (production PCIe/DMA is orders faster); phases_ms attributes it.
    emit({"config": f"tpu_sample_zipf11_int64_2e{n_zipf.bit_length()-1}",
          "metric": "mkeys_per_s", "value": round(n_zipf / dt / 1e6, 2),
          "correct": ok, "span": "host_roundtrip",
          "phases_ms": {k: round(v * 1e3, 1) for k, v in tr.phases.items()},
          "counters": dict(tr.counters)})

    # config 6: the collective micro-bench pair (BASELINE row 7)
    r = subprocess.run(
        [str(REPO / "bench" / "comm_bench")], capture_output=True, text=True,
        env=dict(os.environ, COMM_RANKS="8"), timeout=600,
    )
    if r.returncode == 0 and r.stdout.strip():
        emit({"config": "native_alltoallv_8ranks", **json.loads(r.stdout)})
    r = subprocess.run(
        [sys.executable, str(REPO / "bench" / "collective_bench.py"),
         "--reps", "10"], capture_output=True, text=True, timeout=600,
    )
    for line in r.stderr.splitlines():
        if "GB/s" in line and "lax" in line:
            emit({"config": "lax_all_to_all", "detail": line.strip()})


if __name__ == "__main__":
    main()
