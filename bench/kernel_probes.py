#!/usr/bin/env python3
"""Primitive-cost probes behind the BASELINE.md kernel design study.

Measures, on the real chip, the per-call device time of the primitives
that bound any Pallas sort kernel on this hardware: streaming copy
(bandwidth floor), elementwise VPU ops, sublane vs lane rolls (the 15x
asymmetry that shaped ``ops/bitonic.py``), block transpose, `lax.sort`,
and the bitonic engine itself.

Method: slope of chained in-jit calls between two rep counts — (1, 17)
for the sub-millisecond primitive probes, (1, 3) for the two full sorts
— with a forced scalar ``device_get`` after each timed call:
``block_until_ready`` is advisory over this image's tunnel, and the
~0.1-0.2 s fixed dispatch cost swamps single-call timings (the round-1
numbers in the table at the top of BASELINE.md suffered exactly that).

Usage: python bench/kernel_probes.py [--log2n 26]
Emits one metrics-sidecar JSON line per probe on stderr and a summary
table on stdout.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2n", type=int, default=26)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpitest_tpu.ops import bitonic
    from mpitest_tpu.utils.metrics import Metrics

    n = 1 << args.log2n
    s_rows, lanes = 512, 128
    nblk = n // (s_rows * lanes)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.integers(-(2**31), 2**31, n, dtype=np.int32)
    ).reshape(nblk, s_rows, lanes)

    spec = pl.BlockSpec((1, s_rows, lanes), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)

    def kernel_call(body, k_reps):
        def kern(x_ref, o_ref):
            v = x_ref[0]
            for k in range(k_reps):
                v = body(v, k)
            o_ref[0] = v
        return pl.pallas_call(  # sortlint: disable=SL013 -- on-chip pricing probe, not a production kernel; results feed BASELINE.md, never a sort
            kern,
            out_shape=jax.ShapeDtypeStruct((nblk, s_rows, lanes), jnp.int32),
            grid=(nblk,), in_specs=[spec], out_specs=spec,
        )

    def slope(fn, reps=(1, 17), tries=4):
        out = {}
        for r in reps:
            @jax.jit
            def g(v, r=r):
                for _ in range(r):
                    v = fn(v)
                return v
            y = g(x)
            jax.device_get(y.reshape(-1)[:1])
            ts = []
            for _ in range(tries):
                t0 = time.perf_counter()
                y = g(x)
                jax.device_get(y.reshape(-1)[:1])
                ts.append(time.perf_counter() - t0)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0])

    K = 96
    probes = [
        ("copy_pass", kernel_call(lambda v, k: v + 1, 1), 1),  # noise floor ~±0.2 ms
        ("vpu_add", kernel_call(lambda v, k: v + k, K), K),
        ("vpu_min_mul_add", kernel_call(lambda v, k: jnp.minimum(v, v * 2 + k), K), K),
        ("sublane_roll", kernel_call(lambda v, k: pltpu.roll(v, 1 << (k % 6), 0), K), K),
        ("lane_roll", kernel_call(lambda v, k: pltpu.roll(v, 1 << (k % 6), 1), K), K),
        ("transpose_pair",
         kernel_call(lambda v, k: pltpu.roll(v.T, 1, 0).T, K), K),
    ]

    metrics = Metrics(config={"probe": "kernel_primitives",
                              "log2n": args.log2n})
    print(f"{'probe':22s} {'ms/unit':>10s}")
    for name, call, units in probes:
        per = slope(lambda v, c=call: c(v)) / units
        metrics.record(f"{name}_ms", round(per * 1e3, 4), "ms")
        print(f"{name:22s} {per*1e3:10.4f}")

    # 2-word lexicographic compare-exchange layer vs the 1-word form —
    # the measured basis for "the engine stays one-word" (BASELINE.md):
    # a 64-bit key split into (hi, lo) uint32 planes needs 4 rolls + a
    # 5-op lexicographic compare each way + per-word selects, vs the
    # 1-word layer's 2 rolls + min + max + select.  VERDICT r2 #3 asked
    # for this ratio measured, not projected.
    def kernel_call2(body, k_reps):
        def kern(hi_ref, lo_ref, ohi_ref, olo_ref):
            hi, lo = hi_ref[0], lo_ref[0]
            for k in range(k_reps):
                hi, lo = body(hi, lo, k)
            ohi_ref[0], olo_ref[0] = hi, lo
        return pl.pallas_call(  # sortlint: disable=SL013 -- on-chip pricing probe, not a production kernel; results feed BASELINE.md, never a sort
            kern,
            out_shape=[jax.ShapeDtypeStruct((nblk, s_rows, lanes), jnp.int32)] * 2,
            grid=(nblk,), in_specs=[spec, spec], out_specs=[spec, spec],
        )

    def asc_layer_1w(v, k):
        d, log = 1 << (3 + k % 3), 3 + k % 3  # sublane distances, like bitonic
        size = v.shape[0]
        fwd = pltpu.roll(v, size - d, 0)
        bwd = pltpu.roll(v, d, 0)
        idx = jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        low = ((idx >> log) & 1) == 0
        return jnp.where(low, jnp.minimum(v, fwd), jnp.maximum(v, bwd))

    def asc_layer_2w(hi, lo, k):
        d, log = 1 << (3 + k % 3), 3 + k % 3
        size = hi.shape[0]
        fhi, flo = pltpu.roll(hi, size - d, 0), pltpu.roll(lo, size - d, 0)
        bhi, blo = pltpu.roll(hi, d, 0), pltpu.roll(lo, d, 0)
        # predicates ride as int32 0/1 — Mosaic rejects selects whose
        # RESULTS are i1 vectors ("unsupported target bitwidth")
        lt_f = ((hi < fhi) | ((hi == fhi) & (lo < flo))).astype(jnp.int32)
        gt_b = ((hi > bhi) | ((hi == bhi) & (lo > blo))).astype(jnp.int32)
        idx = jax.lax.broadcasted_iota(jnp.int32, hi.shape, 0)
        low = ((idx >> log) & 1) == 0
        keep = jnp.where(low, lt_f, gt_b) == 1  # keep self on the winning side
        out_hi = jnp.where(keep, hi, jnp.where(low, fhi, bhi))
        out_lo = jnp.where(keep, lo, jnp.where(low, flo, blo))
        return out_hi, out_lo

    def asc_layer_kp(k, p, kk):
        """Key+payload compare-exchange: min/max on the KEY plane (the
        1-word form, no lexicographic predicate chain) plus one <=/>=
        predicate that routes the PAYLOAD plane.  The core primitive of
        the MSD-hybrid 64-bit structure (sort by hi word, lo rides as
        payload; equal keys keep their own payloads — consistent on both
        sides, so no element is lost).  VERDICT r3 #1 asks this priced
        before building."""
        d, log = 1 << (3 + kk % 3), 3 + kk % 3
        size = k.shape[0]
        fk, fp = pltpu.roll(k, size - d, 0), pltpu.roll(p, size - d, 0)
        bk, bp = pltpu.roll(k, d, 0), pltpu.roll(p, d, 0)
        idx = jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
        low = ((idx >> log) & 1) == 0
        out_k = jnp.where(low, jnp.minimum(k, fk), jnp.maximum(k, bk))
        # int32 0/1 predicates: Mosaic rejects i1-vector select results
        le = (k <= fk).astype(jnp.int32)
        ge = (k >= bk).astype(jnp.int32)
        keep = jnp.where(low, le, ge) == 1
        out_p = jnp.where(keep, p, jnp.where(low, fp, bp))
        return out_k, out_p

    def asc_layer_kp2(k, p, kk):
        """kp variant: payload route derived from the key RESULT —
        ``keep = (out_k == k)`` (low side: out==k ⟺ k<=partner; high:
        ⟺ k>=partner; ties keep own payload on BOTH sides — a
        consistent no-swap).  One equality replaces two compares + two
        int32 casts + one select of the naive kp form."""
        d, log = 1 << (3 + kk % 3), 3 + kk % 3
        size = k.shape[0]
        fk, fp = pltpu.roll(k, size - d, 0), pltpu.roll(p, size - d, 0)
        bk, bp = pltpu.roll(k, d, 0), pltpu.roll(p, d, 0)
        idx = jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
        low = ((idx >> log) & 1) == 0
        out_k = jnp.where(low, jnp.minimum(k, fk), jnp.maximum(k, bk))
        out_p = jnp.where(out_k == k, p, jnp.where(low, fp, bp))
        return out_k, out_p

    layer1 = kernel_call(asc_layer_1w, K)
    layer2 = kernel_call2(asc_layer_2w, K)
    layerkp = kernel_call2(asc_layer_kp, K)
    layerkp2 = kernel_call2(asc_layer_kp2, K)
    per1 = slope(lambda v: layer1(v)) / K
    x2 = (x, jnp.asarray(
        rng.integers(-(2**31), 2**31, n, dtype=np.int32)
    ).reshape(nblk, s_rows, lanes))

    def slope2(fn, reps=(1, 17), tries=4):
        out = {}
        for r in reps:
            @jax.jit
            def g(pair, r=r):
                hi, lo = pair
                for _ in range(r):
                    hi, lo = fn(hi, lo)
                return hi, lo
            y = g(x2)
            jax.device_get(y[0].reshape(-1)[:1])
            ts = []
            for _ in range(tries):
                t0 = time.perf_counter()
                y = g(x2)
                jax.device_get(y[0].reshape(-1)[:1])
                ts.append(time.perf_counter() - t0)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0])

    per2 = slope2(lambda h, l: layer2(h, l)) / K
    perkp = slope2(lambda h, l: layerkp(h, l)) / K
    perkp2 = slope2(lambda h, l: layerkp2(h, l)) / K
    metrics.record("bitonic_layer_1w_ms", round(per1 * 1e3, 4), "ms")
    metrics.record("bitonic_layer_2w_ms", round(per2 * 1e3, 4), "ms")
    metrics.record("bitonic_layer_2w_ratio", round(per2 / per1, 3), "x")
    metrics.record("bitonic_layer_kp_ms", round(perkp * 1e3, 4), "ms")
    metrics.record("bitonic_layer_kp_ratio", round(perkp / per1, 3), "x")
    metrics.record("bitonic_layer_kp2_ms", round(perkp2 * 1e3, 4), "ms")
    metrics.record("bitonic_layer_kp2_ratio", round(perkp2 / per1, 3), "x")
    print(f"{'bitonic_layer_1w':22s} {per1*1e3:10.4f}")
    print(f"{'bitonic_layer_2w':22s} {per2*1e3:10.4f}   ratio {per2/per1:.2f}x "
          f"(compare against lax.sort's own 2-word penalty — BASELINE.md)")
    print(f"{'bitonic_layer_kp':22s} {perkp*1e3:10.4f}   ratio {perkp/per1:.2f}x "
          f"(key+payload: the MSD-hybrid core primitive)")
    print(f"{'bitonic_layer_kp2':22s} {perkp2*1e3:10.4f}   ratio {perkp2/per1:.2f}x "
          f"(key+payload via out_k==k routing)")

    flat = x.reshape(-1)
    def slope_flat(fn, reps=(1, 3)):
        out = {}
        for r in reps:
            @jax.jit
            def g(v, r=r):
                for _ in range(r):
                    v = fn(v)
                return v
            y = g(flat)
            jax.device_get(y[:1])
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                y = g(flat)
                jax.device_get(y[:1])
                ts.append(time.perf_counter() - t0)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0])

    for name, fn in [
        ("lax_sort", lambda v: jax.lax.sort([v], num_keys=1, is_stable=False)[0]),
        ("pallas_bitonic", lambda v: jax.lax.bitcast_convert_type(
            bitonic.sort_padded(
                jax.lax.bitcast_convert_type(v, jnp.uint32), n,
                bitonic.BLOCK_LOG2),
            jnp.int32)),
    ]:
        per = slope_flat(fn)
        metrics.record(f"{name}_ms", round(per * 1e3, 2), "ms")
        print(f"{name:22s} {per*1e3:10.2f}")

    metrics.dump()


if __name__ == "__main__":
    main()
