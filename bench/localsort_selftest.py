#!/usr/bin/env python3
"""Fused local-sort gate: the `make localsort-selftest` matrix (ISSUE 17).

Proves the third local-sort engine — the fused per-pass radix kernel,
the device-side merge-order kernel, and the planner's key-width
compaction — end to end on any image, TPU-free (the kernels run under
the Pallas interpreter, the engine's only honest evidence until a real
TPU session re-baselines):

1. **kernel bit-identity** — ``ops/radix_pallas.fused_radix_sort``
   matches the ``np.lexsort`` oracle word for word across every codec
   dtype x {uniform, dup-skew, sorted, tiny (N < chunk), non-divisible
   N} input class, full-width AND compacted plans.
2. **api bit-identity** — ``sort()`` under ``SORT_LOCAL_ENGINE=
   radix_pallas`` is byte-identical to the lax engine with the ladder
   pinned off (``SORT_FALLBACK=0`` — a silent degrade would pass
   vacuously), single-device and on the virtual 8-device mesh for both
   algorithms.
3. **launch accounting** — a fused sort issues exactly one
   ``pallas_call`` per planned pass (the perf claim is fusion, so the
   launch count IS the evidence), and a 20-bit-narrow int64 plan is
   measurably SHORTER than the full-width plan (the compaction win,
   CPU-scale wall clock reported with the no-TPU caveat, gated on pass
   count — interpreter wall time is weather).
4. **merge parity** — the external-sort dataset (the external-selftest
   row's exact generator) spill+merges bit-identical under the device
   merge-order kernel vs the host ``np.lexsort`` path, and the kernel
   provably RAN (call-counted) — not silently capped out to the host.
5. **planner compaction** — a narrow-range profile chooses the
   ``radix_compact`` policy, its predicted pass count matches what the
   distributed radix actually ran (regret 0 on an honest profile), and
   a lying profile (planted wide) stamps nonzero "passes" regret.

Every cell failure prints loudly and the process exits nonzero; the
Makefile target then schema-checks the emitted trace
(``report.py --check --require-registered-spans``).
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SORT_RETRY_BACKOFF", "0")
# Fail-fast by default: every parity cell must exercise the engine it
# names, never a silently degraded rung.  (The ladder's own evidence
# lives in bench/fault_selftest.py's forced-local-engine section.)
os.environ.setdefault("SORT_FALLBACK", "0")
os.environ.setdefault("SORT_MAX_RETRIES", "0")

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402

from mpitest_tpu.models.api import sort  # noqa: E402
from mpitest_tpu.ops import radix_pallas as rp  # noqa: E402
from mpitest_tpu.ops.keys import codec_for  # noqa: E402
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402

#: Gitignored checkout-scoped staging for the merge-parity leg.
SPILL_DIR = REPO / "bench" / ".spill-out" / "localsort"

#: Every codec dtype (the record/external gates' same list).
DTYPES = (np.int8, np.int16, np.int32, np.int64,
          np.uint8, np.uint16, np.uint32, np.uint64,
          np.float32, np.float64)

FAIL = 0


def check(name: str, ok: bool, detail: str = "") -> None:
    global FAIL
    if not ok:
        FAIL += 1
    print(f"  {'ok ' if ok else 'BAD'} {name:<52} {detail}", flush=True)


def _gen(kind: str, n: int, dtype, rng) -> np.ndarray:
    dt = np.dtype(dtype)
    if dt.kind == "f":
        x = rng.normal(size=n).astype(dt)
        if kind == "dup":
            x = np.round(x).astype(dt)
    else:
        info = np.iinfo(dt)
        if kind == "dup":
            x = rng.integers(0, 5, size=n).astype(dt)
        else:
            x = rng.integers(info.min, info.max, size=n,
                             dtype=dt, endpoint=True)
    if kind == "sorted":
        x = np.sort(x)
    return x


def kernel_parity_leg() -> None:
    """Cell grid 1: fused_radix_sort vs the np.lexsort oracle on the
    raw word planes, every dtype x input class, full + compacted."""
    print("kernel bit-identity: fused_radix_sort vs np.lexsort oracle")
    rng = np.random.default_rng(170)
    classes = (("uniform", 2048), ("dup", 2048), ("sorted", 2048),
               ("tiny", 7), ("nondiv", 1537))
    for dtype in DTYPES:
        codec = codec_for(dtype)
        for kind, n in classes:
            x = _gen("dup" if kind == "dup" else
                     "sorted" if kind == "sorted" else "uniform",
                     n, dtype, rng)
            if kind == "sorted":
                x = np.sort(x)
            words = codec.encode(x)
            ref = np.lexsort(tuple(reversed(words)))
            got = rp.fused_radix_sort(
                tuple(np.asarray(w) for w in words), interpret=True)
            ok = all(np.array_equal(np.asarray(g), w[ref])
                     for g, w in zip(got, words))
            check(f"kernel {np.dtype(dtype).name:<8} {kind}", ok,
                  f"n={n} words={len(words)}")
    # compacted plan: 20-bit-narrow values in a 2-word codec — the
    # constant high word is skipped, the low word runs at its width
    x = np.random.default_rng(171).integers(
        0, 1 << 20, size=2048, dtype=np.int64)
    codec = codec_for(np.int64)
    words = codec.encode(x)
    diffs = tuple(int(w.max()) - int(w.min()) for w in words)
    ref = np.lexsort(tuple(reversed(words)))
    got = rp.fused_radix_sort(tuple(np.asarray(w) for w in words),
                              diffs=diffs, interpret=True)
    ok = all(np.array_equal(np.asarray(g), w[ref])
             for g, w in zip(got, words))
    plan = rp.pass_plan(diffs, len(words))
    full = rp.pass_plan(None, len(words))
    check("kernel int64 20-bit compacted plan", ok and len(plan) < len(full),
          f"passes={len(plan)} vs full={len(full)}")


def api_parity_leg(mesh8) -> None:
    """Cell grid 2: sort() byte-identity lax vs fused engine, ladder
    pinned, single-device + mesh8 x both algorithms."""
    print("api bit-identity: SORT_LOCAL_ENGINE=radix_pallas vs lax "
          "(SORT_FALLBACK=0)")
    rng = np.random.default_rng(172)
    for dtype in (np.int32, np.int64, np.uint32, np.float32, np.float64):
        x = _gen("uniform", 4096, dtype, rng)
        with knobs.scoped_env(SORT_LOCAL_ENGINE="lax"):
            a = sort(x)
        t = Tracer()
        with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas"):
            b = sort(x, tracer=t)
        eng = t.counters.get("local_engine")
        check(f"api 1dev {np.dtype(dtype).name}",
              a.tobytes() == b.tobytes()
              and str(eng).startswith("radix_pallas")
              and "local_engine_degraded" not in t.counters,
              f"engine={eng}")
    for algo in ("radix", "sample"):
        for dtype in (np.int64, np.uint32, np.float32):
            x = _gen("uniform", 4096, dtype, rng)
            with knobs.scoped_env(SORT_LOCAL_ENGINE="lax"):
                a = sort(x, algorithm=algo, mesh=mesh8)
            t = Tracer()
            with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas"):
                b = sort(x, algorithm=algo, mesh=mesh8, tracer=t)
            eng = t.counters.get("local_engine")
            check(f"api mesh8 {algo} {np.dtype(dtype).name}",
                  a.tobytes() == b.tobytes()
                  and str(eng).startswith("radix_pallas")
                  and "local_engine_degraded" not in t.counters,
                  f"engine={eng}")
    # N < P: 5 keys across 8 ranks — the fused engine must survive the
    # empty-shard staging exactly like lax
    tiny = np.array([3, -1, 7, 0, 3], dtype=np.int32)
    with knobs.scoped_env(SORT_LOCAL_ENGINE="lax"):
        a = sort(tiny, algorithm="radix", mesh=mesh8)
    with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas"):
        b = sort(tiny, algorithm="radix", mesh=mesh8)
    check("api mesh8 radix N<P", a.tobytes() == b.tobytes()
          and a.tobytes() == np.sort(tiny).tobytes(), "n=5 P=8")


def launch_count_leg() -> None:
    """Cell grid 3: one pallas_call per planned pass, and the narrow
    plan is shorter AND faster at CPU scale (pass count is the gate;
    wall clock is reported with the no-TPU caveat)."""
    print("launch accounting: one pallas_call per pass + compaction win")
    rng = np.random.default_rng(173)
    codec = codec_for(np.int64)
    narrow = rng.integers(0, 1 << 20, size=4096, dtype=np.int64)
    wide = rng.integers(-(2**62), 2**62, size=4096, dtype=np.int64)

    def run(x):
        words = tuple(np.asarray(w) for w in codec.encode(x))
        diffs = tuple(int(w.max()) - int(w.min()) for w in words)
        plan = rp.pass_plan(diffs, len(words))
        before = rp.pass_launches()
        t0 = time.perf_counter()
        out = rp.fused_radix_sort(words, diffs=diffs, interpret=True)
        np.asarray(out[0])
        dt = time.perf_counter() - t0
        return plan, rp.pass_launches() - before, dt

    plan_n, launches_n, dt_n = run(narrow)
    plan_w, launches_w, dt_w = run(wide)
    check("launches == planned passes (narrow)",
          launches_n == len(plan_n), f"{launches_n} == {len(plan_n)}")
    check("launches == planned passes (wide)",
          launches_w == len(plan_w), f"{launches_w} == {len(plan_w)}")
    check("narrow plan shorter than wide", len(plan_n) < len(plan_w),
          f"{len(plan_n)} < {len(plan_w)} passes")
    print(f"  -- interpret wall: narrow {dt_n:.3f}s vs wide {dt_w:.3f}s "
          "(CPU interpreter evidence only; fused kernels have never "
          "lowered on a real TPU — re-baseline there)")


def merge_parity_leg() -> None:
    """Cell grid 4: the external-selftest dataset spill+merged under
    the device merge-order kernel vs the host lexsort — bit-identical,
    and the kernel call-counted as actually having run."""
    print("merge parity: external sort, device merge-order vs host lexsort")
    from mpitest_tpu.store import external, merge

    budget = 1 << 18
    n_keys = budget  # int32 -> 4x budget, the external-selftest ratio
    rng = np.random.default_rng(17)  # the external row's generator
    x = rng.integers(-(2**31), 2**31 - 1, size=n_keys, dtype=np.int32)
    ref = np.sort(x)

    with knobs.scoped_env(SORT_LOCAL_ENGINE="lax"):
        host = external.external_sort(x, budget=budget,
                                      spill_dir=str(SPILL_DIR / "host"))
    calls = {"n": 0}
    orig = rp.merge_order

    def counted(planes, interpret=False):
        calls["n"] += 1
        return orig(planes, interpret=interpret)

    # merge._order_for resolves rp.merge_order at call time, so the
    # module-attribute patch counts every device-ordered round
    rp.merge_order = counted
    try:
        with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas_interpret"):
            dev = external.external_sort(x, budget=budget,
                                         spill_dir=str(SPILL_DIR / "dev"))
    finally:
        rp.merge_order = orig
    check("device merge bit-identical to host",
          host.keys.tobytes() == dev.keys.tobytes()
          and host.keys.tobytes() == ref.tobytes(),
          f"n={n_keys} runs={dev.runs}")
    check("merge-order kernel actually ran", calls["n"] > 0,
          f"calls={calls['n']}")
    check("host path == np.sort", host.keys.tobytes() == ref.tobytes())


def planner_leg(mesh8) -> None:
    """Cell grid 5: narrow profile -> radix_compact policy, honest
    prediction (passes regret 0), lying profile stamps regret."""
    print("planner compaction: radix_compact policy + passes regret")
    from mpitest_tpu.models import plan as plan_mod
    from mpitest_tpu.models import planner

    rng = np.random.default_rng(174)
    narrow = rng.integers(0, 1 << 20, size=1 << 14, dtype=np.int64)

    prof = plan_mod.profile_host_array(narrow)
    choice = planner.choose(prof, "radix", verify_on=True)
    check("narrow profile chooses radix_compact",
          choice.policy == "radix_compact"
          and choice.trigger == "range_narrow",
          f"policy={choice.policy} width={prof.get('key_width')}")

    with knobs.scoped_env(SORT_PLANNER="on",
                          SORT_LOCAL_ENGINE="radix_pallas"):
        t = Tracer()
        out = sort(narrow, algorithm="radix", mesh=mesh8, tracer=t)
    ok_sorted = out.tobytes() == np.sort(narrow).tobytes()
    d = t.plan.decisions.get("passes")
    honest = (d is not None and d.trigger == "planner"
              and d.regret == 0.0
              and int(d.predicted.get("passes", -1)) == int(d.chosen))
    check("honest profile: predicted passes ran, regret 0",
          ok_sorted and honest,
          f"passes={None if d is None else d.chosen} "
          f"regret={None if d is None else d.regret}")

    # lying profile: the sampled min/max promise a narrow key but the
    # data is full-width — the distributed radix runs MORE passes than
    # the planner predicted and the "passes" decision prices the lie.
    wide = rng.integers(-(2**62), 2**62, size=1 << 14, dtype=np.int64)
    orig_prof = plan_mod.profile_host_array

    def lying_profile(arr, *a, **kw):
        out = dict(orig_prof(arr, *a, **kw))
        out["key_width"] = 20  # the lie: real width is ~63 bits
        return out

    plan_mod.profile_host_array = lying_profile
    try:
        with knobs.scoped_env(SORT_PLANNER="on"):
            t2 = Tracer()
            out2 = sort(wide, algorithm="radix", mesh=mesh8, tracer=t2)
    finally:
        plan_mod.profile_host_array = orig_prof
    d2 = t2.plan.decisions.get("passes")
    check("lying profile stamps nonzero passes regret",
          out2.tobytes() == np.sort(wide).tobytes()
          and d2 is not None and (d2.regret or 0.0) > 0.0,
          f"regret={None if d2 is None else d2.regret}")


def main() -> int:
    import shutil

    if SPILL_DIR.exists():
        shutil.rmtree(SPILL_DIR)
    SPILL_DIR.mkdir(parents=True, exist_ok=True)
    mesh8 = make_mesh(8)
    try:
        kernel_parity_leg()
        api_parity_leg(mesh8)
        launch_count_leg()
        merge_parity_leg()
        planner_leg(mesh8)
    finally:
        shutil.rmtree(SPILL_DIR, ignore_errors=True)
    print(f"\nlocalsort-selftest: "
          f"{'CLEAN' if FAIL == 0 else f'{FAIL} BAD cell(s)'}")
    return 1 if FAIL else 0


if __name__ == "__main__":
    sys.exit(main())
