#!/usr/bin/env python3
"""Chaos TCP proxy: wire-level fault injection for the sort server.

PR 3's fault harness stops below the serving layer — all of its sites
live inside ``sort()``.  This proxy (ISSUE 11) attacks the layer the
reference leaves wide open and PR 7 added: the WIRE.  It sits between
a well-behaved client and a real ``sort_server`` and misbehaves on
purpose, per the ``SORT_FAULTS``-style wire-fault spec
(:func:`mpitest_tpu.faults.parse_wire_faults` —
``site[@param][:every]`` entries over ``faults.WIRE_SITES``):

* ``wire_torn_header@k``         — forward only the first ``k`` request
  bytes, then close (a client that died mid-header).
* ``wire_stall_payload@k``       — forward the header + ``k`` payload
  bytes, then go silent holding the connection open (the slow-loris:
  the server's read timeout must shed it and reclaim its admission
  bytes).
* ``wire_disconnect_response@k`` — forward the request, deliver ``k``
  response bytes, then close the client side (a network that died
  mid-download; the client's problem, never the server's).
* ``wire_slow_drip@ms``          — drip the request upstream in tiny
  chunks with ``ms`` pauses: every chunk makes progress, so only a
  TOTAL read budget (not a per-recv timeout) bounds it.
* ``wire_delay_response@ms``     — hold the response back ``ms`` before
  delivering (deterministic injected tail latency — the hedging
  cell's substrate; use ``:4`` to stall every 4th connection).
* ``wire_connect_silence``       — accept the client, never connect
  upstream, never send a byte (the client's connect/read timeouts and
  retry policy are what recovers).

The proxy is deliberately dumb about everything except the one byte
boundary it needs (the header's terminating newline) and keeps a
per-connection decision ``log`` so tests can assert which fault fired
where.  Stdlib-only; importing it never drags in jax.
"""

from __future__ import annotations

import socket
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from mpitest_tpu.faults import WireFault, parse_wire_faults  # noqa: E402

#: Forwarding chunk size for the normal (unfaulted) relay path.
_CHUNK = 1 << 16

#: Drip chunk size for wire_slow_drip — small enough that a multi-KiB
#: payload takes many pauses.
_DRIP_CHUNK = 512


class ChaosProxy:
    """One listening socket relaying to ``(upstream_host,
    upstream_port)`` with wire faults applied per connection index.

    ``faults`` is a spec string or a parsed tuple; each connection
    applies the FIRST entry whose ``every`` matches its index (0-based
    arrival order), so ``"wire_delay_response@800:4"`` stalls exactly
    the 4th, 8th, ... connection and relays the rest cleanly."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 faults: "str | tuple[WireFault, ...]" = (),
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.upstream = (upstream_host, upstream_port)
        self.faults: tuple[WireFault, ...] = (
            parse_wire_faults(faults) if isinstance(faults, str)
            else tuple(faults))
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self._conn_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._open: list[socket.socket] = []
        #: per-connection decisions: (index, fault-site or None)
        self.log: list[tuple[int, str | None]] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-accept", daemon=True)

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "ChaosProxy":
        self._accept_thread.start()
        return self

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._open = self._open, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

    def _track(self, s: socket.socket) -> socket.socket:
        with self._lock:
            self._open.append(s)
        return s

    # -- accept / dispatch --------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._listener.accept()
            except OSError:
                return
            with self._lock:
                idx = self._conn_seq
                self._conn_seq += 1
            fault = next((f for f in self.faults if f.fires_on(idx)),
                         None)
            self.log.append((idx, fault.site if fault else None))
            self._track(client)
            threading.Thread(target=self._serve_conn,
                             args=(client, fault),
                             name=f"chaos-conn-{idx}", daemon=True).start()

    def _serve_conn(self, client: socket.socket,
                    fault: WireFault | None) -> None:
        try:
            if fault is not None and fault.site == "wire_connect_silence":
                # hold the client open, say nothing, connect nowhere —
                # closed when the client gives up or the proxy stops
                self._stop.wait()
                return
            try:
                upstream = self._track(socket.create_connection(
                    self.upstream, timeout=10.0))
            except OSError:
                return
            t_up = threading.Thread(
                target=self._pipe_up, args=(client, upstream, fault),
                daemon=True)
            t_up.start()
            self._pipe_down(upstream, client, fault)
            t_up.join(timeout=1.0)
            try:
                upstream.close()
            except OSError:
                pass
        finally:
            try:
                client.close()
            except OSError:
                pass

    # -- client -> server ---------------------------------------------
    def _pipe_up(self, client: socket.socket, upstream: socket.socket,
                 fault: WireFault | None) -> None:
        """Relay request bytes, applying the request-side faults.  The
        header/payload boundary is the first newline — the only
        protocol knowledge the torn/stall sites need."""
        site = fault.site if fault else None
        param = fault.param if fault else 0
        sent = 0              # total request bytes forwarded
        header_done = False
        payload_sent = 0
        try:
            while True:
                try:
                    data = client.recv(_CHUNK)
                except OSError:
                    break
                if not data:
                    try:
                        upstream.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    break
                if site == "wire_torn_header":
                    budget = param - sent
                    if budget > 0:
                        upstream.sendall(data[:budget])
                        sent += min(len(data), budget)
                    if sent >= param:
                        # died mid-header: close BOTH directions
                        upstream.close()
                        client.close()
                        return
                    continue
                if site == "wire_stall_payload":
                    if not header_done:
                        nl = data.find(b"\n")
                        if nl < 0:
                            upstream.sendall(data)
                            sent += len(data)
                            continue
                        header_done = True
                        head, rest = data[:nl + 1], data[nl + 1:]
                        upstream.sendall(head)
                        sent += len(head)
                        data = rest
                        if not data:
                            continue
                    room = param - payload_sent
                    if room > 0:
                        upstream.sendall(data[:room])
                        payload_sent += min(len(data), room)
                        sent += min(len(data), room)
                    if payload_sent >= param:
                        # the slow-loris: k payload bytes delivered,
                        # then nothing, connection held open — the
                        # server's read budget must shed it
                        self._stop.wait()
                        return
                    continue
                if site == "wire_slow_drip":
                    for off in range(0, len(data), _DRIP_CHUNK):
                        if self._stop.is_set():
                            return
                        upstream.sendall(data[off:off + _DRIP_CHUNK])
                        time.sleep(param / 1e3)
                    sent += len(data)
                    continue
                upstream.sendall(data)
                sent += len(data)
        except OSError:
            pass

    # -- server -> client ---------------------------------------------
    def _pipe_down(self, upstream: socket.socket, client: socket.socket,
                   fault: WireFault | None) -> None:
        site = fault.site if fault else None
        param = fault.param if fault else 0
        delivered = 0
        delayed = False
        try:
            while True:
                try:
                    data = upstream.recv(_CHUNK)
                except OSError:
                    return
                if not data:
                    return
                if site == "wire_delay_response" and not delayed:
                    delayed = True
                    if self._stop.wait(param / 1e3):
                        return
                if site == "wire_disconnect_response":
                    room = param - delivered
                    if room > 0:
                        client.sendall(data[:room])
                        delivered += min(len(data), room)
                    if delivered >= param:
                        client.close()      # died mid-download
                        return
                    continue
                client.sendall(data)
                delivered += len(data)
        except OSError:
            return


def main() -> int:
    """Standalone mode: ``wire_chaos.py UPSTREAM_PORT SPEC [LISTEN_PORT]``
    — run a chaos proxy from the shell (the selftest drives the class
    directly)."""
    if len(sys.argv) not in (3, 4):
        print(f"Usage: {sys.argv[0]} UPSTREAM_PORT SPEC [LISTEN_PORT]",
              file=sys.stderr)
        return 1
    upstream_port = int(sys.argv[1])
    listen = int(sys.argv[3]) if len(sys.argv) == 4 else 0
    proxy = ChaosProxy("127.0.0.1", upstream_port, sys.argv[2],
                       port=listen).start()
    print(f"chaos proxy on 127.0.0.1:{proxy.port} -> "
          f"127.0.0.1:{upstream_port} ({sys.argv[2]})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        proxy.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
