#!/usr/bin/env python3
"""Kill-resume drill: the `make durability-selftest` gate (ISSUE 18).

SIGKILLs a REAL spawned ``sort_server`` mid-external-sort and proves
the journaled spill manifest turns the crash into a checkpoint:

1. server 1 runs with an armed ``merge_stall`` fault (30 s): an
   over-``SORT_SERVE_MAX_BYTES`` request streams to the spill tier,
   every partition run is spilled AND committed to the dataset's
   ``.mfst`` journal, then the merge phase wedges on the stall;
2. the parent watches the journal until ALL expected run lines are
   durable, then ``SIGKILL -9``s the server — no drain, no atexit,
   the genuine crash shape;
3. server 2 restarts over the same ``SORT_SPILL_DIR`` (no faults) and
   the client RETRIES the same request with the same ``dataset_id``:
   the reply must be bit-identical to ``np.sort`` of the input, its
   plan digest must carry ``resumed: true``, and server 2's trace must
   contain ZERO ``external.run`` spans (the sort phase was skipped
   outright) and at least one ``external.resume`` span;
4. the retired manifest must be gone afterwards — a served dataset
   leaves no journal behind.

Runs TPU-free (plain 1-device CPU backend; the crash lives in the
process lifecycle and the spill directory, not in the device math).
"""

from __future__ import annotations

import json
import shutil
import sys
import threading
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

from serve_load import HOST, Server, log                     # noqa: E402

from mpitest_tpu.serve.client import ServeClient             # noqa: E402
from mpitest_tpu.store.external import spill_chunk_elems     # noqa: E402

#: Stable client-chosen dataset id — reusing it on the retry is what
#: keys the resume.
DATASET = "drill1"

#: Keys in the request: 800 kB of int32, far over the 64 kB admission
#: budget below, so the request routes to the spill tier.
N = 200_000

#: External-sort memory budget: 13 spill runs for N int32 keys — under
#: the default merge fan-in (16), so the merge is a single pass and the
#: armed stall wedges it with every run already committed.
BUDGET = 1 << 18

#: The armed merge stall (ms): long enough for the parent to observe
#: the fully-committed journal and deliver the SIGKILL.
STALL_MS = 30_000

results: list[tuple[str, bool, str]] = []


def cell(name: str, ok: bool, detail: str) -> None:
    results.append((name, ok, detail))
    print(f"  {'ok ' if ok else 'BAD'} {name:<38} {detail}", flush=True)


def journal_run_lines(mpath: Path) -> int:
    """Committed ``run`` lines in the manifest journal (the torn tail a
    concurrent append may leave parses as garbage and is skipped, same
    as the loader's contract)."""
    try:
        raw = mpath.read_bytes()
    except OSError:
        return 0
    n = 0
    for ln in raw.split(b"\n"):
        try:
            row = json.loads(ln)
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(row, dict) and row.get("kind") == "run":
            n += 1
    return n


def span_counts(trace: Path) -> dict[str, int]:
    counts: dict[str, int] = {}
    try:
        lines = trace.read_text().splitlines()
    except OSError:
        return counts
    for ln in lines:
        try:
            row = json.loads(ln)
        except json.JSONDecodeError:
            continue
        name = row.get("name")
        if isinstance(name, str):
            counts[name] = counts.get(name, 0) + 1
    return counts


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/mpitest_durability_selftest")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    spill = out / "spill"
    shutil.rmtree(spill, ignore_errors=True)
    spill.mkdir(parents=True)

    rng = np.random.default_rng(args.seed)
    x = rng.integers(-2**31, 2**31 - 1, size=N, dtype=np.int32)
    ref = np.sort(x)
    chunk = spill_chunk_elems(BUDGET, x.dtype, 0)
    n_runs = -(-N // chunk)
    mpath = spill / f"{DATASET}.mfst"
    env_common = {
        "SORT_SERVE_MAX_BYTES": str(64 * 1024),
        "SORT_MEM_BUDGET": str(BUDGET),
        "SORT_SPILL_DIR": str(spill),
        "SORT_RESUME": "auto",
        "SORT_SERVE_BATCH_WINDOW_MS": "0",
        # ISSUE 20: the whole kill/resume drill runs over COMPRESSED
        # (SORTRUN2) runs — crash durability must hold for the new
        # framing, including cross-process resume of .runz journals
        "SORT_SPILL_COMPRESS": "on",
    }

    print(f"kill-resume drill: {N} int32 keys -> {n_runs} journaled "
          f"runs, SIGKILL at the merge stall, restart, retry")

    # ---- phase 1: the victim server, merge wedged ---------------
    srv1 = Server(out, "durability1", {
        **env_common,
        "SORT_FAULTS": "merge_stall",
        "SORT_FAULT_STALL_MS": str(STALL_MS),
        # the stall is ARMED, not a pathology: keep the watchdog from
        # tripping (and dumping flight artifacts) while it holds
        "SORT_SERVE_DISPATCH_TIMEOUT_S": "120",
    })
    victim: dict = {}

    def send_victim() -> None:
        try:
            with ServeClient(HOST, srv1.port, timeout=120) as c:
                victim["reply"] = c.sort(x, dataset_id=DATASET)
        except (OSError, ConnectionError) as e:
            victim["exc"] = e

    t = threading.Thread(target=send_victim, daemon=True)
    t.start()
    deadline = time.monotonic() + 150.0
    committed = 0
    while time.monotonic() < deadline:
        committed = journal_run_lines(mpath)
        if committed >= n_runs:
            break
        if srv1.proc.poll() is not None:
            break
        time.sleep(0.1)
    cell("all runs journaled before kill", committed >= n_runs,
         f"{committed}/{n_runs} run lines in {mpath.name}")

    # SIGKILL, not SIGTERM: no drain, no finally blocks, no atexit —
    # the journal on disk is everything the restart gets
    srv1.proc.kill()
    srv1.proc.wait(timeout=30)
    srv1._stderr_f.close()
    t.join(timeout=30)
    died = "exc" in victim or not victim.get("reply", None)
    cell("victim request died with the server", died,
         f"client saw {type(victim.get('exc')).__name__}"
         if "exc" in victim else f"reply={victim.get('reply')!r}")
    cell("journal survives the crash", mpath.exists(),
         f"{mpath.name} present with {journal_run_lines(mpath)} runs")

    # ---- phase 2: restart + retry = resume ----------------------
    srv2 = Server(out, "durability2", env_common)
    try:
        with ServeClient(HOST, srv2.port, timeout=300) as c:
            r = c.sort(x, dataset_id=DATASET)
        ok_bits = bool(r.ok and np.array_equal(r.arr, ref))
        cell("retried reply bit-identical", ok_bits,
             "np.array_equal vs np.sort" if ok_bits
             else f"ok={r.ok} error={getattr(r, 'error', None)}")
        plan = r.plan or {}
        cell("plan digest says resumed", plan.get("resumed") is True,
             f"plan.resumed={plan.get('resumed')!r}")
    finally:
        rc = srv2.stop()
    cell("restarted server drains clean", rc == 0, f"rc={rc}")

    spans = span_counts(srv2.trace)
    cell("sort phase skipped on resume",
         spans.get("external.run", 0) == 0,
         f"external.run spans={spans.get('external.run', 0)} "
         f"(every chunk came from the journal)")
    cell("manifest replayed", spans.get("external.resume", 0) >= 1,
         f"external.resume spans={spans.get('external.resume', 0)}")
    cell("manifest retired after success", not mpath.exists(),
         f"{mpath.name} {'still present' if mpath.exists() else 'gone'}")

    n_bad = sum(1 for _n, ok, _d in results if not ok)
    print(f"\ndurability-selftest: {len(results) - n_bad}/"
          f"{len(results)} cells clean ({n_bad} failing)")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
