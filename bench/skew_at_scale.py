#!/usr/bin/env python3
"""At-scale skew measurement (VERDICT r3 #2) — the single-chip proxy for
BASELINE row 5 (Zipf 2^30 int64 on v5e-16).

Two modes, each fitting a ~9-minute chip budget per invocation:

* default (``--chip``): device-resident int64 keys at 2^27 (or
  ``SKEW_LOG2N``) for uniform / Zipf(1.1) / Zipf(1.5), through the
  public ``sort(algorithm='sample')`` path, bit-exact median verified
  (``np.partition`` — O(n), no full host sort), timed like ``bench.py``
  (warm + repeats, forced scalar sync — ``block_until_ready`` is
  advisory over this image's tunnel).  On ONE device the sample
  algorithm specializes to the fused local sort (no exchange exists to
  skew), so these rows measure what skewed *data* costs the machine at
  scale; the routing/sniff behavior at the same key counts is the
  second mode's job.
* ``--mesh-counters``: 8-device virtual CPU mesh, device-resident
  Zipf int64 at ``SKEW_MESH_LOG2N`` (default 2^24): ASSERTS the
  at-scale contract VERDICT r3 #2 names — Zipf(1.5) reroutes via the
  on-device sniff (``sample_skew_fallback == 1``) with ZERO failed
  exchange rounds (``exchange_retries == 0``), Zipf(1.1) stays on the
  sample path (fallback 0) with a bounded cap — and verifies the full
  sorted output.  (The reference's corresponding failure mode is the
  silent bucket overflow, ``mpi_sample_sort.c:140-144``.)

Each config appends one JSONL row to ``bench/BASELINE_RESULTS.jsonl``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def _append(row: dict) -> None:
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")


def _dists(n: int):
    from mpitest_tpu.utils.io import generate_uniform, generate_zipf

    return {
        "uniform": lambda: generate_uniform(n, np.int64, seed=1),
        "zipf11": lambda: generate_zipf(n, a=1.1, dtype=np.int64, seed=1),
        "zipf15": lambda: generate_zipf(n, a=1.5, dtype=np.int64, seed=1),
    }


def chip_rows() -> int:
    import jax

    jax.config.update("jax_enable_x64", True)  # device-resident int64
    from mpitest_tpu.models.api import checked_device_put, sort
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.utils.trace import Tracer

    if jax.default_backend() == "cpu":
        print("skew_at_scale --chip: no TPU attached", flush=True)
        return 2
    from mpitest_tpu.utils import knobs

    log2n = knobs.get("SKEW_LOG2N")
    repeats = knobs.get("SKEW_REPEATS")
    # Resumability (verify skill: budget chip jobs <= ~9 min): a degraded
    # tunnel can eat a whole budget on one 2 GiB ingest — SKEW_DISTS
    # selects a subset so a timed-out sweep continues where it stopped
    # (completed rows are already appended).
    only = knobs.get("SKEW_DISTS")
    sel = set(only) if only else None
    n = 1 << log2n
    mesh = make_mesh()
    for name, gen in _dists(n).items():
        if sel is not None and name not in sel:
            continue
        x = gen()
        k = n // 2 - 1
        want = int(np.partition(x, k)[k])
        print(f"{name} 2^{log2n}: ingesting {x.nbytes >> 20} MiB "
              "(tunnel-speed dependent; see verify skill)", flush=True)
        t0 = time.perf_counter()
        x_dev = checked_device_put(x, mesh.devices.flat[0])
        x_dev.block_until_ready()
        jax.device_get(x_dev[-1:])  # the transfer is lazy until synced
        print(f"{name} 2^{log2n}: ingest {time.perf_counter() - t0:.1f}s",
              flush=True)
        tracer = Tracer()
        r = sort(x_dev, algorithm="sample", mesh=mesh, return_result=True,
                 tracer=tracer)  # warm: compile + cap settle
        got = int(r.median_probe_raw())
        ok = got == want
        del r
        times = []
        for i in range(repeats):
            tr = Tracer()
            t0 = time.perf_counter()
            r = sort(x_dev, algorithm="sample", mesh=mesh, return_result=True,
                     tracer=tr)
            jax.device_get(r.words[0][-1:])  # forced sync (tunnel)
            times.append(time.perf_counter() - t0)
            del r
            tracer = tr
            print(f"  run {i}: {times[-1]:.3f}s = {n/times[-1]/1e6:.1f} Mkeys/s",
                  flush=True)
        mkeys = n / min(times) / 1e6
        row = {
            "ts": time.time(),
            "config": f"tpu_sample_{name}_int64_2e{log2n}_device_resident",
            "metric": "mkeys_per_s", "value": round(mkeys, 1),
            "median_ok": ok, "span": "device_resident",
            "counters": dict(tracer.counters),
        }
        _append(row)
        print(f"{name} 2^{log2n}: {mkeys:.1f} Mkeys/s, median "
              f"{'OK' if ok else 'MISMATCH'}, counters {dict(tracer.counters)}",
              flush=True)
        if not ok:
            return 1
    return 0


def mesh_counters() -> int:
    from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices

    ensure_virtual_cpu_devices(8)
    import jax

    jax.config.update("jax_enable_x64", True)
    from mpitest_tpu.models.api import checked_device_put, sort
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.utils.trace import Tracer

    from mpitest_tpu.utils import knobs

    log2n = knobs.get("SKEW_MESH_LOG2N")
    n = 1 << log2n
    mesh = make_mesh(8)
    expect = {"zipf11": 0, "zipf15": 1}  # sample_skew_fallback per dist
    rc = 0
    for name, gen in _dists(n).items():
        if name not in expect:
            continue
        x = gen()
        x_dev = checked_device_put(x, jax.devices()[0])  # device-resident input
        tracer = Tracer()
        t0 = time.perf_counter()
        got = sort(x_dev, algorithm="sample", mesh=mesh, tracer=tracer)
        wall = time.perf_counter() - t0
        correct = bool(np.array_equal(got, np.sort(x)))
        fb = tracer.counters.get("sample_skew_fallback", 0)
        retries = tracer.counters.get("exchange_retries", 0)
        ok = correct and fb == expect[name] and retries == 0
        rc |= 0 if ok else 1
        row = {
            "ts": time.time(),
            "config": f"mesh8_sample_{name}_int64_2e{log2n}_device_resident",
            "wall_s": round(wall, 2), "correct": correct,
            "sample_skew_fallback": fb, "exchange_retries": retries,
            "expected_fallback": expect[name], "ok": ok,
        }
        _append(row)
        print(f"{name} 2^{log2n} on mesh8: sorted={correct} fallback={fb} "
              f"(expect {expect[name]}) retries={retries} wall={wall:.1f}s "
              f"-> {'OK' if ok else 'FAIL'}", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(mesh_counters() if "--mesh-counters" in sys.argv else chip_rows())
