"""Out-of-core gate: the `make external-selftest` matrix (ISSUE 15).

Proves the external-sort subsystem end to end on any image, with the
memory budget forced FAR below the dataset so the whole spill/merge
machinery actually runs:

1. **budget gate** — a dataset >= 4x the forced ``SORT_MEM_BUDGET``
   externally sorts BIT-IDENTICAL to the in-memory supervised sort
   (and ``np.sort``), across >= 4 spill runs; a second cell forces a
   small ``SORT_MERGE_FANIN`` so the multi-pass (intermediate-run)
   merge path is exercised too.
2. **record gate** — key+payload sorts (the in-memory argsort-gather
   AND the external spill path) bit-identical to the numpy
   ``argsort(kind="stable")`` gather oracle across every codec dtype.
3. **fault cells** — ``spill_corrupt`` and ``merge_drop`` each fire
   once and must recover verified (blamed run re-spilled / merge
   re-ran; result still exact, ``recoveries`` recorded); a persistent
   ``spill_corrupt:inf`` must exhaust the recovery budget into a typed
   ``SortIntegrityError`` — never silent wrong bytes.
4. **serve gate** — a spawned ``sort_server`` with a tiny admission
   byte bound: a ``payload_bytes`` record request round-trips
   bit-identical, and an over-budget request succeeds THROUGH the
   spill tier (``spilled: true`` in the reply + plan digest) instead
   of the old typed ``bytes`` rejection — each reply bit-identical to
   the solo in-memory oracle.

``--row`` instead emits the scale-gated bench row
(``external_sort_mkeys_per_s``: spill+merge throughput, run count,
disk bytes) for ``bench.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SORT_RETRY_BACKOFF", "0")

import numpy as np  # noqa: E402

#: Gitignored checkout-scoped staging (never a shared /tmp path).
SPILL_DIR = REPO / "bench" / ".spill-out" / "selftest"

#: Forced budget + dataset sizing: the dataset is >= 4x the budget by
#: construction (the acceptance floor; measured ratio asserted below).
BUDGET = 1 << 18
N_KEYS = (4 * BUDGET) // 4          # int32 → dataset bytes = 4x budget

FAIL = 0


def check(name: str, ok: bool, detail: str = "") -> None:
    global FAIL
    if not ok:
        FAIL += 1
    print(f"  {'ok ' if ok else 'BAD'} {name:<46} {detail}", flush=True)


def lib_legs() -> None:
    from mpitest_tpu import faults
    from mpitest_tpu.models import records
    from mpitest_tpu.models.api import sort as api_sort
    from mpitest_tpu.models.supervisor import SortIntegrityError
    from mpitest_tpu.store import external
    from mpitest_tpu.utils.trace import Tracer

    rng = np.random.default_rng(15)
    x = rng.integers(-(2**31), 2**31 - 1, size=N_KEYS, dtype=np.int32)
    assert x.nbytes >= 4 * BUDGET
    ref = np.sort(x)

    print(f"budget gate: {x.nbytes} B dataset under a "
          f"{BUDGET} B budget ({x.nbytes / BUDGET:.1f}x)")
    t0 = time.perf_counter()
    tr = Tracer()
    res = external.external_sort(x, budget=BUDGET,
                                 spill_dir=str(SPILL_DIR / "keys"),
                                 tracer=tr)
    dt = time.perf_counter() - t0
    inmem = api_sort(x)
    check("external == in-memory == np.sort",
          bool(np.array_equal(res.keys, inmem)
               and np.array_equal(res.keys, ref)),
          f"runs={res.runs} disk={res.disk_bytes}B "
          f"{x.size / dt / 1e6:.1f} Mkeys/s")
    check("spilled across >= 4 runs", res.runs >= 4,
          f"runs={res.runs}")

    res2 = external.external_sort(x, budget=BUDGET, fanin=4,
                                  spill_dir=str(SPILL_DIR / "fanin"))
    check("multi-pass merge (fanin=4) bit-identical",
          bool(np.array_equal(res2.keys, ref)
               and res2.merge_passes >= 2),
          f"passes={res2.merge_passes}")

    print("record gate: key+payload vs numpy stable argsort-gather")
    for dt_name in ("int32", "uint32", "int64", "uint64",
                    "float32", "float64"):
        dt_ = np.dtype(dt_name)
        n = 40_000
        if dt_.kind == "f":
            keys = (rng.standard_normal(n) * 10.0
                    ** rng.integers(-20, 20, n)).astype(dt_)
        else:
            info = np.iinfo(dt_)
            keys = rng.integers(info.min, info.max, n, dtype=dt_)
        pay = rng.integers(0, 256, (n, 7), dtype=np.uint8)
        order = np.argsort(keys, kind="stable")
        sk, sp = records.sort_records(keys, pay)
        check(f"records in-memory [{dt_name}]",
              bool(np.array_equal(sk, keys[order])
                   and np.array_equal(sp, pay[order])))
        rese = external.external_sort(
            keys, pay, budget=BUDGET // 4,
            spill_dir=str(SPILL_DIR / f"rec_{dt_name}"))
        check(f"records external  [{dt_name}]",
              bool(np.array_equal(rese.keys, keys[order])
                   and np.array_equal(rese.payload, pay[order])),
              f"runs={rese.runs}")

    print("fault cells: recover-verified-or-fail-loudly")
    for site in ("spill_corrupt", "merge_drop"):
        reg = faults.FaultRegistry(site, seed=7)
        faults.install(reg)
        tr = Tracer()
        try:
            res = external.external_sort(
                x, budget=BUDGET, tracer=tr,
                spill_dir=str(SPILL_DIR / f"fault_{site}"))
            check(f"{site} x1 recovered",
                  bool(np.array_equal(res.keys, ref)
                       and reg.injected > 0 and res.recoveries > 0),
                  f"injected={reg.injected} "
                  f"recoveries={res.recoveries}")
        except SortIntegrityError as e:
            check(f"{site} x1 recovered", False,
                  f"typed error on a one-shot fault: {e}")
        finally:
            faults.install(None)

    reg = faults.FaultRegistry("spill_corrupt:inf", seed=7)
    faults.install(reg)
    try:
        external.external_sort(x, budget=BUDGET,
                               spill_dir=str(SPILL_DIR / "fault_inf"))
        check("spill_corrupt:inf fails typed", False,
              "persistent corruption shipped bytes")
    except SortIntegrityError:
        check("spill_corrupt:inf fails typed", True,
              "SortIntegrityError")
    finally:
        faults.install(None)


def serve_leg() -> None:
    """The acceptance pair (ISSUE 15): payload_bytes round trip + the
    over-budget request served by the spill tier, both bit-identical to
    the solo in-memory oracle."""
    from serve_load import Server

    from mpitest_tpu.serve.client import ServeClient

    out_dir = SPILL_DIR / "serve"
    out_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(16)
    max_bytes = 1 << 16
    srv = Server(out_dir, "external", env_overrides={
        "SORT_SERVE_MAX_BYTES": str(max_bytes),
        "SORT_SERVE_SPILL": "auto",
        "SORT_MEM_BUDGET": str(1 << 15),
        "SORT_SPILL_DIR": str(out_dir / "spill"),
        "SORT_SERVE_BATCH_WINDOW_MS": "0",
        "SORT_METRICS_PORT": "-1",
    })
    try:
        print("serve gate: payload_bytes + spill tier")
        with ServeClient("127.0.0.1", srv.port, timeout=300.0) as c:
            n = 2000
            keys = rng.integers(-(2**31), 2**31 - 1, n, dtype=np.int32)
            pay = rng.integers(0, 256, (n, 8), dtype=np.uint8)
            order = np.argsort(keys, kind="stable")
            rep = c.sort(keys, payload=pay)
            check("payload_bytes round trip",
                  bool(rep.ok and np.array_equal(rep.arr, keys[order])
                       and np.array_equal(rep.payload, pay[order])),
                  f"spilled={rep.spilled}")

            big = rng.integers(-(2**31), 2**31 - 1, 50_000,
                               dtype=np.int32)
            assert big.nbytes > max_bytes
            rep = c.sort(big)
            check("over-budget request via spill tier",
                  bool(rep.ok and rep.spilled
                       and np.array_equal(rep.arr, np.sort(big))),
                  f"plan={rep.plan}")

            nbig = 30_000
            bigk = rng.integers(-(2**31), 2**31 - 1, nbig,
                                dtype=np.int32)
            bigp = rng.integers(0, 256, (nbig, 8), dtype=np.uint8)
            order = np.argsort(bigk, kind="stable")
            rep = c.sort(bigk, payload=bigp)
            check("over-budget RECORD request via spill tier",
                  bool(rep.ok and rep.spilled
                       and np.array_equal(rep.arr, bigk[order])
                       and np.array_equal(rep.payload, bigp[order])),
                  f"spilled={rep.spilled}")
    finally:
        srv.stop()


def row_main() -> int:
    """Emit the bench row: spill+merge throughput on a dataset 4x the
    forced budget, output verified in-process before the row prints."""
    from mpitest_tpu.store import external
    from mpitest_tpu.utils import knobs

    # ISSUE 17: the merge rounds' order computation is engine-knobbed
    # (store/merge._order_for); the row says which engine ran so the
    # trajectory column can attribute a throughput move to an engine
    # flip.  Measured rows pin the host path.
    os.environ.setdefault("SORT_LOCAL_ENGINE", "lax")

    rng = np.random.default_rng(17)
    x = rng.integers(-(2**31), 2**31 - 1, size=N_KEYS, dtype=np.int32)
    spill = SPILL_DIR / "row"
    # warm the compile caches so the row times spill+merge, not XLA
    external.external_sort(x[: N_KEYS // 4], budget=BUDGET // 4,
                           spill_dir=str(spill))
    t0 = time.perf_counter()
    res = external.external_sort(x, budget=BUDGET,
                                 spill_dir=str(spill))
    dt = time.perf_counter() - t0
    if not np.array_equal(res.keys, np.sort(x)):
        print("external row: WRONG RESULT", file=sys.stderr)
        return 1
    print(json.dumps({
        "metric": "external_sort_mkeys_per_s",
        "value": round(x.size / dt / 1e6, 3),
        "unit": "Mkeys/s",
        "n": int(x.size), "dtype": "int32",
        "budget_bytes": BUDGET,
        "dataset_x_budget": round(x.nbytes / BUDGET, 2),
        "runs": res.runs, "disk_bytes": res.disk_bytes,
        "merge_passes": res.merge_passes,
        "wall_s": round(dt, 4),
        "local_engine": str(knobs.get("SORT_LOCAL_ENGINE")),
        # ISSUE 20: compression + async-IO trajectory fields (rows
        # from older rounds lack them and render "-")
        "spill_ratio": round(res.spill_ratio, 3),
        "disk_overlap": round(res.disk_overlap, 3),
        "spill_compress": str(knobs.get("SORT_SPILL_COMPRESS")),
    }))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--row", action="store_true",
                    help="emit the bench JSONL row instead of the gate")
    args = ap.parse_args()
    if SPILL_DIR.exists():
        shutil.rmtree(SPILL_DIR)
    SPILL_DIR.mkdir(parents=True, exist_ok=True)
    try:
        if args.row:
            return row_main()
        lib_legs()
        serve_leg()
    finally:
        shutil.rmtree(SPILL_DIR, ignore_errors=True)
    print(f"\nexternal-selftest: "
          f"{'CLEAN' if FAIL == 0 else f'{FAIL} BAD cell(s)'}")
    return 1 if FAIL else 0


if __name__ == "__main__":
    sys.exit(main())
