"""Ingest perf gate (ISSUE 6): native-encode speedup + end-to-end ratio.

Run by ``make ingest-selftest`` (after the CLI/span-overlap leg) with
``SORT_NATIVE_ENCODE=on`` and a virtual CPU mesh in the environment.
Two assertions, both recorded in the ``SORT_METRICS`` sidecar so the
final ``report.py --require-ingest-overlap`` pass can re-check the
ratio gate from the same artifacts:

1. **Engine speedup** — the native engine's chunk-encode throughput
   (encode + min/max + pad-key + fingerprint fold, the whole stage) must
   be >= 2x the Python engine's on THIS host, measured back to back on
   identical chunks (best-of-N each, same buffer, warm cache).
2. **End-to-end ratio** — ``sort_incl_ingest_mkeys_per_s >= 0.5 x
   sort_mkeys_per_s`` at the selftest scale: one measured run of
   streamed-ingest-plus-sort against the best warm sort on pre-staged
   words (the ISSUE 6 acceptance shape of ROADMAP item 4's 2x-gap
   target, on whatever hardware runs the gate).

Exit 0 with both gates green; exit 1 with a named failure otherwise.
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from mpitest_tpu.ops.keys import codec_for          # noqa: E402
from mpitest_tpu.utils import knobs, native_encode  # noqa: E402
from mpitest_tpu.utils.io import open_keys_mmap     # noqa: E402

from mpitest_tpu.report import INGEST_RATIO_GATE  # noqa: E402

#: Gate thresholds (ISSUE 6 acceptance).  The ratio gate constant lives
#: in report.py — `--require-ingest-overlap` re-checks the same value
#: from the recorded metrics.
MIN_ENCODE_SPEEDUP = 2.0
MIN_INGEST_RATIO = INGEST_RATIO_GATE

#: A/B measurement shape: enough chunks to stream (and to amortize the
#: per-call ctypes/alloc overhead), best-of to damp the shared-CI-runner
#: jitter this image is known for.
AB_CHUNK_ELEMS = 1 << 20
AB_REPEATS = 5


def log(msg: str) -> None:
    print(msg, flush=True)


def measure_engine(x: np.ndarray, eng: str) -> float:
    """Best-of-N seconds for the full chunk-encode stage over ``x``."""
    codec = codec_for(x.dtype)
    best = float("inf")
    for _ in range(AB_REPEATS):
        t0 = time.perf_counter()
        for off in range(0, x.size, AB_CHUNK_ELEMS):
            native_encode.encode_and_fold(
                x[off:off + AB_CHUNK_ELEMS], codec, True, eng)
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    if len(sys.argv) != 2:
        print(f"Usage: {sys.argv[0]} <keys.bin>", file=sys.stderr)
        return 2
    # forced-on contract: a missing library must fail HERE, loudly
    eng = native_encode.engine()
    if eng != "native":
        print(f"[FAIL] native engine not active (engine={eng}); "
              "run via `make ingest-selftest`", file=sys.stderr)
        return 1

    mm = open_keys_mmap(sys.argv[1])
    x = np.array(mm)  # in-memory copy for the cache-warm A/B
    n = int(x.size)

    from mpitest_tpu.utils.metrics import Metrics

    metrics = Metrics(config={"selftest": "ingest", "n": n,
                              "dtype": str(x.dtype)})

    # ---- gate 1: native >= 2x python on the chunk-encode stage
    py_s = measure_engine(x, "python")
    nat_s = measure_engine(x, "native")
    py_gbs = x.nbytes / py_s / 1e9
    nat_gbs = x.nbytes / nat_s / 1e9
    speedup = py_s / nat_s
    log(f"encode A/B ({n} {x.dtype} keys, chunk {AB_CHUNK_ELEMS}): "
        f"python {py_gbs:.2f} GB/s, native {nat_gbs:.2f} GB/s "
        f"-> {speedup:.2f}x")
    metrics.record("python_encode_gb_per_s", round(py_gbs, 3), "GB/s")
    metrics.record("native_encode_gb_per_s", round(nat_gbs, 3), "GB/s")
    metrics.record("encode_speedup", round(speedup, 3), "x")

    # ---- gate 2: end-to-end ratio on the real pipeline
    from mpitest_tpu.models.api import ingest_to_mesh, sort
    from mpitest_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(4)
    algo = "radix"
    # warmup: compile the SPMD program and settle caches
    staged = ingest_to_mesh(mm, mesh=mesh)
    r = sort(staged, algorithm=algo, mesh=mesh, return_result=True)
    for w in r.words:
        w.block_until_ready()
    del r

    # sort-only numerator source: best warm sort on freshly staged words
    sort_s = float("inf")
    for _ in range(2):
        staged = ingest_to_mesh(mm, mesh=mesh)
        for w in staged.words:
            w.block_until_ready()
        t0 = time.perf_counter()
        r = sort(staged, algorithm=algo, mesh=mesh, return_result=True)
        for w in r.words:
            w.block_until_ready()
        sort_s = min(sort_s, time.perf_counter() - t0)
        del r
    encode_gbs = (staged.stats.host_bytes / staged.stats.encode_s / 1e9
                  if staged.stats.encode_s else 0.0)
    metrics.record("encode_engine", staged.stats.encode_engine)
    metrics.record("encode_gb_per_s", round(encode_gbs, 3), "GB/s")

    # ingest-inclusive: mmap -> streamed ingest -> sort, one wall span
    incl_s = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        staged = ingest_to_mesh(mm, mesh=mesh)
        r = sort(staged, algorithm=algo, mesh=mesh, return_result=True)
        for w in r.words:
            w.block_until_ready()
        incl_s = min(incl_s, time.perf_counter() - t0)
        del r

    sort_mkeys = n / sort_s / 1e6
    incl_mkeys = n / incl_s / 1e6
    ratio = incl_mkeys / sort_mkeys
    log(f"end-to-end: sort {sort_mkeys:.1f} Mkeys/s, "
        f"incl-ingest {incl_mkeys:.1f} Mkeys/s -> ratio {ratio:.3f} "
        f"(engine={staged.stats.encode_engine})")
    metrics.throughput("sort_mkeys_per_s", n, sort_s)
    metrics.throughput("sort_incl_ingest_mkeys_per_s", n, incl_s)
    metrics.record("ingest_ratio", round(ratio, 4), "x")

    metrics_path = knobs.get("SORT_METRICS")
    metrics.dump(metrics_path)

    ok = True
    if speedup < MIN_ENCODE_SPEEDUP:
        print(f"[FAIL] native encode speedup {speedup:.2f}x < "
              f"{MIN_ENCODE_SPEEDUP}x the Python engine", file=sys.stderr)
        ok = False
    if ratio < MIN_INGEST_RATIO:
        print(f"[FAIL] ingest ratio {ratio:.3f} < {MIN_INGEST_RATIO} "
              "(streamed ingest is eating the sort's throughput)",
              file=sys.stderr)
        ok = False
    if ok:
        log(f"ingest selftest OK: encode {speedup:.2f}x (gate "
            f"{MIN_ENCODE_SPEEDUP}x), ratio {ratio:.3f} (gate "
            f"{MIN_INGEST_RATIO})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
