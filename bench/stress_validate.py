"""Final randomized stress validation on the real chip via the public API."""
import numpy as np, sys
sys.path.insert(0, "/root/repo")
import mpitest_tpu

rng = np.random.default_rng(123)
mesh = mpitest_tpu.make_mesh()
fails = 0
cases = []
for trial in range(14):
    n = int(rng.integers(1, 3_000_000))
    dtype = rng.choice([np.int32, np.uint32, np.int64, np.uint64, np.float32, np.float64])
    algo = rng.choice(["radix", "sample"])
    dt = np.dtype(dtype)
    if dt.kind == "f":
        x = (rng.standard_normal(n) * 10**rng.integers(0, 30)).astype(dt)
    else:
        info = np.iinfo(dt)
        span = rng.choice(["full", "narrow"])
        if span == "full":
            x = rng.integers(info.min, info.max, n, dtype=dt, endpoint=True)
        else:
            x = rng.integers(0, 1000, n).astype(dt)
    got = mpitest_tpu.sort(x, algorithm=str(algo), mesh=mesh)
    ok = np.array_equal(got, np.sort(x))
    cases.append((n, dt.name, str(algo), ok))
    if not ok:
        fails += 1
        print("FAIL", cases[-1])
print(f"{len(cases)-fails}/{len(cases)} stress cases OK")
