"""Final randomized stress validation on the real chip via the public API."""
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import mpitest_tpu
from mpitest_tpu.utils.io import generate

def randomized_api_battery() -> None:
    rng = np.random.default_rng(123)
    mesh = mpitest_tpu.make_mesh()
    fails = 0
    cases = []
    for trial in range(14):
        n = int(rng.integers(1, 3_000_000))
        dtype = rng.choice([np.int32, np.uint32, np.int64, np.uint64,
                            np.float32, np.float64])
        algo = rng.choice(["radix", "sample"])
        dt = np.dtype(dtype)
        if dt.kind != "f" and rng.choice(["full", "narrow"]) == "narrow":
            x = rng.integers(0, 1000, n).astype(dt)  # heavy-duplication span
        else:
            x = generate("uniform", n, dt, seed=int(rng.integers(2**31)))
        got = mpitest_tpu.sort(x, algorithm=str(algo), mesh=mesh)
        ok = np.array_equal(got, np.sort(x))
        cases.append((n, dt.name, str(algo), ok))
        if not ok:
            fails += 1
            print("FAIL", cases[-1])
    print(f"{len(cases)-fails}/{len(cases)} stress cases OK")


def adversarial_patterns_at_scale(log2n: int = 28) -> None:
    """Extreme input patterns at full scale, verified ON DEVICE (sorted +
    sum/xor multiset invariants) — result download over the tunnel would
    dominate otherwise.  Catches scale-dependent kernel bugs the
    small-shape interpret tests cannot."""
    import jax
    import jax.numpy as jnp

    from mpitest_tpu.ops import bitonic

    n = 1 << log2n

    @jax.jit
    def sort_and_check(v):
        out = bitonic.sort_padded(v, n, bitonic.BLOCK_LOG2)
        is_sorted = jnp.all(out[1:] >= out[:-1])
        sum_ok = v.sum() == out.sum()
        xor = lambda a: jax.lax.reduce(a, jnp.uint32(0),  # sortlint: disable=SL010 -- single-device jit checksum, no SPMD partitioner
                                       jax.lax.bitwise_xor, (0,))
        return is_sorted, sum_ok, xor(v) == xor(out)

    r = np.random.default_rng(0)
    pats = {
        "sorted": np.arange(n, dtype=np.uint32),
        "reverse": np.arange(n, 0, -1).astype(np.uint32),
        "all-equal": np.full(n, 0xABCD1234, np.uint32),
        "few-distinct": r.integers(0, 3, n).astype(np.uint32),
        "organ-pipe": np.concatenate([
            np.arange(n // 2, dtype=np.uint32),
            np.arange(n // 2, 0, -1).astype(np.uint32)]),
    }
    for name, x in pats.items():
        checks = [bool(t) for t in
                  jax.device_get(sort_and_check(jnp.asarray(x)))]
        assert all(checks), (name, checks)
        print(f"adversarial {name} @2^{log2n}: OK")


def adversarial_patterns_64(log2n: int = 26) -> None:
    """At-scale int64 battery through the PUBLIC API on the pair engine
    (round 4): one pattern per adaptive route — pair engine, both
    constant-word shortcuts, the duplication-sniff reroute, and the
    residual on-device fallback (runs the sniff cannot see) — each
    verified ON DEVICE (lexicographic sortedness of the word planes +
    per-word sum/xor multiset invariants vs the encoded input; results
    never cross the tunnel).  Asserts the tracer recorded the expected
    engine route, so a silent routing regression fails loudly.

    ``STRESS64_PATTERNS=a,b`` selects a subset (resumable under a
    degraded tunnel); ``STRESS64_LOG2N`` overrides the size.
    """
    import os

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)
    from mpitest_tpu.models.api import checked_device_put
    from mpitest_tpu.ops.keys import codec_for
    from mpitest_tpu.utils.trace import Tracer

    from mpitest_tpu.utils import knobs

    log2n = knobs.get("STRESS64_LOG2N") or log2n
    n = 1 << log2n
    r = np.random.default_rng(5)
    codec = codec_for(np.int64)

    def runs_of(length):
        # runs of `length` equal-hi keys over ~n/length distinct hi
        # values: far too many distinct values for the 1024-key sniff
        # to see.  At length <= 16 the in-VMEM fix-up (round-5 mid-tier,
        # bench/fixdepth_probe.py) handles them with NO fallback; above
        # it the residual flag MUST fire and the on-device lax fallback
        # must produce exact bytes.
        def gen():
            # DISTINCT hi values (odd-multiplier hash of arange is
            # injective mod 2^31): drawing n/16 values with replacement
            # from 2^31 yields ~n^2/2^37 birthday collisions, each
            # merging two runs into one of 2*length — which legitimately
            # exceeds the fix depth and made the expected route flaky.
            k = n // length + 1
            hi = ((np.arange(k, dtype=np.int64) * 2654435761) % (2**31))
            hi = np.repeat(hi, length)[:n]
            x = (hi << 32) | r.integers(0, 2**32, n).astype(np.int64)
            r.shuffle(x)
            return x
        return gen

    pats = {
        # name: (generator, accepted engine routes)
        "uniform": (lambda: r.integers(-(2**63), 2**63 - 1, n,
                                       dtype=np.int64),
                    {"bitonic_pair"}),
        "narrow-hi": (lambda: r.integers(0, 2**31, n, dtype=np.int64),
                      {"bitonic_1w1"}),
        "wide-lo-const": (lambda: (n - 1 - np.arange(n, dtype=np.int64)) << 37,
                          {"bitonic_1w0"}),
        "all-equal": (lambda: np.full(n, -42, np.int64), {"constant"}),
        # hi from 8 values: the sniff must catch it and reroute
        "hi-dup8": (lambda: (r.integers(0, 8, n).astype(np.int64) << 33)
                    | r.integers(0, 2**32, n).astype(np.int64), {"lax"}),
        # covered by the 16-pass in-VMEM fix-up: no residual fallback
        # (r5).  The 1024-key sniff still has ~11% odds at 2^26 of
        # sampling two members of one run and rerouting up front —
        # 'lax' is a correct (if pessimistic) route, like mid-runs24.
        "mid-runs16": (runs_of(16), {"bitonic_pair", "lax"}),
        # sniff usually misses (residual fallback); a lucky sample
        # collision may reroute up front — both are correct routes
        "mid-runs24": (runs_of(24), {"bitonic_pair+lax_fallback", "lax"}),
    }
    only = knobs.get("STRESS64_PATTERNS")
    sel = set(only) if only else None

    @jax.jit
    def check(x, hi_o, lo_o):
        hi_i, lo_i = codec.encode_jax(x)
        asc = (hi_o[1:] > hi_o[:-1]) | ((hi_o[1:] == hi_o[:-1])
                                        & (lo_o[1:] >= lo_o[:-1]))
        xor = lambda a: jax.lax.reduce(a, jnp.uint32(0),  # sortlint: disable=SL010 -- single-device jit checksum, no SPMD partitioner
                                       jax.lax.bitwise_xor, (0,))
        return (jnp.all(asc),
                (hi_i.sum() == hi_o.sum()) & (lo_i.sum() == lo_o.sum()),
                (xor(hi_i) == xor(hi_o)) & (xor(lo_i) == xor(lo_o)))

    for name, (gen, routes) in pats.items():
        if sel is not None and name not in sel:
            continue
        x = gen()
        dev = checked_device_put(x, jax.devices()[0])
        jax.device_get(dev[-1:])  # materialize the (lazy) ingest
        tracer = Tracer()
        res = mpitest_tpu.sort(dev, algorithm="radix", return_result=True,
                               tracer=tracer)
        hi_o, lo_o = res.words
        checks = [bool(t) for t in jax.device_get(check(dev, hi_o, lo_o))]
        route = tracer.counters.get("local_engine")
        ok = all(checks) and route in routes
        print(f"int64 {name} @2^{log2n}: "
              f"{'OK' if ok else f'FAIL {checks}'} route={route}"
              f"{'' if route in routes else f' (expected {sorted(routes)})'}",
              flush=True)
        assert ok, (name, checks, route)
        del res, hi_o, lo_o, dev


if __name__ == "__main__":
    # `--patterns` runs ONLY the at-scale adversarial battery; \
    # `--patterns64` the int64 pair-engine battery (each mode alone
    # fits a 10-minute chip budget); default = the randomized
    # cross-dtype API battery.
    if "--patterns64" in sys.argv:
        adversarial_patterns_64()
    elif "--patterns" in sys.argv:
        adversarial_patterns_at_scale()
    else:
        randomized_api_battery()
