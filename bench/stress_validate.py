"""Final randomized stress validation on the real chip via the public API."""
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import mpitest_tpu
from mpitest_tpu.utils.io import generate

def randomized_api_battery() -> None:
    rng = np.random.default_rng(123)
    mesh = mpitest_tpu.make_mesh()
    fails = 0
    cases = []
    for trial in range(14):
        n = int(rng.integers(1, 3_000_000))
        dtype = rng.choice([np.int32, np.uint32, np.int64, np.uint64,
                            np.float32, np.float64])
        algo = rng.choice(["radix", "sample"])
        dt = np.dtype(dtype)
        if dt.kind != "f" and rng.choice(["full", "narrow"]) == "narrow":
            x = rng.integers(0, 1000, n).astype(dt)  # heavy-duplication span
        else:
            x = generate("uniform", n, dt, seed=int(rng.integers(2**31)))
        got = mpitest_tpu.sort(x, algorithm=str(algo), mesh=mesh)
        ok = np.array_equal(got, np.sort(x))
        cases.append((n, dt.name, str(algo), ok))
        if not ok:
            fails += 1
            print("FAIL", cases[-1])
    print(f"{len(cases)-fails}/{len(cases)} stress cases OK")


def adversarial_patterns_at_scale(log2n: int = 28) -> None:
    """Extreme input patterns at full scale, verified ON DEVICE (sorted +
    sum/xor multiset invariants) — result download over the tunnel would
    dominate otherwise.  Catches scale-dependent kernel bugs the
    small-shape interpret tests cannot."""
    import jax
    import jax.numpy as jnp

    from mpitest_tpu.ops import bitonic

    n = 1 << log2n

    @jax.jit
    def sort_and_check(v):
        out = bitonic.sort_padded(v, n, bitonic.BLOCK_LOG2)
        is_sorted = jnp.all(out[1:] >= out[:-1])
        sum_ok = v.sum() == out.sum()
        xor = lambda a: jax.lax.reduce(a, jnp.uint32(0),
                                       jax.lax.bitwise_xor, (0,))
        return is_sorted, sum_ok, xor(v) == xor(out)

    r = np.random.default_rng(0)
    pats = {
        "sorted": np.arange(n, dtype=np.uint32),
        "reverse": np.arange(n, 0, -1).astype(np.uint32),
        "all-equal": np.full(n, 0xABCD1234, np.uint32),
        "few-distinct": r.integers(0, 3, n).astype(np.uint32),
        "organ-pipe": np.concatenate([
            np.arange(n // 2, dtype=np.uint32),
            np.arange(n // 2, 0, -1).astype(np.uint32)]),
    }
    for name, x in pats.items():
        checks = [bool(t) for t in
                  jax.device_get(sort_and_check(jnp.asarray(x)))]
        assert all(checks), (name, checks)
        print(f"adversarial {name} @2^{log2n}: OK")


if __name__ == "__main__":
    # `--patterns` runs ONLY the at-scale adversarial battery (each mode
    # alone fits a 10-minute chip budget); default = the randomized
    # cross-dtype API battery.
    if "--patterns" in sys.argv:
        adversarial_patterns_at_scale()
    else:
        randomized_api_battery()
