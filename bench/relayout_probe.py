#!/usr/bin/env python3
"""On-chip A/B probe for the round-5 rotation-relayout cross schedule.

VERDICT r4 #1: 56% of the pair network is 36 single cross layers at
~2.5x their streaming floor (3n traffic each: two reads + one write).
The relayout schedule fuses them into 4-member closure visits (2 layers
per n-read + n-write) plus a rotation-aware merge.  This probe measures
build-or-refute on the real chip:

1. Correctness ON DEVICE at 2^26: relayout keys bit-equal to the
   variadic ``lax.sort`` keys; pair multiset preserved (order-invariant
   pairing-sensitive checksum); and the two schedules' key planes
   bit-equal to each other.
2. Slope timings (two rep counts, forced scalar sync — see verify
   skill): relayout network vs round-4 network vs variadic 2-word
   ``lax.sort``, plus the full ``sort_two_words_bitonic`` path.

Resumable: ``PROBE_PARTS=agree,net,1w,full`` (default all),
``PROBE_LOG2N`` (default 26).  Budget one part per invocation if the
tunnel is degraded.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() not in ("tpu", "axon"):
        print("relayout_probe: needs a real TPU", flush=True)
        return 2

    from mpitest_tpu.ops import bitonic, kernels

    from mpitest_tpu.utils import knobs

    log2n = knobs.get("PROBE_LOG2N")
    parts = knobs.get("PROBE_PARTS")
    n = 1 << log2n
    rng = np.random.default_rng(7)
    k = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                    .astype(np.uint32))
    p = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                    .astype(np.uint32))
    B = bitonic.PAIR_BLOCK_LOG2
    row: dict = {"ts": time.time(), "config": f"relayout_probe_2e{log2n}"}
    ok = True

    def cksum(kk, pp):
        """Order-invariant, pairing-sensitive: mixes each pair before
        the commutative reduces."""
        m = (kk * jnp.uint32(2654435761)) ^ pp
        x = jax.lax.reduce(  # sortlint: disable=SL010 -- single-device jit checksum, no SPMD partitioner
            m, jnp.uint32(0), jax.lax.bitwise_xor, (0,))
        return m.sum(), x

    if "agree" in parts:
        @jax.jit
        def agree(kk, pp):
            rk, rp = bitonic.sort_pairs_padded(kk, pp, n, B, relayout=True)
            ok_, op = bitonic.sort_pairs_padded(kk, pp, n, B, relayout=False)
            ref = jax.lax.sort([kk, pp], num_keys=2, is_stable=False)
            s_in, x_in = cksum(kk, pp)
            s_r, x_r = cksum(rk, rp)
            return (jnp.all(rk == ref[0]), jnp.all(rk == ok_),
                    (s_in == s_r) & (x_in == x_r))

        t0 = time.perf_counter()
        vs_lax, vs_old, multiset = (bool(v) for v in
                                    jax.device_get(agree(k, p)))
        print(f"relayout keys==lax: {vs_lax}  ==r4-schedule: {vs_old}  "
              f"pair-multiset: {multiset} "
              f"({time.perf_counter() - t0:.1f}s incl. compile)", flush=True)
        row.update(relayout_keys_match_lax=vs_lax,
                   relayout_keys_match_r4=vs_old,
                   relayout_pair_multiset_ok=multiset)
        ok &= vs_lax and vs_old and multiset

    def slope(fn, args, reps=(1, 3), tries=3):
        out = {}
        for r in reps:
            @jax.jit
            def g(ops, r=r):
                for _ in range(r):
                    ops = fn(*ops)
                return ops
            y = g(args)
            jax.device_get(y[0][:1])
            ts = []
            for _ in range(tries):
                t = time.perf_counter()
                y = g(args)
                jax.device_get(y[0][:1])
                ts.append(time.perf_counter() - t)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0])

    if "net" in parts:
        new_ms = slope(
            lambda kk, pp: bitonic.sort_pairs_padded(kk, pp, n, B,
                                                     relayout=True),
            (k, p)) * 1e3
        print(f"pair network relayout: {new_ms:.1f} ms", flush=True)
        old_ms = slope(
            lambda kk, pp: bitonic.sort_pairs_padded(kk, pp, n, B,
                                                     relayout=False),
            (k, p)) * 1e3
        print(f"pair network r4:       {old_ms:.1f} ms "
              f"(relayout {old_ms / new_ms:.2f}x faster)", flush=True)
        row.update(pair_net_relayout_ms=round(new_ms, 1),
                   pair_net_r4_ms=round(old_ms, 1))

    if "1w" in parts:
        @jax.jit
        def agree1(kk):
            r = bitonic.sort_padded(kk, n, bitonic.BLOCK_LOG2, relayout=True)
            o = bitonic.sort_padded(kk, n, bitonic.BLOCK_LOG2, relayout=False)
            ref = jax.lax.sort([kk], num_keys=1, is_stable=False)[0]
            return jnp.all(r == ref), jnp.all(r == o)

        vs_lax1, vs_old1 = (bool(v) for v in jax.device_get(agree1(k)))
        print(f"1w relayout keys==lax: {vs_lax1}  ==r4-schedule: {vs_old1}",
              flush=True)
        row.update(relayout1w_matches_lax=vs_lax1,
                   relayout1w_matches_r4=vs_old1)
        ok &= vs_lax1 and vs_old1
        new1 = slope(
            lambda kk: (bitonic.sort_padded(kk, n, bitonic.BLOCK_LOG2,
                                            relayout=True),), (k,)) * 1e3
        old1 = slope(
            lambda kk: (bitonic.sort_padded(kk, n, bitonic.BLOCK_LOG2,
                                            relayout=False),), (k,)) * 1e3
        lax1 = slope(
            lambda kk: (jax.lax.sort([kk], num_keys=1, is_stable=False)[0],),
            (k,)) * 1e3
        print(f"1w net relayout {new1:.1f} ms  r4 {old1:.1f} ms  "
              f"lax {lax1:.1f} ms  (vs lax {lax1 / new1:.2f}x)", flush=True)
        row.update(net1w_relayout_ms=round(new1, 1),
                   net1w_r4_ms=round(old1, 1), lax_sort_1w_ms=round(lax1, 1))

    if "full" in parts:
        full_ms = slope(
            lambda kk, pp: kernels.sort_two_words_bitonic(kk, pp)[:2],
            (k, p)) * 1e3
        lax2_ms = slope(
            lambda kk, pp: tuple(jax.lax.sort([kk, pp], num_keys=2,
                                              is_stable=False)),
            (k, p)) * 1e3
        print(f"full pair path: {full_ms:.1f} ms  lax 2w: {lax2_ms:.1f} ms  "
              f"ratio {lax2_ms / full_ms:.2f}x", flush=True)
        row.update(pair_full_ms=round(full_ms, 1),
                   lax_sort_2w_ms=round(lax2_ms, 1),
                   pair_speedup=round(lax2_ms / full_ms, 2))

    row["all_ok"] = ok
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"relayout_probe: {'OK' if ok else 'FAIL'}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
