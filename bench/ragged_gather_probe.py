#!/usr/bin/env python3
"""Ragged-gather DMA probe — the BUILT linear-work-movement experiment
(VERDICT r2 #2: "build, don't model").

BASELINE.md prices every linear-work alternative to the bitonic engine
(DMA-composed radix, one-hot MXU permutation, butterfly splits) from
component measurements plus a "fragmentation law": moving (block, digit)
runs by DMA costs ``n·G/B`` run-copies per pass, each charged a serial
~0.5 us issue cost.  That issue-cost assumption was modeled, never
measured — and it is THE deciding number: any radix/MSD hybrid's merge
phase is "concatenate R variable-length runs into the output in a
permuted order", i.e. exactly this kernel.  If real DMA issue overlaps
(multiple outstanding copies hide the latency), the law's 30 ms/pass
floor collapses and a blocksort+DMA-merge MSD sort could beat the
bitonic engine; if issue serializes, the boundary claim gets its
measured footing.

The kernel (built on the ``segment_pack`` misaligned-copy pattern,
``ops/pallas_kernels.py``): grid over 1024-element output chunks; each
chunk gathers up to K source segments (descriptors precomputed on the
host and streamed per-chunk into SMEM: src base, destination offset in
chunk, length).  All K segment DMAs are STARTED before the first wait,
so within a chunk the copies overlap; Mosaic's grid pipelining overlaps
chunks.  Each segment lands via one aligned 2-tile DMA + a vectorized
roll-shift + mask-blend — no per-element addressing anywhere.
Correctness is asserted against the numpy concatenation on every
configuration before it is timed.

Measured sweep: run lengths 2^13 .. 2^8 at 2^26 elements — spanning the
(G, B) design space of any DMA-composed scheme (run length = B/G).

Usage: python bench/ragged_gather_probe.py [--log2n 26] [--interpret]
"""

from __future__ import annotations

import argparse
import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

LANES = 128
ROWS = 8
CHUNK = ROWS * LANES  # 1024 elements = one output tile


def build_descriptors(run_starts, run_lens, order, nchunk):
    """Host-side: for each output chunk, the (src_base, dst_off, len)
    descriptors covering its slice of the permuted-run concatenation.
    K is sized to the actual maximum segments per chunk — padding slots
    would otherwise inflate the measured per-chunk cost with dummy
    DMA+blend work (round-3 review finding)."""
    import numpy as np

    starts = np.asarray(run_starts)[order]
    lens = np.asarray(run_lens)[order]
    out_off = np.concatenate([[0], np.cumsum(lens)])
    total = int(out_off[-1])
    segs = [[] for _ in range(nchunk)]
    for r in range(len(lens)):
        o, ln = int(out_off[r]), int(lens[r])
        src = int(starts[r])
        while ln > 0:
            c = o // CHUNK
            take = min(ln, (c + 1) * CHUNK - o)
            segs[c].append((src, o - c * CHUNK, take))
            o += take
            src += take
            ln -= take
    assert total % CHUNK == 0
    K = max(len(s) for s in segs)
    desc = np.zeros((nchunk, K, 3), np.int32)
    for c, s in enumerate(segs):
        for k, row in enumerate(s):
            desc[c, k] = row
    return desc, K


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2n", type=int, default=26)
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--platform", default=None,
                    help="cpu forces the virtual-CPU backend (CI)")
    args = ap.parse_args()

    if args.platform == "cpu":
        from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices

        ensure_virtual_cpu_devices(1)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpitest_tpu.utils.metrics import Metrics

    n = 1 << args.log2n
    nchunk = n // CHUNK

    def gather_kernel(K, desc_ref, data_ref, out_ref, scratch, sem):
        elem = (jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 1))
        for k in range(K):  # issue ALL segment DMAs up front: overlap
            src = desc_ref[0, k, 0]
            arow = pl.multiple_of(((src // LANES) // ROWS) * ROWS, ROWS)
            pltpu.make_async_copy(
                data_ref.at[pl.ds(arow, 2 * ROWS), :], scratch.at[k], sem.at[k]
            ).start()
        acc = jnp.zeros((ROWS, LANES), jnp.uint32)
        for k in range(K):
            src = desc_ref[0, k, 0]
            dst = desc_ref[0, k, 1]
            ln = desc_ref[0, k, 2]
            arow = pl.multiple_of(((src // LANES) // ROWS) * ROWS, ROWS)
            pltpu.make_async_copy(
                data_ref.at[pl.ds(arow, 2 * ROWS), :], scratch.at[k], sem.at[k]
            ).wait()
            # shift the 2-tile window so window[sh + e] lands at element e
            # (sh may be negative — rolls are cyclic and the 16-row window
            # covers every index sh+e in [0, 2048) exactly)
            sh = (src - arow * LANES) - dst
            x = scratch[k]
            r = sh // LANES
            l = sh - r * LANES  # 0..127
            a = pltpu.roll(x, -r, 0)
            b = pltpu.roll(x, -(r + 1), 0)
            la = pltpu.roll(a, -l, 1)
            lb = pltpu.roll(b, -l, 1)
            lane = jax.lax.broadcasted_iota(jnp.int32, (2 * ROWS, LANES), 1)
            y = jnp.where(lane < LANES - l, la, lb)[:ROWS, :]
            sel = (elem >= dst) & (elem < dst + ln)
            acc = jnp.where(sel, y, acc)
        out_ref[0] = acc

    @functools.partial(jax.jit, static_argnames=("K", "interpret"))
    def ragged_gather(data, desc, K, interpret=False):
        pad = (-n) % LANES + 2 * CHUNK
        data_2d = jnp.concatenate(
            [data, jnp.zeros((pad,), data.dtype)]
        ).reshape(-1, LANES)
        out = pl.pallas_call(  # sortlint: disable=SL013 -- rejected-design probe (measures why the gather kernel lost); never on a production path
            functools.partial(gather_kernel, K),
            grid=(nchunk,),
            in_specs=[
                pl.BlockSpec((1, K, 3), lambda c: (c, 0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, ROWS, LANES), lambda c: (c, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((nchunk, ROWS, LANES), jnp.uint32),
            scratch_shapes=[
                pltpu.VMEM((K, 2 * ROWS, LANES), jnp.uint32),
                pltpu.SemaphoreType.DMA((K,)),
            ],
            interpret=interpret,
        )(desc, data_2d)
        return out.reshape(-1)

    rng = np.random.default_rng(0)
    data_np = rng.integers(0, 2**32, n, dtype=np.uint32)
    data = jnp.asarray(data_np)

    def timed(g, v):
        t0 = time.perf_counter()
        y = g(v)
        jax.device_get(y[:1])
        return time.perf_counter() - t0

    metrics = Metrics(config={"probe": "ragged_gather", "log2n": args.log2n})
    print(f"{'layout':>8s} {'run_len':>8s} {'runs':>9s} {'K':>3s} {'ms':>9s} "
          f"{'GB/s':>7s} {'us/run':>7s}")
    configs = []
    for run_log2 in (13, 12, 11, 10, 9, 8):
        # aligned: uniform chunk-multiple runs (the kindest case — each
        # chunk is exactly one segment); ragged: lengths jittered ±25%
        # like real digit runs, so segments straddle chunk boundaries.
        configs.append(("aligned", run_log2, False))
        configs.append(("ragged", run_log2, True))
    for layout, run_log2, jitter in configs:
        run_len = 1 << run_log2
        nruns = n // run_len
        if nruns < 1:
            print(f"{layout:>8s} {run_len:8d} — skipped (n < run_len)")
            continue
        if jitter:
            # bounded ±25% jitter, total corrected back to n by spreading
            # the residual ±1 per run — lengths stay within [run_len/2,
            # 3·run_len/2], so the per-chunk segment count (K) stays
            # bounded instead of spiking on an outlier chunk
            d = rng.integers(-(run_len // 4), run_len // 4 + 1,
                             size=nruns).astype(np.int64)
            d -= d.sum() // nruns
            res = int(d.sum())
            sgn = 1 if res < 0 else -1
            d[: abs(res)] += sgn
            lens = run_len + d
            assert int(lens.sum()) == n and (lens > 0).all()
            starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
        else:
            starts = np.arange(nruns, dtype=np.int64) * run_len
            lens = np.full(nruns, run_len, np.int64)
        order = rng.permutation(nruns)
        desc, K = build_descriptors(starts, lens, order, nchunk)
        desc_j = jnp.asarray(desc)

        out = ragged_gather(data, desc_j, K, interpret=args.interpret)
        want = data_np[
            np.concatenate([np.arange(starts[r], starts[r] + lens[r])
                            for r in order])
        ]
        # Position-weighted checksums, computed on device (pulling 256 MB
        # through this image's tunnel per config would dominate the
        # probe): two independent sum_i out[i]*phi_m(i) mod 2^32 — any
        # misplacement, drop, or duplication flips them with probability
        # ~1-2^-64.  All uint32: Mosaic/this image lack i64 vectors.
        MULS = (np.uint32(2654435761), np.uint32(0x9E3779B1 ^ 0x55555555))

        @jax.jit
        def checksum(v):
            i = jnp.arange(v.shape[0], dtype=jnp.uint32)
            return tuple(
                jnp.sum(v * ((i + jnp.uint32(m0)) * jnp.uint32(mul)),
                        dtype=jnp.uint32)
                for m0, mul in ((1, MULS[0]), (7, MULS[1]))
            )

        i_np = np.arange(n, dtype=np.uint32)
        want_sums = tuple(
            int(np.sum(want * ((i_np + np.uint32(m0)) * mul),
                       dtype=np.uint32))
            for m0, mul in ((1, MULS[0]), (7, MULS[1]))
        )
        got_sums = tuple(int(s) for s in jax.device_get(checksum(out)))
        assert got_sums == want_sums, f"MISMATCH at run_len={run_len}"

        # slope timing: the gather's output is a same-length uint32 array,
        # so chain reps by feeding it back — same access pattern per rep.
        ts = {}
        for reps in (1, 3):
            @jax.jit
            def g(v, reps=reps):
                for _ in range(reps):
                    v = ragged_gather(v, desc_j, K, interpret=args.interpret)
                return v
            y = g(data)
            jax.device_get(y[:1])
            ts[reps] = min(timed(g, data) for _ in range(3))
        per = (ts[3] - ts[1]) / 2
        gbs = 2 * 4 * n / per / 1e9
        metrics.record(f"ragged_gather_{layout}_runlen{run_len}_ms",
                       round(per * 1e3, 3), "ms")
        print(f"{layout:>8s} {run_len:8d} {nruns:9d} {K:3d} {per*1e3:9.2f} "
              f"{gbs:7.1f} {per/nruns*1e6:7.3f}")
    metrics.dump()


if __name__ == "__main__":
    main()
