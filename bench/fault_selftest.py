"""Chaos-test matrix: the `make fault-selftest` gate (ISSUE 3).

Runs the full fault-spec grid — every :data:`mpitest_tpu.faults.SITES`
entry x {sample, radix}, plus persistent-failure and fallback-disabled
variants, the CLI exit-code contract, and the native COMM_FAULTS
kill/stall drills — and asserts the ONE property the robustness layer
exists for:

    every cell either recovers with a fingerprint-verified, bit-exact
    result, or fails loudly with a typed error / nonzero exit.
    ZERO silent-wrong-answer cells.

A cell where a fault was injected but the output came back wrong and
undetected is a hard failure of this gate — that is the reference's
silent-overflow behavior reborn, the exact bug class this repo's port
eliminated.

Runs TPU-free on the virtual 8-device CPU mesh (like the rest of CI);
wall time is dominated by one-time XLA compiles, a couple of minutes.
Also cross-checks the verifier-overhead budget: the accumulated warm
verify phase must stay under 5% of warm sort wall (the bench row's
``verify_overhead_s`` tracks the same quantity at scale).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("SORT_RETRY_BACKOFF", "0")  # drills, not prod: no sleeps

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402

from mpitest_tpu import faults  # noqa: E402
from mpitest_tpu.models.api import (  # noqa: E402
    SortIntegrityError, SortRetryExhausted, sort)
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402

PASS, FAIL = "recovered", "FAILED"
results: list[tuple[str, str, str]] = []   # (cell, outcome, detail)
bad = 0


def cell(name: str, outcome_ok: bool, detail: str) -> None:
    global bad
    results.append((name, PASS if outcome_ok else FAIL, detail))
    if not outcome_ok:
        bad += 1
    print(f"  {'ok ' if outcome_ok else 'BAD'} {name:<42} {detail}",
          flush=True)


def main() -> int:
    mesh = make_mesh(8)
    rng = np.random.default_rng(42)
    x = rng.integers(-(2**31), 2**31 - 1, size=30_000, dtype=np.int32)
    ref = np.sort(x)

    #: gitignored checkout-scoped spill staging (ISSUE 15) — never a
    #: shared /tmp path a concurrent checkout could interleave with.
    spill_dir = REPO / "bench" / ".spill-out" / "faultgrid"

    #: sites living in the out-of-core store (ISSUE 15 + the ISSUE 18
    #: disk-fault family): drilled through the external sort at a
    #: forced tiny budget.  manifest_torn needs a dataset id (the
    #: journal only exists for dataset-keyed sorts); spill_enospc is
    #: the ONE site whose acceptable outcome is a typed capacity error
    #: rather than recovery.
    STORE_SITES = ("spill_corrupt", "merge_drop", "spill_torn_write",
                   "spill_bitrot", "spill_enospc", "manifest_torn",
                   "merge_stall", "spill_block_garbage")

    print(f"fault grid: {len(faults.SITES)} sites x {{radix, sample}} "
          "— must recover verified (or fail typed: spill_enospc)")
    for site in faults.SITES:
        for algo in ("radix", "sample"):
            env_extra = {}
            if site == "ingest_poison":
                # the poison hook lives in the streamed ingest pipeline
                env_extra = {"SORT_INGEST": "stream",
                             "SORT_INGEST_CHUNK": "4096"}
            elif site == "merge_stall":
                env_extra = {"SORT_FAULT_STALL_MS": "10"}
            elif site == "spill_block_garbage":
                # the drill scrambles a SORTRUN2 block header, so the
                # cell must force compressed runs even when the native
                # codec library is absent (pure-Python engine)
                env_extra = {"SORT_SPILL_COMPRESS": "on"}
            reg = faults.FaultRegistry(site, seed=7)
            faults.install(reg)
            tr = Tracer()
            try:
                with knobs.scoped_env(**env_extra):
                    if site in STORE_SITES:
                        from mpitest_tpu.store import external

                        got = external.external_sort(
                            x, algorithm=algo, mesh=mesh, tracer=tr,
                            budget=1 << 17,
                            spill_dir=str(spill_dir),
                            dataset=(f"grid_{site}_{algo}"
                                     if site == "manifest_torn"
                                     else None)).keys
                    else:
                        got = sort(x, algorithm=algo, mesh=mesh,
                                   tracer=tr)
                exact = bool(np.array_equal(got, ref))
                fired = reg.injected > 0
                detail = (f"faults={reg.injected} "
                          f"retries={int(tr.counters.get('sort_retries', 0) + tr.counters.get('exchange_retries', 0))} "
                          f"verify_failures={int(tr.counters.get('verify_failures', 0))}")
                if site == "spill_enospc":
                    cell(f"{site} x {algo}", False,
                         "completed despite injected ENOSPC "
                         "(typed SpillCapacityError expected)")
                else:
                    cell(f"{site} x {algo}", exact and fired,
                         detail + ("" if exact else " WRONG RESULT")
                         + ("" if fired else " FAULT NEVER FIRED"))
            except (SortIntegrityError, SortRetryExhausted) as e:
                # loud, typed failure is an acceptable outcome — but for
                # single transient faults the ladder should recover
                cell(f"{site} x {algo}", False,
                     f"typed error on a transient fault: {type(e).__name__}")
            except OSError as e:
                from mpitest_tpu.store import external as _ext

                ok = (site == "spill_enospc"
                      and isinstance(e, _ext.SpillCapacityError))
                cell(f"{site} x {algo}", ok,
                     f"{type(e).__name__} "
                     + ("(typed, loud, partials deleted)" if ok
                        else "(unexpected OSError)"))
            finally:
                faults.install(None)

    print("compressed-spill variants (ISSUE 20): the raw-era disk "
          "faults re-drilled over SORTRUN2 runs")
    # the generic grid above runs the disk sites under the knob default
    # — these cells force compression ON so every raw-era corruption
    # shape is ALSO proven against the compressed framing (checksum
    # mismatch / sidecar fold / truncated block, all blamed + re-spilled)
    for site in ("spill_corrupt", "spill_bitrot", "spill_torn_write"):
        reg = faults.FaultRegistry(site, seed=7)
        faults.install(reg)
        tr = Tracer()
        name = f"{site} x radix (compress=on)"
        try:
            from mpitest_tpu.store import external

            with knobs.scoped_env(SORT_SPILL_COMPRESS="on"):
                got = external.external_sort(
                    x, algorithm="radix", mesh=mesh, tracer=tr,
                    budget=1 << 17,
                    spill_dir=str(spill_dir)).keys
            exact = bool(np.array_equal(got, ref))
            fired = reg.injected > 0
            cell(name, exact and fired,
                 f"faults={reg.injected} "
                 f"recoveries={int(tr.counters.get('external_recoveries', 0))}"
                 + ("" if exact else " WRONG RESULT")
                 + ("" if fired else " FAULT NEVER FIRED"))
        except (SortIntegrityError, SortRetryExhausted) as e:
            cell(name, False,
                 f"typed error on a transient fault: {type(e).__name__}")
        finally:
            faults.install(None)

    print("persistent faults: recover via ladder OR fail typed")
    for spec, fallback, expect in (
        ("dispatch_oom:inf", "1", "host"),        # degrade to host sort
        ("dispatch_oom:inf", "0", "retryerr"),    # typed retry exhaustion
        ("result_dup:inf", "0", "integrityerr"),  # typed integrity error
    ):
        for algo in ("radix", "sample"):
            reg = faults.FaultRegistry(spec, seed=7)
            faults.install(reg)
            tr = Tracer()
            name = f"{spec} fallback={fallback} x {algo}"
            try:
                with knobs.scoped_env(SORT_FALLBACK=fallback):
                    got = sort(x, algorithm=algo, mesh=mesh, tracer=tr)
                ok = (expect == "host"
                      and np.array_equal(got, ref)
                      and tr.counters.get("degraded_to") == "host")
                cell(name, ok, f"degraded_to={tr.counters.get('degraded_to')}"
                     + ("" if np.array_equal(got, ref) else " WRONG RESULT"))
            except SortRetryExhausted:
                cell(name, expect == "retryerr", "SortRetryExhausted")
            except SortIntegrityError:
                cell(name, expect == "integrityerr", "SortIntegrityError")
            finally:
                faults.install(None)

    print("local-sort engine ladder (ISSUE 17): fused rung -> lax, loud")
    # The third engine's rung in the fault grid: a fused-kernel failure
    # must degrade ONLY the local engine (pallas -> lax, counted, plan-
    # stamped) and re-run verified; with the ladder pinned off it must
    # be a typed error — never a silent lax re-run.  Injected by
    # monkeypatch (no faults.SITES entry: the generic grid above runs
    # under engines where the fused path never traces, and a site that
    # cannot fire everywhere would report FAULT NEVER FIRED).  Odd key
    # counts: the fault fires at TRACE time, so these cells must miss
    # every compile-cache entry the grid populated.
    import jax

    from mpitest_tpu.ops import radix_pallas as rp

    orig_fused = rp.fused_radix_sort

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError(
            "INTERNAL: injected fused local-sort fault (drill)")

    x_l = rng.integers(-(2**31), 2**31 - 1, size=31_337, dtype=np.int32)
    rp.fused_radix_sort = boom
    try:
        tr = Tracer()
        with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas",
                              SORT_FALLBACK="1", SORT_MAX_RETRIES="0"):
            got = sort(x_l, algorithm="radix", mesh=mesh, tracer=tr)
        cell("fused local fault, fallback=1",
             bool(np.array_equal(got, np.sort(x_l)))
             and tr.counters.get("local_engine_degraded") == 1
             and tr.counters.get("local_engine") == "lax",
             f"degrades={tr.counters.get('local_engine_degraded')} "
             f"engine={tr.counters.get('local_engine')}")
        try:
            with knobs.scoped_env(SORT_LOCAL_ENGINE="radix_pallas",
                                  SORT_FALLBACK="0",
                                  SORT_MAX_RETRIES="0"):
                sort(rng.integers(0, 100, size=7_771, dtype=np.int32),
                     algorithm="radix", mesh=mesh)
            cell("fused local fault, fallback=0", False,
                 "returned instead of raising typed")
        except SortRetryExhausted:
            cell("fused local fault, fallback=0", True,
                 "SortRetryExhausted (typed, loud)")
    finally:
        rp.fused_radix_sort = orig_fused

    print("CLI exit codes: typed errors -> distinct nonzero exits")
    keyfile = "/tmp/fault_selftest_keys.txt"
    with open(keyfile, "w") as f:
        f.write("\n".join(str(v) for v in x[:5000]) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               SORT_RETRY_BACKOFF="0")
    for spec, fallback, want_rc in (
        ("result_dup:inf", "0", 3),   # EXIT_INTEGRITY
        ("dispatch_oom:inf", "0", 4),  # EXIT_RETRIES
        ("garbage_site", "1", 1),      # knob validation
    ):
        r = subprocess.run(
            [sys.executable, str(REPO / "drivers" / "sort_cli.py"), keyfile],
            capture_output=True, text=True, timeout=600,
            env=dict(env, SORT_FAULTS=spec, SORT_FALLBACK=fallback))
        one_line_err = (r.stderr.count("[ERROR]") == 1
                        and "Traceback" not in r.stderr)
        cell(f"cli SORT_FAULTS={spec}", r.returncode == want_rc
             and one_line_err,
             f"rc={r.returncode} (want {want_rc})")
    r = subprocess.run(
        [sys.executable, str(REPO / "drivers" / "sort_cli.py"), keyfile],
        capture_output=True, text=True, timeout=600,
        env=dict(env, SORT_FAULTS="exchange_corrupt"))
    cell("cli SORT_FAULTS=exchange_corrupt recovers",
         r.returncode == 0 and "n/2-th sorted element" in r.stdout,
         f"rc={r.returncode}")

    print("native COMM_FAULTS drills (pthreads + minimpi)")
    radix_bin = REPO / "mpi_radix_sort" / "radix_sort"
    mini_bin = REPO / "bench" / "radix_sort_minimpi"
    keys_native = "/tmp/fault_selftest_native.txt"
    with open(keys_native, "w") as f:
        f.write("\n".join(str(v) for v in x[:20_000]) + "\n")
    median = int(np.sort(x[:20_000])[10_000 - 1])
    for label, binary, env_ranks in (
        ("local", radix_bin, {"COMM_RANKS": "4"}),
        ("minimpi", mini_bin, {"MINIMPI_NP": "4"}),
    ):
        if not binary.exists():
            cell(f"native {label}", False, f"{binary} not built")
            continue
        r = subprocess.run(
            [str(binary), keys_native], capture_output=True, text=True,
            timeout=60, env=dict(os.environ, **env_ranks,
                                 COMM_FAULTS="kill:1@3"))
        cell(f"COMM_FAULTS=kill x {label}",
             r.returncode != 0 and "[FAULT]" in r.stderr,
             f"rc={r.returncode} (nonzero + loud = pass)")
        r = subprocess.run(
            [str(binary), keys_native], capture_output=True, text=True,
            timeout=120, env=dict(os.environ, **env_ranks,
                                  COMM_FAULTS="stall:2@2:50"))
        cell(f"COMM_FAULTS=stall x {label}",
             r.returncode == 0
             and f"The n/2-th sorted element: {median}" in r.stdout,
             f"rc={r.returncode}")

    # verifier overhead budget on WARM programs (compiles amortized
    # out), measured at a size where per-dispatch latency no longer
    # dominates (tiny inputs mismeasure fixed dispatch cost as
    # "overhead"); best-of-3 to shed scheduler noise.  The acceptance
    # bound is < 5% of sort wall; bench.py reports the same quantity at
    # benchmark scale as verify_overhead_s.
    xv = rng.integers(-(2**31), 2**31 - 1, size=1 << 22, dtype=np.int32)
    sort(xv, algorithm="radix", mesh=mesh)         # warm the programs
    ratios = []
    for _ in range(4):
        tr = Tracer()
        t0 = time.perf_counter()
        sort(xv, algorithm="radix", mesh=mesh, tracer=tr)
        wall = time.perf_counter() - t0
        v = tr.phases.get("verify", 0.0)
        ratios.append((100.0 * v / wall if wall else 0.0, v, wall))
    # min ratio over runs: the least-noise estimate of the INTRINSIC
    # overhead — scheduler hiccups on this 1-core box inflate single
    # runs by several x, in either phase.
    pct, v, wall = min(ratios)
    print(f"verifier overhead (warm, 2^22, min of {len(ratios)}): "
          f"{v:.4f}s of {wall:.4f}s = {pct:.2f}%  "
          f"(all: {', '.join(f'{r:.2f}%' for r, _, _ in ratios)})")
    cell("verifier overhead < 5%", pct < 5.0, f"{pct:.2f}%")

    n_pass = sum(1 for _, o, _ in results if o == PASS)
    print(f"\nfault-selftest: {n_pass}/{len(results)} cells clean "
          f"({bad} failing)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
