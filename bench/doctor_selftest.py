#!/usr/bin/env python3
"""Doctor + sentinel selftest — the ISSUE 16 diagnosis-layer gate.

Plants each registered pathology DETERMINISTICALLY (synthesized
registered-span streams through the real ``SpanLog`` API — the same
spans the live producers emit) and asserts the doctor names it, and
ONLY it, with evidence citations.  Cells:

* one cell per ``DOCTOR_RULES`` pathology (9): the evidence fold +
  timeline + ``diagnose()`` over the planted trace yields exactly the
  planted rule, and the rendered finding cites its evidence spans;
* a CLI drill: ``report.py --doctor <trace>`` renders the skew cell's
  finding and exits 0, and every planted trace passes ``report.py
  --check --require-registered-spans`` (the pathologies are built
  from REGISTERED vocabulary only);
* a clean-run cell: a REAL tiny sort's trace raises ZERO findings —
  the doctor's false-positive gate;
* sentinel cells (in-process ``SpanLog`` + ``LiveMetrics`` + bridge +
  ``SortSentinel``, the exact server wiring): a clean window raises
  zero alerts; an error burst raises exactly ``deadline_burn``
  (critical) — bridged into ``sort_alerts_total{rule,severity}`` and
  dumping a flight-recorder artifact that passes ``report.py
  --check``; repeated skewed exchanges raise ``skew_imbalance``; the
  per-rule cooldown keeps a sustained burst at one alert per window.

Run directly or via ``make doctor-selftest`` (CI wires it beside the
fault/serve/multichip/external selftests).
"""

from __future__ import annotations

import argparse
import contextlib
import io
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FAIL = 0


def check(name: str, ok: bool, detail: str = "") -> None:
    global FAIL
    tag = "ok " if ok else "BAD"
    print(f"  [{tag}] {name}" + (f" — {detail}" if detail else ""))
    if not ok:
        FAIL += 1


def _diagnose_rows(rows: list[dict], slo_target: float | None = None):
    """The exact fold chain ``report.py --doctor`` runs."""
    from mpitest_tpu import doctor
    from mpitest_tpu.utils import timeline

    ev = doctor.evidence_from_rows(
        rows, timeline=timeline.build_timeline(rows))
    if slo_target is not None:
        ev["slo_target_pct"] = slo_target
    return doctor.diagnose(ev)


def _planted_log(out_dir: Path, cell: str):
    """Fresh SpanLog streaming to a per-cell trace file."""
    from mpitest_tpu.utils.spans import SpanLog

    log = SpanLog()
    log.stream_path = str(out_dir / f"{cell}.jsonl")
    return log


# ---------------------------------------------------------------- cells

def plant_skew(log) -> None:
    log.record("sort", 0.0, 2.0)
    log.record("exchange_balance", 0.5, 0.0,
               recv_bytes=[100.0, 110.0, 90.0, 420.0],
               send_bytes=[180.0, 180.0, 180.0, 180.0],
               negotiated_cap=256, worst_cap=1024)


def plant_cap_thrash(log) -> None:
    log.record("sort", 0.0, 1.0)
    log.record("sort.plan", 1.0, 0.0, algo="sample", decisions={
        "cap": {"chosen": 128, "predicted": {"cap": 128},
                "actual": {"cap": 310, "regrows": 3}, "regret": 1.4}})


def plant_compile_storm(log) -> None:
    for i in range(6):
        log.record("serve.compile_cache", float(i), 0.0, hit=False,
                   bucket=1 << (10 + i), dtype="int32", compile_s=0.2)
    log.record("serve.compile_cache", 7.0, 0.0, hit=True,
               bucket=1024, dtype="int32")


def plant_window_misfit(log) -> None:
    log.record("sort.plan", 0.0, 0.0, algo="sample", decisions={
        "batch": {"chosen": 4096, "predicted": {"waste": 0.1},
                  "actual": {"waste": 0.7}, "regret": 0.6}})


def plant_spill_bound(log) -> None:
    log.record("jit_execute", 0.0, 0.5)
    log.record("external.run", 0.5, 1.2, run=0, n=1 << 20,
               bytes=1 << 22, dtype="int32", payload_width=0)
    log.record("external.merge", 1.7, 2.3, runs=4, n=1 << 22,
               merge_pass=0, final=True)


def plant_verify_overhead(log) -> None:
    log.record("phase:sort", 0.0, 2.0)
    log.record("phase:verify", 2.0, 1.0)


def plant_local_sort_lax(log) -> None:
    # sort dominates the phase wall AND the plan says the local sort
    # lowered through generic lax.sort on a TPU backend (ISSUE 17)
    log.record("phase:sort", 0.0, 2.0)
    log.record("phase:decode", 2.0, 0.5)
    log.record("sort.plan", 2.5, 0.0, algo="radix", decisions={
        "engine": {"chosen": "xla",
                   "actual": {"local_engine": "lax", "backend": "tpu",
                              "fallbacks": 0}}})


def plant_spill_churn(log) -> None:
    # one integrity recovery + one crash resume in the same trace —
    # the spill volume itself becomes the suspect (ISSUE 18)
    log.record("external.recover", 0.0, 0.0, reason="fingerprint",
               bad_runs=1, attempt=1)
    log.record("external.resume", 1.0, 0.0, dataset="ds1", committed=4,
               valid=4, skipped_lines=0)


def plant_breaker_flap(log) -> None:
    log.record("serve.watchdog", 0.0, 0.0, event="trip", age_s=130.0)
    log.record("serve.watchdog", 1.0, 0.0, event="recovered")
    log.record("serve.watchdog", 2.0, 0.0, event="trip", age_s=131.0)


def plant_deadline_burn(log) -> None:
    for i in range(12):
        log.record("serve.request", float(i), 0.01, status="ok",
                   n=4096, dtype="int32")
    for i in range(4):
        log.record("serve.request", 12.0 + i, 0.01, status="deadline",
                   n=4096, dtype="int32")
        log.record("serve.deadline", 12.0 + i, 0.0, stage="queue")


PATHOLOGY_CELLS = (
    ("skew_imbalance", plant_skew),
    ("cap_thrash", plant_cap_thrash),
    ("compile_storm", plant_compile_storm),
    ("window_misfit", plant_window_misfit),
    ("spill_bound", plant_spill_bound),
    ("verify_overhead_regression", plant_verify_overhead),
    ("local_sort_lax", plant_local_sort_lax),
    ("spill_churn", plant_spill_churn),
    ("breaker_flap", plant_breaker_flap),
    ("deadline_burn", plant_deadline_burn),
)


def run_pathology_cells(out_dir: Path) -> None:
    from mpitest_tpu import doctor, report

    print(f"pathology cells ({len(PATHOLOGY_CELLS)} planted rules):")
    assert {c[0] for c in PATHOLOGY_CELLS} == set(doctor.DOCTOR_RULES), \
        "cell list out of sync with DOCTOR_RULES"
    for rule, plant in PATHOLOGY_CELLS:
        log = _planted_log(out_dir, rule)
        plant(log)
        trace = Path(log.stream_path)
        rows = report.load_rows(str(trace))
        findings = _diagnose_rows(rows)
        named = [f.rule for f in findings]
        check(f"{rule}: diagnosed", named == [rule],
              f"findings={named}")
        if findings:
            f = findings[0]
            check(f"{rule}: evidence cited",
                  bool(f.evidence) and all(isinstance(c, str) and c
                                           for c in f.evidence),
                  f"{len(f.evidence)} citation(s)")
            check(f"{rule}: knob suggested",
                  bool(f.knob) and bool(f.direction),
                  f"{f.knob} -> {f.direction}")
        # the planted stream is registered-vocabulary only
        rc = report.main(["--check", "--require-registered-spans",
                          str(trace)])
        check(f"{rule}: trace passes --check --require-registered-spans",
              rc == 0, f"rc={rc}")


def run_cli_cell(out_dir: Path) -> None:
    from mpitest_tpu import report

    print("report.py --doctor CLI drill:")
    trace = out_dir / "skew_imbalance.jsonl"
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report.main(["--doctor", str(trace)])
    rendered = buf.getvalue()
    check("--doctor exits 0", rc == 0, f"rc={rc}")
    check("--doctor names the rule", "skew_imbalance" in rendered)
    check("--doctor cites evidence", "exchange_balance" in rendered)
    check("--doctor suggests a knob", "SORT_RESTAGE" in rendered)


def run_clean_cell(out_dir: Path) -> None:
    import numpy as np

    from mpitest_tpu.models.api import sort
    from mpitest_tpu.utils.io import generate
    from mpitest_tpu.utils.trace import Tracer

    print("clean-run cell (real tiny sort, zero findings):")
    tracer = Tracer()
    x = generate("uniform", 1 << 10, np.dtype(np.int32), seed=7)
    out = sort(x, algorithm="sample", tracer=tracer)
    check("sorted", bool(np.array_equal(out, np.sort(x))))
    rows = [s.to_dict() for s in tracer.spans.spans]
    findings = _diagnose_rows(rows)
    check("zero findings on a clean run", not findings,
          f"findings={[f.rule for f in findings]}")


def run_sentinel_cells(out_dir: Path) -> None:
    from mpitest_tpu import report
    from mpitest_tpu.serve.sentinel import SortSentinel
    from mpitest_tpu.utils import flight_recorder
    from mpitest_tpu.utils.metrics_live import LiveMetrics, SpanMetricsBridge
    from mpitest_tpu.utils.spans import SpanLog

    print("sentinel cells (in-process server wiring):")

    def wired(trace_name: str):
        log = SpanLog()
        log.stream_path = str(out_dir / trace_name)
        metrics = LiveMetrics()
        log.observers.append(SpanMetricsBridge(metrics))
        s = SortSentinel(metrics, log, window_s=60.0, burn_rate=2.0)
        log.observers.append(s)
        return log, metrics, s

    # clean window: ok traffic only -> zero alerts, zero alert spans
    log, metrics, s = wired("sentinel_clean.jsonl")
    for _ in range(30):
        log.record("serve.request", time.perf_counter(), 0.01,
                   status="ok", n=4096)
    check("clean window: zero alerts", len(s.alerts) == 0,
          f"{len(s.alerts)} alert(s)")
    check("clean window: no serve.alert spans",
          not any(sp.name == "serve.alert" for sp in log.spans))

    # error burst -> exactly deadline_burn, critical, with a flight
    # artifact that passes report --check
    flight_recorder.reset()
    log, metrics, s = wired("sentinel_burn.jsonl")
    for _ in range(12):
        log.record("serve.request", time.perf_counter(), 0.01,
                   status="ok", n=4096)
    for _ in range(6):
        log.record("serve.request", time.perf_counter(), 0.01,
                   status="deadline", n=4096)
    rules = [a["rule"] for a in s.alerts]
    check("burst: exactly deadline_burn", rules == ["deadline_burn"],
          f"alerts={rules}")
    sevs = [a["severity"] for a in s.alerts]
    check("burst: critical severity", sevs == ["critical"],
          f"severities={sevs}")
    prom = metrics.render_prom()
    check("burst: bridged into sort_alerts_total",
          'sort_alerts_total{rule="deadline_burn",severity="critical"} 1'
          in prom)
    check("burst: serve.alert span emitted",
          sum(1 for sp in log.spans if sp.name == "serve.alert") == 1)
    rec = flight_recorder.get()
    check("burst: flight artifact dumped", rec.dumps == 1,
          f"dumps={rec.dumps}")
    dump_files = sorted(Path(rec.directory).glob("*.jsonl"),
                        key=os.path.getmtime)
    rc = report.main(["--check", str(dump_files[-1])]) \
        if dump_files else 1
    check("burst: flight artifact passes report --check", rc == 0,
          f"rc={rc} file={dump_files[-1].name if dump_files else None}")
    # cooldown: a sustained burst stays at one alert per window
    for _ in range(6):
        log.record("serve.request", time.perf_counter(), 0.01,
                   status="internal", n=4096)
    check("cooldown: still one alert in the window",
          len(s.alerts) == 1, f"{len(s.alerts)} alert(s)")

    # repeated skewed exchanges -> skew_imbalance via the EWMA
    log, metrics, s = wired("sentinel_skew.jsonl")
    for i in range(4):
        log.record("exchange_balance", time.perf_counter(), 0.0,
                   recv_bytes=[100.0, 100.0, 100.0, 400.0],
                   send_bytes=[175.0] * 4, peer_ratio=4.0,
                   negotiated_cap=256)
    rules = [a["rule"] for a in s.alerts]
    check("skew: exactly skew_imbalance", rules == ["skew_imbalance"],
          f"alerts={rules}")
    # the /alerts snapshot carries the series state
    snap = s.snapshot()
    check("snapshot: enabled with series",
          snap.get("enabled") is True and "series" in snap
          and snap["series"]["imbalance_ewma"] is not None)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/mpitest_doctor_selftest",
                    help="directory for per-cell traces and flight "
                         "artifacts")
    args = ap.parse_args()
    out_dir = Path(args.out)
    out_dir.mkdir(parents=True, exist_ok=True)
    # flight artifacts land inside the selftest dir (env write — reads
    # stay inside the knob registry)
    os.environ["SORT_FLIGHT_RECORDER_DIR"] = str(out_dir / "flightrec")

    run_pathology_cells(out_dir)
    run_cli_cell(out_dir)
    run_clean_cell(out_dir)
    run_sentinel_cells(out_dir)

    print(f"doctor selftest: "
          f"{'CLEAN' if FAIL == 0 else f'{FAIL} BAD cell(s)'}")
    return 1 if FAIL else 0


if __name__ == "__main__":
    sys.exit(main())
