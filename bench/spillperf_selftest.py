"""Disk-speed external-sort gate: `make spillperf-selftest` (ISSUE 20).

The tentpole claim of the spill-compression + async-IO work is a
PERFORMANCE contract, so this gate measures it instead of trusting it:
on a simulated slow disk (``SORT_SPILL_THROTTLE_MBPS`` — the shared
token bucket in ``store/runs.py`` that makes "disk-bound" reproducible
on any CI box with fast local storage), an external sort over
compressed (SORTRUN2) runs must beat the raw-run baseline by the
bandwidth the compression saves, and the merge's read-ahead/
write-behind engine must actually overlap its disk time with compute:

1. **parity cell** — both legs (raw and compressed, same data, same
   budget) are bit-identical to ``np.sort`` AND the in-memory
   supervised sort; the compressed leg really spilled compressed
   (``spill_ratio`` well above 1) across >= 8 runs.
2. **throughput cell** — compressed external sort >= 1.5x the raw
   baseline at the disk-bound budget (the saved bytes are saved
   seconds when the disk is the bottleneck).
3. **overlap cell** — the final merge's measured disk/compute overlap
   (``ExternalResult.disk_overlap``, also stamped on the final
   ``external.merge`` span) >= 0.5: the engine genuinely hides disk
   behind compute rather than alternating.

A small unthrottled warm-up sort runs first so XLA compiles and the
native codec load are amortized out of both timed legs.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SORT_RETRY_BACKOFF", "0")

import numpy as np  # noqa: E402

#: Gitignored checkout-scoped staging (never a shared /tmp path).
SPILL_DIR = REPO / "bench" / ".spill-out" / "spillperf"

#: 2^21 int32 keys = 8 MiB of data under a 2 MiB budget -> 16 spill
#: runs, single merge pass at the default fanin of 16.
N_KEYS = 1 << 21
BUDGET = 1 << 21

#: Simulated disk bandwidth: slow enough that the ~16 MiB of raw spill
#: traffic dominates the wall (disk-bound by construction), fast enough
#: the gate stays a few seconds per leg.
THROTTLE_MBPS = 4.0

#: Acceptance floors (the ISSUE 20 acceptance criteria).
SPEEDUP_FLOOR = 1.5
OVERLAP_FLOOR = 0.5

FAIL = 0


def check(name: str, ok: bool, detail: str = "") -> None:
    global FAIL
    if not ok:
        FAIL += 1
    print(f"  {'ok ' if ok else 'BAD'} {name:<46} {detail}", flush=True)


def main() -> int:
    from mpitest_tpu.models.api import sort as api_sort
    from mpitest_tpu.store import compress, external
    from mpitest_tpu.utils import knobs

    if SPILL_DIR.exists():
        shutil.rmtree(SPILL_DIR)
    SPILL_DIR.mkdir(parents=True, exist_ok=True)

    rng = np.random.default_rng(20)
    # Bounded key domain (IDs / timestamps shape): sorted-neighbor
    # deltas land well under the raw 32-bit width, which is exactly
    # the redundancy the delta+bit-pack codec targets.  (Full-range
    # uniform keys are the adversarial floor — the codec still wins
    # there, ~1.7x, but this gate pins the representative case.)
    x = rng.integers(0, 1 << 27, size=N_KEYS, dtype=np.int32)
    ref = np.sort(x)

    print(f"spillperf gate: {x.nbytes} B dataset, {BUDGET} B budget, "
          f"disk throttled to {THROTTLE_MBPS:g} MB/s "
          f"(codec engine: {compress.engine()}"
          + ("" if compress.available()
             else f"; native unavailable: {compress.unavailable_reason()}")
          + ")")

    # warm-up: compiles + codec load, unthrottled, small
    with knobs.scoped_env(SORT_SPILL_COMPRESS="on"):
        external.external_sort(x[: N_KEYS // 8], budget=BUDGET // 8,
                               spill_dir=str(SPILL_DIR / "warm"))

    legs: dict[str, tuple[float, "external.ExternalResult"]] = {}
    for mode in ("off", "on"):
        with knobs.scoped_env(
                SORT_SPILL_COMPRESS=mode,
                SORT_SPILL_THROTTLE_MBPS=str(THROTTLE_MBPS)):
            t0 = time.perf_counter()
            res = external.external_sort(
                x, budget=BUDGET, spill_dir=str(SPILL_DIR / mode))
            legs[mode] = (time.perf_counter() - t0, res)
        dt, res = legs[mode]
        print(f"  leg compress={mode}: {dt:.2f}s "
              f"({x.size / dt / 1e6:.2f} Mkeys/s) runs={res.runs} "
              f"disk={res.disk_bytes}B ratio={res.spill_ratio:.2f} "
              f"overlap={res.disk_overlap:.2f}")

    dt_raw, res_raw = legs["off"]
    dt_cmp, res_cmp = legs["on"]

    inmem = api_sort(x)
    check("raw leg bit-identical (np.sort + in-memory)",
          bool(np.array_equal(res_raw.keys, ref)
               and np.array_equal(res_raw.keys, inmem)))
    check("compressed leg bit-identical (np.sort + in-memory)",
          bool(np.array_equal(res_cmp.keys, ref)
               and np.array_equal(res_cmp.keys, inmem)))
    check("spilled across >= 8 runs (both legs)",
          res_raw.runs >= 8 and res_cmp.runs >= 8,
          f"runs={res_raw.runs}/{res_cmp.runs}")
    check("compressed leg really compressed",
          res_cmp.spill_ratio > 1.2 > res_raw.spill_ratio,
          f"spill_ratio on={res_cmp.spill_ratio:.2f} "
          f"off={res_raw.spill_ratio:.2f}")

    speedup = dt_raw / dt_cmp if dt_cmp > 0 else 0.0
    check(f"compressed >= {SPEEDUP_FLOOR:g}x raw at disk-bound budget",
          speedup >= SPEEDUP_FLOOR, f"{speedup:.2f}x")
    check(f"final-merge disk overlap >= {OVERLAP_FLOOR:g}",
          res_cmp.disk_overlap >= OVERLAP_FLOOR,
          f"overlap={res_cmp.disk_overlap:.2f}")

    print(json.dumps({
        "metric": "spillperf_speedup_x",
        "value": round(speedup, 3),
        "unit": "x",
        "n": int(x.size), "dtype": "int32",
        "budget_bytes": BUDGET,
        "throttle_mbps": THROTTLE_MBPS,
        "raw_wall_s": round(dt_raw, 3),
        "compressed_wall_s": round(dt_cmp, 3),
        "spill_ratio": round(res_cmp.spill_ratio, 3),
        "disk_overlap": round(res_cmp.disk_overlap, 3),
        "engine": compress.engine(),
    }))
    print(f"\nspillperf-selftest: "
          f"{'CLEAN' if FAIL == 0 else f'{FAIL} BAD cell(s)'}")
    return 1 if FAIL else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    finally:
        shutil.rmtree(SPILL_DIR, ignore_errors=True)
