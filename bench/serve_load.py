#!/usr/bin/env python3
"""Closed-loop load generator + regression gate for the sort server.

Drives ``drivers/sort_server.py`` over the wire protocol with a mixed
small-request size distribution (log-uniform 2^7..2^10 int32 keys — the
"heavy traffic from millions of users" shape where per-dispatch
overhead, not device throughput, dominates) and C concurrent closed-loop
clients (each sends its next request when its previous reply lands).
Every reply is verified BIT-IDENTICAL to ``np.sort`` of its request —
the batched multi-tenant path must be indistinguishable from a private
sort.

Modes:

* ``--selftest`` (the ``make serve-selftest`` gate):

  1. **warm-cache gate** — after warmup, the measured window's server
     span stream must contain ZERO compile activity: no
     ``jit_compile_execute`` spans and no ``serve.compile_cache``
     misses (the executor cache's whole point).
  2. **batching gate** — server-side DISPATCH throughput (keys per
     second of ``serve.batch`` pipeline wall: pack + device sort +
     verify + split) of the batched server must be >= 2x the same load
     against a ``SORT_SERVE_BATCH_WINDOW_MS=0`` server (per-request
     dispatch): the measured value of multi-tenant packing, isolated
     from per-request socket/framing costs that are identical in both
     modes.
  3. **backpressure gate** — a burst against a ``MAX_INFLIGHT=1``
     server must produce typed ``backpressure`` rejections AND leave
     the server serving.
  4. **fault gate** — a poisoned request (per-request ``faults`` spec,
     test mode) must come back as a typed ``integrity`` error while the
     next clean request succeeds: per-request isolation.

* ``--row`` (bench.py's serve row): spawn, warm, measure the batched
  phase, emit ONE JSON bench row on stdout (p50/p99 + Mkeys/s) — the
  regression-gated sort-as-a-service headline beside the 1-chip and
  8-device rows.

The spawned server writes ``SORT_TRACE`` JSONL; ``python -m
mpitest_tpu.report`` renders the p50/p99 SLO table from exactly that
stream (the Makefile target does both).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from mpitest_tpu.report import percentile          # noqa: E402
from mpitest_tpu.serve.client import ServeClient   # noqa: E402
from mpitest_tpu.utils import knobs                # noqa: E402
from mpitest_tpu.utils import metrics_live         # noqa: E402

#: Request-size mix: log-uniform in [2^LOG2_MIN, 2^LOG2_MAX] int32 keys
#: — small enough that per-dispatch overhead (not O(n log n) sort work)
#: is what a request pays, which is exactly the traffic shape batching
#: exists to amortize.
LOG2_MIN, LOG2_MAX = 7, 10

#: Batching gate (ISSUE 8 acceptance): batched throughput must be at
#: least this multiple of per-request sequential dispatch.
MIN_BATCH_SPEEDUP = 2.0

#: Batch window the measured/bench phases use: wide enough that a
#: closed-loop round's worth of tenants packs into one dispatch on a
#: loaded 1-2 core runner (measured sweet spot; the production default
#: knob stays latency-leaning).
BATCH_WINDOW_MS = "8"

#: --chaos leg (ISSUE 11): injected response-delay tail — every Nth
#: proxied connection's reply is held this long, so the chaos p99 is a
#: deterministic property of the schedule, not of runner weather.
CHAOS_DELAY_MS = 400
CHAOS_EVERY = 4
CHAOS_REQUESTS = 32

HOST = "127.0.0.1"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ------------------------------------------------------------- server mgmt

class Server:
    """One spawned sort_server subprocess (ephemeral port, own trace)."""

    #: Startup budget: jax import + prewarm compiles on a loaded
    #: shared runner.  The wait is select()-bounded — a wedged server
    #: fails HERE at the deadline, never hangs the CI job on a
    #: blocking pipe read.
    STARTUP_TIMEOUT_S = 180.0

    def __init__(self, out_dir: Path, tag: str,
                 env_overrides: dict | None = None) -> None:
        self.trace = out_dir / f"server_trace_{tag}.jsonl"
        # stderr goes to a FILE, not a pipe: the child may log more
        # than a pipe buffer before binding (prewarm lines), and an
        # undrained pipe would deadlock exactly the startup path the
        # timeout exists to bound.
        self.stderr_path = out_dir / f"server_{tag}.stderr.log"
        self._stderr_f = open(self.stderr_path, "w")
        # defaults first, THEN overrides — a caller may override any
        # default (e.g. SORT_METRICS_PORT=-1), which dict(**kwargs)
        # would reject as a duplicate keyword
        env = dict(os.environ)
        env.update({"SORT_SERVE_PORT": "0",
                    "SORT_METRICS_PORT": "0",
                    "SORT_TRACE": str(self.trace)})
        env.update(env_overrides or {})
        self.proc = subprocess.Popen(
            [sys.executable, str(REPO / "drivers" / "sort_server.py")],
            stdout=subprocess.PIPE, stderr=self._stderr_f, text=True,
            env=env)
        assert self.proc.stdout is not None
        self._stdout_buf = ""
        line = self._await_listening_line()
        m = re.search(r"listening on [\d.]+:(\d+)", line or "")
        if not m:
            self.proc.kill()
            self.proc.wait(timeout=10)
            raise RuntimeError(
                f"server ({tag}) did not come up: {line!r}\n"
                f"{self._stderr_tail()}")
        self.port = int(m.group(1))
        # second sync line (ISSUE 10): the telemetry side port.  Only
        # awaited when the spawn env left metrics enabled.
        self.metrics_port: int | None = None
        if env.get("SORT_METRICS_PORT") != "-1":
            mline = self._await_listening_line()
            mm = re.search(r"metrics on [\d.]+:(\d+)", mline or "")
            if not mm:
                self.proc.kill()
                self.proc.wait(timeout=10)
                raise RuntimeError(
                    f"server ({tag}) printed no metrics line: {mline!r}"
                    f"\n{self._stderr_tail()}")
            self.metrics_port = int(mm.group(1))
        log(f"server[{tag}] up on :{self.port}"
            + (f" (metrics :{self.metrics_port})"
               if self.metrics_port else ""))

    def scrape_metrics(self) -> str:
        """One /metrics scrape (Prometheus text)."""
        import urllib.request

        assert self.metrics_port is not None, "metrics port disabled"
        with urllib.request.urlopen(
                f"http://{HOST}:{self.metrics_port}/metrics",
                timeout=30) as r:
            return r.read().decode("utf-8")

    def _await_listening_line(self) -> str:
        """Bounded wait for ONE sync line: select() + os.read on the
        raw fd with our own line buffer.  Two sync lines are read back
        to back (listening + metrics) and they usually arrive in ONE
        pipe chunk — a text-mode readline() would swallow both into
        Python's internal buffer, and a later select() on the fd would
        then block on data that already arrived."""
        import select

        deadline = time.monotonic() + self.STARTUP_TIMEOUT_S
        stdout = self.proc.stdout
        assert stdout is not None
        fd = stdout.fileno()
        while time.monotonic() < deadline:
            if "\n" in self._stdout_buf:
                line, self._stdout_buf = self._stdout_buf.split("\n", 1)
                return line + "\n"
            if self.proc.poll() is not None:
                return ""          # child died before binding
            ready, _, _ = select.select([fd], [], [],
                                        min(1.0, deadline
                                            - time.monotonic()))
            if ready:
                chunk = os.read(fd, 1 << 16)
                if not chunk:
                    return ""      # EOF
                self._stdout_buf += chunk.decode("utf-8", "replace")
        return ""

    def _stderr_tail(self, nbytes: int = 2000) -> str:
        try:
            return self.stderr_path.read_text()[-nbytes:]
        except OSError:
            return "(no stderr captured)"

    def stop(self) -> int:
        import signal

        self.proc.send_signal(signal.SIGTERM)
        try:
            rc = self.proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            rc = -9
        self._stderr_f.close()
        for ln in self._stderr_tail().strip().splitlines()[-3:]:
            log(f"  server| {ln}")
        return rc

    def trace_cut(self) -> int:
        """Current trace line count — the warm-window marker."""
        try:
            return len(self.trace.read_text().splitlines())
        except FileNotFoundError:
            return 0

    def spans_after(self, cut: int) -> list[dict]:
        rows = []
        for ln in self.trace.read_text().splitlines()[cut:]:
            try:
                rows.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
        return rows


# ------------------------------------------------------------ load driving

def run_load(port: int, requests: int, concurrency: int, seed: int,
             ) -> dict:
    """Closed-loop phase: C clients, ``requests`` total, every reply
    verified bit-identical to np.sort of its request.  Returns
    latencies (ok only), statuses, keys, wall seconds."""
    lock = threading.Lock()
    lat: list[float] = []
    statuses: dict[str, int] = {}
    keys = [0]
    bad_parity = [0]
    counter = [0]
    #: plan digests echoed in response headers (ISSUE 12): the
    #: client-visible decision record, folded into the bench row
    plans: list[dict] = []

    def worker(widx: int) -> None:
        rng = np.random.default_rng(seed + widx)
        client = ServeClient(HOST, port)
        try:
            while True:
                with lock:
                    if counter[0] >= requests:
                        return
                    counter[0] += 1
                n = int(2 ** rng.uniform(LOG2_MIN, LOG2_MAX))
                x = rng.integers(-2**31, 2**31 - 1, size=n, dtype=np.int32)
                t0 = time.perf_counter()
                try:
                    r = client.sort(x)
                except (ConnectionError, OSError) as e:
                    # Every CLAIMED request must land in a status
                    # bucket — a silently vanished request would let
                    # the gates pass on a partial measurement.  One
                    # reconnect attempt keeps a dropped keep-alive
                    # (e.g. after a framing-lost rejection) from
                    # wiping the rest of this worker's share.
                    with lock:
                        st = f"client_error:{type(e).__name__}"
                        statuses[st] = statuses.get(st, 0) + 1
                    try:
                        client.close()
                        client = ServeClient(HOST, port)
                        continue
                    except OSError:
                        return
                dt = time.perf_counter() - t0
                with lock:
                    st = "ok" if r.ok else (r.error or "?")
                    statuses[st] = statuses.get(st, 0) + 1
                    if r.ok:
                        lat.append(dt)
                        keys[0] += n
                        if r.plan is not None:
                            plans.append(r.plan)
                        if not np.array_equal(r.arr, np.sort(x)):
                            bad_parity[0] += 1
        finally:
            try:
                client.close()
            except OSError:
                pass

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"wall_s": wall, "latencies": sorted(lat),
            "latency_hist": latency_histogram(lat),
            "statuses": statuses, "keys": keys[0],
            "bad_parity": bad_parity[0],
            "keys_per_s": keys[0] / wall if wall > 0 else 0.0,
            "plan": fold_plans(plans)}


def fold_plans(plans: list) -> dict:
    """Fold response-header plan digests (ISSUE 12) into the summary
    the bench row pins: digest count, algo census, mean regret and the
    bucket set — `report.py --baseline` flags drift in these alongside
    the throughput numbers."""
    regrets = [float(p["regret"]) for p in plans
               if isinstance(p.get("regret"), (int, float))]
    algos: dict = {}
    for p in plans:
        a = str(p.get("algo", "?"))
        algos[a] = algos.get(a, 0) + 1
    return {
        "digests": len(plans),
        "algos": algos,
        "mean_regret": (round(sum(regrets) / len(regrets), 6)
                        if regrets else None),
        "buckets": sorted({int(p["bucket"]) for p in plans
                           if isinstance(p.get("bucket"), int)}),
    }


def latency_histogram(latencies: list) -> dict:
    """Client-observed latency histogram over the SAME fixed buckets —
    and the same bucketing rule (``metrics_live.cumulative_buckets``) —
    as the server's live registry (ISSUE 10), so the two sides line up
    1:1: ``{"le_<bound>": cumulative count}``."""
    out = {f"le_{b:g}": cum for b, cum in metrics_live.cumulative_buckets(
        latencies, metrics_live.LATENCY_BUCKETS_S)}
    out["le_inf"] = len(latencies)
    return out


def reconcile_with_server(prom_text: str, statuses: dict) -> list[str]:
    """The dropped-reply catcher (ISSUE 10 satellite): the server's
    ``sort_serve_requests_total`` MUST equal the client's own reply
    accounting — a silently dropped reply shows up as a server-side
    request with no client-side status.  Also validates the exposition
    format and that every exported name is registered.  Returns a list
    of failures (empty = reconciled)."""
    errs = metrics_live.check_exposition(prom_text)
    try:
        fams = metrics_live.parse_prom_text(prom_text)
    except ValueError as e:
        return errs + [f"/metrics unparseable: {e}"]
    reqs = fams.get("sort_serve_requests_total")
    server_total = int(sum(v for _n, _l, v in reqs["samples"])) \
        if reqs else 0
    # a transport-level client error usually means no server reply —
    # but the server counts a request in _finish BEFORE writing the
    # reply bytes, so a connection dropped mid-reply is counted
    # server-side while the client files it under client_error.  Exact
    # equality is therefore required only against the clean count; each
    # client_error may or may not have a server-side twin.
    client_clean = sum(v for k, v in statuses.items()
                       if not k.startswith("client_error:"))
    client_errors = sum(v for k, v in statuses.items()
                        if k.startswith("client_error:"))
    if not client_clean <= server_total <= client_clean + client_errors:
        errs.append(
            f"request-count reconciliation failed: server counted "
            f"{server_total}, client observed {client_clean} clean "
            f"(+{client_errors} transport errors; statuses {statuses}) "
            "— replies were dropped or double-counted")
    return errs


def phase_stats(name: str, st: dict) -> None:
    lat = st["latencies"]
    log(f"{name}: {sum(st['statuses'].values())} requests "
        f"({st['statuses']}), {st['keys']} keys in {st['wall_s']:.3f}s "
        f"= {st['keys_per_s']/1e6:.3f} Mkeys/s; "
        f"p50 {percentile(lat, 50)*1e3:.2f} ms, "
        f"p99 {percentile(lat, 99)*1e3:.2f} ms")


def measure_phase(out: Path, tag: str, window_ms: str, requests: int,
                  concurrency: int, seed: int,
                  ) -> tuple[dict, list[dict], int]:
    """Spawn a server at the given batch window, warm it, run the
    measured phase; returns (stats, measured-window spans, server rc).
    The default ``SORT_SERVE_SHAPE_BUCKETS`` prewarm covers every
    bucket the packed path can request, so the warm-cache gate holds
    with a default-config server.

    Before shutdown the server's live ``/metrics`` endpoint is scraped
    (ISSUE 10): exposition validated, server-side request count
    reconciled against the client's own accounting over BOTH phases
    (warmup + measured) — failures land in ``stats["metrics_errors"]``
    and fail the selftest leg."""
    srv = Server(out, tag, {
        "SORT_SERVE_BATCH_WINDOW_MS": window_ms,
    })
    try:
        warm = run_load(srv.port, max(16, concurrency), concurrency,
                        seed + 1000)
        phase_stats(f"{tag} warmup", warm)
        cut = srv.trace_cut()
        stats = run_load(srv.port, requests, concurrency, seed)
        phase_stats(tag, stats)
        spans = srv.spans_after(cut)
        combined = dict(warm["statuses"])
        for k, v in stats["statuses"].items():
            combined[k] = combined.get(k, 0) + v
        try:
            prom = srv.scrape_metrics()
        except OSError as e:
            stats["metrics_errors"] = [f"/metrics scrape failed: {e}"]
        else:
            stats["metrics_errors"] = reconcile_with_server(prom, combined)
            (out / f"metrics_{tag}.prom").write_text(prom)
    finally:
        rc = srv.stop()
    return stats, spans, rc


def dispatch_mkeys_per_s(spans: list) -> float:
    """Server-side DISPATCH throughput over a measured window: keys per
    second of dispatch-pipeline wall (``serve.batch`` span durations —
    pack + device sort + verify + split).  This is the quantity
    multi-tenant packing amortizes; client-side closed-loop numbers add
    per-request socket/framing costs that are identical in both modes
    and would mask it."""
    keys = sum(s.get("attrs", {}).get("keys", 0) for s in spans
               if s.get("name") == "serve.batch")
    secs = sum(s.get("dt", 0.0) for s in spans
               if s.get("name") == "serve.batch")
    return keys / secs / 1e6 if secs > 0 else 0.0


def emit_row(stats: dict, extra: dict) -> dict:
    lat = stats["latencies"]
    row = {
        "metric": "serve_small_mix_mkeys_per_s",
        "value": round(stats["keys_per_s"] / 1e6, 3),
        "unit": "Mkeys/s",
        "p50_ms": round(percentile(lat, 50) * 1e3, 3),
        "p99_ms": round(percentile(lat, 99) * 1e3, 3),
        "requests": sum(stats["statuses"].values()),
        "keys": stats["keys"],
        # client-observed latency histogram (same buckets as the
        # server's live registry — the two sides line up 1:1)
        "latency_hist": stats.get("latency_hist"),
        **extra,
    }
    # plan digest summary (ISSUE 12): the decisions the server made for
    # this row's traffic, pinned so decision drift is baseline-flaggable
    p = stats.get("plan") or {}
    if p.get("digests"):
        row["plan_digests"] = p["digests"]
        row["plan_algos"] = p["algos"]
        if p.get("mean_regret") is not None:
            row["plan_regret"] = p["mean_regret"]
        if p.get("buckets"):
            row["plan_buckets"] = p["buckets"]
    print(json.dumps(row), flush=True)
    return row


def record_metrics(stats: dict, speedup: float | None) -> None:
    """SORT_METRICS sidecar (when set): the SLO numbers as metrics so
    the report CLI folds them beside the span-derived table."""
    if not knobs.get("SORT_METRICS"):
        return
    from mpitest_tpu.utils.metrics import Metrics

    lat = stats["latencies"]
    m = Metrics(config={"driver": "serve_load",
                        "mix": f"2^{LOG2_MIN}..2^{LOG2_MAX} int32"})
    m.record("serve_mkeys_per_s", round(stats["keys_per_s"] / 1e6, 3),
             "Mkeys/s")
    m.record("serve_p50_ms", round(percentile(lat, 50) * 1e3, 3), "ms")
    m.record("serve_p99_ms", round(percentile(lat, 99) * 1e3, 3), "ms")
    if speedup is not None:
        m.record("serve_batched_speedup", round(speedup, 3), "x")
    m.dump(knobs.get("SORT_METRICS"))


# ----------------------------------------------------------- planner leg

#: --planner A/B leg (ISSUE 14): both servers run the same deliberately
#: mis-set fixed window — small enough that a closed-loop round's worth
#: of tenants cannot pack — and only the planner-on server may re-size
#: it from the observed mix.  The A/B isolates the tuner's value.
PLANNER_FIXED_WINDOW_MS = "1"

#: Warmup requests of the planner leg: the tuner commits after two
#: consecutive agreeing evaluations (RETUNE_EVERY observations each),
#: so the warm phase must span >= 2 evaluation rounds.
PLANNER_WARMUP_REQUESTS = 96


def planner_phase(out: Path, requests: int, concurrency: int,
                  seed: int) -> dict:
    """Window-auto vs fixed-window dispatch throughput and p99 beside
    the clean row (ISSUE 14).  Both legs keep the full correctness
    contract: every reply bit-identical to ``np.sort``, clean SIGTERM,
    and ``reconcile_with_server`` still exact (the tuner must never
    cost a reply).  Returns the extra row fields (``None`` values when
    a leg failed its correctness checks)."""
    fields: dict = {"planner_fixed_window_ms":
                    float(PLANNER_FIXED_WINDOW_MS),
                    "planner_dispatch_mkeys_per_s": None,
                    "fixed_dispatch_mkeys_per_s": None,
                    "p99_planner_ms": None, "p99_fixed_ms": None,
                    "planner_window_retunes": None}
    legs: dict[str, tuple[dict, list[dict], str]] = {}
    for tag, mode in (("planner_fixed", "off"), ("planner_auto", "on")):
        srv = Server(out, tag, {
            "SORT_SERVE_BATCH_WINDOW_MS": PLANNER_FIXED_WINDOW_MS,
            "SORT_PLANNER": mode,
        })
        try:
            warm = run_load(srv.port, PLANNER_WARMUP_REQUESTS,
                            concurrency, seed + 2000)
            phase_stats(f"{tag} warmup", warm)
            cut = srv.trace_cut()
            stats = run_load(srv.port, requests, concurrency, seed + 2500)
            phase_stats(tag, stats)
            spans = srv.spans_after(cut)
            combined = dict(warm["statuses"])
            for k, v in stats["statuses"].items():
                combined[k] = combined.get(k, 0) + v
            prom = srv.scrape_metrics()
            errs = reconcile_with_server(prom, combined)
        except (OSError, ConnectionError, RuntimeError) as e:
            log(f"planner leg {tag} failed: {e}")
            srv.stop()
            return fields
        rc = srv.stop()
        if rc != 0 or stats["bad_parity"] or errs:
            log(f"planner leg {tag} FAILED correctness: rc={rc} "
                f"bad_parity={stats['bad_parity']} errs={errs}")
            return fields
        (out / f"metrics_{tag}.prom").write_text(prom)
        legs[tag] = (stats, spans, prom)
    fixed_stats, fixed_spans, _ = legs["planner_fixed"]
    auto_stats, auto_spans, auto_prom = legs["planner_auto"]
    retunes = 0.0
    try:
        fams = metrics_live.parse_prom_text(auto_prom)
        ret = fams.get("sort_serve_window_retunes_total")
        if ret:
            retunes = sum(v for _n, _l, v in ret["samples"])
    except ValueError:
        pass
    fields.update({
        "planner_dispatch_mkeys_per_s":
            round(dispatch_mkeys_per_s(auto_spans), 3),
        "fixed_dispatch_mkeys_per_s":
            round(dispatch_mkeys_per_s(fixed_spans), 3),
        "p99_planner_ms":
            round(percentile(auto_stats["latencies"], 99) * 1e3, 3),
        "p99_fixed_ms":
            round(percentile(fixed_stats["latencies"], 99) * 1e3, 3),
        "planner_window_retunes": int(retunes),
    })
    log(f"planner leg: dispatch {fields['planner_dispatch_mkeys_per_s']}"
        f" (auto, {fields['planner_window_retunes']} retune(s)) vs "
        f"{fields['fixed_dispatch_mkeys_per_s']} Mkeys/s (fixed "
        f"{PLANNER_FIXED_WINDOW_MS} ms); p99 {fields['p99_planner_ms']}"
        f" vs {fields['p99_fixed_ms']} ms")
    return fields


# ------------------------------------------------------------- chaos leg

def chaos_phase(out: Path, seed: int) -> dict:
    """p99-under-chaos beside the clean row (ISSUE 11): a fresh server
    behind the chaos proxy's deterministic injected tail
    (``wire_delay_response@CHAOS_DELAY_MS:CHAOS_EVERY``), measured
    twice — plain client, then hedged (``hedge_after_s=0.1``).  Returns
    the extra row fields (``None`` values when the leg failed)."""
    from wire_chaos import ChaosProxy

    from mpitest_tpu.serve.client import ResilientClient

    rng = np.random.default_rng(seed + 7000)
    spec = f"wire_delay_response@{CHAOS_DELAY_MS}:{CHAOS_EVERY}"
    srv = Server(out, "chaosleg", {"SORT_SERVE_BATCH_WINDOW_MS": "0",
                                   "SORT_SERVE_SHAPE_BUCKETS": "10"})
    fields: dict = {"chaos_spec": spec, "p99_chaos_ms": None,
                    "p99_chaos_hedged_ms": None}
    try:
        warm = rng.integers(-2**31, 2**31 - 1, size=512, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            if not c.sort(warm).ok:
                log("chaos leg: warmup failed; skipping")
                return fields

        def run(hedge: "float | None") -> list[float]:
            lats: list[float] = []
            with ChaosProxy(HOST, srv.port, spec) as px:
                client = ResilientClient(HOST, px.port,
                                         read_timeout=30.0,
                                         max_attempts=1,
                                         hedge_after_s=hedge)
                for _ in range(CHAOS_REQUESTS):
                    a = rng.integers(-2**31, 2**31 - 1, size=512,
                                     dtype=np.int32)
                    t0 = time.perf_counter()
                    r = client.sort(a)
                    lats.append(time.perf_counter() - t0)
                    if not (r.ok and np.array_equal(r.arr, np.sort(a))):
                        raise RuntimeError(f"chaos reply bad: {r.header}")
            return sorted(lats)

        plain = run(None)
        hedged = run(0.1)
        fields["p99_chaos_ms"] = round(percentile(plain, 99) * 1e3, 3)
        fields["p99_chaos_hedged_ms"] = round(
            percentile(hedged, 99) * 1e3, 3)
        log(f"chaos leg ({spec}): p99 {fields['p99_chaos_ms']} ms "
            f"plain vs {fields['p99_chaos_hedged_ms']} ms hedged")
    except (OSError, ConnectionError, RuntimeError) as e:
        log(f"chaos leg failed: {e}")
    finally:
        srv.stop()
    return fields


# ---------------------------------------------------------------- selftest

def check_leg(tag: str, stats: dict, rc: int, requests: int,
              fails: list) -> None:
    """Correctness checks EVERY measured leg must pass — retry legs
    included: a leg whose replies are not bit-identical, whose server
    did not drain cleanly, or whose request accounting leaks may not
    contribute to any throughput gate."""
    if rc != 0:
        fails.append(f"{tag}: server exited rc={rc} on SIGTERM")
    if stats["bad_parity"]:
        fails.append(f"{tag}: {stats['bad_parity']} replies were NOT "
                     "bit-identical to np.sort")
    if set(stats["statuses"]) != {"ok"}:
        fails.append(f"{tag}: non-ok statuses under clean load: "
                     f"{stats['statuses']}")
    if sum(stats["statuses"].values()) != requests:
        fails.append(f"{tag}: request accounting mismatch: "
                     f"{sum(stats['statuses'].values())} recorded of "
                     f"{requests} claimed")
    for e in stats.get("metrics_errors", []):
        fails.append(f"{tag}: {e}")


def selftest(out: Path, requests: int, concurrency: int, seed: int) -> int:
    fails: list[str] = []

    # -- 1+2: batched phase, warm-cache gate, then the sequential A/B --
    stats, spans, rc = measure_phase(out, "batched", BATCH_WINDOW_MS,
                                     requests, concurrency, seed)
    check_leg("batched", stats, rc, requests, fails)
    compiles = [s for s in spans if s.get("name") == "jit_compile_execute"]
    misses = [s for s in spans if s.get("name") == "serve.compile_cache"
              and not s.get("attrs", {}).get("hit")]
    if compiles or misses:
        fails.append(f"warm window recorded compile activity: "
                     f"{len(compiles)} jit_compile_execute span(s), "
                     f"{len(misses)} executor-cache miss(es)")
    else:
        log("warm-cache gate OK: zero compile spans in the measured "
            "window")
    batched_reqs = [s for s in spans if s.get("name") == "serve.request"
                    and s.get("attrs", {}).get("batched")]
    if not batched_reqs:
        fails.append("no batched serve.request spans in the measured "
                     "window (batching never engaged)")

    batched_tput = dispatch_mkeys_per_s(spans)
    speedup = None
    for attempt in (1, 2, 3):
        # every attempt is a MATCHED pair measured back to back: on a
        # loaded shared runner either leg can catch a bad patch of
        # machine weather, so a retry re-measures both, never just the
        # denominator
        pre = len(fails)
        if attempt > 1:
            b_stats, b_spans, b_rc = measure_phase(
                out, f"batched{attempt}", BATCH_WINDOW_MS, requests,
                concurrency, seed)
            check_leg(f"batched{attempt}", b_stats, b_rc, requests,
                      fails)
            if len(fails) > pre:
                break     # a corrupt retry leg may not feed the gate
            attempt_tput = dispatch_mkeys_per_s(b_spans)
            batched_tput = max(batched_tput, attempt_tput)
        else:
            attempt_tput = batched_tput
        seq, seq_spans, seq_rc = measure_phase(
            out, f"sequential{attempt}", "0", requests, concurrency,
            seed)
        check_leg(f"sequential{attempt}", seq, seq_rc, requests, fails)
        if len(fails) > pre:
            break
        seq_tput = dispatch_mkeys_per_s(seq_spans)
        if seq_tput > 0:
            ratio = attempt_tput / seq_tput
            speedup = max(speedup or 0.0, ratio)
            log(f"dispatch throughput: batched {attempt_tput:.3f} vs "
                f"sequential {seq_tput:.3f} Mkeys/s -> {ratio:.2f}x "
                f"(closed-loop client: {stats['keys_per_s']/1e6:.3f} vs "
                f"{seq['keys_per_s']/1e6:.3f} Mkeys/s)")
            if speedup >= MIN_BATCH_SPEEDUP:
                break
            if attempt < 3:
                log("below the gate; re-measuring the matched A/B pair "
                    "(shared-runner jitter)")
    if speedup is None or speedup < MIN_BATCH_SPEEDUP:
        fails.append(f"batched dispatch throughput only "
                     f"{speedup or 0:.2f}x sequential "
                     f"(gate {MIN_BATCH_SPEEDUP}x)")
    else:
        log(f"batching gate OK: {speedup:.2f}x >= {MIN_BATCH_SPEEDUP}x")

    # -- 3+4: backpressure typing + per-request fault isolation --------
    srv = Server(out, "limits", {
        "SORT_SERVE_SHAPE_BUCKETS": "10",
        "SORT_SERVE_MAX_INFLIGHT": "1",
        "SORT_SERVE_BATCH_WINDOW_MS": "20",
        "SORT_SERVE_ALLOW_FAULTS": "1",
        "SORT_FALLBACK": "0",
        "SORT_MAX_RETRIES": "0",
        # the result-corruption fault sites live on the DISTRIBUTED
        # sort path; a 1-device CPU process would take the local path
        # and never exercise them, so this server gets a 2-device
        # virtual mesh
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    try:
        burst = run_load(srv.port, 16, 8, seed + 3000)
        log(f"backpressure burst statuses: {burst['statuses']}")
        if burst["statuses"].get("backpressure", 0) < 1:
            fails.append("MAX_INFLIGHT=1 burst produced no typed "
                         "backpressure rejection")
        if burst["statuses"].get("ok", 0) < 1:
            fails.append("MAX_INFLIGHT=1 burst produced no successful "
                         "request (server wedged?)")
        rng = np.random.default_rng(seed)
        x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            r = c.sort(x, faults="result_swap:inf")
            if r.ok or r.error != "integrity":
                fails.append(f"poisoned request: expected typed "
                             f"'integrity' error, got "
                             f"{r.header}")
            else:
                log(f"fault gate: typed error OK ({r.error}: "
                    f"{r.detail[:60]})")
            r2 = c.sort(x)
            if not (r2.ok and np.array_equal(r2.arr, np.sort(x))):
                fails.append("server did not keep serving after the "
                             "poisoned request")
            else:
                log("fault gate OK: server kept serving, next request "
                    "verified")
    finally:
        srv.stop()

    emit_row(stats, {"batched_speedup":
                     round(speedup, 3) if speedup else None,
                     "dispatch_mkeys_per_s": round(batched_tput, 3),
                     "concurrency": concurrency})
    record_metrics(stats, speedup)
    if fails:
        for f in fails:
            log(f"[FAIL] {f}")
        return 1
    log("serve selftest OK (warm cache, batching >= "
        f"{MIN_BATCH_SPEEDUP}x, typed backpressure, per-request fault "
        "isolation, graceful drain)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--selftest", action="store_true",
                    help="run the full serve gate (make serve-selftest)")
    ap.add_argument("--row", action="store_true",
                    help="measure the batched phase only; emit one "
                         "bench JSON row (bench.py serve row)")
    ap.add_argument("--chaos", action="store_true",
                    help="also measure p99 under the chaos proxy's "
                         "injected response-delay tail, plain AND "
                         "hedged, recorded in the row beside the "
                         "clean numbers (ISSUE 11)")
    ap.add_argument("--planner", action="store_true",
                    help="also measure the window-auto vs fixed-window "
                         "A/B (SORT_PLANNER=on vs off at a mis-set "
                         "fixed window), recorded in the row beside "
                         "the clean numbers (ISSUE 14)")
    ap.add_argument("--out", default="/tmp/mpitest_serve_load",
                    help="artifact dir (server traces)")
    ap.add_argument("--requests", type=int, default=160)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    if args.selftest:
        return selftest(out, args.requests, args.concurrency, args.seed)
    # --row (and the bare default): batched measurement + row
    stats, spans, rc = measure_phase(out, "batched", BATCH_WINDOW_MS,
                                     args.requests, args.concurrency,
                                     args.seed)
    if rc != 0:
        log(f"server exited rc={rc}")
        return 1
    if stats["bad_parity"] or set(stats["statuses"]) != {"ok"}:
        log(f"load errors: {stats['statuses']} "
            f"bad_parity={stats['bad_parity']}")
        return 1
    if stats.get("metrics_errors"):
        for e in stats["metrics_errors"]:
            log(f"[FAIL] {e}")
        return 1
    extra = {"concurrency": args.concurrency,
             "dispatch_mkeys_per_s":
             round(dispatch_mkeys_per_s(spans), 3),
             # ISSUE 14: the planner column (the measured phase runs
             # whatever the spawn env set — off unless overridden)
             "planner": str(knobs.get("SORT_PLANNER"))}
    if args.chaos:
        extra.update(chaos_phase(out, args.seed))
    if args.planner:
        extra.update(planner_phase(out, args.requests,
                                   args.concurrency, args.seed))
    emit_row(stats, extra)
    record_metrics(stats, None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
