#!/usr/bin/env python3
"""Self-tuning planner selftest: the `make planner-selftest` gate (ISSUE 14).

The planner's whole claim is that measured policy beats hand-set
defaults.  This gate measures exactly that, TPU-free on the virtual
8-device CPU mesh, against an **adversarial mix** — sorted, near-sorted
(overlapping runs), duplicate-heavy, skewed and uniform inputs, each
requested under the hand-set default config — plus a bursty
small-request serve mix against a deliberately mis-set fixed batching
window:

1. **Throughput gate** — planner-on end-to-end throughput on the
   library mix must be >= :data:`MIX_SPEEDUP_GATE` x planner-off
   (matched A/B pairs, re-measured up to 3x for shared-runner
   weather); the serve leg's window-auto dispatch throughput must be
   >= :data:`SERVE_SPEEDUP_GATE` x the fixed mis-set window.
2. **Regret gate** — aggregate ``plan_regret`` over the mix must be
   STRICTLY lower planner-on than planner-off (the learned cap margin
   alone guarantees a gap on the estimate cells; a planner that wins
   wall-clock while losing regret is mis-accounting its decisions).
3. **Byte-identity gates** — planner-off outputs are bit-identical to
   ``np.sort`` (sorted output is canonical: "today's outputs" is a
   checkable function, not a fixture); ``SORT_PLANNER=shadow`` outputs
   are bit-identical to planner-off byte for byte while every plan
   carries the logged would-have-been ``planner`` decision
   (applied=False); planner-ON outputs are ALSO bit-identical to
   ``np.sort`` — the policies may only choose among correct paths.

Every cell failure prints loudly and the process exits nonzero — this
runs in CI beside the fault/serve/multichip selftests.

``--row`` emits the ``planner_mix_mkeys_per_s`` bench row instead: the
library mix measured ONCE with the planner pinned off (trajectory
comparability, like the `exchange_engine` pin), the planner's win
evidence staying in this selftest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

# Fail-fast supervisor pinning (like the other gates): the A/B must
# compare the two policy modes, never a silently degraded ladder rung.
os.environ.setdefault("SORT_FALLBACK", "0")
os.environ.setdefault("SORT_MAX_RETRIES", "0")
os.environ.setdefault("SORT_EXCHANGE_ENGINE", "lax")

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402

from mpitest_tpu.models import plan as plan_mod  # noqa: E402
from mpitest_tpu.models.api import sort  # noqa: E402
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.metrics import Metrics  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402

#: Library-mix throughput gate: planner-on wall-clock win over the
#: hand-set defaults on the adversarial mix (the ISSUE 14 headline).
MIX_SPEEDUP_GATE = 1.3

#: Serve-leg gate: window-auto dispatch throughput over the mis-set
#: fixed window (bench/serve_load.py planner_phase measures the pair).
SERVE_SPEEDUP_GATE = 1.2

#: Matched-pair re-measurements on shared-runner weather.
MAX_ATTEMPTS = 3


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ------------------------------------------------------------------ mix

def build_mix(seed: int = 0) -> list[tuple[str, np.ndarray, str]]:
    """The adversarial cells: ``(name, keys, requested_algo)``.  Every
    cell is requested under a plausible HAND-SET default (the bench
    default radix, or the reference default sample) — the planner's
    job is to beat exactly that static assignment."""
    # Cell sizes balance two constraints on the CPU-only CI image: XLA
    # CPU compile time for the shard_map programs grows super-linearly
    # with n (the lax pass's n-element iota/searchsorted planes get
    # constant-folded at compile time — 2^20 cells measured MINUTES of
    # compile), while the cap/margin regret needs fair shares well
    # above the 128-lane cap rounding to differentiate the learned
    # margin from x1.25 (2^17 keys -> fair 2048 -> 6% granularity).
    rng = np.random.default_rng(seed)
    cells: list[tuple[str, np.ndarray, str]] = []
    # fully sorted (2^17): the passthrough's home turf — planner-off
    # radix pays every pass + a skew re-stage for one verify's work
    n = 1 << 17
    cells.append(("sorted", np.arange(-(n // 2), n - n // 2,
                                      dtype=np.int32), "radix"))
    # near-sorted (2^16): 32 overlapping ascending runs — ~3% of the
    # strided profile's adjacent pairs decrease (run boundaries), so
    # the scorer reads near_sorted (not sorted) and takes the
    # one-exchange sample path over multi-pass radix
    n = 1 << 16
    runs = 32
    span = (1 << 31) // runs
    base = np.repeat(np.arange(runs, dtype=np.int64) * span,
                     n // runs)
    # sort PER RUN (axis=1): a global sort would leave the whole
    # array sorted and the cell would test the passthrough twice
    off = np.sort(rng.integers(0, 2 * span, size=(runs, n // runs)),
                  axis=1)
    near = (base + off.reshape(-1) - (1 << 30)).astype(np.int32)
    cells.append(("near_sorted", near, "radix"))
    # duplicate-heavy (2^15): 64 distinct values — the measured
    # effective key width collapses the radix pass count; both modes
    # route to radix (sniff vs scored policy), throughput equal
    n = 1 << 15
    cells.append(("dup_heavy",
                  rng.integers(0, 64, size=n).astype(np.int32),
                  "sample"))
    # skewed (2^15): 70% one hot value + uniform tail — degenerate
    # splitters; the reroute-to-radix must fire up front in both modes
    n = 1 << 15
    hot = np.full(int(n * 0.7), 12345, dtype=np.int32)
    tail = rng.integers(-2**31, 2**31 - 1, size=n - hot.size,
                        dtype=np.int32)
    skew = np.concatenate([hot, tail])
    rng.shuffle(skew)
    cells.append(("skewed", skew, "sample"))
    # uniform x3 (2^17): the cap/margin policy's cells — the hand-set
    # x1.25 margin pays ~0.25 cap regret per run against an accurate
    # estimator; the learned margin sizes it from observed quantiles
    for i in range(3):
        cells.append((f"uniform{i}",
                      rng.integers(-2**31, 2**31 - 1, size=1 << 17,
                                   dtype=np.int32), "sample"))
    return cells


def run_mix(cells, mesh, mode: str, verbose: bool = False,
            ) -> tuple[float, float, list[bytes], list[dict]]:
    """One pass over the mix under ``SORT_PLANNER=mode``.  Returns
    (wall seconds, total plan regret, output bytes per cell, planner
    decision dicts per cell).  ``verbose`` logs per-cell wall times —
    compile-bound warmup passes are visible, not silent minutes."""
    outs: list[bytes] = []
    decisions: list[dict] = []
    regret = 0.0
    t0 = time.perf_counter()
    with knobs.scoped_env(SORT_PLANNER=mode):
        for name, x, algo in cells:
            tc = time.perf_counter()
            tracer = Tracer()
            out = sort(x, algorithm=algo, mesh=mesh, tracer=tracer)
            outs.append(out.tobytes())
            regret += float(tracer.counters.get("plan_regret", 0.0))
            p = tracer.plan
            d = {}
            if isinstance(p, plan_mod.SortPlan) and \
                    "planner" in p.decisions:
                d = p.decisions["planner"].to_dict()
            decisions.append(d)
            if verbose:
                log(f"    [{mode}] {name}: "
                    f"{time.perf_counter() - tc:.3f}s")
    wall = time.perf_counter() - t0
    return wall, regret, outs, decisions


def mix_keys(cells) -> int:
    return sum(int(x.size) for _n, x, _a in cells)


# ------------------------------------------------------------- selftest

def selftest(out_dir: Path, seed: int) -> int:
    import serve_load

    fails: list[str] = []
    mesh = make_mesh(8)
    cells = build_mix(seed)
    total_keys = mix_keys(cells)
    refs = [np.sort(x).tobytes() for _n, x, _a in cells]

    # -- byte-identity: planner-off == today's outputs (np.sort is the
    # canonical definition of "today" — sorted output is bit-exact)
    log(f"mix: {len(cells)} cells, {total_keys} keys; warmup (off)")
    run_mix(cells, mesh, "off", verbose=True)   # compile warmup, untimed
    wall_off, regret_off, outs_off, dec_off = run_mix(cells, mesh, "off")
    for (name, _x, _a), got, ref in zip(cells, outs_off, refs):
        if got != ref:
            fails.append(f"planner-off output NOT bit-identical to "
                         f"np.sort on cell {name}")
    if any(d for d in dec_off):
        fails.append("planner-off minted planner decisions "
                     f"({dec_off}) — off must be the pre-planner "
                     "stack byte for byte")

    # -- shadow: provably no output-byte change, decisions logged
    _w, _r, outs_sh, dec_sh = run_mix(cells, mesh, "shadow")
    for (name, _x, _a), got, ref in zip(cells, outs_sh, outs_off):
        if got != ref:
            fails.append(f"SHADOW output differs from planner-off on "
                         f"cell {name} (shadow must be byte-identical)")
    for (name, _x, _a), d in zip(cells, dec_sh):
        if not d:
            fails.append(f"shadow logged no planner decision on cell "
                         f"{name}")
        elif (d.get("predicted") or {}).get("applied") is not False:
            fails.append(f"shadow planner decision on {name} not "
                         f"marked applied=False: {d}")

    # -- ON warmup: compiles the planner-path programs AND seeds the
    # flight ring with estimate decisions the margin policy learns from
    log("warmup (on)")
    run_mix(cells, mesh, "on", verbose=True)

    # -- throughput + regret gates: matched A/B pairs ------------------
    speedup = None
    wall_on = regret_on = 0.0
    for attempt in range(1, MAX_ATTEMPTS + 1):
        if attempt > 1:
            log(f"attempt {attempt}: re-measuring the matched pair "
                "(shared-runner weather)")
            wall_off, regret_off, outs_off2, _d = run_mix(cells, mesh,
                                                          "off")
            if outs_off2 != refs:
                fails.append("planner-off retry output drifted from "
                             "np.sort")
                break
        wall_on, regret_on, outs_on, dec_on = run_mix(cells, mesh, "on")
        for (name, _x, _a), got, ref in zip(cells, outs_on, refs):
            if got != ref:
                fails.append(f"planner-ON output NOT bit-identical to "
                             f"np.sort on cell {name}")
        if fails:
            break
        speedup = wall_off / wall_on if wall_on > 0 else 0.0
        log(f"mix wall: off {wall_off:.3f}s vs on {wall_on:.3f}s -> "
            f"{speedup:.2f}x; regret off {regret_off:.4f} vs on "
            f"{regret_on:.4f}")
        for (name, _x, _a), d in zip(cells, dec_on):
            log(f"  cell {name}: policy={d.get('chosen')} "
                f"trigger={d.get('trigger')} regret={d.get('regret')}")
        if speedup >= MIX_SPEEDUP_GATE and regret_on < regret_off:
            break
    if speedup is None or speedup < MIX_SPEEDUP_GATE:
        fails.append(f"planner-on mix throughput only "
                     f"{speedup or 0:.2f}x planner-off "
                     f"(gate {MIX_SPEEDUP_GATE}x)")
    else:
        log(f"throughput gate OK: {speedup:.2f}x >= {MIX_SPEEDUP_GATE}x")
    if not (regret_on < regret_off):
        fails.append(f"aggregate plan_regret not strictly lower "
                     f"planner-on ({regret_on:.4f}) vs planner-off "
                     f"({regret_off:.4f})")
    else:
        log(f"regret gate OK: {regret_on:.4f} < {regret_off:.4f}")

    # -- serve leg: window-auto vs mis-set fixed window ----------------
    serve_fields: dict = {}
    for attempt in range(1, MAX_ATTEMPTS + 1):
        serve_fields = serve_load.planner_phase(out_dir, requests=128,
                                                concurrency=8,
                                                seed=seed + attempt)
        auto = serve_fields.get("planner_dispatch_mkeys_per_s")
        fixed = serve_fields.get("fixed_dispatch_mkeys_per_s")
        if auto and fixed and fixed > 0:
            ratio = auto / fixed
            log(f"serve leg: auto {auto:.3f} vs fixed {fixed:.3f} "
                f"Mkeys/s -> {ratio:.2f}x "
                f"({serve_fields.get('planner_window_retunes')} "
                "retune(s))")
            if ratio >= SERVE_SPEEDUP_GATE:
                break
            if attempt < MAX_ATTEMPTS:
                log("below the serve gate; re-measuring the A/B pair")
        else:
            fails.append(f"serve planner leg failed: {serve_fields}")
            break
    auto = serve_fields.get("planner_dispatch_mkeys_per_s")
    fixed = serve_fields.get("fixed_dispatch_mkeys_per_s")
    if auto and fixed and fixed > 0:
        if auto / fixed < SERVE_SPEEDUP_GATE:
            fails.append(f"window-auto dispatch only "
                         f"{auto / fixed:.2f}x the fixed window "
                         f"(gate {SERVE_SPEEDUP_GATE}x)")
        else:
            log(f"serve gate OK: {auto / fixed:.2f}x >= "
                f"{SERVE_SPEEDUP_GATE}x")
        if not serve_fields.get("planner_window_retunes"):
            fails.append("window-auto server committed zero retunes "
                         "(the tuner never engaged)")

    # -- artifacts -----------------------------------------------------
    metrics_path = knobs.get("SORT_METRICS")
    if metrics_path:
        m = Metrics(config={"driver": "planner_selftest",
                            "cells": [n for n, _x, _a in cells]})
        if speedup is not None:
            m.record("planner_mix_speedup", round(speedup, 3), "x")
        m.record("planner_regret_off", round(regret_off, 4), "x")
        m.record("planner_regret_on", round(regret_on, 4), "x")
        m.dump(metrics_path)
    if fails:
        for f in fails:
            log(f"[FAIL] {f}")
        return 1
    log(f"planner selftest OK (mix {speedup:.2f}x >= "
        f"{MIX_SPEEDUP_GATE}x, regret {regret_on:.4f} < "
        f"{regret_off:.4f}, shadow byte-identical, serve window-auto "
        f">= {SERVE_SPEEDUP_GATE}x)")
    return 0


# ----------------------------------------------------------------- row

def emit_row(seed: int) -> int:
    """``--row``: the ``planner_mix_mkeys_per_s`` bench row — the
    adversarial mix measured with the planner PINNED OFF (trajectory
    comparability, like the exchange_engine pin; the planner's win
    lives in the selftest, not the measured row)."""
    os.environ.setdefault("SORT_PLANNER", "off")
    mesh = make_mesh(8)
    cells = build_mix(seed)
    run_mix(cells, mesh, knobs.get("SORT_PLANNER"))       # warmup
    wall, regret, outs, _d = run_mix(cells, mesh,
                                     knobs.get("SORT_PLANNER"))
    refs = [np.sort(x).tobytes() for _n, x, _a in cells]
    if outs != refs:
        log("planner row: CORRECTNESS FAILURE — reporting value 0")
        wall = float("inf")
    row = {"metric": "planner_mix_mkeys_per_s",
           "value": round(mix_keys(cells) / wall / 1e6, 3)
           if wall != float("inf") else 0.0,
           "unit": "Mkeys/s",
           "cells": [n for n, _x, _a in cells],
           "plan_regret": round(regret, 6),
           "planner": str(knobs.get("SORT_PLANNER"))}
    print(json.dumps(row), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/mpitest_planner_selftest",
                    help="artifact dir (serve-leg server traces)")
    ap.add_argument("--row", action="store_true",
                    help="emit the planner_mix_mkeys_per_s bench row "
                         "(planner pinned off) instead of the gate")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.row:
        return emit_row(args.seed)
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    return selftest(out, args.seed)


if __name__ == "__main__":
    sys.exit(main())
