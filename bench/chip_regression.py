#!/usr/bin/env python3
"""One-command ON-CHIP regression gate (VERDICT r3 #3).

The interpret-mode tests (`tests/test_bitonic.py`, `test_pallas_pack.py`)
catch logic bugs and the AOT compiles catch lowering breaks, but real-
Mosaic *numerics* — what the hardware actually computes — were previously
only checked in manual sessions.  This script is the recorded gate: run
``make chip-test`` (or ``python -u bench/chip_regression.py``) in any
session with a real TPU attached; it finishes in minutes and appends one
JSONL row to ``bench/BASELINE_RESULTS.jsonl``.

Checks (all correctness verdicts computed ON DEVICE — scalars, not
hundreds of MB, cross this image's tunnel; see the verify skill):

1. Real-Mosaic bitonic engine vs ``lax.sort`` at 2^26: bit-equal output
   (the engines must agree exactly — sorted uint32 is canonical), plus
   slope-method timing of both (recorded, not gated: tunnel variance is
   ±15-20%; the ratio is the number to eyeball against BASELINE.md's
   1.6-2.2x).
2. ``segment_pack`` (the Pallas DMA exchange pack) vs a numpy reference
   on ragged segments.
3. The 5-pattern adversarial battery (sorted / reverse / all-equal /
   few-distinct / organ-pipe) at 2^26 through the real kernels, verified
   on device by sortedness + sum/xor multiset invariants.

Exit 0 = all correctness checks passed (timings are informational).
Exit 2 = no TPU attached (the gate is meaningless in interpret mode).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() not in ("tpu", "axon"):
        print("chip_regression: no real TPU attached "
              f"(backend={jax.default_backend()}); refusing to gate on "
              "interpret-mode numerics", flush=True)
        return 2

    from mpitest_tpu.ops import bitonic
    from mpitest_tpu.ops.pallas_kernels import CHUNK, segment_pack

    row: dict = {"ts": time.time(), "config": "chip_regression"}
    ok = True

    # ---- 1. bitonic vs lax.sort @ 2^26: bit-equal + slope timings ----
    log2n = 26
    n = 1 << log2n
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64).astype(np.uint32))

    @jax.jit
    def both_agree(v):
        b = bitonic.sort_padded(v, n, bitonic.BLOCK_LOG2)
        l = jax.lax.sort([v], num_keys=1, is_stable=False)[0]
        return jnp.all(b == l)

    t0 = time.perf_counter()
    agree = bool(jax.device_get(both_agree(x)))
    print(f"bitonic==lax.sort @2^{log2n}: {'OK' if agree else 'FAIL'} "
          f"({time.perf_counter() - t0:.1f}s incl. compile)", flush=True)
    row["bitonic_matches_lax"] = agree
    ok &= agree

    def slope(fn, args, reps=(1, 3), tries=3):
        """Slope-method device time of ``fn`` (operand tuple -> operand
        tuple), with a forced scalar fetch after each timed call —
        block_until_ready is advisory over this image's tunnel."""
        out = {}
        for r in reps:
            @jax.jit
            def g(ops, r=r):
                for _ in range(r):
                    ops = fn(*ops)
                return ops
            y = g(args)
            jax.device_get(y[0][:1])
            ts = []
            for _ in range(tries):
                t = time.perf_counter()
                y = g(args)
                jax.device_get(y[0][:1])
                ts.append(time.perf_counter() - t)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0])

    bit_ms = slope(
        lambda v: (bitonic.sort_padded(v, n, bitonic.BLOCK_LOG2),), (x,)) * 1e3
    lax_ms = slope(
        lambda v: (jax.lax.sort([v], num_keys=1, is_stable=False)[0],),
        (x,)) * 1e3
    ratio = lax_ms / bit_ms if bit_ms > 0 else float("nan")
    print(f"bitonic {bit_ms:.1f} ms  lax.sort {lax_ms:.1f} ms  "
          f"ratio {ratio:.2f}x (BASELINE.md regression band: 2.0-4.2x "
          "post-relayout; r4 band was 1.6-2.2x)",
          flush=True)
    row.update(bitonic_ms=round(bit_ms, 1), lax_sort_ms=round(lax_ms, 1),
               bitonic_speedup=round(ratio, 2))

    # ---- 1b. 64-bit pair engine vs variadic lax.sort: bit-equal + slope ----
    from mpitest_tpu.ops import kernels

    lo2 = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                      .astype(np.uint32))

    @jax.jit
    def pair_agree(h, l):
        hs, ls, bad = kernels.sort_two_words_bitonic(h, l)
        ref = jax.lax.sort([h, l], num_keys=2, is_stable=False)
        return jnp.all(hs == ref[0]) & jnp.all(ls == ref[1]) & ~bad

    pagree = bool(jax.device_get(pair_agree(x, lo2)))
    print(f"pair engine == lax.sort 2w @2^{log2n}: "
          f"{'OK' if pagree else 'FAIL'}", flush=True)
    row["pair_matches_lax"] = pagree
    ok &= pagree

    pair_ms = slope(
        lambda h, l: kernels.sort_two_words_bitonic(h, l)[:2],
        (x, lo2)) * 1e3
    lax2_ms = slope(
        lambda h, l: tuple(jax.lax.sort([h, l], num_keys=2,
                                        is_stable=False)),
        (x, lo2)) * 1e3
    pratio = lax2_ms / pair_ms if pair_ms > 0 else float("nan")
    print(f"pair {pair_ms:.1f} ms  lax.sort-2w {lax2_ms:.1f} ms  "
          f"ratio {pratio:.2f}x (regression band: 1.5-2.3x post-relayout; "
          "r4 band was 1.25-1.45x)", flush=True)
    row.update(pair_ms=round(pair_ms, 1), lax_sort_2w_ms=round(lax2_ms, 1),
               pair_speedup=round(pratio, 2))

    # ---- 2. segment_pack vs numpy on ragged segments ----
    P = 8
    nd = 1 << 20
    cnts = rng.integers(0, nd // P, P).astype(np.int32)
    starts = np.concatenate([[0], np.cumsum(cnts)[:-1]]).astype(np.int32)
    data = rng.integers(0, 2**32, nd, dtype=np.uint64).astype(np.uint32)
    cap = int(-(-int(cnts.max()) // CHUNK) * CHUNK)
    got = np.asarray(segment_pack(jnp.asarray(data), jnp.asarray(starts),
                                  jnp.asarray(cnts), cap, P, fill=0))
    want = np.zeros((P, cap), np.uint32)
    for p in range(P):
        want[p, : cnts[p]] = data[starts[p]: starts[p] + cnts[p]]
    pack_ok = bool(np.array_equal(got, want))
    print(f"segment_pack ragged [P={P}, cap={cap}]: "
          f"{'OK' if pack_ok else 'FAIL'}", flush=True)
    row["segment_pack_ok"] = pack_ok
    ok &= pack_ok

    # ---- 3. adversarial pattern battery @ 2^26 on the real kernels ----
    @jax.jit
    def sort_and_check(v):
        out = bitonic.sort_padded(v, n, bitonic.BLOCK_LOG2)
        is_sorted = jnp.all(out[1:] >= out[:-1])
        xor = lambda a: jax.lax.reduce(a, jnp.uint32(0),  # sortlint: disable=SL010 -- single-device jit checksum, no SPMD partitioner
                                       jax.lax.bitwise_xor, (0,))
        return is_sorted, v.sum() == out.sum(), xor(v) == xor(out)

    pats = {
        "sorted": np.arange(n, dtype=np.uint32),
        "reverse": np.arange(n, 0, -1).astype(np.uint32),
        "all-equal": np.full(n, 0xABCD1234, np.uint32),
        "few-distinct": rng.integers(0, 3, n).astype(np.uint32),
        "organ-pipe": np.concatenate([
            np.arange(n // 2, dtype=np.uint32),
            np.arange(n // 2, 0, -1).astype(np.uint32)]),
    }
    pat_ok = True
    for name, p in pats.items():
        checks = [bool(t) for t in jax.device_get(sort_and_check(jnp.asarray(p)))]
        good = all(checks)
        pat_ok &= good
        print(f"adversarial {name} @2^{log2n}: {'OK' if good else f'FAIL {checks}'}",
              flush=True)
    row["patterns_ok"] = pat_ok
    ok &= pat_ok

    row["all_ok"] = ok
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"chip_regression: {'ALL OK' if ok else 'FAILURES'} "
          f"(row appended to {RESULTS.name})", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
