#!/usr/bin/env python3
"""Wire-chaos matrix: the `make chaos-serve-selftest` gate (ISSUE 11).

Drives a REAL spawned ``sort_server`` through the chaos TCP proxy
(``bench/wire_chaos.py``) and a hostile raw socket, one wire-fault
cell at a time, and asserts the request-lifecycle robustness contract
after EVERY cell:

    the server is alive (``/healthz`` reachable), its in-flight
    admission bytes are back to 0 (scraped from ``/metrics`` within
    the read timeout), no handler threads leaked (the ``/healthz``
    thread census returns to its baseline), and a clean follow-up
    request is served bit-exact.

Cells:

* ``wire_torn_header``         — client dies mid-header.
* ``wire_stall_payload``       — slow-loris: payload stalls at byte k;
  the server must disconnect it within ``SORT_SERVE_READ_TIMEOUT_S``
  and reclaim the admitted bytes (the PR 7 leak this PR fixes).
* killed mid-payload           — raw socket RST halfway through the
  payload (the satellite regression: admission bytes to 0).
* ``wire_slow_drip``           — one byte trickle: per-chunk progress,
  so only the TOTAL read budget bounds it.
* ``wire_disconnect_response`` — network dies mid-download: the
  client's problem, never the server's.
* ``wire_connect_silence``     — the resilient client gives up within
  its bounded retry budget instead of hanging.
* watchdog drill               — a per-request ``dispatch_stall``
  fault wedges the REAL dispatch thread past
  ``SORT_SERVE_DISPATCH_TIMEOUT_S``: the watchdog must trip
  (``/healthz`` 503, fast typed rejections, a flight-recorder
  artifact that passes ``report.py --check``), then the breaker must
  half-open and recover WITHOUT a restart once the dispatch returns.
* hedging cell                 — deterministic injected tail (every
  4th connection's response held 700 ms): hedged p99 must be
  STRICTLY below the unhedged p99 on the same fault schedule.

Runs TPU-free (plain 1-device CPU backend; the faults live on the
wire and in the dispatch thread, not in the device math).
"""

from __future__ import annotations

import json
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "bench"))

from serve_load import HOST, Server, log                     # noqa: E402
from wire_chaos import ChaosProxy                            # noqa: E402

from mpitest_tpu.report import percentile                    # noqa: E402
from mpitest_tpu.serve.client import (                       # noqa: E402
    ResilientClient, ServeClient)
from mpitest_tpu.utils import metrics_live                   # noqa: E402

#: Server-side wire budget for the stall cells — every stalled
#: connection must be shed (and its bytes reclaimed) within this.
READ_TIMEOUT_S = 2.0

#: Injected response delay of the hedging cell (ms) and its cadence.
TAIL_DELAY_MS = 700
TAIL_EVERY = 4

results: list[tuple[str, bool, str]] = []


def cell(name: str, ok: bool, detail: str) -> None:
    results.append((name, ok, detail))
    print(f"  {'ok ' if ok else 'BAD'} {name:<34} {detail}", flush=True)


# ------------------------------------------------------------ scraping

def scrape(port: int, route: str) -> tuple[int, str]:
    req = urllib.request.Request(f"http://{HOST}:{port}{route}")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def inflight_bytes(metrics_port: int) -> float:
    _code, text = scrape(metrics_port, "/metrics")
    fams = metrics_live.parse_prom_text(text)
    fam = fams.get("sort_serve_inflight_bytes")
    if not fam or not fam["samples"]:
        return 0.0
    return sum(v for _n, _l, v in fam["samples"])


def counter_total(metrics_port: int, name: str) -> float:
    _code, text = scrape(metrics_port, "/metrics")
    fams = metrics_live.parse_prom_text(text)
    fam = fams.get(name)
    if not fam:
        return 0.0
    return sum(v for n, _l, v in fam["samples"] if n == name)


def healthz(metrics_port: int) -> tuple[int, dict]:
    code, text = scrape(metrics_port, "/healthz")
    return code, json.loads(text)


def wait_until(pred, timeout_s: float, interval: float = 0.1) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------- the invariant

def assert_recovered(name: str, srv: Server, baseline_threads: int,
                     rng: np.random.Generator) -> None:
    """The post-cell contract every chaos cell must satisfy."""
    assert srv.metrics_port is not None
    # 1. admission bytes provably reclaimed within the read budget
    bytes_ok = wait_until(
        lambda: inflight_bytes(srv.metrics_port) == 0,
        READ_TIMEOUT_S + 3.0)
    # 2. handler threads reclaimed (the stalled one exits at the
    #    budget; +1 slack for a scrape handler mid-flight)
    def threads_ok() -> bool:
        code, h = healthz(srv.metrics_port)
        return h["threads"] <= baseline_threads + 1
    th_ok = wait_until(threads_ok, READ_TIMEOUT_S + 3.0)
    # 3. server alive and serving: a clean follow-up request bit-exact
    x = rng.integers(-2**31, 2**31 - 1, size=700, dtype=np.int32)
    try:
        with ServeClient(HOST, srv.port, timeout=30) as c:
            r = c.sort(x)
        clean_ok = bool(r.ok and np.array_equal(r.arr, np.sort(x)))
    except (OSError, ConnectionError) as e:
        clean_ok = False
        r = None
    detail = (f"inflight0={bytes_ok} threads={th_ok} "
              f"follow-up={'ok' if clean_ok else 'FAILED'}")
    cell(name, bytes_ok and th_ok and clean_ok, detail)


# ----------------------------------------------------------- the cells

def wire_cells(out: Path, rng: np.random.Generator) -> None:
    srv = Server(out, "chaos", {
        "SORT_SERVE_SHAPE_BUCKETS": "10",
        "SORT_SERVE_READ_TIMEOUT_S": str(READ_TIMEOUT_S),
        "SORT_SERVE_IDLE_TIMEOUT_S": "60",
    })
    try:
        assert srv.metrics_port is not None
        # warm once so compiles / lazy series are out of the way
        x = rng.integers(-2**31, 2**31 - 1, size=700, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            assert c.sort(x).ok
        _code, h = healthz(srv.metrics_port)
        baseline = h["threads"]
        log(f"chaos server up (baseline threads={baseline})")

        # -- torn header ------------------------------------------
        with ChaosProxy(HOST, srv.port, "wire_torn_header@5") as px:
            try:
                ServeClient(HOST, px.port, timeout=5).sort(x)
                outcome = "reply?!"
            except (OSError, ConnectionError):
                outcome = "conn error (expected)"
        log(f"torn header: client saw {outcome}")
        assert_recovered("wire_torn_header", srv, baseline, rng)

        # -- stalled payload at byte k (slow-loris) ----------------
        t0 = time.monotonic()
        with ChaosProxy(HOST, srv.port, "wire_stall_payload@64") as px:
            try:
                r = ServeClient(HOST, px.port,
                                timeout=READ_TIMEOUT_S + 8).sort(x)
                outcome = f"typed {r.error}"
            except (OSError, ConnectionError):
                outcome = "conn closed"
            shed_s = time.monotonic() - t0
        within = shed_s <= READ_TIMEOUT_S + 3.0
        log(f"stalled payload: {outcome} after {shed_s:.2f}s "
            f"(read timeout {READ_TIMEOUT_S:g}s)")
        cell("stall shed within read timeout", within,
             f"{shed_s:.2f}s <= {READ_TIMEOUT_S + 3.0:g}s")
        assert_recovered("wire_stall_payload", srv, baseline, rng)

        # -- killed mid-payload (raw RST; the satellite regression) -
        big = rng.integers(-2**31, 2**31 - 1, size=1 << 16,
                           dtype=np.int32)
        hdr = json.dumps({"v": "sortserve.v1", "dtype": "int32",
                          "n": int(big.size)}).encode() + b"\n"
        s = socket.create_connection((HOST, srv.port), timeout=10)
        s.sendall(hdr + big.tobytes()[: big.nbytes // 2])
        time.sleep(0.2)      # let the server start (and block on) the read
        # RST, not FIN: the kill -9 shape, no orderly shutdown
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.close()
        assert_recovered("killed mid-payload", srv, baseline, rng)

        # -- slow-drip writes (progress per chunk, budget still binds)
        with ChaosProxy(HOST, srv.port, "wire_slow_drip@300") as px:
            try:
                r = ServeClient(HOST, px.port,
                                timeout=READ_TIMEOUT_S + 8).sort(big)
                outcome = f"typed {r.error}"
            except (OSError, ConnectionError):
                outcome = "conn closed"
        log(f"slow drip: {outcome}")
        assert_recovered("wire_slow_drip", srv, baseline, rng)

        # -- mid-response disconnect -------------------------------
        with ChaosProxy(HOST, srv.port,
                        "wire_disconnect_response@16") as px:
            try:
                ServeClient(HOST, px.port, timeout=10).sort(x)
                outcome = "reply?!"
            except (OSError, ConnectionError):
                outcome = "short response (expected)"
        log(f"mid-response disconnect: {outcome}")
        assert_recovered("wire_disconnect_response", srv, baseline, rng)

        # -- connect-then-silence: the client must give up, bounded -
        with ChaosProxy(HOST, srv.port, "wire_connect_silence") as px:
            rc = ResilientClient(HOST, px.port, connect_timeout=1.0,
                                 read_timeout=1.0, max_attempts=2,
                                 backoff_s=0.05)
            t0 = time.monotonic()
            try:
                rc.sort(x)
                bounded = False
            except (OSError, ConnectionError):
                bounded = (time.monotonic() - t0) < 10.0
        cell("wire_connect_silence bounded", bounded,
             f"gave up in {time.monotonic() - t0:.2f}s after "
             f"{rc.stats['attempts']} attempt(s)")
        assert_recovered("wire_connect_silence", srv, baseline, rng)

        # enforced timeouts must be visible in /metrics
        timeouts = counter_total(srv.metrics_port,
                                 "sort_serve_timeouts_total")
        cell("timeouts_total exported", timeouts >= 2.0,
             f"sort_serve_timeouts_total={timeouts:g}")
    finally:
        rc_stop = srv.stop()
        cell("chaos server SIGTERM drain", rc_stop == 0,
             f"rc={rc_stop}")


def watchdog_cell(out: Path, rng: np.random.Generator) -> None:
    srv = Server(out, "watchdog", {
        "SORT_SERVE_SHAPE_BUCKETS": "10",
        "SORT_SERVE_ALLOW_FAULTS": "1",
        "SORT_FAULT_STALL_MS": "4000",
        "SORT_SERVE_DISPATCH_TIMEOUT_S": "1",
        "SORT_SERVE_BREAKER_BACKOFF_S": "0.5",
        "SORT_FLIGHT_RECORDER_DIR": str(out / "flightrec"),
        # the dispatch fault sites live on the DISTRIBUTED sort path
        # (supervisor.dispatch); a 1-device process takes the fused
        # local path and would never stall — same 2-device virtual
        # mesh the serve-selftest fault leg uses
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    try:
        assert srv.metrics_port is not None
        x = rng.integers(-2**31, 2**31 - 1, size=700, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            assert c.sort(x).ok            # warm
        stalled: dict = {}

        def stalled_request() -> None:
            try:
                with ServeClient(HOST, srv.port, timeout=60) as c:
                    stalled["reply"] = c.sort(x, faults="dispatch_stall")
            except (OSError, ConnectionError) as e:
                stalled["exc"] = e

        t = threading.Thread(target=stalled_request, daemon=True)
        t.start()
        tripped = wait_until(
            lambda: healthz(srv.metrics_port)[0] == 503, 3.5)
        cell("watchdog trips -> healthz 503", tripped,
             f"breaker={healthz(srv.metrics_port)[1].get('breaker')}")
        # while open: admission is a FAST typed rejection
        try:
            with ServeClient(HOST, srv.port, timeout=10) as c:
                r = c.sort(x)
            fast_reject = (not r.ok) and r.error == "backpressure"
            detail = f"error={r.error}"
        except (OSError, ConnectionError) as e:
            fast_reject, detail = False, f"transport: {e}"
        cell("breaker fast-rejects typed", fast_reject, detail)
        # the wedged dispatch returns at ~4s; the half-open probe
        # must then close the breaker WITHOUT a restart
        recovered = wait_until(
            lambda: healthz(srv.metrics_port)[0] == 200, 20.0)
        cell("breaker half-opens and recovers", recovered,
             f"breaker={healthz(srv.metrics_port)[1].get('breaker')}")
        t.join(timeout=30)
        r = stalled.get("reply")
        cell("stalled request still served", bool(r is not None and r.ok),
             f"reply={'ok' if r is not None and r.ok else stalled}")
        trips = counter_total(srv.metrics_port,
                              "sort_serve_watchdog_trips_total")
        cell("watchdog_trips_total exported", trips >= 1.0,
             f"{trips:g} trip(s)")
        # clean request after recovery
        with ServeClient(HOST, srv.port, timeout=30) as c:
            r2 = c.sort(x)
        cell("post-recovery request ok",
             bool(r2.ok and np.array_equal(r2.arr, np.sort(x))),
             f"ok={r2.ok}")
        # flight-recorder artifact: exists and passes report --check
        artifacts = sorted((out / "flightrec").glob(
            "flight-*-watchdog-*.jsonl"))
        if not artifacts:
            cell("watchdog flight artifact", False, "no artifact written")
        else:
            chk = subprocess.run(
                [sys.executable, "-m", "mpitest_tpu.report", "--check",
                 str(artifacts[-1])],
                capture_output=True, text=True, cwd=str(REPO),
                timeout=120)
            cell("watchdog flight artifact", chk.returncode == 0,
                 f"{artifacts[-1].name}: report --check rc="
                 f"{chk.returncode}"
                 + ("" if chk.returncode == 0
                    else f" ({chk.stderr.strip()[:120]})"))
    finally:
        srv.stop()


def hedging_cell(out: Path, rng: np.random.Generator) -> None:
    """Injected-tail p99: hedged strictly below unhedged on the SAME
    deterministic fault schedule (every 4th connection's response held
    TAIL_DELAY_MS)."""
    srv = Server(out, "hedge", {
        "SORT_SERVE_SHAPE_BUCKETS": "10",
        "SORT_SERVE_BATCH_WINDOW_MS": "0",
    })
    try:
        x = rng.integers(-2**31, 2**31 - 1, size=700, dtype=np.int32)
        with ServeClient(HOST, srv.port) as c:
            assert c.sort(x).ok            # warm
        spec = f"wire_delay_response@{TAIL_DELAY_MS}:{TAIL_EVERY}"
        n_req = 24

        def run(hedge: "float | None") -> list[float]:
            lats = []
            with ChaosProxy(HOST, srv.port, spec) as px:
                client = ResilientClient(
                    HOST, px.port, read_timeout=30.0, max_attempts=1,
                    hedge_after_s=hedge)
                for i in range(n_req):
                    a = rng.integers(-2**31, 2**31 - 1, size=512,
                                     dtype=np.int32)
                    t0 = time.perf_counter()
                    r = client.sort(a)
                    lats.append(time.perf_counter() - t0)
                    assert r.ok and np.array_equal(r.arr, np.sort(a)), \
                        f"hedging cell reply {i} bad: {r.header}"
            return sorted(lats)

        unhedged = run(None)
        hedged = run(0.1)
        p99_u = percentile(unhedged, 99) * 1e3
        p99_h = percentile(hedged, 99) * 1e3
        log(f"hedging: unhedged p50 {percentile(unhedged, 50)*1e3:.1f} "
            f"p99 {p99_u:.1f} ms; hedged p50 "
            f"{percentile(hedged, 50)*1e3:.1f} p99 {p99_h:.1f} ms")
        cell("hedged p99 < unhedged p99", p99_h < p99_u,
             f"{p99_h:.1f} ms < {p99_u:.1f} ms "
             f"(injected tail {TAIL_DELAY_MS} ms on every "
             f"{TAIL_EVERY}th connection)")
    finally:
        srv.stop()


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="/tmp/mpitest_chaos_selftest")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(args.seed)

    print("wire-chaos cells: server survives, bytes reclaimed, "
          "threads bounded, next request served")
    wire_cells(out, rng)
    print("watchdog drill: wedged dispatch -> trip -> half-open -> "
          "recover")
    watchdog_cell(out, rng)
    print("hedging: injected-tail p99 strictly cut")
    hedging_cell(out, rng)

    n_bad = sum(1 for _n, ok, _d in results if not ok)
    print(f"\nchaos-serve-selftest: {len(results) - n_bad}/"
          f"{len(results)} cells clean ({n_bad} failing)")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
