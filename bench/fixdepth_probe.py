#!/usr/bin/env python3
"""Price the equal-hi run fix-up depth (VERDICT r4 weak #3 / next #3).

Today runs longer than ``fix_passes=8`` that evade the 1024-key sniff
cost pair-network + full ``lax.sort`` (the residual fallback) — worst
case ~2.4x ``lax.sort`` alone.  The mid-tier candidate: deeper in-VMEM
fix-up (the kernel already takes ``passes``).  This probe prices, on
chip at 2^26:

1. The marginal cost of passes in {8, 16, 32} on uniform keys (what
   everyone pays when the fix-up is NOT needed).
2. The runs-of-16 adversarial pattern (mid-length equal-hi runs the
   sniff cannot see) at each depth: at 8 it double-sorts via the
   residual fallback; at >= 16 the fix-up handles it in-VMEM.
3. On-device exactness of the runs-16 pattern at the chosen depth.

Resumable: ``FIX_PARTS=uniform,runs16,exact`` (default all).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() not in ("tpu", "axon"):
        print("fixdepth_probe: needs a real TPU", flush=True)
        return 2

    from mpitest_tpu.ops import kernels

    from mpitest_tpu.utils import knobs

    parts = knobs.get("FIX_PARTS")
    n = 1 << 26
    rng = np.random.default_rng(11)
    row: dict = {"ts": time.time(), "config": "fixdepth_probe_2e26"}

    ku = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                     .astype(np.uint32))
    pu = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                     .astype(np.uint32))
    # runs-of-16: every hi value repeats exactly 16x, shuffled — longer
    # than fix_passes=8, invisible to a 1024-key strided sniff.
    hi16 = np.repeat(rng.choice(2**32, n // 16, replace=False)
                     .astype(np.uint32), 16)
    perm = rng.permutation(n)
    k16 = jnp.asarray(hi16[perm])
    p16 = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint64)
                      .astype(np.uint32))

    def slope(fn, args, reps=(1, 3), tries=3):
        out = {}
        for r in reps:
            @jax.jit
            def g(ops, r=r):
                for _ in range(r):
                    ops = fn(*ops)
                return ops
            y = g(args)
            jax.device_get(y[0][:1])
            ts = []
            for _ in range(tries):
                t = time.perf_counter()
                y = g(args)
                jax.device_get(y[0][:1])
                ts.append(time.perf_counter() - t)
            out[r] = min(ts)
        return (out[reps[1]] - out[reps[0]]) / (reps[1] - reps[0]) * 1e3

    def full_with_fallback(passes):
        """The b_pair branch shape: pair path at ``passes``, residual ->
        on-device lax fallback (what the fused jit runs)."""
        def f(h, l):
            hs, ls, bad = kernels.sort_two_words_bitonic(
                h, l, fix_passes=passes)
            hs, ls = jax.lax.cond(
                bad, lambda a, b: tuple(jax.lax.sort([a, b], num_keys=2,
                                                     is_stable=False)),
                lambda a, b: (hs, ls), h, l)
            return hs, ls
        return f

    if "uniform" in parts:
        for passes in (8, 16, 32):
            ms = slope(full_with_fallback(passes), (ku, pu))
            print(f"uniform, fix_passes={passes}: {ms:.1f} ms", flush=True)
            row[f"uniform_fix{passes}_ms"] = round(ms, 1)

    if "runs16" in parts:
        for passes in (8, 16, 32):
            ms = slope(full_with_fallback(passes), (k16, p16))
            print(f"runs-of-16, fix_passes={passes}: {ms:.1f} ms "
                  f"({'double-sorts via fallback' if passes < 16 else 'in-VMEM fix'})",
                  flush=True)
            row[f"runs16_fix{passes}_ms"] = round(ms, 1)
        lax_ms = slope(
            lambda h, l: tuple(jax.lax.sort([h, l], num_keys=2,
                                            is_stable=False)), (k16, p16))
        print(f"runs-of-16, lax 2w: {lax_ms:.1f} ms", flush=True)
        row["runs16_lax_ms"] = round(lax_ms, 1)

    if "exact" in parts:
        def make_check(passes):
            @jax.jit
            def check(h, l):
                hs, ls, bad = kernels.sort_two_words_bitonic(
                    h, l, fix_passes=passes)
                ref = jax.lax.sort([h, l], num_keys=2, is_stable=False)
                return jnp.all(hs == ref[0]) & jnp.all(ls == ref[1]), bad
            return check

        for passes in (16, 32):
            ok, bad = (bool(v) for v in
                       jax.device_get(make_check(passes)(k16, p16)))
            print(f"runs-of-16 exact at fix_passes={passes}: {ok} "
                  f"(residual={bad})", flush=True)
            row[f"runs16_exact_fix{passes}"] = ok and not bad
    row_ok = all(v for k, v in row.items() if k.startswith("runs16_exact"))
    row["all_ok"] = row_ok
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print("fixdepth_probe: done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
