#!/usr/bin/env python3
"""float64 at scale via host-encoded word planes (VERDICT r4 #4).

This stack cannot hold f64 on device exactly (f32-pair emulation,
~2e-15 rel err) nor lower f64→u32 bitcasts (``models/api.py``
``_f64_known_broken``), so ``sort()`` host-fallbacks for device f64
arrays.  That blocks the *device-array* path, NOT the measurement: the
framework's 64-bit machinery operates on uint32 word planes, and the
f64 totalOrder codec (``ops/keys.py``) produces those on host
losslessly.  This probe encodes on host, ``device_put``s the two word
planes, and times the full adaptive 64-bit device program (pair
network + run fix + residual cond) — the exact sort a
native-f64-capable stack would run — with a bit-exact encoded-median
probe.

Env: ``F64_LOG2N`` (default 27), ``F64_REPEATS`` (default 2).
Appends one JSONL row.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() not in ("tpu", "axon"):
        print("f64_at_scale: needs a real TPU", flush=True)
        return 2

    from mpitest_tpu.models.ingest import checked_device_put
    from mpitest_tpu.ops import kernels
    from mpitest_tpu.ops.keys import codec_for

    from mpitest_tpu.utils import knobs

    log2n = knobs.get("F64_LOG2N")
    repeats = knobs.get("F64_REPEATS")
    n = 1 << log2n
    rng = np.random.default_rng(3)
    # Wide-dynamic-range doubles incl. the totalOrder edge cases.
    x = rng.standard_normal(n) * 10.0 ** rng.integers(-250, 250, n)
    x[:4] = [0.0, -0.0, np.inf, -np.inf]
    x = x.astype(np.float64)

    codec = codec_for(np.float64)
    t0 = time.perf_counter()
    hi_np, lo_np = codec.encode(x)
    enc_s = time.perf_counter() - t0
    # Reference: encoded uint64 median (int truncation collides floats).
    enc64 = (hi_np.astype(np.uint64) << np.uint64(32)) | lo_np
    ref_median = int(np.partition(enc64, n // 2 - 1)[n // 2 - 1])

    t0 = time.perf_counter()
    hi = checked_device_put(jnp.asarray(hi_np), jax.devices()[0])
    lo = checked_device_put(jnp.asarray(lo_np), jax.devices()[0])
    jax.device_get(hi[-1:]), jax.device_get(lo[-1:])
    ingest_s = time.perf_counter() - t0
    print(f"host encode {enc_s:.2f}s; ingest {ingest_s:.1f}s "
          f"({x.nbytes / ingest_s / 1e9:.2f} GB/s)", flush=True)

    @jax.jit
    def sort_words(h, l):
        hs, ls, bad = kernels.sort_two_words_bitonic(h, l)
        hs, ls = jax.lax.cond(
            bad,
            lambda a, b: tuple(jax.lax.sort([a, b], num_keys=2,
                                            is_stable=False)),
            lambda a, b: (hs, ls), h, l)
        return hs, ls, bad

    # Warmup (compile) + probe; `residual` records which route the
    # timed runs take (False = pair network, True = lax fallback), so
    # the JSONL row carries its route like every other round-5 row.
    hs, ls, bad = sort_words(hi, lo)
    residual = bool(jax.device_get(bad))
    print(f"route: {'lax fallback (residual)' if residual else 'pair network'}",
          flush=True)
    got = ((int(jax.device_get(hs[n // 2 - 1])) << 32)
           | int(jax.device_get(ls[n // 2 - 1])))
    ok = got == ref_median
    print(f"encoded median probe: {'OK' if ok else 'MISMATCH'} "
          f"({got} vs {ref_median})", flush=True)

    times = []
    for i in range(repeats):
        t0 = time.perf_counter()
        hs, ls, _ = sort_words(hi, lo)
        jax.device_get(hs[-1:])
        dt = time.perf_counter() - t0
        times.append(dt)
        print(f"run {i}: {dt:.3f}s = {n / dt / 1e6:.1f} Mkeys/s", flush=True)
    best = min(times)
    mkeys = n / best / 1e6
    # Round-trip decode check on a sample: codec order law.
    back = codec.decode((np.asarray(jax.device_get(hs[:4096])),
                         np.asarray(jax.device_get(ls[:4096]))))
    mono = bool(np.all(np.diff(back[np.isfinite(back)]) >= 0))
    print(f"decoded prefix monotone: {mono}", flush=True)

    row = {"ts": time.time(),
           "config": f"tpu_f64_words_2e{log2n}_device_resident",
           "metric": "mkeys_per_s", "value": round(mkeys, 1),
           "median_ok": ok, "decoded_monotone": mono,
           "route": "lax_fallback" if residual else "bitonic_pair",
           "span": "device_words", "host_encode_s": round(enc_s, 2)}
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"f64_at_scale: {mkeys:.1f} Mkeys/s "
          f"{'OK' if ok and mono else 'FAIL'}", flush=True)
    return 0 if ok and mono else 1


if __name__ == "__main__":
    sys.exit(main())
