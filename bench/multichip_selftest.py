"""Multi-chip scale-out selftest: the `make multichip-selftest` gate (ISSUE 7).

The north-star path is the P-device sharded sort, so its three
load-bearing claims are gated here, TPU-free on a virtual 8-device CPU
mesh (the identical shard_map/collective code drives real chips):

1. **Bit-identical output** — the 8-device sharded sort equals the
   1-device result byte for byte, for both algorithms, across uniform,
   N<P, non-divisible-N and skewed (clustered / duplicate-heavy)
   inputs.  Sorted output is canonical; any divergence is a sharding or
   exchange bug, never an acceptable difference.
2. **Exchange balance** — after the count probe (and the skew re-stage
   it may trigger), per-rank received exchange bytes stay within
   :data:`BALANCE_GATE` x the mean, and no single peer segment needs
   more than :data:`BALANCE_GATE` x the fair share.
3. **Capacity negotiation** — on a skewed input the negotiated capacity
   is STRICTLY below the worst-case cap (the shard size), and the
   exchange completes with ZERO overflow retries (the probe made the
   recompile-on-overflow path unnecessary, not just rarer).

Every cell failure prints loudly and the process exits nonzero — this
runs in CI beside the ingest/fault/telemetry selftests.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Fail-fast supervisor pinning (like bench.py): the gate must see the
# real scale-out path, never a silently degraded ladder rung.
os.environ.setdefault("SORT_FALLBACK", "0")
os.environ.setdefault("SORT_MAX_RETRIES", "0")

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402

from mpitest_tpu.models.api import sort  # noqa: E402
from mpitest_tpu.parallel.mesh import make_mesh  # noqa: E402
from mpitest_tpu.utils import knobs  # noqa: E402
from mpitest_tpu.utils.metrics import Metrics  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402

#: Max allowed per-rank exchange imbalance after probe/re-stage: both
#: the recv-byte max/mean ratio and the peer-segment/fair-share ratio.
BALANCE_GATE = 2.0

results: list[tuple[str, bool, str]] = []


def cell(name: str, ok: bool, detail: str) -> None:
    results.append((name, ok, detail))
    marker = "ok  " if ok else "FAIL"
    print(f"[{marker}] {name}: {detail}", flush=True)


def main() -> int:
    t_start = time.perf_counter()
    rng = np.random.default_rng(7)
    mesh8 = make_mesh(8)
    mesh1 = make_mesh(1)

    # ---- 1. bit-identical parity: devices=8 vs devices=1 ------------
    inputs = {
        "uniform": rng.integers(-2**31, 2**31 - 1, size=1 << 15,
                                dtype=np.int32),
        "n_lt_p": rng.integers(0, 100, size=3, dtype=np.int32),
        "non_divisible": rng.integers(-2**31, 2**31 - 1, size=1000,
                                      dtype=np.int32),
        "sorted_skew": np.sort(rng.integers(0, 1 << 16, size=1 << 15)
                               .astype(np.int32)),
        "duplicate_skew": rng.choice(
            np.asarray([3, 7, 7, 7, 42], np.int32), size=1 << 14),
    }
    from mpitest_tpu.models import verify as vfy
    from mpitest_tpu.ops.keys import codec_for

    for algo in ("radix", "sample"):
        for name, x in inputs.items():
            # one 8-device sort per engine per cell — sections 1 and 1b
            # below compare these SAME outputs (the engine axis is pure
            # byte/fingerprint comparison, no extra interpret sorts)
            out8 = sort(x, algorithm=algo, mesh=mesh8,
                        exchange_engine="lax")
            out1 = sort(x, algorithm=algo, mesh=mesh1)
            same = (np.array_equal(out8, out1)
                    and out8.tobytes() == out1.tobytes())
            cell(f"parity/{algo}/{name}", same,
                 "8-device output bit-identical to 1-device"
                 if same else "OUTPUT DIVERGED between mesh sizes")
            # ISSUE 13: the 1-vs-8 parity cell re-run under the pallas
            # exchange engine (interpret form on this CPU image — the
            # fused pack + engine plumbing run for real, the remote-DMA
            # hop rides the bit-identical lax transport).
            out8p = sort(x, algorithm=algo, mesh=mesh8,
                         exchange_engine="pallas_interpret")
            same_p = (np.array_equal(out8p, out1)
                      and out8p.tobytes() == out1.tobytes())
            cell(f"parity/{algo}/{name}/pallas", same_p,
                 "pallas-engine 8-device output bit-identical to 1-device"
                 if same_p else "PALLAS ENGINE OUTPUT DIVERGED")

            # ---- 1b. engine axis: lax vs pallas_interpret -----------
            # Bit-identical output AND multiset fingerprint across the
            # engine knob (ISSUE 13), on the outputs already computed.
            same_e = (np.array_equal(out8, out8p)
                      and out8.tobytes() == out8p.tobytes())
            codec = codec_for(np.dtype(x.dtype))
            fp_lax = vfy.fingerprint_host(codec.encode(out8))
            fp_pal = vfy.fingerprint_host(codec.encode(out8p))
            ok = same_e and fp_lax == fp_pal
            cell(f"engine/{algo}/{name}", ok,
                 "lax vs pallas_interpret bit-identical + fingerprints "
                 "equal" if ok else
                 f"ENGINE DIVERGENCE (bytes={same_e}, "
                 f"fp={fp_lax == fp_pal})")

    # ---- 2+3. balance + negotiated capacity on skewed inputs --------
    skewed = inputs["sorted_skew"]
    for algo in ("radix", "sample"):
        tracer = Tracer()
        out = sort(skewed, algorithm=algo, mesh=mesh8, tracer=tracer)
        c = tracer.counters
        ok_sorted = np.array_equal(out, skewed)
        cell(f"skew/{algo}/correct", ok_sorted, "sorted output exact")
        neg = c.get("negotiated_cap")
        worst = c.get("worst_cap")
        ok_neg = neg is not None and worst and neg < worst
        cell(f"skew/{algo}/negotiated_below_worst", bool(ok_neg),
             f"negotiated cap {neg} vs worst-case {worst}")
        retries = int(c.get("exchange_retries", 0))
        cell(f"skew/{algo}/no_overflow_retry", retries == 0,
             f"exchange_retries={retries} (probe sized the cap exactly)"
             if retries == 0 else
             f"exchange_retries={retries} — negotiation failed to size "
             "the cap")
        balance = float(c.get("exchange_balance_ratio", np.inf))
        peer = float(c.get("exchange_peer_ratio", np.inf))
        ok_bal = balance <= BALANCE_GATE and peer <= BALANCE_GATE
        cell(f"skew/{algo}/balance_under_gate", ok_bal,
             f"recv max/mean {balance} and peer/fair {peer} "
             f"(gate {BALANCE_GATE}) — restaged={int(c.get('skew_restage', 0))}")

    # ---- summary + metrics sidecar ----------------------------------
    bad = [r for r in results if not r[1]]
    wall = time.perf_counter() - t_start
    m = Metrics(config={"selftest": "multichip", "devices": 8})
    m.record("multichip_cells", len(results))
    m.record("multichip_failures", len(bad))
    m.record("multichip_wall_s", round(wall, 2), "s")
    m.dump(knobs.get("SORT_METRICS"))
    print(f"\nmultichip-selftest: {len(results) - len(bad)}/{len(results)} "
          f"cells passed in {wall:.1f}s "
          f"({'OK' if not bad else 'FAILURES ABOVE'})")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
