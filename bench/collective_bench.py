#!/usr/bin/env python3
"""lax.all_to_all micro-benchmark — the ICI half of BASELINE.md row 7.

Times the padded all-to-all the sort engines actually use (uint32 lanes,
``tiled=True``) over the available mesh and reports achieved GB/s through
the metrics sidecar.  The native half is ``native/comm_bench.c`` (same
traffic pattern over the comm.h shim); run both for the MPI-vs-ICI
comparison the north star describes.

Usage: python bench/collective_bench.py [--bytes-per-peer B] [--reps R]
       [--ranks P] [--cpu]
"""

from __future__ import annotations

import argparse
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bytes-per-peer", type=int, default=1 << 22)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--ranks", type=int, default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="virtual CPU mesh (8 devices) instead of TPU")
    args = ap.parse_args()

    if args.cpu:
        import os

        from mpitest_tpu.utils import knobs

        os.environ["XLA_FLAGS"] = (
            knobs.get("XLA_FLAGS")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from mpitest_tpu.parallel.mesh import AXIS, make_mesh
    from mpitest_tpu.utils.metrics import Metrics

    mesh = make_mesh(args.ranks)
    n_ranks = int(mesh.devices.size)
    lanes = args.bytes_per_peer // 4  # uint32 lanes per peer block

    def step(x):
        # the exact exchange shape the sort engines use: [P, lanes] tiled
        return lax.all_to_all(x, AXIS, 0, 0, tiled=True)

    fn = jax.jit(
        compat.shard_map(
            lambda x: step(x), mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS)
        )
    )
    x = jnp.arange(n_ranks * n_ranks * lanes, dtype=jnp.uint32).reshape(
        n_ranks * n_ranks, lanes
    )
    from mpitest_tpu.models.ingest import checked_device_put

    x = checked_device_put(x, jax.sharding.NamedSharding(mesh, P(AXIS)))

    out = fn(x)  # compile + warm
    int(jax.device_get(out[-1, -1]))
    t0 = time.perf_counter()
    for _ in range(args.reps):
        out = fn(out)
    int(jax.device_get(out[-1, -1]))  # sync (block_until_ready is advisory here)
    dt = time.perf_counter() - t0

    # inter-rank bytes only, matching native/comm_bench.c (self-destined
    # blocks never cross the fabric)
    remote_peers = n_ranks - 1 if n_ranks > 1 else 1
    moved = float(n_ranks * remote_peers * lanes * 4) * args.reps
    m = Metrics(config={
        "ranks": n_ranks, "bytes_per_peer": args.bytes_per_peer,
        "reps": args.reps, "platform": jax.devices()[0].platform,
    })
    gbs = m.bandwidth("lax_all_to_all_gb_per_s", int(moved), dt)
    m.dump()
    print(f"lax.all_to_all: {gbs:.3f} GB/s over {n_ranks} ranks", file=sys.stderr)


if __name__ == "__main__":
    main()
