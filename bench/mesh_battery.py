#!/usr/bin/env python3
"""One-command multi-device readiness battery (VERDICT r4 #5).

Consolidates the multi-device correctness evidence that previously
lived scattered across tests into one script + one JSONL row: on the
8-device virtual CPU mesh (the same ``shard_map`` programs that run on
a real TPU mesh — see ``tests/test_aot_topology.py`` for the compile
proof on real topologies), at non-trivial scale (>= 2^18 keys/device):

* ``dtypes``  — both algorithms x all 10 supported dtypes, uniform
  keys, non-divisible N: output must equal ``np.sort`` exactly.
* ``zipf``    — Zipf(1.1)/(1.5) int64 through the sample path:
  exactness plus the routing counters (bounded cap vs sniffed
  reroute, zero overflow retries).
* ``pack``    — the Pallas DMA exchange pack (interpret mode) on the
  radix path.
* ``engines`` — the bitonic engines under ``shard_map`` (interpret
  mode; block sizes shrunk like the test suite so the interpreter
  runs the REAL multi-stage network in reasonable time): 1-word and
  the 64-bit pair engine.

``dryrun_multichip`` (``__graft_entry__.py``) stays the fast smoke;
this is the at-scale artifact.  Resumable:
``MESHB_PARTS=dtypes,zipf,pack,engines``; ``MESHB_LOG2N`` total keys
(default 21 = 2^18/device).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import numpy as np  # noqa: E402

RESULTS = Path(__file__).resolve().parent / "BASELINE_RESULTS.jsonl"


def main() -> int:
    from mpitest_tpu.models.api import sort
    from mpitest_tpu.ops import bitonic
    from mpitest_tpu.ops.keys import _CODECS
    from mpitest_tpu.parallel.mesh import make_mesh
    from mpitest_tpu.utils.io import generate_zipf
    from mpitest_tpu.utils.trace import Tracer

    from mpitest_tpu.utils import knobs

    parts = knobs.get("MESHB_PARTS")
    log2n = knobs.get("MESHB_LOG2N")
    n = (1 << log2n) + 1371  # non-divisible by 8: exercises padding
    mesh = make_mesh(8)
    rng = np.random.default_rng(17)
    row: dict = {"ts": time.time(), "config": f"mesh_battery_8dev_2e{log2n}",
                 "keys_per_device": n // 8}
    ok_all = True

    def check(name, x, algo, **kw):
        nonlocal ok_all
        t0 = time.perf_counter()
        tracer = Tracer()
        got = sort(x, algorithm=algo, mesh=mesh, tracer=tracer, **kw)
        exact = bool(np.array_equal(got, np.sort(x)))
        ok_all &= exact
        print(f"{name}: {'OK' if exact else 'FAIL'} "
              f"({time.perf_counter() - t0:.1f}s)", flush=True)
        return exact, tracer

    if "dtypes" in parts:
        res = {}
        for dt in sorted(_CODECS, key=str):
            if dt.kind == "f":
                x = (rng.standard_normal(n) * 10.0
                     ** rng.integers(-30, 30, n)).astype(dt)
            else:
                info = np.iinfo(dt)
                x = rng.integers(info.min, info.max, size=n, dtype=dt,
                                 endpoint=True)
            for algo in ("radix", "sample"):
                exact, _ = check(f"dtypes {algo} {dt}", x, algo)
                res[f"{algo}_{dt}"] = exact
        row["dtypes_ok"] = all(res.values())

    if "zipf" in parts:
        for alpha, name, want_fb in ((1.1, "zipf11", 0), (1.5, "zipf15", 1)):
            x = generate_zipf(n, a=alpha, dtype=np.int64, seed=23)
            exact, tracer = check(f"zipf {name} sample int64", x, "sample")
            fb = int(tracer.counters.get("sample_skew_fallback", 0))
            retries = int(tracer.counters.get("exchange_retries", 0))
            route_ok = fb == want_fb and retries == 0
            ok_all &= route_ok
            print(f"  counters: fallback={fb} (expect {want_fb}) "
                  f"retries={retries} -> {'OK' if route_ok else 'FAIL'}",
                  flush=True)
            row[f"{name}_ok"] = exact and route_ok

    if "pack" in parts:
        x = rng.integers(-(2**31), 2**31 - 1, size=n, dtype=np.int32)
        exact, _ = check("pack pallas_interpret radix int32", x, "radix",
                         pack="pallas_interpret")
        row["pack_interpret_ok"] = exact

    if "engines" in parts:
        # Shrink block sizes so the Pallas interpreter runs the real
        # multi-stage network (block sort + visits + rot-merge + run
        # fix) in tractable time — same approach as the test suite.
        saved = (bitonic.MIN_SORT_LOG2, bitonic.BLOCK_LOG2,
                 bitonic.PAIR_BLOCK_LOG2)
        bitonic.MIN_SORT_LOG2 = 8
        bitonic.BLOCK_LOG2 = 10
        bitonic.PAIR_BLOCK_LOG2 = 10
        try:
            with knobs.scoped_env(SORT_LOCAL_ENGINE="bitonic"):
                x32 = rng.integers(-(2**31), 2**31 - 1, size=n,
                                   dtype=np.int32)
                e1, _ = check("engine bitonic-1w sample int32 shard_map",
                              x32, "sample")
                x64 = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
                e2, _ = check("engine bitonic-pair sample int64 shard_map",
                              x64, "sample")
                row["engine_1w_ok"], row["engine_pair_ok"] = e1, e2
        finally:
            (bitonic.MIN_SORT_LOG2, bitonic.BLOCK_LOG2,
             bitonic.PAIR_BLOCK_LOG2) = saved

    row["all_ok"] = ok_all
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(f"mesh_battery: {'ALL OK' if ok_all else 'FAILURES'}", flush=True)
    return 0 if ok_all else 1


if __name__ == "__main__":
    sys.exit(main())
