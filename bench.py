"""Benchmark driver: one JSON metric line on stdout, details on stderr.

Primary metric (BASELINE.md rows 3-4): sort throughput in Mkeys/s on the
flagship device-resident path at the driver-specified scale (2^28
default on TPU; 2^30 via BENCH_LOG2N=30 when HBM allows).

``vs_baseline`` is the north-star ratio (BASELINE.json): this framework
vs the repo's OWN native backend at 8 host-CPU ranks sorting the same
keys at the same N — the moral equivalent of the reference's
``mpirun -np 8`` on one host.  ``vs_np_sort`` (single-core ``np.sort``)
is reported as a secondary field.

The timed span is the framework's steady-state contract: keys start and
end **device-resident and sharded on the mesh** (the design removes
every root/host round-trip the reference pays — SURVEY.md §5
long-context row), so the metric times encode + full SPMD sort to
completion.  The host→device ingest (which on this image rides a
network tunnel at ~0.3 GB/s, nothing like production PCIe/DMA) runs
through the streamed pipeline (models/ingest.py: chunked parse/encode
overlapped with per-shard DMA) and is reported separately in the stderr
sidecar with parse/encode/transfer sub-metrics and overlap efficiency;
``sort_incl_ingest_mkeys_per_s`` is ONE measured end-to-end run of
streamed ingest + sort on the staged words (ISSUE 2 headline).  Note the per-dispatch overhead of this
image's tunnel (~0.18 s fixed per jit call round-trip, measured by
chained-call subtraction) is part of every timed run; it amortizes with
N, which is one reason the target scale is 2^28+.

Env knobs: BENCH_LOG2N (default 28 on TPU, 20 on CPU), BENCH_ALGO
(radix|sample), BENCH_REPEATS (default 3), BENCH_DTYPE (int32),
BENCH_NATIVE_RANKS (default 8; 0 disables the native denominator),
BENCH_NATIVE_REPEATS (default 3 — the denominator is the MEDIAN of
these runs; see CANONICAL_NATIVE_MKEYS for the pinned cross-round
protocol, VERDICT r4 weak #4).

Output contract: one JSON line per measured configuration — the primary
row (unchanged since round 1, so the r01+ trajectory stays comparable)
plus, unless ``BENCH_MULTICHIP=off``, the ``devices=8`` scale-out row
(ISSUE 7): measured on the real mesh when >= 8 chips are visible, else
on a ``BENCH_PLATFORM=cpu:8`` virtual mesh in a subprocess, at the
largest N that fits, carrying per-rank exchange balance and the
negotiated-vs-worst-case capacity saving.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def tooling_state() -> dict:
    """The lint/sanitizer gate state stamped into every bench row, so a
    BENCH number is attributable to the tooling that was in force when
    it was measured (ISSUE 4): sortlint version + rule count, the C
    warning flags, the sanitizer matrix, and the mypy version when the
    strict gate could run (None = gate skipped on this image)."""
    t: dict = {
        "cwarn": "-Wconversion -Wshadow -Werror",
        "sanitize": "tsan:local asan,ubsan:local+minimpi",
    }
    try:
        from tools.sortlint import LINT_VERSION, RULES

        t["sortlint"] = LINT_VERSION
        t["sortlint_rules"] = len(RULES)
    except Exception as e:  # tools/ not importable: record why, loudly
        t["sortlint"] = f"unavailable ({type(e).__name__})"
    try:
        from mypy.version import __version__ as mypy_version

        t["mypy"] = mypy_version
    except Exception:
        t["mypy"] = None
    return t


def encoded_median(x_or_scalar, dtype: np.dtype) -> int:
    """Collapse key(s) to one comparable integer for the median probe:
    the native value for ints; the encoded totalOrder bit pattern for
    floats (int truncation collides distinct float medians, and
    ``np.sort``'s placement of ±0.0 at the median index need not match
    totalOrder).  Arrays are encoded, sorted, and probed at n/2-1;
    scalars are encoded directly."""
    from mpitest_tpu.ops.keys import codec_for

    arr = np.asarray(x_or_scalar, dtype=dtype).reshape(-1)
    if dtype.kind != "f":
        val = np.sort(arr)[arr.size // 2 - 1] if arr.size > 1 else arr[0]
        return int(val)
    words = codec_for(dtype).encode(arr)
    enc = words[0] if len(words) == 1 else (
        (words[0].astype(np.uint64) << np.uint64(32)) | words[1])
    return int(np.sort(enc)[arr.size // 2 - 1]) if arr.size > 1 else int(enc[0])


#: The scale-out row's mesh size (ISSUE 7): the north-star target shape
#: is v5e-8, and the TPU-less fallback (`BENCH_PLATFORM=cpu:8`) uses the
#: same count so the row is structurally identical either way.
MULTICHIP_DEVICES = 8


def _measure_multichip(algo: str, dtype: np.dtype, log2n: int,
                       repeats: int, platform: str) -> dict | None:
    """Measure the ``devices=8`` scale-out row on ``make_mesh(8)`` —
    requires >= 8 visible devices (real chips or a virtual CPU mesh).

    "Largest N that fits": starts at ``log2n`` and backs off one power
    of two per RESOURCE_EXHAUSTED until the sharded sort completes (the
    2^30-on-v5e-8 target is HBM-edge by design).  The row carries the
    scale-out telemetry the 1-device rows cannot: per-rank exchange-byte
    balance (max/mean), the negotiated-vs-worst-case capacity saving,
    and whether the skew re-stage fired.  Returns None (after logging)
    when nothing fits — never kills the primary row."""
    import jax

    from mpitest_tpu.models.api import (SortRetryExhausted,
                                        checked_device_put, sort)
    from mpitest_tpu.parallel.mesh import key_sharding, make_mesh
    from mpitest_tpu.utils import knobs, timeline
    from mpitest_tpu.utils.io import generate
    from mpitest_tpu.utils.metrics import Metrics
    from mpitest_tpu.utils.trace import Tracer

    mesh = make_mesh(MULTICHIP_DEVICES)
    x = x_dev = None
    while log2n >= 16:
        n = 1 << log2n
        try:
            x = generate("uniform", n, dtype, seed=0)
            ref_median = encoded_median(x, dtype)
            x_dev = checked_device_put(x, key_sharding(mesh))
            x_dev.block_until_ready()
            log(f"multichip: devices={MULTICHIP_DEVICES} algo={algo} "
                f"N=2^{log2n} dtype={dtype}")
            tracer = Tracer()
            res = sort(x_dev, algorithm=algo, mesh=mesh,
                       return_result=True, tracer=tracer)
            probe = encoded_median(res.median_probe_raw(), dtype)
            del res
            if probe != ref_median:
                log("multichip: CORRECTNESS FAILURE — omitting row")
                return None
            times = []
            for i in range(repeats):
                run_tracer = Tracer()
                t0 = time.perf_counter()
                r = sort(x_dev, algorithm=algo, mesh=mesh,
                         return_result=True, tracer=run_tracer)
                for w in r.words:
                    w.block_until_ready()
                jax.device_get(r.words[0][-1:])
                dt = time.perf_counter() - t0
                del r
                times.append(dt)
                tracer = run_tracer
                log(f"multichip run {i}: {dt:.3f}s = {n/dt/1e6:.1f} Mkeys/s")
            break
        except (jax.errors.JaxRuntimeError, SortRetryExhausted) as e:
            cause = f"{e} {getattr(e, '__cause__', None) or ''}"
            if "RESOURCE_EXHAUSTED" not in cause:
                raise
            # free the failed attempt's buffers BEFORE shrinking: the
            # retry must not have to fit beside the buffer that just
            # exhausted HBM, or the backoff lands far below the true
            # largest-N-that-fits
            x = x_dev = None
            log(f"multichip: 2^{log2n} exhausted HBM; retrying at "
                f"2^{log2n - 1}")
            log2n -= 1
    else:
        log("multichip: no N fits; omitting row")
        return None

    mkeys = n / min(times) / 1e6
    c = tracer.counters
    row: dict = {
        "metric": f"{algo}_sort_mkeys_per_s_2e{log2n}_{dtype.name}_8dev",
        "value": round(mkeys, 2),
        "unit": "Mkeys/s",
        "devices": MULTICHIP_DEVICES,
        "platform": platform,
        # ISSUE 13: the engine the timed exchange ran (the primary/8dev
        # rows pin lax for trajectory comparability; the pallas smoke
        # cell below carries the new engine's parity evidence).
        "exchange_engine": c.get("exchange_engine", "lax"),
        # ISSUE 17: local-sort engine column (pinned lax on measured
        # rows; the fused engine's evidence is `make localsort-selftest`
        # until a real-TPU round re-baselines).
        "local_engine": c.get("local_engine", "lax"),
        # ISSUE 14: planner column (pinned off on measured rows).
        "planner": str(knobs.get("SORT_PLANNER")),
    }
    if str(row["local_engine"]).startswith("radix_pallas"):
        row["local_engine_note"] = (
            "fused engine never lowered on real TPU; interpret-mode "
            "evidence only — re-baseline on first TPU session")
    # ISSUE 16: the timeline fold's trajectory scalars — worst per-pass
    # straggler (max/median rank bytes) and the dominant phase — from
    # the LAST timed run's spans; absent keys render "-" downstream.
    row.update(timeline.bench_fold(tracer.spans.spans))
    metrics = Metrics(config={"platform": platform, "algo": algo,
                              "log2n": log2n, "dtype": dtype.name,
                              "devices": MULTICHIP_DEVICES})
    metrics.throughput("sort_mkeys_per_s_8dev", n, min(times))
    # Scale-out telemetry (ISSUE 7): exchange balance + capacity saving.
    if "negotiated_cap" in c:
        neg, worst = int(c["negotiated_cap"]), int(c["worst_cap"])
        saving = round(100.0 * (1.0 - neg / worst), 2) if worst else 0.0
        row["negotiated_cap"] = neg
        row["worst_cap"] = worst
        row["cap_saving_pct"] = saving
        row["exchange_balance_ratio"] = c.get("exchange_balance_ratio")
        row["exchange_peer_ratio"] = c.get("exchange_peer_ratio")
        log(f"multichip: negotiated cap {neg} vs worst-case {worst} "
            f"({saving}% saved), recv balance "
            f"{c.get('exchange_balance_ratio')}")
    if c.get("skew_restage"):
        row["restaged"] = int(c["skew_restage"])
    # Plan digest (ISSUE 12): the decisions behind this row's number,
    # pinned beside it so `report.py --baseline` can flag DECISION
    # drift (algo/cap/restage/regret), not just throughput drift.
    if "plan_regret" in c:
        row["plan_regret"] = round(float(c["plan_regret"]), 6)
        metrics.record("plan_regret", row["plan_regret"], "x")
    if "plan_cap_regret" in c:
        row["plan_cap_regret"] = round(float(c["plan_cap_regret"]), 6)
    # ISSUE 13: the pallas_interpret smoke cell — parity evidence for
    # the second exchange engine, SCALE-GATED to a tiny fixed N so the
    # interpreter never times (or delays) a measured row.  On a TPU
    # backend the same knob value exercises the fused pack under the
    # interpreter while the remote-DMA hop rides the lax transport
    # (ops/exchange.py interpret contract).
    try:
        n_smoke = 1 << 12
        xs = generate("uniform", n_smoke, dtype, seed=1)
        out_lax = sort(xs, algorithm=algo, mesh=mesh, exchange_engine="lax")
        out_pal = sort(xs, algorithm=algo, mesh=mesh,
                       exchange_engine="pallas_interpret")
        parity = bool(np.array_equal(out_lax, out_pal)
                      and out_lax.tobytes() == out_pal.tobytes())
        row["pallas_interpret_smoke"] = {
            "n": n_smoke, "parity": parity, "engine": "pallas_interpret"}
        metrics.record("exchange_pallas_smoke_parity", int(parity))
        log(f"multichip: pallas_interpret smoke at 2^12 — "
            f"{'bit-identical' if parity else 'PARITY FAILURE'}")
        if not parity:
            # zero BOTH surfaces: the sidecar must not keep a healthy
            # throughput for a round whose row was zeroed for parity
            row["value"] = 0.0
            metrics.record("sort_mkeys_per_s_8dev", 0.0, "Mkeys/s")
            log("multichip: CORRECTNESS FAILURE (engine parity) — "
                "reporting value 0")
    except Exception as e:  # noqa: BLE001 — smoke must not kill the row
        log(f"multichip: pallas smoke skipped ({type(e).__name__}: {e})")
        row["pallas_interpret_smoke"] = {"error": type(e).__name__}
    metrics.record_tracer(tracer)
    metrics.dump()
    return row


def _emit_multichip_row(log2n: int, algo: str, dtype: np.dtype,
                        repeats: int, primary_mkeys: float,
                        platform: str) -> None:
    """Emit the second (devices=8) JSONL row: in-process when the mesh
    is already big enough, else a ``BENCH_PLATFORM=cpu:8`` subprocess —
    the fallback every image supports.  Best-effort by contract: any
    failure logs and skips, never costs the primary row."""
    import jax

    try:
        if len(jax.devices()) >= MULTICHIP_DEVICES:
            row = _measure_multichip(algo, dtype, log2n, repeats, platform)
            if row is not None:
                if row["value"] > 0 and primary_mkeys > 0 \
                        and f"2e{log2n}_" in row["metric"]:
                    row["vs_primary"] = round(row["value"] / primary_mkeys, 3)
                print(json.dumps(row))
            return
        # Too few visible devices: re-exec on a virtual cpu:8 mesh (the
        # XLA device-count flag only takes effect before backend init,
        # so this NEEDS a fresh process).  Virtual CPU devices share one
        # host, so the row size is capped at the CPU default scale.
        env = dict(os.environ,
                   BENCH_PLATFORM=f"cpu:{MULTICHIP_DEVICES}",
                   BENCH_LOG2N=str(min(log2n, 20)))
        log(f"multichip: {len(jax.devices())} visible device(s); "
            f"spawning the cpu:{MULTICHIP_DEVICES} virtual-mesh fallback")
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--multichip-row"],
            capture_output=True, text=True, env=env, timeout=3600)
        for line in r.stderr.splitlines():
            log(f"multichip| {line}")
        rows = [ln for ln in r.stdout.splitlines() if ln.strip()]
        if r.returncode != 0 or not rows:
            log(f"multichip: fallback run failed (rc={r.returncode}); "
                "omitting row")
            return
        row = json.loads(rows[-1])  # re-validate before re-emitting
        print(json.dumps(row))
    except Exception as e:  # noqa: BLE001 — the row is best-effort
        log(f"multichip: skipped ({type(e).__name__}: {e})")


def _emit_serve_row() -> None:
    """Third JSONL row (ISSUE 8): the sort-as-a-service measurement —
    ``bench/serve_load.py --row`` spawns a server subprocess, drives the
    small-request mix closed-loop, and emits the p50/p99 + Mkeys/s row
    beside the 1-chip and devices=8 rows.  Best-effort by contract: any
    failure logs and skips, never costs the other rows.  The load
    generator runs in its own process GROUP: a timeout kill must take
    its spawned sort_server grandchildren with it (a SIGKILLed
    serve_load never reaches its own cleanup, and an orphaned server
    would hold a JAX runtime forever)."""
    import signal

    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "bench" / "serve_load.py"),
             "--row", "--out",
             str(REPO / "bench" / ".serve-row")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True)
        try:
            out, err = proc.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            log("serve: row run timed out (process group killed); "
                "omitting row")
            return
        for line in err.splitlines():
            log(f"serve| {line}")
        rows = [ln for ln in out.splitlines() if ln.strip()]
        if proc.returncode != 0 or not rows:
            log(f"serve: row run failed (rc={proc.returncode}); "
                "omitting row")
            return
        row = json.loads(rows[-1])  # re-validate before re-emitting
        print(json.dumps(row))
    except Exception as e:  # noqa: BLE001 — the row is best-effort
        log(f"serve: skipped ({type(e).__name__}: {e})")
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass


def _emit_planner_row() -> None:
    """Fourth JSONL row (ISSUE 14): the adversarial-mix measurement —
    ``bench/planner_selftest.py --row`` runs the sorted/near-sorted/
    dup/skew/uniform mix on a cpu:8 virtual mesh (its own subprocess,
    like the multichip fallback) with the planner PINNED OFF, so the
    r01+ trajectory stays policy-comparable; the planner's on-vs-off
    evidence is `make planner-selftest`.  Best-effort by contract."""
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench" / "planner_selftest.py"),
             "--row"],
            capture_output=True, text=True, timeout=1800)
        for line in r.stderr.splitlines():
            log(f"planner| {line}")
        rows = [ln for ln in r.stdout.splitlines() if ln.strip()]
        if r.returncode != 0 or not rows:
            log(f"planner: row run failed (rc={r.returncode}); "
                "omitting row")
            return
        row = json.loads(rows[-1])  # re-validate before re-emitting
        print(json.dumps(row))
    except Exception as e:  # noqa: BLE001 — the row is best-effort
        log(f"planner: skipped ({type(e).__name__}: {e})")


def _emit_external_row() -> None:
    """Fifth JSONL row (ISSUE 15): the out-of-core measurement —
    ``bench/external_selftest.py --row`` externally sorts a dataset 4x
    a forced ``SORT_MEM_BUDGET`` (spill runs + k-way merge, output
    verified bit-identical in-process) and emits spill+merge Mkeys/s
    with run count and disk bytes.  Best-effort by contract, its own
    subprocess like the planner row."""
    try:
        r = subprocess.run(
            [sys.executable,
             str(REPO / "bench" / "external_selftest.py"), "--row"],
            capture_output=True, text=True, timeout=1800)
        for line in r.stderr.splitlines():
            log(f"external| {line}")
        rows = [ln for ln in r.stdout.splitlines() if ln.strip()]
        if r.returncode != 0 or not rows:
            log(f"external: row run failed (rc={r.returncode}); "
                "omitting row")
            return
        row = json.loads(rows[-1])  # re-validate before re-emitting
        print(json.dumps(row))
    except Exception as e:  # noqa: BLE001 — the row is best-effort
        log(f"external: skipped ({type(e).__name__}: {e})")


def multichip_main() -> None:
    """``bench.py --multichip-row``: measure ONLY the devices=8 row (the
    subprocess side of :func:`_emit_multichip_row`)."""
    from mpitest_tpu.utils import knobs

    try:
        ndev = knobs.get("BENCH_PLATFORM")
        dtype = np.dtype(knobs.get("BENCH_DTYPE"))
        knobs.validate("BENCH_LOG2N", "BENCH_ALGO", "BENCH_REPEATS")
    except ValueError as e:
        raise SystemExit(str(e)) from None
    if ndev:
        from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices

        ensure_virtual_cpu_devices(ndev)
    import jax

    if dtype.itemsize == 8:
        jax.config.update("jax_enable_x64", True)
    # same supervisor + engine pinning as the primary driver:
    # degradation, retry sleeps or an engine flip must not silently
    # rewrite a metric (the pallas evidence is the smoke cell)
    os.environ.setdefault("SORT_FALLBACK", "0")
    os.environ.setdefault("SORT_MAX_RETRIES", "0")
    os.environ.setdefault("SORT_EXCHANGE_ENGINE", "lax")
    os.environ.setdefault("SORT_PLANNER", "off")
    os.environ.setdefault("SORT_LOCAL_ENGINE", "lax")  # ISSUE 17
    platform = jax.devices()[0].platform
    if len(jax.devices()) < MULTICHIP_DEVICES:
        raise SystemExit(
            f"--multichip-row needs >= {MULTICHIP_DEVICES} devices "
            f"(have {len(jax.devices())}); set BENCH_PLATFORM=cpu:8")
    log2n = knobs.get("BENCH_LOG2N") or (28 if platform != "cpu" else 20)
    row = _measure_multichip(knobs.get("BENCH_ALGO"), dtype, log2n,
                             knobs.get("BENCH_REPEATS"), platform)
    if row is None:
        raise SystemExit("multichip row failed")
    print(json.dumps(row))


#: Canonical north-star denominator (VERDICT r4 weak #4): the native
#: backend's throughput measured median-of-5 in one quiet session (no
#: concurrent chip or pytest load on this image's single CPU core), so
#: the headline ratio has a reproducible denominator instead of a
#: weather-dependent one.  Keyed by (algo, log2n, dtype, ranks);
#: measured band recorded beside it.  bench.py reports BOTH the same-run
#: ratio (vs_baseline) and vs_canonical when the config matches.
CANONICAL_NATIVE_MKEYS: dict = {
    # Median of 5 runs, quiet session (no concurrent pytest/chip jobs),
    # 2026-07-31; band 9.94-13.78 Mkeys/s.  A loaded-CPU session the
    # same day measured 4.65 (band 3.95-6.11) — the 2.7x swing is why
    # the ratio is pinned.  Protocol to re-pin: BASELINE.md round-5
    # "north-star denominator" section.
    #
    # "host" is the provenance fingerprint of the machine class the pin
    # was measured on (utils/platform.py host_fingerprint — CPU vendor/
    # family/model + cores, the thing that actually determines native
    # throughput).  On any other host bench.py OMITS vs_canonical_native
    # and records why, instead of silently comparing against another
    # machine's CPU (ADVICE round 5).  Re-pinning on a new host = re-run
    # the BASELINE.md protocol there and update value + host together.
    ("radix", 28, "int32", 8): {"mkeys": 12.641,
                                "host": "GenuineIntel-6-143/2c"},
}


def measure_native(x: np.ndarray, algo: str, ranks: int,
                   repeats: int = 3) -> tuple[float | None, int]:
    """Run the repo's native backend (pthreads, `ranks` host-CPU ranks) on
    the same keys; return ``(median_seconds, repeats_used)`` — the MEDIAN
    of up to ``repeats`` runs of its own timer (the reference span:
    after-read through final gather), or ``(None, 0)`` if unavailable.
    ``repeats_used`` < ``repeats`` means some runs failed and the median
    rides a degraded denominator — callers surface it in the JSONL row
    (ADVICE round 5), not just this stderr log.  Median-of-N because the
    8-rank run on this image's one CPU core swings 1.5-4x run to run
    (VERDICT r4 weak #4).  Never raises: a missing toolchain / full /tmp
    / timeout must not cost the already-measured TPU result its stdout
    JSON line."""
    try:
        if x.dtype != np.int32:
            log("native baseline: skipped (int32 only)")
            return None, 0
        if shutil.which("cc") is None and shutil.which("gcc") is None:
            log("native baseline: skipped (no C compiler)")
            return None, 0
        d = "mpi_radix_sort" if algo == "radix" else "mpi_sample_sort"
        binary = REPO / d / ("radix_sort" if algo == "radix" else "sample_sort")
        r = subprocess.run(["make", "-C", str(REPO / d), "BACKEND=local"],
                           capture_output=True, text=True)
        if r.returncode != 0:
            log(f"native baseline: build failed: {r.stderr[-500:]}")
            return None, 0
        from mpitest_tpu.utils.io import write_keys_binary
        from mpitest_tpu.utils.nativebench import run_native_sort

        with tempfile.NamedTemporaryFile(suffix=".bin", delete=False) as f:
            path = f.name
        try:
            write_keys_binary(path, x)
            times = []
            for _ in range(max(1, repeats)):
                secs, err = run_native_sort(binary, path, ranks)
                if err:
                    log(f"native baseline: {err}")
                if secs is None:
                    break
                times.append(secs)
            if not times:
                return None, 0
            times.sort()
            if len(times) > 1:
                log(f"native baseline: median of {len(times)} runs "
                    f"(band {times[0]:.2f}-{times[-1]:.2f}s)")
            return times[len(times) // 2], len(times)
        finally:
            os.unlink(path)
    except Exception as e:  # noqa: BLE001 — baseline is best-effort
        log(f"native baseline: failed ({type(e).__name__}: {e})")
        return None, 0


def main() -> None:
    if "--multichip-row" in sys.argv[1:]:
        # subprocess side of the devices=8 row (see _emit_multichip_row)
        multichip_main()
        return
    # BENCH_PLATFORM=cpu[:N] forces an N-device virtual CPU mesh (for
    # TPU-less CI of the bench contract) via the one shared recipe —
    # must land before the first backend query.  The knob registry
    # parses cpu[:N] to the device count (garbage raises KnobError).
    from mpitest_tpu.utils import knobs

    try:
        ndev = knobs.get("BENCH_PLATFORM")
        dtype = np.dtype(knobs.get("BENCH_DTYPE"))
        knobs.validate("BENCH_LOG2N", "BENCH_ALGO", "BENCH_REPEATS",
                       "BENCH_NATIVE_RANKS", "BENCH_NATIVE_REPEATS")
    except ValueError as e:
        # the pre-registry contract: one clean line, never a traceback
        raise SystemExit(str(e)) from None
    if ndev:
        from mpitest_tpu.utils.platform import ensure_virtual_cpu_devices

        ensure_virtual_cpu_devices(ndev)
    import jax

    if dtype.itemsize == 8:
        # Device-resident 64-bit keys exist only under x64 — without it
        # jax.device_put silently DOWNCASTS the host array (observed:
        # float64 2^18 bench produced a wrong sort via a float32 shadow).
        jax.config.update("jax_enable_x64", True)

    from mpitest_tpu.models.api import (SortRetryExhausted,
                                        checked_device_put, ingest_to_mesh,
                                        sort)
    from mpitest_tpu.parallel.mesh import key_sharding, make_mesh
    from mpitest_tpu.utils.metrics import Metrics
    from mpitest_tpu.utils.trace import Tracer

    # The bench measures the DEVICE path: graceful degradation to a host
    # sort or retry backoff sleeps would silently rewrite the metric, so
    # the supervisor is pinned fail-fast here (the chaos grid — `make
    # fault-selftest` — is where recovery is exercised).  Verification
    # stays ON by default: its cost is part of the honest number and is
    # reported below as verify_overhead_s.
    os.environ.setdefault("SORT_FALLBACK", "0")
    os.environ.setdefault("SORT_MAX_RETRIES", "0")
    # ISSUE 13: the measured rows pin the lax exchange engine so the
    # r01+ trajectory stays engine-comparable (auto would flip the
    # primary row to pallas on the first TPU session); the pallas
    # engine's evidence rides the scale-gated smoke cell in the
    # multichip row + `bench/multichip_selftest.py`'s engine axis.
    # Remove the pin deliberately (SORT_EXCHANGE_ENGINE=pallas) when a
    # TPU round is ready to re-baseline the trajectory.
    os.environ.setdefault("SORT_EXCHANGE_ENGINE", "lax")
    # ISSUE 14: measured rows pin the planner off for the same reason —
    # a policy flip (passthrough, algo reroute, learned margin) must
    # never silently rewrite the r01+ trajectory; the planner's own
    # evidence is `make planner-selftest`'s A/B gate.
    os.environ.setdefault("SORT_PLANNER", "off")
    # ISSUE 17: measured rows pin the lax LOCAL engine too — the fused
    # radix_pallas family has only ever run under the interpreter (no
    # Mosaic lowering exercised on a real TPU yet), so auto flipping it
    # in would rewrite the trajectory with an unbaselined engine.  The
    # fused engine's evidence is `make localsort-selftest`; remove the
    # pin deliberately on the first real-TPU re-baseline round.
    os.environ.setdefault("SORT_LOCAL_ENGINE", "lax")

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    log2n = knobs.get("BENCH_LOG2N") or (28 if on_tpu else 20)
    algo = knobs.get("BENCH_ALGO")
    repeats = knobs.get("BENCH_REPEATS")
    native_ranks = knobs.get("BENCH_NATIVE_RANKS")
    n = 1 << log2n

    log(f"bench: platform={platform} devices={len(jax.devices())} "
        f"algo={algo} N=2^{log2n} dtype={dtype} repeats={repeats}")

    from mpitest_tpu.utils.io import generate

    x = generate("uniform", n, dtype, seed=0)
    mesh = make_mesh()

    # Secondary baseline: single-core np.sort of the same keys.
    t0 = time.perf_counter()
    xs = np.sort(x)
    np_s = time.perf_counter() - t0
    np_mkeys = n / np_s / 1e6
    log(f"baseline np.sort: {np_s:.3f}s = {np_mkeys:.1f} Mkeys/s")
    # Correctness reference for the median probe.  Ints reuse the sort
    # above; floats need the encoded (totalOrder) sort for exact bits.
    ref_median = (encoded_median(x, dtype) if dtype.kind == "f"
                  else int(xs[n // 2 - 1]))
    del xs

    # Ingest: stream the keys onto the mesh once through the chunked
    # double-buffered pipeline (models/ingest.py) — untimed for the
    # primary metric; wall + per-stage seconds + overlap recorded.  The
    # staged words feed the ingest-inclusive end-to-end run below.
    t0 = time.perf_counter()
    staged = ingest_to_mesh(x, mesh=mesh)
    for w in staged.words:
        w.block_until_ready()
    ingest_s = time.perf_counter() - t0
    ing = staged.stats
    log(f"ingest (streamed host→mesh): {ingest_s:.3f}s = "
        f"{x.nbytes/ingest_s/1e9:.2f} GB/s (parse {ing.parse_s:.3f}s, "
        f"encode {ing.encode_s:.3f}s [{ing.encode_engine}], "
        f"transfer {ing.transfer_s:.3f}s, "
        f"overlap {ing.overlap_efficiency()*100:.0f}%, {ing.chunks} chunks)")
    del staged  # free the staged words before the steady-state loop

    # Steady-state input: device-resident raw keys (dtype-guarded put —
    # the silent-downcast hazard this file used to only footnote is now
    # a hard error at the source, models/ingest.checked_device_put).
    x_dev = checked_device_put(x, key_sharding(mesh))
    x_dev.block_until_ready()

    # Warmup: compiles the program and settles the exchange cap.
    res = sort(x_dev, algorithm=algo, mesh=mesh, return_result=True)
    probe = encoded_median(res.median_probe_raw(), dtype)
    ok = probe == ref_median
    del res  # free the result buffers: at 2^30 two live results OOM HBM
    log(f"median probe: got {probe} expect {ref_median} ({'OK' if ok else 'MISMATCH'})")
    metric_name = f"{algo}_sort_mkeys_per_s_2e{log2n}_{dtype.name}"
    if not ok:
        log("CORRECTNESS FAILURE — reporting value 0")
        print(json.dumps({"metric": metric_name, "value": 0.0,
                          "unit": "Mkeys/s", "vs_baseline": 0.0,
                          "tooling": tooling_state()}))
        return

    metrics = Metrics(config={"platform": platform, "algo": algo,
                              "log2n": log2n, "dtype": dtype.name,
                              "devices": len(jax.devices())})
    times = []
    tracer = Tracer()  # tracer of the last COMPLETED run (sidecar source)
    for i in range(repeats):
        run_tracer = Tracer()  # per-run: counters/phases must not accumulate
        t0 = time.perf_counter()
        try:
            r = sort(x_dev, algorithm=algo, mesh=mesh, return_result=True,
                     tracer=run_tracer)
            for w in r.words:
                w.block_until_ready()
            # block_until_ready is advisory on the axon tunnel; force a sync.
            jax.device_get(r.words[0][-1:])
        except (jax.errors.JaxRuntimeError, SortRetryExhausted) as e:
            # Near the HBM limit (2^30 = 4 GB keys on a 16 GB chip) the
            # previous run's buffers may not have deallocated yet; keep
            # whatever repeats completed rather than losing the result.
            # (With SORT_MAX_RETRIES=0 the supervisor surfaces the OOM
            # as SortRetryExhausted with the real error as __cause__.)
            cause = f"{e} {getattr(e, '__cause__', None) or ''}"
            if "RESOURCE_EXHAUSTED" not in cause or not times:
                raise
            log(f"run {i}: skipped (HBM exhausted; keeping {len(times)} runs)")
            break
        dt = time.perf_counter() - t0
        del r  # free before the next run (2^30: two live results OOM)
        times.append(dt)
        tracer = run_tracer
        log(f"run {i}: {dt:.3f}s = {n/dt/1e6:.1f} Mkeys/s")

    best = min(times)
    mkeys = metrics.throughput("sort_mkeys_per_s", n, best)

    # North-star denominator: the native backend, 8 host-CPU ranks, same
    # keys, same N (BASELINE.json: ">=8x the throughput of 8-rank
    # host-CPU MPI"; the pthreads backend is the same shared-memory
    # transport class mpirun uses on one host).
    vs_native = None
    native_repeats = knobs.get("BENCH_NATIVE_REPEATS")
    native_repeats_used = None
    if native_ranks > 0:
        native_s, native_repeats_used = measure_native(
            x, algo, native_ranks, repeats=native_repeats)
        if native_s is not None:
            native_mkeys = n / native_s / 1e6
            vs_native = mkeys / native_mkeys
            log(f"native {algo} x{native_ranks} ranks: {native_s:.3f}s = "
                f"{native_mkeys:.1f} Mkeys/s -> vs_native = {vs_native:.2f}x")
            metrics.record(f"native_{native_ranks}rank_mkeys_per_s",
                           round(native_mkeys, 3), "Mkeys/s")
            metrics.record("native_repeats_used", native_repeats_used)
    # Canonical (pinned) denominator: reproducible across rounds even
    # when the same-run native measurement rides a loaded CPU.  The pin
    # is host-specific — on any other machine class it is OMITTED and
    # the skip reason recorded instead (ADVICE round 5).
    canon = CANONICAL_NATIVE_MKEYS.get((algo, log2n, dtype.name, native_ranks))
    vs_canonical = canon_skipped = None
    if canon:
        from mpitest_tpu.utils.platform import host_fingerprint

        fp = host_fingerprint()
        if fp == canon["host"]:
            vs_canonical = mkeys / canon["mkeys"]
            log(f"vs_canonical (pinned {canon['mkeys']} Mkeys/s): "
                f"{vs_canonical:.2f}x")
            metrics.record("vs_canonical_native", round(vs_canonical, 3), "x")
        else:
            canon_skipped = (f"host {fp!r} != pinned {canon['host']!r}")
            log(f"vs_canonical_native omitted: {canon_skipped}")

    # Ingest-inclusive end-to-end: ONE measured run of the real pipeline
    # — streamed ingest (parse/encode overlapped with DMA) feeding the
    # sort directly on the staged words (no device-side re-encode).
    # Programs are warm from the loop above, so this times steady-state
    # data movement + sort, exactly what a production request pays.
    staged = None
    try:
        t0 = time.perf_counter()
        staged = ingest_to_mesh(x, mesh=mesh)
        r = sort(staged, algorithm=algo, mesh=mesh, return_result=True)
        for w in r.words:
            w.block_until_ready()
        jax.device_get(r.words[0][-1:])
        incl_s = time.perf_counter() - t0
        incl_probe = encoded_median(r.median_probe_raw(), dtype)
        del r
        if incl_probe != ref_median:
            log("ingest-inclusive run: MEDIAN MISMATCH — omitting metric")
            incl_s = None
        else:
            # the recorded sub-metrics must describe the SAME run as the
            # sort_incl_ingest headline in this row — the first (warmup)
            # staging ran under different memory/cache conditions
            ing = staged.stats
            ingest_s = ing.wall_s
    except (jax.errors.JaxRuntimeError, SortRetryExhausted) as e:
        # the second staging doubles resident key bytes next to x_dev —
        # near the HBM limit it may OOM; keep the already-measured row.
        cause = f"{e} {getattr(e, '__cause__', None) or ''}"
        if "RESOURCE_EXHAUSTED" not in cause:
            raise
        log("ingest-inclusive run: skipped (HBM exhausted)")
        incl_s = None
    del staged

    metrics.record("baseline_np_sort_mkeys_per_s", round(np_mkeys, 3), "Mkeys/s")
    # Ingest sub-metrics (ISSUE 2): the split that shows WHERE host-path
    # time goes and how much of it the pipeline hides.  overlap
    # efficiency = fraction of transfer wall time intersected by host
    # parse/encode intervals (0 = serial, →1 = fully hidden).
    metrics.record("ingest_gb_per_s", round(x.nbytes / ingest_s / 1e9, 3), "GB/s")
    metrics.record("ingest_wall_s", round(ingest_s, 4), "s")
    metrics.record("ingest_parse_s", round(ing.parse_s, 4), "s")
    metrics.record("ingest_encode_s", round(ing.encode_s, 4), "s")
    metrics.record("ingest_transfer_s", round(ing.transfer_s, 4), "s")
    metrics.record("ingest_overlap_efficiency",
                   round(ing.overlap_efficiency(), 4))
    metrics.record("ingest_chunks", ing.chunks)
    # ISSUE 6: which engine encoded (auto may have degraded — the row
    # must say so, not just the spans) and its measured throughput.
    encode_engine = ing.encode_engine
    encode_gbs = (round(ing.host_bytes / ing.encode_s / 1e9, 3)
                  if ing.encode_s else None)
    metrics.record("encode_engine", encode_engine)
    if encode_gbs is not None:
        metrics.record("encode_gb_per_s", encode_gbs, "GB/s")
    ingest_ratio = None
    if incl_s is not None:
        incl_mkeys = metrics.throughput("sort_incl_ingest_mkeys_per_s",
                                        n, incl_s)
        ingest_ratio = round(incl_mkeys / mkeys, 4)
        metrics.record("ingest_ratio", ingest_ratio, "x")
    # Robustness cost accounting (ISSUE 3): retries actually paid,
    # faults injected (nonzero only under SORT_FAULTS drills), and the
    # wall seconds the always-on verifier added to the LAST timed run —
    # so BENCH JSONs track what robustness costs, not just that it
    # exists.  The acceptance budget is verifier overhead < 5% of sort
    # wall time.
    retries = int(tracer.counters.get("exchange_retries", 0)
                  + tracer.counters.get("sort_retries", 0))
    faults_injected = int(tracer.counters.get("faults_injected", 0))
    verify_s = round(tracer.phases.get("verify", 0.0), 6)
    if verify_s:
        log(f"verifier overhead: {verify_s:.4f}s = "
            f"{100.0 * verify_s / best:.2f}% of best sort wall")
    metrics.record("retries", retries)
    metrics.record("faults_injected", faults_injected)
    metrics.record("verify_overhead_s", verify_s, "s")
    metrics.record_tracer(tracer)  # last run's tracer: per-run values
    metrics.dump()  # structured sidecar → stderr

    # The driver contract: exactly one JSON line on stdout.  vs_baseline
    # is the north-star ratio (vs 8-rank native); when that baseline
    # could not run, the fallback denominator is named in "baseline" so
    # a consumer can never mistake np.sort for the 8-rank target.
    vs_baseline = vs_native if vs_native is not None else mkeys / np_mkeys
    out = {
        "metric": metric_name,
        "value": round(mkeys, 2),
        "unit": "Mkeys/s",
        "vs_baseline": round(vs_baseline, 3),
        "baseline": (f"native_{native_ranks}rank" if vs_native is not None
                     else "np_sort"),
        "vs_np_sort": round(mkeys / np_mkeys, 3),
        "retries": retries,
        "faults_injected": faults_injected,
        "verify_overhead_s": verify_s,
        "encode_engine": encode_engine,
        "exchange_engine": tracer.counters.get("exchange_engine", "lax"),
        # ISSUE 17: the LOCAL engine the timed sort ran (measured rows
        # pin lax via the setdefault above); string cell, no regression
        # math in bench_history.
        "local_engine": tracer.counters.get("local_engine", "lax"),
        # ISSUE 14: the planner column — measured rows pin "off" (see
        # the setdefault above); string cell, no regression math.
        "planner": str(knobs.get("SORT_PLANNER")),
        "tooling": tooling_state(),
    }
    if str(out["local_engine"]).startswith("radix_pallas"):
        # honest caveat: the fused engine has never lowered on a real
        # TPU — any number it produced here is interpreter/CPU-scale
        # evidence, not a TPU measurement.
        out["local_engine_note"] = (
            "fused engine never lowered on real TPU; interpret-mode "
            "evidence only — re-baseline on first TPU session")
    if encode_gbs is not None:
        out["encode_gb_per_s"] = encode_gbs
    if ingest_ratio is not None:
        out["ingest_ratio"] = ingest_ratio
    # ISSUE 16: the timeline fold's trajectory scalars (straggler
    # factor, critical-path phase) from the last timed run's spans —
    # single-device runs carry no exchange balance, so the straggler
    # key is usually absent here and the bench-history cell renders "-".
    from mpitest_tpu.utils import timeline
    out.update(timeline.bench_fold(tracer.spans.spans))
    # Plan digest (ISSUE 12): decision provenance pinned in the row so
    # the trajectory captures what was DECIDED, not only what it scored.
    if "plan_regret" in tracer.counters:
        out["plan_regret"] = round(float(tracer.counters["plan_regret"]),
                                   6)
    if vs_canonical is not None:
        out["vs_canonical_native"] = round(vs_canonical, 3)
    elif canon_skipped:
        out["vs_canonical_native_skipped"] = canon_skipped
    if (native_repeats_used is not None and vs_native is not None
            and native_repeats_used < max(1, native_repeats)):
        # Degraded denominator: fewer native runs succeeded than the
        # documented median-of-N protocol — visible in the row itself,
        # not just the stderr log (ADVICE round 5).
        out["native_repeats_used"] = native_repeats_used
    print(json.dumps(out))

    # Second JSONL row (ISSUE 7): the devices=8 scale-out measurement —
    # real chips when the mesh has them, the BENCH_PLATFORM=cpu:8
    # virtual mesh in a subprocess otherwise.  The primary row above is
    # untouched so the r01+ trajectory stays comparable.
    if knobs.get("BENCH_MULTICHIP") != "off":
        _emit_multichip_row(log2n, algo, dtype, repeats, mkeys, platform)

    # Third JSONL row (ISSUE 8): the sort-as-a-service headline — the
    # persistent server under the closed-loop small-request mix
    # (bench/serve_load.py), p50/p99 latency + throughput.  Scale-gated
    # like the multichip row: tiny-scale runs are driver-contract
    # smoke tests (and several tests scrape stdout's last line as the
    # primary row), so only measured-scale benches pay the ~minute of
    # server spawns.
    if knobs.get("BENCH_SERVE") != "off":
        if log2n >= 16:
            _emit_serve_row()
        else:
            log(f"serve: skipped at 2^{log2n} (scale-gated like the "
                "multichip row; run bench/serve_load.py --row directly)")

    # Fourth JSONL row (ISSUE 14): the adversarial-mix measurement,
    # planner pinned off for trajectory comparability.  Scale-gated
    # like the serve row.
    if knobs.get("BENCH_PLANNER") != "off":
        if log2n >= 16:
            _emit_planner_row()
        else:
            log(f"planner: skipped at 2^{log2n} (scale-gated; run "
                "bench/planner_selftest.py --row directly)")

    # Fifth JSONL row (ISSUE 15): the out-of-core measurement — the
    # same dataset spilled + k-way-merged under a forced SORT_MEM_BUDGET
    # far below its size (spill+merge throughput, run count, disk
    # bytes).  Scale-gated like the serve/planner rows.
    if knobs.get("BENCH_EXTERNAL") != "off":
        if log2n >= 16:
            _emit_external_row()
        else:
            log(f"external: skipped at 2^{log2n} (scale-gated; run "
                "bench/external_selftest.py --row directly)")


if __name__ == "__main__":
    main()
