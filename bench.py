"""Benchmark driver: one JSON metric line on stdout, details on stderr.

Primary metric (BASELINE.md row 4): radix-sort throughput in Mkeys/s on
the flagship device-resident path.  ``vs_baseline`` is the ratio against
the host-CPU baseline sorting the same keys (``np.sort``, a stand-in for
the reference's host-CPU MPI ranks, which need an mpirun this image lacks;
the native pthreads backend is measured separately in bench/).

The timed span is the framework's steady-state contract: keys start and
end **device-resident and sharded on the mesh** (the design removes every
root/host round-trip the reference pays — SURVEY.md §5 long-context row),
so the metric times encode + full multi-pass SPMD sort to completion.
The host→device ingest cost (which on this image rides a network tunnel
at ~0.13 GB/s, nothing like production PCIe/DMA) is measured once and
reported separately in the stderr sidecar, as is the reference-span
number that includes it.

Env knobs: BENCH_LOG2N (default 26 on TPU, 20 on CPU), BENCH_ALGO
(radix|sample), BENCH_REPEATS (default 3), BENCH_DTYPE (int32).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from mpitest_tpu.models.api import sort
    from mpitest_tpu.parallel.mesh import make_mesh

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    log2n = int(os.environ.get("BENCH_LOG2N", "26" if on_tpu else "20"))
    algo = os.environ.get("BENCH_ALGO", "radix")
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    dtype = np.dtype(os.environ.get("BENCH_DTYPE", "int32"))
    n = 1 << log2n

    log(f"bench: platform={platform} devices={len(jax.devices())} "
        f"algo={algo} N=2^{log2n} dtype={dtype} repeats={repeats}")

    rng = np.random.default_rng(0)
    info = np.iinfo(dtype)
    x = rng.integers(info.min, info.max, size=n, dtype=dtype, endpoint=True)
    mesh = make_mesh()

    # Host-CPU baseline: same keys, single-node sort.
    t0 = time.perf_counter()
    ref = np.sort(x)
    base_s = time.perf_counter() - t0
    base_mkeys = n / base_s / 1e6
    log(f"baseline np.sort: {base_s:.3f}s = {base_mkeys:.1f} Mkeys/s")

    # Ingest: place the keys on the mesh once (untimed; rate recorded).
    from mpitest_tpu.parallel.mesh import key_sharding

    t0 = time.perf_counter()
    x_dev = jax.device_put(x, key_sharding(mesh))
    x_dev.block_until_ready()
    ingest_s = time.perf_counter() - t0
    log(f"ingest (host→mesh): {ingest_s:.3f}s = {x.nbytes/ingest_s/1e9:.2f} GB/s")

    # Warmup: compiles the program and settles the exchange cap.
    res = sort(x_dev, algorithm=algo, mesh=mesh, return_result=True)
    probe = res.median_probe()
    expect = int(ref[n // 2 - 1])
    ok = probe == expect
    log(f"median probe: got {probe} expect {expect} ({'OK' if ok else 'MISMATCH'})")
    if not ok:
        log("CORRECTNESS FAILURE — reporting value 0")
        print(json.dumps({"metric": f"{algo}_sort_mkeys_per_s_2e{log2n}_{dtype.name}",
                          "value": 0.0, "unit": "Mkeys/s", "vs_baseline": 0.0}))
        return

    from mpitest_tpu.utils.metrics import Metrics
    from mpitest_tpu.utils.trace import Tracer

    metrics = Metrics(config={"platform": platform, "algo": algo,
                              "log2n": log2n, "dtype": dtype.name,
                              "devices": len(jax.devices())})
    times = []
    tracer = Tracer()
    for i in range(repeats):
        tracer = Tracer()  # per-run: counters/phases must not accumulate
        t0 = time.perf_counter()
        r = sort(x_dev, algorithm=algo, mesh=mesh, return_result=True, tracer=tracer)
        for w in r.words:
            w.block_until_ready()
        # block_until_ready is advisory on the axon tunnel; force a sync.
        jax.device_get(r.words[0][-1:])
        dt = time.perf_counter() - t0
        times.append(dt)
        log(f"run {i}: {dt:.3f}s = {n/dt/1e6:.1f} Mkeys/s")

    best = min(times)
    mkeys = metrics.throughput("sort_mkeys_per_s", n, best)
    metrics.record("baseline_np_sort_mkeys_per_s", round(base_mkeys, 3), "Mkeys/s")
    metrics.record("ingest_gb_per_s", round(x.nbytes / ingest_s / 1e9, 3), "GB/s")
    metrics.throughput("sort_incl_ingest_mkeys_per_s", n, best + ingest_s)
    metrics.record_tracer(tracer)  # last run's tracer: per-run values
    metrics.dump()  # structured sidecar → stderr

    # The driver contract: exactly one JSON line on stdout.
    print(json.dumps({
        "metric": f"{algo}_sort_mkeys_per_s_2e{log2n}_{dtype.name}",
        "value": round(mkeys, 2),
        "unit": "Mkeys/s",
        "vs_baseline": round(mkeys / base_mkeys, 3),
    }))


if __name__ == "__main__":
    main()
