"""Version-compat shims for the JAX APIs this repo uses.

The framework is developed against current JAX (``jax.shard_map``,
``pallas.tpu.CompilerParams``); some images pin older releases where the
same features live under pre-stabilization names (``jax.experimental.
shard_map.shard_map`` with ``check_rep``, ``TPUCompilerParams``).  One
shim module keeps every call site written against the CURRENT spelling
and degrades to the old one only when the new is absent — so upgrading
JAX never needs a code change here, and downgraded images still import.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-stabilization spelling (jax < 0.5)
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f=None, /, *, mesh, in_specs, out_specs, check_vma=True,
                  **kw):
        # old name for the varying-mesh-axes check: check_rep
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma, **kw)


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (current) / ``TPUCompilerParams`` (old)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def enable_x64(enabled: bool = True):
    """``jax.enable_x64`` (current) / ``jax.experimental.enable_x64``
    (old) — the scoped 64-bit-mode context manager."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    from jax.experimental import enable_x64 as _old

    return _old(enabled)


def axis_size(name) -> int | None:
    """Static size of a named mesh axis while tracing under shard_map —
    ``jax.lax.axis_size`` where it exists, the axis-env frame otherwise.
    Returns None outside any axis binding (telemetry then records only
    the per-shard side of a collective's byte accounting)."""
    try:
        return int(jax.lax.axis_size(name))  # current spelling
    except Exception:
        pass
    try:
        frame = jax.core.axis_frame(name)    # old: frame object or int
        return int(getattr(frame, "size", frame))
    except Exception:
        return None


def shape_dtype_struct(shape, dtype, vma=()):
    """``jax.ShapeDtypeStruct`` with varying-mesh-axes where supported;
    old releases have no ``vma`` parameter (their shard_map tracks
    replication via ``check_rep`` instead, see :func:`shard_map`)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)
