"""Structured metrics sidecar — observability the reference lacks.

The reference's entire machine-readable surface is two text lines (stdout
median probe, stderr elapsed seconds — ``mpi_sample_sort.c:205,207``).
This module adds the structured counterpart prescribed by SURVEY.md §5:
throughput (Mkeys/s), per-phase milliseconds, bytes moved, and achieved
collective bandwidth, emitted as one JSON object to a file or stream.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field


@dataclass
class Metrics:
    """Accumulates named measurements; one JSON object out."""

    config: dict = field(default_factory=dict)
    values: dict = field(default_factory=dict)

    def record(self, name: str, value, unit: str | None = None) -> None:
        self.values[name] = {"value": value, **({"unit": unit} if unit else {})}

    def record_phases(self, phases: dict[str, float]) -> None:
        """Fold a Tracer's phase→seconds map in as per-phase milliseconds."""
        for name, secs in phases.items():
            self.record(f"phase_{name}_ms", round(secs * 1e3, 3), "ms")

    def record_tracer(self, tracer) -> None:
        """Fold ONE run's Tracer in: phases, counters, and the achieved
        exchange bandwidth — the single definition shared by bench.py and
        the CLI sidecar.  The denominator is the tracer's "sort" phase
        (the SPMD program span, compute included; the per-pass breakdown
        lives in a SORT_PROFILE trace).  Pass a per-run Tracer — feeding
        one accumulated across R runs inflates every value R-fold."""
        self.record_phases(tracer.phases)
        for name, v in tracer.counters.items():
            self.record(name, v)
        xbytes = tracer.counters.get("exchange_bytes", 0)
        sort_s = tracer.phases.get("sort")
        if xbytes and sort_s:
            self.bandwidth("exchange_gb_per_s", int(xbytes), sort_s)

    def throughput(self, name: str, n_keys: int, seconds: float) -> float:
        mkeys = n_keys / seconds / 1e6
        self.record(name, round(mkeys, 3), "Mkeys/s")
        return mkeys

    def bandwidth(self, name: str, n_bytes: int, seconds: float) -> float:
        gbs = n_bytes / seconds / 1e9
        self.record(name, round(gbs, 3), "GB/s")
        return gbs

    def to_json(self) -> str:
        return json.dumps(
            {"ts": time.time(), "config": self.config, "metrics": self.values}
        )

    def dump(self, path: str | None = None) -> None:
        """Append one JSON line to ``path``, or stderr when no path given."""
        line = self.to_json()
        if path:
            with open(path, "a") as f:
                f.write(line + "\n")
        else:
            print(line, file=sys.stderr)
