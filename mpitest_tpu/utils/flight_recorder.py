"""Flight recorder — a bounded in-memory ring of recent telemetry spans.

Before this module the choice was binary: stream EVERY span to a
``SORT_TRACE`` JSONL (unbounded disk, per-span write) or keep nothing
and have a 3am typed error leave no artifact at all.  The recorder is
the always-on middle: every completed span (every :class:`SpanLog`
process-wide — ``utils/spans.py`` feeds it from its flush path) lands
in one ``collections.deque(maxlen=...)`` ring, costing an append and
nothing else, and the LAST ``SORT_FLIGHT_RECORDER_SIZE`` spans are
dumped to a timestamped JSONL artifact when something goes wrong:

* a typed sort error (``SortIntegrityError`` / ``SortRetryExhausted``
  — hooked at the ``models/api.py`` chokepoint where they escape),
* a fault-site firing (``models/supervisor.wire_registry``),
* ``SIGQUIT`` to the sort server, or its ``/flightrecorder`` endpoint.

Dump artifacts are ordinary span-schema JSONL (plus one metrics-kind
header line naming the trigger), so ``python -m mpitest_tpu.report
--check <dump>`` validates them and the ordinary report tables render
them — incidents self-document in the format every other tool already
reads.  Parent links pointing at spans the ring already evicted are
nulled at dump time (a dangling parent is a schema violation).

Dumps are rate-limited per reason (:data:`MIN_DUMP_INTERVAL_S`) and
capped per process (:data:`MAX_DUMPS`) so a fault storm produces a few
artifacts, never a disk full.  ``SORT_FLIGHT_RECORDER_SIZE=0`` disables
recording entirely.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import TYPE_CHECKING, Any

from mpitest_tpu.utils import knobs

if TYPE_CHECKING:
    from mpitest_tpu.utils.spans import Span

#: At most one dump per distinct reason per this many seconds — a
#: persistent fault loop documents itself once, not once per firing.
MIN_DUMP_INTERVAL_S = 30.0

#: Hard per-process artifact cap (incident evidence, not a trace log).
MAX_DUMPS = 32


class FlightRecorder:
    """The ring + dump mechanics.  One per process (module singleton via
    :func:`get`); tests may construct their own."""

    def __init__(self, capacity: int, directory: str) -> None:
        self.capacity = int(capacity)
        self.directory = directory
        self.ring: "collections.deque[Any]" = collections.deque(
            maxlen=max(self.capacity, 1))
        self.dumps = 0
        self.recorded = 0
        self._seq = 0
        self._last_dump: dict[str, float] = {}
        # reentrant: dump() snapshots while holding it (rate-limit +
        # ring copy must be one atomic decision)
        self._lock = threading.RLock()

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def add(self, span: "Span") -> None:
        """Hot path: one deque append under the ring lock.  The lock
        matters: ``list(deque)`` in a concurrent :meth:`snapshot`
        raises ``RuntimeError: deque mutated during iteration`` against
        a bare append — the planner reads this ring from handler
        threads while every span close appends (ISSUE 14), so both
        sides serialize on the same lock (an uncontended acquire is
        noise next to the span's own JSON encode)."""
        if self.capacity > 0:
            with self._lock:
                self.ring.append(span)
                self.recorded += 1

    def snapshot(self, last_n: int | None = None,
                 kinds: "tuple[str, ...] | None" = None) -> list[dict]:
        """The ring as span dicts — the bounded, lock-consistent read
        API (ISSUE 14: the planner's data source; callers must never
        iterate the deque raw against concurrent appends).  ``kinds``
        filters by span name (e.g. ``("sort.plan",)``); ``last_n``
        keeps only the newest N rows AFTER filtering.  Parent links are
        sanitized: a parent the ring evicted (or the filter dropped)
        becomes ``None`` so the snapshot passes ``report.py --check``
        (dangling parents are schema errors)."""
        with self._lock:
            spans = list(self.ring)
        if kinds is not None:
            want = frozenset(kinds)
            spans = [s for s in spans if getattr(s, "name", None) in want]
        if last_n is not None and last_n >= 0:
            spans = spans[-last_n:] if last_n else []
        dicts = [s.to_dict() for s in spans]
        present = {(d.get("pid"), d.get("id")) for d in dicts}
        for d in dicts:
            if d.get("parent") is not None and \
                    (d.get("pid"), d.get("parent")) not in present:
                d["parent"] = None
        return dicts

    def dump(self, reason: str, rate_limit: bool = False) -> str | None:
        """Write the ring to ``<dir>/flight-<pid>-<seq>-<reason>.jsonl``;
        returns the path (None when disabled, empty, rate-limited or
        past the cap).  Never raises — an incident artifact that cannot
        be written must not compound the incident."""
        if not self.enabled:
            return None
        reason = "".join(c if c.isalnum() or c in "_-" else "_"
                         for c in reason)[:48] or "unknown"
        with self._lock:
            now = time.monotonic()
            if self.dumps >= MAX_DUMPS:
                return None
            if rate_limit and \
                    now - self._last_dump.get(reason, -1e9) \
                    < MIN_DUMP_INTERVAL_S:
                return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
            rows = self.snapshot()
            if not rows:
                return None
            self.dumps += 1
        ts = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(
            self.directory,
            f"flight-{os.getpid()}-{seq:03d}-{reason}-{ts}.jsonl")
        try:
            os.makedirs(self.directory, exist_ok=True)
            header = {"config": {"driver": "flight_recorder",
                                 "reason": reason, "pid": os.getpid(),
                                 "ts": time.time()},
                      "metrics": {"flight_spans": {"value": len(rows)}}}
            with open(path, "w") as f:
                f.write(json.dumps(header) + "\n")
                for d in rows:
                    f.write(json.dumps(d) + "\n")
        except OSError:
            return None
        return path


_SINGLETON: FlightRecorder | None = None
_SINGLETON_LOCK = threading.Lock()


def get() -> FlightRecorder:
    """The process-wide recorder, configured from the knobs at first
    use (``SORT_FLIGHT_RECORDER_SIZE`` / ``SORT_FLIGHT_RECORDER_DIR``)."""
    global _SINGLETON
    rec = _SINGLETON
    if rec is None:
        with _SINGLETON_LOCK:
            rec = _SINGLETON
            if rec is None:
                try:
                    cap = knobs.get("SORT_FLIGHT_RECORDER_SIZE")
                    directory = knobs.get("SORT_FLIGHT_RECORDER_DIR")
                except ValueError:
                    # garbage knob values: the drivers fail fast on
                    # these; a library user gets a disabled recorder,
                    # never a crash from the telemetry layer
                    cap, directory = 0, "."
                rec = _SINGLETON = FlightRecorder(cap, directory)
    return rec


def reset() -> None:
    """Drop the singleton so the next :func:`get` re-reads the knobs
    (tests reconfigure the recorder through ``knobs.scoped_env``)."""
    global _SINGLETON
    with _SINGLETON_LOCK:
        _SINGLETON = None


def record(span: "Span") -> None:
    """SpanLog flush hook (called for every completed span)."""
    get().add(span)


def dump_on_error(reason: str) -> str | None:
    """Incident chokepoint: dump the ring, rate-limited per reason.
    Never raises."""
    try:
        return get().dump(reason, rate_limit=True)
    except Exception:
        return None
