"""ctypes shim over the native ingest engine (native/encode.{h,c}).

The engine replaces the ingest pipeline's four-to-five numpy passes per
chunk (materialize, codec encode, per-word min, per-word max,
fingerprint fold) with ONE C pass that reads each key once and folds
every reduction in registers — and replaces numpy's str->int token
conversion with a C decimal parser for text inputs.  ctypes releases
the GIL around every call, so the ``SORT_INGEST_THREADS`` encode pool
gets real parallelism instead of contended interpreter time.

Engine selection is the registered knob ``SORT_NATIVE_ENCODE``:

* ``auto`` (default) — native when ``native/libencode.so`` loads,
  Python otherwise (the seed behavior);
* ``on`` — native, and a missing/stale library is a LOUD RuntimeError
  (`make native-encode` builds it) — forcing the engine must never
  silently fall back;
* ``off`` — the pure-Python path, bit-for-bit today's behavior.

Parity contract (tests/test_native_encode.py): both engines produce
bit-identical words, min/max, pad key and fingerprint on every chunk,
and raise the SAME exception types on malformed input (ValueError for
bad tokens/headers, OverflowError for out-of-range tokens).  The chosen
engine is visible in spans (``encode_engine`` attr), ``IngestStats``
and bench rows — a degraded ``auto`` is observable, never silent.

Float TEXT parsing stays Python on both engines: C ``strtod`` and
Python ``float()`` agree on conforming inputs, but the parity suite
cannot bound the last-ulp behavior across libcs, and float text is not
the hot format (SORTBIN1 is).  Float *encoding* (the totalOrder bit
flip) is native — it is pure bit arithmetic with no rounding.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import TYPE_CHECKING

import numpy as np

from mpitest_tpu.utils import knobs

if TYPE_CHECKING:
    from mpitest_tpu.models.verify import Fingerprint
    from mpitest_tpu.ops.keys import KeyCodec

_REPO = Path(__file__).resolve().parents[2]
LIB_PATH = _REPO / "native" / "libencode.so"

#: Must match ENC_ABI_VERSION in native/encode.h — a stale .so is
#: refused at load, never called into.
ABI_VERSION = 1

# status codes (native/encode.h)
_ENC_OK = 0
_ENC_EDTYPE = -1
_ENC_EBADTOK = -2
_ENC_ERANGE = -3
_ENC_EMAGIC = -4
_ENC_EHDR = -5
_ENC_ECAP = -6


class _EncFold(ctypes.Structure):
    _fields_ = [
        ("count", ctypes.c_uint64),
        ("xor0", ctypes.c_uint32), ("xor1", ctypes.c_uint32),
        ("sum0", ctypes.c_uint32), ("sum1", ctypes.c_uint32),
        ("min0", ctypes.c_uint32), ("min1", ctypes.c_uint32),
        ("max0", ctypes.c_uint32), ("max1", ctypes.c_uint32),
        ("lexmax0", ctypes.c_uint32), ("lexmax1", ctypes.c_uint32),
    ]


_LOADED = False
_LIB: ctypes.CDLL | None = None
_LIB_ERR: str | None = None
#: guards the one-time load: concurrent first resolutions (two ingest
#: runs, or io's text reader racing stream_to_mesh) must both see the
#: COMPLETED verdict, never a half-written (_LOADED, _LIB) pair.
_LOAD_LOCK = threading.Lock()


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.enc_abi_version.restype = ctypes.c_int
    lib.enc_abi_version.argtypes = []
    lib.enc_encode_fold.restype = ctypes.c_int
    lib.enc_encode_fold.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_char, ctypes.c_int,
        u32p, u32p, ctypes.c_int, ctypes.POINTER(_EncFold)]
    lib.enc_count_tokens.restype = ctypes.c_longlong
    lib.enc_count_tokens.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.enc_parse_i64.restype = ctypes.c_longlong
    lib.enc_parse_i64.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t)]
    lib.enc_parse_u64.restype = ctypes.c_longlong
    lib.enc_parse_u64.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_size_t)]
    lib.enc_check_header.restype = ctypes.c_int
    lib.enc_check_header.argtypes = [
        u8p, ctypes.c_size_t, ctypes.c_char, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char), ctypes.POINTER(ctypes.c_int)]


def _load() -> ctypes.CDLL | None:
    """Load (once) and ABI-check the engine library; None + a recorded
    reason on any failure — ``auto`` degrades to Python, ``on`` raises."""
    global _LOADED, _LIB, _LIB_ERR
    if _LOADED:
        return _LIB
    with _LOAD_LOCK:
        if _LOADED:  # another thread completed the load while we waited
            return _LIB
        lib: ctypes.CDLL | None = None
        err: str | None = None
        if not LIB_PATH.exists():
            err = f"{LIB_PATH} not built (run `make native-encode`)"
        else:
            try:
                lib = ctypes.CDLL(str(LIB_PATH))
                _bind(lib)
                got = int(lib.enc_abi_version())
                if got != ABI_VERSION:
                    err = (f"{LIB_PATH} has ABI v{got}, shim expects "
                           f"v{ABI_VERSION} (rebuild: `make native-encode`)")
                    lib = None
            except (OSError, AttributeError) as e:
                # AttributeError: a stale .so missing a symbol dies
                # inside _bind() before the ABI stamp can be read —
                # same verdict (unusable library), same loud-or-degrade
                # handling.
                err = (f"{LIB_PATH} failed to load: {e} "
                       "(rebuild: `make native-encode`)")
                lib = None
        _LIB, _LIB_ERR = lib, err
        _LOADED = True  # published LAST: readers never see a half-load
    return _LIB


def available() -> bool:
    """True iff the native library is present, loadable and ABI-matched."""
    return _load() is not None


def unavailable_reason() -> str | None:
    _load()
    return _LIB_ERR


def engine() -> str:
    """Resolve ``SORT_NATIVE_ENCODE`` to the engine for this run:
    ``"native"`` or ``"python"``.  ``on`` with no usable library raises
    (forcing the engine must never silently degrade)."""
    mode = knobs.get("SORT_NATIVE_ENCODE")
    if mode == "off":
        return "python"
    if available():
        return "native"
    if mode == "on":
        raise RuntimeError(
            f"SORT_NATIVE_ENCODE=on but the native engine is unavailable: "
            f"{_LIB_ERR}")
    return "python"


def build(quiet: bool = True) -> bool:
    """Best-effort build of the engine library (`make -C bench libencode`)
    — the test suite's fixture hook; selftests go through the Makefile."""
    global _LOADED, _LIB, _LIB_ERR
    r = subprocess.run(
        ["make", "-C", str(_REPO / "bench"), "libencode"],
        capture_output=quiet, text=True)
    with _LOAD_LOCK:  # a racing _load() must not republish a stale handle
        _LOADED, _LIB, _LIB_ERR = False, None, None  # force a re-probe
    return r.returncode == 0 and available()


# ------------------------------------------------------------ encode path

def encode_and_fold(
    chunk: np.ndarray,
    codec: "KeyCodec",
    fold_fp: bool,
    eng: str | None = None,
) -> "tuple[tuple[np.ndarray, ...], list[int], list[int], object, Fingerprint | None]":
    """One chunk's full encode-stage work, engine-dispatched: returns
    ``(words, word_mins, word_maxs, native_max, fingerprint)`` where
    ``words`` are the codec's planar uint32 arrays (msw first),
    ``word_mins``/``word_maxs`` are per-word reductions over the encoded
    words, ``native_max`` is the chunk's maximum key in native dtype
    (None for float dtypes — they pad with the totalOrder sentinel), and
    ``fingerprint`` is the models/verify.py chunk digest (None when
    ``fold_fp`` is False).  Both engines return bit-identical values.

    Chunks must be non-empty: the pipeline never produces one, and an
    empty chunk has no well-defined min/max/pad — rejected identically
    for both engines rather than letting the Python path crash in
    ``w.min()`` while the native path returns inverted neutral folds.
    """
    if np.asarray(chunk).size == 0:
        raise ValueError("encode_and_fold: empty chunk (no min/max/pad "
                         "is defined; the pipeline never produces one)")
    if eng is None:
        eng = engine()
    if eng == "native":
        return _encode_fold_native(chunk, codec, fold_fp)
    return _encode_fold_python(chunk, codec, fold_fp)


def _encode_fold_python(
    chunk: np.ndarray, codec: "KeyCodec", fold_fp: bool,
) -> "tuple[tuple[np.ndarray, ...], list[int], list[int], object, Fingerprint | None]":
    """The pure-Python encode stage — exactly the pre-engine pipeline
    behavior (codec encode + per-word min/max passes + host fingerprint
    fold + native max), kept as the ``off`` path and the parity oracle."""
    from mpitest_tpu.models.verify import fingerprint_host

    words = codec.encode(chunk)
    los = [int(w.min()) for w in words]
    his = [int(w.max()) for w in words]
    m = chunk.max() if chunk.dtype.kind != "f" else None
    fp = fingerprint_host(words) if fold_fp else None
    return words, los, his, m, fp


def _encode_fold_native(
    chunk: np.ndarray, codec: "KeyCodec", fold_fp: bool,
) -> "tuple[tuple[np.ndarray, ...], list[int], list[int], object, Fingerprint | None]":
    from mpitest_tpu.models.verify import Fingerprint

    lib = _load()
    assert lib is not None, "engine() guards this path"
    dt = codec.dtype
    if (not chunk.flags.c_contiguous or not chunk.flags.aligned
            or chunk.dtype != dt):
        # strided views cannot hand C a flat pointer, and a misaligned
        # buffer (np.frombuffer at an odd offset) would make the kernel
        # do unaligned uint32/uint64 loads — UB; normalize first
        chunk = np.ascontiguousarray(chunk, dtype=dt)
    n = int(chunk.size)
    words = tuple(np.empty(n, np.uint32) for _ in range(codec.n_words))
    w0 = words[0].ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
    w1 = (words[1].ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
          if codec.n_words == 2 else None)
    fold = _EncFold()
    rc = lib.enc_encode_fold(
        chunk.ctypes.data_as(ctypes.c_void_p), n,
        dt.kind.encode(), int(dt.itemsize), w0, w1,
        1 if fold_fp else 0, ctypes.byref(fold))
    if rc != _ENC_OK:
        raise TypeError(f"unsupported key dtype: {dt}")
    if codec.n_words == 1:
        los, his = [int(fold.min0)], [int(fold.max0)]
        lexmax = (int(fold.lexmax0),)
        fp = (Fingerprint(n, (int(fold.xor0),), (int(fold.sum0),))
              if fold_fp else None)
    else:
        los = [int(fold.min0), int(fold.min1)]
        his = [int(fold.max0), int(fold.max1)]
        lexmax = (int(fold.lexmax0), int(fold.lexmax1))
        fp = (Fingerprint(n, (int(fold.xor0), int(fold.xor1)),
                          (int(fold.sum0), int(fold.sum1)))
              if fold_fp else None)
    if dt.kind == "f":
        m = None
    else:
        # the lex max of the encoded words IS encode(max key) (the codec
        # is order-preserving); decode the 1-element pad key back to the
        # native scalar the pipeline's pad logic expects
        m = codec.decode(tuple(np.full(1, v, np.uint32)
                               for v in lexmax))[0]
    return words, los, his, m, fp


# ------------------------------------------------------------- text parse

def parse_text_tokens(block: bytes, dt: np.dtype,
                      eng: str | None = None) -> np.ndarray:
    """Whitespace-separated decimal tokens -> keys of ``dt``, matching
    ``utils.io._parse_text_block`` semantics exactly: int dtypes go
    through an int64 intermediate then truncate; uint64 parses exact;
    float dtypes ALWAYS use the Python parser (see module docstring).
    Malformed tokens raise ValueError, out-of-container tokens raise
    OverflowError — the same types numpy's str casts raise."""
    if eng is None:
        eng = engine()
    if eng != "native" or dt.kind == "f":
        return _parse_text_python(block, dt)
    lib = _load()
    assert lib is not None
    n_toks = int(lib.enc_count_tokens(block, len(block)))
    if n_toks == 0:
        return np.empty(0, dt)
    bad = ctypes.c_size_t()
    if dt == np.dtype(np.uint64):
        out = np.empty(n_toks, np.uint64)
        rc = int(lib.enc_parse_u64(
            block, len(block),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            n_toks, ctypes.byref(bad)))
    else:
        out = np.empty(n_toks, np.int64)
        rc = int(lib.enc_parse_i64(
            block, len(block),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n_toks, ctypes.byref(bad)))
    if rc < 0:
        tok = block[bad.value:bad.value + 32].split()[0]
        if rc == _ENC_ERANGE:
            raise OverflowError(
                f"token {tok.decode(errors='replace')!r} out of range "
                f"for the {('uint64' if dt == np.dtype(np.uint64) else 'int64')} "
                "container")
        raise ValueError(
            "invalid literal for int() with base 10: "
            f"{tok.decode(errors='replace')!r}")
    assert rc == n_toks, "token count and parse disagree (engine bug)"
    return out if out.dtype == dt else out.astype(dt)


def _parse_text_python(block: bytes, dt: np.dtype) -> np.ndarray:
    """The numpy token parse — today's ``io._parse_text_block`` body."""
    tokens = block.split()
    if not tokens:
        return np.empty(0, dt)
    toks = np.array(tokens)
    if dt == np.dtype(np.uint64):
        return toks.astype(np.uint64)
    if dt.kind == "f":
        return toks.astype(np.float64).astype(dt)
    return toks.astype(np.int64).astype(dt)


# ----------------------------------------------------------------- header

def check_bin_header(header: bytes, path: str, dtype: np.dtype,
                     eng: str | None = None) -> None:
    """SORTBIN1 header validation, engine-dispatched, raising io.py's
    exact error messages from either engine (the parity suite asserts
    message equality, not just type equality, for headers)."""
    if eng is None:
        eng = engine()
    if eng == "native":
        lib = _load()
        assert lib is not None
        got_kind = ctypes.c_char()
        got_size = ctypes.c_int()
        buf = (ctypes.c_uint8 * len(header)).from_buffer_copy(header)
        rc = int(lib.enc_check_header(
            buf, len(header), dtype.kind.encode(), int(dtype.itemsize),
            ctypes.byref(got_kind), ctypes.byref(got_size)))
        if rc == _ENC_EMAGIC:
            raise ValueError(f"'{path}' is not a SORTBIN1 key file")
        if rc == _ENC_EHDR:
            # latin-1: any byte value decodes to the same char chr()
            # gives the Python engine — a garbage 0xFF kind byte must
            # reproduce io.py's message, not a UnicodeDecodeError
            kind = got_kind.value.decode("latin-1")
            raise ValueError(
                f"'{path}' holds {kind}{got_size.value * 8} keys, "
                f"not {dtype.name}")
        return
    if header[:8] != b"SORTBIN1" or len(header) < 16:
        raise ValueError(f"'{path}' is not a SORTBIN1 key file")
    kind, itemsize = chr(header[8]), header[9]
    if (kind, itemsize) != (dtype.kind, dtype.itemsize):
        raise ValueError(
            f"'{path}' holds {kind}{itemsize * 8} keys, not {dtype.name}")
