"""Central env-knob registry — the ONE place the process environment is read.

Before this module, ~36 ``os.environ`` reads were scattered across
``models/``, ``parallel/``, ``utils/``, ``faults.py``, the CLI driver and
the bench scripts, each with its own ad-hoc validation (or none).  A
typo'd knob could die deep inside a sort, and nothing listed what knobs
even existed.  Now every knob is **registered** here with a name, type,
default, validator and one-line doc; every read goes through
:func:`get` / :func:`get_raw`; and the whole surface is self-documenting
(:func:`reference_table` emits the markdown table README embeds —
``python -m mpitest_tpu.utils.knobs`` prints it).

The contract is enforced mechanically: ``tools/sortlint`` rule
``SL001 env-knob-read`` fails the lint gate on any ``os.environ.get`` /
``os.getenv`` / ``os.environ[...]`` read outside this file.  Writes
(``os.environ[k] = v``, ``setdefault``, building a subprocess env with
``dict(os.environ, ...)``) stay legal everywhere — the rule targets
*reads*, because reads are where unvalidated garbage enters.

Validation is fail-fast and message-stable: a bad value raises
:class:`KnobError` (a ``ValueError``) whose text names the knob and the
accepted values — the same ``[ERROR]``-line contract the CLI has had
since round 1, now produced in exactly one place.

Native-consumed knobs (``COMM_RANKS``, ``COMM_FAULTS``, ...) are
registered too with ``consumer="native"`` so the reference table covers
the whole system; their values are parsed and validated by the C side
(``comm/comm_faults.h`` etc.), so :func:`get` returns them raw.
"""

from __future__ import annotations

import contextlib
import math
import os
from dataclasses import dataclass
from typing import Any, Callable, Iterator

__all__ = [
    "Knob", "KnobError", "get", "get_raw", "iter_knobs", "main",
    "reference_table", "register", "scoped_env", "validate",
]

#: Default elements per streamed ingest chunk: 2^22 keys = 16 MiB of
#: int32 (utils/io.py re-exports this as DEFAULT_CHUNK_ELEMS).
DEFAULT_INGEST_CHUNK = 1 << 22


class KnobError(ValueError):
    """A knob's value failed validation.  Subclasses ``ValueError`` so
    every pre-existing ``except ValueError`` fail-fast site still
    catches it; the message always starts with ``NAME=<raw!r>``."""


@dataclass(frozen=True)
class Knob:
    """One registered environment knob."""

    name: str
    kind: str                     # int | float | flag | enum | csv | str | path | spec | dtype
    default: Any                  # typed default returned when unset (may be None)
    spec: str                     # one-line "validates" column for the table
    doc: str                      # one-line description (required non-empty)
    parse: Callable[[str], Any]   # raw string -> typed value; raises KnobError
    consumer: str = "python"      # "python" | "native" (validated by the C side)

    def read(self) -> Any:
        """Typed, validated value of this knob (the default when unset)."""
        raw = os.environ.get(self.name)
        if raw is None:
            if isinstance(self.default, str):
                # string defaults go through the same parser as env
                # input, so callers always see the parsed type (e.g.
                # SORT_DTYPE yields np.dtype whether set or defaulted)
                return self.parse(self.default)
            return self.default
        return self.parse(raw)


_REGISTRY: dict[str, Knob] = {}


def register(name: str, kind: str, default: Any, spec: str, doc: str,
             parse: Callable[[str], Any], consumer: str = "python") -> None:
    """Register one knob.  Every knob must carry a nonempty one-line doc
    — sortlint rule SL030 fails the gate otherwise, and SL031 fails it
    when a registered knob is missing from README's reference table."""
    if not doc:
        raise ValueError(f"knob {name}: doc must be nonempty")
    if name in _REGISTRY:
        raise ValueError(f"knob {name} registered twice")
    _REGISTRY[name] = Knob(name, kind, default, spec, doc, parse, consumer)


def get(name: str) -> Any:
    """The typed, validated value of registered knob ``name`` (its
    default when unset).  Raises :class:`KeyError` for unregistered
    names — reading an unregistered env var is exactly the bug class
    this module exists to end."""
    return _REGISTRY[name].read()


def get_raw(name: str) -> str | None:
    """The raw (unparsed) string value of a *registered* knob, or None
    when unset — for pass-through uses (subprocess env plumbing,
    read-modify-write of ``XLA_FLAGS``) where the consumer parses."""
    knob = _REGISTRY[name]  # KeyError on unregistered names, like get()
    return os.environ.get(knob.name)


def validate(*names: str) -> None:
    """Fail-fast parse of the named knobs (all registered python-side
    knobs when none given) — the CLI's startup contract: garbage in any
    knob is one clean ``[ERROR]`` line, never a mid-sort stack trace."""
    for name in names or tuple(_REGISTRY):
        knob = _REGISTRY[name]
        if knob.consumer == "python":
            knob.read()


def iter_knobs() -> Iterator[Knob]:
    """Registered knobs in name order (the table's row order)."""
    for name in sorted(_REGISTRY):
        yield _REGISTRY[name]


@contextlib.contextmanager
def scoped_env(**overrides: str | None) -> Iterator[None]:
    """Temporarily set (or, with ``None``, unset) environment variables,
    restoring the previous state on exit — the sanctioned way for
    drivers/tests to flip knobs for a scoped region (the save/restore
    dance ``bench/fault_selftest.py`` and ``bench/mesh_battery.py`` each
    hand-rolled before this existed)."""
    old = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ------------------------------------------------------------- parse kit

def _int(name: str, lo: int | None = None, hi: int | None = None,
         err: str | None = None) -> Callable[[str], int]:
    def parse(raw: str) -> int:
        v: int | None
        try:
            v = int(raw)
        except ValueError:
            v = None
        if (v is None or (lo is not None and v < lo)
                or (hi is not None and v > hi)):
            if err is not None:
                raise KnobError(err.format(name=name, raw=raw)) from None
            bound = f" >= {lo}" if lo is not None else ""
            raise KnobError(f"{name}={raw!r}: use an integer{bound}") from None
        return v
    return parse


def _float(name: str, lo: float, exclusive: bool = False,
           note: str | None = None) -> Callable[[str], float]:
    """Finite-float parser with one bound — the ONE rule for every
    float knob (a finite requirement everywhere: 'inf' backoffs and
    'nan' timeouts are garbage, not policy)."""
    def parse(raw: str) -> float:
        v: float | None
        try:
            v = float(raw)
        except ValueError:
            v = None
        ok = (v is not None and math.isfinite(v)
              and (v > lo if exclusive else v >= lo))
        if not ok:
            op = ">" if exclusive else ">="
            extra = f" ({note})" if note else ""
            raise KnobError(f"{name}={raw!r}: use a finite number "
                            f"{op} {lo:g}{extra}")
        return v  # type: ignore[return-value]
    return parse


def _float_ge0(name: str) -> Callable[[str], float]:
    return _float(name, 0.0)


def _flag(name: str) -> Callable[[str], bool]:
    def parse(raw: str) -> bool:
        if raw not in ("0", "1"):
            raise KnobError(f"{name}={raw!r}: use '1' or '0'")
        return raw == "1"
    return parse


def _enum(name: str, choices: tuple[str, ...],
          err: str | None = None) -> Callable[[str], str]:
    def parse(raw: str) -> str:
        if raw not in choices:
            raise KnobError(err.format(name=name, raw=raw) if err else
                            f"{name}={raw!r}; use one of {choices}")
        return raw
    return parse


def _csv(name: str) -> Callable[[str], tuple[str, ...]]:
    def parse(raw: str) -> tuple[str, ...]:
        parts = tuple(p.strip() for p in raw.split(",") if p.strip())
        if not parts:
            raise KnobError(f"{name}={raw!r}: use a comma-separated list")
        return parts
    return parse


def _passthrough(raw: str) -> str:
    return raw


# ---------------------------------------------------------- registrations
# Core sort knobs (drivers/sort_cli.py + models/api.py).

register("SORT_ALGO", "enum", "sample", "sample | radix",
         "Sort algorithm the CLI dispatches (reference default: sample).",
         _enum("SORT_ALGO", ("sample", "radix"),
               err="{name}={raw!r}: use 'sample' or 'radix'"))


def _dtype(name: str) -> Callable[[str], Any]:
    def parse(raw: str) -> Any:
        from mpitest_tpu.ops.keys import codec_for
        try:
            # np.dtype raises TypeError/ValueError/SyntaxError depending
            # on the garbage; codec_for rejects valid-but-unsupported
            # dtypes with the supported list in the message.
            return codec_for(raw).dtype
        except Exception as e:
            raise KnobError(f"{name}={raw!r}: {e}") from None
    return parse


register("SORT_DTYPE", "dtype", "int32", "a codec-supported numpy dtype",
         "Key dtype for text inputs (int32/uint32/int64/uint64/f32/f64).",
         _dtype("SORT_DTYPE"))


def _parse_digit_bits(raw: str) -> int | None:
    if raw == "auto":
        return None
    try:
        v = int(raw)
    except ValueError:
        v = 0
    if not 1 <= v <= 16:
        raise KnobError(f"SORT_DIGIT_BITS={raw!r}: use 'auto' or an "
                        "integer in [1, 16]") from None
    return v


register("SORT_DIGIT_BITS", "int", None, "'auto' or an integer in [1, 16]",
         "Radix digit width in bits; auto picks from key width and P.",
         _parse_digit_bits)


def _parse_ranks(raw: str) -> int | None:
    if raw == "":
        return None
    try:
        v = int(raw)
    except ValueError:
        v = 0
    if v < 1:
        raise KnobError(f"SORT_RANKS={raw!r}: use a positive integer")
    return v


register("SORT_RANKS", "int", None, "a positive integer (default: all devices)",
         "Mesh size (device count) the sort runs over.", _parse_ranks)


def _parse_cap_factor(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        v = 0.0
    # isfinite: 'nan' passes a <= 0 gate (NaN compares False) and 'inf'
    # overflows the downstream int() — both are garbage, same contract.
    if not math.isfinite(v) or v <= 0:
        raise KnobError(f"SORT_CAP_FACTOR={raw!r}: use a finite number > 0")
    return v


register("SORT_CAP_FACTOR", "float", 2.0, "a finite number > 0",
         "Exchange cap as a multiple of the fair per-peer share.",
         _parse_cap_factor)


def _parse_oversample(raw: str) -> int | None:
    if raw == "":
        return None
    try:
        v = int(raw)
    except ValueError:
        v = 0
    if v < 1:
        raise KnobError(f"SORT_OVERSAMPLE={raw!r}: use an integer >= 1")
    return v


register("SORT_OVERSAMPLE", "int", None, "an integer >= 1 (default: 2P-1)",
         "Samples per shard for sample sort's splitter selection.",
         _parse_oversample)

register("SORT_LOCAL_ENGINE", "enum", "auto",
         "auto | bitonic | lax | radix_pallas | radix_pallas_interpret",
         "Local (single-device) sort engine; auto = bitonic on TPU. "
         "radix_pallas = fused per-pass radix kernel "
         "(ops/radix_pallas.py, one pallas_call per pass, planner-"
         "compacted pass plans); never chosen by auto until the first "
         "real-TPU re-baseline.",
         _enum("SORT_LOCAL_ENGINE",
               ("auto", "bitonic", "lax", "radix_pallas",
                "radix_pallas_interpret")))

register("SORT_EXCHANGE_ENGINE", "enum", "auto",
         "auto | lax | pallas | pallas_interpret",
         "Inter-device exchange engine (ops/exchange.py remote-DMA + "
         "fused pass vs lax.all_to_all); auto = pallas on TPU.",
         _enum("SORT_EXCHANGE_ENGINE",
               ("auto", "lax", "pallas", "pallas_interpret")))


def _parse_devices(raw: str) -> int | None:
    if raw == "auto":
        return None
    try:
        v = int(raw)
    except ValueError:
        v = 0
    if v < 1:
        raise KnobError(f"SORT_DEVICES={raw!r}: use 'auto' or an "
                        "integer >= 1") from None
    return v


# Scale-out knobs (ISSUE 7): the P-device sharded path is the primary
# path, so the device count, the capacity negotiation and the skew
# re-stage are all first-class, registered knobs.

register("SORT_DEVICES", "int", None, "'auto' or an integer >= 1",
         "Mesh device count when none is passed explicitly (auto: all).",
         _parse_devices)
register("SORT_NEGOTIATE", "enum", "auto", "auto | on | off",
         "Exchange-capacity negotiation from a count probe (auto: P>1).",
         _enum("SORT_NEGOTIATE", ("auto", "on", "off")))
register("SORT_RESTAGE", "enum", "auto", "auto | off",
         "Skew-aware re-stage (shard interleave) on exchange imbalance.",
         _enum("SORT_RESTAGE", ("auto", "off")))


def _parse_restage_ratio(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        v = 0.0
    if not math.isfinite(v) or v <= 1.0:
        raise KnobError(f"SORT_RESTAGE_RATIO={raw!r}: use a finite "
                        "number > 1")
    return v


register("SORT_RESTAGE_RATIO", "float", 4.0, "a finite number > 1",
         "Per-peer max/fair-share count ratio that triggers a re-stage.",
         _parse_restage_ratio)

# Plan provenance (ISSUE 12): every runtime decision (algo reroute,
# negotiated cap, re-stage, engine, ladder rung, serve bucket) is
# recorded with predicted-vs-actual quantities and a regret scalar —
# the read side of the ROADMAP item-5 planner.
register("SORT_PLAN", "enum", "on", "on | off",
         "Decision provenance: mint SortPlan records, emit sort.plan "
         "spans and the plan-regret metrics (off = PR 8 behavior).",
         _enum("SORT_PLAN", ("on", "off")))

# Self-tuning planner (ISSUE 14): the policy layer that acts on the
# plan telemetry — per-request algo/cap-margin policy, serve-side
# window/bucket auto-tuning, shadow/canary evaluation.


def _parse_hysteresis(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        v = 0.0
    if not math.isfinite(v) or v <= 1.0:
        raise KnobError(f"SORT_PLANNER_HYSTERESIS={raw!r}: use a finite "
                        "number > 1")
    return v


register("SORT_PLANNER", "enum", "off", "off | shadow | on",
         "Self-tuning planner: off = hand-set defaults (byte-identical "
         "pre-planner stack), shadow = compute + log every policy "
         "choice without acting, on = act (models/planner.py).",
         _enum("SORT_PLANNER", ("off", "shadow", "on")))
register("SORT_PLANNER_WINDOW", "int", 256, "an integer >= 16",
         "Rolling look-back of the planner's learning policies: flight-"
         "ring plan records (cap margin) / request arrivals (serve "
         "tuner).",
         # 16 = planner.MIN_OBSERVATIONS: the serve tuner declines to
         # recommend below it, so a smaller window would validate but
         # silently behave as 16 — fail fast instead
         _int("SORT_PLANNER_WINDOW", lo=16))
register("SORT_PLANNER_HYSTERESIS", "float", 1.5, "a finite number > 1",
         "Minimum up/down ratio a serve-tuner recommendation must "
         "differ by before it may commit (two consecutive agreeing "
         "evaluations required — the window never thrashes).",
         _parse_hysteresis)

# Observability sidecar paths (off when unset — the byte-compatible CLI
# contract is untouched by default).
register("SORT_TRACE", "path", None, "a writable file path",
         "Stream the structured span log as JSONL to this path.",
         _passthrough)
register("SORT_TRACE_CHROME", "path", None, "a writable file path",
         "Write the run's Chrome trace-event JSON (Perfetto) here.",
         _passthrough)
register("SORT_METRICS", "path", None, "a writable file path",
         "Append one JSON metrics sidecar line per run to this path.",
         _passthrough)
register("SORT_PROFILE", "path", None, "a writable directory path",
         "Capture a jax.profiler trace of the sort into this logdir.",
         _passthrough)

# Live-telemetry knobs (ISSUE 10): the operational layer — stream
# sampling, the /metrics side port, the always-on flight recorder, and
# the on-demand device profiling hooks.


def _parse_sample(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        v = 0.0
    if not (math.isfinite(v) and 0.0 < v <= 1.0):
        raise KnobError(f"SORT_TRACE_SAMPLE={raw!r}: use a number in "
                        "(0, 1]")
    return v


register("SORT_TRACE_SAMPLE", "float", 1.0, "a number in (0, 1]",
         "Down-sample the SORT_TRACE stream: keep ~this fraction of "
         "top-level spans (whole subtrees — parent links stay intact; "
         "the flight recorder still sees everything).",
         _parse_sample)


def _parse_metrics_port(raw: str) -> int:
    try:
        v = int(raw)
    except ValueError:
        v = -2
    if not -1 <= v <= 65535:
        raise KnobError(f"SORT_METRICS_PORT={raw!r}: use an integer in "
                        "[-1, 65535] (0 = ephemeral, -1 = disabled)")
    return v


register("SORT_METRICS_PORT", "int", 0, "an integer in [-1, 65535]",
         "Side port for the server's live /metrics, /healthz, /varz, "
         "/flightrecorder, /profile endpoints (0 = ephemeral, -1 = off).",
         _parse_metrics_port)
register("SORT_FLIGHT_RECORDER_SIZE", "int", 2048,
         "an integer >= 0 (0 disables)",
         "Flight-recorder ring capacity: recent spans kept in memory "
         "for incident dumps (typed errors, faults, SIGQUIT).",
         _int("SORT_FLIGHT_RECORDER_SIZE", lo=0))
register("SORT_FLIGHT_RECORDER_DIR", "path", "/tmp/mpitest_flightrec",
         "a writable directory path",
         "Directory flight-recorder dump artifacts land in.",
         _passthrough)
register("SORT_PROFILE_EVERY", "int", 0, "an integer >= 0 (0 = off)",
         "Capture a jax.profiler trace around every Nth server dispatch "
         "(into SORT_PROFILE, else <flight dir>/profile).",
         _int("SORT_PROFILE_EVERY", lo=0))

# Streaming-ingest knobs (utils/io.py + models/ingest.py).

register("SORT_INGEST", "enum", "auto", "auto | stream | mono",
         "Ingest pipeline selector; auto streams inputs above ~32 MiB.",
         _enum("SORT_INGEST", ("auto", "stream", "mono")))
register("SORT_INGEST_CHUNK", "int", None, "an integer >= 1 (default 2^22)",
         "Keys per streamed ingest chunk.",
         _int("SORT_INGEST_CHUNK", lo=1))
register("SORT_INGEST_THREADS", "int", 2, "an integer >= 1",
         "Host parse/encode worker threads in the ingest pipeline.",
         _int("SORT_INGEST_THREADS", lo=1))
register("SORT_DONATE", "enum", "auto", "auto | 1 | 0",
         "Donate staged word buffers to the SPMD program (auto: on TPU).",
         _enum("SORT_DONATE", ("auto", "1", "0"),
               err="{name}={raw!r}: use 'auto', '1' or '0'"))
register("SORT_NATIVE_ENCODE", "enum", "auto", "auto | on | off",
         "Native C encode/parse engine for ingest (utils/native_encode.py).",
         _enum("SORT_NATIVE_ENCODE", ("auto", "on", "off")))

# Robustness knobs (models/supervisor.py + faults.py).

register("SORT_VERIFY", "flag", True, "1 | 0",
         "Always-on output verification (sortedness + fingerprint).",
         _flag("SORT_VERIFY"))
register("SORT_MAX_RETRIES", "int", 2, "an integer >= 0",
         "Dispatch retry budget for transient SPMD launch failures.",
         _int("SORT_MAX_RETRIES", lo=0))
register("SORT_RETRY_BACKOFF", "float", 0.05, "a number >= 0",
         "Base seconds of exponential dispatch-retry backoff.",
         _float_ge0("SORT_RETRY_BACKOFF"))
register("SORT_FALLBACK", "flag", True, "1 | 0",
         "Graceful-degradation ladder (other algorithm, then host sort).",
         _flag("SORT_FALLBACK"))


def _parse_faults(raw: str) -> str | None:
    if not raw:
        return None
    from mpitest_tpu import faults
    faults.FaultRegistry(raw)  # raises ValueError with the site list
    return raw


register("SORT_FAULTS", "spec", None, "comma list of site[:count|:inf]",
         "Deterministic fault-injection plan (mpitest_tpu/faults.py).",
         _parse_faults)


def _parse_faults_seed(raw: str) -> int:
    try:
        return int(raw)
    except ValueError:
        raise KnobError(f"SORT_FAULTS_SEED={raw!r}: use an integer") from None


register("SORT_FAULTS_SEED", "int", 0, "an integer",
         "Seed of the splitmix64 stream fault corruption values draw from.",
         _parse_faults_seed)
register("SORT_FAULT_STALL_MS", "int", 250, "an integer >= 1",
         "Milliseconds the dispatch_stall fault site blocks the "
         "dispatch thread (the chaos drill behind the watchdog gate).",
         _int("SORT_FAULT_STALL_MS", lo=1))
register("SORT_FAULT_ENOSPC_AT", "int", 1, "an integer >= 1",
         "Which spill write (1-based, counted per registry) the armed "
         "spill_enospc fault site fails with ENOSPC — deterministic "
         "mid-merge disk-full drills.",
         _int("SORT_FAULT_ENOSPC_AT", lo=1))

# Sort-as-a-service knobs (ISSUE 8: mpitest_tpu/serve/ + the
# drivers/sort_server.py entry point).  All validated fail-fast at
# server startup — garbage in any of them is one [ERROR] line, never a
# traceback out of the first request.


def _parse_port(raw: str) -> int:
    try:
        v = int(raw)
    except ValueError:
        v = -1
    if not 0 <= v <= 65535:
        raise KnobError(f"SORT_SERVE_PORT={raw!r}: use an integer in "
                        "[0, 65535] (0 = ephemeral)") from None
    return v


register("SORT_SERVE_PORT", "int", 7077, "an integer in [0, 65535]",
         "TCP port the sort server listens on (0 = ephemeral).",
         _parse_port)
register("SORT_SERVE_HOST", "str", "127.0.0.1", "a bind address",
         "Address the sort server binds (default loopback).",
         _passthrough)
register("SORT_SERVE_MAX_INFLIGHT", "int", 64, "an integer >= 1",
         "Admission bound: concurrent in-flight requests before typed "
         "backpressure rejection.",
         _int("SORT_SERVE_MAX_INFLIGHT", lo=1))
register("SORT_SERVE_MAX_BYTES", "int", 1 << 28, "an integer >= 1",
         "Admission bound: total in-flight request payload bytes.",
         _int("SORT_SERVE_MAX_BYTES", lo=1))


def _parse_window_ms(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        v = -1.0
    if not (math.isfinite(v) and v >= 0.0):
        raise KnobError(f"SORT_SERVE_BATCH_WINDOW_MS={raw!r}: use a "
                        "finite number >= 0 (0 disables packing)")
    return v


register("SORT_SERVE_BATCH_WINDOW_MS", "float", 2.0, "a number >= 0",
         "Batching window: how long a dispatch waits to pack more "
         "small requests (0 = dispatch each alone).",
         _parse_window_ms)
register("SORT_SERVE_BATCH_KEYS", "int", 1 << 16, "an integer >= 1",
         "Requests up to this many keys are batchable; one packed "
         "dispatch carries at most this many keys.",
         _int("SORT_SERVE_BATCH_KEYS", lo=1))


def _parse_buckets(raw: str) -> tuple[int, ...]:
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            v = int(part)
        except ValueError:
            v = 0
        if not 1 <= v <= 30:
            raise KnobError(
                f"SORT_SERVE_SHAPE_BUCKETS={raw!r}: use a comma list of "
                "log2 bucket sizes in [1, 30]") from None
        out.append(v)
    if not out:
        raise KnobError(f"SORT_SERVE_SHAPE_BUCKETS={raw!r}: use a comma "
                        "list of log2 bucket sizes in [1, 30]")
    return tuple(sorted(set(out)))


# Default prewarm covers every bucket the packed path can actually
# request: bucket_for() floors at 2^10 and a packed dispatch carries at
# most SORT_SERVE_BATCH_KEYS (default 2^16) keys — prewarming outside
# that range would pay startup compiles for executables no dispatch
# ever uses while leaving reachable buckets to compile on the request
# path (the warm-traffic latency spike prewarm exists to prevent).
register("SORT_SERVE_SHAPE_BUCKETS", "csv", "10,11,12,13,14,15,16",
         "comma list of log2 sizes in [1, 30]",
         "Power-of-two shape buckets the executor cache prewarms at "
         "server startup.",
         _parse_buckets)
register("SORT_SERVE_PREWARM", "enum", "auto", "auto | off",
         "AOT-prewarm the executor cache at startup (off = "
         "jit-on-first-use).",
         _enum("SORT_SERVE_PREWARM", ("auto", "off")))
register("SORT_SERVE_ALLOW_FAULTS", "flag", False, "1 | 0",
         "Honor per-request fault-injection specs (test mode only; "
         "production servers reject them as bad requests).",
         _flag("SORT_SERVE_ALLOW_FAULTS"))

# Request-lifecycle robustness knobs (ISSUE 11): every wire interaction
# and every dispatch is time-bounded, so a hostile network or a wedged
# device costs one bounded thread — never a pinned byte budget or a
# silently dead server.


def _float_gt0(name: str) -> Callable[[str], float]:
    return _float(name, 0.0, exclusive=True)


register("SORT_SERVE_IDLE_TIMEOUT_S", "float", 300.0, "a finite number > 0",
         "Per-connection idle bound: seconds a keep-alive connection may "
         "sit between requests before the server closes it.",
         _float_gt0("SORT_SERVE_IDLE_TIMEOUT_S"))
register("SORT_SERVE_READ_TIMEOUT_S", "float", 30.0, "a finite number > 0",
         "Total wire-read budget per request (header payload reads, "
         "rejected-payload drains, response writes): a client stalled "
         "mid-payload is disconnected and its admission bytes reclaimed "
         "within this bound.",
         _float_gt0("SORT_SERVE_READ_TIMEOUT_S"))


register("SORT_SERVE_DISPATCH_TIMEOUT_S", "float", 120.0,
         "a finite number >= 0 (0 = watchdog off)",
         "Dispatch watchdog bound: a single dispatch exceeding this "
         "trips the circuit breaker (healthz 503, fast typed "
         "rejections) and dumps the flight recorder.",
         _float("SORT_SERVE_DISPATCH_TIMEOUT_S", 0.0,
                note="0 disables the watchdog"))
register("SORT_SERVE_BREAKER_BACKOFF_S", "float", 5.0,
         "a finite number > 0",
         "Seconds the tripped circuit breaker stays open before "
         "half-opening with a probe request (doubles per failed probe).",
         _float_gt0("SORT_SERVE_BREAKER_BACKOFF_S"))
register("SORT_SERVE_COMPLETION_TIMEOUT_S", "float", 600.0,
         "a finite number > 0",
         "Backstop bound a handler thread waits for its dispatched "
         "request to complete before failing it typed 'internal'.",
         _float_gt0("SORT_SERVE_COMPLETION_TIMEOUT_S"))

# Out-of-core external-sort knobs (ISSUE 15: mpitest_tpu/store/).  The
# budget is deliberately forceable far below real device/host memory so
# the whole spill/merge path is CPU-testable; 0 (the default) disables
# the external path entirely — nothing spills unless asked to.

register("SORT_SPILL_DIR", "path", None,
         "a writable directory path (default: a per-process tmp dir)",
         "Directory spill runs are staged in (store/runs.py owns every "
         "read/write of it — sortlint SL014).",
         _passthrough)
register("SORT_MEM_BUDGET", "int", 0, "an integer >= 0 (0 = unlimited)",
         "Host/device byte budget the external sort partitions against; "
         "inputs above it spill to sorted runs and k-way merge back.",
         _int("SORT_MEM_BUDGET", lo=0))
register("SORT_MERGE_FANIN", "int", 16, "an integer >= 2",
         "Maximum runs merged per k-way merge pass; more runs merge in "
         "multiple passes through intermediate runs.",
         _int("SORT_MERGE_FANIN", lo=2))
register("SORT_SERVE_SPILL", "enum", "auto", "auto | off",
         "Route serve requests larger than SORT_SERVE_MAX_BYTES to the "
         "out-of-core spill tier instead of a typed 'bytes' rejection.",
         _enum("SORT_SERVE_SPILL", ("auto", "off")))
register("SORT_SPILL_COMPRESS", "enum", "auto", "auto | on | off",
         "Order-preserving compression of spill runs (SORTRUN2: delta + "
         "bitpacked key blocks, raw payload blocks): 'auto' compresses "
         "when native/libspillz.so loads, 'on' forces it (pure-Python "
         "codec if the library is missing), 'off' writes raw runs.",
         _enum("SORT_SPILL_COMPRESS", ("auto", "on", "off")))
register("SORT_SPILL_THROTTLE_MBPS", "float", 0.0,
         "a finite number >= 0 (0 = unthrottled)",
         "Simulated spill-disk bandwidth cap in MB/s, shared across ALL "
         "spill readers/writers in the process (one token bucket = one "
         "disk) — makes disk-bound external sorts reproducible on fast "
         "local storage for the spillperf gate.",
         _float_ge0("SORT_SPILL_THROTTLE_MBPS"))

# Crash-durable external sort (ISSUE 18: store/manifest.py) — journaled
# spill manifests, kill-resume at the merge phase, and the age-gated
# orphan GC sweep.

register("SORT_RESUME", "enum", "auto", "auto | off",
         "Crash resume of dataset-keyed external sorts: 'auto' "
         "durably journals every committed spill run in a manifest "
         "and a retried/restarted sort of the same dataset id replays "
         "it, re-validates the runs and re-enters at the merge phase; "
         "'off' disables journaling and resume entirely.",
         _enum("SORT_RESUME", ("auto", "off")))
register("SORT_SPILL_GC_AGE_S", "int", 3600, "an integer >= 0",
         "Minimum age in seconds before an orphaned spill file (one "
         "no live manifest references) is reclaimed by the startup GC "
         "sweep — a concurrent sort's fresh files are never swept.",
         _int("SORT_SPILL_GC_AGE_S", lo=0))

# Streaming-sentinel knobs (ISSUE 16: serve/sentinel.py) — live anomaly
# detection over the span stream; alerts ride registered serve.alert
# spans into /alerts, sort_alerts_total and the flight recorder.

register("SORT_SENTINEL", "enum", "on", "on | off",
         "Streaming SLO sentinel in the serve core: rolling-window "
         "burn-rate/drift/imbalance detection raising registered "
         "serve.alert events ('off' detaches the observer entirely).",
         _enum("SORT_SENTINEL", ("on", "off")))
register("SORT_SENTINEL_WINDOW_S", "float", 60.0, "a finite number > 0",
         "Rolling evaluation window of the sentinel's series (burn "
         "rate, regrows, breaker trips) — also the per-rule alert "
         "cooldown.",
         _float_gt0("SORT_SENTINEL_WINDOW_S"))
register("SORT_ALERT_BURN_RATE", "float", 2.0, "a finite number > 0",
         "Error-budget burn-rate multiple (vs the 99.9% SLO allowance) "
         "at which the sentinel raises deadline_burn; 2x that multiple "
         "escalates to critical and dumps the flight recorder.",
         _float_gt0("SORT_ALERT_BURN_RATE"))

# Bench-driver knobs (bench.py).


def _parse_bench_platform(raw: str) -> int | None:
    name, _, ndev = raw.partition(":")
    if name != "cpu":
        raise KnobError(f"BENCH_PLATFORM supports cpu[:N], got {raw!r}")
    try:
        n = int(ndev) if ndev else 1
    except ValueError:
        n = 0
    if n < 1:
        raise KnobError(f"BENCH_PLATFORM supports cpu[:N], got {raw!r}")
    return n


register("BENCH_PLATFORM", "str", None, "cpu[:N]",
         "Force an N-device virtual CPU mesh for TPU-less bench runs.",
         _parse_bench_platform)
register("BENCH_DTYPE", "dtype", "int32", "a codec-supported numpy dtype",
         "Key dtype the bench driver generates and sorts.",
         _dtype("BENCH_DTYPE"))
register("BENCH_LOG2N", "int", None, "an integer >= 1 (default 28 TPU / 20 CPU)",
         "log2 of the bench key count.", _int("BENCH_LOG2N", lo=1))
register("BENCH_ALGO", "enum", "radix", "radix | sample",
         "Algorithm the bench driver measures.",
         _enum("BENCH_ALGO", ("radix", "sample")))
register("BENCH_REPEATS", "int", 3, "an integer >= 1",
         "Timed sort repeats; the row reports the best.",
         _int("BENCH_REPEATS", lo=1))
register("BENCH_NATIVE_RANKS", "int", 8, "an integer >= 0 (0 disables)",
         "Host-CPU ranks for the native denominator run.",
         _int("BENCH_NATIVE_RANKS", lo=0))
register("BENCH_NATIVE_REPEATS", "int", 3, "an integer >= 1",
         "Native denominator runs; the median is the denominator.",
         _int("BENCH_NATIVE_REPEATS", lo=1))
register("BENCH_MULTICHIP", "enum", "auto", "auto | off",
         "Emit the devices=8 bench row (real mesh, else cpu:8 fallback).",
         _enum("BENCH_MULTICHIP", ("auto", "off")))
register("BENCH_SERVE", "enum", "auto", "auto | off",
         "Emit the sort-as-a-service bench row (bench/serve_load.py "
         "against a spawned server).",
         _enum("BENCH_SERVE", ("auto", "off")))
register("BENCH_PLANNER", "enum", "auto", "auto | off",
         "Emit the planner_mix_mkeys_per_s bench row (the adversarial "
         "mix of bench/planner_selftest.py, planner pinned off).",
         _enum("BENCH_PLANNER", ("auto", "off")))
register("BENCH_EXTERNAL", "enum", "auto", "auto | off",
         "Emit the external_sort_mkeys_per_s bench row (out-of-core "
         "spill+merge under a forced SORT_MEM_BUDGET).",
         _enum("BENCH_EXTERNAL", ("auto", "off")))

# Bench-script knobs (bench/*.py probes and batteries).

register("F64_LOG2N", "int", 27, "an integer >= 1",
         "log2 key count for the f64-at-scale probe.",
         _int("F64_LOG2N", lo=1))
register("F64_REPEATS", "int", 2, "an integer >= 1",
         "Repeats for the f64-at-scale probe.", _int("F64_REPEATS", lo=1))
register("MESHB_PARTS", "csv", ("dtypes", "zipf", "pack", "engines"),
         "comma list of battery parts",
         "Which mesh-battery parts to run.", _csv("MESHB_PARTS"))
register("MESHB_LOG2N", "int", 21, "an integer >= 1",
         "log2 key count for the mesh battery.", _int("MESHB_LOG2N", lo=1))
register("STRESS64_LOG2N", "int", None, "an integer >= 1",
         "log2 key count override for the 64-bit stress battery.",
         _int("STRESS64_LOG2N", lo=1))
register("STRESS64_PATTERNS", "csv", None, "comma list of pattern names",
         "Restrict the 64-bit stress battery to these patterns.",
         _csv("STRESS64_PATTERNS"))
register("SKEW_LOG2N", "int", 27, "an integer >= 1",
         "log2 key count for the skew-at-scale battery.",
         _int("SKEW_LOG2N", lo=1))
register("SKEW_REPEATS", "int", 2, "an integer >= 1",
         "Repeats for the skew-at-scale battery.", _int("SKEW_REPEATS", lo=1))
register("SKEW_DISTS", "csv", None, "comma list of distribution names",
         "Restrict the skew battery to these distributions.",
         _csv("SKEW_DISTS"))
register("SKEW_MESH_LOG2N", "int", 24, "an integer >= 1",
         "log2 key count for the skew battery's mesh sweep.",
         _int("SKEW_MESH_LOG2N", lo=1))
register("PROBE_LOG2N", "int", 26, "an integer >= 1",
         "log2 key count for the relayout probe.", _int("PROBE_LOG2N", lo=1))
register("PROBE_PARTS", "csv", ("agree", "net", "1w", "full"),
         "comma list of probe parts",
         "Which relayout-probe parts to run.", _csv("PROBE_PARTS"))
register("FIX_PARTS", "csv", ("uniform", "runs16", "exact"),
         "comma list of probe parts",
         "Which fixdepth-probe parts to run.", _csv("FIX_PARTS"))

# Infrastructure pass-throughs and native-consumed knobs.

register("XLA_FLAGS", "str", "", "XLA flag string (pass-through)",
         "Extra XLA flags; utils/platform.py appends the device-count flag.",
         _passthrough)
register("COMM_RANKS", "int", None, "a positive integer",
         "Rank count for the native pthreads (local) comm backend.",
         _passthrough, consumer="native")
register("COMM_STATS", "path", None, "a writable file path",
         "Native backends append one comm-stats JSON line per run here.",
         _passthrough, consumer="native")
register("COMM_FAULTS", "spec", None,
         "kill:<rank>@<nth> | stall:<rank>@<nth>:<ms>",
         "Native fault drills at collective entry (comm/comm_faults.h).",
         _passthrough, consumer="native")
register("MINIMPI_NP", "int", None, "a positive integer",
         "Process count for the fork-based minimpi runtime.",
         _passthrough, consumer="native")


# ----------------------------------------------------------------- table

def reference_table() -> str:
    """The knob reference as a markdown table — the generated source of
    README's "Environment knobs" section (``make knob-docs`` regenerates
    it; a registered knob missing from README fails sortlint SL031)."""
    rows = ["| knob | type | default | validates | description |",
            "|---|---|---|---|---|"]
    for k in iter_knobs():
        if k.name == "SORT_INGEST_CHUNK":
            # registered default is None (= "use the constant"); the
            # table shows the effective value
            default = str(DEFAULT_INGEST_CHUNK) + " (2^22)"
        elif k.default is None:
            default = "_(unset)_"
        elif isinstance(k.default, bool):
            default = "1" if k.default else "0"
        elif isinstance(k.default, tuple):
            default = ",".join(k.default)
        else:
            default = str(k.default)
        doc = k.doc + (" _(consumed by the C backends)_"
                       if k.consumer == "native" else "")
        spec = k.spec.replace("|", "\\|")  # literal pipes inside md cells
        rows.append(f"| `{k.name}` | {k.kind} | {default} | {spec} | {doc} |")
    return "\n".join(rows)


def main() -> int:
    print(reference_table())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
