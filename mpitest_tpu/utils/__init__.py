from mpitest_tpu.utils import io, metrics, trace  # noqa: F401
