from mpitest_tpu.utils import (  # noqa: F401
    io, knobs, metrics, span_schema, spans, trace)
