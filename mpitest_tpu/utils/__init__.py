from mpitest_tpu.utils import io, metrics, spans, trace  # noqa: F401
