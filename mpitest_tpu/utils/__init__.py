from mpitest_tpu.utils import io, trace  # noqa: F401
