"""Debug-log + phase-timing contract.

The reference's observability surface is (a) an integer debug level from
``argv[2]`` gating printf traces with ``[MASTER] [SLAVE] [COMMON] [VERBOSE]
[ERROR]`` prefixes (``mpi_sample_sort.c:30,42,62,117``), and (b) one
``MPI_Wtime`` pair on rank 0 printed to stderr
(``mpi_sample_sort.c:61,201,207``).  This module keeps that CLI contract
(same prefixes, same levels) and adds what the reference lacks: per-phase
wall timers and a structured metrics sidecar hook (see
:mod:`mpitest_tpu.utils.metrics`).
"""

from __future__ import annotations

import sys
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from mpitest_tpu.utils.spans import SpanLog


@dataclass
class Tracer:
    """Reference-compatible leveled logger + phase timer.

    Prefix vocabulary matches the reference exactly — ``[COMMON]``
    (any-rank step logs, ``mpi_sample_sort.c:30,87``), ``[MASTER]`` /
    ``[SLAVE]`` (root / non-root protocol logs, ``:42,68``),
    ``[VERBOSE]`` (value dumps, ``:84``), ``[ERROR]`` (``:97``).
    ``counters`` accumulates machine-readable measurements (bytes moved,
    pass counts) for the metrics sidecar — observability the reference
    lacks (SURVEY.md §5 metrics row).
    """

    level: int = 0
    phases: dict[str, float] = field(default_factory=dict)
    counters: dict[str, float] = field(default_factory=dict)
    #: Structured span log (utils/spans.py): every ``phase()`` opens a
    #: nested span here too, and sort() adds jit/collective/pass spans.
    #: ``SORT_TRACE=<path>`` streams it as JSONL (wired in models/api.py).
    spans: SpanLog = field(default_factory=SpanLog)
    #: The LAST finished decision record (models/plan.py SortPlan) —
    #: set by sort() at completion so drivers/serve can read the plan
    #: digest without re-parsing the span stream.  One dispatch thread
    #: per tracer by contract, so last-write is the right answer.
    plan: object | None = None

    # -- reference printf contract ------------------------------------
    def common(self, msg: str, min_level: int = 1) -> None:
        if self.level >= min_level:
            print(f"[COMMON] {msg}")

    def verbose(self, msg: str) -> None:
        if self.level >= 1:
            print(f"[VERBOSE] {msg}")

    def master(self, msg: str, min_level: int = 2) -> None:
        if self.level >= min_level:
            print(f"[MASTER] {msg}")

    def slave(self, msg: str, min_level: int = 2) -> None:
        """Non-root protocol log (the reference's per-rank Recv lines,
        ``mpi_sample_sort.c:68,132``)."""
        if self.level >= min_level:
            print(f"[SLAVE] {msg}")

    def error(self, msg: str) -> None:
        print(f"[ERROR] {msg}", file=sys.stderr)

    def count(self, name: str, value: float) -> None:
        self.counters[name] = self.counters.get(name, 0.0) + value

    # -- additions: per-phase timers ----------------------------------
    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        with self.spans.span(f"phase:{name}"):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self.phases[name] = self.phases.get(name, 0.0) + dt
                if self.level >= 1:
                    print(f"[VERBOSE] phase {name}: {dt*1e3:.3f} ms")

    def span(self, name: str, **attrs):
        """Nested structured span (see :mod:`mpitest_tpu.utils.spans`) —
        the finer-grained sibling of :meth:`phase` for events that need
        identity and attributes, not just accumulated wall time."""
        return self.spans.span(name, **attrs)


@contextmanager
def jax_profile(logdir: str | None):
    """Optional jax.profiler trace around the hot region (TPU tracing hook)."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
