"""Structured span tracing — the nested telemetry layer of SURVEY.md §5.

The flat ``Tracer.phases`` dict answers "how long did phase X take in
total"; it cannot answer "which radix pass, which collective, which jit
compile".  This module adds the structured counterpart: nested ``Span``
events (name, parent, t0/dt, attrs) accumulated by a :class:`SpanLog`
that the existing :class:`~mpitest_tpu.utils.trace.Tracer` owns, emitted
three ways:

* **JSONL event stream** — one self-contained JSON object per completed
  span, appended live to ``SORT_TRACE=<path>`` (the native backends'
  ``COMM_STATS`` sidecar is the same schema family; see
  ``comm/comm_stats.h`` and :mod:`mpitest_tpu.report`).
* **Chrome trace-event export** — :meth:`SpanLog.to_chrome_trace`
  produces the ``{"traceEvents": [...]}`` JSON that chrome://tracing and
  Perfetto open directly.
* **In-process** — ``SpanLog.spans`` for tests and the report CLI.

Device-side granularity contract: collectives and radix passes execute
inside ONE fused XLA program, so they are not individually host-timable
— their wall time lives in the enclosing ``jit`` span, and per-op device
timing remains ``SORT_PROFILE``'s job (``jax.profiler``).  What IS
knowable per collective — and what the MPI-vs-ICI comparison needs — is
the static byte/shape accounting, so ``parallel/collectives.py`` and the
SPMD models emit **trace-time point events** (``dt == 0``) carrying
exact byte counts, nested under the jit span whose compile traced them.
A warm (cache-hit) jit call re-emits nothing; the report CLI aggregates
per compiled program, exactly like ``COMM_STATS`` aggregates per native
run.

Robustness vocabulary (ISSUE 3): the supervisor/verifier layer emits
``fault`` (one point event per injected fault, attrs: site/seq),
``supervisor_retry`` (one per retried dispatch, attrs: label/attempt/
error) and ``verify`` (one per verification, attrs: ok/sorted_ok/fp_ok)
— all point events on this same stream, aggregated by the report CLI's
robustness table, so a chaos drill's evidence rides the ordinary
``SORT_TRACE`` file.

Thread model: one SpanLog per Tracer.  The *nesting* API (``span()`` /
``event()``) remains single-threaded — only the host driver thread opens
nested spans.  Pipeline worker threads (the streaming ingest/egress
stages of :mod:`mpitest_tpu.models.ingest`, which measure their own
parse/encode/DMA intervals with ``perf_counter``) report through the
thread-safe :meth:`SpanLog.record` instead: it allocates ids, retains
and streams under a lock, and parents the span under the innermost span
the driver thread currently has open WITHOUT touching the nesting
stack, so concurrent workers can never corrupt span nesting.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Iterator

from mpitest_tpu.utils import knobs

#: In-memory retention cap per SpanLog.  phases/counters accumulate by
#: design across runs on a reused Tracer, but retaining every span of
#: every warm run forever would grow without bound (~a dozen spans per
#: sort); past the cap spans still STREAM to SORT_TRACE and still time
#: correctly — they are just not retained for in-process export, and
#: ``SpanLog.dropped`` counts them.
MAX_RETAINED_SPANS = 65_536

#: Version tag stamped on every JSONL line so the report CLI can reject
#: files from a future incompatible schema instead of misparsing them.
SCHEMA = "span.v1"

#: TPU collective -> its native comm.h twin (SURVEY.md §2.3 census) —
#: the shared vocabulary that lets `python -m mpitest_tpu.report` line
#: up TPU span rows against the C backends' COMM_STATS rows.
MPI_EQUIV = {
    "ragged_all_to_all": "alltoallv",
    "all_to_all": "alltoall",
    "all_gather": "allgather",
    "psum": "allreduce",
    "pmax": "allreduce",
}


def merge_intervals(iv: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted, coalesced ``(t0, t1)`` intervals — shared by the report
    CLI's overlap tables and the ingest pipeline's own stats, so both
    compute 'host work ∩ transfer' identically."""
    out: list[list[float]] = []
    for a, b in sorted(iv):
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def overlap_seconds(a: list[tuple[float, float]],
                    b: list[tuple[float, float]]) -> float:
    """Total intersection of two MERGED interval lists — the wall-clock
    seconds the two activities genuinely ran concurrently.  Clocks are
    process-relative ``perf_counter``, so this is only meaningful for
    intervals from one process."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


@dataclass
class Span:
    """One event: a timed interval (``dt >= 0``) or a point event
    (``dt == 0`` — trace-time collective/pass records)."""

    name: str
    id: int
    parent: int | None
    t0: float               # seconds, process-relative (perf_counter)
    dt: float = 0.0
    attrs: dict[str, object] = field(default_factory=dict)
    #: transient: excluded from the SORT_TRACE stream by the sampler
    #: (SORT_TRACE_SAMPLE < 1); never serialized.  A root's verdict is
    #: inherited by its whole subtree so parent links in the streamed
    #: JSONL always resolve.
    stream_drop: bool = field(default=False, repr=False, compare=False)

    def to_dict(self) -> dict[str, object]:
        # pid scopes the process-relative perf_counter timeline: rows
        # appended to one SORT_TRACE file by different runs must never
        # be compared on t0 (report.py groups overlap math by it).
        return {
            "v": SCHEMA, "name": self.name, "id": self.id,
            "parent": self.parent, "t0": round(self.t0, 9),
            "dt": round(self.dt, 9), "pid": os.getpid(),
            "attrs": self.attrs,
        }


#: Stack of SpanLogs with an open span — `emit()` targets the top one.
#: Module-level so trace-time code (collectives, SPMD models) needs no
#: plumbed-through handle: whatever sort() is running owns the log.
_ACTIVE: list["SpanLog"] = []


def current_log() -> "SpanLog | None":
    return _ACTIVE[-1] if _ACTIVE else None


def emit(name: str, **attrs: object) -> None:
    """Record a point event on the active SpanLog (no-op when tracing is
    off) — the one-line hook the parallel/model layers call."""
    log = current_log()
    if log is not None:
        log.event(name, **attrs)


def maybe_span(
    name: str, **attrs: object,
) -> "contextlib.AbstractContextManager[Span | None]":
    """Span twin of :func:`emit`: a span on the active log, or a no-op
    context manager when tracing is off — what instrumented SPMD model
    code opens around trace-time regions (radix passes, splitter
    rounds)."""
    log = current_log()
    if log is None:
        return contextlib.nullcontext()
    return log.span(name, **attrs)


#: Thread-local request/trace context (ISSUE 10): attributes merged
#: into EVERY span the current thread creates while a context is open.
#: This is how one serve request's ``trace_id`` (and its batch's
#: ``batch_id``) reaches the ``sort`` umbrella, its phases, the
#: supervisor's retry events and the verifier's verdicts WITHOUT
#: plumbing an argument through every layer — the dispatch thread opens
#: the context, everything it runs inherits the identity.
_TRACE_CTX = threading.local()


@contextmanager
def trace_context(**attrs: object) -> Iterator[None]:
    """Attach ``attrs`` (e.g. ``trace_id=...``, ``batch_id=...``) to
    every span this thread creates inside the block.  Nests: inner
    contexts merge over outer ones; explicit span attrs always win over
    context attrs."""
    prev: dict[str, object] | None = getattr(_TRACE_CTX, "attrs", None)
    _TRACE_CTX.attrs = {**prev, **attrs} if prev else dict(attrs)
    try:
        yield
    finally:
        _TRACE_CTX.attrs = prev


def current_trace_context() -> dict[str, object] | None:
    """The attrs the current thread's open :func:`trace_context` would
    stamp (None outside any context)."""
    return getattr(_TRACE_CTX, "attrs", None)


#: Lazily-bound flight-recorder hook (utils/flight_recorder.py): every
#: completed span of every SpanLog lands in the process-wide ring.
#: Bound on first flush so importing spans never drags the knob
#: registry's env reads in at import time.
_flight_record: "Callable[[Span], None] | None" = None


def _flight(s: Span) -> None:
    global _flight_record
    if _flight_record is None:
        from mpitest_tpu.utils import flight_recorder

        _flight_record = flight_recorder.record
    _flight_record(s)


class SpanLog:
    """Accumulates nested spans; exports JSONL and Chrome trace-event.

    ``stream_path``: when set, every completed span appends one JSON
    line immediately (the ``SORT_TRACE`` contract — a crash loses only
    the spans still open, and multiple runs append like any JSONL).
    """

    def __init__(self, stream_path: str | None = None) -> None:
        self.spans: list[Span] = []
        self.stream_path = stream_path
        self.dropped = 0       # spans past MAX_RETAINED_SPANS (streamed only)
        #: observers called with every COMPLETED span (the span-close
        #: path): the live-metrics bridge, tests.  Exceptions are
        #: swallowed — telemetry may never take down the traced path.
        self.observers: list[Callable[[Span], None]] = []
        self._stack: list[int] = []
        self._drop_stack: list[bool] = []   # sampler verdicts, mirrors _stack
        #: trace-context of each open span's OPENER thread (mirrors
        #: _stack) — worker-thread record()s inherit the innermost one.
        self._ctx_stack: list[dict[str, object] | None] = []
        self._next_id = 0
        # SORT_TRACE_SAMPLE stream down-sampling: keep ~rate of the
        # root spans (and each root's whole subtree — parent links in
        # the streamed JSONL must resolve); retention, observers and
        # the flight recorder always see everything.  Deterministic
        # error-diffusion keep rule (a root is kept iff its index
        # crosses an integer multiple of 1/rate), so EVERY rate in
        # (0, 1) thins the stream by exactly that fraction long-run —
        # a keep-every-Nth quantization would silently disable rates
        # above 2/3.
        try:
            rate = float(knobs.get("SORT_TRACE_SAMPLE"))
        except ValueError:
            rate = 1.0
        self._sample_rate = min(rate, 1.0)
        self._sample_seq = 0
        #: guards id allocation, retention and streaming — the pieces
        #: pipeline worker threads share with the driver thread.  The
        #: nesting stack stays driver-thread-only by contract.
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------
    def _new(self, name: str, attrs: dict[str, object],
             t0: float | None = None, dt: float = 0.0) -> Span:
        ctx = current_trace_context()
        with self._lock:
            if ctx is None and self._ctx_stack:
                # a worker thread (ingest/egress stages) reporting under
                # the driver's innermost open span inherits THAT span's
                # trace context — "every span a request touches" must
                # include the pipeline stages its sort ran, even though
                # trace_context itself is thread-local
                ctx = self._ctx_stack[-1]
            if ctx:
                attrs = {**ctx, **attrs}
            s = Span(
                name=name, id=self._next_id,
                parent=self._stack[-1] if self._stack else None,
                t0=time.perf_counter() if t0 is None else t0,
                dt=dt, attrs=attrs,
            )
            self._next_id += 1
            if self._sample_rate < 1.0:
                if self._stack:
                    # subtree follows its root's verdict
                    s.stream_drop = (self._drop_stack[-1]
                                     if self._drop_stack else False)
                else:
                    seq = self._sample_seq
                    self._sample_seq += 1
                    keep = (int((seq + 1) * self._sample_rate)
                            != int(seq * self._sample_rate))
                    s.stream_drop = not keep
        return s

    def _retain(self, s: Span) -> None:
        with self._lock:
            if len(self.spans) < MAX_RETAINED_SPANS:
                self.spans.append(s)
            else:
                self.dropped += 1

    def record(self, name: str, t0: float, dt: float,
               **attrs: object) -> Span:
        """Thread-safe completed-span recording — the entry point for
        pipeline worker threads (ingest/egress stages), which time their
        own intervals and report them here after the fact.  Parents
        under the driver thread's innermost open span; never touches
        the nesting stack."""
        s = self._new(name, attrs, t0=t0, dt=dt)
        self._retain(s)
        self._flush(s)
        return s

    def event(self, name: str, **attrs: object) -> Span:
        """Point event (dt=0) under the currently open span."""
        s = self._new(name, attrs)
        self._retain(s)
        self._flush(s)
        return s

    @contextmanager
    def span(self, name: str, **attrs: object) -> Iterator[Span]:
        """Timed interval; nests under the enclosing open span.  The
        outermost span activates this log for module-level `emit()`."""
        s = self._new(name, attrs)
        self._retain(s)
        # stack mutations under the SAME lock _new reads them under:
        # a worker-thread record() racing this push/pop must see the
        # (parent id, drop verdict) PAIR consistently — a torn read
        # could stream a kept span whose parent subtree was dropped
        # (a dangling parent, which the schema check rejects).
        opener_ctx = current_trace_context()
        with self._lock:
            if opener_ctx is None and self._ctx_stack:
                opener_ctx = self._ctx_stack[-1]  # inherit downward
            self._stack.append(s.id)
            self._drop_stack.append(s.stream_drop)
            self._ctx_stack.append(opener_ctx)
            outermost = len(self._stack) == 1
        if outermost:
            _ACTIVE.append(self)
        try:
            yield s
        finally:
            s.dt = time.perf_counter() - s.t0
            with self._lock:
                self._stack.pop()
                self._drop_stack.pop()
                self._ctx_stack.pop()
            if outermost and _ACTIVE and _ACTIVE[-1] is self:
                _ACTIVE.pop()
            self._flush(s)

    #: serializes stream appends across threads (O_APPEND writes of one
    #: line are atomic on Linux, but don't bet a JSONL schema on it).
    _flush_lock = threading.Lock()

    def _flush(self, s: Span) -> None:
        _flight(s)
        for cb in self.observers:
            try:
                cb(s)
            except Exception:  # noqa: BLE001 — observers never break the path
                pass
        if self.stream_path and not s.stream_drop:
            with self._flush_lock, open(self.stream_path, "a") as f:
                f.write(json.dumps(s.to_dict()) + "\n")

    # -- export -------------------------------------------------------
    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(s.to_dict()) for s in self.spans)

    def dump(self, path: str) -> None:
        """Append ALL spans as JSONL (for logs not opened streaming)."""
        if self.spans:
            with open(path, "a") as f:
                f.write(self.to_jsonl() + "\n")

    def to_chrome_trace(self) -> dict[str, object]:
        """Chrome trace-event JSON (loads in chrome://tracing/Perfetto).

        Timed spans become ``"ph": "X"`` complete events; point events
        become ``"ph": "i"`` instants.  Timestamps are microseconds on
        the same process-relative clock the spans were recorded on.

        The host driver lane is tid 1; ``utils/timeline.py`` then adds
        one stable tid per rank (estimated activity lanes from the
        exchange byte accounting — ISSUE 16: overlapping passes render
        side by side instead of flattening onto the host lane), a disk
        track, and counter tracks for inflight bytes / cap regrowth.
        """
        events: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": 1, "tid": 1,
            "args": {"name": "mpitest_tpu"},
        }]
        for s in self.spans:
            args = {k: v for k, v in s.attrs.items()}
            args["span_id"] = s.id
            if s.parent is not None:
                args["parent_id"] = s.parent
            if s.dt:
                events.append({
                    "name": s.name, "ph": "X", "pid": 1, "tid": 1,
                    "ts": s.t0 * 1e6, "dur": s.dt * 1e6, "args": args,
                })
            else:
                events.append({
                    "name": s.name, "ph": "i", "s": "t", "pid": 1,
                    "tid": 1, "ts": s.t0 * 1e6, "args": args,
                })
        try:
            # lazy: timeline imports this module's interval helpers
            from mpitest_tpu.utils import timeline
            events.extend(timeline.chrome_events(list(self.spans)))
        except Exception:
            pass  # enrichment is best-effort; the host lane stands alone
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    # -- aggregation (shared with the report CLI) ---------------------
    def collective_totals(self) -> dict[str, dict[str, float]]:
        """Per-collective ``{calls, bytes, seconds}`` — the SAME schema
        the native backends dump at ``COMM_STATS`` (comm/comm_stats.h),
        keyed by the comm.h name via :data:`MPI_EQUIV`.  ``seconds`` is
        0.0 for trace-time point events (device-side wall time is not
        per-op observable; see module docstring)."""
        out: dict[str, dict[str, float]] = {}
        for s in self.spans:
            if s.name not in MPI_EQUIV:
                continue
            row = out.setdefault(
                MPI_EQUIV[s.name], {"calls": 0, "bytes": 0, "seconds": 0.0})
            row["calls"] += 1
            row["bytes"] += int(s.attrs.get("bytes", 0))
            row["seconds"] += s.dt
        return out
