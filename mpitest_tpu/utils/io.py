"""Input/output: the reference text format, a binary fast path, generators.

The reference reads whitespace-separated decimal ints on rank 0 with a
one-int-at-a-time ``realloc`` loop (``mpi_sample_sort.c:41-60``,
``mpi_radix_sort.c:74-97``).  That loop has a known ``feof`` overcount bug
(SURVEY.md §2.2) — this reader reads *exactly* the tokens present.

The reference ships no generators; the benchmark configs (BASELINE.json)
need uniform and Zipf(1.1) key streams, so they live here.

Streaming layer (ISSUE 2): the monolithic readers above materialize the
whole array before anything else can start; :func:`open_keys_mmap`
instead hands the ingest pipeline in :mod:`mpitest_tpu.models.ingest` a
zero-copy view of a SORTBIN1 file whose fixed-size slices page in
chunk-by-chunk, so parse, encode and host→device DMA overlap with
bounded host memory.  Text files parse through the multi-threaded
chunked block reader (:func:`iter_key_chunks`) but materialize once on
the IN-MEMORY path — the pipeline's shard bounds need the total key
count up front.  The OUT-OF-CORE path (ISSUE 15,
``store/external.external_sort_file``) has no such need: it consumes
:func:`iter_key_chunks` directly, spilling each parsed chunk straight
to a sorted run, so a text input larger than ``SORT_MEM_BUDGET`` peaks
at chunk-sized host memory instead of the whole file.  The
``SORT_INGEST_CHUNK`` / ``SORT_INGEST_THREADS`` knobs below are the one
canonical reader for both the CLI and the library.
"""

from __future__ import annotations

import numpy as np

from mpitest_tpu.utils import knobs, native_encode


#: Binary key-file header (mirrored in native/sort_common.h): 8 bytes of
#: b"SORTBIN1", 1 byte numpy dtype kind (b'i'/b'u'), 1 byte itemsize,
#: 6 pad bytes, then raw little-endian keys.  The dtype tag makes a
#: width/signedness mismatch a hard error instead of silent regrouping.
BIN_MAGIC = b"SORTBIN1"
BIN_HEADER_LEN = 16


def _bin_header(dtype: np.dtype) -> bytes:
    return BIN_MAGIC + dtype.kind.encode() + bytes([dtype.itemsize]) + b"\0" * 6


def _check_bin_header(header: bytes, path: str, dtype: np.dtype) -> None:
    """Engine-dispatched (ISSUE 6): the native kernel and the Python
    check raise byte-identical errors — utils/native_encode.py owns the
    one message contract, the parity suite asserts it."""
    native_encode.check_bin_header(header, path, dtype)


def read_keys_text(path: str, dtype=np.int32) -> np.ndarray:
    """Read keys: the reference's whitespace-separated decimal format, or
    the SORTBIN1 binary fast path when the magic header is present (both
    the CLI and the native binaries sniff the same magic)."""
    with open(path, "rb") as f:
        head = f.read(BIN_HEADER_LEN)
        if head[:8] == BIN_MAGIC:
            _check_bin_header(head, path, np.dtype(dtype))
            return np.frombuffer(f.read(), dtype=dtype).copy()
    dt = np.dtype(dtype)
    if dt == np.dtype(np.uint64):
        # int64 intermediate would saturate keys above 2^63-1; parse exactly.
        with open(path) as f:
            return np.array([int(t) for t in f.read().split()], dtype=dt)
    if dt.kind == "f":
        # Float tokens (decimal/exponent/inf/nan forms) parse through
        # Python float() — exact IEEE double semantics; the int64
        # intermediate below would garble them (VERDICT r3 weak #3).
        # float32 narrows from that double (C strtod-then-narrow
        # semantics): for long decimal tokens the two roundings can
        # differ from a direct correctly-rounded decimal->f32 parse in
        # the last ulp; shortest-round-trip outputs (write_keys_text)
        # are unaffected, so self-round-trip stays bit-exact.
        with open(path) as f:
            return np.array([float(t) for t in f.read().split()],
                            dtype=np.float64).astype(dt)
    try:
        arr = np.fromfile(path, dtype=np.int64, sep=" ")
    except FileNotFoundError:
        raise FileNotFoundError(f"'{path}' is not a valid file for read.")
    return arr.astype(dt)


#: Keys per buffered block in write_keys_text: ~1 MiB of int32 text per
#: write() call — hundreds of times fewer syscalls than the old
#: np.savetxt row loop, constant memory at any key count.
_WRITE_CHUNK_ELEMS = 1 << 16


def write_keys_text(path: str, keys: np.ndarray,
                    chunk_elems: int = _WRITE_CHUNK_ELEMS) -> None:
    """Write keys in the reference input format (one key per line).
    Floats print with shortest-guaranteed-round-trip precision (9 / 17
    significant digits for f32 / f64), so text round-trips bit-exactly
    for finite values.  Writes are buffered and chunked (``chunk_elems``
    keys per block) — byte-identical output to the old per-row
    ``np.savetxt`` loop at a fraction of the syscalls."""
    keys = np.asarray(keys).reshape(-1)
    if keys.dtype.kind == "f":
        fmt = "%.9g" if keys.dtype.itemsize == 4 else "%.17g"
    else:
        fmt = "%d"
    with open(path, "w", buffering=1 << 20) as f:
        for i in range(0, keys.size, chunk_elems):
            seg = keys[i:i + chunk_elems].tolist()
            if fmt == "%d":
                f.write("\n".join(map(str, seg)))
            else:
                f.write("\n".join(fmt % v for v in seg))
            f.write("\n")


def read_keys_binary(path: str, dtype=np.int32) -> np.ndarray:
    """Binary fast path: SORTBIN1 header + raw little-endian keys (for
    2^28+-scale benches, where text parsing would dominate setup)."""
    with open(path, "rb") as f:
        head = f.read(BIN_HEADER_LEN)
        if head[:8] != BIN_MAGIC:
            raise ValueError(f"'{path}' is not a SORTBIN1 key file")
        _check_bin_header(head, path, np.dtype(dtype))
        return np.frombuffer(f.read(), dtype=dtype).copy()


def write_keys_binary(path: str, keys: np.ndarray) -> None:
    keys = np.asarray(keys).reshape(-1)
    with open(path, "wb") as f:
        f.write(_bin_header(keys.dtype))
        keys.tofile(f)


# --------------------------------------------------------------------------
# Streaming ingest layer (ISSUE 2): env knobs, format sniff, chunked readers
# --------------------------------------------------------------------------

#: Default elements per streamed chunk: 2^22 keys = 16 MiB of int32 —
#: large enough to amortize per-chunk dispatch, small enough that the
#: double-buffered pipeline holds only tens of MiB of host memory and
#: a 2^28 bench run pipelines across 64 chunks.  (Registered — with the
#: rest of the ingest knobs — in utils/knobs.py.)
DEFAULT_CHUNK_ELEMS = knobs.DEFAULT_INGEST_CHUNK

INGEST_MODES = ("auto", "stream", "mono")


def ingest_mode() -> str:
    """Ingest pipeline selector: ``SORT_INGEST`` ∈ {auto, stream, mono}.
    ``auto`` (default) streams when the input is large enough for the
    overlap to pay for the pipeline's thread machinery; ``stream``
    forces the pipeline at any size (tests, the selftest); ``mono``
    forces the legacy monolithic encode + one device_put."""
    return knobs.get("SORT_INGEST")


def ingest_chunk_elems() -> int:
    """Elements per streamed chunk (``SORT_INGEST_CHUNK``, default
    :data:`DEFAULT_CHUNK_ELEMS`)."""
    v = knobs.get("SORT_INGEST_CHUNK")
    return DEFAULT_CHUNK_ELEMS if v is None else v


def ingest_threads() -> int:
    """Host parse/encode worker threads (``SORT_INGEST_THREADS``,
    default 2 — one chunk encoding while another parses; the DMA issue
    thread is separate and always single so transfers stay in order)."""
    return knobs.get("SORT_INGEST_THREADS")


DONATE_MODES = ("auto", "1", "0")


def donate_setting() -> str:
    """Validated ``SORT_DONATE`` value (auto/1/0) — the ONE definition
    of the accepted set, shared by the CLI's fail-fast block and the
    sort dispatch's resolver (models/api.py), which maps ``auto`` to
    backend-dependent behavior."""
    return knobs.get("SORT_DONATE")


def sniff_format(path: str) -> str:
    """``"binary"`` (SORTBIN1 magic) or ``"text"`` — sniffed ONCE here so
    no caller re-checks the magic (each reader used to)."""
    with open(path, "rb") as f:
        return "binary" if f.read(len(BIN_MAGIC)) == BIN_MAGIC else "text"


def open_keys_mmap(path: str, dtype=np.int32) -> np.ndarray:
    """SORTBIN1 file as an mmap-backed array (header checked, zero-copy):
    slicing it costs nothing until the bytes are touched, which is what
    lets the ingest pipeline's parse stage page keys in chunk-by-chunk
    while earlier chunks are already encoding/transferring."""
    dt = np.dtype(dtype)
    with open(path, "rb") as f:
        head = f.read(BIN_HEADER_LEN)
        if head[:8] != BIN_MAGIC:
            raise ValueError(f"'{path}' is not a SORTBIN1 key file")
        _check_bin_header(head, path, dt)
    return np.memmap(path, dtype=dt, mode="r", offset=BIN_HEADER_LEN)


def _parse_text_block(block: bytes, dt: np.dtype,
                      eng: str | None = None) -> np.ndarray:
    """One whitespace-delimited text block -> keys, same per-dtype
    semantics as :func:`read_keys_text` (uint64 exact, floats through a
    float64 parse then narrowed, ints via an int64 intermediate).
    Engine-dispatched (ISSUE 6): the native C decimal parser handles
    integer dtypes when ``SORT_NATIVE_ENCODE`` selects it; float text
    and ``off`` go through the numpy token cast.  Both paths raise the
    same exception types on malformed tokens."""
    return native_encode.parse_text_tokens(block, dt, eng=eng)


#: Text-chunk byte budget per key: covers sign + 10 digits + newline for
#: int32; wider dtypes just yield slightly larger chunks, which is fine
#: (chunk size is a pipeline granularity, not a correctness parameter).
_TEXT_BYTES_PER_KEY = 12


def _iter_text_blocks(path: str, block_bytes: int):
    """Whitespace-safe byte blocks: each block ends on a token boundary,
    the partial trailing token carries into the next block — a chunk
    boundary can never split a key."""
    carry = b""
    with open(path, "rb") as f:
        while True:
            block = f.read(block_bytes)
            if not block:
                if carry.strip():
                    yield carry
                return
            block = carry + block
            cut = max(block.rfind(w) for w in (b" ", b"\n", b"\t", b"\r"))
            if cut < 0:
                carry = block  # one giant token so far; keep accreting
                continue
            carry = block[cut + 1:]
            piece = block[: cut + 1]
            if piece.strip():
                yield piece


def _iter_text_key_chunks(path: str, dt: np.dtype, chunk_elems: int,
                          threads: int | None):
    """Text half of :func:`iter_key_chunks`, post-sniff: blocks parsed
    by a ``threads``-wide pool with bounded prefetch, so parsing chunk
    k+1 overlaps whatever the consumer does with chunk k."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    threads = threads or ingest_threads()
    eng = native_encode.engine()  # resolved ONCE per file, not per block
    blocks = _iter_text_blocks(path, chunk_elems * _TEXT_BYTES_PER_KEY)
    # threadlint TL010: pool threads must be attributable in stacks
    with ThreadPoolExecutor(max_workers=threads,
                            thread_name_prefix="io-parse") as ex:
        pending = deque()
        for b in blocks:
            pending.append(ex.submit(_parse_text_block, b, dt, eng))
            while len(pending) > threads:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()


def iter_key_chunks(path: str, dtype=np.int32, chunk_elems: int | None = None,
                    threads: int | None = None):
    """Yield the file's keys as a sequence of arrays of (approximately)
    ``chunk_elems`` keys, concatenation-equal to :func:`read_keys_auto`.

    SORTBIN1 files yield mmap-backed zero-copy slices (exactly
    ``chunk_elems`` long except the tail); text files parse through
    :func:`_iter_text_key_chunks`.
    """
    dt = np.dtype(dtype)
    chunk_elems = chunk_elems or ingest_chunk_elems()
    if sniff_format(path) == "binary":
        mm = open_keys_mmap(path, dt)
        for i in range(0, mm.size, chunk_elems):
            yield mm[i:i + chunk_elems]
        return
    yield from _iter_text_key_chunks(path, dt, chunk_elems, threads)


def read_keys_auto(path: str, dtype=np.int32, mmap: bool = False) -> np.ndarray:
    """Read keys, sniffing SORTBIN1 vs text ONCE (the sniff used to be
    re-done by every caller, and the text branch dispatches straight to
    the post-sniff iterator).  ``mmap=True`` returns the zero-copy
    mmap-backed array for binary files (the streaming ingest path pages
    it in chunk-by-chunk); text files parse through the multi-threaded
    chunked reader.  Well-formed decimal tokens only — the same contract
    :func:`read_keys_text` documents."""
    dt = np.dtype(dtype)
    if sniff_format(path) == "binary":
        return open_keys_mmap(path, dt) if mmap else read_keys_binary(path, dt)
    parts = list(_iter_text_key_chunks(path, dt, ingest_chunk_elems(), None))
    if not parts:
        return np.empty(0, dt)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def generate_uniform(n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    """Uniform random keys over the full range of ``dtype``.

    Float dtypes get finite, sign-symmetric values spanning most of the
    exponent range (normal significand x per-element power of ten).  No
    NaN/Inf: the ``np.sort`` median-parity probe must be well-defined
    (totalOrder NaN placement is the codec's documented divergence,
    ``ops/keys.py``), and finite wide-exponent keys already exercise
    every bit of the encode path."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if dt.kind == "f":
        max_exp = 30 if dt.itemsize == 4 else 250
        expo = rng.integers(-max_exp, max_exp, size=n, endpoint=True)
        return (rng.standard_normal(n) * 10.0 ** expo).astype(dt)
    info = np.iinfo(dt)
    return rng.integers(info.min, info.max, size=n, dtype=dt, endpoint=True)


def generate_zipf(n: int, a: float = 1.1, dtype=np.int64, seed: int = 0) -> np.ndarray:
    """Zipf-skewed keys — the splitter-imbalance stressor (BASELINE.json
    configs[4]).  Heavy duplication of small values exercises bucket-cap
    overflow paths (the reference overflows silently,
    ``mpi_sample_sort.c:140-144``; this framework detects and retries)."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    vals = rng.zipf(a, size=n)
    if dt.kind == "f":
        # heavy-tail draws beyond the float's exact-integer range round;
        # harmless for sort inputs (the rounded array IS the input)
        return vals.astype(dt)
    return np.clip(vals, None, int(np.iinfo(dt).max)).astype(dt)


def generate(kind: str, n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    if kind == "uniform":
        return generate_uniform(n, dtype, seed)
    if kind == "zipf":
        return generate_zipf(n, dtype=dtype, seed=seed)
    raise ValueError(f"unknown generator kind: {kind!r}")
