"""Input/output: the reference text format, a binary fast path, generators.

The reference reads whitespace-separated decimal ints on rank 0 with a
one-int-at-a-time ``realloc`` loop (``mpi_sample_sort.c:41-60``,
``mpi_radix_sort.c:74-97``).  That loop has a known ``feof`` overcount bug
(SURVEY.md §2.2) — this reader reads *exactly* the tokens present.

The reference ships no generators; the benchmark configs (BASELINE.json)
need uniform and Zipf(1.1) key streams, so they live here.
"""

from __future__ import annotations

import numpy as np


#: Binary key-file header (mirrored in native/sort_common.h): 8 bytes of
#: b"SORTBIN1", 1 byte numpy dtype kind (b'i'/b'u'), 1 byte itemsize,
#: 6 pad bytes, then raw little-endian keys.  The dtype tag makes a
#: width/signedness mismatch a hard error instead of silent regrouping.
BIN_MAGIC = b"SORTBIN1"
BIN_HEADER_LEN = 16


def _bin_header(dtype: np.dtype) -> bytes:
    return BIN_MAGIC + dtype.kind.encode() + bytes([dtype.itemsize]) + b"\0" * 6


def _check_bin_header(header: bytes, path: str, dtype: np.dtype) -> None:
    kind, itemsize = chr(header[8]), header[9]
    if (kind, itemsize) != (dtype.kind, dtype.itemsize):
        raise ValueError(
            f"'{path}' holds {kind}{itemsize * 8} keys, not {dtype.name}"
        )


def read_keys_text(path: str, dtype=np.int32) -> np.ndarray:
    """Read keys: the reference's whitespace-separated decimal format, or
    the SORTBIN1 binary fast path when the magic header is present (both
    the CLI and the native binaries sniff the same magic)."""
    with open(path, "rb") as f:
        head = f.read(BIN_HEADER_LEN)
        if head[:8] == BIN_MAGIC:
            _check_bin_header(head, path, np.dtype(dtype))
            return np.frombuffer(f.read(), dtype=dtype).copy()
    dt = np.dtype(dtype)
    if dt == np.dtype(np.uint64):
        # int64 intermediate would saturate keys above 2^63-1; parse exactly.
        with open(path) as f:
            return np.array([int(t) for t in f.read().split()], dtype=dt)
    if dt.kind == "f":
        # Float tokens (decimal/exponent/inf/nan forms) parse through
        # Python float() — exact IEEE double semantics; the int64
        # intermediate below would garble them (VERDICT r3 weak #3).
        # float32 narrows from that double (C strtod-then-narrow
        # semantics): for long decimal tokens the two roundings can
        # differ from a direct correctly-rounded decimal->f32 parse in
        # the last ulp; shortest-round-trip outputs (write_keys_text)
        # are unaffected, so self-round-trip stays bit-exact.
        with open(path) as f:
            return np.array([float(t) for t in f.read().split()],
                            dtype=np.float64).astype(dt)
    try:
        arr = np.fromfile(path, dtype=np.int64, sep=" ")
    except FileNotFoundError:
        raise FileNotFoundError(f"'{path}' is not a valid file for read.")
    return arr.astype(dt)


def write_keys_text(path: str, keys: np.ndarray) -> None:
    """Write keys in the reference input format (one key per line).
    Floats print with shortest-guaranteed-round-trip precision (9 / 17
    significant digits for f32 / f64), so text round-trips bit-exactly
    for finite values."""
    keys = np.asarray(keys).reshape(-1)
    if keys.dtype.kind == "f":
        fmt = "%.9g" if keys.dtype.itemsize == 4 else "%.17g"
    else:
        fmt = "%d"
    np.savetxt(path, keys, fmt=fmt)


def read_keys_binary(path: str, dtype=np.int32) -> np.ndarray:
    """Binary fast path: SORTBIN1 header + raw little-endian keys (for
    2^28+-scale benches, where text parsing would dominate setup)."""
    with open(path, "rb") as f:
        head = f.read(BIN_HEADER_LEN)
        if head[:8] != BIN_MAGIC:
            raise ValueError(f"'{path}' is not a SORTBIN1 key file")
        _check_bin_header(head, path, np.dtype(dtype))
        return np.frombuffer(f.read(), dtype=dtype).copy()


def write_keys_binary(path: str, keys: np.ndarray) -> None:
    keys = np.asarray(keys).reshape(-1)
    with open(path, "wb") as f:
        f.write(_bin_header(keys.dtype))
        keys.tofile(f)


def generate_uniform(n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    """Uniform random keys over the full range of ``dtype``.

    Float dtypes get finite, sign-symmetric values spanning most of the
    exponent range (normal significand x per-element power of ten).  No
    NaN/Inf: the ``np.sort`` median-parity probe must be well-defined
    (totalOrder NaN placement is the codec's documented divergence,
    ``ops/keys.py``), and finite wide-exponent keys already exercise
    every bit of the encode path."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if dt.kind == "f":
        max_exp = 30 if dt.itemsize == 4 else 250
        expo = rng.integers(-max_exp, max_exp, size=n, endpoint=True)
        return (rng.standard_normal(n) * 10.0 ** expo).astype(dt)
    info = np.iinfo(dt)
    return rng.integers(info.min, info.max, size=n, dtype=dt, endpoint=True)


def generate_zipf(n: int, a: float = 1.1, dtype=np.int64, seed: int = 0) -> np.ndarray:
    """Zipf-skewed keys — the splitter-imbalance stressor (BASELINE.json
    configs[4]).  Heavy duplication of small values exercises bucket-cap
    overflow paths (the reference overflows silently,
    ``mpi_sample_sort.c:140-144``; this framework detects and retries)."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    vals = rng.zipf(a, size=n)
    if dt.kind == "f":
        # heavy-tail draws beyond the float's exact-integer range round;
        # harmless for sort inputs (the rounded array IS the input)
        return vals.astype(dt)
    return np.clip(vals, None, int(np.iinfo(dt).max)).astype(dt)


def generate(kind: str, n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    if kind == "uniform":
        return generate_uniform(n, dtype, seed)
    if kind == "zipf":
        return generate_zipf(n, dtype=dtype, seed=seed)
    raise ValueError(f"unknown generator kind: {kind!r}")
