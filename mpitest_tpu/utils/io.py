"""Input/output: the reference text format, a binary fast path, generators.

The reference reads whitespace-separated decimal ints on rank 0 with a
one-int-at-a-time ``realloc`` loop (``mpi_sample_sort.c:41-60``,
``mpi_radix_sort.c:74-97``).  That loop has a known ``feof`` overcount bug
(SURVEY.md §2.2) — this reader reads *exactly* the tokens present.

The reference ships no generators; the benchmark configs (BASELINE.json)
need uniform and Zipf(1.1) key streams, so they live here.
"""

from __future__ import annotations

import numpy as np


def read_keys_text(path: str, dtype=np.int32) -> np.ndarray:
    """Read whitespace-separated decimal integers (reference input format)."""
    dt = np.dtype(dtype)
    if dt == np.dtype(np.uint64):
        # int64 intermediate would saturate keys above 2^63-1; parse exactly.
        with open(path) as f:
            return np.array([int(t) for t in f.read().split()], dtype=dt)
    try:
        arr = np.fromfile(path, dtype=np.int64, sep=" ")
    except FileNotFoundError:
        raise FileNotFoundError(f"'{path}' is not a valid file for read.")
    return arr.astype(dt)


def write_keys_text(path: str, keys: np.ndarray) -> None:
    """Write keys in the reference input format (one int per line)."""
    np.savetxt(path, np.asarray(keys).reshape(-1), fmt="%d")


def read_keys_binary(path: str, dtype=np.int32) -> np.ndarray:
    """Binary fast path: raw little-endian keys (for 2^30-scale benches,
    where text parsing would dominate the measured span's setup)."""
    return np.fromfile(path, dtype=dtype)


def write_keys_binary(path: str, keys: np.ndarray) -> None:
    np.asarray(keys).tofile(path)


def generate_uniform(n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    """Uniform random keys over the full range of ``dtype``."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    info = np.iinfo(dt)
    return rng.integers(info.min, info.max, size=n, dtype=dt, endpoint=True)


def generate_zipf(n: int, a: float = 1.1, dtype=np.int64, seed: int = 0) -> np.ndarray:
    """Zipf-skewed keys — the splitter-imbalance stressor (BASELINE.json
    configs[4]).  Heavy duplication of small values exercises bucket-cap
    overflow paths (the reference overflows silently,
    ``mpi_sample_sort.c:140-144``; this framework detects and retries)."""
    rng = np.random.default_rng(seed)
    info = np.iinfo(np.dtype(dtype))
    vals = rng.zipf(a, size=n)
    return np.clip(vals, None, int(info.max)).astype(dtype)


def generate(kind: str, n: int, dtype=np.int32, seed: int = 0) -> np.ndarray:
    if kind == "uniform":
        return generate_uniform(n, dtype, seed)
    if kind == "zipf":
        return generate_zipf(n, dtype=dtype, seed=seed)
    raise ValueError(f"unknown generator kind: {kind!r}")
