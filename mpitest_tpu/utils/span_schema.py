"""Registered span-name schema — the vocabulary contract of the telemetry layer.

``report.py`` aggregates spans **by string match** (phase tables, the
collective table via ``MPI_EQUIV``, the robustness table, the ingest
overlap gate).  Before this module, a renamed span silently vanished
from those tables: the producer compiled, the tests that grep'd other
names passed, and the telemetry got quietly poorer.  Now every span
name a producer may emit is registered HERE, report.py consumes the
same constants, and ``tools/sortlint`` rule ``SL003 span-name`` fails
the lint gate on any literal span name outside the registry — a rename
must touch this file, which is exactly where the report aggregations
look.

Two name classes:

* **exact names** (:data:`SPAN_NAMES`) — every key maps to a one-line
  doc of what the span means and who emits it;
* **phase names** (:data:`PHASE_NAMES`) — ``Tracer.phase(name)`` emits
  ``phase:<name>``; the report's per-phase table keys on the suffix.

This module is import-light on purpose (stdlib only): sortlint loads it
without pulling jax/numpy, so the lint CI job needs no device stack.
"""

from __future__ import annotations

#: ``Tracer.phase(name)`` vocabulary → ``phase:<name>`` spans, summed
#: into the report's per-phase wall-time table.
PHASE_NAMES: frozenset[str] = frozenset({
    "sort",        # SPMD program dispatch + execution
    "encode",      # host-side key codec encode
    "device_put",  # host→device placement (monolithic path)
    "decode",      # device→host decode of the sorted words
    "verify",      # always-on output verification (ISSUE 3)
    "ingest",      # streamed ingest pipeline region
    "plan",        # pass/splitter planning
})

#: Prefix of every phase span (``Tracer.phase`` is the only producer).
PHASE_PREFIX = "phase:"

#: Exact span/event names → one-line doc.  Grouped by producer.
SPAN_NAMES: dict[str, str] = {
    # models/api.py — run umbrellas and the jit split
    "sort": "one sort() run (umbrella span; device-mem high-water attr)",
    "ingest": "one ingest_to_mesh() run (umbrella span)",
    "jit_compile_execute": "first call of a jit program (trace+compile+run)",
    "jit_execute": "warm call of a jit program",
    # models/* — trace-time algorithm structure
    "radix_pass": "one LSD radix pass (trace-time, per compile)",
    "splitter_round": "one sample-sort splitter round (trace-time)",
    # parallel/collectives.py — trace-time collective byte accounting
    "all_gather": "lax.all_gather point event (bytes, ranks)",
    "psum": "lax.psum point event (bytes, op=sum)",
    "pmax": "lax.pmax point event (bytes, op=max)",
    "ragged_all_to_all": "padded alltoallv exchange (bytes, wire_bytes, cap)",
    # robustness vocabulary (ISSUE 3)
    "fault": "one injected fault firing (site, seq)",
    "supervisor_retry": "one retried SPMD dispatch (label, attempt, error)",
    "verify": "one output verification (ok, sorted_ok, fp_ok)",
    # scale-out vocabulary (ISSUE 7)
    "exchange_balance": ("negotiated exchange capacity + per-rank "
                         "send/recv byte balance (host count probe)"),
    "restage": "skew-aware re-stage (shard interleave) of the input words",
    "negotiate_probe": ("one capacity-negotiation count probe "
                        "(trace-time; its collectives nest here, "
                        "not under a pass)"),
    # serve/ — sort-as-a-service vocabulary (ISSUE 8); the report CLI's
    # SLO table computes p50/p99 latency from serve.request durations
    "serve.request": ("one served sort request (n, dtype, status, "
                      "batched, bucket) — the SLO latency unit"),
    "serve.batch": ("one packed multi-tenant dispatch (segments, keys, "
                    "bucket)"),
    "serve.compile_cache": ("executor-cache lookup point event (hit, "
                            "bucket, dtype; compile_s + XLA cost "
                            "analysis flops/bytes on miss)"),
    "serve.profile": ("one on-demand jax.profiler capture (logdir, "
                      "trigger=endpoint|every, seq) — ISSUE 10 device "
                      "profiling hook"),
    # request-lifecycle robustness vocabulary (ISSUE 11)
    "serve.deadline": ("one request cancelled because its deadline_ms "
                       "expired before dispatch (stage=admission|queue|"
                       "dispatch, trace_id) — never dispatched"),
    "serve.watchdog": ("dispatch-watchdog state change (event=trip|"
                       "probe|recovered|reopen|drain_timeout; stuck "
                       "trace_ids, age_s) — the circuit-breaker audit "
                       "trail"),
    "serve.hedge": ("one client-side hedged request (winner=primary|"
                    "hedge, waited_ms) — the p99-tail second attempt"),
    # streaming sentinel vocabulary (ISSUE 16): one point event per
    # raised anomaly alert; rule names come from doctor.DOCTOR_RULES
    # (sortlint SL007) and the bridge folds them into
    # sort_alerts_total{rule,severity}
    "serve.alert": ("one sentinel anomaly alert (rule, severity, "
                    "value, threshold, window_s) — serve/sentinel.py "
                    "rolling-window detection; /alerts lists them"),
    # plan provenance (ISSUE 12): one point event per finished sort (or
    # packed serve dispatch) carrying the full decision record —
    # decisions {algo, cap, restage, engine, passes, ladder, batch}
    # with predicted/actual/regret, plus the input-distribution profile
    # (models/plan.py is the registered decision vocabulary, SL005)
    "sort.plan": ("one finished plan record (algo, regret, decisions, "
                  "profile) — report.py --explain and /varz consume it"),
    # store/ — out-of-core external sort (ISSUE 15)
    "external.run": ("one spill run written (run, n, bytes, dtype, "
                     "payload_width) — partition chunk sorted + "
                     "persisted with its fingerprint sidecar"),
    "external.merge": ("one k-way merge pass (runs, n, merge_pass, "
                       "final) — intermediate passes stream into a "
                       "run, the final pass into the caller's sink"),
    "external.recover": ("external-sort integrity recovery point event "
                         "(reason, bad_runs, attempt) — blamed runs "
                         "re-spilled from source before the re-merge"),
    # crash-durable spill tier (ISSUE 18, store/manifest.py)
    "external.resume": ("one spill-manifest replay (dataset, "
                        "committed, valid, skipped_lines) — committed "
                        "runs re-validated and re-entered at the merge "
                        "phase instead of being re-sorted"),
    "external.gc": ("one orphaned-spill sweep (dir, reclaimed, bytes, "
                    "age_s) — files no live manifest references, "
                    "reclaimed age-gated at startup"),
    # models/ingest.py — streamed pipeline stages (ISSUE 2)
    "ingest.parse": "parse/materialize one host chunk",
    "ingest.encode": "codec-encode one chunk (worker pool)",
    "ingest.transfer": "host→device DMA of one chunk's shard pieces",
    "ingest.pipeline": "whole streamed-ingest wall interval",
    "egress.fetch": "device→host fetch of one result shard",
    "egress.decode": "codec-decode one fetched shard",
}

#: Ingest/egress stage split used by the report overlap tables: host-side
#: work vs host↔device transfer, per direction (the span name's prefix).
INGEST_HOST_STAGES = ("ingest.parse", "ingest.encode", "egress.decode")
INGEST_XFER_STAGES = ("ingest.transfer", "egress.fetch")

#: Robustness event names the report's robustness table folds.
FAULT_SPAN = "fault"
RETRY_SPAN = "supervisor_retry"
VERIFY_SPAN = "verify"

#: Scale-out event names the report's scale-out table folds (ISSUE 7).
BALANCE_SPAN = "exchange_balance"
RESTAGE_SPAN = "restage"

#: Sort-as-a-service names the report's SLO table folds (ISSUE 8).
SERVE_REQUEST_SPAN = "serve.request"
SERVE_BATCH_SPAN = "serve.batch"
SERVE_CACHE_SPAN = "serve.compile_cache"
SERVE_PROFILE_SPAN = "serve.profile"

#: Request-lifecycle robustness names (ISSUE 11): deadline expiries,
#: watchdog/breaker transitions, client-side hedges.
SERVE_DEADLINE_SPAN = "serve.deadline"
SERVE_WATCHDOG_SPAN = "serve.watchdog"
SERVE_HEDGE_SPAN = "serve.hedge"

#: Streaming-sentinel name (ISSUE 16): anomaly alerts over rolling
#: windows; rule vocabulary lives in mpitest_tpu/doctor.py.
SERVE_ALERT_SPAN = "serve.alert"

#: Plan-provenance name (ISSUE 12): the decision record report.py
#: --explain renders and the /varz decision snapshot aggregates.
PLAN_SPAN = "sort.plan"

#: Out-of-core external sort names (ISSUE 15).
EXTERNAL_RUN_SPAN = "external.run"
EXTERNAL_MERGE_SPAN = "external.merge"
EXTERNAL_RECOVER_SPAN = "external.recover"

#: Crash-durable spill tier names (ISSUE 18).
EXTERNAL_RESUME_SPAN = "external.resume"
EXTERNAL_GC_SPAN = "external.gc"

#: Request-trace attributes (ISSUE 10): the wire layer mints one
#: ``trace_id`` per request (echoed in the response) and the dispatch
#: thread opens a ``spans.trace_context`` carrying it, so EVERY span a
#: request touches — admission, batching, the ``sort`` umbrella and its
#: phases, supervisor retries, fault events, verification — is stamped
#: with the same id; packed dispatches additionally stamp the shared
#: ``batch_id`` (and ``serve.batch`` lists every member's trace id
#: under ``trace_ids``).  ``report.py --trace-id`` reconstructs one
#: request end-to-end from exactly these attrs.
TRACE_ID_ATTR = "trace_id"
BATCH_ID_ATTR = "batch_id"
BATCH_TRACE_IDS_ATTR = "trace_ids"


def is_registered(name: str) -> bool:
    """True iff ``name`` is a registered span name (exact, or a
    ``phase:`` span over a registered phase)."""
    if name in SPAN_NAMES:
        return True
    return (name.startswith(PHASE_PREFIX)
            and name[len(PHASE_PREFIX):] in PHASE_NAMES)


def all_names() -> tuple[str, ...]:
    """Every registered name, phases expanded — for docs and tests."""
    return tuple(sorted(SPAN_NAMES)) + tuple(
        sorted(PHASE_PREFIX + p for p in PHASE_NAMES))
