"""Rank-resolved timeline reconstruction over the span stream (ISSUE 16).

The span layer records what the HOST saw: one driver lane of nested
spans, plus byte-exact per-rank accounting on the ``exchange_balance``
events and worker-thread ingest/egress/disk intervals.  Nothing put
those back together: the Chrome export flattened every rank onto one
tid, and "which rank straggled / which phase is the critical path /
did compute actually overlap the DMA" required hand-correlating raw
JSONL.  This module is that fold, computed once and consumed three
ways:

* :func:`build_timeline` — the full reconstruction: estimated per-rank
  activity lanes (pass wall time distributed over ranks in proportion
  to their exchanged bytes — the one per-rank observable the SPMD
  model exposes), per-pass straggler factors (max/median rank time),
  critical-path phase attribution, and compute/DMA/disk overlap
  fractions on the shared interval math of ``utils/spans.py``.
* :func:`bench_fold` — the two trajectory scalars bench rows carry
  (``straggler_factor``, ``critical_path_phase``).
* :func:`chrome_events` — the Perfetto enrichment: one track per rank
  (stable tid), a disk-IO track, and counter tracks for inflight DMA
  bytes and exchange-capacity regrowth, appended to
  ``SpanLog.to_chrome_trace``'s host lane.

Lanes are *estimates* and say so (``"estimated": true`` on every
derived event): collectives execute inside one fused XLA program, so
per-rank wall time is not host-observable — but per-rank bytes are
exact, and time-proportional-to-bytes is precisely the model the
capacity negotiation already plans with.

Input is duck-typed: span dicts (``report.py`` rows, flight-recorder
snapshots) or live :class:`~mpitest_tpu.utils.spans.Span` objects
(bench folds a run's tracer directly) — anything with ``name/t0/dt/
attrs`` (+ optional ``id/parent/pid``).
"""

from __future__ import annotations

from typing import Any

from mpitest_tpu.utils.spans import merge_intervals, overlap_seconds

#: Stable Perfetto tid layout: host driver on tid 1 (the historical
#: lane), disk IO on 900, rank R on 1000+R — ranks render side by side
#: instead of interleaved on the host lane (the ISSUE 16 satellite fix).
HOST_TID = 1
DISK_TID = 900
RANK_TID_BASE = 1000

#: Span names folded into each activity class (registered names —
#: utils/span_schema.py; consumed by string match like report.py).
COMPUTE_SPANS = ("jit_compile_execute", "jit_execute")
DMA_SPANS = ("ingest.transfer", "egress.fetch")
DISK_SPANS = ("external.run", "external.merge")
BALANCE_SPAN = "exchange_balance"
PLAN_SPAN = "sort.plan"
PHASE_PREFIX = "phase:"


def _as_dict(s: Any) -> dict:
    """Span object or dict -> plain dict (no copy when already one)."""
    if isinstance(s, dict):
        return s
    return {"name": getattr(s, "name", "?"), "id": getattr(s, "id", None),
            "parent": getattr(s, "parent", None),
            "t0": float(getattr(s, "t0", 0.0)),
            "dt": float(getattr(s, "dt", 0.0) or 0.0),
            "attrs": getattr(s, "attrs", None) or {}}


def _rank_bytes(attrs: dict) -> list[float] | None:
    """Per-rank byte list of one balance event (recv preferred — the
    receive side is what a straggler waits on), tolerant of ragged /
    partially-missing lists: non-numeric entries are dropped, and a
    list with fewer than 2 usable ranks carries no imbalance signal."""
    for key in ("recv_bytes", "send_bytes"):
        raw = attrs.get(key)
        if isinstance(raw, (list, tuple)):
            vals = []
            for v in raw:
                try:
                    vals.append(float(v))
                except (TypeError, ValueError):
                    continue
            if len(vals) >= 2:
                return vals
    return None


def straggler_stats(rank_bytes: list[float]) -> dict[str, float] | None:
    """max/median straggler factor of one per-rank byte list.  Under
    the bytes-proportional time model, the byte ratio IS the time
    ratio.  Median 0 (most ranks idle) falls back to the mean; an
    all-zero list has no signal and returns None."""
    vals = sorted(v for v in rank_bytes if v >= 0)
    if len(vals) < 2 or vals[-1] <= 0:
        return None
    mid = len(vals) // 2
    median = (vals[mid] if len(vals) % 2
              else (vals[mid - 1] + vals[mid]) / 2.0)
    base = median if median > 0 else sum(vals) / len(vals)
    if base <= 0:
        return None
    return {"factor": round(vals[-1] / base, 4),
            "max": vals[-1], "median": median}


def _anchor(span: dict, by_id: dict[tuple, dict]) -> dict | None:
    """Nearest ancestor with real wall time (dt > 0) — the duration
    budget a point event's rank lanes are scaled into."""
    seen = 0
    cur: dict | None = span
    while cur is not None and seen < 64:
        if float(cur.get("dt", 0.0) or 0.0) > 0:
            return cur
        parent = cur.get("parent")
        if parent is None:
            return None
        cur = by_id.get((cur.get("pid"), parent))
        seen += 1
    return None


def build_timeline(spans: list[Any]) -> dict[str, Any]:
    """Fold a span stream into the rank-resolved timeline.

    Returns::

        {"passes":   [{seq, t0, dt, straggler, ranks, algorithm,
                       rank_bytes, anchor}],
         "lanes":    {rank: [{t0, dt, bytes, seq, estimated}]},
         "ranks":    sorted rank ids with a lane,
         "straggler_factor":   worst per-pass max/median (None = no
                               balance data),
         "phases":   {phase: wall seconds},
         "critical_path_phase": dominant phase (None = no phase spans),
         "overlap":  {compute_s, dma_s, disk_s, compute_dma_pct,
                      compute_disk_pct, spill_disk_overlap},
         "counters": {"inflight_bytes": [(t, value)],
                      "exchange_cap":   [(t, cap)],
                      "cap_regrows":    [(t, cumulative)]}}

    Missing inputs degrade to empty/None fields, never raise — the
    fold runs on partial traces (flight-recorder rings, single-request
    slices) by design.
    """
    rows = [_as_dict(s) for s in spans]
    by_id: dict[tuple, dict] = {}
    for r in rows:
        if r.get("id") is not None:
            by_id[(r.get("pid"), r["id"])] = r

    phases: dict[str, float] = {}
    spill_overlap: float | None = None
    passes: list[dict] = []
    lanes: dict[int, list[dict]] = {}
    comp_iv: dict[Any, list] = {}
    dma_iv: dict[Any, list] = {}
    disk_iv: dict[Any, list] = {}
    inflight: list[tuple[float, float]] = []   # (t, delta bytes)
    cap_series: list[tuple[float, float]] = []
    regrow_series: list[tuple[float, float]] = []
    regrow_total = 0.0

    for r in rows:
        name = str(r.get("name", "?"))
        t0 = float(r.get("t0", 0.0) or 0.0)
        dt = float(r.get("dt", 0.0) or 0.0)
        attrs = r.get("attrs") or {}
        pid = r.get("pid")
        if name.startswith(PHASE_PREFIX):
            phase = name[len(PHASE_PREFIX):]
            phases[phase] = phases.get(phase, 0.0) + dt
        if name in COMPUTE_SPANS and dt > 0:
            comp_iv.setdefault(pid, []).append((t0, t0 + dt))
        elif name in DMA_SPANS and dt > 0:
            dma_iv.setdefault(pid, []).append((t0, t0 + dt))
            nbytes = attrs.get("bytes")
            if isinstance(nbytes, (int, float)) and nbytes > 0:
                inflight.append((t0, float(nbytes)))
                inflight.append((t0 + dt, -float(nbytes)))
        elif name in DISK_SPANS and dt > 0:
            disk_iv.setdefault(pid, []).append((t0, t0 + dt))
            # the external sort's own measured read-ahead/write-behind
            # concurrency (ISSUE 20) rides the FINAL merge span; older
            # traces simply lack the attr (renders None, never 0)
            if name == "external.merge" and attrs.get("final"):
                ov = attrs.get("disk_overlap")
                if isinstance(ov, (int, float)):
                    spill_overlap = float(ov)
        elif name == BALANCE_SPAN:
            bytes_by_rank = _rank_bytes(attrs)
            stats = (straggler_stats(bytes_by_rank)
                     if bytes_by_rank else None)
            cap = attrs.get("negotiated_cap")
            if isinstance(cap, (int, float)):
                cap_series.append((t0, float(cap)))
            anchor = _anchor(r, by_id)
            entry = {
                "seq": len(passes), "t0": t0, "dt": dt,
                "algorithm": attrs.get("algorithm"),
                "ranks": (len(bytes_by_rank) if bytes_by_rank
                          else attrs.get("ranks")),
                "rank_bytes": bytes_by_rank,
                "straggler": stats["factor"] if stats else None,
                "anchor": anchor.get("name") if anchor else None,
            }
            passes.append(entry)
            if bytes_by_rank and anchor is not None:
                # estimated lane: the anchor's wall time distributed
                # over ranks in proportion to exchanged bytes
                budget = float(anchor.get("dt", 0.0) or 0.0)
                start = float(anchor.get("t0", 0.0) or 0.0)
                peak = max(bytes_by_rank)
                if budget > 0 and peak > 0:
                    for rank, b in enumerate(bytes_by_rank):
                        lanes.setdefault(rank, []).append({
                            "t0": start,
                            "dt": budget * b / peak,
                            "bytes": b, "seq": entry["seq"],
                            "estimated": True,
                        })
        elif name == PLAN_SPAN:
            cap_d = ((attrs.get("decisions") or {}).get("cap")
                     if isinstance(attrs.get("decisions"), dict) else None)
            if isinstance(cap_d, dict):
                regrows = (cap_d.get("actual") or {}).get("regrows")
                if isinstance(regrows, (int, float)) and regrows > 0:
                    regrow_total += float(regrows)
                    regrow_series.append((t0, regrow_total))

    comp_s = dma_s = disk_s = ov_dma = ov_disk = 0.0
    for pid in set(comp_iv) | set(dma_iv) | set(disk_iv):
        cm = merge_intervals(comp_iv.get(pid, []))
        dm = merge_intervals(dma_iv.get(pid, []))
        km = merge_intervals(disk_iv.get(pid, []))
        comp_s += sum(b - a for a, b in cm)
        dma_s += sum(b - a for a, b in dm)
        disk_s += sum(b - a for a, b in km)
        ov_dma += overlap_seconds(cm, dm)
        ov_disk += overlap_seconds(cm, km)

    factors = [p["straggler"] for p in passes if p["straggler"]]
    inflight.sort(key=lambda tv: tv[0])
    level = 0.0
    inflight_series: list[tuple[float, float]] = []
    for t, delta in inflight:
        level += delta
        inflight_series.append((t, max(level, 0.0)))

    critical = max(phases, key=lambda k: phases[k]) if phases else None
    return {
        "passes": passes,
        "lanes": {r: lanes[r] for r in sorted(lanes)},
        "ranks": sorted(lanes),
        "straggler_factor": (round(max(factors), 4) if factors else None),
        "phases": {k: round(v, 9) for k, v in sorted(phases.items())},
        "critical_path_phase": critical,
        "overlap": {
            "compute_s": round(comp_s, 9),
            "dma_s": round(dma_s, 9),
            "disk_s": round(disk_s, 9),
            "compute_dma_pct": (round(100.0 * ov_dma / dma_s, 2)
                                if dma_s > 0 else 0.0),
            "compute_disk_pct": (round(100.0 * ov_disk / disk_s, 2)
                                 if disk_s > 0 else 0.0),
            "spill_disk_overlap": (round(spill_overlap, 4)
                                   if spill_overlap is not None
                                   else None),
        },
        "counters": {"inflight_bytes": inflight_series,
                     "exchange_cap": cap_series,
                     "cap_regrows": regrow_series},
    }


def bench_fold(spans: list[Any]) -> dict[str, Any]:
    """The two trajectory scalars a bench row carries (ISSUE 16
    satellite): worst per-pass straggler factor + the dominant phase.
    Keys are present only when the trace actually carried the signal —
    a missing key renders "-" in tools/bench_history.py, never 0."""
    tl = build_timeline(spans)
    out: dict[str, Any] = {}
    if tl["straggler_factor"] is not None:
        out["straggler_factor"] = tl["straggler_factor"]
    if tl["critical_path_phase"] is not None:
        out["critical_path_phase"] = tl["critical_path_phase"]
    return out


def chrome_events(spans: list[Any]) -> list[dict]:
    """Perfetto enrichment events for ``SpanLog.to_chrome_trace``:
    thread-name metadata + one estimated activity track per rank, a
    disk-IO track, and ``"ph": "C"`` counter tracks (inflight DMA
    bytes, negotiated exchange capacity, cumulative cap regrows)."""
    tl = build_timeline(spans)
    events: list[dict] = []
    for rank in tl["ranks"]:
        tid = RANK_TID_BASE + int(rank)
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid,
                       "args": {"name": f"rank {rank} (estimated)"}})
        for ev in tl["lanes"][rank]:
            if ev["dt"] <= 0:
                continue
            events.append({
                "name": f"exchange pass {ev['seq']}", "ph": "X",
                "pid": 1, "tid": tid, "ts": ev["t0"] * 1e6,
                "dur": ev["dt"] * 1e6,
                "args": {"bytes": ev["bytes"], "estimated": True,
                         "seq": ev["seq"]},
            })
    disk = [(_as_dict(s)) for s in spans
            if str(_as_dict(s).get("name")) in DISK_SPANS]
    if disk:
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": DISK_TID, "args": {"name": "disk io"}})
        for r in disk:
            dt = float(r.get("dt", 0.0) or 0.0)
            if dt <= 0:
                continue
            events.append({
                "name": str(r.get("name")), "ph": "X", "pid": 1,
                "tid": DISK_TID, "ts": float(r.get("t0", 0.0)) * 1e6,
                "dur": dt * 1e6, "args": dict(r.get("attrs") or {}),
            })
    for counter, series, key in (
            ("inflight bytes", tl["counters"]["inflight_bytes"], "bytes"),
            ("exchange cap", tl["counters"]["exchange_cap"], "cap"),
            ("cap regrows", tl["counters"]["cap_regrows"], "regrows")):
        for t, v in series:
            events.append({"name": counter, "ph": "C", "pid": 1,
                           "ts": t * 1e6, "args": {key: v}})
    if events:
        # name the historical host lane only when enrichment tracks
        # exist beside it — a plain trace stays byte-identical
        events.insert(0, {"name": "thread_name", "ph": "M", "pid": 1,
                          "tid": HOST_TID,
                          "args": {"name": "host driver"}})
    return events
