"""Thread-root and lock vocabularies — the concurrency registry (ISSUE 19).

The serving stack is genuinely multi-threaded (dispatch thread,
watchdog, sentinel observers, telemetry HTTP server, ingest/egress
pools, hedging client), and its load-bearing invariants — "only the
dispatch thread touches JAX", "compiles never run under the executor
cache lock on the prewarm path", "locks nest in one global order" —
lived only in docstrings until this registry.  ``tools/threadlint``
loads this module BY FILE PATH (it never imports the package under
lint, same contract as sortlint's registries), walks the call graph of
``mpitest_tpu/``, ``drivers/`` and ``bench/`` from every root declared
here, and enforces those invariants statically in the CI lint job.

Like the knob/span/metric/plan registries, the vocabulary is closed:

* every ``threading.Thread(target=...)``, pool submit target, handler
  entry and signal handler must resolve to a :class:`ThreadRoot` here
  (threadlint TL010 otherwise);
* every ``threading.Lock()`` / ``RLock()`` / ``Condition()`` creation
  site must carry a :class:`LockDecl` with a documented **rank** —
  the global acquisition order TL002 enforces (lower rank acquires
  first; a cycle or an out-of-rank nesting is a finding).

Stdlib-only by design; imports nothing, not even :mod:`threading`.
"""

from __future__ import annotations

from dataclasses import dataclass

#: Valid values for :attr:`ThreadRoot.kind`.
ROOT_KINDS = ("thread", "pool", "handler", "signal", "main")


@dataclass(frozen=True)
class ThreadRoot:
    """One registered thread entry point.

    ``entry`` is the module-qualified function the thread runs (nested
    defs join with dots: ``mpitest_tpu.models.ingest.stream_to_mesh.
    parse_chunks``).  ``jax_ok`` declares whether code reachable from
    this root may touch the JAX/XLA surface — the thread-ownership
    fence TL001 enforces.  Granting it is a REVIEWED act: the doc must
    say why the root is allowed on the device path."""

    name: str
    kind: str
    entry: str
    jax_ok: bool
    doc: str


@dataclass(frozen=True)
class LockDecl:
    """One registered lock instance site.

    ``site`` qualifies where the lock object lives (``module.Class.
    attr`` for instance/class locks, ``module.NAME`` for module
    globals, ``module.func.name`` for function locals).  ``rank`` is
    the position in the ONE global acquisition order: holding a lock
    while acquiring another is only legal when the second lock's rank
    is strictly greater (TL002).  ``reentrant`` marks RLocks, whose
    self-reacquisition is legal."""

    name: str
    rank: int
    site: str
    doc: str
    reentrant: bool = False


# ---------------------------------------------------------------- roots
#
# jax_ok=True is the short, audited list: the dispatch loop (the ONE
# thread the serve layer lets at the device), the tuner's background
# prewarm (deliberate warm compile; XLA releases the GIL), the ingest
# transfer/egress fetch stages (device DMA is their whole job), and the
# process main thread.

THREAD_ROOTS: tuple[ThreadRoot, ...] = (
    # -- serve layer --------------------------------------------------
    ThreadRoot(
        "serve-dispatch", "thread",
        "mpitest_tpu.serve.batching.Batcher._loop", True,
        "THE dispatch thread — the only serve thread allowed to touch "
        "JAX: executors, segmented dispatch, executor-cache lookups "
        "and the profiler hook all run here (ISSUE 8)."),
    ThreadRoot(
        "serve-watchdog", "thread",
        "mpitest_tpu.serve.watchdog.DispatchWatchdog._loop", False,
        "Ages the dispatch heartbeat and trips the breaker; its "
        "half-open probe goes THROUGH batcher.submit (the dispatch "
        "thread runs the actual sort), so the watchdog itself never "
        "touches the device (ISSUE 11)."),
    ThreadRoot(
        "serve-accept", "thread",
        "mpitest_tpu.serve.server.SortServer.serve_forever", False,
        "socketserver accept loop (stdlib body); per-connection work "
        "runs on the serve-wire-handler root."),
    ThreadRoot(
        "serve-wire-handler", "handler",
        "mpitest_tpu.serve.server._Handler.handle", False,
        "Per-connection wire handler (ThreadingTCPServer): parses, "
        "admits and ENQUEUES requests, then waits on the completion "
        "event — results are produced by the dispatch thread."),
    ThreadRoot(
        "serve-telemetry", "thread",
        "mpitest_tpu.serve.telemetry.TelemetryServer.serve_forever",
        False,
        "Telemetry side-port accept loop (stdlib body); scrapes run on "
        "the telemetry-http-handler root."),
    ThreadRoot(
        "telemetry-http-handler", "handler",
        "mpitest_tpu.serve.telemetry._Handler.do_GET", False,
        "/metrics /healthz /varz /flightrecorder /profile scrapes: "
        "read-only snapshots of core state; arming a profile capture "
        "flips a flag under the hook lock — jax.profiler itself runs "
        "on the dispatch thread (ISSUE 10)."),
    ThreadRoot(
        "serve-tuner-prewarm", "thread",
        "mpitest_tpu.serve.server.ServerCore._tuner_observe._prewarm",
        True,
        "The serve tuner's background warm compile (ISSUE 14): "
        "deliberately builds packed executables OFF the dispatch "
        "thread via _build_detached (compile outside the cache lock); "
        "XLA compiles release the GIL."),
    ThreadRoot(
        "client-hedge", "thread",
        "mpitest_tpu.serve.client.ResilientClient._hedged.attempt",
        False,
        "Hedged request attempt (primary and hedge legs share the "
        "entry): pure wire I/O against the server socket (ISSUE 11)."),
    # -- ingest/egress pipeline ---------------------------------------
    ThreadRoot(
        "ingest-parse", "thread",
        "mpitest_tpu.models.ingest.stream_to_mesh.parse_chunks", False,
        "Streamed-ingest producer: reads and splits the input file "
        "into bounded queue chunks; host-side bytes only (ISSUE 6)."),
    ThreadRoot(
        "ingest-enc", "pool",
        "mpitest_tpu.models.ingest.stream_to_mesh.encode_one", False,
        "Encode workers: numpy/native codec folds on host chunks; the "
        "device transfer belongs to ingest-xfer."),
    ThreadRoot(
        "ingest-xfer", "pool",
        "mpitest_tpu.models.ingest.stream_to_mesh.transfer_one", True,
        "The ONE transfer thread: checked_device_put + "
        "block_until_ready per chunk — device DMA is its whole job, "
        "serialized so chunk k+1's encode overlaps chunk k's DMA."),
    ThreadRoot(
        "egress-fetch", "pool",
        "mpitest_tpu.models.ingest.stream_result_to_numpy.fetch", True,
        "Egress prefetch: pulls device shard k+1 to host while the "
        "driver decodes shard k — reads device buffers by design."),
    ThreadRoot(
        "io-parse", "pool",
        "mpitest_tpu.utils.io._parse_text_block", False,
        "Text-ingest parse workers (iter_key_chunks): numpy/native "
        "parsing of file blocks; no device access."),
    # -- external-sort async spill IO (ISSUE 20) ----------------------
    ThreadRoot(
        "spill-readahead", "thread",
        "mpitest_tpu.store.aio.ReadAhead._worker", False,
        "Per-run merge read-ahead: reads + decodes the NEXT spill "
        "chunk (disk read, block decompression) while the merge "
        "consumes the current one; host bytes/numpy only — the merge "
        "loop owns any device work."),
    ThreadRoot(
        "spill-writebehind", "thread",
        "mpitest_tpu.store.aio.WriteBehind._worker", False,
        "Merge write-behind: drains output chunks into the "
        "RunStreamWriter (encode, compress, throttle, write) behind "
        "the emit loop; errors re-raise at the caller's next append."),
    # -- driver signals -----------------------------------------------
    ThreadRoot(
        "signal-drain", "signal",
        "drivers.sort_server.main.on_signal", False,
        "SIGTERM/SIGINT: flips admission to draining and sets the stop "
        "event; never touches the device."),
    ThreadRoot(
        "signal-flight-dump", "signal",
        "drivers.sort_server.main.on_sigquit", False,
        "SIGQUIT: dumps the flight-recorder ring WITHOUT shutting "
        "down (the operator's 3am incident snapshot)."),
    ThreadRoot(
        "server-main", "main",
        "drivers.sort_server.main", True,
        "The server process main thread: startup prewarm (behind the "
        "bounded topology probe), then parks on the stop event."),
    # -- bench/ load generators & selftests ---------------------------
    ThreadRoot(
        "chaos-accept", "thread",
        "bench.wire_chaos.ChaosProxy._accept_loop", False,
        "Chaos proxy accept loop (wire-level fault injection)."),
    ThreadRoot(
        "chaos-conn", "thread",
        "bench.wire_chaos.ChaosProxy._serve_conn", False,
        "Per-connection chaos pipe (downstream leg)."),
    ThreadRoot(
        "chaos-pipe-up", "thread",
        "bench.wire_chaos.ChaosProxy._pipe_up", False,
        "Per-connection chaos pipe (upstream leg)."),
    ThreadRoot(
        "load-worker", "thread",
        "bench.serve_load.run_load.worker", False,
        "Load-generator worker: hammers the wire protocol."),
    ThreadRoot(
        "telemetry-selftest-worker", "thread",
        "bench.telemetry_live_selftest.run.worker", False,
        "Telemetry selftest load worker."),
    ThreadRoot(
        "durability-victim", "thread",
        "bench.durability_selftest.main.send_victim", False,
        "Durability selftest: the request the kill drill strands."),
    ThreadRoot(
        "chaos-stalled-request", "thread",
        "bench.chaos_serve_selftest.watchdog_cell.stalled_request",
        False,
        "Chaos selftest: the deliberately wedged request that trips "
        "the watchdog."),
)


# ---------------------------------------------------------------- locks
#
# ONE global acquisition order.  Ranks are spaced by 5 so a future lock
# slots in without renumbering; the order encodes today's real nesting
# edges (admission -> metrics via the on_change publish; sentinel ->
# metrics via alert counters; spans.log -> spans.flush in _flush) plus
# a sensible default for locks that never nest.

LOCKS: tuple[LockDecl, ...] = (
    LockDecl("batcher.pending", 10,
             "mpitest_tpu.serve.batching.Batcher._pending_lock",
             "Guards the incompatible-requests set-aside list."),
    LockDecl("breaker.state", 15,
             "mpitest_tpu.serve.watchdog.CircuitBreaker._lock",
             "All breaker state transitions; leaf in practice."),
    LockDecl("admission.state", 20,
             "mpitest_tpu.serve.admission.AdmissionControl._lock",
             "Admission byte/inflight accounting; the on_change "
             "publish fires under it, so it ranks BELOW the metrics "
             "registry lock it reaches."),
    LockDecl("sentinel.series", 25,
             "mpitest_tpu.serve.sentinel.SortSentinel._lock",
             "Rolling alert series + cooldowns; written from every "
             "span-closing thread via the observer hook."),
    LockDecl("cache.entries", 30,
             "mpitest_tpu.serve.executor_cache.ExecutorCache._lock",
             "Executor-cache entries/stats.  get_packed compiles "
             "under it by documented choice (cold-key dogpile); "
             "_build_detached is the compile-outside-the-lock path "
             "TL003 enforces for the prewarm side."),
    LockDecl("tuner.series", 35,
             "mpitest_tpu.models.planner.ServeTuner._lock",
             "Tuner observation deques + retune bookkeeping."),
    LockDecl("batcher.heartbeat", 40,
             "mpitest_tpu.serve.batching.Batcher._hb_lock",
             "Dispatch heartbeat cell — set/cleared around every "
             "executor call; aged by the watchdog."),
    LockDecl("spans.log", 45,
             "mpitest_tpu.utils.spans.SpanLog._lock",
             "Span id allocation/retention/stacks; observers run "
             "AFTER release (flush holds no log lock)."),
    LockDecl("spans.flush", 50,
             "mpitest_tpu.utils.spans.SpanLog._flush_lock",
             "Serializes JSONL stream appends across threads."),
    LockDecl("flight.ring", 55,
             "mpitest_tpu.utils.flight_recorder.FlightRecorder._lock",
             "Flight-recorder ring; reentrant because dump() "
             "snapshots while holding it.", reentrant=True),
    LockDecl("flight.singleton", 60,
             "mpitest_tpu.utils.flight_recorder._SINGLETON_LOCK",
             "Double-checked init of the process flight recorder."),
    LockDecl("server.tally", 65,
             "mpitest_tpu.serve.server.ServerCore._tally_lock",
             "requests_ok/requests_err counters (leaf)."),
    LockDecl("server.inflight", 70,
             "mpitest_tpu.serve.server.ServerCore._inflight_lock",
             "The in-flight request map for stuck_trace_ids (leaf)."),
    LockDecl("profile.hook", 75,
             "mpitest_tpu.serve.telemetry.ProfileHook._lock",
             "Profile-capture arm/disarm state; the jax.profiler "
             "calls themselves run OUTSIDE it on the dispatch "
             "thread."),
    LockDecl("faults.registry", 80,
             "mpitest_tpu.faults.FaultRegistry._lock",
             "Fault budgets/rng — ingest workers poll concurrently."),
    LockDecl("probe.verdict", 82,
             "mpitest_tpu.utils.topology_probe._PROBE_LOCK",
             "Serializes the bounded topology subprocess probe and "
             "guards its cached verdict (TL004: written from main "
             "prewarm AND the tuner prewarm thread)."),
    LockDecl("compress.load", 83,
             "mpitest_tpu.store.compress._LOAD_LOCK",
             "One-time spill-compression library resolution (same "
             "double-checked shim shape as native.load)."),
    LockDecl("runs.throttle", 84,
             "mpitest_tpu.store.runs._THROTTLE_LOCK",
             "The shared spill-disk token bucket "
             "(SORT_SPILL_THROTTLE_MBPS): one bucket = one simulated "
             "disk across every reader/writer thread; the sleep "
             "happens OUTSIDE it (TL003)."),
    LockDecl("native.load", 85,
             "mpitest_tpu.utils.native_encode._LOAD_LOCK",
             "One-time native-library resolution."),
    LockDecl("aio.readahead", 86,
             "mpitest_tpu.store.aio.ReadAhead._lock",
             "Read-ahead IO/stall interval stats — appended from the "
             "worker AND the consuming merge thread (leaf)."),
    LockDecl("aio.writebehind", 87,
             "mpitest_tpu.store.aio.WriteBehind._lock",
             "Write-behind interval stats + the parked worker error "
             "re-raised at the caller's next append/close (leaf)."),
    LockDecl("ingest.stream", 88,
             "mpitest_tpu.models.ingest._StreamState.lock",
             "Streamed-ingest shared fold/stats state."),
    LockDecl("metrics.registry", 90,
             "mpitest_tpu.utils.metrics_live.LiveMetrics._lock",
             "The live metric registry + every series update; ranks "
             "ABOVE admission/sentinel which update metrics under "
             "their own locks (leaf — holds no other lock)."),
    LockDecl("client.stats", 95,
             "mpitest_tpu.serve.client.ResilientClient._stats_lock",
             "Client attempt/hedge accounting (leaf)."),
    # bench/ locals
    LockDecl("bench.chaos", 100, "bench.wire_chaos.ChaosProxy._lock",
             "Chaos proxy connection/fault bookkeeping."),
    LockDecl("bench.load", 101, "bench.serve_load.run_load.lock",
             "Load-generator latency accumulators."),
    LockDecl("bench.telemetry-selftest", 102,
             "bench.telemetry_live_selftest.run.lock",
             "Telemetry selftest latency accumulators."),
)

#: Lock objects reached through a second name: the admission Condition
#: wraps the admission lock (``with self._idle`` acquires ``_lock``),
#: and Metric handles borrow the registry lock at construction.
LOCK_ALIASES: dict[str, str] = {
    "mpitest_tpu.serve.admission.AdmissionControl._idle":
        "mpitest_tpu.serve.admission.AdmissionControl._lock",
    "mpitest_tpu.utils.metrics_live.Metric._lock":
        "mpitest_tpu.utils.metrics_live.LiveMetrics._lock",
}


# ------------------------------------------------- call-graph alias maps
#
# The analyzer resolves ``self.x.m()`` chains through these explicit
# tables (ISSUE 19: "receiver-type heuristics + an explicit alias
# table") — attribute -> class for object fields, attribute -> callees
# for constructor-injected callbacks, function -> class for factory
# returns, and caller -> callees for dynamic observer fan-out.

#: ``module.Class.attr`` -> class qualname of the object stored there.
RECEIVER_TYPES: dict[str, str] = {
    "mpitest_tpu.serve.server.ServerCore.batcher":
        "mpitest_tpu.serve.batching.Batcher",
    "mpitest_tpu.serve.server.ServerCore.cache":
        "mpitest_tpu.serve.executor_cache.ExecutorCache",
    "mpitest_tpu.serve.server.ServerCore.admission":
        "mpitest_tpu.serve.admission.AdmissionControl",
    "mpitest_tpu.serve.server.ServerCore.breaker":
        "mpitest_tpu.serve.watchdog.CircuitBreaker",
    "mpitest_tpu.serve.server.ServerCore.metrics":
        "mpitest_tpu.utils.metrics_live.LiveMetrics",
    "mpitest_tpu.serve.server.ServerCore.sentinel":
        "mpitest_tpu.serve.sentinel.SortSentinel",
    "mpitest_tpu.serve.server.ServerCore.tuner":
        "mpitest_tpu.models.planner.ServeTuner",
    "mpitest_tpu.serve.server.ServerCore.profile_hook":
        "mpitest_tpu.serve.telemetry.ProfileHook",
    "mpitest_tpu.serve.server.SortServer.core":
        "mpitest_tpu.serve.server.ServerCore",
    "mpitest_tpu.serve.server._Handler.server":
        "mpitest_tpu.serve.server.SortServer",
    "mpitest_tpu.serve.watchdog.DispatchWatchdog.core":
        "mpitest_tpu.serve.server.ServerCore",
    "mpitest_tpu.serve.watchdog.DispatchWatchdog.breaker":
        "mpitest_tpu.serve.watchdog.CircuitBreaker",
    "mpitest_tpu.serve.telemetry.TelemetryServer.core":
        "mpitest_tpu.serve.server.ServerCore",
    "mpitest_tpu.serve.telemetry._Handler.server":
        "mpitest_tpu.serve.telemetry.TelemetryServer",
    "mpitest_tpu.serve.sentinel.SortSentinel.spans":
        "mpitest_tpu.utils.spans.SpanLog",
    "mpitest_tpu.serve.sentinel.SortSentinel.metrics":
        "mpitest_tpu.utils.metrics_live.LiveMetrics",
    "mpitest_tpu.serve.executor_cache.ExecutorCache.spans":
        "mpitest_tpu.utils.spans.SpanLog",
}

#: Constructor-injected callbacks: calling ``<site>(...)`` runs these.
ATTR_CALLS: dict[str, tuple[str, ...]] = {
    # Batcher's executors are ServerCore methods handed to __init__
    "mpitest_tpu.serve.batching.Batcher.run_batch":
        ("mpitest_tpu.serve.server.ServerCore._run_batch",),
    "mpitest_tpu.serve.batching.Batcher.run_solo":
        ("mpitest_tpu.serve.server.ServerCore._run_solo",),
    # admission change observer -> the server's gauge publish
    "mpitest_tpu.serve.admission.AdmissionControl.on_change":
        ("mpitest_tpu.serve.server.ServerCore._publish_admission",),
}

#: Factory functions -> class qualname of the returned object.
RETURN_TYPES: dict[str, str] = {
    "mpitest_tpu.utils.flight_recorder.get":
        "mpitest_tpu.utils.flight_recorder.FlightRecorder",
}

#: Dynamic fan-out the AST cannot see: span close runs the registered
#: observers (the metrics bridge and the sentinel) on WHATEVER thread
#: closed the span — this edge is what makes the sentinel's state
#: multi-root and the TL004 lockset check on it meaningful.
EXTRA_EDGES: dict[str, tuple[str, ...]] = {
    "mpitest_tpu.utils.spans.SpanLog._flush":
        ("mpitest_tpu.serve.sentinel.SortSentinel.__call__",),
}


# ------------------------------------------------------ call surfaces

#: Attribute-chain heads that mean "the JAX surface" (TL001): any
#: ``jax.*`` / ``jnp.*`` call.
JAX_SURFACE_HEADS: tuple[str, ...] = ("jax", "jnp")

#: Call names (bare or attribute tail) that mean the JAX surface even
#: without a ``jax.`` head: the device-put guard, device syncs, the
#: executor-cache hot path, and the packed-sort compiler.
JAX_SURFACE_CALLS: tuple[str, ...] = (
    "device_put", "checked_device_put", "block_until_ready",
    "get_packed", "compile_packed_sort",
)

#: Blocking calls TL003 refuses under any registered lock, with the
#: label findings carry.  Names are matched as dotted chains
#: (``os.fsync``) or attribute tails (``.sendall``).
BLOCKING_CALLS: dict[str, str] = {
    "os.fsync": "fsync",
    "time.sleep": "sleep",
    "sleep": "sleep",
    "subprocess.run": "subprocess",
    "subprocess.Popen": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.call": "subprocess",
    ".sendall": "socket send",
    ".recv": "socket recv",
    ".recv_into": "socket recv",
    ".accept": "socket accept",
    ".connect": "socket connect",
    "jax.jit": "XLA compile",
}

#: Repo functions that perform an XLA compile (TL003's compile leg
#: resolves calls interprocedurally to these).
COMPILE_FUNCS: tuple[str, ...] = (
    "mpitest_tpu.models.segmented.compile_packed_sort",
)

#: Calls that can block FOREVER while holding the GIL (TL005): an
#: in-process watchdog can never fire on them, so every use must ride
#: the bounded-subprocess probe.  ``get_topology_desc`` loops inside
#: one C call when the TPU-compiler tunnel is unreachable (PR 5).
GIL_WEDGE_CALLS: tuple[str, ...] = ("get_topology_desc",)

#: The module allowed to (indirectly) own GIL-wedge calls: the probe
#: runs them in a killable child process.
GIL_WEDGE_HOME: tuple[str, ...] = ("mpitest_tpu/utils/topology_probe.py",)

#: Attribute sites whose unlocked multi-root writes are DOCUMENTED
#: GIL-atomic single-reference swaps (TL004 exemptions need the same
#: review a jax_ok grant does).
ATOMIC_OK: tuple[str, ...] = (
    # live window resize: one float swap, re-read at every pack open
    "mpitest_tpu.serve.batching.Batcher.window_s",
    "mpitest_tpu.serve.batching.Batcher.window_retunes",
    # lazy flight-recorder hook bind: every racing writer stores the
    # SAME function object (idempotent single-reference swap), and the
    # hot flush path must not pay a lock for it
    "mpitest_tpu.utils.spans._flight_record",
)
