"""Bounded TPU-compiler reachability probe (subprocess, killable).

Why a subprocess: on images where the TPU compiler rides a network
tunnel, ``jax.experimental.topologies.get_topology_desc`` BLOCKS FOREVER
at ~0% CPU **while holding the GIL** when the tunnel is unreachable (the
libtpu metadata fetch loops inside one C call).  An in-process watchdog
thread can never fire — ``join()`` never returns — so anything that
calls it unguarded wedges until an external timeout kills the whole
process.  Tier-1 used to wedge exactly here (PR 4 caution; fixed in
PR 5 for the AOT tests), and a sort *server* that AOT-prewarms its
executable cache at startup would wedge the same way before accepting
its first request.

The probe therefore runs ONE throwaway ``get_topology_desc`` in a child
process that a timeout can always kill, and caches the verdict for the
process lifetime.  Both consumers share it:

* ``tests/test_aot_topology.py`` — skip the AOT-compile tests (instead
  of wedging tier-1) when the tunnel is unreachable;
* ``mpitest_tpu/serve/executor_cache.py`` — degrade server startup to
  jit-on-first-use (instead of wedging before the first request) when
  prewarming on a TPU backend whose compiler path does not answer.

A reachable tunnel answers in low seconds; the 45 s budget is
comfortably past any healthy handshake.
"""

from __future__ import annotations

import subprocess
import sys
import threading

#: Bounded connect-probe budget (seconds) — see module docstring.
PROBE_TIMEOUT_S = 45.0

#: Serializes the probe and guards ``_verdict`` — threadlint TL004:
#: the verdict is written from the startup prewarm (main thread) AND
#: the tuner's background prewarm thread.
_PROBE_LOCK = threading.Lock()

#: Topology the throwaway fetch asks for; any valid name works (the
#: probe tests reachability, not the shape).
_PROBE_TOPOLOGY = "v5e:2x4"

#: Cached verdict: None = not yet run, "" = compiler path reachable,
#: anything else = the human-readable reason it is not.
_verdict: str | None = None


def probe_tpu_compiler(timeout_s: float = PROBE_TIMEOUT_S) -> str:
    """Run one throwaway ``get_topology_desc`` in a killable child
    process.  Returns ``""`` when the TPU-compiler path is usable, else
    the reason callers should skip/degrade.  Runs at most once per
    process; the verdict is cached (call :func:`reset_cache` to force a
    re-probe)."""
    global _verdict
    with _PROBE_LOCK:
        if _verdict is not None:
            return _verdict
        code = ("from jax.experimental import topologies; "
                "topologies.get_topology_desc(platform='tpu', "
                f"topology_name='{_PROBE_TOPOLOGY}')")
        try:
            # serializing concurrent probes under the lock is the point
            # (one child process, one cached verdict for everyone), and
            # the child is timeout-bounded so the lock hold is too
            # threadlint: disable=TL003 -- bounded one-shot probe, held deliberately
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _verdict = (f"TPU topology probe timed out after "
                        f"{timeout_s:.0f}s (compiler tunnel "
                        "unreachable); AOT compiles skipped, not wedged")
            return _verdict
        if r.returncode != 0:
            tail = (r.stderr.strip().splitlines()
                    or ["no error output"])[-1]
            _verdict = f"TPU topology AOT unavailable: {tail[:200]}"
            return _verdict
        _verdict = ""
        return _verdict


def reset_cache() -> None:
    """Drop the cached verdict (tests)."""
    global _verdict
    with _PROBE_LOCK:
        _verdict = None
