"""Shared runner for the native sort binaries' timer contract.

Both benchmark drivers (bench.py's north-star denominator and
bench/run_baselines.py's reference rows) execute a native binary and
scrape its stderr timer line (``Endtime()-Starttime() = %.5f sec``,
native/sort_common.h print_result).  One copy of the invocation + regex
lives here so the contract cannot drift between them.
"""

from __future__ import annotations

import os
import re
import subprocess

TIMER_RE = re.compile(r"Endtime\(\)-Starttime\(\) = ([0-9.]+) sec")


def run_native_sort(binary, path, ranks: int, timeout: int = 3600,
                    debug: int = 0):
    """Run a native sort binary (local backend, ``ranks`` pthread ranks)
    on key file ``path``.

    Returns ``(elapsed_seconds, None)`` on success — the binary's OWN
    timer span (after-read through final gather, the reference contract)
    — or ``(None, error_message)`` on any failure; never raises.
    """
    argv = [str(binary), str(path)] + ([str(debug)] if debug else [])
    try:
        r = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, COMM_RANKS=str(ranks)),
        )
    except (OSError, subprocess.SubprocessError) as e:
        return None, f"{type(e).__name__}: {e}"
    if r.returncode != 0:
        return None, (r.stderr.strip() or "nonzero exit")[-300:]
    m = TIMER_RE.search(r.stderr)
    if not m:
        return None, "no timer line in stderr"
    return float(m.group(1)), None
