"""Virtual CPU-mesh provisioning — the one copy of an order-sensitive
recipe.

This image's sitecustomize pins an experimental TPU platform, and both
the host-platform device-count flag and the platform pin only take
effect BEFORE the first JAX backend query.  Every entry point that needs
an N-device virtual CPU mesh (tests, the driver's multichip dryrun,
TPU-less bench runs) must therefore apply the same two settings in the
same window — this helper is that recipe, with the guards the inline
copies lacked: it never re-appends the flag, never silently hijacks a
process that already initialized a real backend, and is idempotent.
"""

from __future__ import annotations

import os

_provisioned: int | None = None


def host_fingerprint() -> str:
    """Provenance identifier for host-dependent pinned numbers (ADVICE
    round 5): CPU model + core count — what actually determines the
    native backend's throughput.  Hostnames are useless here (container
    names are random); a CPU fingerprint survives container rebuilds on
    the same machine class and differs where the numbers would differ.
    Shared by bench.py (CANONICAL_NATIVE_MKEYS gate) and the report
    CLI's baseline comparison."""
    fields = {}
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if ":" not in line:
                    continue
                k, v = line.split(":", 1)
                k, v = k.strip(), v.strip()
                # first processor block only; VMs often report model
                # name "unknown", so keep vendor/family/model numbers too
                if k in ("vendor_id", "cpu family", "model", "model name"
                         ) and k not in fields:
                    fields[k] = v
    except OSError:
        pass
    name = fields.get("model name", "")
    if name and name != "unknown":
        cpu = name
    elif fields:
        cpu = "-".join(filter(None, (fields.get("vendor_id"),
                                     fields.get("cpu family"),
                                     fields.get("model"))))
    else:
        import platform as _platform

        cpu = _platform.processor() or _platform.machine() or "unknown-cpu"
    return f"{cpu}/{os.cpu_count()}c"


def _backend_initialized() -> bool:
    # jax.devices() would *create* the backend; peek at the registry
    # instead (private, but the only non-initializing probe there is —
    # pinned-version image, exercised by tests).
    from jax._src import xla_bridge

    return bool(xla_bridge._backends)


def ensure_virtual_cpu_devices(n_devices: int) -> None:
    """Pin this process to an ``n_devices``-device virtual CPU platform.

    Must be called before the first backend query.  If JAX was already
    initialized: a no-op when enough devices exist (or this helper
    already provisioned at least as many), otherwise an actionable
    error — never a silent platform hijack of a live TPU process.
    """
    global _provisioned
    import jax

    if _provisioned is not None or _backend_initialized():
        if (_provisioned or 0) >= n_devices or len(jax.devices()) >= n_devices:
            return
        raise RuntimeError(
            f"need {n_devices} devices but JAX is already initialized "
            f"({_provisioned or len(jax.devices())} available); call "
            "ensure_virtual_cpu_devices() before any backend query, or "
            "run in a fresh process with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
        )
    from mpitest_tpu.utils import knobs

    os.environ["XLA_FLAGS"] = (
        knobs.get("XLA_FLAGS")
        + f" --xla_force_host_platform_device_count={n_devices}"
    )
    jax.config.update("jax_platforms", "cpu")
    _provisioned = n_devices
