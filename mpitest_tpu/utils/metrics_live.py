"""Live metrics registry — the scrapeable counterpart of the span stream.

The span/JSONL layer (``utils/spans.py``) is *post-hoc*: spans land in a
file and ``report.py`` reads it after the process exits.  A serving
process needs the *live* view — queue depth, cache hit ratio, p99 drift
— without being killed first.  This module is that view: a
dependency-free (stdlib-only, like ``span_schema.py`` — sortlint loads
it by path with no jax/numpy) registry of **counters**, **gauges** and
**fixed-bucket latency histograms**, updated from the span-close path
(:class:`SpanMetricsBridge`) and the serve hot paths, rendered as
Prometheus text exposition by the server's ``/metrics`` endpoint
(``serve/telemetry.py``).

Metric names are REGISTERED here (:data:`METRICS`), exactly like span
names in ``utils/span_schema.py``: ``report.py`` and the dashboards
key on these strings, so an unregistered name is a hard ``KeyError``
at runtime and a sortlint ``SL004`` finding at lint time — a renamed
metric must touch this file, never silently vanish from a scrape.

Updates are lock-cheap by design: one registry lock around plain
dict/float ops (no allocation on the hot path once a series exists) —
measured noise next to a single span's JSON encode.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Iterator

#: Exposition content type (the Prometheus text format this module
#: renders and parses).
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Fixed latency buckets (seconds) — request latency / queue wait.
#: Spanning 1 ms .. 60 s: below serving resolution to far past any SLO.
LATENCY_BUCKETS_S: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: Fixed batch-occupancy buckets (segments packed per dispatch).
OCCUPANCY_BUCKETS: tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64)

#: The registered metric vocabulary: name -> (type, help).  Histograms
#: carry their bucket set in :data:`_HISTOGRAM_BUCKETS`.  sortlint rule
#: SL004 fails the lint gate on any literal metric name outside this
#: dict (same pattern as SL003 for span names).
METRICS: dict[str, tuple[str, str]] = {
    # serve request path
    "sort_serve_requests_total": (
        "counter", "Served requests by terminal status (label: status)."),
    "sort_serve_request_latency_seconds": (
        "histogram", "End-to-end request latency (successful requests)."),
    "sort_serve_queue_wait_seconds": (
        "histogram", "Admission-to-dispatch queue wait per request."),
    "sort_serve_rejected_total": (
        "counter", "Admission rejections by typed reason (label: reason)."),
    "sort_serve_inflight": (
        "gauge", "Requests currently admitted and in flight."),
    "sort_serve_inflight_bytes": (
        "gauge", "Payload bytes currently admitted and in flight."),
    # batching / dispatch
    "sort_serve_batches_total": (
        "counter", "Packed multi-tenant dispatches."),
    "sort_serve_batch_segments": (
        "histogram", "Segments (tenants) packed per dispatch."),
    "sort_serve_batch_keys_total": (
        "counter", "Keys dispatched through the packed path."),
    "sort_serve_batch_fallbacks_total": (
        "counter", "Whole-batch dispatch failures (every tenant re-ran "
                   "solo)."),
    "sort_serve_segment_requeues_total": (
        "counter", "Segments that failed verification and re-ran solo."),
    # request-lifecycle robustness (ISSUE 11)
    "sort_serve_timeouts_total": (
        "counter", "Wire timeouts enforced (label: kind=idle|read|"
                   "write) — stalled/half-dead connections closed."),
    "sort_serve_deadline_exceeded_total": (
        "counter", "Requests cancelled before dispatch because their "
                   "deadline_ms expired (label: stage)."),
    "sort_serve_watchdog_trips_total": (
        "counter", "Dispatch-watchdog trips (a dispatch exceeded "
                   "SORT_SERVE_DISPATCH_TIMEOUT_S; breaker opened)."),
    "sort_serve_drain_timeout_total": (
        "counter", "SIGTERM drains that timed out with work still in "
                   "flight (the server exited rc=1)."),
    "sort_client_hedges_total": (
        "counter", "Client-side hedged requests (second attempt fired "
                   "after the latency threshold)."),
    # executor cache
    "sort_serve_cache_hits_total": (
        "counter", "Executor-cache hits."),
    "sort_serve_cache_misses_total": (
        "counter", "Executor-cache misses (AOT compiles on the request "
                   "path)."),
    "sort_serve_compile_seconds_total": (
        "counter", "Seconds spent compiling executors on cache misses."),
    # robustness (supervisor + verifier, span-close fed)
    "sort_verify_runs_total": ("counter", "Output verifications run."),
    "sort_verify_failures_total": (
        "counter", "Output verifications that FAILED."),
    "sort_verify_seconds_total": (
        "counter", "Wall seconds spent in output verification "
                   "(the verify overhead)."),
    "sort_retries_total": (
        "counter", "Supervisor dispatch retries."),
    "sort_faults_total": (
        "counter", "Injected faults fired (label: site)."),
    # scale-out exchange balance (PR 6 probe)
    "sort_exchange_recv_ratio": (
        "gauge", "Last exchange's recv max/mean byte ratio."),
    "sort_exchange_peer_ratio": (
        "gauge", "Last exchange's max single-peer/fair-share ratio."),
    "sort_exchange_negotiated_cap": (
        "gauge", "Last negotiated exchange capacity (keys per peer)."),
    "sort_exchange_worst_cap": (
        "gauge", "Worst-case exchange capacity the negotiation beat."),
    "sort_exchange_rank_recv_bytes": (
        "gauge", "Last exchange's per-rank received bytes (label: rank)."),
    "sort_exchange_rank_send_bytes": (
        "gauge", "Last exchange's per-rank sent bytes (label: rank)."),
    # profiling / flight recorder
    "sort_profile_captures_total": (
        "counter", "On-demand jax.profiler captures taken."),
    "sort_flight_dumps_total": (
        "counter", "Flight-recorder artifacts dumped."),
    # streaming sentinel (ISSUE 16): serve.alert spans bridged by rule
    # + severity; rule names are the doctor.DOCTOR_RULES vocabulary
    "sort_alerts_total": (
        "counter", "Sentinel anomaly alerts raised (labels: rule, "
                   "severity)."),
    # plan provenance (ISSUE 12): predicted-vs-actual regret per
    # decision, exported live so mis-sized caps / wasted restages /
    # wrong reroutes are visible in /metrics before they cost
    # throughput.  Fed from sort.plan span closes by the bridge.
    "sort_plans_total": (
        "counter", "Finished plan records (label: algo)."),
    "sort_plan_regret": (
        "gauge", "Last plan's total predicted-vs-actual regret."),
    "sort_plan_decision_regret": (
        "gauge", "Last plan's regret per decision (label: decision)."),
    "sort_plan_cap_regret": (
        "gauge", "Last plan's exchange-cap regret (|cap-need|/need + "
                 "overflow regrows) — rises when negotiation is off or "
                 "mis-predicts."),
    "sort_plan_reroutes_total": (
        "counter", "Plans whose algorithm was rerouted away from the "
                   "requested one (label: trigger)."),
    # self-tuning planner (ISSUE 14): the policy layer's own telemetry
    # — a bad policy is visible here before it costs throughput.
    "sort_planner_decisions_total": (
        "counter", "Planner policy decisions (labels: policy, "
                   "applied) — shadow decisions count with "
                   "applied=\"false\"."),
    "sort_planner_regret": (
        "gauge", "Last plan's planner-decision regret (the planner's "
                 "own cost: wasted passthrough verifies) — rises when "
                 "the policy chooses worse than the best-known "
                 "config."),
    "sort_serve_window_retunes_total": (
        "counter", "Serve batching-window re-sizes the tuner applied "
                   "(on mode; hysteresis-gated)."),
    "sort_serve_batch_window_ms": (
        "gauge", "Current (possibly auto-tuned) serve batching window "
                 "in milliseconds."),
    # out-of-core external sort (ISSUE 15): spill/merge volume and the
    # integrity-recovery tally, fed from external.* span closes; the
    # spilled-request counter is written by the serve spill tier.
    "sort_external_runs_total": (
        "counter", "Spill runs written by the external sort."),
    "sort_external_spill_bytes_total": (
        "counter", "Bytes written to spill runs (keys + payload + "
                   "framing)."),
    "sort_external_merge_seconds_total": (
        "counter", "Wall seconds spent in k-way merge passes."),
    "sort_external_recoveries_total": (
        "counter", "External-sort integrity recoveries (bad run "
                   "re-spilled / merge re-ran before a verified "
                   "result)."),
    "sort_external_spilled_requests_total": (
        "counter", "Serve requests routed to the out-of-core spill "
                   "tier (payload larger than the admission byte "
                   "bound)."),
    # crash-durable spill tier (ISSUE 18): manifest-replay resumes and
    # the startup orphan sweep, fed from external.resume / external.gc
    # span closes.
    "sort_external_resumes_total": (
        "counter", "External sorts that replayed a journaled spill "
                   "manifest and re-entered at the merge phase "
                   "(kill -9 / retried-request recovery)."),
    "sort_external_orphans_reclaimed_total": (
        "counter", "Orphaned spill files reclaimed by the age-gated "
                   "startup GC sweep (files no live manifest "
                   "references)."),
}

_HISTOGRAM_BUCKETS: dict[str, tuple[float, ...]] = {
    "sort_serve_request_latency_seconds": LATENCY_BUCKETS_S,
    "sort_serve_queue_wait_seconds": LATENCY_BUCKETS_S,
    "sort_serve_batch_segments": OCCUPANCY_BUCKETS,
}

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def cumulative_buckets(values: "list[float] | tuple[float, ...]",
                       bounds: tuple[float, ...],
                       ) -> list[tuple[float, int]]:
    """Cumulative ``(le_bound, count)`` pairs over fixed buckets — the
    ONE bucketing rule (``v <= bound``, first match) shared by the live
    histogram exposition and any client-side histogram that must line
    up against it 1:1 (``bench/serve_load.py``).  The ``+Inf`` bucket
    is the caller's ``len(values)``."""
    counts = [0] * len(bounds)
    for v in values:
        for i, b in enumerate(bounds):
            if v <= b:
                counts[i] += 1
                break
    out, cum = [], 0
    for b, c in zip(bounds, counts):
        cum += c
        out.append((b, cum))
    return out


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Series:
    """One (name, labelset) time series.  For histograms, ``value`` is
    the sum and ``buckets`` the cumulative-at-render counts."""

    __slots__ = ("value", "count", "buckets")

    def __init__(self, n_buckets: int = 0) -> None:
        self.value = 0.0
        self.count = 0
        self.buckets = [0] * n_buckets  # per-bucket (non-cumulative)


class Metric:
    """Handle for one registered metric family (all its label series).
    Obtained via :meth:`LiveMetrics.counter` / ``gauge`` /
    ``histogram`` — never constructed directly."""

    def __init__(self, name: str, kind: str, help_text: str,
                 buckets: tuple[float, ...], lock: threading.Lock) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self.bucket_bounds = buckets
        self._lock = lock
        self._series: dict[tuple[tuple[str, str], ...], _Series] = {}

    def _get(self, labels: dict[str, str] | None) -> _Series:
        key = tuple(sorted((k, str(v)) for k, v in (labels or {}).items()))
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _Series(len(self.bucket_bounds))
        return s

    # -- update API ---------------------------------------------------
    def inc(self, amount: float = 1.0, **labels: str) -> None:
        """Counter increment (negative amounts are a ValueError)."""
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            s = self._get(labels)
            s.value += amount
            s.count += 1

    def set(self, value: float, **labels: str) -> None:
        """Gauge assignment."""
        with self._lock:
            self._get(labels).value = float(value)

    def observe(self, value: float, **labels: str) -> None:
        """Histogram observation."""
        with self._lock:
            s = self._get(labels)
            s.value += value
            s.count += 1
            for i, bound in enumerate(self.bucket_bounds):
                if value <= bound:
                    s.buckets[i] += 1
                    break
            # above the last bound: counted only in +Inf (s.count)

    # -- read API (tests / varz) --------------------------------------
    def get(self, **labels: str) -> float:
        with self._lock:
            key = tuple(sorted((k, str(v)) for k, v in labels.items()))
            s = self._series.get(key)
            return s.value if s else 0.0

    def total(self) -> float:
        """Sum over every label series (counters/gauges)."""
        with self._lock:
            return sum(s.value for s in self._series.values())

    def sample_count(self, **labels: str) -> int:
        with self._lock:
            key = tuple(sorted((k, str(v)) for k, v in labels.items()))
            s = self._series.get(key)
            return s.count if s else 0


class LiveMetrics:
    """One live registry (the server owns one).  Lookup of an
    unregistered name raises ``KeyError`` — the metric vocabulary is
    closed (:data:`METRICS`), like the knob and span registries."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}

    def _family(self, name: str, want_kind: str) -> Metric:
        # the kind check runs on EVERY lookup, not just family
        # creation — a warm registry must reject a miskinded accessor
        # exactly like a cold one (a gauge handle to a counter family
        # would let .set() overwrite an accumulated count)
        kind, help_text = METRICS[name]  # KeyError = unregistered
        if kind != want_kind:
            raise KeyError(
                f"metric {name!r} is registered as a {kind}, "
                f"not a {want_kind}")
        m = self._metrics.get(name)
        if m is None:
            buckets = _HISTOGRAM_BUCKETS.get(name, ())
            m = Metric(name, kind, help_text, buckets, self._lock)
            with self._lock:
                m = self._metrics.setdefault(name, m)
        return m

    def counter(self, name: str) -> Metric:
        return self._family(name, "counter")

    def gauge(self, name: str) -> Metric:
        return self._family(name, "gauge")

    def histogram(self, name: str) -> Metric:
        return self._family(name, "histogram")

    def families(self) -> Iterator[Metric]:
        with self._lock:
            out = sorted(self._metrics.values(), key=lambda m: m.name)
        return iter(out)

    # -- exposition ---------------------------------------------------
    def render_prom(self) -> str:
        """Prometheus text exposition of every touched family."""
        out: list[str] = []
        for m in self.families():
            out.append(f"# HELP {m.name} {_esc(m.help)}")
            out.append(f"# TYPE {m.name} {m.kind}")
            with self._lock:
                series = list(m._series.items())
            for key, s in sorted(series):
                lbl = ",".join(f'{k}="{_esc(v)}"' for k, v in key)
                if m.kind != "histogram":
                    out.append(f"{m.name}{{{lbl}}} {_fmt(s.value)}"
                               if lbl else f"{m.name} {_fmt(s.value)}")
                    continue
                cum = 0
                for bound, cnt in zip(m.bucket_bounds, s.buckets):
                    cum += cnt
                    le = f'le="{_fmt(bound)}"'
                    full = f"{lbl},{le}" if lbl else le
                    out.append(f"{m.name}_bucket{{{full}}} {cum}")
                le = 'le="+Inf"'
                full = f"{lbl},{le}" if lbl else le
                out.append(f"{m.name}_bucket{{{full}}} {s.count}")
                out.append(f"{m.name}_sum{{{lbl}}} {_fmt(s.value)}"
                           if lbl else f"{m.name}_sum {_fmt(s.value)}")
                out.append(f"{m.name}_count{{{lbl}}} {s.count}"
                           if lbl else f"{m.name}_count {s.count}")
        return "\n".join(out) + ("\n" if out else "")


# ------------------------------------------------------ scrape parsing

def parse_prom_text(text: str) -> dict[str, dict]:
    """Parse Prometheus text exposition into ``{base_name: {"type", "help",
    "samples": [(suffixed_name, labels_dict, value)]}}`` — the consumer
    half (``report.py --prom``, the load generator's reconciliation
    scrape).  Tolerant of unknown families; strict on line grammar
    (a malformed line raises ``ValueError`` naming it)."""
    fams: dict[str, dict] = {}

    def fam(name: str) -> dict:
        return fams.setdefault(name, {"type": "untyped", "help": "",
                                      "samples": []})

    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(" ", 1)
            fam(rest[0])["help"] = rest[1] if len(rest) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2:
                raise ValueError(f"line {lineno}: malformed TYPE line")
            fam(parts[0])["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue
        m = _NAME_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: no metric name in {line!r}")
        name = m.group(0)
        rest = line[m.end():].strip()
        labels: dict[str, str] = {}
        if rest.startswith("{"):
            close = rest.find("}")
            if close < 0:
                raise ValueError(f"line {lineno}: unterminated label set")
            labels = {k: v.replace('\\"', '"').replace("\\\\", "\\")
                      for k, v in _LABEL_RE.findall(rest[1:close])}
            rest = rest[close + 1:].strip()
        val_str = rest.split()[0] if rest else ""
        try:
            value = float("inf") if val_str == "+Inf" else float(val_str)
        except ValueError:
            raise ValueError(
                f"line {lineno}: bad sample value {val_str!r}") from None
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in fams:
                base = name[:-len(suffix)]
                break
        fam(base)["samples"].append((name, labels, value))
    return fams


def check_exposition(text: str) -> list[str]:
    """Validate a ``/metrics`` scrape: parseable grammar AND every
    family name registered in :data:`METRICS` with the registered type.
    Returns a list of violations (empty = clean) — the
    ``telemetry-selftest`` gate."""
    errors: list[str] = []
    try:
        fams = parse_prom_text(text)
    except ValueError as e:
        return [str(e)]
    for name, f in fams.items():
        reg = METRICS.get(name)
        if reg is None:
            errors.append(f"metric {name!r} is not registered in "
                          "utils/metrics_live.py METRICS")
        elif f["type"] not in ("untyped", reg[0]):
            errors.append(f"metric {name!r} exposed as {f['type']}, "
                          f"registered as {reg[0]}")
        if not f["samples"]:
            errors.append(f"metric family {name!r} has no samples")
    return errors


# ------------------------------------------------- span-close bridge

class SpanMetricsBridge:
    """SpanLog observer: maps closed spans onto live metrics — the
    "updated from the existing span-close path" half of the design.
    Attach with ``spanlog.observers.append(SpanMetricsBridge(metrics))``;
    every mapping is attr-tolerant (telemetry must never take down the
    path it observes)."""

    def __init__(self, metrics: LiveMetrics) -> None:
        self.metrics = metrics

    def __call__(self, span: object) -> None:
        # local import: keeps this module loadable by sortlint with no
        # package context (span duck-typed: .name/.dt/.attrs)
        name = getattr(span, "name", "")
        dt = float(getattr(span, "dt", 0.0) or 0.0)
        attrs = getattr(span, "attrs", None) or {}
        metrics = self.metrics
        if name == "serve.request":
            status = str(attrs.get("status", "?"))
            metrics.counter("sort_serve_requests_total").inc(
                1, status=status)
            if status == "ok":
                metrics.histogram(
                    "sort_serve_request_latency_seconds").observe(dt)
            reject = attrs.get("reject")
            if reject:
                metrics.counter("sort_serve_rejected_total").inc(
                    1, reason=str(reject))
            q = attrs.get("queue_s")
            if q is not None:
                metrics.histogram(
                    "sort_serve_queue_wait_seconds").observe(float(q))
        elif name == "serve.batch":
            metrics.counter("sort_serve_batches_total").inc(1)
            metrics.histogram("sort_serve_batch_segments").observe(
                float(attrs.get("segments", 0) or 0))
            metrics.counter("sort_serve_batch_keys_total").inc(
                float(attrs.get("keys", 0) or 0))
        elif name == "serve.compile_cache":
            if attrs.get("hit"):
                metrics.counter("sort_serve_cache_hits_total").inc(1)
            else:
                metrics.counter("sort_serve_cache_misses_total").inc(1)
                metrics.counter("sort_serve_compile_seconds_total").inc(
                    float(attrs.get("compile_s", 0.0) or 0.0))
        elif name == "serve.profile":
            metrics.counter("sort_profile_captures_total").inc(1)
        elif name == "external.run":
            metrics.counter("sort_external_runs_total").inc(1)
            metrics.counter("sort_external_spill_bytes_total").inc(
                float(attrs.get("bytes", 0) or 0))
        elif name == "external.merge":
            metrics.counter(
                "sort_external_merge_seconds_total").inc(dt)
        elif name == "external.recover":
            metrics.counter("sort_external_recoveries_total").inc(1)
        elif name == "external.resume":
            metrics.counter("sort_external_resumes_total").inc(1)
        elif name == "external.gc":
            metrics.counter(
                "sort_external_orphans_reclaimed_total").inc(
                float(attrs.get("reclaimed", 0) or 0))
        elif name == "serve.deadline":
            metrics.counter("sort_serve_deadline_exceeded_total").inc(
                1, stage=str(attrs.get("stage", "?")))
        elif name == "serve.watchdog":
            event = str(attrs.get("event", "?"))
            if event == "trip":
                metrics.counter("sort_serve_watchdog_trips_total").inc(1)
            elif event == "drain_timeout":
                metrics.counter("sort_serve_drain_timeout_total").inc(1)
        elif name == "serve.alert":
            # sentinel anomaly alerts (ISSUE 16) — rule names are the
            # registered doctor.DOCTOR_RULES vocabulary (SL007)
            metrics.counter("sort_alerts_total").inc(
                1, rule=str(attrs.get("rule", "?")),
                severity=str(attrs.get("severity", "?")))
        # serve.hedge is deliberately NOT bridged: the ResilientClient
        # increments sort_client_hedges_total directly at hedge-launch
        # (semantics: hedges FIRED), and a client wired with both a
        # bridged spanlog and a metrics registry must not double-count.
        elif name == "verify":
            metrics.counter("sort_verify_runs_total").inc(1)
            if not attrs.get("ok", True):
                metrics.counter("sort_verify_failures_total").inc(1)
        elif name == "phase:verify":
            metrics.counter("sort_verify_seconds_total").inc(dt)
        elif name == "supervisor_retry":
            metrics.counter("sort_retries_total").inc(1)
        elif name == "fault":
            metrics.counter("sort_faults_total").inc(
                1, site=str(attrs.get("site", "?")))
        elif name == "sort.plan":
            metrics.counter("sort_plans_total").inc(
                1, algo=str(attrs.get("algo", "?")))
            r = attrs.get("regret")
            if r is not None:
                metrics.gauge("sort_plan_regret").set(float(r))
            decisions = attrs.get("decisions")
            if isinstance(decisions, dict):
                for dname, d in decisions.items():
                    if not isinstance(d, dict):
                        continue
                    dr = d.get("regret")
                    if dr is not None:
                        metrics.gauge("sort_plan_decision_regret").set(
                            float(dr), decision=str(dname))
                cap = decisions.get("cap")
                if isinstance(cap, dict) and cap.get("regret") is not None:
                    metrics.gauge("sort_plan_cap_regret").set(
                        float(cap["regret"]))
                algo_d = decisions.get("algo")
                if isinstance(algo_d, dict) and \
                        algo_d.get("requested") is not None and \
                        algo_d.get("chosen") != algo_d.get("requested"):
                    metrics.counter("sort_plan_reroutes_total").inc(
                        1, trigger=str(algo_d.get("trigger", "?")))
                pl = decisions.get("planner")
                if isinstance(pl, dict):
                    # ISSUE 14: the planner's own census + regret —
                    # `applied` distinguishes acting from shadow
                    applied = bool((pl.get("predicted") or {})
                                   .get("applied"))
                    metrics.counter("sort_planner_decisions_total").inc(
                        1, policy=str(pl.get("chosen", "?")),
                        applied=str(applied).lower())
                    if pl.get("regret") is not None:
                        metrics.gauge("sort_planner_regret").set(
                            float(pl["regret"]))
        elif name == "exchange_balance":
            for key, metric in (
                    ("recv_ratio", "sort_exchange_recv_ratio"),
                    ("peer_ratio", "sort_exchange_peer_ratio"),
                    ("negotiated_cap", "sort_exchange_negotiated_cap"),
                    ("worst_cap", "sort_exchange_worst_cap")):
                v = attrs.get(key)
                if v is not None:
                    metrics.gauge(metric).set(float(v))
            for key, metric in (
                    ("recv_bytes", "sort_exchange_rank_recv_bytes"),
                    ("send_bytes", "sort_exchange_rank_send_bytes")):
                vals = attrs.get(key)
                if isinstance(vals, (list, tuple)):
                    g = metrics.gauge(metric)
                    for rank, v in enumerate(vals):
                        g.set(float(v), rank=str(rank))
