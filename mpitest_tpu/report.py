"""Telemetry aggregation + regression CLI: ``python -m mpitest_tpu.report``.

The consumer end of the unified telemetry layer (SURVEY.md §5 metrics
row): every producer in the repo emits JSONL with a self-identifying
shape, and this module reads them all —

* ``SORT_TRACE`` span streams (``{"v": "span.v1", ...}`` —
  utils/spans.py),
* ``COMM_STATS`` native backend records (``{"v": "comm_stats.v1", ...}``
  — comm/comm_stats.h),
* ``SORT_METRICS`` sidecars (``{"ts", "config", "metrics"}`` —
  utils/metrics.py),
* bench driver rows (``{"metric", "value", ...}`` — bench.py stdout and
  ``bench/BASELINE_RESULTS.jsonl``)

— and renders one per-phase / per-collective table in which native
(MPI/pthreads) and TPU runs line up on the comm.h collective vocabulary
(:data:`mpitest_tpu.utils.spans.MPI_EQUIV`): the per-pass, per-collective
evidence the MPI-vs-ICI north star needs.

Modes:

* default — aggregate the given files (``bench/BASELINE_RESULTS.jsonl``
  when none given) and print the tables.
* ``--baseline FILE`` — flag metric regressions against pinned rows.  A
  baseline row carrying a ``"host"`` provenance fingerprint is only
  compared when it matches this machine (``utils/platform.py
  host_fingerprint``) — cross-host ratios are weather, not regressions
  (ADVICE round 5).  Exit code 2 when any regression is flagged.
* ``--check`` — schema-validate the files (the ``make
  telemetry-selftest`` gate): span streams must parse, nest, and export
  to Chrome trace-event; comm_stats lines must carry
  calls/bytes/seconds per collective.  Exit 1 on any violation.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

from mpitest_tpu.utils import span_schema
from mpitest_tpu.utils.span_schema import (BALANCE_SPAN,
                                           BATCH_ID_ATTR,
                                           BATCH_TRACE_IDS_ATTR,
                                           FAULT_SPAN,
                                           INGEST_HOST_STAGES,
                                           INGEST_XFER_STAGES, PHASE_PREFIX,
                                           PLAN_SPAN,
                                           RESTAGE_SPAN, RETRY_SPAN,
                                           SERVE_BATCH_SPAN,
                                           SERVE_CACHE_SPAN,
                                           SERVE_DEADLINE_SPAN,
                                           SERVE_HEDGE_SPAN,
                                           SERVE_REQUEST_SPAN,
                                           SERVE_WATCHDOG_SPAN,
                                           TRACE_ID_ATTR, VERIFY_SPAN)
from mpitest_tpu.utils.spans import (MPI_EQUIV, SCHEMA as SPAN_SCHEMA,
                                     merge_intervals, overlap_seconds)

COMM_STATS_SCHEMA = "comm_stats.v1"

#: End-to-end ingest gate (ISSUE 6): sort_incl_ingest must hold at least
#: this fraction of the raw sort throughput.  The ONE definition —
#: bench/ingest_selftest.py asserts the same constant it records, and
#: ``--require-ingest-overlap`` re-checks it from the recorded
#: ``ingest_ratio`` metric when one is present.
INGEST_RATIO_GATE = 0.5

#: Default availability SLO target for the error-budget line (ISSUE 10):
#: at 99.9%, an 0.1% error rate burns the budget at exactly 1.0x.
DEFAULT_SLO_TARGET_PCT = 99.9

#: Absolute floor when comparing a CURRENT plan_regret against a pinned
#: one (ISSUE 12): a pin of 0.0 is the common clean-run value, and a
#: pure ratio band would either never flag (pin=0 skip) or flag on
#: meaningless near-zero jitter.  Same rationale as
#: tools/bench_history.py LOWER_BEST_FLOOR (kept separate: that tool is
#: import-light by design and must not pull this package).
PLAN_REGRET_FLOOR = 0.25


# --------------------------------------------------------------- loading

def load_rows(path: str) -> list[dict]:
    """All JSON objects in a JSONL file, each tagged with its source
    ``kind``: span | comm_stats | metrics | bench | unknown."""
    rows = []
    for lineno, line in enumerate(Path(path).read_text().splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            rows.append({"kind": "invalid", "path": path, "line": lineno,
                         "error": f"not valid JSON ({e})"})
            continue
        if not isinstance(obj, dict):
            # valid JSON, wrong shape (e.g. a bare list/number) — a
            # schema violation to report, never a crash in the checker
            rows.append({"kind": "invalid", "path": path, "line": lineno,
                         "error": "top-level value is not an object"})
            continue
        obj["_path"], obj["_line"] = path, lineno
        v = obj.get("v")
        if v == SPAN_SCHEMA:
            obj["kind"] = "span"
        elif v == COMM_STATS_SCHEMA:
            obj["kind"] = "comm_stats"
        elif "metrics" in obj and "config" in obj:
            obj["kind"] = "metrics"
        elif "metric" in obj and "value" in obj:
            obj["kind"] = "bench"
        else:
            obj["kind"] = "unknown"
        rows.append(obj)
    return rows


# ----------------------------------------------------------- aggregation

# Ingest/egress stage split: imported from utils/span_schema.py — the
# ONE registered vocabulary producers and this consumer share, enforced
# by sortlint rule SL003 (a renamed span can no longer silently vanish
# from these tables).  Overlap is computed PER DIRECTION (the span
# name's prefix): ingest host work against ingest transfers, egress
# decode against egress fetches — pooling them would let egress-only
# overlap satisfy the --require-ingest-overlap gate after an ingest
# regression.  ``ingest.pipeline`` is the umbrella span and is
# excluded from per-stage sums (it would double-count its children).


def aggregate(rows: list[dict]) -> dict:
    """Fold rows into the report tables.

    Returns ``{"phases": {name: {"ms", "count"}},
               "collectives": {source: {coll: {calls, bytes, seconds}}},
               "metrics": {metric: latest bench/metrics value row},
               "spans": {name: count},
               "ingest": {stage: {seconds, count, bytes}},
               "robustness": {faults, fault_sites, retries, verify_runs,
                              verify_failures},
               "ingest_overlap"/"egress_overlap":
                   {host_s, transfer_s, overlap_s, pct} | None}``.
    Collective sources are ``tpu`` (span events, mapped through
    MPI_EQUIV) and ``native/<backend>x<ranks>`` (comm_stats records).
    """
    phases: dict[str, dict] = {}
    colls: dict[str, dict] = {}
    metrics: dict[str, dict] = {}
    span_counts: dict[str, int] = {}
    ingest: dict[str, dict] = {}
    # robustness events (ISSUE 3): injected faults, supervisor retries
    # and verification outcomes ride the same span stream — fold them
    # into one table so a chaos run's telemetry is one `report` away.
    robust = {"faults": 0, "fault_sites": {}, "retries": 0,
              "verify_runs": 0, "verify_failures": 0}
    # sort-as-a-service events (ISSUE 8): one serve.request span per
    # served request (its duration is the SLO latency unit), one
    # serve.batch per packed multi-tenant dispatch, one
    # serve.compile_cache point event per executor-cache lookup.
    serve = {"requests": [], "batches": 0, "batch_segments": 0,
             "batch_keys": 0, "cache_hits": 0, "cache_misses": 0,
             "compile_s": 0.0,
             # request-lifecycle robustness events (ISSUE 11)
             "deadline_expired": {}, "watchdog": {}, "hedges": 0}
    # scale-out events (ISSUE 7): one exchange_balance event per
    # negotiated exchange (per-rank send/recv bytes, negotiated vs
    # worst-case capacity) + the restage count — the evidence row of
    # the multi-chip path.
    scaleout = {"balance": [], "restages": 0}
    # tooling state (ISSUE 4): bench rows stamp the lint/sanitizer gate
    # versions; the report surfaces the last-seen state so a table of
    # numbers names the rule set that guarded them.
    tooling: dict | None = None
    # encode engines seen on ingest.pipeline spans (ISSUE 6)
    encode_engines: set = set()
    # overlap intervals grouped per (file, pid): t0 is a process-relative
    # perf_counter clock, so intervals from different runs appended to
    # one SORT_TRACE file live on unrelated timelines — comparing them
    # would manufacture phantom overlap (and green-light a serial
    # pipeline through --require-ingest-overlap).
    host_iv: dict[tuple, list] = {}
    xfer_iv: dict[tuple, list] = {}

    def add_coll(source: str, name: str, calls, nbytes, seconds) -> None:
        row = colls.setdefault(source, {}).setdefault(
            name, {"calls": 0, "bytes": 0, "seconds": 0.0})
        row["calls"] += int(calls)
        row["bytes"] += int(nbytes)
        row["seconds"] += float(seconds)

    for obj in rows:
        kind = obj.get("kind")
        if kind == "span":
            name = obj.get("name", "?")
            span_counts[name] = span_counts.get(name, 0) + 1
            if name.startswith(PHASE_PREFIX):
                p = phases.setdefault(name[len(PHASE_PREFIX):],
                                      {"ms": 0.0, "count": 0})
                p["ms"] += float(obj.get("dt", 0.0)) * 1e3
                p["count"] += 1
            elif name in MPI_EQUIV:
                add_coll("tpu", MPI_EQUIV[name], 1,
                         obj.get("attrs", {}).get("bytes", 0),
                         obj.get("dt", 0.0))
            elif name == FAULT_SPAN:
                robust["faults"] += 1
                site = obj.get("attrs", {}).get("site", "?")
                robust["fault_sites"][site] = \
                    robust["fault_sites"].get(site, 0) + 1
            elif name == RETRY_SPAN:
                robust["retries"] += 1
            elif name == BALANCE_SPAN:
                scaleout["balance"].append(obj.get("attrs", {}))
            elif name == RESTAGE_SPAN:
                scaleout["restages"] += 1
            elif name == SERVE_REQUEST_SPAN:
                a = obj.get("attrs", {})
                serve["requests"].append(
                    {"dt": float(obj.get("dt", 0.0)),
                     "status": str(a.get("status", "?")),
                     "batched": bool(a.get("batched")),
                     "n": int(a.get("n", 0) or 0)})
            elif name == SERVE_BATCH_SPAN:
                a = obj.get("attrs", {})
                serve["batches"] += 1
                serve["batch_segments"] += int(a.get("segments", 0) or 0)
                serve["batch_keys"] += int(a.get("keys", 0) or 0)
            elif name == SERVE_CACHE_SPAN:
                a = obj.get("attrs", {})
                if a.get("hit"):
                    serve["cache_hits"] += 1
                else:
                    serve["cache_misses"] += 1
                    serve["compile_s"] += float(a.get("compile_s", 0.0)
                                                or 0.0)
            elif name == SERVE_DEADLINE_SPAN:
                stage = str(obj.get("attrs", {}).get("stage", "?"))
                serve["deadline_expired"][stage] = \
                    serve["deadline_expired"].get(stage, 0) + 1
            elif name == SERVE_WATCHDOG_SPAN:
                event = str(obj.get("attrs", {}).get("event", "?"))
                serve["watchdog"][event] = \
                    serve["watchdog"].get(event, 0) + 1
            elif name == SERVE_HEDGE_SPAN:
                serve["hedges"] += 1
            elif name == VERIFY_SPAN:
                robust["verify_runs"] += 1
                if not obj.get("attrs", {}).get("ok", True):
                    robust["verify_failures"] += 1
            elif name == "ingest.pipeline":
                # umbrella span (excluded from stage sums): carries the
                # run's chosen encode engine (ISSUE 6 — a degraded
                # SORT_NATIVE_ENCODE=auto is visible here, never silent)
                e = obj.get("attrs", {}).get("encode_engine")
                if e:
                    encode_engines.add(str(e))
            elif name in INGEST_HOST_STAGES or name in INGEST_XFER_STAGES:
                row = ingest.setdefault(
                    name, {"seconds": 0.0, "count": 0, "bytes": 0})
                dt = float(obj.get("dt", 0.0))
                t0 = float(obj.get("t0", 0.0))
                row["seconds"] += dt
                row["count"] += 1
                row["bytes"] += int(obj.get("attrs", {}).get("bytes", 0))
                run = (obj.get("_path"), obj.get("pid"),
                       name.split(".", 1)[0])
                (host_iv if name in INGEST_HOST_STAGES
                 else xfer_iv).setdefault(run, []).append((t0, t0 + dt))
        elif kind == "comm_stats":
            source = f"native/{obj.get('backend', '?')}x{obj.get('ranks', '?')}"
            for cname, c in obj.get("collectives", {}).items():
                add_coll(source, cname, c.get("calls", 0),
                         c.get("bytes", 0), c.get("seconds", 0.0))
        elif kind == "metrics":
            for mname, m in obj.get("metrics", {}).items():
                if mname.startswith("phase_") and mname.endswith("_ms"):
                    p = phases.setdefault(mname[len("phase_"):-len("_ms")],
                                          {"ms": 0.0, "count": 0})
                    p["ms"] += float(m.get("value", 0.0))
                    p["count"] += 1
                else:
                    metrics[mname] = {"value": m.get("value"),
                                      "unit": m.get("unit"),
                                      "config": obj.get("config")}
        elif kind == "bench":
            metrics[obj["metric"]] = {k: v for k, v in obj.items()
                                      if not k.startswith("_")}
            if isinstance(obj.get("tooling"), dict):
                tooling = obj["tooling"]
    def direction_overlap(direction: str) -> dict | None:
        runs = {r for r in set(host_iv) | set(xfer_iv) if r[2] == direction}
        if not runs:
            return None
        host_s = xfer_s = ov = 0.0
        for run in runs:
            hm = merge_intervals(host_iv.get(run, []))
            xm = merge_intervals(xfer_iv.get(run, []))
            host_s += sum(b - a for a, b in hm)
            xfer_s += sum(b - a for a, b in xm)
            ov += overlap_seconds(hm, xm)
        return {"host_s": host_s, "transfer_s": xfer_s, "overlap_s": ov,
                "pct": 100.0 * ov / xfer_s if xfer_s > 0 else 0.0}

    return {"phases": phases, "collectives": colls, "metrics": metrics,
            "spans": span_counts, "ingest": ingest, "robustness": robust,
            "scaleout": scaleout, "serve": serve, "tooling": tooling,
            "encode_engines": sorted(encode_engines),
            "ingest_overlap": direction_overlap("ingest"),
            "egress_overlap": direction_overlap("egress")}


# -------------------------------------------------------------- scale-out

#: Bench metric-name shape of a sorted-throughput row; the ``_8dev``
#: suffix marks the devices=8 scale-out row (bench.py ISSUE 7).
_THROUGHPUT_RE = re.compile(
    r"^(radix|sample)_sort_mkeys_per_s_2e(\d+)_([a-z0-9]+?)(_8dev)?$")


def scaleout_throughput(metrics: dict) -> list[dict]:
    """Pair the 1-device and devices=8 throughput rows by (algo, dtype)
    for the scale-out table: each entry carries both values (where
    present) and their ratio when the scales match — comparing rows at
    different N would manufacture a fake speedup, so mismatched scales
    report the values but no ratio."""
    base: dict[tuple, dict] = {}
    multi: dict[tuple, dict] = {}
    for name, m in metrics.items():
        mt = _THROUGHPUT_RE.match(name)
        if not mt or m.get("value") is None:
            continue
        row = {"log2n": int(mt.group(2)), "value": float(m["value"])}
        (multi if mt.group(4) else base)[(mt.group(1), mt.group(3))] = row
    out = []
    for key in sorted(set(base) | set(multi)):
        b, p8 = base.get(key), multi.get(key)
        entry: dict = {"algo": key[0], "dtype": key[1], "p1": b, "p8": p8}
        if b and p8 and b["log2n"] == p8["log2n"] and b["value"] > 0:
            entry["speedup"] = round(p8["value"] / b["value"], 3)
        out.append(entry)
    return out


# ----------------------------------------------------------------- serve

def percentile(sorted_values: list, q: float) -> float:
    """Nearest-rank percentile of an ASCENDING-sorted list (the SLO
    convention: p99 is the smallest value >= 99% of the samples)."""
    if not sorted_values:
        return 0.0
    import math as _math

    rank = max(1, _math.ceil(q / 100.0 * len(sorted_values)))
    return float(sorted_values[rank - 1])


def error_budget(requests: int, errors: int,
                 target_pct: float = DEFAULT_SLO_TARGET_PCT) -> dict:
    """Error-budget / burn-rate arithmetic (ISSUE 10), shared by the
    span-derived SLO table and the ``--prom`` snapshot view: the budget
    is ``100 - target_pct`` percent of requests; burn is the measured
    error rate over that allowance (1.0x = exactly on budget)."""
    rate = 100.0 * errors / requests if requests else 0.0
    allowance = 100.0 - target_pct
    return {
        "slo_target_pct": target_pct,
        "error_rate_pct": round(rate, 4),
        "budget_burn": (round(rate / allowance, 2) if allowance > 0
                        else None),
    }


def serve_slo(serve: dict,
              slo_target: float = DEFAULT_SLO_TARGET_PCT) -> dict | None:
    """Fold the serve.* span census into the SLO table (ISSUE 8):
    p50/p99/mean request latency over SUCCESSFUL requests (an error is
    an error budget line, not a latency sample), error counts by typed
    code, the batched fraction, the executor-cache hit ratio, and the
    error-budget burn against ``slo_target`` (ISSUE 10).
    None when no serve activity was recorded."""
    reqs = serve.get("requests", [])
    if not reqs and not serve.get("batches") \
            and not (serve.get("cache_hits") or serve.get("cache_misses")):
        return None
    ok = [r for r in reqs if r["status"] == "ok"]
    lat = sorted(r["dt"] for r in ok)
    errors: dict[str, int] = {}
    for r in reqs:
        if r["status"] != "ok":
            errors[r["status"]] = errors.get(r["status"], 0) + 1
    out = {
        "requests": len(reqs), "ok": len(ok), "errors": errors,
        "batched": sum(1 for r in ok if r["batched"]),
        "keys": sum(r["n"] for r in ok),
        "p50_ms": round(percentile(lat, 50) * 1e3, 3),
        "p99_ms": round(percentile(lat, 99) * 1e3, 3),
        "mean_ms": round(1e3 * sum(lat) / len(lat), 3) if lat else 0.0,
        "batches": serve.get("batches", 0),
        "batch_segments": serve.get("batch_segments", 0),
        "cache_hits": serve.get("cache_hits", 0),
        "cache_misses": serve.get("cache_misses", 0),
        "compile_s": round(serve.get("compile_s", 0.0), 4),
        "deadline_expired": dict(serve.get("deadline_expired") or {}),
        "watchdog": dict(serve.get("watchdog") or {}),
        "hedges": serve.get("hedges", 0),
    }
    out.update(error_budget(len(reqs), len(reqs) - len(ok), slo_target))
    return out


# ----------------------------------------------------- trace view (live)

def trace_view(rows: list[dict], trace_id: str) -> str | None:
    """Reconstruct ONE request end-to-end from its ``trace_id`` (ISSUE
    10): its ``serve.request`` span (queue wait, status, latency), the
    packed dispatch it shared (via ``batch_id`` — batchmates counted,
    never leaked), and every dispatch-side span stamped with either id
    (the ``sort`` umbrella, phases, retries, faults, verifications),
    rendered as one chronological timeline.  None when no span carries
    the id."""
    spans = [r for r in rows if r.get("kind") == "span"]
    direct = [s for s in spans
              if s.get("attrs", {}).get(TRACE_ID_ATTR) == trace_id]
    batch_ids = {s["attrs"][BATCH_ID_ATTR] for s in direct
                 if s.get("attrs", {}).get(BATCH_ID_ATTR) is not None}
    batchmates: set[str] = set()
    for s in spans:
        if s.get("name") == SERVE_BATCH_SPAN:
            tids = s.get("attrs", {}).get(BATCH_TRACE_IDS_ATTR) or []
            if trace_id in tids:
                bid = s["attrs"].get(BATCH_ID_ATTR)
                if bid is not None:
                    batch_ids.add(bid)
                batchmates.update(t for t in tids if t != trace_id)
    direct_keys = {(s.get("_path"), s.get("pid"), s.get("id"))
                   for s in direct}
    related = [
        s for s in spans
        if (s.get("_path"), s.get("pid"), s.get("id")) not in direct_keys
        and s.get("attrs", {}).get(BATCH_ID_ATTR) in batch_ids
        # a batchmate's own serve.request carries ITS trace_id — that
        # is someone else's request, not part of this timeline
        and s.get("attrs", {}).get(TRACE_ID_ATTR) in (None, trace_id)
    ]
    selected = direct + related
    if not selected:
        return None
    selected.sort(key=lambda s: (str(s.get("_path")), s.get("pid"),
                                 float(s.get("t0", 0.0))))
    t_base = min(float(s.get("t0", 0.0)) for s in selected)
    req = next((s for s in direct if s.get("name") == SERVE_REQUEST_SPAN),
               None)
    out = [f"request trace {trace_id}"]
    if req is not None:
        a = req.get("attrs", {})
        line = (f"  status={a.get('status')} n={a.get('n')} "
                f"dtype={a.get('dtype')} "
                f"latency={float(req.get('dt', 0.0)) * 1e3:.3f}ms")
        if a.get("queue_s") is not None:
            line += f" queue_wait={float(a['queue_s']) * 1e3:.3f}ms"
        if a.get(BATCH_ID_ATTR):
            line += (f" batch={a[BATCH_ID_ATTR]} "
                     f"(+{len(batchmates)} batchmate(s), "
                     f"bucket={a.get('bucket')})")
        else:
            line += " batched=" + str(bool(a.get("batched")))
        out.append(line)
    out.append(f"  {'t+ms':>10} {'dur ms':>10} {'span':<20} attrs")
    hidden = ("trace_id", "batch_id", "trace_ids")
    for s in selected:
        a = {k: v for k, v in s.get("attrs", {}).items()
             if k not in hidden and not isinstance(v, list)}
        attr_txt = " ".join(f"{k}={v}" for k, v in sorted(a.items()))
        out.append(
            f"  {(float(s.get('t0', 0.0)) - t_base) * 1e3:>10.3f} "
            f"{float(s.get('dt', 0.0)) * 1e3:>10.3f} "
            f"{s.get('name', '?'):<20} {attr_txt}"[:120])
    return "\n".join(out)


# --------------------------------------------------- EXPLAIN (plans)

def _fmt_kv(d: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(d.items()))


def render_plan(attrs: dict) -> list[str]:
    """One ``sort.plan`` record as an EXPLAIN-ANALYZE-style tree:
    decision → inputs → prediction → actual → regret, one branch per
    registered decision (models/plan.py vocabulary)."""
    head = (f"plan algo={attrs.get('algo')} n={attrs.get('n')} "
            f"dtype={attrs.get('dtype')} ranks={attrs.get('ranks')} "
            f"regret={attrs.get('regret')}")
    tid = attrs.get(TRACE_ID_ATTR)
    if tid:
        head += f" trace_id={tid}"
    out = [head]
    profile = attrs.get("profile") or {}
    if profile:
        out.append(f"  profile: {_fmt_kv(profile)}")
    decisions = attrs.get("decisions") or {}
    names = sorted(decisions)
    for i, name in enumerate(names):
        d = decisions[name]
        if not isinstance(d, dict):
            continue
        branch = "└─" if i == len(names) - 1 else "├─"
        line = f"  {branch} {name:<8} chosen={d.get('chosen')}"
        if d.get("requested") is not None \
                and d.get("requested") != d.get("chosen"):
            line += f" (requested={d['requested']})"
        if d.get("trigger") is not None:
            line += f" trigger={d['trigger']}"
        line += f" regret={d.get('regret', 0)}"
        out.append(line)
        pad = "     " if i == len(names) - 1 else "  │  "
        if d.get("predicted"):
            out.append(f"  {pad}predicted: {_fmt_kv(d['predicted'])}")
        if d.get("actual"):
            out.append(f"  {pad}actual:    {_fmt_kv(d['actual'])}")
    return out


def explain_view(rows: list[dict], trace_id: str | None = None,
                 ) -> str | None:
    """The ``--explain`` surface (ISSUE 12).  With a ``trace_id``:
    render every plan that request produced (its own dispatch, or the
    packed dispatch it shared via ``batch_id``) as full decision trees.
    Without one: every plan in the files as trees PLUS the aggregate
    regret table per decision — mis-sized caps and wasted restages as
    one ranked summary.  None when no ``sort.plan`` span is present."""
    plans = [r for r in rows if r.get("kind") == "span"
             and r.get("name") == PLAN_SPAN]
    if trace_id is not None:
        batch_ids = {
            s["attrs"][BATCH_ID_ATTR]
            for s in rows
            if s.get("kind") == "span"
            and s.get("attrs", {}).get(TRACE_ID_ATTR) == trace_id
            and s.get("attrs", {}).get(BATCH_ID_ATTR) is not None}
        for s in rows:
            if s.get("kind") == "span" and s.get("name") == SERVE_BATCH_SPAN:
                tids = s.get("attrs", {}).get(BATCH_TRACE_IDS_ATTR) or []
                bid = s.get("attrs", {}).get(BATCH_ID_ATTR)
                if trace_id in tids and bid is not None:
                    batch_ids.add(bid)
        plans = [p for p in plans
                 if p.get("attrs", {}).get(TRACE_ID_ATTR) == trace_id
                 or p.get("attrs", {}).get(BATCH_ID_ATTR) in batch_ids]
    if not plans:
        return None
    out: list[str] = []
    for p in plans:
        out.extend(render_plan(p.get("attrs") or {}))
        out.append("")
    if trace_id is None and len(plans) > 1:
        from mpitest_tpu.models.plan import fold_decision_stats

        agg = fold_decision_stats([p.get("attrs") or {} for p in plans])
        out.append(f"aggregate regret over {len(plans)} plan(s)")
        out.append(f"  {'decision':<10} {'count':>6} {'mean':>10} "
                   f"{'max':>10}")
        for name, row in sorted(agg.items(),
                                key=lambda kv: -kv[1]["regret_sum"]):
            out.append(
                f"  {name:<10} {row['count']:>6} "
                f"{row['regret_sum'] / row['count']:>10.4f} "
                f"{row['regret_max']:>10.4f}")
        # planner policy census (ISSUE 14): which registered policies
        # the self-tuning planner chose across these plans, split by
        # whether it acted (on) or only logged (shadow)
        census: dict[tuple[str, bool], int] = {}
        for p in plans:
            d = ((p.get("attrs") or {}).get("decisions") or {}
                 ).get("planner")
            if not isinstance(d, dict):
                continue
            applied = bool((d.get("predicted") or {}).get("applied"))
            key = (str(d.get("chosen", "?")), applied)
            census[key] = census.get(key, 0) + 1
        if census:
            out.append("planner policies")
            for (pol, applied), cnt in sorted(census.items()):
                out.append(f"  {pol:<20} "
                           f"{'applied' if applied else 'shadow':<8} "
                           f"{cnt:>6}")
    return "\n".join(out).rstrip()


# --------------------------------------------- live metrics snapshots

def render_prom_snapshot(path: str, text: str,
                         slo_target: float = DEFAULT_SLO_TARGET_PCT,
                         ) -> str:
    """Render a scraped ``/metrics`` snapshot (Prometheus text) beside
    the span-derived tables — the "live mode" for state sampled from a
    RUNNING server instead of a finished trace file.  Includes the
    error-budget line computed from the request counters."""
    from mpitest_tpu.utils.metrics_live import parse_prom_text

    fams = parse_prom_text(text)
    out = [f"live metrics snapshot ({path})"]
    reqs = fams.get("sort_serve_requests_total")
    if reqs:
        by_status = {lbl.get("status", "?"): v
                     for name, lbl, v in reqs["samples"]}
        total = int(sum(by_status.values()))
        errs = int(sum(v for k, v in by_status.items() if k != "ok"))
        out.append("  requests " + ", ".join(
            f"{k}={int(v)}" for k, v in sorted(by_status.items())))
        eb = error_budget(total, errs, slo_target)
        burn = eb["budget_burn"]
        out.append(
            f"  error budget ({eb['slo_target_pct']}% target): "
            f"{eb['error_rate_pct']}% errors"
            + (f" -> burn {burn}x" if burn is not None else ""))
    lat = fams.get("sort_serve_request_latency_seconds")
    if lat:
        plain = {n: v for n, lbl, v in lat["samples"] if not lbl}
        cnt = plain.get("sort_serve_request_latency_seconds_count", 0)
        tot = plain.get("sort_serve_request_latency_seconds_sum", 0.0)
        if cnt:
            out.append(f"  latency mean {1e3 * tot / cnt:.3f} ms "
                       f"over {int(cnt)} request(s)")
    for name, label in (("sort_serve_inflight", "in flight"),
                        ("sort_serve_cache_hits_total", "cache hits"),
                        ("sort_serve_cache_misses_total", "cache misses"),
                        ("sort_retries_total", "dispatch retries"),
                        ("sort_verify_failures_total", "verify failures")):
        fam = fams.get(name)
        if fam and fam["samples"]:
            v = sum(v for _n, _l, v in fam["samples"])
            out.append(f"  {label}: {int(v)}")
    out.append(f"  families: {len(fams)}")
    return "\n".join(out)


# ------------------------------------------------------------ regression

def flag_regressions(current: dict, baseline_rows: list[dict],
                     threshold: float, host: str) -> list[dict]:
    """Compare the aggregated ``current["metrics"]`` against pinned
    baseline bench rows.  Higher is better (every repo metric is a
    throughput/ratio); a current value below ``threshold * pinned`` is a
    regression.  A baseline row with a ``host`` fingerprint that does
    not match this machine is reported as skipped, never compared."""
    findings = []
    for row in baseline_rows:
        if row.get("kind", "bench") != "bench":
            continue
        name = row["metric"]
        pinned = float(row["value"])
        row_host = row.get("host")
        if row_host and row_host != host:
            findings.append({"metric": name, "status": "skipped",
                             "reason": f"host mismatch (pinned on "
                                       f"{row_host!r})"})
            continue
        cur = current["metrics"].get(name)
        if cur is None or cur.get("value") is None:
            findings.append({"metric": name, "status": "missing",
                             "reason": "no current row for pinned metric"})
            continue
        # devices provenance (ISSUE 7): a row pinned at devices=8 only
        # gates a devices=8 measurement — a 1-device run "regressing"
        # against an 8-chip pin is a topology difference, not a
        # regression (and vice versa).
        row_dev = row.get("devices")
        if row_dev is not None and cur.get("devices") != row_dev:
            findings.append({"metric": name, "status": "skipped",
                             "reason": f"devices mismatch (pinned at "
                                       f"devices={row_dev}, current="
                                       f"{cur.get('devices')})"})
            continue
        val = float(cur["value"])
        if pinned > 0 and val < threshold * pinned:
            findings.append({"metric": name, "status": "REGRESSION",
                             "current": val, "pinned": pinned,
                             "ratio": round(val / pinned, 3)})
        else:
            findings.append({"metric": name, "status": "ok",
                             "current": val, "pinned": pinned,
                             "ratio": round(val / pinned, 3)
                             if pinned else None})
        # decision drift (ISSUE 12): a row that pinned its plan digest
        # also pins the DECISIONS behind the number — same throughput
        # from a different algo/cap/restage is drift worth flagging
        # even when no throughput gate fires.
        for key in ("restaged", "negotiated_cap", "plan_regret"):
            if key not in row:
                continue
            cur_v, pin_v = cur.get(key), row[key]
            if cur_v is None:
                findings.append({"metric": f"{name}.{key}",
                                 "status": "missing",
                                 "reason": "pinned plan field absent "
                                           "from the current row"})
            elif key == "restaged":
                if bool(cur_v) != bool(pin_v):
                    findings.append({
                        "metric": f"{name}.{key}", "status": "DRIFT",
                        "reason": f"restage decision flipped "
                                  f"(pinned {bool(pin_v)}, "
                                  f"current {bool(cur_v)})"})
            elif key == "plan_regret":
                # lower is better, and a clean pin of 0.0 must still
                # gate later regret — compare against pin-or-floor
                floor = max(float(pin_v), PLAN_REGRET_FLOOR)
                if float(cur_v) > floor / threshold:
                    findings.append({
                        "metric": f"{name}.{key}", "status": "DRIFT",
                        "reason": f"pinned {pin_v}, current {cur_v} "
                                  f"(allowed <= {floor / threshold:.3g})"})
            elif float(pin_v) > 0 and not (
                    threshold * float(pin_v) <= float(cur_v)
                    <= float(pin_v) / threshold):
                findings.append({
                    "metric": f"{name}.{key}", "status": "DRIFT",
                    "reason": f"pinned {pin_v}, current {cur_v} "
                              f"({float(cur_v) / float(pin_v):.2f}x)"})
    return findings


# ----------------------------------------------------------------- check

def check_rows(rows: list[dict]) -> list[str]:
    """Schema violations in loaded rows (empty list = clean).  This is
    the contract `make telemetry-selftest` enforces on both the
    SORT_TRACE stream and the COMM_STATS record."""
    errors = []
    spans_by_id: dict[tuple, dict] = {}
    for obj in rows:
        where = f"{obj.get('_path', obj.get('path'))}:{obj.get('_line', obj.get('line'))}"
        kind = obj.get("kind")
        if kind == "invalid":
            errors.append(f"{where}: {obj['error']}")
        elif kind == "span":
            for key in ("name", "id", "t0", "dt", "attrs"):
                if key not in obj:
                    errors.append(f"{where}: span missing {key!r}")
            if "attrs" in obj and not isinstance(obj["attrs"], dict):
                errors.append(f"{where}: span attrs must be an object")
            if isinstance(obj.get("dt"), (int, float)) and obj["dt"] < 0:
                errors.append(f"{where}: span dt < 0")
            spans_by_id[(obj.get("_path"), obj.get("id"))] = obj
        elif kind == "comm_stats":
            if not isinstance(obj.get("ranks"), int) or obj["ranks"] < 1:
                errors.append(f"{where}: comm_stats needs integer ranks >= 1")
            cols = obj.get("collectives")
            if not isinstance(cols, dict) or not cols:
                errors.append(f"{where}: comm_stats needs a non-empty "
                              "collectives object")
                continue
            for cname, c in cols.items():
                if not isinstance(c, dict):
                    errors.append(f"{where}: collective {cname!r} must be "
                                  "an object")
                    continue
                for key in ("calls", "bytes", "seconds"):
                    if key not in c:
                        errors.append(f"{where}: collective {cname!r} "
                                      f"missing {key!r}")
        elif kind == "unknown":
            errors.append(f"{where}: unrecognized record shape")
    # span parent links must resolve within the same stream
    for (path, _), obj in spans_by_id.items():
        parent = obj.get("parent")
        if parent is not None and (path, parent) not in spans_by_id:
            errors.append(f"{path}: span id={obj.get('id')} has dangling "
                          f"parent {parent}")
    return errors


# ---------------------------------------------------------------- tables

def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024 or unit == "GiB":
            return f"{b:.1f}{unit}" if unit != "B" else f"{int(b)}B"
        b /= 1024
    return f"{b:.1f}GiB"


def render(agg: dict, slo_target: float = DEFAULT_SLO_TARGET_PCT) -> str:
    out = []
    if agg["phases"]:
        out.append("per-phase wall time")
        out.append(f"  {'phase':<16} {'ms':>12} {'count':>7}")
        for name, p in sorted(agg["phases"].items(),
                              key=lambda kv: -kv[1]["ms"]):
            out.append(f"  {name:<16} {p['ms']:>12.3f} {p['count']:>7}")
    if agg["collectives"]:
        out.append("")
        out.append("per-collective traffic (comm.h vocabulary)")
        out.append(f"  {'source':<18} {'collective':<12} {'calls':>7} "
                   f"{'bytes':>12} {'seconds':>11}")
        for source in sorted(agg["collectives"]):
            for cname, c in sorted(agg["collectives"][source].items()):
                out.append(
                    f"  {source:<18} {cname:<12} {c['calls']:>7} "
                    f"{_fmt_bytes(c['bytes']):>12} {c['seconds']:>11.6f}")
    if agg.get("ingest"):
        out.append("")
        out.append("ingest/egress pipeline (streamed host↔device)")
        out.append(f"  {'stage':<18} {'seconds':>11} {'count':>7} "
                   f"{'bytes':>12} {'GB/s':>8}")
        for name, r in sorted(agg["ingest"].items()):
            gbs = (r["bytes"] / r["seconds"] / 1e9) if r["seconds"] else 0.0
            out.append(f"  {name:<18} {r['seconds']:>11.6f} {r['count']:>7} "
                       f"{_fmt_bytes(r['bytes']):>12} {gbs:>8.2f}")
        for label, key in (("ingest parse/encode ∩ transfer",
                            "ingest_overlap"),
                           ("egress decode ∩ fetch", "egress_overlap")):
            ov = agg.get(key)
            if ov:
                out.append(
                    f"  {label} overlap: {ov['overlap_s']:.6f}s "
                    f"({ov['pct']:.1f}% of {ov['transfer_s']:.6f}s transfer)")
        # ISSUE 6 telemetry: the engine that encoded, its measured
        # throughput, and the end-to-end ratio (when recorded)
        engines = agg.get("encode_engines") or []
        if engines:
            out.append(f"  encode engine: {', '.join(engines)}")
        for mname, label in (("encode_gb_per_s", "encode throughput"),
                             ("encode_speedup", "native-vs-python encode"),
                             ("ingest_ratio", "incl-ingest / sort ratio")):
            m = agg["metrics"].get(mname)
            if m and m.get("value") is not None:
                unit = m.get("unit") or ""
                out.append(f"  {label}: {m['value']} {unit}".rstrip())
    so = agg.get("scaleout") or {}
    pairs = scaleout_throughput(agg["metrics"])
    if so.get("balance") or so.get("restages") or any(
            p.get("p8") for p in pairs):
        out.append("")
        out.append("scale-out (negotiated exchange + P=1 vs P=8)")
        for b in so.get("balance", []):
            neg, worst = b.get("negotiated_cap"), b.get("worst_cap")
            saving = (f" ({100.0 * (1 - neg / worst):.1f}% below worst-case "
                      f"{worst})" if neg and worst else "")
            out.append(
                f"  {b.get('algorithm', '?'):<7} ranks={b.get('ranks', '?')}"
                f" negotiated cap {neg}{saving}; recv max/mean "
                f"{b.get('recv_ratio')}x, peer/fair {b.get('peer_ratio')}x"
                + (" [re-staged]" if b.get("restaged") else "")
                + ("" if b.get("exact") else " [estimate]"))
        for p in pairs:
            if not p.get("p8"):
                continue
            p1 = (f"P=1 {p['p1']['value']} Mkeys/s (2^{p['p1']['log2n']})"
                  if p.get("p1") else "P=1 (no row)")
            line = (f"  throughput {p['algo']}/{p['dtype']}: {p1} vs "
                    f"P=8 {p['p8']['value']} Mkeys/s (2^{p['p8']['log2n']})")
            if "speedup" in p:
                line += f" -> {p['speedup']}x"
            out.append(line)
        if so.get("restages"):
            out.append(f"  skew re-stages: {so['restages']}")
    slo = serve_slo(agg.get("serve") or {}, slo_target)
    if slo:
        out.append("")
        out.append("sort-as-a-service (serve.* spans — request latency SLO)")
        out.append(f"  requests {slo['requests']} (ok {slo['ok']}, "
                   f"batched {slo['batched']}, {slo['keys']} keys)"
                   + ("; errors " + ", ".join(
                       f"{k}={v}" for k, v in sorted(slo["errors"].items()))
                      if slo["errors"] else ""))
        out.append(f"  latency p50 {slo['p50_ms']} ms, "
                   f"p99 {slo['p99_ms']} ms, mean {slo['mean_ms']} ms")
        if slo["requests"]:
            burn = slo["budget_burn"]
            out.append(
                f"  error budget ({slo['slo_target_pct']}% target): "
                f"{slo['error_rate_pct']}% errors"
                + (f" -> burn {burn}x" if burn is not None else ""))
        if slo["batches"]:
            segs = slo["batch_segments"] / slo["batches"]
            out.append(f"  batches {slo['batches']} "
                       f"({segs:.1f} segments/dispatch)")
        out.append(f"  executor cache: {slo['cache_hits']} hits, "
                   f"{slo['cache_misses']} misses "
                   f"({slo['compile_s']}s compiling)")
        # request-lifecycle robustness lines (ISSUE 11), only when the
        # events occurred — a clean run's table stays byte-unchanged
        if slo["deadline_expired"]:
            out.append("  deadlines expired pre-dispatch: " + ", ".join(
                f"{stage}={n}" for stage, n in
                sorted(slo["deadline_expired"].items())))
        if slo["watchdog"]:
            out.append("  watchdog: " + ", ".join(
                f"{ev}={n}" for ev, n in sorted(slo["watchdog"].items())))
        if slo["hedges"]:
            out.append(f"  client hedges: {slo['hedges']}")
    rb = agg.get("robustness") or {}
    if any(rb.get(k) for k in ("faults", "retries", "verify_runs")):
        out.append("")
        out.append("robustness (supervisor + verifier events)")
        out.append(f"  verify runs {rb['verify_runs']}, "
                   f"failures {rb['verify_failures']}; "
                   f"dispatch retries {rb['retries']}; "
                   f"faults injected {rb['faults']}"
                   + (" (" + ", ".join(f"{s}={c}" for s, c in
                                       sorted(rb["fault_sites"].items()))
                      + ")" if rb["fault_sites"] else ""))
    if agg["metrics"]:
        out.append("")
        out.append("metrics (latest row per name)")
        for name, m in sorted(agg["metrics"].items()):
            unit = m.get("unit") or ""
            out.append(f"  {name:<40} {m.get('value')} {unit}")
    if agg["spans"]:
        out.append("")
        out.append("span census: " + ", ".join(
            f"{n}={c}" for n, c in sorted(agg["spans"].items())))
    if agg.get("tooling"):
        out.append("")
        out.append("tooling state (lint/sanitizer gates of the bench rows): "
                   + ", ".join(f"{k}={v}" for k, v in
                               sorted(agg["tooling"].items())))
    return "\n".join(out) if out else "(no telemetry rows)"


# ------------------------------------------------------------------ main

def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpitest_tpu.report",
        description="Aggregate mpitest_tpu telemetry JSONL (SORT_TRACE "
                    "spans, COMM_STATS, SORT_METRICS, bench rows); flag "
                    "regressions against a pinned baseline.")
    ap.add_argument("files", nargs="*",
                    help="JSONL files (default: bench/BASELINE_RESULTS.jsonl"
                         " when present)")
    ap.add_argument("--check", action="store_true",
                    help="schema-validate the files; exit 1 on violations")
    ap.add_argument("--require-registered-spans", action="store_true",
                    help="with --check: also fail on span names outside "
                         "the registered schema (utils/span_schema.py) — "
                         "the telemetry-selftest gate that makes a "
                         "renamed span a loud failure instead of a "
                         "silently thinner report")
    ap.add_argument("--require-ingest-overlap", action="store_true",
                    help="exit 1 unless the ingest.* spans show nonzero "
                         "parse/encode ∩ transfer overlap (the `make "
                         "ingest-selftest` gate: proves the pipeline "
                         "genuinely overlapped host work with DMA in "
                         "this run; egress overlap does not count)")
    ap.add_argument("--baseline",
                    help="pinned baseline JSONL of bench rows; regressions "
                         "exit 2")
    ap.add_argument("--threshold", type=float, default=0.9,
                    help="regression threshold: flag when current < "
                         "THRESHOLD * pinned (default 0.9)")
    ap.add_argument("--trace-id",
                    help="live mode (ISSUE 10): reconstruct ONE request "
                         "end-to-end from its trace id — queue wait, "
                         "batch membership, dispatch, verify and reply "
                         "spans as a timeline; exit 1 when no span "
                         "carries the id")
    ap.add_argument("--explain", nargs="?", const="", default=None,
                    metavar="TRACE_ID|FILE",
                    help="plan provenance (ISSUE 12): render sort.plan "
                         "decision records as EXPLAIN-ANALYZE-style "
                         "trees (decision → prediction → actual → "
                         "regret).  The optional value is a trace id "
                         "(one request's plans) or a span file to read; "
                         "bare --explain renders every plan in the "
                         "given files plus the aggregate regret table. "
                         "Combine with --trace-id to scope; exit 1 when "
                         "no plan matches")
    ap.add_argument("--doctor", nargs="?", const="", default=None,
                    metavar="TRACE_ID|FILE",
                    help="sort doctor (ISSUE 16): diagnose known "
                         "pathologies over the trace — skew, cap "
                         "thrash, compile storms, window misfit, "
                         "spill-bound merges, verify overhead, breaker "
                         "flap, SLO burn — each finding citing its "
                         "evidence spans and the knob to turn.  The "
                         "optional value is a trace id (one request) "
                         "or a span file to read; exit 1 when the "
                         "files carry no spans.  Rule vocabulary: "
                         "mpitest_tpu/doctor.py DOCTOR_RULES")
    ap.add_argument("--prom", action="append", default=[],
                    metavar="FILE",
                    help="live mode: render a scraped /metrics snapshot "
                         "(Prometheus text exposition) beside the tables, "
                         "including the error-budget line")
    ap.add_argument("--slo-target", type=float,
                    default=DEFAULT_SLO_TARGET_PCT,
                    help="availability target (%%) the error-budget/"
                         "burn-rate line is computed against "
                         f"(default {DEFAULT_SLO_TARGET_PCT})")
    args = ap.parse_args(argv)

    files = list(args.files)
    explain_tid: str | None = None
    if args.explain is not None and args.explain:
        # the one optional value is either a span FILE or a trace id;
        # a path-shaped value that does not exist is a missing file,
        # not a trace id (trace ids are [A-Za-z0-9_-]{1,64} — they can
        # never contain a slash or a .jsonl suffix)
        if Path(args.explain).exists():
            files.append(args.explain)
        elif "/" in args.explain or args.explain.endswith(".jsonl"):
            print(f"[ERROR] --explain: {args.explain}: no such file",
                  file=sys.stderr)
            return 1
        else:
            explain_tid = args.explain
    doctor_tid: str | None = None
    if args.doctor is not None and args.doctor:
        # same file-vs-trace-id disambiguation as --explain
        if Path(args.doctor).exists():
            files.append(args.doctor)
        elif "/" in args.doctor or args.doctor.endswith(".jsonl"):
            print(f"[ERROR] --doctor: {args.doctor}: no such file",
                  file=sys.stderr)
            return 1
        else:
            doctor_tid = args.doctor
    if not files and not args.prom:
        default = Path("bench/BASELINE_RESULTS.jsonl")
        if default.exists():
            files = [str(default)]
        else:
            ap.error("no files given and bench/BASELINE_RESULTS.jsonl "
                     "not found")
    rows: list[dict] = []
    for f in files:
        try:
            rows.extend(load_rows(f))
        except OSError as e:
            print(f"[ERROR] {f}: {e}", file=sys.stderr)
            return 1

    if args.explain is not None:
        tid = explain_tid or args.trace_id
        view = explain_view(rows, tid)
        if view is None:
            where = f" for trace_id {tid!r}" if tid else ""
            print(f"[ERROR] no sort.plan span{where} across "
                  f"{len(files)} file(s) (SORT_PLAN=off, or the run "
                  "predates plan provenance)", file=sys.stderr)
            return 1
        print(view)
        return 0

    if args.doctor is not None:
        tid = doctor_tid or args.trace_id
        span_rows = [r for r in rows if r.get("kind") == "span"]
        if tid:
            span_rows = [r for r in span_rows
                         if (r.get("attrs") or {}).get(
                             span_schema.TRACE_ID_ATTR) == tid]
        if not span_rows:
            where = f" carrying trace_id {tid!r}" if tid else ""
            print(f"[ERROR] --doctor: no spans{where} across "
                  f"{len(files)} file(s)", file=sys.stderr)
            return 1
        # lazy: the doctor is import-light but the timeline fold pulls
        # the span layer; neither belongs on the other report paths
        from mpitest_tpu import doctor as doctor_mod
        from mpitest_tpu.utils import timeline
        ev = doctor_mod.evidence_from_rows(
            span_rows, timeline=timeline.build_timeline(span_rows))
        ev["slo_target_pct"] = args.slo_target
        findings = doctor_mod.diagnose(ev)
        print(doctor_mod.render(findings))
        # a diagnosis is a report, not a gate — findings exit 0
        return 0

    if args.trace_id is not None:
        view = trace_view(rows, args.trace_id)
        if view is None:
            print(f"[ERROR] no span carries trace_id {args.trace_id!r} "
                  f"across {len(files)} file(s)", file=sys.stderr)
            return 1
        print(view)
        return 0

    # each gate runs standalone — --require-registered-spans without
    # --check must still check (a gate that silently skips is worse
    # than no gate)
    errors = check_rows(rows) if args.check else []
    if args.require_registered_spans:
        for r in rows:
            if (r.get("kind") == "span"
                    and not span_schema.is_registered(r.get("name", "?"))):
                errors.append(
                    f"{r.get('_path')}:{r.get('_line')}: span name "
                    f"{r.get('name')!r} is not in the registered "
                    "schema (utils/span_schema.py)")
    if errors:
        for e in errors:
            print(f"[ERROR] {e}", file=sys.stderr)
        return 1
    if args.check or args.require_registered_spans:
        n_spans = sum(1 for r in rows if r.get("kind") == "span")
        n_stats = sum(1 for r in rows if r.get("kind") == "comm_stats")
        print(f"telemetry check OK: {len(rows)} rows "
              f"({n_spans} spans, {n_stats} comm_stats) across "
              f"{len(files)} file(s)")
        if not args.require_ingest_overlap:
            return 0

    agg = aggregate(rows)
    if args.require_ingest_overlap:
        ov = agg["ingest_overlap"]
        if not ov or ov["overlap_s"] <= 0:
            print("[ERROR] ingest spans show NO parse/encode ∩ transfer "
                  "overlap — the pipeline ran serially (or no ingest.* "
                  "spans were emitted)", file=sys.stderr)
            return 1
        print(f"ingest overlap OK: {ov['overlap_s']:.6f}s "
              f"({ov['pct']:.1f}% of transfer)")
        # ISSUE 6: when a run recorded its end-to-end ratio, re-check
        # the 0.5x gate here — the selftest's artifacts must not say
        # one thing while the gate says another.
        m = agg["metrics"].get("ingest_ratio")
        if m and m.get("value") is not None:
            ratio = float(m["value"])
            if ratio < INGEST_RATIO_GATE:
                print(f"[ERROR] recorded ingest_ratio {ratio} < "
                      f"{INGEST_RATIO_GATE} (sort_incl_ingest fell below "
                      "half the raw sort throughput)", file=sys.stderr)
                return 1
            print(f"ingest ratio OK: {ratio} >= {INGEST_RATIO_GATE}")
    print(render(agg, args.slo_target))
    for prom_file in args.prom:
        try:
            text = Path(prom_file).read_text()
        except OSError as e:
            print(f"[ERROR] {prom_file}: {e}", file=sys.stderr)
            return 1
        try:
            print("\n" + render_prom_snapshot(prom_file, text,
                                              args.slo_target))
        except ValueError as e:
            print(f"[ERROR] {prom_file}: {e}", file=sys.stderr)
            return 1

    if args.baseline:
        from mpitest_tpu.utils.platform import host_fingerprint

        try:
            baseline_rows = load_rows(args.baseline)
        except OSError as e:
            print(f"[ERROR] {args.baseline}: {e}", file=sys.stderr)
            return 1
        findings = flag_regressions(agg, baseline_rows, args.threshold,
                                    host_fingerprint())
        print("\nbaseline comparison "
              f"(threshold {args.threshold:g}, host {host_fingerprint()!r})")
        bad = False
        for f in findings:
            if f["status"] == "REGRESSION":
                bad = True
                print(f"  REGRESSION {f['metric']}: {f['current']} vs "
                      f"pinned {f['pinned']} ({f['ratio']}x)")
            elif f["status"] == "ok":
                print(f"  ok         {f['metric']}: {f['current']} vs "
                      f"pinned {f['pinned']} ({f['ratio']}x)")
            else:
                print(f"  {f['status']:<10} {f['metric']}: {f['reason']}")
        if bad:
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
