"""Out-of-core external sort: partition → device-sort → spill → k-way merge.

The in-memory path is bounded by device/host memory; this driver is
bounded by disk.  The input partitions into ``SORT_MEM_BUDGET``-sized
chunks; each chunk rides the ordinary **verified** device sort
(``models/api.sort`` for keys, the record argsort-gather for
key+payload) and spills to a sorted run (``store/runs.py``: SORTBIN1
framing + fingerprint sidecar); the runs then stream through the
bounded k-way merge (``store/merge.py``), at most ``SORT_MERGE_FANIN``
at a time (more runs merge in passes through intermediate runs, each
written through the streaming run writer — no pass materializes its
output).

The budget is deliberately forceable far below real memory, so the
whole spill/merge machinery is exercised on a laptop-sized dataset in
CI (``make external-selftest``); on real hardware the same knob makes
dataset size a disk limit.

Integrity ladder (the external twin of the supervisor ladder):

1. every chunk sort is already supervised + fingerprint-verified;
2. every run carries a sidecar folded before its bytes hit disk; the
   merge re-folds each run on read-back and raises a typed
   :class:`~mpitest_tpu.store.merge.RunIntegrityError` naming a bad
   run (the ``spill_corrupt`` shape);
3. the merged output is folded chunk-by-chunk and compared against the
   COMBINED run sidecars (count + per-word XOR/sum + record mix) with
   a boundary-inclusive sortedness sweep — silent merge truncation
   (the ``merge_drop`` shape) trips here;
4. a tripped check re-spills exactly the blamed slices from the source
   and re-merges (one recovery round, ``external.recover`` event +
   ``sort_external_recoveries_total``); a second failure raises the
   typed ``SortIntegrityError`` — never silent wrong bytes.

Durability (ISSUE 18): a caller-supplied ``dataset`` id opts the sort
into the crash-durable path — every spilled run commits via write-temp
→ fsync → ``os.replace`` → fsync(dir) and is journaled in an
append-only manifest (``store/manifest.py``), so completed runs + the
journal ARE a checkpoint: a killed process (or a retried spilled serve
request) replays the manifest, re-validates every committed run and
re-enters at the merge phase instead of re-sorting.  The startup GC
(:func:`gc_spill_dir`) reclaims age-gated orphans no live manifest
references, and a mid-sort ``ENOSPC`` surfaces as the typed
:class:`SpillCapacityError` with partial outputs deleted.

Telemetry: registered ``external.run`` / ``external.merge`` /
``external.resume`` / ``external.gc`` spans (+ the
``external.recover`` event) ride the ordinary span stream and feed
the ``sort_external_*`` live metrics through the span bridge; the plan
record (ISSUE 12) grows an ``external`` decision so ``--explain`` and
the serve plan digest (``spilled: true`` / ``resumed: true``) name the
tier that ran.
"""

from __future__ import annotations

import errno
import os
import tempfile
import time
from dataclasses import dataclass
from itertools import islice
from typing import Any, Callable, Iterator

import numpy as np

from mpitest_tpu.models import plan as plan_mod
from mpitest_tpu.models.supervisor import SortIntegrityError
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.store import aio
from mpitest_tpu.store import manifest as mfstlib
from mpitest_tpu.store import merge as mergelib
from mpitest_tpu.store import runs as runlib
from mpitest_tpu.utils import knobs

#: Host-memory multiplier per record during partition/sort: the raw
#: chunk + its encoded words + the device copy + sort working set.
#: chunk_elems = budget // (SPILL_FACTOR * record_bytes).
SPILL_FACTOR = 4

#: Floor on chunk/buffer sizes — below this the per-chunk overheads
#: (dispatch, syscalls) dominate and the budget arithmetic is noise.
MIN_CHUNK_ELEMS = 1 << 10

#: Recovery budget: full merge attempts before the typed error.
MERGE_ATTEMPTS = 2

#: Spill-artifact suffixes the orphan GC may reclaim (age-gated,
#: manifest-referenced files excluded) — run files, staging files,
#: durable-commit temps, and journals themselves.
GC_SUFFIXES = (".run", ".runz", ".pay", ".fpr.json", ".spill", ".tmp",
               mfstlib.MANIFEST_SUFFIX)


class SpillCapacityError(OSError):
    """The spill volume ran out of space mid-sort (a real — or injected
    ``spill_enospc`` — ``ENOSPC`` during a run/merge/staging write).
    Partial outputs are deleted before this raises; the serve tier maps
    it to the typed retryable ``backpressure`` rejection, mirroring the
    admission-time ``bytes`` bound — never an untyped 500."""

    def __init__(self, detail: str) -> None:
        super().__init__(errno.ENOSPC, detail)


@dataclass
class ExternalResult:
    """Outcome of one external sort."""

    n: int
    dtype: np.dtype
    payload_width: int
    runs: int                 # spill runs written by the partition pass
    disk_bytes: int           # bytes spilled (initial runs)
    merge_passes: int         # k-way passes (1 = single final pass)
    recoveries: int           # integrity recoveries taken
    keys: np.ndarray | None = None        # sink="array"
    payload: np.ndarray | None = None     # sink="array", records only
    out_run: "runlib.RunInfo | None" = None   # sink="file"
    #: runs re-validated from a journaled manifest instead of being
    #: re-sorted (ISSUE 18 crash resume; 0 = cold run)
    resumed_runs: int = 0
    #: logical bytes / spilled bytes of the partition runs (ISSUE 20):
    #: > 1.0 when SORTRUN2 compression shrank the spill, 0.0 when
    #: nothing spilled
    spill_ratio: float = 0.0
    #: fraction of the final merge's disk time that overlapped its
    #: compute (read-ahead/write-behind concurrency; 0.0 = synchronous)
    disk_overlap: float = 0.0


def _budget() -> int:
    return int(knobs.get("SORT_MEM_BUDGET"))


def _fanin() -> int:
    return int(knobs.get("SORT_MERGE_FANIN"))


def resolve_spill_dir(spill_dir: str | None = None) -> str:
    """The spill staging directory: the explicit argument, else
    ``SORT_SPILL_DIR``, else a fresh per-process temp dir."""
    d = spill_dir or knobs.get("SORT_SPILL_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"mpitest_spill_{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


def spill_chunk_elems(budget: int, dtype: np.dtype,
                      payload_width: int = 0) -> int:
    """Records per partition chunk under ``budget`` bytes."""
    rec = int(np.dtype(dtype).itemsize) + int(payload_width)
    return max(MIN_CHUNK_ELEMS, budget // max(1, SPILL_FACTOR * rec))


def merge_chunk_elems(budget: int, dtype: np.dtype, payload_width: int,
                      n_runs: int) -> int:
    """Records per per-run read-ahead buffer during a merge of
    ``n_runs`` runs: the buffers plus one output round must fit the
    budget."""
    rec = int(np.dtype(dtype).itemsize) + int(payload_width)
    per_run = budget // max(1, SPILL_FACTOR * rec * (n_runs + 2))
    return max(MIN_CHUNK_ELEMS, per_run)


def _sort_chunk(keys: np.ndarray, pay: np.ndarray | None,
                algorithm: str, mesh: Any, tracer: Any,
                ) -> tuple[np.ndarray, np.ndarray | None]:
    """One supervised, verified device sort of a partition chunk."""
    from mpitest_tpu.models import api

    if pay is not None:
        out_k, out_p = api.sort(keys, algorithm=algorithm, mesh=mesh,
                                tracer=tracer, payload=pay)
        return out_k, out_p
    return api.sort(np.asarray(keys), algorithm=algorithm, mesh=mesh,
                    tracer=tracer), None


def _spans(tracer: Any):
    return tracer.spans if tracer is not None else None


def _spill_one(idx: int, keys: np.ndarray, pay: np.ndarray | None,
               spill_dir: str, algorithm: str, mesh: Any, tracer: Any,
               durable: bool = False) -> "runlib.RunInfo":
    t0 = time.perf_counter()
    out_k, out_p = _sort_chunk(keys, pay, algorithm, mesh, tracer)
    info = runlib.write_run(spill_dir, f"r{os.getpid():x}_{idx:05d}",
                            out_k, out_p, durable=durable)
    spans = _spans(tracer)
    if spans is not None:
        spans.record("external.run", t0, time.perf_counter() - t0,
                     run=idx, n=info.n, bytes=info.disk_bytes,
                     dtype=info.dtype.name,
                     payload_width=info.payload_width)
    return info


def _merge_level(level: "list[runlib.RunInfo]", spill_dir: str,
                 budget: int, fanin: int, dtype: np.dtype, width: int,
                 pass_idx: int, tracer: Any) -> "list[runlib.RunInfo]":
    """One fan-in-bounded intermediate pass: groups of ``fanin`` runs
    merge into one run each, streamed through the run writer."""
    out: list[runlib.RunInfo] = []
    for gi in range(0, len(level), fanin):
        group = level[gi:gi + fanin]
        if len(group) == 1:
            out.append(group[0])
            continue
        t0 = time.perf_counter()
        ch = merge_chunk_elems(budget, dtype, width, len(group))
        w = runlib.RunStreamWriter(
            spill_dir, f"m{os.getpid():x}_{pass_idx}_{gi:05d}",
            dtype, width)
        # async IO engine (ISSUE 20): per-run read-ahead decode +
        # write-behind encode, so the pass's disk time overlaps its
        # merge compute instead of alternating with it
        io = aio.MergeIO()
        wb = io.wrap_writer(w)
        try:
            for kws, pws in mergelib.merge_runs(group, ch, io=io):
                wb.append_words(kws, pws)
            info = wb.close()
        except BaseException:
            # an ENOSPC (or integrity failure) mid-pass must not leak
            # the half-written intermediate run
            wb.abort()
            raise
        finally:
            io.close()
        iostats = io.stats(t0, time.perf_counter())
        spans = _spans(tracer)
        if spans is not None:
            spans.record("external.merge", t0,
                         time.perf_counter() - t0,
                         runs=len(group), n=info.n,
                         bytes=info.disk_bytes, final=False,
                         merge_pass=pass_idx,
                         disk_overlap=iostats["disk_overlap"],
                         disk_busy_s=iostats["disk_busy_s"],
                         overlap_s=iostats["overlap_s"])
        out.append(info)
    return out


def external_sort(
    x: Any,
    payload: Any = None,
    *,
    algorithm: str = "radix",
    mesh: Any = None,
    tracer: Any = None,
    budget: int | None = None,
    spill_dir: str | None = None,
    fanin: int | None = None,
    sink: "str | Callable[[np.ndarray, np.ndarray | None], None]" = "array",
    out_name: str = "merged",
    dataset: str | None = None,
) -> ExternalResult:
    """Externally sort host keys ``x`` (optionally with per-record
    ``payload`` bytes) under a byte ``budget`` (default
    ``SORT_MEM_BUDGET``; must be > 0 — the external path never engages
    implicitly).

    ``dataset`` (ISSUE 18) opts the sort into the crash-durable path:
    every spilled run commits durably and is journaled in a manifest
    keyed by the id, and a retried/restarted sort of the same dataset
    replays the journal, re-validates the committed runs and re-enters
    at the merge phase instead of re-sorting (``SORT_RESUME=off``
    disables both halves).

    ``sink`` selects where the merged output goes: ``"array"``
    materializes ``result.keys`` (+ ``result.payload``) — bit-identical
    to the in-memory sort; ``"file"`` streams it into one output run
    (``result.out_run``) so even the result never lives in host memory
    (the serve spill tier's reply source); a callable receives each
    decoded ``(keys_chunk, payload_chunk | None)`` in order (the CLI's
    streamed median probe)."""
    from mpitest_tpu.models.records import as_payload_matrix

    keys = np.asarray(x).reshape(-1)
    dtype = np.dtype(keys.dtype)
    n = int(keys.size)
    pay = as_payload_matrix(payload, n) if payload is not None else None
    width = int(pay.shape[1]) if pay is not None else 0

    def chunks(chunk_elems: int) -> Iterator[
            tuple[np.ndarray, np.ndarray | None]]:
        for off in range(0, n, chunk_elems):
            yield (keys[off:off + chunk_elems],
                   pay[off:off + chunk_elems] if pay is not None else None)

    return _external_core(chunks, n, dtype, width, algorithm=algorithm,
                          mesh=mesh, tracer=tracer, budget=budget,
                          spill_dir=spill_dir, fanin=fanin, sink=sink,
                          out_name=out_name, dataset=dataset)


def external_sort_file(
    path: str,
    dtype: Any = np.int32,
    *,
    algorithm: str = "radix",
    mesh: Any = None,
    tracer: Any = None,
    budget: int | None = None,
    spill_dir: str | None = None,
    fanin: int | None = None,
    sink: "str | Callable[[np.ndarray, np.ndarray | None], None]" = "array",
    out_name: str = "merged",
    sink_factory: Any = None,
    dataset: str | None = None,
) -> ExternalResult:
    """External sort of a key FILE — SORTBIN1 or reference text —
    without ever materializing it: chunks stream through
    ``utils/io.iter_key_chunks`` (mmap slices for binary; the threaded
    token-safe block parser for text) straight into spill runs, so a
    text input larger than ``SORT_MEM_BUDGET`` peaks at chunk-sized
    host memory instead of the whole file (the PR 2 documented
    limitation, closed for the external path)."""
    from mpitest_tpu.utils import io as kio

    dtype = np.dtype(dtype)

    def chunks(chunk_elems: int) -> Iterator[
            tuple[np.ndarray, np.ndarray | None]]:
        for c in kio.iter_key_chunks(path, dtype,
                                     chunk_elems=chunk_elems):
            yield c, None

    return _external_core(chunks, None, dtype, 0, algorithm=algorithm,
                          mesh=mesh, tracer=tracer, budget=budget,
                          spill_dir=spill_dir, fanin=fanin, sink=sink,
                          out_name=out_name, sink_factory=sink_factory,
                          dataset=dataset)


def _external_core(
    chunks_fn: Callable[[int], Iterator[tuple[np.ndarray,
                                              np.ndarray | None]]],
    n_hint: int | None,
    dtype: np.dtype,
    width: int,
    *,
    algorithm: str,
    mesh: Any,
    tracer: Any,
    budget: int | None,
    spill_dir: str | None,
    fanin: int | None,
    sink: "str | Callable[[np.ndarray, np.ndarray | None], None]",
    out_name: str,
    sink_factory: "Callable[[int], Callable[[np.ndarray, np.ndarray | None], None]] | None" = None,
    dataset: str | None = None,
) -> ExternalResult:
    from mpitest_tpu.utils.trace import Tracer

    tracer = tracer or Tracer()
    trace_path = knobs.get("SORT_TRACE")
    if trace_path and tracer.spans.stream_path is None:
        tracer.spans.stream_path = trace_path
    budget = _budget() if budget is None else int(budget)
    if budget <= 0:
        raise ValueError(
            "external sort needs a positive byte budget "
            "(SORT_MEM_BUDGET or the budget= argument)")
    fanin = _fanin() if fanin is None else int(fanin)
    if fanin < 2:
        raise ValueError(f"merge fan-in must be >= 2, got {fanin}")
    spill_dir = resolve_spill_dir(spill_dir)
    codec = codec_for(dtype)
    chunk_elems = spill_chunk_elems(budget, dtype, width)

    from mpitest_tpu import faults as faultlib

    reg = faultlib.for_run()
    from mpitest_tpu.models import supervisor as supervision

    supervision.wire_registry(reg, tracer)
    spans = _spans(tracer)

    resume_on = dataset is not None and knobs.get("SORT_RESUME") != "off"

    with faultlib.active(reg):
        # ---- crash resume (ISSUE 18) --------------------------------
        # a journaled manifest from a killed (or typed-failed-and-
        # retried) sort of the SAME dataset is a checkpoint: replay it,
        # re-validate every committed run (structure + sidecar fold),
        # and skip the sort phase for every chunk that survives.
        resumed: dict[int, runlib.RunInfo] = {}
        resumed_meta: dict[int, mfstlib.ManifestRun] = {}
        mwriter: mfstlib.ManifestWriter | None = None
        if resume_on:
            gc_spill_dir(spill_dir, tracer=tracer)
            t0 = time.perf_counter()
            m = mfstlib.load(mfstlib.manifest_path(spill_dir, dataset))
            if m is not None and (m.dtype == dtype.name
                                  and m.payload_width == width
                                  and m.chunk_elems == chunk_elems
                                  and (n_hint is None or m.n is None
                                       or m.n == n_hint)):
                for mr in m.runs:
                    try:
                        info = runlib.open_run(mr.path)
                        ok = (info.n == mr.n
                              and info.fingerprint == mr.fingerprint
                              and runlib.verify_run(info))
                    except runlib.RunVersionError:
                        raise  # version skew is typed, never silent
                    except (runlib.RunFormatError, OSError):
                        ok = False  # torn/missing partial: discarded
                    if ok:
                        resumed[mr.chunk] = info
                        resumed_meta[mr.chunk] = mr
                    else:
                        tracer.verbose(
                            f"resume: discarding invalid committed "
                            f"run {mr.path!r} (chunk {mr.chunk})")
                        # the damaged files must not linger: this
                        # chunk re-spills to a fresh path below
                        runlib.remove_run_paths(mr.path)
                if spans is not None:
                    spans.record(
                        "external.resume", t0,
                        time.perf_counter() - t0, dataset=dataset,
                        committed=len(m.runs), valid=len(resumed),
                        skipped_lines=m.skipped_lines)
            mwriter = mfstlib.ManifestWriter(
                spill_dir, dataset, dtype=dtype.name, n=n_hint,
                payload_width=width, algorithm=algorithm,
                chunk_elems=chunk_elems, budget=budget, fanin=fanin,
                resumed=[resumed_meta[c] for c in sorted(resumed_meta)])

        # ---- partition + spill --------------------------------------
        run_infos: list[runlib.RunInfo] = []
        #: source chunk index behind each run — the recovery path
        #: re-slices chunks_fn by THIS index (empty chunks are skipped,
        #: so run order and chunk order can differ)
        chunk_of_run: list[int] = []
        n = 0
        resumed_count = 0
        try:
            for idx, (kchunk, pchunk) in enumerate(
                    chunks_fn(chunk_elems)):
                kchunk = np.asarray(kchunk, dtype).reshape(-1)
                if kchunk.size == 0:
                    continue
                prev = resumed.get(idx)
                if prev is not None and prev.n == int(kchunk.size):
                    # checkpoint hit: the committed run IS this chunk
                    # sorted — re-enter at the merge without re-sorting
                    run_infos.append(prev)
                    chunk_of_run.append(idx)
                    n += int(kchunk.size)
                    resumed_count += 1
                    continue
                info = _spill_one(idx, kchunk, pchunk, spill_dir,
                                  algorithm, mesh, tracer,
                                  durable=mwriter is not None)
                if mwriter is not None:
                    mwriter.commit_run(idx, info)
                run_infos.append(info)
                chunk_of_run.append(idx)
                n += int(kchunk.size)
            if n_hint is not None and n != n_hint:
                raise SortIntegrityError(
                    f"partition saw {n} records, expected {n_hint}")

            if not run_infos:
                res = ExternalResult(0, dtype, width, 0, 0, 0, 0,
                                     keys=np.empty(0, dtype),
                                     payload=(np.zeros((0, width),
                                                       np.uint8)
                                              if width else None))
                _finish_plan(tracer, res, budget, fanin)
                return res

            disk0 = sum(r.disk_bytes for r in run_infos)
            expected_fp = run_infos[0].fingerprint
            for r in run_infos[1:]:
                expected_fp = expected_fp.combine(r.fingerprint)

            # ---- merge (+ bounded integrity recovery) ---------------
            # partition runs are dataset-sized: deleted on EVERY exit
            # path below (the success case and the typed failure alike
            # — the flight recorder, not the disk, carries the
            # postmortem).  Only a CRASH skips this cleanup, and that
            # is exactly what the manifest + resume exist for.
            try:
                return _merge_with_recovery(
                    chunks_fn, chunk_elems, run_infos, chunk_of_run, n,
                    disk0, expected_fp, spill_dir, budget, fanin, dtype,
                    width, codec, algorithm, mesh, sink, sink_factory,
                    out_name, tracer, spans, mwriter, resumed_count)
            finally:
                for r in run_infos:
                    runlib.remove_run(r)
        except BaseException as e:
            # a FAILED sort (typed or not) never leaves partial runs
            # behind — only a crash does, and the manifest + resume
            # exist for exactly that.  The merge path already removed
            # its runs in the finally above; remove_run is idempotent.
            for r in run_infos:
                runlib.remove_run(r)
            if isinstance(e, OSError) and e.errno == errno.ENOSPC \
                    and not isinstance(e, SpillCapacityError):
                # in-flight partial outputs were already deleted at
                # their write sites (writer.abort); surface the typed
                # retryable shape
                raise SpillCapacityError(
                    f"spill volume full ({spill_dir!r}): {e}") from e
            raise
        finally:
            if mwriter is not None:
                mwriter.delete()


def _merge_with_recovery(
    chunks_fn: Any,
    chunk_elems: int,
    run_infos: "list[runlib.RunInfo]",
    chunk_of_run: "list[int]",
    n: int,
    disk0: int,
    expected_fp: Any,
    spill_dir: str,
    budget: int,
    fanin: int,
    dtype: np.dtype,
    width: int,
    codec: Any,
    algorithm: str,
    mesh: Any,
    sink: Any,
    sink_factory: Any,
    out_name: str,
    tracer: Any,
    spans: Any,
    mwriter: "mfstlib.ManifestWriter | None" = None,
    resumed_count: int = 0,
) -> ExternalResult:
    """The bounded merge/recovery loop of :func:`_external_core` (split
    out so the caller owns partition-run cleanup on every exit)."""

    def _run_ok(r: "runlib.RunInfo") -> bool:
        # blame must survive structurally-torn runs too: a truncated
        # file raises RunFormatError from the chunk reader, which for
        # blame purposes is simply "bad run, re-spill it"
        try:
            return runlib.verify_run(r)
        except (runlib.RunFormatError, OSError):
            return False

    recoveries = 0
    merge_passes = 0
    out: ExternalResult | None = None
    last_err: str | None = None
    for attempt in range(MERGE_ATTEMPTS + 1):
        # the sink is rebuilt PER ATTEMPT: a merge streams chunks
        # to it before verification can finish, so an attempt that
        # fails integrity has already fed the sink possibly-bad
        # data — array/file sinks restart inside _merge_all, and a
        # streaming caller provides sink_factory(n) so ITS state
        # (e.g. the CLI's running median probe) restarts too.  A
        # bare callable sink must be stateless across attempts.
        attempt_sink = (sink_factory(n) if sink_factory is not None
                        else sink)
        try:
            out, merge_passes = _merge_all(
                run_infos, expected_fp, n, spill_dir, budget, fanin,
                dtype, width, codec, attempt_sink, out_name, tracer)
            break
        except mergelib.RunIntegrityError as e:
            # a named bad run: re-spill exactly that slice (an
            # INTERMEDIATE merge run cannot be re-spilled directly
            # — blame falls back to scanning the originals)
            bad = ([e.info] if e.info in run_infos
                   else [r for r in run_infos if not _run_ok(r)])
            last_err = str(e)
        except runlib.RunVersionError:
            raise  # version skew is typed all the way out, never blamed
        except runlib.RunFormatError as e:
            # structural damage mid-merge (the spill_torn_write shape:
            # disk holds fewer bytes than the sidecar promises) —
            # blame by scanning, exactly like a fold mismatch
            bad = [r for r in run_infos if not _run_ok(r)]
            last_err = str(e)
        except SortIntegrityError as e:
            # output-side mismatch (merge_drop shape): blame by
            # scanning every run against its sidecar
            bad = [r for r in run_infos if not _run_ok(r)]
            last_err = str(e)
        if attempt >= MERGE_ATTEMPTS:
            break
        recoveries += 1
        tracer.count("external_recoveries", 1)
        if spans is not None:
            spans.event("external.recover",
                        reason=last_err,
                        bad_runs=[r.path for r in bad],
                        attempt=attempt + 1)
        tracer.verbose(
            f"external sort integrity failure ({last_err}); "
            f"re-spilling {len(bad)} run(s) and re-merging")
        for r in bad:
            i = run_infos.index(r)
            ci = chunk_of_run[i]
            src = next(islice(chunks_fn(chunk_elems), ci, ci + 1))
            run_infos[i] = _spill_one(ci, np.asarray(src[0], dtype),
                                      src[1], spill_dir, algorithm,
                                      mesh, tracer,
                                      durable=mwriter is not None)
            if mwriter is not None:
                # journal the replacement (replay is last-wins per
                # chunk, so the blamed run's old line is superseded)
                mwriter.commit_run(ci, run_infos[i])
            if r.path != run_infos[i].path:
                # a blamed RESUMED run kept its old (other-pid) name;
                # the replacement got a fresh one — drop the old files
                runlib.remove_run(r)
        expected_fp = run_infos[0].fingerprint
        for r in run_infos[1:]:
            expected_fp = expected_fp.combine(r.fingerprint)
    if out is None:
        raise SortIntegrityError(
            "external sort produced no verified result after "
            f"{MERGE_ATTEMPTS} recovery attempt(s): {last_err}")

    out.runs = len(run_infos)
    out.disk_bytes = disk0
    out.recoveries = recoveries
    out.merge_passes = merge_passes
    out.resumed_runs = resumed_count
    rec_bytes = int(np.dtype(dtype).itemsize) + int(width)
    out.spill_ratio = (n * rec_bytes / disk0) if disk0 else 0.0
    tracer.counters["external_runs"] = out.runs
    tracer.counters["external_disk_bytes"] = out.disk_bytes
    tracer.counters["external_merge_passes"] = out.merge_passes
    tracer.counters["external_recoveries"] = recoveries
    _finish_plan(tracer, out, budget, fanin)
    return out


def _merge_all(
    run_infos: "list[runlib.RunInfo]",
    expected_fp: Any,
    n: int,
    spill_dir: str,
    budget: int,
    fanin: int,
    dtype: np.dtype,
    width: int,
    codec: Any,
    sink: "str | Callable[[np.ndarray, np.ndarray | None], None]",
    out_name: str,
    tracer: Any,
) -> tuple[ExternalResult, int]:
    """Fan-in-bounded merge of all runs + the output-side verification
    (fingerprint vs combined sidecars, boundary-inclusive sortedness).
    Raises typed integrity errors; never returns unverified bytes."""
    from mpitest_tpu import faults as faultlib
    from mpitest_tpu.models.records import words_to_payload

    # merge_stall drill (ISSUE 18): the durability selftest's SIGKILL
    # barrier — every partition run is committed, the merge has not
    # consumed them yet
    faultlib.maybe_merge_stall()
    spans = _spans(tracer)
    level = list(run_infos)
    merge_passes = 0
    #: intermediate runs created by the fan-in passes — deleted once
    #: the final pass has consumed them (success OR failure), so a
    #: multi-pass merge never leaks dataset-sized files
    created: list[runlib.RunInfo] = []
    while len(level) > fanin:
        merge_passes += 1
        level = _merge_level(level, spill_dir, budget, fanin, dtype,
                             width, merge_passes, tracer)
        created.extend(r for r in level if r not in run_infos)

    merge_passes += 1
    t0 = time.perf_counter()
    ch = merge_chunk_elems(budget, dtype, width, len(level))

    # async IO engine (ISSUE 20): read-ahead sources for every input
    # run + (file sink) a write-behind on the output writer; the final
    # span carries the measured disk/compute overlap
    io = aio.MergeIO()
    out_keys: list[np.ndarray] = []
    out_pay: list[np.ndarray] = []
    wb: "aio.WriteBehind | None" = None
    emit: Callable[[np.ndarray, np.ndarray | None], None]
    if sink == "array":
        def emit(k: np.ndarray, p: np.ndarray | None) -> None:
            out_keys.append(k)
            if p is not None:
                out_pay.append(p)
    elif sink == "file":
        # the OUTPUT run is always raw (compress=False): consumers
        # (the serve spill tier's zero-copy wire path, run_body_views)
        # read its body directly — only intermediate spill traffic
        # rides the compressed SORTRUN2 framing
        wb = io.wrap_writer(runlib.RunStreamWriter(
            spill_dir, out_name, dtype, width, compress=False))

        def emit(k: np.ndarray, p: np.ndarray | None) -> None:
            wb.append(k, p)
    elif callable(sink):
        emit = sink
    else:
        raise ValueError(f"unknown sink {sink!r}")

    from mpitest_tpu.models.segmented import lex_sorted_host

    got_fp = None
    got_n = 0
    prev_last: tuple[int, ...] | None = None
    sorted_ok = True
    out_info: "runlib.RunInfo | None" = None
    try:
        for kws, pws in mergelib.merge_runs(level, ch, io=io):
            cfp = runlib.run_fingerprint(kws, pws)
            got_fp = cfp if got_fp is None else got_fp.combine(cfp)
            m = int(kws[0].size)
            got_n += m
            if m:
                if not lex_sorted_host(kws):
                    sorted_ok = False
                first = tuple(int(w[0]) for w in kws)
                if prev_last is not None and first < prev_last:
                    sorted_ok = False
                prev_last = tuple(int(w[-1]) for w in kws)
            keys_dec = codec.decode(kws)
            pay_dec = words_to_payload(pws, m, width) if width else None
            emit(keys_dec, pay_dec)
        if wb is not None:
            # drain + publish BEFORE verification so the not-ok path
            # below can delete the published names
            out_info = wb.close()
    except BaseException:
        if wb is not None:
            # stop the worker and delete the partial output run: a
            # failed merge must not leak a dataset-sized out_<name>
            # file per attempt (the serve spill tier mints a fresh
            # name per request)
            wb.abort()
        raise
    finally:
        io.close()
        for r in created:
            runlib.remove_run(r)
    iostats = io.stats(t0, time.perf_counter())

    ok = (sorted_ok and got_n == n
          and (got_fp == expected_fp if got_fp is not None else n == 0))
    tracer.count("verify_runs", 1)
    if spans is not None:
        spans.event("verify", ok=bool(ok), sorted_ok=bool(sorted_ok),
                    fp_ok=bool(got_fp == expected_fp or n == 0), n=n)
        spans.record("external.merge", t0, time.perf_counter() - t0,
                     runs=len(level), n=got_n, final=True,
                     merge_pass=merge_passes,
                     disk_overlap=iostats["disk_overlap"],
                     disk_busy_s=iostats["disk_busy_s"],
                     overlap_s=iostats["overlap_s"])
    if not ok:
        tracer.count("verify_failures", 1)
        if out_info is not None:
            runlib.remove_run(out_info)  # see the except above
        raise SortIntegrityError(
            f"merged output failed verification (sorted={sorted_ok}, "
            f"n={got_n}/{n}, fingerprint="
            f"{'ok' if got_fp == expected_fp else 'MISMATCH'})")

    res = ExternalResult(n, dtype, width, len(run_infos), 0,
                         merge_passes, 0,
                         disk_overlap=iostats["disk_overlap"])
    if sink == "array":
        res.keys = (np.concatenate(out_keys) if out_keys
                    else np.empty(0, dtype))
        if width:
            res.payload = (np.concatenate(out_pay) if out_pay
                           else np.zeros((0, width), np.uint8))
    elif sink == "file":
        res.out_run = out_info
    return res, merge_passes


def gc_spill_dir(spill_dir: str | None = None, *,
                 age_s: float | None = None, tracer: Any = None) -> int:
    """Startup GC (ISSUE 18): reclaim orphaned spill artifacts — run /
    staging / temp / journal files under ``spill_dir`` that no live
    manifest references.  A SIGKILLed process leaks its nonce-named
    partials forever otherwise.  Age-gated (``SORT_SPILL_GC_AGE_S``):
    a concurrent sort's fresh files are never swept.  Returns the
    number of files reclaimed (the ``external.gc`` span feeds
    ``sort_external_orphans_reclaimed_total``)."""
    d = resolve_spill_dir(spill_dir)
    if age_s is None:
        age_s = float(knobs.get("SORT_SPILL_GC_AGE_S"))
    t0 = time.perf_counter()
    live: set[str] = set()
    for m in mfstlib.live_manifests(d):
        live.add(m.path)
        for mr in m.runs:
            live.add(mr.path)
            live.add(mr.path + ".pay")
            live.add(mr.path + ".fpr.json")
    now = time.time()
    reclaimed = 0
    freed = 0
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return 0
    for fn in names:
        if not fn.endswith(GC_SUFFIXES):
            continue
        p = os.path.join(d, fn)
        if p in live:
            continue
        try:
            st = os.stat(p)
        except OSError:
            continue
        if now - st.st_mtime < age_s:
            continue
        try:
            os.unlink(p)
        except OSError:
            continue
        reclaimed += 1
        freed += int(st.st_size)
    if reclaimed and tracer is not None:
        spans = _spans(tracer)
        if spans is not None:
            spans.record("external.gc", t0, time.perf_counter() - t0,
                         dir=d, reclaimed=reclaimed, bytes=freed,
                         age_s=float(age_s))
    return reclaimed


def _finish_plan(tracer: Any, res: ExternalResult, budget: int,
                 fanin: int) -> None:
    """Record the external plan decision (ISSUE 12): the tier choice,
    its sizing, and what it actually cost — the serve plan digest's
    ``spilled: true`` and ``--explain``'s external row come from
    here."""
    if not plan_mod.enabled():
        return
    plan = plan_mod.SortPlan(algo="external", n=res.n,
                             dtype=res.dtype.name, ranks=1)
    plan.decide("external", chosen="spill", trigger="budget",
                budget=budget, fanin=fanin,
                payload_width=res.payload_width)
    plan.actual("external", runs=res.runs, disk_bytes=res.disk_bytes,
                merge_passes=res.merge_passes,
                recoveries=res.recoveries,
                resumed=res.resumed_runs,
                spill_ratio=round(res.spill_ratio, 3),
                disk_overlap=round(res.disk_overlap, 3))
    if res.recoveries:
        plan.bump("external", "recoveries", float(res.recoveries))
    plan.finalize()
    tracer.spans.event("sort.plan", **plan.to_attrs())
    tracer.plan = plan
